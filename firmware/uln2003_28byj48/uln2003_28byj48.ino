// Turntable firmware: ULN2003 board + 28BYJ-48 geared stepper.
//
// Serial protocol (115200 baud): receive "<degrees>\n", rotate (blocking),
// reply "DONE\n". See firmware/README.md.
//
// The 28BYJ-48's internal gearbox ratio is nominally 64:1 but actually
// 63.68395:1, so steps-per-degree is calibrated as a float rather than
// derived from the nominal 2048 steps/rev.

#include <Arduino.h>
#include <Stepper.h>

// ---- wiring (IN1..IN4 on the ULN2003 board) --------------------------------
constexpr int PIN_IN1 = 19;
constexpr int PIN_IN2 = 18;
constexpr int PIN_IN3 = 5;
constexpr int PIN_IN4 = 17;

// 32 steps/rev rotor * 63.68395 gearbox = 4075.7728 half-steps... the Stepper
// library drives full steps: 2037.8864 per output rev -> 32.298 per 30 deg of
// nominal 2048. Calibrated against a printed protractor.
constexpr float STEPS_PER_DEGREE = 2037.8864f / 360.0f;
constexpr int RPM = 12;

// Stepper wants the coil order IN1-IN3-IN2-IN4 for this board
Stepper stepper(2048, PIN_IN1, PIN_IN3, PIN_IN2, PIN_IN4);

static String line;

static void releaseCoils() {  // avoid cooking the motor while idle
  digitalWrite(PIN_IN1, LOW);
  digitalWrite(PIN_IN2, LOW);
  digitalWrite(PIN_IN3, LOW);
  digitalWrite(PIN_IN4, LOW);
}

void setup() {
  stepper.setSpeed(RPM);
  Serial.begin(115200);
  line.reserve(32);
}

void loop() {
  while (Serial.available()) {
    char ch = static_cast<char>(Serial.read());
    if (ch == '\n' || ch == '\r') {
      if (line.length()) {
        float deg = line.toFloat();
        stepper.step(lroundf(deg * STEPS_PER_DEGREE));
        releaseCoils();
        Serial.println("DONE");
        line = "";
      }
    } else {
      line += ch;
    }
  }
}
