// Turntable firmware: A4988 driver + NEMA-17, 1/16 microstepping.
//
// Serial protocol (115200 baud): receive "<degrees>\n", rotate (blocking),
// reply "DONE\n". Negative degrees reverse direction. See firmware/README.md.

#include <Arduino.h>

// ---- wiring ----------------------------------------------------------------
constexpr int PIN_STEP = 26;
constexpr int PIN_DIR = 27;
constexpr int PIN_ENABLE = 25;  // active low

// ---- motion ----------------------------------------------------------------
// 200 full steps/rev * 16 microsteps (MS1=MS2=MS3 high) = 3200 steps/rev
constexpr long STEPS_PER_REV = 3200;
constexpr unsigned int STEP_PULSE_US = 500;  // half-period; ~1 kHz step rate

static String line;

static void rotateDegrees(float deg) {
  digitalWrite(PIN_DIR, deg >= 0 ? HIGH : LOW);
  long steps = lroundf(fabsf(deg) * STEPS_PER_REV / 360.0f);
  digitalWrite(PIN_ENABLE, LOW);  // energize
  for (long i = 0; i < steps; ++i) {
    digitalWrite(PIN_STEP, HIGH);
    delayMicroseconds(STEP_PULSE_US);
    digitalWrite(PIN_STEP, LOW);
    delayMicroseconds(STEP_PULSE_US);
  }
  digitalWrite(PIN_ENABLE, HIGH);  // release (no holding torque needed)
}

void setup() {
  pinMode(PIN_STEP, OUTPUT);
  pinMode(PIN_DIR, OUTPUT);
  pinMode(PIN_ENABLE, OUTPUT);
  digitalWrite(PIN_ENABLE, HIGH);
  Serial.begin(115200);
  line.reserve(32);
}

void loop() {
  while (Serial.available()) {
    char ch = static_cast<char>(Serial.read());
    if (ch == '\n' || ch == '\r') {
      if (line.length()) {
        rotateDegrees(line.toFloat());
        Serial.println("DONE");
        line = "";
      }
    } else {
      line += ch;
    }
  }
}
