"""ctypes binding for the native IO runtime (native/libslio.so).

The reference's IO hot paths live in C++ (OpenCV imread, Open3D writers); the
TPU build mirrors that with its own native library: thread-pooled PNG stack
decode and buffered binary PLY/STL writers. Everything here degrades to the
pure-Python implementations when the library hasn't been built
(`make -C native`), so the framework has zero hard native dependencies.
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

__all__ = ["available", "load_gray_stack", "write_ply_native",
           "write_stl_native", "probe_png"]

_LIB = None
_TRIED = False


def _find_lib():
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates = [
        os.environ.get("SLIO_LIBRARY", ""),
        os.path.join(here, "native", "libslio.so"),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return c
    return None


def _lib():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.slio_abi_version.restype = ctypes.c_int
        if lib.slio_abi_version() != 1:
            return None
        lib.slio_probe_png.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.slio_probe_png.restype = ctypes.c_int
        lib.slio_load_gray_stack.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        lib.slio_load_gray_stack.restype = ctypes.c_int
        lib.slio_write_ply.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float)]
        lib.slio_write_ply.restype = ctypes.c_int
        lib.slio_write_stl.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32)]
        lib.slio_write_stl.restype = ctypes.c_int
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return _lib() is not None


def probe_png(path: str):
    """(width, height, channels) of a PNG, or None on failure/unavailable."""
    lib = _lib()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    if lib.slio_probe_png(path.encode(), ctypes.byref(w), ctypes.byref(h),
                          ctypes.byref(c)) != 0:
        return None
    return w.value, h.value, c.value


def load_gray_stack(paths: list[str], width: int, height: int,
                    n_threads: int = 0) -> np.ndarray | None:
    """Parallel-decode PNGs to a uint8 [F, H, W] stack; None if unavailable
    or any file fails (caller falls back to the Python loader)."""
    lib = _lib()
    if lib is None or not paths:
        return None
    if not all(p.lower().endswith(".png") for p in paths):
        return None
    out = np.empty((len(paths), height, width), np.uint8)
    arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
    rc = lib.slio_load_gray_stack(
        arr, len(paths), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        width, height, n_threads)
    if rc != 0:
        return None
    return out


def write_ply_native(path: str, points: np.ndarray,
                     colors: np.ndarray | None = None,
                     normals: np.ndarray | None = None) -> bool:
    """Binary PLY via the native writer. Returns False if unavailable."""
    lib = _lib()
    if lib is None:
        return False
    pts = np.ascontiguousarray(points, np.float32)
    n = len(pts)
    rgb_ptr = None
    nrm_ptr = None
    if colors is not None:
        rgb = np.ascontiguousarray(colors, np.uint8)
        rgb_ptr = rgb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    if normals is not None:
        nrm = np.ascontiguousarray(normals, np.float32)
        nrm_ptr = nrm.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    rc = lib.slio_write_ply(
        path.encode(), n, pts.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rgb_ptr, nrm_ptr)
    return rc == 0


def write_stl_native(path: str, vertices: np.ndarray,
                     faces: np.ndarray) -> bool:
    """Binary STL via the native writer. Returns False if unavailable."""
    lib = _lib()
    if lib is None:
        return False
    v = np.ascontiguousarray(vertices, np.float32)
    f = np.ascontiguousarray(faces, np.int32)
    rc = lib.slio_write_stl(
        path.encode(), len(f),
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        f.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return rc == 0
