"""PLY point-cloud/mesh IO — binary-first, fully vectorized.

Replaces the reference's ASCII writer (server/processing.py:237-248: a Python
f-string loop over ~10^6 points, a measured bottleneck independent of the
compute backend) with numpy-structured-array binary encode/decode. An ASCII
mode is kept for interop with the reference's artifacts (including its %.4f
formatting and header layout); the reader handles both formats.

Color convention: this framework is RGB end-to-end. The reference stores BGR
in memory (cv2) and swaps at write time (processing.py:245-248); our acquire
layer swaps BGR->RGB at image-load time instead, so IO never reorders.
"""
from __future__ import annotations

import numpy as np

from structured_light_for_3d_model_replication_tpu.io.atomic import (
    atomic_write,
)
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["write_ply", "read_ply", "write_mesh_ply", "WritebackQueue",
           "PlyWriteError"]

_PLY_DTYPES = {
    "float": "<f4", "float32": "<f4", "double": "<f8", "float64": "<f8",
    "uchar": "u1", "uint8": "u1", "char": "i1", "int8": "i1",
    "ushort": "<u2", "uint16": "<u2", "short": "<i2", "int16": "<i2",
    "uint": "<u4", "uint32": "<u4", "int": "<i4", "int32": "<i4",
}


def _vertex_dtype(has_colors: bool, has_normals: bool) -> np.dtype:
    fields = [("x", "<f4"), ("y", "<f4"), ("z", "<f4")]
    if has_normals:
        fields += [("nx", "<f4"), ("ny", "<f4"), ("nz", "<f4")]
    if has_colors:
        fields += [("red", "u1"), ("green", "u1"), ("blue", "u1")]
    return np.dtype(fields)


def write_ply(path: str, points: np.ndarray, colors: np.ndarray | None = None,
              normals: np.ndarray | None = None, binary: bool = True) -> None:
    """Write a point cloud. points [N,3] float; colors [N,3] uint8 RGB;
    normals [N,3] float; binary little-endian by default.

    ``binary=False`` writes the reference's ASCII layout with ``%.4f``
    coordinates — a LOSSY roundtrip (~0.1 um at mm scale, plus outright
    truncation for |coord| >= 10^4). It exists for interop with the
    reference's artifacts only: every *intermediate* pipeline artifact is
    written binary regardless of user-facing ASCII flags (see docs/API.md),
    so lossiness can only ever appear in a final, user-requested export.

    Crash-safe: bytes are staged into ``<path>.tmp`` and published with
    fsync + atomic rename, so an interrupt at any point leaves either the
    previous complete file or a sweepable orphan — never a truncated PLY."""
    faults.fire("ply.write", item=path)
    points = np.asarray(points, np.float32)
    n = points.shape[0]
    has_c = colors is not None
    has_n = normals is not None

    if binary and n >= 100_000:
        from structured_light_for_3d_model_replication_tpu.io import (
            atomic as at,
        )
        from structured_light_for_3d_model_replication_tpu.io import native

        tmp = path + ".tmp"
        try:
            if native.write_ply_native(tmp, points, colors, normals):
                at.commit(tmp, path)
                return
        finally:
            at.discard(tmp)

    header = ["ply",
              "format binary_little_endian 1.0" if binary else "format ascii 1.0",
              f"element vertex {n}",
              "property float x", "property float y", "property float z"]
    if has_n:
        header += ["property float nx", "property float ny", "property float nz"]
    if has_c:
        header += ["property uchar red", "property uchar green", "property uchar blue"]
    header.append("end_header")

    if binary:
        rec = np.empty(n, _vertex_dtype(has_c, has_n))
        rec["x"], rec["y"], rec["z"] = points[:, 0], points[:, 1], points[:, 2]
        if has_n:
            nrm = np.asarray(normals, np.float32)
            rec["nx"], rec["ny"], rec["nz"] = nrm[:, 0], nrm[:, 1], nrm[:, 2]
        if has_c:
            col = np.asarray(colors, np.uint8)
            rec["red"], rec["green"], rec["blue"] = col[:, 0], col[:, 1], col[:, 2]
        with atomic_write(path) as tmp, open(tmp, "wb") as f:
            f.write(("\n".join(header) + "\n").encode("ascii"))
            rec.tofile(f)
    else:
        # vectorized ASCII: one np.savetxt-style formatting pass, %.4f floats
        # (the reference's precision, processing.py:247)
        cols: list[np.ndarray] = [points.astype(np.float64)]
        fmt = "%.4f %.4f %.4f"
        if has_n:
            cols.append(np.asarray(normals, np.float64))
            fmt += " %.6f %.6f %.6f"
        if has_c:
            cols.append(np.asarray(colors, np.float64))
            fmt += " %d %d %d"
        body = np.concatenate(cols, axis=1)
        lines = [fmt % tuple(row) for row in body]
        with atomic_write(path) as tmp, open(tmp, "w") as f:
            f.write("\n".join(header) + "\n")
            f.write("\n".join(lines))
            if lines:
                f.write("\n")


class PlyWriteError(RuntimeError):
    """Aggregate of every write failure in one ``WritebackQueue.drain`` —
    the ExceptionGroup-style summary (py3.10-compatible) that keeps later
    failures from being silently dropped behind the first one."""

    def __init__(self, errors: list[tuple[str, Exception]]):
        self.errors = errors
        detail = "; ".join(f"{p}: {type(e).__name__}: {e}"
                           for p, e in errors)
        super().__init__(f"{len(errors)} PLY write(s) failed: {detail}")


class WritebackQueue:
    """Background PLY writeback: the handoff that takes artifact writes off
    the critical path of a pipelined producer.

    One writer thread (disk writes of one artifact stream don't benefit from
    concurrency, and a single worker preserves submission order on disk, so a
    crash leaves a clean prefix of the batch). ``submit`` returns a
    ``Future`` the caller holds until its drain point; the future carries the
    written path on success and re-raises the write error on failure — the
    producer maps it back to its per-item failure accounting. Bytes are
    identical to a direct ``write_ply`` call: same writer, same arrays.

    ``retry``: an optional ``faults.RetryPolicy``; transient write errors
    (EAGAIN-class, injected transients) are then retried with backoff inside
    the writer thread, with ``on_retry(path, retry_index, exc)`` notified —
    the executor's per-lane retry counter hook.
    """

    def __init__(self, on_write=None, retry=None, on_retry=None):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="sl3d-plywrite")
        self._pending: list[tuple[str, object]] = []
        # optional (path, elapsed_s) hook, called in the writer thread after
        # each successful write — the pipeline's write-wall gauge
        self._on_write = on_write
        self._retry = retry
        self._on_retry = on_retry

    def submit(self, path: str, points: np.ndarray,
               colors: np.ndarray | None = None,
               normals: np.ndarray | None = None, binary: bool = True):
        """Enqueue one cloud write; returns a Future resolving to ``path``."""

        def _write() -> str:
            import time

            # work-started heartbeat for the stall watchdog (completion
            # beats flow through on_write -> OverlapStats.add)
            dl.beat("write")
            t0 = time.perf_counter()
            if self._retry is not None:
                faults.retry_call(
                    lambda: write_ply(path, points, colors, normals,
                                      binary=binary),
                    self._retry,
                    on_retry=lambda n, e: (self._on_retry(path, n, e)
                                           if self._on_retry else None))
            else:
                write_ply(path, points, colors, normals, binary=binary)
            if self._on_write is not None:
                self._on_write(path, time.perf_counter() - t0)
            return path

        fut = self._pool.submit(_write)
        self._pending.append((path, fut))
        return fut

    @property
    def backlog(self) -> int:
        """Writes submitted but not yet finished (the queue-depth gauge)."""
        return sum(1 for _, f in self._pending if not f.done())

    def drain(self, timeout_s: float | None = None) -> list[str]:
        """Block until every submitted write finished; returns successfully
        written paths. ALL write errors are collected and raised together as
        one :class:`PlyWriteError` (callers holding per-item futures instead
        call ``.result()`` on those and never need drain).

        ``timeout_s`` bounds the WHOLE drain (one shared monotonic
        deadline, not per write): a stalled writer thread can no longer
        block the pipeline forever — writes still pending at expiry are
        aggregated into the same :class:`PlyWriteError` as a
        :class:`~.utils.deadline.DeadlineExceeded` per path, alongside any
        ordinary write failures. None keeps the historical unbounded
        behavior."""
        out: list[str] = []
        errors: list[tuple[str, Exception]] = []
        deadline = dl.Deadline.after(timeout_s, "writeback drain")
        for path, f in self._pending:
            try:
                # NB: remaining() can be <= 0 once the shared budget is
                # spent — that means "expired", never "unbounded"
                rem = deadline.remaining() if deadline is not None else None
                if rem is not None and rem <= 0:
                    settled = f.done()
                elif rem is None:
                    f.exception()   # blocks without raising; result below
                    settled = True
                else:
                    settled = dl.wait_settled(f, rem)
                if settled:
                    out.append(f.result())
                else:
                    errors.append((path, dl.DeadlineExceeded(
                        f"write still pending after the {timeout_s:g}s "
                        f"drain budget (stalled writer thread?)")))
            except Exception as e:
                errors.append((path, e))
        self._pending.clear()
        if errors:
            raise PlyWriteError(errors)
        return out

    def close(self, wait: bool = True,
              timeout_s: float | None = None) -> None:
        """Shut the writer down. ``timeout_s`` (with ``wait=True``) bounds
        how long a stalled in-flight write may delay shutdown: pending
        futures get one shared deadline, and anything still unsettled is
        abandoned (``cancel_futures`` drops the queued tail; the wedged
        thread is left to die with the process — Python cannot kill it)."""
        if wait and timeout_s is not None and timeout_s > 0:
            deadline = dl.Deadline.after(timeout_s, "writeback close")
            settled = True
            for _, f in self._pending:
                rem = deadline.remaining()
                # a spent budget means expired, never unbounded
                if rem <= 0 or not dl.wait_settled(f, rem):
                    settled = False
                    break
            self._pool.shutdown(wait=settled, cancel_futures=not settled)
            return
        self._pool.shutdown(wait=wait, cancel_futures=not wait)

    def __enter__(self) -> "WritebackQueue":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # on error, don't block shutdown on a backlog nobody will consume
        self.close(wait=exc_type is None)


def write_mesh_ply(path: str, vertices: np.ndarray, faces: np.ndarray,
                   colors: np.ndarray | None = None,
                   normals: np.ndarray | None = None) -> None:
    """Write a triangle mesh (binary little-endian, crash-safe tmp+rename)."""
    faults.fire("ply.write", item=path)
    vertices = np.asarray(vertices, np.float32)
    faces = np.asarray(faces, np.int32)
    has_c = colors is not None
    has_n = normals is not None
    n, m = vertices.shape[0], faces.shape[0]
    header = ["ply", "format binary_little_endian 1.0",
              f"element vertex {n}",
              "property float x", "property float y", "property float z"]
    if has_n:
        header += ["property float nx", "property float ny", "property float nz"]
    if has_c:
        header += ["property uchar red", "property uchar green", "property uchar blue"]
    header += [f"element face {m}", "property list uchar int vertex_indices",
               "end_header"]
    rec = np.empty(n, _vertex_dtype(has_c, has_n))
    rec["x"], rec["y"], rec["z"] = vertices[:, 0], vertices[:, 1], vertices[:, 2]
    if has_n:
        nrm = np.asarray(normals, np.float32)
        rec["nx"], rec["ny"], rec["nz"] = nrm[:, 0], nrm[:, 1], nrm[:, 2]
    if has_c:
        col = np.asarray(colors, np.uint8)
        rec["red"], rec["green"], rec["blue"] = col[:, 0], col[:, 1], col[:, 2]
    frec = np.empty(m, np.dtype([("k", "u1"), ("a", "<i4"), ("b", "<i4"), ("c", "<i4")]))
    frec["k"] = 3
    frec["a"], frec["b"], frec["c"] = faces[:, 0], faces[:, 1], faces[:, 2]
    with atomic_write(path) as tmp, open(tmp, "wb") as f:
        f.write(("\n".join(header) + "\n").encode("ascii"))
        rec.tofile(f)
        frec.tofile(f)


def read_ply(path: str):
    """Read a PLY file (binary little-endian or ascii).

    Returns dict with 'points' [N,3] f32, optional 'colors' [N,3] u8,
    'normals' [N,3] f32, 'faces' [M,3] i32.
    """
    with open(path, "rb") as f:
        # header is ascii lines terminated by 'end_header'
        header_lines = []
        while True:
            line = f.readline()
            if not line:
                raise ValueError(f"{path}: truncated PLY header")
            header_lines.append(line.decode("ascii", "replace").strip())
            if header_lines[-1] == "end_header":
                break
        fmt = None
        elements: list[tuple[str, int, list]] = []  # (name, count, [(prop, type)])
        for ln in header_lines:
            parts = ln.split()
            if not parts:
                continue
            if parts[0] == "format":
                fmt = parts[1]
            elif parts[0] == "element":
                elements.append((parts[1], int(parts[2]), []))
            elif parts[0] == "property" and elements:
                if parts[1] == "list":
                    elements[-1][2].append(("list", parts[2], parts[3], parts[4]))
                else:
                    elements[-1][2].append((parts[2], parts[1]))
        if fmt is None:
            raise ValueError(f"{path}: no format line in PLY header")
        body = f.read()

    out: dict[str, np.ndarray] = {}
    offset = 0
    for name, count, props in elements:
        is_list = any(p[0] == "list" for p in props)
        if fmt == "ascii":
            text = body.decode("ascii", "replace").split("\n")
            rows = [r.split() for r in text if r.strip()][:count]
            if is_list:
                faces = np.array([[int(v) for v in r[1:1 + int(r[0])]] for r in rows],
                                 np.int32)
                out["faces"] = faces
            else:
                arr = np.array([[float(v) for v in r] for r in rows], np.float64)
                _unpack_vertex(out, arr, [p[0] for p in props])
            break  # ascii path: simple single-pass (vertex [+faces]) support
        if is_list:
            # uniform triangle lists only (the overwhelmingly common case)
            ldt = np.dtype([("k", _PLY_DTYPES[props[0][1]]),
                            ("v", _PLY_DTYPES[props[0][2]], 3)])
            _check_body(path, name, body, ldt.itemsize, count, offset)
            rec = np.frombuffer(body, ldt, count=count, offset=offset)
            if count and not (rec["k"] == 3).all():
                raise ValueError(f"{path}: only triangle faces supported")
            out["faces"] = rec["v"].astype(np.int32)
            offset += ldt.itemsize * count
        else:
            dt = np.dtype([(p[0], _PLY_DTYPES[p[1]]) for p in props])
            _check_body(path, name, body, dt.itemsize, count, offset)
            rec = np.frombuffer(body, dt, count=count, offset=offset)
            arr = np.stack([rec[p[0]].astype(np.float64) for p in props], axis=1)
            _unpack_vertex(out, arr, [p[0] for p in props])
            offset += dt.itemsize * count
    return out


def _check_body(path: str, element: str, body: bytes, itemsize: int,
                count: int, offset: int) -> None:
    """A body shorter than the header promises is a truncated file (torn
    write, partial copy) — name it as such instead of letting np.frombuffer
    raise a generic buffer error."""
    have = len(body) - offset
    need = itemsize * count
    if have < need:
        raise ValueError(
            f"{path}: truncated PLY body — {have} bytes for {count} "
            f"'{element}' records ({need} expected)")


def _unpack_vertex(out: dict, arr: np.ndarray, names: list[str]) -> None:
    idx = {nm: i for i, nm in enumerate(names)}
    if all(k in idx for k in ("x", "y", "z")):
        out["points"] = arr[:, [idx["x"], idx["y"], idx["z"]]].astype(np.float32)
    if all(k in idx for k in ("red", "green", "blue")):
        out["colors"] = arr[:, [idx["red"], idx["green"], idx["blue"]]].astype(np.uint8)
    if all(k in idx for k in ("nx", "ny", "nz")):
        out["normals"] = arr[:, [idx["nx"], idx["ny"], idx["nz"]]].astype(np.float32)
