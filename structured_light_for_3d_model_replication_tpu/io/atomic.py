"""Crash-safe artifact publishing: tmp + fsync + rename, plus the startup
sweep for the tmp files a ``kill -9`` leaves behind.

Every final artifact writer (PLY, STL, stage-cache entries, failure
manifests) stages its bytes into ``<path>.tmp`` and publishes with an
atomic ``os.replace`` after an fsync — an interrupt at ANY byte offset
leaves either the previous complete artifact or a ``.tmp`` orphan, never a
half-written final file. The deterministic ``.tmp`` suffix is what makes
orphans sweepable: pipelines call :func:`sweep_tmp` on startup so a crashed
run's debris never masquerades as data.
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["atomic_write", "commit", "discard", "sweep_tmp"]

_TMP_SUFFIXES = (".tmp", ".tmp.npz")


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def commit(tmp: str, path: str, sync: bool = True) -> None:
    """Publish a fully-written tmp file as ``path`` (fsync + atomic rename)."""
    if sync:
        _fsync_path(tmp)
    os.replace(tmp, path)


def discard(tmp: str) -> None:
    """Best-effort removal of an abandoned tmp file."""
    try:
        os.remove(tmp)
    except OSError:
        pass


@contextlib.contextmanager
def atomic_write(path: str, sync: bool = True):
    """Yield the staging path for ``path``; commit on clean exit, discard on
    ANY exception (including BaseException — an InjectedCrash/KeyboardInterrupt
    must not publish partial bytes; a real SIGKILL leaves the .tmp for the
    startup sweep)."""
    tmp = path + ".tmp"
    try:
        yield tmp
    except BaseException:
        discard(tmp)
        raise
    commit(tmp, path, sync=sync)


def sweep_tmp(folder: str, log=None, recursive: bool = False) -> list[str]:
    """Remove stale ``*.tmp`` (and numpy's ``*.tmp.npz``) orphans under
    ``folder``; returns the removed paths. Safe on a missing folder."""
    removed: list[str] = []
    if not os.path.isdir(folder):
        return removed
    if recursive:
        walker = ((r, fs) for r, _, fs in os.walk(folder))
    else:
        walker = [(folder, os.listdir(folder))]
    for root, files in walker:
        for f in files:
            if f.endswith(_TMP_SUFFIXES):
                p = os.path.join(root, f)
                try:
                    os.remove(p)
                    removed.append(p)
                except OSError:
                    continue
    if removed and log is not None:
        log(f"[sweep] removed {len(removed)} stale .tmp file(s) under "
            f"{folder} (interrupted earlier run)")
    return removed
