"""Binary STL mesh IO — the print-ready output format.

The reference writes STL through Open3D (server/processing.py:739,859); here it
is a direct vectorized binary codec (80-byte header, uint32 count, 50-byte
records), with normals computed from the winding when not supplied.
"""
from __future__ import annotations

import numpy as np

from structured_light_for_3d_model_replication_tpu.io import atomic
from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["write_stl", "read_stl"]


def face_normals(vertices: np.ndarray, faces: np.ndarray) -> np.ndarray:
    v = np.asarray(vertices, np.float64)
    f = np.asarray(faces, np.int64)
    a, b, c = v[f[:, 0]], v[f[:, 1]], v[f[:, 2]]
    n = np.cross(b - a, c - a)
    norm = np.linalg.norm(n, axis=1, keepdims=True)
    return (n / np.where(norm > 0, norm, 1)).astype(np.float32)


def write_stl(path: str, vertices: np.ndarray, faces: np.ndarray,
              normals: np.ndarray | None = None) -> None:
    """Write a binary STL. vertices [N,3] float, faces [M,3] int.

    Crash-safe: staged into ``<path>.tmp`` and published with fsync +
    atomic rename — an interrupt mid-write never leaves a truncated STL
    masquerading as a print-ready model."""
    faults.fire("ply.write", item=path)
    vertices = np.asarray(vertices, np.float32)
    faces = np.asarray(faces, np.int64)
    m = faces.shape[0]
    if normals is None and m >= 50_000:
        from structured_light_for_3d_model_replication_tpu.io import native

        tmp = path + ".tmp"
        try:
            if native.write_stl_native(tmp, vertices, faces):
                atomic.commit(tmp, path)
                return
        finally:
            atomic.discard(tmp)
    if normals is None:
        normals = face_normals(vertices, faces)
    rec = np.zeros(m, np.dtype([
        ("normal", "<f4", 3), ("v0", "<f4", 3), ("v1", "<f4", 3), ("v2", "<f4", 3),
        ("attr", "<u2"),
    ]))
    rec["normal"] = np.asarray(normals, np.float32)
    rec["v0"] = vertices[faces[:, 0]]
    rec["v1"] = vertices[faces[:, 1]]
    rec["v2"] = vertices[faces[:, 2]]
    with atomic.atomic_write(path) as tmp, open(tmp, "wb") as f:
        f.write(b"structured_light_for_3d_model_replication_tpu".ljust(80, b"\0"))
        f.write(np.uint32(m).tobytes())
        rec.tofile(f)


def read_stl(path: str):
    """Read a binary STL. Returns (vertices [3M,3] f32, faces [M,3] i32,
    normals [M,3] f32). Vertices are NOT deduplicated."""
    with open(path, "rb") as f:
        f.seek(80)
        m = int(np.frombuffer(f.read(4), "<u4")[0])
        rec = np.frombuffer(f.read(m * 50), np.dtype([
            ("normal", "<f4", 3), ("v0", "<f4", 3), ("v1", "<f4", 3), ("v2", "<f4", 3),
            ("attr", "<u2"),
        ]), count=m)
    verts = np.stack([rec["v0"], rec["v1"], rec["v2"]], axis=1).reshape(-1, 3)
    faces = np.arange(3 * m, dtype=np.int32).reshape(-1, 3)
    return verts.copy(), faces, rec["normal"].copy()
