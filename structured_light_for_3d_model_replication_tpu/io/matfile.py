"""Calibration .mat IO — format-compatible with the reference's artifacts.

The reference persists calibration as a MATLAB .mat of
{Nc, Oc, dc, wPlaneCol, wPlaneRow, cam_K, proj_K, R, T}
(server/sl_system.py:413-423, loaded at processing.py:279-284). We keep that
exact layout so clouds can be reconstructed from calibrations produced by
either system. A .npz twin format is also supported (native, faster).
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["save_calibration", "load_calibration"]

_CALIB_KEYS = ("Nc", "Oc", "dc", "wPlaneCol", "wPlaneRow", "cam_K", "proj_K", "R", "T")


def save_calibration(path: str, calib: dict) -> None:
    """Save to .mat (reference-compatible) or .npz by extension."""
    data = {k: np.asarray(v) for k, v in calib.items() if v is not None}
    if path.endswith(".npz"):
        np.savez_compressed(path, **data)
    else:
        import scipy.io

        scipy.io.savemat(path, data)


def load_calibration(path: str) -> dict:
    """Load a calibration dict; normalizes scipy's loadmat artifacts
    (squeezes MATLAB metadata keys, keeps matrix shapes)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"Calibration file not found: {path}")
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    import scipy.io

    raw = scipy.io.loadmat(path)
    calib = {k: v for k, v in raw.items() if not k.startswith("__")}
    missing = [k for k in ("Oc", "wPlaneCol", "wPlaneRow") if k not in calib]
    if missing:
        raise ValueError(f"{path}: not a calibration file (missing {missing})")
    return calib
