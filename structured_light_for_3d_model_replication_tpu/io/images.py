"""Capture-stack image IO + the packed bit-plane codec.

The reference reads scan folders of 46 numbered frames ("01.png".."46.png",
server/sl_system.py:126-150) one cv2.imread at a time inside the decode loop
(processing.py:95-101). Here the stack loads once into a [F, H, W] array (and
the white frame additionally as RGB texture), so the decode kernel sees a
single device buffer. cv2 is used when present; a PNG/PPM fallback via PIL
keeps the path alive without it.

Packed bit-plane format (``frames.slbp``)
-----------------------------------------
Gray-code decode reads each pattern/inverse frame pair exactly once, as the
comparison ``pattern > inverse`` — one bit per pixel per pair. The packed
format stores precisely what decode consumes:

  - the white and black frames VERBATIM as u8 (thresholds and the shadow/
    contrast mask depend only on these two frames, so storing them whole
    preserves threshold resolution and masking bit-for-bit)
  - each of the P = (F-2)//2 pattern pairs collapsed to its comparison bit,
    packed 8 planes/byte, plane-major, LSB-first: plane p lands in byte
    p//8 at bit p%8 of a u8 [ceil(P/8), H, W] array
  - the RGB texture (color of the white frame) in the container, so a
    packed source round-trips ``load_stack``'s return contract

A 46-frame 1080p stack (46·H·W upload bytes) becomes 2·H·W (white+black)
+ ceil(22/8)·H·W (packed planes) = 5·H·W on the wire — 9.2x fewer frame
bytes, and decode from the planes is bit-identical to ``decode_stack_np``
on the raw stack because the stored bits ARE decode's comparisons.

The on-disk container is a deterministic flat binary (magic + JSON header +
raw sections) rather than an npz: zip archives embed timestamps, and the
stage cache keys on content bytes — a re-pack of identical frames must hash
identically.
"""
from __future__ import annotations

import glob
import json
import os
import struct
import threading
from dataclasses import dataclass

import numpy as np

__all__ = ["list_frame_files", "load_stack", "save_stack", "load_gray",
           "load_color", "save_image", "PackedStack", "pack_stack",
           "unpack_stack", "save_packed_stack", "load_packed_stack",
           "probe_packed", "packed_file", "is_packed_source", "count_frames",
           "pack_scan_folder", "PACKED_NAME"]

_EXTS = (".bmp", ".png", ".jpg", ".jpeg", ".ppm", ".pgm")
PACKED_EXT = ".slbp"
PACKED_NAME = "frames" + PACKED_EXT
_PACKED_MAGIC = b"SLBP1\n"

# one shared decode pool for the whole process: per-call executors cost
# ~ms of thread spin-up — more than a small frame decodes in — and a shared
# pool also caps TOTAL imread concurrency when the batch pipeline prefetches
# several stacks at once. Grown (never shrunk) to the largest request.
_POOL: "object | None" = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()


def _imread_pool(workers: int):
    from concurrent.futures import ThreadPoolExecutor

    global _POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _POOL is None or _POOL_SIZE < workers:
            old = _POOL
            _POOL = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="sl3d-imread")
            _POOL_SIZE = workers
            if old is not None:
                old.shutdown(wait=False)
        return _POOL


def _imread(path: str, gray: bool):
    try:
        import cv2

        img = cv2.imread(path, 0 if gray else 1)
        if img is None:
            raise IOError(f"unreadable image: {path}")
        if not gray:
            img = img[:, :, ::-1]  # BGR -> RGB at the IO boundary, once
        return img
    except ImportError:
        from PIL import Image

        img = Image.open(path)
        img = img.convert("L" if gray else "RGB")
        return np.asarray(img)


def _imwrite(path: str, img: np.ndarray):
    try:
        import cv2

        ok = cv2.imwrite(path, img if img.ndim == 2 else img[:, :, ::-1])
        if not ok:
            raise IOError(f"failed to write {path}")
    except ImportError:
        from PIL import Image

        Image.fromarray(img).save(path)


def save_image(path: str, img: np.ndarray) -> None:
    """Write one image; color images are RGB (the IO-boundary convention)."""
    _imwrite(path, np.asarray(img, np.uint8))


def load_gray(path: str) -> np.ndarray:
    return _imread(path, gray=True)


def load_color(path: str) -> np.ndarray:
    """Returns RGB uint8 [H, W, 3]."""
    return _imread(path, gray=False)


def list_frame_files(source) -> list[str]:
    """Resolve a scan source (folder or explicit file list) to a sorted frame list.

    Mirrors the reference's resolution order: .bmp glob first, then .png
    (processing.py:49-54), extended with the other common formats. A folder
    holding a packed container (``frames.slbp``) resolves to just that file —
    downstream content hashing (the stage cache keys on the bytes of every
    listed file) then covers the packed bytes exactly like raw frames.
    """
    if isinstance(source, (list, tuple)):
        return list(source)
    if not os.path.isdir(source):
        raise FileNotFoundError(f"scan folder not found: {source}")
    packed = os.path.join(source, PACKED_NAME)
    if os.path.isfile(packed):
        return [packed]
    for ext in _EXTS:
        files = sorted(glob.glob(os.path.join(source, f"*{ext}")))
        if files:
            return files
    raise FileNotFoundError(f"no frames ({'/'.join(_EXTS)}) in {source}")


def load_stack(source, expected: int | None = None,
               io_workers: int | None = None):
    """Load a capture folder/list -> (frames uint8 [F,H,W], texture uint8 [H,W,3]).

    The texture is the white frame (frame 0) in color, per the reference's use
    of files[0] as the point-cloud color source (processing.py:124).

    ``io_workers``: per-frame decodes run on a bounded thread pool when > 1
    (cv2/PIL release the GIL inside the codec, so decodes genuinely overlap);
    None or <= 1 keeps the serial loop. Identical arrays either way — the
    pool only reorders WHEN each frame decodes, every frame still lands in
    its own preallocated slot.
    """
    from structured_light_for_3d_model_replication_tpu.io import native

    files = list_frame_files(source)
    if len(files) == 1 and files[0].endswith(PACKED_EXT):
        ps = load_packed_stack(files[0])
        if expected is not None and ps.n_frames < expected:
            raise ValueError(
                f"{source}: expected >= {expected} frames, found {ps.n_frames}")
        return unpack_stack(ps)
    if expected is not None and len(files) < expected:
        raise ValueError(f"{source}: expected >= {expected} frames, found {len(files)}")
    if len(files) < 4:
        raise ValueError(f"{source}: need at least 4 frames, found {len(files)}")
    # native thread-pooled decoder first: byte-exact for grayscale PNGs (the
    # pattern frames this framework writes); color-PNG gray conversion may
    # differ from cv2's SIMD path by +-1 level (inside every threshold's
    # tolerance). Header-only probe avoids decoding frame 0 twice.
    stack = None
    probe = native.probe_png(files[0])
    if probe is not None:
        stack = native.load_gray_stack(files, probe[0], probe[1])
    if stack is not None:
        frames = stack
    else:
        first = load_gray(files[0])
        frames = np.empty((len(files),) + first.shape, np.uint8)
        frames[0] = first

        def _load_into(i: int, p: str) -> None:
            img = load_gray(p)
            if img.shape != first.shape:
                raise ValueError(f"{p}: frame size {img.shape} != {first.shape}")
            frames[i] = img

        rest = list(enumerate(files[1:], start=1))
        if io_workers and io_workers > 1 and len(rest) > 1:
            # list() drains the map so the first decode error re-raises
            # here with its original traceback, like the serial loop
            list(_imread_pool(io_workers).map(lambda a: _load_into(*a), rest))
        else:
            for i, p in rest:
                _load_into(i, p)
    texture = load_color(files[0])
    return frames, texture


def save_stack(folder: str, frames: np.ndarray, ext: str = "png") -> list[str]:
    """Write frames as the reference's numbered-file contract (01.png, 02.png, ...)."""
    os.makedirs(folder, exist_ok=True)
    paths = []
    for i, frame in enumerate(frames):
        p = os.path.join(folder, f"{i + 1:02d}.{ext}")
        _imwrite(p, np.asarray(frame, np.uint8))
        paths.append(p)
    return paths


# ---------------------------------------------------------------------------
# Packed bit-plane codec (format spec in the module docstring)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PackedStack:
    """A Gray-code capture stack collapsed to what decode actually reads.

    ``planes`` is u8 [ceil(n_pairs/8), H, W]: pattern pair p's comparison bit
    (``pattern > inverse``) lives in byte p//8 at bit p%8 (LSB-first).
    ``white``/``black`` are the first two frames verbatim. A trailing unpaired
    frame (odd F-2) is never read by decode and is not stored; it unpacks as
    zeros.
    """

    planes: np.ndarray
    white: np.ndarray
    black: np.ndarray
    n_frames: int
    texture: np.ndarray | None = None

    @property
    def n_pairs(self) -> int:
        return (self.n_frames - 2) // 2

    @property
    def shape(self) -> tuple[int, int, int]:
        # matches the raw stack's [F, H, W] so shape-keyed batching logic
        # (bucket flushes, heterogeneity checks) is format-agnostic
        return (self.n_frames,) + self.white.shape

    @property
    def nbytes(self) -> int:
        """Wire size: the bytes a device upload of this stack actually moves."""
        return self.planes.nbytes + self.white.nbytes + self.black.nbytes


def pack_stack(frames: np.ndarray, texture: np.ndarray | None = None) -> PackedStack:
    """Pack a raw [F, H, W] u8 stack to bit-planes. Lossless for decode:
    ``decode_packed_np(pack_stack(f), ...)`` is bit-identical to
    ``decode_stack_np(f, ...)`` (the stored bits ARE decode's comparisons,
    and thresholds/mask read only the preserved white/black frames)."""
    frames = np.asarray(frames, np.uint8)
    if frames.ndim != 3 or frames.shape[0] < 4:
        raise ValueError(f"pack_stack: need [F>=4, H, W] u8, got {frames.shape}")
    n_pairs = (frames.shape[0] - 2) // 2
    pat = frames[2:2 + 2 * n_pairs:2].astype(np.int16)
    inv = frames[3:3 + 2 * n_pairs:2].astype(np.int16)
    bits = (pat > inv).astype(np.uint8)
    # bitorder="little" puts plane p at byte p//8, bit p%8 — the LSB-first
    # layout the on-device unpack kernel extracts with (byte >> (p & 7)) & 1
    planes = np.packbits(bits, axis=0, bitorder="little")
    return PackedStack(planes=planes, white=frames[0].copy(),
                       black=frames[1].copy(), n_frames=int(frames.shape[0]),
                       texture=None if texture is None
                       else np.asarray(texture, np.uint8))


def unpack_stack(ps: PackedStack):
    """Inverse of :func:`pack_stack` up to binarization: returns
    (frames u8 [F, H, W], texture u8 [H, W, 3]).

    Pattern frames come back binarized (pattern = 255*bit, inverse =
    255*(1-bit)); every decode comparison ``pattern > inverse`` evaluates
    identically to the raw stack's, so downstream results are bit-exact.
    Texture falls back to the white frame replicated to RGB when the
    container carries none."""
    F = ps.n_frames
    n_pairs = ps.n_pairs
    out = np.zeros((F,) + ps.white.shape, np.uint8)
    out[0] = ps.white
    out[1] = ps.black
    if n_pairs:
        bits = np.unpackbits(ps.planes, axis=0, count=n_pairs,
                             bitorder="little")
        out[2:2 + 2 * n_pairs:2] = bits * np.uint8(255)
        out[3:3 + 2 * n_pairs:2] = (1 - bits) * np.uint8(255)
    texture = ps.texture
    if texture is None:
        texture = np.repeat(ps.white[:, :, None], 3, axis=2)
    return out, texture


def packed_file(source) -> str | None:
    """The packed-container path for a source, or None if the source is raw."""
    if isinstance(source, (list, tuple)):
        if len(source) == 1 and str(source[0]).endswith(PACKED_EXT):
            return str(source[0])
        return None
    if isinstance(source, str):
        if source.endswith(PACKED_EXT) and os.path.isfile(source):
            return source
        if os.path.isdir(source):
            p = os.path.join(source, PACKED_NAME)
            if os.path.isfile(p):
                return p
    return None


def is_packed_source(source) -> bool:
    return packed_file(source) is not None


def count_frames(source) -> int:
    """Logical frame count of a source — header-only for packed containers,
    so planning never pays an unpack."""
    p = packed_file(source)
    if p is not None:
        hdr = probe_packed(p)
        if hdr is None:
            raise IOError(f"corrupt packed container: {p}")
        return int(hdr["n_frames"])
    return len(list_frame_files(source))


def probe_packed(path: str) -> dict | None:
    """Read just the header of a packed container; None if not one."""
    try:
        with open(path, "rb") as f:
            magic = f.read(len(_PACKED_MAGIC))
            if magic != _PACKED_MAGIC:
                return None
            (hlen,) = struct.unpack("<Q", f.read(8))
            if hlen > 1 << 20:
                return None
            return json.loads(f.read(hlen).decode("utf-8"))
    except (OSError, ValueError, struct.error):
        return None


def save_packed_stack(target: str, ps: PackedStack) -> str:
    """Write a packed container. ``target`` is the .slbp path or a folder
    (-> ``<folder>/frames.slbp``). The layout is a deterministic flat binary
    — magic, length-prefixed JSON header, raw sections — NOT an npz: zip
    members embed timestamps, and the stage cache keys on content bytes, so
    re-packing identical frames must produce identical bytes."""
    path = target if target.endswith(PACKED_EXT) \
        else os.path.join(target, PACKED_NAME)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    h, w = ps.white.shape
    header = {
        "height": int(h),
        "n_frames": int(ps.n_frames),
        "n_planes": int(ps.planes.shape[0]),
        "texture": ps.texture is not None,
        "version": 1,
        "width": int(w),
    }
    blob = json.dumps(header, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_PACKED_MAGIC)
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        f.write(np.ascontiguousarray(ps.white, np.uint8).tobytes())
        f.write(np.ascontiguousarray(ps.black, np.uint8).tobytes())
        f.write(np.ascontiguousarray(ps.planes, np.uint8).tobytes())
        if ps.texture is not None:
            f.write(np.ascontiguousarray(ps.texture, np.uint8).tobytes())
    os.replace(tmp, path)  # atomic: readers never see a torn container
    return path


def load_packed_stack(source) -> PackedStack:
    """Load a packed container from a .slbp path or a folder holding one."""
    path = packed_file(source)
    if path is None:
        raise FileNotFoundError(f"no packed container at {source}")
    with open(path, "rb") as f:
        magic = f.read(len(_PACKED_MAGIC))
        if magic != _PACKED_MAGIC:
            raise IOError(f"bad magic in {path}")
        (hlen,) = struct.unpack("<Q", f.read(8))
        hdr = json.loads(f.read(hlen).decode("utf-8"))
        h, w = int(hdr["height"]), int(hdr["width"])
        n_planes = int(hdr["n_planes"])

        def section(count, shape):
            raw = f.read(count)
            if len(raw) != count:
                raise IOError(f"truncated packed container: {path}")
            return np.frombuffer(raw, np.uint8).reshape(shape).copy()

        white = section(h * w, (h, w))
        black = section(h * w, (h, w))
        planes = section(n_planes * h * w, (n_planes, h, w))
        texture = section(h * w * 3, (h, w, 3)) if hdr.get("texture") else None
    return PackedStack(planes=planes, white=white, black=black,
                       n_frames=int(hdr["n_frames"]), texture=texture)


def pack_scan_folder(folder: str, keep_raw: bool = False) -> str:
    """Pack a captured raw-frame folder in place -> the .slbp path.

    Used by the acquire lane (``acquire.pack_frames``) right after a view's
    stripes land: the white frame's color read becomes the container texture,
    and unless ``keep_raw`` the now-redundant per-frame images are removed so
    ``list_frame_files`` resolves to the container alone."""
    files = list_frame_files(folder)
    if len(files) == 1 and files[0].endswith(PACKED_EXT):
        return files[0]  # already packed
    frames, texture = load_stack(folder)
    path = save_packed_stack(folder, pack_stack(frames, texture=texture))
    if not keep_raw:
        for p in files:
            try:
                os.remove(p)
            except OSError:
                pass
    return path
