"""Capture-stack image IO.

The reference reads scan folders of 46 numbered frames ("01.png".."46.png",
server/sl_system.py:126-150) one cv2.imread at a time inside the decode loop
(processing.py:95-101). Here the stack loads once into a [F, H, W] array (and
the white frame additionally as RGB texture), so the decode kernel sees a
single device buffer. cv2 is used when present; a PNG/PPM fallback via PIL
keeps the path alive without it.
"""
from __future__ import annotations

import glob
import os
import threading

import numpy as np

__all__ = ["list_frame_files", "load_stack", "save_stack", "load_gray",
           "load_color", "save_image"]

_EXTS = (".bmp", ".png", ".jpg", ".jpeg", ".ppm", ".pgm")

# one shared decode pool for the whole process: per-call executors cost
# ~ms of thread spin-up — more than a small frame decodes in — and a shared
# pool also caps TOTAL imread concurrency when the batch pipeline prefetches
# several stacks at once. Grown (never shrunk) to the largest request.
_POOL: "object | None" = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()


def _imread_pool(workers: int):
    from concurrent.futures import ThreadPoolExecutor

    global _POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _POOL is None or _POOL_SIZE < workers:
            old = _POOL
            _POOL = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="sl3d-imread")
            _POOL_SIZE = workers
            if old is not None:
                old.shutdown(wait=False)
        return _POOL


def _imread(path: str, gray: bool):
    try:
        import cv2

        img = cv2.imread(path, 0 if gray else 1)
        if img is None:
            raise IOError(f"unreadable image: {path}")
        if not gray:
            img = img[:, :, ::-1]  # BGR -> RGB at the IO boundary, once
        return img
    except ImportError:
        from PIL import Image

        img = Image.open(path)
        img = img.convert("L" if gray else "RGB")
        return np.asarray(img)


def _imwrite(path: str, img: np.ndarray):
    try:
        import cv2

        ok = cv2.imwrite(path, img if img.ndim == 2 else img[:, :, ::-1])
        if not ok:
            raise IOError(f"failed to write {path}")
    except ImportError:
        from PIL import Image

        Image.fromarray(img).save(path)


def save_image(path: str, img: np.ndarray) -> None:
    """Write one image; color images are RGB (the IO-boundary convention)."""
    _imwrite(path, np.asarray(img, np.uint8))


def load_gray(path: str) -> np.ndarray:
    return _imread(path, gray=True)


def load_color(path: str) -> np.ndarray:
    """Returns RGB uint8 [H, W, 3]."""
    return _imread(path, gray=False)


def list_frame_files(source) -> list[str]:
    """Resolve a scan source (folder or explicit file list) to a sorted frame list.

    Mirrors the reference's resolution order: .bmp glob first, then .png
    (processing.py:49-54), extended with the other common formats.
    """
    if isinstance(source, (list, tuple)):
        return list(source)
    if not os.path.isdir(source):
        raise FileNotFoundError(f"scan folder not found: {source}")
    for ext in _EXTS:
        files = sorted(glob.glob(os.path.join(source, f"*{ext}")))
        if files:
            return files
    raise FileNotFoundError(f"no frames ({'/'.join(_EXTS)}) in {source}")


def load_stack(source, expected: int | None = None,
               io_workers: int | None = None):
    """Load a capture folder/list -> (frames uint8 [F,H,W], texture uint8 [H,W,3]).

    The texture is the white frame (frame 0) in color, per the reference's use
    of files[0] as the point-cloud color source (processing.py:124).

    ``io_workers``: per-frame decodes run on a bounded thread pool when > 1
    (cv2/PIL release the GIL inside the codec, so decodes genuinely overlap);
    None or <= 1 keeps the serial loop. Identical arrays either way — the
    pool only reorders WHEN each frame decodes, every frame still lands in
    its own preallocated slot.
    """
    from structured_light_for_3d_model_replication_tpu.io import native

    files = list_frame_files(source)
    if expected is not None and len(files) < expected:
        raise ValueError(f"{source}: expected >= {expected} frames, found {len(files)}")
    if len(files) < 4:
        raise ValueError(f"{source}: need at least 4 frames, found {len(files)}")
    # native thread-pooled decoder first: byte-exact for grayscale PNGs (the
    # pattern frames this framework writes); color-PNG gray conversion may
    # differ from cv2's SIMD path by +-1 level (inside every threshold's
    # tolerance). Header-only probe avoids decoding frame 0 twice.
    stack = None
    probe = native.probe_png(files[0])
    if probe is not None:
        stack = native.load_gray_stack(files, probe[0], probe[1])
    if stack is not None:
        frames = stack
    else:
        first = load_gray(files[0])
        frames = np.empty((len(files),) + first.shape, np.uint8)
        frames[0] = first

        def _load_into(i: int, p: str) -> None:
            img = load_gray(p)
            if img.shape != first.shape:
                raise ValueError(f"{p}: frame size {img.shape} != {first.shape}")
            frames[i] = img

        rest = list(enumerate(files[1:], start=1))
        if io_workers and io_workers > 1 and len(rest) > 1:
            # list() drains the map so the first decode error re-raises
            # here with its original traceback, like the serial loop
            list(_imread_pool(io_workers).map(lambda a: _load_into(*a), rest))
        else:
            for i, p in rest:
                _load_into(i, p)
    texture = load_color(files[0])
    return frames, texture


def save_stack(folder: str, frames: np.ndarray, ext: str = "png") -> list[str]:
    """Write frames as the reference's numbered-file contract (01.png, 02.png, ...)."""
    os.makedirs(folder, exist_ok=True)
    paths = []
    for i, frame in enumerate(frames):
        p = os.path.join(folder, f"{i + 1:02d}.{ext}")
        _imwrite(p, np.asarray(frame, np.uint8))
        paths.append(p)
    return paths
