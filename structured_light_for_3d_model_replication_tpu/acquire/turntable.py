"""Turntable control: the serial line protocol to the stepper firmware.

Capability parity (behavior studied from server/arduino.py:5-71 and the
ESP_code.ino sketches): the host writes ``"<degrees>\n"`` at 115200 baud; the
firmware rotates (blocking) and answers ``"DONE"``. The driver scans candidate
ports, waits out the boot delay after opening, and polls for the DONE line
with a timeout.

Three interchangeable backends behind one interface:
  SerialTurntable    real hardware (requires pyserial, imported lazily)
  SimulatedTurntable no hardware — fixed-delay stand-in (the reference's
                     "Simulation" auto-scan mode, server/gui.py:1705-1779)
  LoopbackTurntable  deterministic in-memory fake for tests (records every
                     command; configurable latency/failures)
"""
from __future__ import annotations

import time

from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = [
    "TurntableError",
    "SerialTurntable",
    "SimulatedTurntable",
    "LoopbackTurntable",
    "open_turntable",
]


class TurntableError(RuntimeError):
    pass


class SerialTurntable:
    """pyserial-backed driver speaking the ``<deg>\\n`` -> ``DONE`` protocol."""

    BAUD = 115200
    BOOT_WAIT_S = 2.0  # firmware resets on port open (server/arduino.py:16-27)

    def __init__(self, port: str | None = None, boot_wait: float | None = None):
        try:
            import serial
            import serial.tools.list_ports
        except ImportError as e:  # pragma: no cover - environment dependent
            raise TurntableError(
                "SerialTurntable requires pyserial; use SimulatedTurntable "
                "or LoopbackTurntable without hardware"
            ) from e
        self._serial_mod = serial
        if port is None:
            ports = self.available_ports()
            if not ports:
                raise TurntableError("no serial ports found")
            port = ports[0]
        self.port_name = port
        self._conn = serial.Serial(port, self.BAUD, timeout=0.1)
        time.sleep(self.BOOT_WAIT_S if boot_wait is None else boot_wait)
        self._conn.reset_input_buffer()

    @staticmethod
    def available_ports() -> list[str]:
        try:
            from serial.tools import list_ports
        except ImportError:  # pragma: no cover
            return []
        return [p.device for p in list_ports.comports()]

    def rotate(self, degrees: float) -> None:
        faults.fire("serial.rotate", item=self.port_name)
        # drop any stale DONE from a previously timed-out rotation, or the
        # next wait_for_done would return before the table stops moving
        self._conn.reset_input_buffer()
        self._conn.write(f"{degrees}\n".encode())
        self._conn.flush()

    def reopen(self) -> None:
        """Recovery path for a wedged/dropped serial line: close and re-open
        the port (the firmware resets on open, so this is also the bounded
        re-home — the table holds position, the controller restarts clean).
        The boot delay is paid again; callers re-issue the lost rotation."""
        try:
            self._conn.close()
        except Exception:
            pass
        self._conn = self._serial_mod.Serial(self.port_name, self.BAUD,
                                             timeout=0.1)
        time.sleep(self.BOOT_WAIT_S)
        self._conn.reset_input_buffer()

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        """Poll for the firmware's DONE line at ~10 Hz (server/arduino.py:49-71)."""
        deadline = time.monotonic() + timeout
        buf = b""
        while time.monotonic() < deadline:
            buf += self._conn.read(64)
            if b"DONE" in buf:
                return True
            time.sleep(0.1)
        return False

    def close(self) -> None:
        self._conn.close()


class SimulatedTurntable:
    """Hardware-free stand-in: rotations 'complete' after a fixed delay."""

    def __init__(self, rotate_time_s: float = 2.0):
        self.rotate_time_s = rotate_time_s
        self.angle = 0.0
        self._done_at = 0.0

    def rotate(self, degrees: float) -> None:
        faults.fire("serial.rotate", item="sim")
        self.angle = (self.angle + degrees) % 360.0
        self._done_at = time.monotonic() + self.rotate_time_s

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        remaining = self._done_at - time.monotonic()
        if remaining > timeout:
            time.sleep(timeout)
            return False
        if remaining > 0:
            time.sleep(remaining)
        return True

    def close(self) -> None:
        pass


class LoopbackTurntable:
    """Test fake: instant (or scripted) completion, full command log."""

    def __init__(self, fail_after: int | None = None,
                 recover_on_reopen: bool = True):
        self.commands: list[float] = []
        self.fail_after = fail_after
        self.recover_on_reopen = recover_on_reopen
        self.reopens = 0
        self.closed = False

    def rotate(self, degrees: float) -> None:
        faults.fire("serial.rotate", item="loopback")
        self.commands.append(float(degrees))

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        if self.fail_after is not None and len(self.commands) > self.fail_after:
            return False
        return True

    def reopen(self) -> None:
        """Models the serial recovery path: by default the fake 'hardware'
        comes back healthy after a reopen (``recover_on_reopen=False``
        scripts a permanently dead line)."""
        self.reopens += 1
        if self.recover_on_reopen:
            self.fail_after = None

    @property
    def angle(self) -> float:
        return sum(self.commands) % 360.0

    def close(self) -> None:
        self.closed = True


def open_turntable(kind: str = "auto", port: str | None = None,
                   rotate_time_s: float = 2.0):
    """Factory: ``serial``, ``sim``, ``loopback``, or ``auto`` (serial when a
    port exists, else simulation — the reference's confirm-dialog fallback)."""
    if kind == "serial":
        return SerialTurntable(port)
    if kind == "sim":
        return SimulatedTurntable(rotate_time_s)
    if kind == "loopback":
        return LoopbackTurntable()
    if kind == "auto":
        try:
            return SerialTurntable(port)
        except TurntableError:
            return SimulatedTurntable(rotate_time_s)
    raise ValueError(f"unknown turntable kind: {kind}")
