"""Capture sequencer: project the Gray-code stack and collect one frame each.

Capability parity (behavior studied from server/sl_system.py:114-182,430-486):
a scan of a 1920x1080 projector is 46 frames — white, black, then
pattern/inverse pairs for 11 column bits and 11 row bits — written to a pose
folder as ``01.png``..``46.png``. Calibration capture repeats the same
sequence once per chessboard pose with a longer settle. The capture trigger
is pluggable: the HTTP rendezvous (CaptureServer.trigger_capture), the
Android host client, or any callable ``(save_path) -> None``.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Sequence

import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import graycode as gc

__all__ = ["CaptureSequencer", "scan_frame_names"]

CaptureFn = Callable[[str], object]


def scan_frame_names(n_frames: int, ext: str = "png") -> list[str]:
    """The numbered-file contract: 01.png, 02.png, ... (server/sl_system.py:146)."""
    return [f"{i + 1:02d}.{ext}" for i in range(n_frames)]


class CaptureSequencer:
    """Drives projector + camera through one full pattern sequence per pose."""

    def __init__(self, projector, capture: CaptureFn,
                 proj_size: tuple[int, int] = (1920, 1080),
                 brightness: int = 200, downsample: int = 1,
                 scan_settle_ms: int = 200, calib_settle_ms: int = 250,
                 pack_frames: bool = False, pack_keep_raw: bool = False,
                 log=print):
        self.projector = projector
        self.capture = capture
        self.proj_size = proj_size
        self.brightness = brightness
        self.downsample = downsample
        self.scan_settle_ms = scan_settle_ms
        self.calib_settle_ms = calib_settle_ms
        self.pack_frames = pack_frames
        self.pack_keep_raw = pack_keep_raw
        self.log = log
        self._patterns: np.ndarray | None = None

    @property
    def patterns(self) -> np.ndarray:
        if self._patterns is None:
            self._patterns = gc.generate_pattern_stack(
                self.proj_size[0], self.proj_size[1],
                brightness=self.brightness, downsample=self.downsample,
            )
        return self._patterns

    def capture_sequence(self, save_dir: str, settle_ms: int,
                         progress: Callable[[int, int], None] | None = None
                         ) -> list[str]:
        """Project every frame, capturing each to its numbered file."""
        os.makedirs(save_dir, exist_ok=True)
        frames = self.patterns
        names = scan_frame_names(frames.shape[0])
        paths = []
        t0 = time.monotonic()
        for i, (frame, name) in enumerate(zip(frames, names)):
            self.projector.show(frame, settle_ms)
            path = os.path.join(save_dir, name)
            self.capture(path)
            paths.append(path)
            if progress:
                progress(i + 1, frames.shape[0])
        self.log(f"[capture] {len(paths)} frames -> {save_dir} "
                 f"({time.monotonic() - t0:.1f}s)")
        return paths

    def capture_scan(self, save_dir: str,
                     progress: Callable[[int, int], None] | None = None
                     ) -> list[str]:
        """One object scan (46 frames at 1080p), scan settle time.

        With ``pack_frames`` the landed sequence is immediately packed to
        the 1-bit bit-plane container (``frames.slbp``, io/images.py) —
        the scan folder ships ~8x fewer bytes and the pipeline's packed
        ingest uploads it as-is. Calibration captures are never packed:
        chessboard detection needs the full grayscale frames. A failure
        here raises like any capture failure, so auto-scan's per-view
        retry budget (``acquire.capture_retries``) covers it."""
        paths = self.capture_sequence(save_dir, self.scan_settle_ms,
                                      progress)
        if self.pack_frames:
            from structured_light_for_3d_model_replication_tpu.io import (
                images as imio,
            )
            from structured_light_for_3d_model_replication_tpu.utils import (
                faults,
            )

            faults.fire("frame.pack", item=save_dir)
            packed = imio.pack_scan_folder(save_dir,
                                           keep_raw=self.pack_keep_raw)
            self.log(f"[capture] packed -> {packed} "
                     f"({os.path.getsize(packed)} B)")
            paths = [packed] + (paths if self.pack_keep_raw else [])
        return paths

    def capture_calibration(self, save_dir: str, num_poses: int,
                            on_pose: Callable[[int], None] | None = None,
                            pose_names: Sequence[str] | None = None
                            ) -> list[str]:
        """Calibration capture: one full sequence per chessboard pose.

        ``on_pose(i)`` is the operator hook between poses (the reference blocks
        on a messagebox while the user repositions the board,
        server/sl_system.py:158-165); in scripted runs it can rotate a fixture.
        """
        done = []
        for p in range(num_poses):
            if on_pose:
                on_pose(p)
            name = pose_names[p] if pose_names else f"pose{p + 1:02d}"
            pose_dir = os.path.join(save_dir, name)
            self.capture_sequence(pose_dir, self.calib_settle_ms)
            done.append(pose_dir)
        return done
