"""Local-webcam capture backend.

Capability parity (behavior studied from Old/sl_calib_capture.py:1-126): the
reference's legacy path captures with a locally attached webcam via
cv2.VideoCapture instead of the phone — proving the capture trigger is
swappable. This backend plugs the same ``capture(save_path)`` contract the
CaptureSequencer takes, so projector sequencing, calibration capture, and
auto-scan all work with a USB camera and no phone/server at all.
"""
from __future__ import annotations

import numpy as np

__all__ = ["WebcamCapture"]


class WebcamCapture:
    """``capture(save_path)`` against a local cv2.VideoCapture device.

    Parameters: device index, requested size, and how many frames to discard
    per trigger so auto-exposure settles on the new pattern (the legacy script
    grabs several frames per capture for the same reason).
    """

    def __init__(self, device: int = 0, size: tuple[int, int] | None = None,
                 warmup_frames: int = 3):
        import cv2

        self._cv2 = cv2
        self.cap = cv2.VideoCapture(device)
        if not self.cap.isOpened():
            raise RuntimeError(f"cannot open webcam device {device}")
        if size is not None:
            self.cap.set(cv2.CAP_PROP_FRAME_WIDTH, size[0])
            self.cap.set(cv2.CAP_PROP_FRAME_HEIGHT, size[1])
        self.warmup_frames = warmup_frames

    def read(self) -> np.ndarray:
        for _ in range(self.warmup_frames):
            self.cap.grab()
        ok, frame = self.cap.read()
        if not ok:
            raise RuntimeError("webcam read failed")
        return frame

    def __call__(self, save_path: str) -> str:
        frame = self.read()
        if not self._cv2.imwrite(save_path, frame):
            raise IOError(f"failed to write {save_path}")
        return save_path

    def close(self) -> None:
        self.cap.release()

    def __enter__(self) -> "WebcamCapture":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
