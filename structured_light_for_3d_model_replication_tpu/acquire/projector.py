"""Projector display: fullscreen pattern presentation on the second monitor.

Capability parity (behavior studied from server/sl_system.py:22-42,470-476):
an OpenCV window is created at the projector's screen offset, forced
fullscreen, and each pattern is shown with a settle delay before the capture
triggers. A virtual backend records frames for headless runs and tests.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["OpenCVProjector", "VirtualProjector", "open_projector"]


class OpenCVProjector:
    """Real projector output via an OpenCV fullscreen window (cv2-gated)."""

    WINDOW = "slscan-projector"

    def __init__(self, screen_offset_x: int = 1920, width: int = 1920,
                 height: int = 1080):
        import cv2

        self._cv2 = cv2
        self.size = (width, height)
        cv2.namedWindow(self.WINDOW, cv2.WINDOW_NORMAL)
        cv2.moveWindow(self.WINDOW, screen_offset_x, 0)
        cv2.setWindowProperty(
            self.WINDOW, cv2.WND_PROP_FULLSCREEN, cv2.WINDOW_FULLSCREEN
        )

    def show(self, frame: np.ndarray, settle_ms: int = 200) -> None:
        """Display one pattern and block for the projector settle time."""
        self._cv2.imshow(self.WINDOW, np.asarray(frame, np.uint8))
        self._cv2.waitKey(max(1, int(settle_ms)))

    def close(self) -> None:
        self._cv2.destroyWindow(self.WINDOW)


class VirtualProjector:
    """Headless backend: records every shown frame (tests, dry runs)."""

    def __init__(self, width: int = 1920, height: int = 1080,
                 realtime: bool = False):
        self.size = (width, height)
        self.realtime = realtime
        self.shown: list[np.ndarray] = []
        self.settle_log: list[int] = []

    def show(self, frame: np.ndarray, settle_ms: int = 200) -> None:
        self.shown.append(np.asarray(frame, np.uint8).copy())
        self.settle_log.append(int(settle_ms))
        if self.realtime:
            time.sleep(settle_ms / 1000.0)

    def close(self) -> None:
        pass


def open_projector(kind: str = "auto", screen_offset_x: int = 1920,
                   width: int = 1920, height: int = 1080):
    """Factory: ``opencv``, ``virtual``, or ``auto`` (opencv when importable +
    a display exists, else virtual)."""
    if kind == "opencv":
        return OpenCVProjector(screen_offset_x, width, height)
    if kind == "virtual":
        return VirtualProjector(width, height)
    if kind == "auto":
        try:
            return OpenCVProjector(screen_offset_x, width, height)
        except Exception:
            return VirtualProjector(width, height)
    raise ValueError(f"unknown projector kind: {kind}")
