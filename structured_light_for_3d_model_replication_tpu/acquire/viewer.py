"""Operator web viewer: browse and orbit per-stage artifacts (point-cloud
PLYs, mesh STLs) from any browser on the LAN.

Capability parity: the reference's operator front-end shows clouds/meshes at
every stage — the blocking per-step Open3D merge preview
(server/processing.py:600-603, server/gui.py:1549-1564), the cleanup tab's
in-memory per-step point counts (gui.py:1391-1522), and the auto-scan
progress popup (gui.py:1740-1783). This module provides the web-native
equivalent: a dependency-free single-page viewer (inline JS PLY/STL parsers +
2D-canvas painter projection — no CDN, works in a zero-egress lab) served by
the same stdlib HTTP stack as the capture server, plus a ``StageRecorder``
callback that persists each merge step as an artifact so previews are
non-blocking and re-entrant instead of modal.

Endpoints
---------
  GET /              the viewer page
  GET /api/list      JSON: artifacts ({name, bytes, mtime, kind}) + progress
  GET /api/file?name=X  raw bytes of one artifact (PLY/STL only, no traversal)
  GET /api/progress  JSON: the live stage-progress feed (auto-scan parity)
  GET /api/poses     JSON: pending calibration pose review (per-pose
                     reprojection errors), when one is active
  POST /api/poses    {"keep": [names]} — the operator's pose selection;
                     the reference's click-to-prune dialog
                     (server/gui.py:1211-1250) as a non-modal web flow:
                     ``sl3d calibrate --review`` publishes the errors here
                     and waits for this POST before the final solve
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

__all__ = ["ViewerServer", "StageRecorder"]

_EXTS = (".ply", ".stl", ".png")
POSE_REVIEW_FILE = "pose_review.json"       # published by calibrate --review
POSE_SELECTION_FILE = "pose_selection.json"  # written back by the operator


def publish_pose_review(artifact_dir: str, errors: dict) -> str:
    """Publish per-pose (cam_px, proj_px) reprojection errors for the
    viewer's review panel; clears any stale selection. Returns the path."""
    os.makedirs(artifact_dir, exist_ok=True)
    sel = os.path.join(artifact_dir, POSE_SELECTION_FILE)
    if os.path.exists(sel):
        os.remove(sel)
    path = os.path.join(artifact_dir, POSE_REVIEW_FILE)
    payload = {"status": "pending",
               "poses": {name: {"cam_px": round(float(ec), 3),
                                "proj_px": round(float(ep), 3)}
                         for name, (ec, ep) in errors.items()}}
    with open(path + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)
    return path


def await_pose_selection(artifact_dir: str, timeout: float = 600.0,
                         poll: float = 0.5) -> list[str] | None:
    """Block until the operator POSTs a selection (or ``timeout``); returns
    the kept pose names, or None on timeout. Consumes the selection file
    and marks the review done."""
    sel = os.path.join(artifact_dir, POSE_SELECTION_FILE)
    # monotonic, never wall-clock: an NTP step or suspend/resume must not
    # stretch or collapse the wait (turntable.wait_for_done's convention)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(sel):
            with open(sel) as f:
                keep = json.load(f).get("keep", [])
            review = os.path.join(artifact_dir, POSE_REVIEW_FILE)
            if os.path.exists(review):
                os.remove(review)
            return [str(k) for k in keep]
        time.sleep(poll)
    # timed out: clear the review too — a pending panel that nothing will
    # ever consume would keep soliciting (and falsely acknowledging)
    # selections after calibration already finished with auto pruning
    review = os.path.join(artifact_dir, POSE_REVIEW_FILE)
    if os.path.exists(review):
        os.remove(review)
    return None


class StageRecorder:
    """Persist per-stage artifacts + progress lines for the viewer.

    Use as ``merge_360(..., step_callback=StageRecorder(dir).merge_step)``:
    each chain step writes ``merge_step_NN.ply`` (the reference's blocking
    per-step preview, processing.py:600-603, made non-blocking) and appends a
    progress entry the viewer polls (gui.py:1740-1783's elapsed/remaining
    readout)."""

    def __init__(self, artifact_dir: str, max_points_per_step: int = 200_000):
        self.dir = artifact_dir
        self.max_points = int(max_points_per_step)
        self._t0 = time.time()
        self._lock = threading.Lock()
        os.makedirs(artifact_dir, exist_ok=True)
        self._progress_path = os.path.join(artifact_dir, "progress.json")
        self._events: list[dict] = []

    def log_stage(self, stage: str, **info) -> None:
        with self._lock:
            self._events.append({"stage": stage, "t": round(time.time() - self._t0, 2),
                                 **info})
            tmp = self._progress_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._events, f)
            os.replace(tmp, self._progress_path)

    def merge_step(self, i: int, points, colors, total=None) -> None:
        """merge step_callback consumer. The contract hands over ONLY the
        newly folded view's arrays plus the running point count (O(new
        view) per step — the old full-list form was O(V) per step, O(V^2)
        over a chain): the recorder keeps its own per-view accumulation for
        the strided preview. ``i == 0`` seeds the base view without writing
        a step artifact (matching the historical first artifact at step 1).
        A LIST ``points``/``colors`` is still accepted as the legacy
        full-state form (``total`` ignored)."""
        from structured_light_for_3d_model_replication_tpu.io import ply

        if isinstance(points, (list, tuple)):
            views_p = [np.asarray(p) for p in points]
            views_c = [np.asarray(c) for c in colors]
        else:
            with self._lock:
                if i == 0:
                    self._merge_p, self._merge_c = [], []
                elif not hasattr(self, "_merge_p"):
                    self._merge_p, self._merge_c = [], []
                self._merge_p.append(np.asarray(points))
                self._merge_c.append(np.asarray(colors))
                views_p, views_c = list(self._merge_p), list(self._merge_c)
            if i == 0:
                return
        total = int(total) if total is not None \
            else sum(len(p) for p in views_p)
        stride = max(1, total // self.max_points)
        pts = np.concatenate([p[::stride] for p in views_p])
        cols = np.concatenate([c[::stride] for c in views_c])
        path = os.path.join(self.dir, f"merge_step_{i:02d}.ply")
        # atomic: the viewer may serve this file mid-merge
        ply.write_ply(path + ".tmp", pts, cols)
        os.replace(path + ".tmp", path)
        self.log_stage("merge", step=i, points=int(total),
                       file=os.path.basename(path))

    def autoscan_progress(self, info: dict) -> None:
        """acquire.autoscan progress hook: the live elapsed / estimated-
        remaining readout of the reference's auto-scan popup
        (gui.py:1740-1783), polled by the viewer page instead of modal."""
        self.log_stage("autoscan", view=info.get("view"),
                       turns=info.get("turns"), angle=info.get("angle"),
                       elapsed_s=round(float(info.get("elapsed_s", 0.0)), 1),
                       remaining_s=round(float(info.get("remaining_s", 0.0)), 1))

    def save_cloud(self, name: str, points: np.ndarray,
                   colors: np.ndarray | None = None) -> str:
        """Preview-capped (max_points_per_step stride) atomic PLY write +
        progress entry; the recorder's generic per-stage artifact hook
        (cleanup chain steps, ad-hoc inspection dumps)."""
        from structured_light_for_3d_model_replication_tpu.io import ply

        total = len(points)
        stride = max(1, total // self.max_points)
        pts = np.asarray(points)[::stride]
        if colors is None:
            cols = np.full((len(pts), 3), 180, np.uint8)
        else:
            cols = np.asarray(colors)[::stride]
        path = os.path.join(self.dir, name if name.endswith(".ply") else name + ".ply")
        ply.write_ply(path + ".tmp", pts, cols)
        os.replace(path + ".tmp", path)
        self.log_stage("cloud", points=int(total), file=os.path.basename(path))
        return path


class _ViewerHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # pragma: no cover - logging detail
        pass

    @property
    def root(self) -> str:
        return self.server.artifact_dir  # type: ignore[attr-defined]

    def _bytes(self, payload: bytes, ctype: str, code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.send_header("Access-Control-Allow-Origin", "*")
        self.end_headers()
        self.wfile.write(payload)

    def _json(self, obj, code: int = 200) -> None:
        self._bytes(json.dumps(obj).encode(), "application/json", code)

    def do_GET(self):  # noqa: N802 (stdlib handler contract)
        url = urlparse(self.path)
        if url.path in ("/", "/index.html"):
            self._bytes(_PAGE.encode(), "text/html; charset=utf-8")
        elif url.path == "/api/list":
            items = []
            try:
                for name in sorted(os.listdir(self.root)):
                    if not name.lower().endswith(_EXTS):
                        continue
                    st = os.stat(os.path.join(self.root, name))
                    items.append({"name": name, "bytes": st.st_size,
                                  "mtime": st.st_mtime,
                                  "kind": name.rsplit(".", 1)[-1].lower()})
            except FileNotFoundError:
                pass
            self._json({"artifacts": items})
        elif url.path == "/api/progress":
            p = os.path.join(self.root, "progress.json")
            if os.path.exists(p):
                with open(p, "rb") as f:
                    self._bytes(f.read(), "application/json")
            else:
                self._json([])
        elif url.path == "/api/poses":
            p = os.path.join(self.root, POSE_REVIEW_FILE)
            if os.path.exists(p):
                with open(p, "rb") as f:
                    self._bytes(f.read(), "application/json")
            else:
                self._json({"status": "none", "poses": {}})
        elif url.path == "/api/file":
            name = parse_qs(url.query).get("name", [""])[0]
            # no traversal: basename only, known extensions only
            safe = os.path.basename(name)
            if safe != name or not safe.lower().endswith(_EXTS):
                self._json({"error": "bad name"}, 400)
                return
            full = os.path.join(self.root, safe)
            if not os.path.exists(full):
                self._json({"error": "not found"}, 404)
                return
            ctype = ("image/png" if safe.lower().endswith(".png")
                     else "application/octet-stream")
            with open(full, "rb") as f:
                self._bytes(f.read(), ctype)
        else:
            self._json({"error": "unknown endpoint"}, 404)

    def do_POST(self):  # noqa: N802 (stdlib handler contract)
        url = urlparse(self.path)
        if url.path != "/api/poses":
            self._json({"error": "unknown endpoint"}, 404)
            return
        if not os.path.exists(os.path.join(self.root, POSE_REVIEW_FILE)):
            self._json({"error": "no pose review pending"}, 409)
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(n) or b"{}")
            keep = body["keep"]
            assert isinstance(keep, list)
        except Exception:
            self._json({"error": "body must be JSON {\"keep\": [names]}"}, 400)
            return
        sel = os.path.join(self.root, POSE_SELECTION_FILE)
        with open(sel + ".tmp", "w") as f:
            json.dump({"keep": [str(k) for k in keep],
                       "t": time.time()}, f)
        os.replace(sel + ".tmp", sel)
        self._json({"ok": True, "kept": len(keep)})


class ViewerServer:
    """Threaded artifact viewer on ``http://host:port/`` for one directory."""

    def __init__(self, artifact_dir: str, host: str = "0.0.0.0",
                 port: int = 5051):
        self.artifact_dir = artifact_dir
        self._httpd = ThreadingHTTPServer((host, port), _ViewerHandler)
        self._httpd.artifact_dir = artifact_dir  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "ViewerServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "ViewerServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# self-contained page: PLY/STL parsing + orbit rendering in vanilla JS on a
# 2D canvas (painter projection) — zero external assets by design
_PAGE = r"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>slscan viewer</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
 body{margin:0;font:14px system-ui,sans-serif;background:#14161a;color:#dde}
 #bar{padding:8px 12px;background:#1d2026;display:flex;gap:12px;align-items:center;flex-wrap:wrap}
 select,button{background:#2a2e36;color:#dde;border:1px solid #444;border-radius:4px;padding:4px 8px}
 #cv{display:block;width:100vw;height:calc(100vh - 46px);touch-action:none}
 #info{opacity:.75}
</style></head><body>
<div id="bar">
 <b>slscan</b>
 <select id="sel"></select>
 <button id="reload">refresh</button>
 <span id="info">pick an artifact</span>
</div>
<div id="poses" style="display:none;padding:8px 12px;background:#171b22">
 <b>Calibration pose review</b>
 <span style="opacity:.7">— untick bad poses, then apply
 (&lt;0.5 px EXCELLENT, &lt;1.0 px GOOD, else POOR)</span>
 <table id="posetab" style="border-collapse:collapse;margin:6px 0"></table>
 <button id="poseapply">Apply selection</button>
 <span id="posemsg"></span>
</div>
<canvas id="cv"></canvas>
<script>
"use strict";
let pts=null, cols=null, tris=null, center=[0,0,0], scale=1;
let rotX=-0.4, rotY=0.6, zoom=1, drag=null;
const cv=document.getElementById('cv'), ctx=cv.getContext('2d');
const info=document.getElementById('info'), sel=document.getElementById('sel');

function fit(){cv.width=cv.clientWidth; cv.height=cv.clientHeight;}
window.addEventListener('resize',()=>{fit();draw();});

async function list(){
  const r=await fetch('api/list'); const j=await r.json();
  const cur=sel.value;
  sel.innerHTML='';
  for(const a of j.artifacts){
    const o=document.createElement('option');
    o.value=a.name; o.textContent=`${a.name} (${(a.bytes/1e6).toFixed(1)} MB)`;
    sel.appendChild(o);
  }
  if(cur) sel.value=cur;
  if(!cur && j.artifacts.length){sel.value=j.artifacts[j.artifacts.length-1].name; load();}
}
sel.onchange=load;
document.getElementById('reload').onclick=list;

function parsePLY(buf){
  const head=new TextDecoder().decode(buf.slice(0,4096));
  const end=head.indexOf('end_header');
  if(end<0) throw 'no PLY header';
  const headerTxt=head.slice(0,end);
  const lines=headerTxt.split('\n').map(s=>s.trim());
  let n=0, props=[], fmt='ascii';
  for(const l of lines){
    if(l.startsWith('format')) fmt=l.split(/\s+/)[1];
    else if(l.startsWith('element vertex')) n=parseInt(l.split(/\s+/)[2]);
    else if(l.startsWith('element')&&!l.includes('vertex')) break;
    else if(l.startsWith('property')&&n>0){const p=l.split(/\s+/);props.push({t:p[1],n:p[2]});}
  }
  const bodyOff=head.indexOf('\n',end)+1;
  const P=new Float32Array(n*3), C=new Uint8Array(n*3).fill(200);
  if(fmt==='ascii'){
    const txt=new TextDecoder().decode(buf.slice(bodyOff));
    const rows=txt.split('\n');
    for(let i=0;i<n;i++){
      const v=rows[i].trim().split(/\s+/).map(Number);
      const m={}; props.forEach((p,k)=>m[p.n]=v[k]);
      P[3*i]=m.x;P[3*i+1]=m.y;P[3*i+2]=m.z;
      if('red' in m){C[3*i]=m.red;C[3*i+1]=m.green;C[3*i+2]=m.blue;}
    }
  } else {
    const little=fmt.includes('little');
    const sz={float:4,float32:4,double:8,uchar:1,uint8:1,char:1,int:4,int32:4,uint:4,short:2,ushort:2};
    let stride=0; const offs=[];
    for(const p of props){offs.push(stride); stride+=sz[p.t]||4;}
    const dv=new DataView(buf,bodyOff);
    const get=(t,off)=> t==='double'?dv.getFloat64(off,little)
      :(t==='uchar'||t==='uint8'||t==='char')?dv.getUint8(off)
      :(t==='short'||t==='ushort')?dv.getUint16(off,little)
      :(t==='int'||t==='int32'||t==='uint')?dv.getInt32(off,little)
      :dv.getFloat32(off,little);
    for(let i=0;i<n;i++){
      const base=i*stride; const m={};
      props.forEach((p,k)=>m[p.n]=get(p.t,base+offs[k]));
      P[3*i]=m.x;P[3*i+1]=m.y;P[3*i+2]=m.z;
      if('red' in m){C[3*i]=m.red;C[3*i+1]=m.green;C[3*i+2]=m.blue;}
    }
  }
  return {P,C,T:null};
}

function parseSTL(buf){
  const dv=new DataView(buf);
  // binary STL: 80-byte header + uint32 count
  const nt=dv.getUint32(80,true);
  if(84+nt*50===buf.byteLength){
    const P=new Float32Array(nt*9), T=new Uint32Array(nt*3);
    for(let i=0;i<nt;i++){
      const b=84+i*50+12;
      for(let v=0;v<3;v++)for(let c=0;c<3;c++)
        P[9*i+3*v+c]=dv.getFloat32(b+12*v+4*c,true);
      T[3*i]=3*i;T[3*i+1]=3*i+1;T[3*i+2]=3*i+2;
    }
    return {P,C:null,T};
  }
  // ascii STL
  const txt=new TextDecoder().decode(buf);
  const v=[...txt.matchAll(/vertex\s+([-\d.eE+]+)\s+([-\d.eE+]+)\s+([-\d.eE+]+)/g)];
  const P=new Float32Array(v.length*3), T=new Uint32Array(v.length);
  v.forEach((m,i)=>{P[3*i]=+m[1];P[3*i+1]=+m[2];P[3*i+2]=+m[3];T[i]=i;});
  return {P,C:null,T};
}

async function load(){
  const name=sel.value; if(!name) return;
  info.textContent='loading '+name+'…';
  const r=await fetch('api/file?name='+encodeURIComponent(name));
  const buf=await r.arrayBuffer();
  if(name.toLowerCase().endsWith('.png')){
    // calibration plots etc. render as plain images
    const img=new Image();
    img.onload=()=>{pts=null;
      ctx.fillStyle='#14161a';ctx.fillRect(0,0,cv.width,cv.height);
      const sc=Math.min(cv.width/img.width,cv.height/img.height,1);
      ctx.drawImage(img,(cv.width-img.width*sc)/2,(cv.height-img.height*sc)/2,
                    img.width*sc,img.height*sc);
      info.textContent=`${name}: ${img.width}x${img.height} image`;};
    img.src=URL.createObjectURL(new Blob([buf],{type:'image/png'}));
    return;
  }
  const parsed=name.toLowerCase().endsWith('.stl')?parseSTL(buf):parsePLY(buf);
  pts=parsed.P; cols=parsed.C; tris=parsed.T;
  const n=pts.length/3;
  let mn=[1e30,1e30,1e30],mx=[-1e30,-1e30,-1e30];
  for(let i=0;i<n;i++)for(let c=0;c<3;c++){
    const x=pts[3*i+c]; if(x<mn[c])mn[c]=x; if(x>mx[c])mx[c]=x;}
  center=[(mn[0]+mx[0])/2,(mn[1]+mx[1])/2,(mn[2]+mx[2])/2];
  scale=2/Math.max(mx[0]-mn[0],mx[1]-mn[1],mx[2]-mn[2],1e-9);
  info.textContent=`${name}: ${n.toLocaleString()} ${tris?'tri-verts':'points'}`;
  draw();
}

function draw(){
  if(!pts){ctx.fillStyle='#14161a';ctx.fillRect(0,0,cv.width,cv.height);return;}
  const w=cv.width,h=cv.height,n=pts.length/3;
  const img=ctx.createImageData(w,h); const d=img.data; const depth=new Float32Array(w*h).fill(-1e30);
  const cy=Math.cos(rotY),sy=Math.sin(rotY),cx=Math.cos(rotX),sx=Math.sin(rotX);
  const s=0.45*Math.min(w,h)*zoom;
  const step=n>2500000?2:1;
  for(let i=0;i<n;i+=step){
    let x=(pts[3*i]-center[0])*scale,y=(pts[3*i+1]-center[1])*scale,z=(pts[3*i+2]-center[2])*scale;
    let X=cy*x+sy*z, Z=-sy*x+cy*z;
    let Y=cx*y-sx*Z, Z2=sx*y+cx*Z;
    const px=(w/2+X*s)|0, py=(h/2-Y*s)|0;
    if(px<0||py<0||px>=w||py>=h) continue;
    const o=py*w+px;
    if(Z2<depth[o]) continue;
    depth[o]=Z2;
    const sh=0.65+0.35*Math.max(-1,Math.min(1,Z2)); const k=4*o;
    if(cols){d[k]=cols[3*i]*sh;d[k+1]=cols[3*i+1]*sh;d[k+2]=cols[3*i+2]*sh;}
    else{d[k]=140*sh+40;d[k+1]=160*sh+40;d[k+2]=200*sh+40;}
    d[k+3]=255;
  }
  ctx.putImageData(img,0,0);
}

cv.addEventListener('pointerdown',e=>{drag=[e.clientX,e.clientY];cv.setPointerCapture(e.pointerId);});
cv.addEventListener('pointermove',e=>{
  if(!drag)return;
  rotY+=(e.clientX-drag[0])*0.008; rotX+=(e.clientY-drag[1])*0.008;
  drag=[e.clientX,e.clientY]; draw();});
cv.addEventListener('pointerup',()=>drag=null);
cv.addEventListener('wheel',e=>{e.preventDefault();zoom*=Math.exp(-e.deltaY*0.001);draw();},{passive:false});

async function poll(){
  try{const r=await fetch('api/progress'); const j=await r.json();
    if(j.length){const last=j[j.length-1];
      info.textContent=`stage ${last.stage} ${last.step??''} t=${last.t}s `+(sel.value?`| ${sel.value}`:'');}
  }catch(e){}
  setTimeout(poll,2000);
}

// calibration pose review: per-pose reprojection errors + prune
// (server/gui.py:1211-1250's dialog, non-modal)
const poseBox=document.getElementById('poses'), poseTab=document.getElementById('posetab');
function band(e){return e<0.5?['EXCELLENT','#30a46c']:e<1.0?['GOOD','#ad8b00']:['POOR','#e5484d'];}
async function pollPoses(){
  try{
    const j=await (await fetch('api/poses')).json();
    if(j.status==='pending'){
      if(!poseBox.dataset.shown){
        poseBox.dataset.shown='1'; poseBox.style.display='block';
        poseTab.innerHTML='<tr><th></th><th style="text-align:left">pose</th>'+
          '<th>cam px</th><th>proj px</th><th>quality</th></tr>';
        for(const [name,e] of Object.entries(j.poses).sort()){
          const [q,c]=band(Math.max(e.cam_px,e.proj_px));
          const tr=document.createElement('tr');
          tr.innerHTML=`<td><input type="checkbox" data-pose="${name}" `+
            `${q==='POOR'?'':'checked'}></td><td>${name}</td>`+
            `<td style="text-align:right">${e.cam_px.toFixed(2)}</td>`+
            `<td style="text-align:right">${e.proj_px.toFixed(2)}</td>`+
            `<td style="color:${c}">${q}</td>`;
          poseTab.appendChild(tr);
        }
      }
    } else if(poseBox.dataset.shown){
      poseBox.style.display='none'; delete poseBox.dataset.shown;
    }
  }catch(e){}
  setTimeout(pollPoses,2000);
}
document.getElementById('poseapply').onclick=async()=>{
  const keep=[...poseTab.querySelectorAll('input:checked')].map(i=>i.dataset.pose);
  const r=await fetch('api/poses',{method:'POST',
    headers:{'Content-Type':'application/json'},body:JSON.stringify({keep})});
  document.getElementById('posemsg').textContent=
    r.ok?`kept ${keep.length} poses — calibration resuming`:'apply failed';
};
fit();list();poll();pollPoses();
</script></body></html>
"""
