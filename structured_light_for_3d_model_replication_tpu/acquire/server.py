"""HTTP capture server: the PC<->phone acquisition rendezvous.

Capability parity (behavior studied from server/server.py:9-120 and
server/sl_system.py:88-109): the phone long-polls ``GET /poll_command`` for
work; when the pipeline wants a frame it arms a capture command with a fresh
id and blocks until the phone POSTs the image back to ``/upload``, which
stores it at the armed path and releases the waiter. A monitor thread flags
the phone as disconnected after a silence window.

Unlike the reference (Flask + flask-cors + a module-global mutable dict
mutated from three threads), this is a dependency-free ``http.server``
threading server around an explicitly locked ``CaptureState``; the rendezvous
(`trigger_capture`) is the same single synchronization point. The wire
protocol is unchanged, so the reference's phone clients (browser PWA,
frontend/App.tsx; Android host) work against this server as-is.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from email import policy
from email.parser import BytesParser
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["CaptureState", "CaptureServer", "CaptureTimeout"]


class CaptureTimeout(TimeoutError):
    """The phone did not deliver a frame inside the rendezvous window."""


class CaptureState:
    """Locked shared state between the HTTP handlers and the pipeline thread."""

    def __init__(self, disconnect_after: float = 5.0,
                 fallback_dir: str | None = None):
        self._lock = threading.Lock()
        self.command = "idle"
        self.command_id: str = ""
        self.save_path: str | None = None
        self.upload_received = threading.Event()
        self.last_seen = 0.0
        self.connected = False
        self.disconnect_after = disconnect_after
        self.fallback_dir = fallback_dir
        self._fallback_seq = 0
        self.on_connect = None   # optional callbacks for the orchestrator/GUI
        self.on_disconnect = None

    def arm(self, save_path: str) -> str:
        """Arm a capture command; returns the fresh command id."""
        with self._lock:
            self.upload_received.clear()
            self.save_path = save_path
            self.command_id = uuid.uuid4().hex
            self.command = "capture"
            return self.command_id

    def disarm(self) -> None:
        with self._lock:
            self.command = "idle"
            self.save_path = None

    def current_command(self) -> dict:
        with self._lock:
            return {"action": self.command, "id": self.command_id}

    def touch(self) -> None:
        """Record phone activity; fires on_connect on silence -> active edge."""
        with self._lock:
            was = self.connected
            self.last_seen = time.monotonic()
            self.connected = True
            cb = None if was else self.on_connect
        if cb:
            cb()

    def check_disconnect(self) -> None:
        with self._lock:
            silent = time.monotonic() - self.last_seen > self.disconnect_after
            was = self.connected
            if silent and was:
                self.connected = False
                cb = self.on_disconnect
            else:
                cb = None
        if cb:
            cb()

    def complete_upload(self, payload: bytes, upload_id: str | None = None) -> str:
        """Store the uploaded frame at the armed path and release the waiter.

        ``upload_id`` (when the client echoes the command id) guards against a
        late upload from a timed-out command landing on the next command's
        path. Clients that don't send an id (the reference PWA doesn't) get
        the armed-command check only. The event is set only if the same
        command is still armed after the file write, so a concurrent re-arm
        can never be released by a stale frame.

        With no capture armed, the frame lands in ``fallback_dir`` (when set)
        under a timestamped name — the standalone ``serve`` flow, where a
        phone uploads without a command round-trip.
        """
        fallback_path = None
        with self._lock:
            if self.command != "capture" or self.save_path is None:
                if self.fallback_dir is None:
                    raise ValueError("no capture armed")
                name = time.strftime("upload_%Y%m%d_%H%M%S")
                fallback_path = os.path.join(
                    self.fallback_dir,
                    f"{name}_{os.getpid()}_{self._fallback_seq}.png")
                self._fallback_seq += 1
        if fallback_path is not None:
            # file IO outside the lock: a slow multi-MB phone upload must not
            # stall /poll_command handlers and connection-state tracking
            os.makedirs(self.fallback_dir, exist_ok=True)
            with open(fallback_path, "wb") as f:
                f.write(payload)
            return fallback_path
        with self._lock:
            if self.command != "capture" or self.save_path is None:
                raise ValueError("capture disarmed during upload")
            if upload_id and upload_id != self.command_id:
                raise ValueError(
                    f"stale upload for command {upload_id[:8]}..., "
                    f"armed is {self.command_id[:8]}..."
                )
            path = self.save_path
            armed_id = self.command_id
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "wb") as f:
            f.write(payload)
        with self._lock:
            if self.command == "capture" and self.command_id == armed_id:
                self.upload_received.set()
            else:
                raise ValueError("capture disarmed during upload")
        return path


def _multipart_file(headers, body: bytes) -> tuple[bytes | None, str | None]:
    """Extract the ``file`` field (and optional ``id`` field) from a
    multipart/form-data body (stdlib only). Returns (payload, command_id)."""
    ctype = headers.get("Content-Type", "")
    if not ctype.startswith("multipart/"):
        return body or None, None  # raw-body fallback for simple clients
    msg = BytesParser(policy=policy.HTTP).parsebytes(
        b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + body
    )
    fallback = None
    found = None
    cmd_id = None
    for part in msg.iter_parts():
        payload = part.get_payload(decode=True)
        if payload is None:
            continue
        name = part.get_param("name", header="content-disposition")
        if name == "file":
            found = payload
        elif name == "id":
            cmd_id = payload.decode(errors="replace").strip()
        elif fallback is None:
            fallback = payload
    return (found if found is not None else fallback), cmd_id


class _Handler(BaseHTTPRequestHandler):
    # the reference silences Flask's request log (server/server.py:14-15)
    def log_message(self, *args):  # pragma: no cover - logging detail
        pass

    @property
    def state(self) -> CaptureState:
        return self.server.capture_state  # type: ignore[attr-defined]

    def _json(self, obj: dict, code: int = 200) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Access-Control-Allow-Origin", "*")
        self.end_headers()
        self.wfile.write(data)

    def do_OPTIONS(self):  # CORS preflight (flask-cors parity)
        self.send_response(204)
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods", "GET, POST, OPTIONS")
        self.send_header("Access-Control-Allow-Headers", "Content-Type")
        self.end_headers()

    def do_GET(self):
        path = self.path.split("?")[0]
        if path == "/poll_command":
            self.state.touch()
            # long-poll: hold while idle so the phone doesn't spam
            # (server/server.py:45-55 holds 2 s in 100 ms steps)
            deadline = time.monotonic() + self.server.poll_hold  # type: ignore[attr-defined]
            while time.monotonic() < deadline:
                cmd = self.state.current_command()
                if cmd["action"] != "idle":
                    break
                time.sleep(0.1)
            self._json(self.state.current_command())
        elif path == "/status":
            st = self.state
            self._json({
                "connected": st.connected,
                "command": st.current_command(),
            })
        elif path in ("/", "/index.html"):
            page = self.server.capture_page  # type: ignore[attr-defined]
            if page is None:
                self._json({"error": "no capture page configured"}, 404)
            else:
                data = page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        if self.path.split("?")[0] != "/upload":
            self._json({"error": "not found"}, 404)
            return
        self.state.touch()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        payload, cmd_id = _multipart_file(self.headers, body)
        # the id may also travel as a header or query param for raw-body clients
        cmd_id = cmd_id or self.headers.get("X-Command-Id")
        if cmd_id is None and "?" in self.path:
            from urllib.parse import parse_qs, urlsplit

            cmd_id = parse_qs(urlsplit(self.path).query).get("id", [None])[0]
        if not payload:
            self._json({"error": "no file in upload"}, 400)
            return
        try:
            path = self.state.complete_upload(payload, cmd_id)
        except ValueError as e:
            self._json({"error": str(e)}, 409)
            return
        self._json({"status": "ok", "path": path})


def default_capture_page() -> str | None:
    """The bundled phone capture client (capture_page.html) — the browser-PWA
    equivalent (frontend/App.tsx capability), served at GET /."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "capture_page.html")
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:  # pragma: no cover - packaging problem only
        return None


class CaptureServer:
    """Threaded capture server + the pipeline-side rendezvous API."""

    def __init__(self, host: str = "0.0.0.0", port: int = 5000,
                 poll_hold: float = 2.0, disconnect_after: float = 5.0,
                 capture_page: str | None = None,
                 upload_dir: str | None = None):
        if capture_page is None:
            capture_page = default_capture_page()
        self.state = CaptureState(disconnect_after=disconnect_after,
                                  fallback_dir=upload_dir)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.capture_state = self.state  # type: ignore[attr-defined]
        self._httpd.poll_hold = poll_hold       # type: ignore[attr-defined]
        self._httpd.capture_page = capture_page  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._serve_thread: threading.Thread | None = None
        self._monitor_stop = threading.Event()
        self._monitor_thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "CaptureServer":
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="capture-http"
        )
        self._serve_thread.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="capture-monitor"
        )
        self._monitor_thread.start()
        return self

    def _monitor(self) -> None:
        while not self._monitor_stop.wait(1.0):
            self.state.check_disconnect()

    def stop(self) -> None:
        self._monitor_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "CaptureServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def trigger_capture(self, save_path: str, timeout: float = 20.0) -> str:
        """Arm a capture and block until the phone uploads (the single
        cross-machine sync point; server/sl_system.py:88-109)."""
        self.state.arm(save_path)
        try:
            if not self.state.upload_received.wait(timeout):
                raise CaptureTimeout(
                    f"no upload within {timeout:.0f}s for {save_path}"
                )
        finally:
            self.state.disarm()
        return save_path
