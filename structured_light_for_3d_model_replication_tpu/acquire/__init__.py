"""Acquisition layer: everything between the pipeline and the physical rig
(reference parity: server/server.py, server/sl_system.py capture paths,
server/arduino.py, server/gui.py auto-scan tab).

  server     HTTP capture rendezvous (phone long-poll + upload), stdlib-only
  sequencer  Gray-code pattern sequence -> numbered frame files per pose
  projector  fullscreen pattern display (OpenCV) + virtual backend
  turntable  serial stepper protocol + simulation/loopback backends
  android    client for the Android camera-host pull API
  autoscan   the 360-degree turntable sweep orchestrator
  viewer     operator web viewer for per-stage artifacts + StageRecorder
"""
from structured_light_for_3d_model_replication_tpu.acquire.autoscan import (  # noqa: F401
    auto_scan_360,
    view_folder_name,
)
from structured_light_for_3d_model_replication_tpu.acquire.sequencer import (  # noqa: F401
    CaptureSequencer,
)
from structured_light_for_3d_model_replication_tpu.acquire.server import (  # noqa: F401
    CaptureServer,
    CaptureTimeout,
)
from structured_light_for_3d_model_replication_tpu.acquire.turntable import (  # noqa: F401
    LoopbackTurntable,
    SerialTurntable,
    SimulatedTurntable,
    open_turntable,
)
from structured_light_for_3d_model_replication_tpu.acquire.viewer import (  # noqa: F401
    StageRecorder,
    ViewerServer,
)
