"""Auto-scan 360: the turntable sweep orchestrator.

Capability parity (behavior studied from server/gui.py:1700-1787): N turns of
(capture full pattern sequence) -> (rotate turntable, wait DONE), writing each
view to ``{base}_{angle}deg_scan/``. A rotation timeout logs a warning and
continues (the reference's behavior, gui.py:1774-1776). Progress reporting
carries elapsed + estimated-remaining wall-clock.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable

__all__ = ["AutoScanResult", "auto_scan_360", "view_folder_name"]


def view_folder_name(base: str, angle_deg: float) -> str:
    """The angle-tagged folder contract the merge stage sorts by
    (``"<n>deg"`` substring, server/processing.py:499-519)."""
    return f"{base}_{int(round(angle_deg)):03d}deg_scan"


@dataclass
class AutoScanResult:
    view_dirs: list[str] = field(default_factory=list)
    angles: list[float] = field(default_factory=list)
    rotation_warnings: list[int] = field(default_factory=list)
    elapsed_s: float = 0.0


def auto_scan_360(sequencer, turntable, output_root: str,
                  turns: int = 12, step_deg: float = 30.0,
                  base_name: str = "scan", rotate_timeout: float = 30.0,
                  progress: Callable[[dict], None] | None = None,
                  log=print) -> AutoScanResult:
    """Run the full turntable sweep; returns per-view folders + angles.

    ``sequencer`` is a CaptureSequencer (or anything with ``capture_scan``);
    ``turntable`` anything with ``rotate``/``wait_for_done`` (serial, sim, fake).
    """
    os.makedirs(output_root, exist_ok=True)
    result = AutoScanResult()
    t0 = time.monotonic()
    for i in range(turns):
        angle = i * step_deg
        view_dir = os.path.join(output_root, view_folder_name(base_name, angle))
        log(f"[autoscan] view {i + 1}/{turns} @ {angle:.0f}deg")
        sequencer.capture_scan(view_dir)
        result.view_dirs.append(view_dir)
        result.angles.append(angle)
        if progress:
            elapsed = time.monotonic() - t0
            per_view = elapsed / (i + 1)
            progress({
                "view": i + 1, "turns": turns, "angle": angle,
                "elapsed_s": elapsed,
                "remaining_s": per_view * (turns - i - 1),
            })
        if i < turns - 1:
            turntable.rotate(step_deg)
            if not turntable.wait_for_done(rotate_timeout):
                # continue with a warning, like the reference (gui.py:1774-1776)
                log(f"[autoscan] WARNING: rotation {i + 1} timed out; continuing")
                result.rotation_warnings.append(i + 1)
    result.elapsed_s = time.monotonic() - t0
    log(f"[autoscan] {turns} views in {result.elapsed_s:.1f}s")
    return result
