"""Auto-scan 360: the turntable sweep orchestrator.

Capability parity (behavior studied from server/gui.py:1700-1787): N turns of
(capture full pattern sequence) -> (rotate turntable, wait DONE), writing each
view to ``{base}_{angle}deg_scan/``. A rotation timeout logs a warning and
continues (the reference's behavior, gui.py:1774-1776). Progress reporting
carries elapsed + estimated-remaining wall-clock.

Resilience (ISSUE 3): the sweep is a long serial chain of fallible hardware
steps, so each step carries a bounded recovery budget instead of aborting
hours of upstream work:

  - a failed capture sequence (dropped phone connection, injected
    ``http.capture`` fault) retries up to ``capture_retries`` times; an
    exhausted budget records the view as a :class:`FailureRecord` in
    ``AutoScanResult.failures`` and the sweep CONTINUES — the reconstruction
    pipeline's min-views degradation handles the hole downstream
  - a failed rotation (missed DONE, serial error, injected ``serial.rotate``
    fault) retries up to ``rotate_retries`` times, calling the turntable's
    ``reopen()`` between attempts when it has one (the serial re-open +
    bounded re-home path); exhaustion falls back to the reference's
    warn-and-continue
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable

from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["AutoScanResult", "auto_scan_360", "view_folder_name"]


def view_folder_name(base: str, angle_deg: float) -> str:
    """The angle-tagged folder contract the merge stage sorts by
    (``"<n>deg"`` substring, server/processing.py:499-519)."""
    return f"{base}_{int(round(angle_deg)):03d}deg_scan"


@dataclass
class AutoScanResult:
    view_dirs: list[str] = field(default_factory=list)
    angles: list[float] = field(default_factory=list)
    rotation_warnings: list[int] = field(default_factory=list)
    failures: list[faults.FailureRecord] = field(default_factory=list)
    capture_retries: int = 0
    rotate_retries: int = 0
    elapsed_s: float = 0.0


def _capture_view(sequencer, view_dir: str, retries: int,
                  result: AutoScanResult, view_name: str, log) -> bool:
    """One per-view capture under a bounded retry budget; False quarantines
    the view (recorded in ``result.failures``) and the sweep continues."""
    for attempt in range(1, retries + 2):
        try:
            sequencer.capture_scan(view_dir)
            return True
        except faults.InjectedCrash:
            raise
        except Exception as e:
            if attempt <= retries and faults.is_transient(e):
                result.capture_retries += 1
                log(f"[autoscan] {view_name}: capture failed "
                    f"({type(e).__name__}: {e}); retry "
                    f"{attempt}/{retries}")
                continue
            rec = faults.FailureRecord.from_exception(
                "capture", view_name, e, attempts=attempt)
            result.failures.append(rec)
            log(f"[autoscan] {view_name} FAILED after {attempt} "
                f"attempt(s): {e} — continuing the sweep without it")
            return False


def _rotate_step(turntable, step_deg: float, timeout: float, retries: int,
                 result: AutoScanResult, step_index: int, log) -> bool:
    """Rotate + wait-DONE with serial recovery: on a missed DONE or a serial
    error, re-open the port (``turntable.reopen()`` when available) and
    re-issue the rotation, up to ``retries`` times. Exhaustion degrades to
    the reference's warn-and-continue (gui.py:1774-1776)."""
    for attempt in range(1, retries + 2):
        try:
            turntable.rotate(step_deg)
            if turntable.wait_for_done(timeout):
                return True
            err: Exception = TimeoutError(
                f"rotation {step_index} missed DONE within {timeout:.0f}s")
        except faults.InjectedCrash:
            raise
        except Exception as e:
            err = e
        if attempt > retries:
            break
        result.rotate_retries += 1
        log(f"[autoscan] rotation {step_index} failed ({err}); "
            f"re-opening the turntable and retrying "
            f"{attempt}/{retries}")
        reopen = getattr(turntable, "reopen", None)
        if reopen is not None:
            try:
                reopen()
            except Exception as e:
                log(f"[autoscan] turntable re-open failed ({e})")
    # continue with a warning, like the reference (gui.py:1774-1776)
    log(f"[autoscan] WARNING: rotation {step_index} failed ({err}); "
        f"continuing")
    result.rotation_warnings.append(step_index)
    return False


def auto_scan_360(sequencer, turntable, output_root: str,
                  turns: int = 12, step_deg: float = 30.0,
                  base_name: str = "scan", rotate_timeout: float = 30.0,
                  capture_retries: int = 0, rotate_retries: int = 0,
                  progress: Callable[[dict], None] | None = None,
                  token=None, log=print) -> AutoScanResult:
    """Run the full turntable sweep; returns per-view folders + angles.

    ``sequencer`` is a CaptureSequencer (or anything with ``capture_scan``);
    ``turntable`` anything with ``rotate``/``wait_for_done`` (serial, sim,
    fake — ``reopen()`` is used for recovery when present).
    ``capture_retries``/``rotate_retries`` default to 0 (the reference's
    single-attempt behavior); the CLI wires ``acquire.capture_retries`` /
    ``acquire.rotate_retries``.

    ``token`` (a :class:`~.utils.deadline.CancelToken`) makes the sweep
    cooperatively cancellable: checked between hardware steps, a raised
    token stops the sweep CLEANLY after the current view — captured views
    remain usable, nothing half-rotates. An hours-long sweep should never
    need ``kill -9`` to stop.
    """
    os.makedirs(output_root, exist_ok=True)
    result = AutoScanResult()
    t0 = time.monotonic()
    for i in range(turns):
        if token is not None and token.cancelled:
            log(f"[autoscan] cancelled after {i}/{turns} view(s) "
                f"({token.reason or 'no reason given'}); stopping the "
                f"sweep cleanly")
            break
        angle = i * step_deg
        view_dir = os.path.join(output_root, view_folder_name(base_name, angle))
        view_name = os.path.basename(view_dir)
        log(f"[autoscan] view {i + 1}/{turns} @ {angle:.0f}deg")
        if _capture_view(sequencer, view_dir, capture_retries, result,
                         view_name, log):
            result.view_dirs.append(view_dir)
            result.angles.append(angle)
        if progress:
            elapsed = time.monotonic() - t0
            per_view = elapsed / (i + 1)
            progress({
                "view": i + 1, "turns": turns, "angle": angle,
                "elapsed_s": elapsed,
                "remaining_s": per_view * (turns - i - 1),
            })
        if i < turns - 1:
            _rotate_step(turntable, step_deg, rotate_timeout, rotate_retries,
                         result, i + 1, log)
    result.elapsed_s = time.monotonic() - t0
    done = f"{len(result.view_dirs)}/{turns} views"
    if result.failures:
        done += f" ({len(result.failures)} FAILED + quarantined)"
    log(f"[autoscan] {done} in {result.elapsed_s:.1f}s")
    return result
