"""Client for the Android camera-host HTTP API (pull-model capture).

Capability parity (protocol studied from android_camera_host/CameraHostServer.kt:20-72
and Old/android_camera_host_client.py:1-105): the phone app runs an HTTP server
(default port 8765) with ``GET /status``, ``GET /capabilities``,
``POST /settings`` (manual exposure/ISO/focus/zoom/AWB/stabilization), and
``POST /capture/jpeg`` which returns the JPEG bytes plus an ``X-Capture-Meta``
JSON header. Reachable over Wi-Fi or USB via ``adb reverse tcp:8765``.

Stdlib urllib only — no client dependency.

Resilience: every request runs under a bounded transient-retry budget
(``retries``/``backoff_s``, defaults matching ``acquire.http_retries`` /
``acquire.http_backoff_s``) — a dropped Wi-Fi association or a restarting
phone app is a blip, not a lost view. HTTP is connectionless here, so
"reconnect" IS the retry; 4xx statuses are permanent and never retried.
Captured frames land on disk via tmp+rename, so a connection cut mid-body
never leaves a truncated frame masquerading as a capture.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import asdict, dataclass

from structured_light_for_3d_model_replication_tpu.io.atomic import (
    atomic_write,
)
from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["CameraSettings", "AndroidCameraClient"]


@dataclass
class CameraSettings:
    """Manual camera controls; None fields are left at the phone's defaults.

    Field names are pythonic; ``to_dict`` emits the EXACT wire keys the
    reference device app parses (Camera2Controller.kt:167-185 reads
    ``exposure_time_ns`` / ``focus_distance`` / ``zoom_ratio`` / ``eis`` —
    unknown keys are silently ignored by its ``as?`` casts, so a wrong
    name would no-op without an error; docs/android_protocol.md pins the
    full key set and tests/test_android_client.py asserts it)."""

    exposure_ns: int | None = None
    iso: int | None = None
    exposure_compensation: int | None = None
    ae_mode: str | None = None          # "on" | "off" (manual)
    af_mode: str | None = None          # "auto" | "off" (manual)
    focus_diopters: float | None = None
    awb_mode: str | None = None
    zoom: float | None = None
    # eis/ois are independent wire controls (EIS's frame warp corrupts
    # structured-light correspondence; OIS does not) — set them separately,
    # or use `stabilization` as a both-at-once convenience
    eis: bool | None = None
    ois: bool | None = None
    stabilization: bool | None = None
    jpeg_quality: int | None = None
    camera_id: str | None = None

    _WIRE_KEYS = {  # pythonic field -> reference wire key
        "exposure_ns": "exposure_time_ns",
        "focus_diopters": "focus_distance",
        "zoom": "zoom_ratio",
    }

    def to_dict(self) -> dict:
        out = {}
        for k, v in asdict(self).items():
            if v is None:
                continue
            if k == "stabilization":  # convenience: explicit eis/ois win
                out.setdefault("eis", bool(v))
                out.setdefault("ois", bool(v))
            else:
                out[self._WIRE_KEYS.get(k, k)] = v
        return out


class AndroidCameraClient:
    def __init__(self, host: str, port: int = 8765, timeout: float = 10.0,
                 retries: int = 2, backoff_s: float = 0.2,
                 on_retry=None):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout
        self.retry_count = 0  # lifetime transient retries (the blip gauge)
        self._policy = faults.RetryPolicy(max_retries=retries,
                                          backoff_base_s=backoff_s,
                                          backoff_max_s=max(2.0, backoff_s))
        self._on_retry = on_retry  # optional (retry_index, exc) hook

    @staticmethod
    def _transient(e: BaseException) -> bool:
        """Socket-level failures retry; an HTTP status is the app answering,
        so only 5xx (app mid-restart) is worth the budget."""
        if isinstance(e, urllib.error.HTTPError):
            return e.code >= 500
        return faults.is_transient(e)

    def _retry(self, fn):
        def note(n, e):
            self.retry_count += 1
            if self._on_retry is not None:
                self._on_retry(n, e)

        return faults.retry_call(fn, self._policy, classify=self._transient,
                                 on_retry=note)

    def _request(self, path: str, data: bytes | None = None,
                 headers: dict | None = None):
        req = urllib.request.Request(
            self.base + path, data=data, headers=headers or {},
            method="POST" if data is not None else "GET",
        )
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _json(self, path: str, payload: dict | None = None,
              retry: bool = True) -> dict:
        data = None
        headers = {}
        if payload is not None:
            data = json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"

        def _once() -> dict:
            with self._request(path, data, headers) as resp:
                return json.loads(resp.read().decode() or "{}")

        return self._retry(_once) if retry else _once()

    def status(self) -> dict:
        return self._json("/status")

    def capabilities(self) -> dict:
        return self._json("/capabilities")

    def apply_settings(self, settings: CameraSettings) -> dict:
        return self._json("/settings", settings.to_dict())

    def reachable(self) -> bool:
        try:
            # a probe, not a request worth the retry budget: one attempt
            self._json("/status", retry=False)
            return True
        except (urllib.error.URLError, OSError, ValueError):
            return False

    def capture_jpeg(self) -> tuple[bytes, dict]:
        """Trigger a still capture; returns (jpeg_bytes, capture_metadata).
        Transient failures (dropped connection, app restart, injected
        ``http.capture`` faults) retry with backoff inside the budget."""

        def _once() -> tuple[bytes, dict]:
            faults.fire("http.capture", item=self.base)
            with self._request("/capture/jpeg", data=b"") as resp:
                meta_hdr = resp.headers.get("X-Capture-Meta", "{}")
                try:
                    meta = json.loads(meta_hdr)
                except json.JSONDecodeError:
                    meta = {"raw": meta_hdr}
                return resp.read(), meta

        return self._retry(_once)

    def capture_to_path(self, path: str) -> dict:
        """Capture one frame to disk — drop-in CaptureFn for the sequencer.
        tmp+rename publish: a failure at any byte offset leaves no partial
        frame for the decoder to trip on (sync skipped: frame cadence
        matters more than power-loss durability for re-capturable data)."""
        jpeg, meta = self.capture_jpeg()
        with atomic_write(path, sync=False) as tmp, open(tmp, "wb") as f:
            f.write(jpeg)
        return meta
