"""360-degree multi-view merge: the reference's flagship post-processing flow.

Capability parity (behavior studied from server/processing.py:489-629
merge_pro_360): clouds sorted by turntable angle chain-align view i onto the
accumulated frame of view i-1 — per pair: voxel downsample + normals + FPFH,
RANSAC global init (fitness warning below 0.05), point-to-plane ICP refine,
accumulate T, transform the full-resolution cloud and concatenate; then final
voxel downsample, optional uniform sampling, statistical outlier removal and
normal re-estimation.

Every per-pair step runs on-device through ops/{pointcloud,normals,
registration}; the view chain itself is a host loop (inherently sequential).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.config import MergeConfig
from structured_light_for_3d_model_replication_tpu.ops import (
    normals as nrmlib,
    pointcloud as pc,
    registration as reg,
)

__all__ = ["merge_360", "preprocess_for_registration", "chamfer_distance"]


@dataclass
class _Prep:
    points: jnp.ndarray
    valid: jnp.ndarray
    normals: jnp.ndarray
    features: jnp.ndarray


def preprocess_for_registration(points, colors, valid, voxel_size: float) -> _Prep:
    """Voxel downsample -> normals (r=2*voxel) -> FPFH (r=5*voxel): the
    reference's preprocess_point_cloud (processing.py:455-466)."""
    cols = colors if colors is not None else np.zeros_like(points, dtype=np.uint8)
    p, c, v = pc.voxel_downsample(jnp.asarray(points), jnp.asarray(cols),
                                  jnp.asarray(valid), voxel_size)
    nr = nrmlib.estimate_normals(p, v, k=30)
    feat = reg.fpfh_features(p, nr, v, radius=5.0 * voxel_size, k=48)
    return _Prep(p, v, nr, feat)


def merge_360(clouds, cfg: MergeConfig | None = None, log=print,
              step_callback=None):
    """Merge ordered per-view clouds into one 360-degree cloud.

    clouds: list of (points [N,3] f32, colors [N,3] u8) in turntable order.
    Returns (points, colors, transforms) — transforms[i] maps view i into the
    frame of view 0 (T_accum chain, processing.py:585-593).
    """
    cfg = cfg or MergeConfig()
    voxel = float(cfg.voxel_size)
    merged_p = [np.asarray(clouds[0][0], np.float32)]
    merged_c = [np.asarray(clouds[0][1], np.uint8)]
    transforms = [np.eye(4, dtype=np.float32)]

    def maybe_sample(p, c, every):
        if every and every > 1:
            return p[::every], c[::every]
        return p, c

    prev_p, prev_c = clouds[0]
    prev_p, prev_c = maybe_sample(np.asarray(prev_p), np.asarray(prev_c),
                                  cfg.sample_before)
    prev = preprocess_for_registration(prev_p, prev_c,
                                       np.ones(len(prev_p), bool), voxel)
    t_accum = np.eye(4, dtype=np.float32)

    for i in range(1, len(clouds)):
        cur_p_full = np.asarray(clouds[i][0], np.float32)
        cur_c_full = np.asarray(clouds[i][1], np.uint8)
        cur_p, cur_c = maybe_sample(cur_p_full, cur_c_full, cfg.sample_before)
        cur = preprocess_for_registration(cur_p, cur_c,
                                          np.ones(len(cur_p), bool), voxel)

        glob = reg.ransac_global_registration(
            cur.points, cur.features, cur.valid,
            prev.points, prev.features, prev.valid,
            max_dist=voxel * 1.5, trials=cfg.ransac_trials,
        )
        if float(glob.fitness) < 0.05:
            log(f"[merge_360] WARNING view {i}: global fitness "
                f"{float(glob.fitness):.3f} < 0.05 — alignment may fail "
                f"(processing.py:566-569 semantics)")

        icp = reg.icp_point_to_plane(
            cur.points, cur.valid, prev.points, prev.valid, prev.normals,
            init_transform=glob.transform,
            max_dist=voxel * float(cfg.icp_dist_ratio), iters=cfg.icp_iters,
        )
        log(f"[merge_360] view {i}: global fit {float(glob.fitness):.3f} | "
            f"ICP fit {float(icp.fitness):.3f} rmse {float(icp.rmse):.3f}")

        t_local = np.asarray(icp.transform, np.float32)
        t_accum = (t_accum @ t_local).astype(np.float32)
        transforms.append(t_accum.copy())
        moved = cur_p_full @ t_accum[:3, :3].T + t_accum[:3, 3]
        merged_p.append(moved.astype(np.float32))
        merged_c.append(cur_c_full)
        if step_callback is not None:
            step_callback(i, np.concatenate(merged_p), np.concatenate(merged_c))
        prev = cur

    points = np.concatenate(merged_p)
    colors = np.concatenate(merged_c)

    # ---- post-processing chain (processing.py:605-629) ----
    n = len(points)
    valid = np.ones(n, bool)
    if cfg.final_voxel and cfg.final_voxel > 0:
        p, c, v = pc.voxel_downsample(jnp.asarray(points), jnp.asarray(colors),
                                      jnp.asarray(valid), float(cfg.final_voxel))
        keep = np.asarray(v)
        points = np.asarray(p)[keep]
        colors = np.asarray(c)[keep]
        valid = np.ones(len(points), bool)
    if cfg.sample_after and cfg.sample_after > 1:
        points = points[:: cfg.sample_after]
        colors = colors[:: cfg.sample_after]
        valid = valid[:: cfg.sample_after]
    if cfg.outlier_nb > 0:
        m = np.asarray(pc.statistical_outlier_mask(
            jnp.asarray(points), jnp.asarray(valid),
            cfg.outlier_nb, cfg.outlier_std))
        points, colors = points[m], colors[m]
    return points, colors, transforms


def chamfer_distance(a, b) -> float:
    """Symmetric mean nearest-neighbor distance between clouds [Na,3], [Nb,3].
    The accuracy metric BASELINE.json tracks (Chamfer vs CPU path)."""
    from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib

    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def one_way(x, y):
        ext = np.asarray(jnp.max(y, 0) - jnp.min(y, 0), np.float64)
        vol = float(np.prod(np.maximum(ext, 1e-6)))
        cell = 2.0 * (vol / max(y.shape[0], 1)) ** (1 / 3)
        g = gridlib.build_grid(y, jnp.ones(y.shape[0], bool), cell)
        _, d2 = gridlib.grid_query_knn(g, x, 1, rings=3)
        d = jnp.sqrt(d2[:, 0])
        d = jnp.where(jnp.isfinite(d), d, 0.0)  # out-of-range: grid miss
        return float(d.mean())

    return 0.5 * (one_way(a, b) + one_way(b, a))
