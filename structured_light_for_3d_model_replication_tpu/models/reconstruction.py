"""360-degree multi-view merge: the reference's flagship post-processing flow.

Capability parity (behavior studied from server/processing.py:489-629
merge_pro_360): clouds sorted by turntable angle chain-align view i onto the
accumulated frame of view i-1 — per pair: voxel downsample + normals + FPFH,
RANSAC global init (fitness warning below 0.05), point-to-plane ICP refine,
accumulate T, transform the full-resolution cloud and concatenate; then final
voxel downsample, optional uniform sampling, statistical outlier removal and
normal re-estimation.

Every per-pair step runs on-device through ops/{pointcloud,normals,
registration}; the view chain itself is a host loop (inherently sequential).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.config import MergeConfig
from structured_light_for_3d_model_replication_tpu.ops import (
    normals as nrmlib,
    pointcloud as pc,
    registration as reg,
)

__all__ = ["merge_360", "merge_360_posegraph", "preprocess_for_registration",
           "chamfer_distance"]


@dataclass
class _Prep:
    points: jnp.ndarray
    valid: jnp.ndarray
    normals: jnp.ndarray
    features: jnp.ndarray


def preprocess_for_registration(points, colors, valid, voxel_size: float) -> _Prep:
    """Voxel downsample -> normals (r=2*voxel) -> FPFH (r=5*voxel): the
    reference's preprocess_point_cloud (processing.py:455-466).

    The downsample keeps fixed [N] shapes; surviving voxels are host-compacted
    (padded to a 2048-multiple bucket) before the quadratic-cost feature stages so
    normals/FPFH/RANSAC cost scales with the downsampled count, not the input
    slot count — the compaction is the same export-boundary pattern as
    ops/triangulate.compact_cloud."""
    cols = colors if colors is not None else np.zeros_like(points, dtype=np.uint8)
    p, c, v = pc.voxel_downsample(jnp.asarray(points), jnp.asarray(cols),
                                  jnp.asarray(valid), voxel_size)
    keep = np.asarray(v)
    p_c = np.asarray(p)[keep]
    n = len(p_c)
    # bucket the padded size (multiple of 2048) so consecutive views of similar
    # density reuse the same compiled kNN/FPFH/RANSAC executables
    n_pad = -n % 2048
    if n_pad:
        p_c = np.concatenate([p_c, np.full((n_pad, 3), 1e9, np.float32)])
    v_c = np.arange(n + n_pad) < n
    p, v = jnp.asarray(p_c), jnp.asarray(v_c)
    nr = nrmlib.estimate_normals(p, v, k=30)
    feat = reg.fpfh_features(p, nr, v, radius=5.0 * voxel_size, k=48)
    return _Prep(p, v, nr, feat)


def merge_360(clouds, cfg: MergeConfig | None = None, log=print,
              step_callback=None):
    """Merge ordered per-view clouds into one 360-degree cloud.

    clouds: list of (points [N,3] f32, colors [N,3] u8) in turntable order.
    Returns (points, colors, transforms) — transforms[i] maps view i into the
    frame of view 0 (T_accum chain, processing.py:585-593).
    """
    cfg = cfg or MergeConfig()
    voxel = float(cfg.voxel_size)
    merged_p = [np.asarray(clouds[0][0], np.float32)]
    merged_c = [np.asarray(clouds[0][1], np.uint8)]
    transforms = [np.eye(4, dtype=np.float32)]

    prev_p, prev_c = _sample_every(np.asarray(clouds[0][0]),
                                   np.asarray(clouds[0][1]), cfg.sample_before)
    prev = preprocess_for_registration(prev_p, prev_c,
                                       np.ones(len(prev_p), bool), voxel)
    t_accum = np.eye(4, dtype=np.float32)

    for i in range(1, len(clouds)):
        cur_p_full = np.asarray(clouds[i][0], np.float32)
        cur_c_full = np.asarray(clouds[i][1], np.uint8)
        cur_p, cur_c = _sample_every(cur_p_full, cur_c_full, cfg.sample_before)
        cur = preprocess_for_registration(cur_p, cur_c,
                                          np.ones(len(cur_p), bool), voxel)

        t_local, gfit, icp = _register_pair(cur, prev, voxel, cfg)
        if gfit < 0.05:
            log(f"[merge_360] WARNING view {i}: global fitness "
                f"{gfit:.3f} < 0.05 — alignment may fail "
                f"(processing.py:566-569 semantics)")
        log(f"[merge_360] view {i}: global fit {gfit:.3f} | "
            f"ICP fit {float(icp.fitness):.3f} rmse {float(icp.rmse):.3f}")

        t_accum = (t_accum @ t_local).astype(np.float32)
        transforms.append(t_accum.copy())
        moved = cur_p_full @ t_accum[:3, :3].T + t_accum[:3, 3]
        merged_p.append(moved.astype(np.float32))
        merged_c.append(cur_c_full)
        if step_callback is not None:
            step_callback(i, np.concatenate(merged_p), np.concatenate(merged_c))
        prev = cur

    points = np.concatenate(merged_p)
    colors = np.concatenate(merged_c)
    points, colors = _postprocess_merged(points, colors, cfg)
    return points, colors, transforms


def _sample_every(p, c, every):
    """Uniform pre-registration subsampling (sample_before semantics)."""
    if every and every > 1:
        return p[::every], c[::every]
    return p, c


def _postprocess_merged(points, colors, cfg: MergeConfig):
    """Final voxel/sample/outlier chain shared by both merge modes
    (processing.py:605-629)."""
    valid = np.ones(len(points), bool)
    if cfg.final_voxel and cfg.final_voxel > 0:
        p, c, v = pc.voxel_downsample(jnp.asarray(points), jnp.asarray(colors),
                                      jnp.asarray(valid), float(cfg.final_voxel))
        keep = np.asarray(v)
        points = np.asarray(p)[keep]
        colors = np.asarray(c)[keep]
        valid = np.ones(len(points), bool)
    if cfg.sample_after and cfg.sample_after > 1:
        points = points[:: cfg.sample_after]
        colors = colors[:: cfg.sample_after]
        valid = valid[:: cfg.sample_after]
    if cfg.outlier_nb > 0:
        m = np.asarray(pc.statistical_outlier_mask(
            jnp.asarray(points), jnp.asarray(valid),
            cfg.outlier_nb, cfg.outlier_std))
        points, colors = points[m], colors[m]
    return points, colors


def _register_pair(cur: "_Prep", dst: "_Prep", voxel: float, cfg: MergeConfig):
    """RANSAC global init + point-to-plane ICP refine of cur onto dst.
    Returns (transform dst<-cur as np [4,4], global fitness, icp result)."""
    glob = reg.ransac_global_registration(
        cur.points, cur.features, cur.valid,
        dst.points, dst.features, dst.valid,
        max_dist=voxel * 1.5, trials=cfg.ransac_trials,
    )
    icp = reg.icp_point_to_plane(
        cur.points, cur.valid, dst.points, dst.valid, dst.normals,
        init_transform=glob.transform,
        max_dist=voxel * float(cfg.icp_dist_ratio), iters=cfg.icp_iters,
    )
    return np.asarray(icp.transform, np.float32), float(glob.fitness), icp


def merge_360_posegraph(clouds, cfg: MergeConfig | None = None, log=print,
                        pg_iters: int = 20):
    """Multiway pose-graph merge: the robust mode the reference keeps in its
    legacy layer (Old/360Merge.py:50-78 — sequential edges + a first<->last
    loop-closure edge, globally optimized with LM; Old/new360Merge.py adds the
    per-pair FPFH/RANSAC init this uses too).

    Returns (points, colors, transforms) with transforms[i] = world-from-view-i
    after global optimization (world = view 0).
    """
    from structured_light_for_3d_model_replication_tpu.ops import (
        posegraph as pglib,
    )

    cfg = cfg or MergeConfig()
    voxel = float(cfg.voxel_size)
    n = len(clouds)
    if n < 3:
        return merge_360(clouds, cfg, log=log)

    preps = []
    for p_full, c_full in clouds:
        p_s, c_s = _sample_every(np.asarray(p_full, np.float32),
                                 np.asarray(c_full, np.uint8), cfg.sample_before)
        preps.append(preprocess_for_registration(
            p_s, c_s, np.ones(len(p_s), bool), voxel))

    edges_i, edges_j, edge_T, edge_w = [], [], [], []
    # odometry chain: edge (i-1 <- i)
    init = [np.eye(4, dtype=np.float32)]
    for i in range(1, n):
        T, gfit, icp = _register_pair(preps[i], preps[i - 1], voxel, cfg)
        log(f"[posegraph] edge {i - 1}<-{i}: global fit {gfit:.3f} | "
            f"ICP fit {float(icp.fitness):.3f} rmse {float(icp.rmse):.3f}")
        edges_i.append(i - 1)
        edges_j.append(i)
        edge_T.append(T)
        edge_w.append(max(float(icp.fitness), 1e-3))
        init.append((init[-1] @ T).astype(np.float32))
    # loop closure: edge (0 <- n-1)
    T_lc, gfit, icp = _register_pair(preps[n - 1], preps[0], voxel, cfg)
    log(f"[posegraph] loop closure 0<-{n - 1}: global fit {gfit:.3f} | "
        f"ICP fit {float(icp.fitness):.3f} rmse {float(icp.rmse):.3f}")
    lc_ok = float(icp.fitness) >= 0.05
    if lc_ok:
        edges_i.append(0)
        edges_j.append(n - 1)
        edge_T.append(T_lc)
        edge_w.append(max(float(icp.fitness), 1e-3))
    else:
        log("[posegraph] WARNING: loop closure rejected (fitness < 0.05); "
            "result equals the odometry chain")

    res = pglib.optimize_pose_graph(np.stack(init), edges_i, edges_j,
                                    np.stack(edge_T), edge_w, iters=pg_iters)
    log(f"[posegraph] residual rmse {float(res.initial_rmse):.4f} -> "
        f"{float(res.residual_rmse[-1]):.4f} over {pg_iters} iters")
    transforms = [np.asarray(res.poses[i], np.float32) for i in range(n)]

    merged_p, merged_c = [], []
    for i, (p_full, c_full) in enumerate(clouds):
        T = transforms[i]
        moved = np.asarray(p_full, np.float32) @ T[:3, :3].T + T[:3, 3]
        merged_p.append(moved.astype(np.float32))
        merged_c.append(np.asarray(c_full, np.uint8))
    points = np.concatenate(merged_p)
    colors = np.concatenate(merged_c)
    points, colors = _postprocess_merged(points, colors, cfg)
    return points, colors, transforms


def chamfer_distance(a, b) -> float:
    """Symmetric mean nearest-neighbor distance between clouds [Na,3], [Nb,3].
    The accuracy metric BASELINE.json tracks (Chamfer vs CPU path)."""
    from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    if pk.use_pallas() and max(a.shape[0], b.shape[0]) <= 131072:
        def one_way_nn(x, y):
            _, d2 = pk.nn1(x, y)
            return float(jnp.sqrt(jnp.maximum(d2, 0.0)).mean())

        try:
            return 0.5 * (one_way_nn(a, b) + one_way_nn(b, a))
        except Exception:  # Mosaic compile failure at this shape: grid path
            pass

    def one_way(x, y):
        ext = np.asarray(jnp.max(y, 0) - jnp.min(y, 0), np.float64)
        vol = float(np.prod(np.maximum(ext, 1e-6)))
        cell = 2.0 * (vol / max(y.shape[0], 1)) ** (1 / 3)
        g = gridlib.build_grid(y, jnp.ones(y.shape[0], bool), cell)
        _, d2 = gridlib.grid_query_knn(g, x, 1, rings=3)
        d = jnp.sqrt(d2[:, 0])
        d = jnp.where(jnp.isfinite(d), d, 0.0)  # out-of-range: grid miss
        return float(d.mean())

    return 0.5 * (one_way(a, b) + one_way(b, a))
