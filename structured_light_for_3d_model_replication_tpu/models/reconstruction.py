"""360-degree multi-view merge: the reference's flagship post-processing flow.

Capability parity (behavior studied from server/processing.py:489-629
merge_pro_360): clouds sorted by turntable angle chain-align view i onto the
accumulated frame of view i-1 — per pair: voxel downsample + normals + FPFH,
RANSAC global init (fitness warning below 0.05), point-to-plane ICP refine,
accumulate T, transform the full-resolution cloud and concatenate; then final
voxel downsample, optional uniform sampling, statistical outlier removal and
normal re-estimation.

Every per-pair step runs on-device through ops/{pointcloud,normals,
registration}; the view chain itself is a host loop (inherently sequential).
"""
from __future__ import annotations

import functools
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.config import MergeConfig
from structured_light_for_3d_model_replication_tpu.ops import (
    knn as knnlib,
    normals as nrmlib,
    pointcloud as pc,
    registration as reg,
)

__all__ = ["merge_360", "merge_360_posegraph", "preprocess_for_registration",
           "chamfer_distance", "DeviceClouds", "compact_views_device",
           "stack_views_device", "prep_view", "prep_view_device",
           "register_prep_pairs", "finalize_chain", "transform_views_batched"]


@dataclass
class _Prep:
    points: jnp.ndarray
    valid: jnp.ndarray
    normals: jnp.ndarray
    features: jnp.ndarray


@dataclass
class DeviceClouds:
    """Device-resident per-view clouds: the fused decode -> merge handoff.

    ``points`` [V,S,3] f32 / ``valid`` [V,S] bool / ``colors`` [V,S,3] u8,
    one shared padded slot count S per view (compact_views_device). On an
    accelerator, merge_360 consumes this WITHOUT the per-view host pack +
    ~12 MB re-upload the host-cloud list pays — the clouds a device decode
    just produced never round-trip the tunnel. The reference's equivalent
    boundary is the .ply-per-view file contract between scan and merge
    (server/processing.py:489-515); the TPU-first boundary is HBM."""
    points: jnp.ndarray
    valid: jnp.ndarray
    colors: jnp.ndarray
    # per-view survivor counts (host array) — compact_views_device fills
    # this so merge_360's occupancy gate needs no extra device sync
    counts: np.ndarray | None = None

    def to_host_list(self):
        """Materialize as the host (points, colors) list merge_360 and
        every tool/test accepts — the compatibility boundary."""
        p = np.asarray(self.points, np.float32)
        v = np.asarray(self.valid, bool)
        c = np.asarray(self.colors, np.uint8)
        return [(p[i][v[i]], c[i][v[i]]) for i in range(p.shape[0])]


def _bucket_pad(max_count: int, slots: int | None = None,
                multiple: int = 2048) -> int:
    """Round a survivor count up to the shared per-view bucket size,
    clamped to the available slot count — the one idiom behind every
    fixed-shape view stack in this module."""
    b = -(-max(max_count, 1) // multiple) * multiple
    return b if slots is None else min(b, slots)


@jax.jit
def _compact_views_jit(pts, valid, cols):
    # stable valid-first ordering puts each view's survivors in a slot
    # prefix (same export-boundary pattern as triangulate.compact_cloud,
    # but batched over views and staying on device)
    order = jnp.argsort(~valid, axis=1, stable=True)
    return (jnp.take_along_axis(pts, order[..., None], axis=1),
            jnp.take_along_axis(valid, order, axis=1),
            jnp.take_along_axis(cols, order[..., None], axis=1))


# slot index packs into the low bits of one u32 sort key (validity in the
# bit above), so the compaction order is ONE single-array sort instead of
# a (key, index-payload) pair sort — and the gathers below touch only the
# bucket prefix instead of every slot (12x less gather traffic at decode
# occupancy). 2^21 slots covers 1080p stacks (2,073,600).
_COMPACT_IOTA_BITS = 21


@jax.jit
def _compact_order_counts_jit(valid):
    iota = jax.lax.broadcasted_iota(jnp.uint32, valid.shape, 1)
    key = jnp.where(valid, iota, iota + jnp.uint32(1 << _COMPACT_IOTA_BITS))
    skey = jnp.sort(key, axis=1)
    order = (skey & jnp.uint32((1 << _COMPACT_IOTA_BITS) - 1)).astype(
        jnp.int32)
    return order, valid.sum(axis=1)


@functools.partial(jax.jit, static_argnames=("bucket",))
def _compact_gather_jit(pts, valid, cols, order, bucket: int):
    o = order[:, :bucket]
    return (jnp.take_along_axis(pts, o[..., None], axis=1),
            jnp.take_along_axis(valid, o, axis=1),
            jnp.take_along_axis(cols, o[..., None], axis=1))


def compact_views_device(points, valid, colors) -> DeviceClouds:
    """Compact a decoded view stack ([V, H*W] slots, ~15-25% valid) to one
    shared 2048-bucket so downstream per-view launches scale with real
    point counts — the only host traffic is the [V] survivor counts."""
    pts = jnp.asarray(points)
    v = jnp.asarray(valid)
    c = jnp.asarray(colors)
    if c.shape[-1] == 1:
        # scanner paths ship one gray channel; the DeviceClouds contract is
        # RGB. Replicating BEFORE compaction keeps the gathers shared, and
        # on device the repeat costs bandwidth only over the bucket prefix
        c = jnp.repeat(c, 3, axis=-1)
    if pts.shape[1] <= (1 << _COMPACT_IOTA_BITS):
        order, cnts_dev = _compact_order_counts_jit(v)
        cnts = np.asarray(cnts_dev).astype(int)           # one small sync
        bucket = _bucket_pad(int(cnts.max()), pts.shape[1])
        p2, v2, c2 = _compact_gather_jit(pts, v, c, order, bucket)
        return DeviceClouds(p2, v2, c2, cnts)
    p, v2, c2 = _compact_views_jit(pts, v, c)             # giant stacks
    cnts = np.asarray(v2.sum(axis=1)).astype(int)
    bucket = _bucket_pad(int(cnts.max()), p.shape[1])
    return DeviceClouds(p[:, :bucket], v2[:, :bucket], c2[:, :bucket], cnts)


def stack_views_device(clouds) -> DeviceClouds:
    """Per-view COMPACT clouds [(points [Ni,3], colors [Ni,3]), ...] -> one
    DeviceClouds stack on the shared _bucket_pad bucket. The fused pipeline's
    clean -> merge handoff: each view's survivors already occupy a dense
    prefix, so no sort is needed — pad to one bucket, stack, mask by count.
    Inputs may be host or device arrays; on an accelerator this is the one
    upload of the (cleaned, compact) clouds, ~5-20x smaller than re-uploading
    full decode slots."""
    counts = np.asarray([len(p) for p, _ in clouds], int)
    bucket = _bucket_pad(int(counts.max()) if len(counts) else 1)
    v = len(clouds)
    if all(isinstance(p, np.ndarray) for p, _ in clouds):
        # host inputs: pack once, upload once
        pts_h = np.zeros((v, bucket, 3), np.float32)
        cols_h = np.zeros((v, bucket, 3), np.uint8)
        for i, (p, c) in enumerate(clouds):
            pts_h[i, :len(p)] = np.asarray(p, np.float32)
            cols_h[i, :len(p)] = np.asarray(c, np.uint8)
        pts, cols = jnp.asarray(pts_h), jnp.asarray(cols_h)
    else:
        # device-resident inputs stay resident: pad each view in place
        pts = jnp.stack([
            jnp.concatenate([jnp.asarray(p, jnp.float32),
                             jnp.zeros((bucket - len(p), 3), jnp.float32)])
            for p, _ in clouds])
        cols = jnp.stack([
            jnp.concatenate([jnp.asarray(c, jnp.uint8),
                             jnp.zeros((bucket - len(c), 3), jnp.uint8)])
            for _, c in clouds])
    valid = (jnp.asarray(counts, jnp.int32)[:, None]
             > jnp.arange(bucket, dtype=jnp.int32)[None, :])
    return DeviceClouds(pts, valid, cols, counts)


# feature-prep configuration, shared with tools/profile_merge's attribution
# arms so the profiler can never drift from the production values.
# FEAT_K: the reference's Open3D preprocess uses max_nn=100
# (processing.py:455-466); 48 was the original perf departure and the r5
# on-chip sweep measured 32 equal-or-better (gfit 0.863 vs 0.856@48 vs
# 0.828@exact-48, ifit 0.940 all; kNN 0.273 vs 0.328 s, FPFH 0.183 vs
# 0.212 s across 24 views) — FPFH's 11-bin histograms saturate well
# before 48 neighbors. Registration fitness is the acceptance gate for
# this knob; features carry no bit-exactness contract.
FEAT_K = 32            # shared kNN depth (FPFH neighborhood)
NORMALS_K = 30         # normals use the nearest 30 of FEAT_K
FEAT_RADIUS_SCALE = 5.0  # FPFH radius = 5 * voxel (reference's preprocess)
FEATURE_CHUNK = 8      # views batched per vmap launch (memory bound)


def _feat_knn_selector() -> str:
    """kNN selection strategy for feature prep. Accelerators use
    approx_min_k at 0.95 per-row recall: the r5 on-chip features A/B
    measured 0.327 s vs lax.top_k's 0.483 s across 24 views with
    registration quality unchanged (gfit 0.856 vs 0.818, ifit 0.941
    both — a missed neighbor only swaps in a slightly-farther one, and
    FPFH's 11-bin histograms don't resolve the difference; recall 0.99
    was SLOWER than exact, 0.543 s). Features are a registration aid,
    not an export surface — every exactness contract (outlier stats,
    chamfer, bitexact PLYs) keeps its own exact path. Hosts keep exact
    top_k (XLA:CPU has no PartialReduce win and the parity tests pin
    the exact arm). SLSCAN_FEAT_EXACT=1 forces the exact selector on
    the brute path — set it BEFORE the first merge/preprocess call in
    the process: the choice is latched into the jit trace, and a view
    large enough for knn()'s large-N accelerator dispatch (>65536
    downsampled points) selects via approx_min_k regardless."""
    if os.environ.get("SLSCAN_FEAT_EXACT") == "1":
        return "topk"
    return "topk" if jax.default_backend() == "cpu" else "approx:0.95"


def preprocess_for_registration(points, colors, valid, voxel_size: float,
                                pad_to: int | None = None) -> _Prep:
    """Voxel downsample -> normals (r=2*voxel) -> FPFH (r=5*voxel): the
    reference's preprocess_point_cloud (processing.py:455-466).

    The downsample keeps fixed [N] shapes; surviving voxels are host-compacted
    (padded to ``pad_to``, default the next 2048-multiple) before the
    quadratic-cost feature stages so normals/FPFH/RANSAC cost scales with the
    downsampled count, not the input slot count — the compaction is the same
    export-boundary pattern as ops/triangulate.compact_cloud."""
    p_c = _downsample_compact(points, colors, valid, voxel_size)
    p, v = _pad_prep(p_c, pad_to)
    nr, feat = _prep_features_jit(p, v, jnp.float32(FEAT_RADIUS_SCALE * voxel_size))
    return _Prep(p, v, nr, feat)


def _downsample_compact(points, colors, valid, voxel_size: float) -> np.ndarray:
    cols = colors if colors is not None else np.zeros_like(points, dtype=np.uint8)
    p, c, v = pc.voxel_downsample(jnp.asarray(points), jnp.asarray(cols),
                                  jnp.asarray(valid), voxel_size)
    keep = np.asarray(v)
    return np.asarray(p)[keep]


def _pad_prep(p_c: np.ndarray, pad_to: int | None):
    n = len(p_c)
    total = pad_to if pad_to is not None else -(-max(n, 1) // 2048) * 2048
    if n > total:
        raise ValueError(
            f"pad_to={total} is smaller than the downsampled cloud ({n} "
            f"points); raise pad_to or the voxel size")
    if n < total:
        p_c = np.concatenate([p_c, np.full((total - n, 3), 1e9, np.float32)])
    v_c = np.arange(total) < n
    return jnp.asarray(p_c), jnp.asarray(v_c)


@functools.partial(jax.jit, static_argnames=())
def _prep_features_jit(p, v, feat_radius):
    # one kNN (k=FEAT_K, ascending) feeds both stages: the neighbor search
    # is the dominant cost of feature prep, and normals only need the
    # nearest NORMALS_K of the FEAT_K neighbors. Stays on knn()'s brute
    # dispatch — an r5
    # on-chip session that routed accelerators through knn_dense_approx
    # here measured register_s 0.94 -> 1.35 s (the 8192-bucket padding and
    # chunking hurt at per-view sizes) — but swaps the SELECTOR inside the
    # brute tiling on accelerators (_feat_knn_selector: approx_min_k at
    # 0.95 recall, 0.327 vs 0.483 s on-chip, registration quality equal)
    idx, d2 = knnlib.knn(p, v, FEAT_K, selector=_feat_knn_selector())
    nr = nrmlib.estimate_normals(p, v, k=NORMALS_K, idx_d2=(idx, d2))
    feat = reg.fpfh_features(p, nr, v, radius=feat_radius, k=FEAT_K,
                             idx_d2=(idx, d2))
    return nr, feat


@jax.jit
def _voxel_views_jit(pts_v, valid_v, vs):
    def one(a):
        # zero colors created in-graph: the color segment-sums are dead code
        # and XLA eliminates them (registration only needs geometry)
        p, _, v = pc.voxel_downsample(a[0], jnp.zeros(a[0].shape, jnp.uint8),
                                      a[1], vs)
        return p, v

    return jax.lax.map(one, (pts_v, valid_v))


@jax.jit
def _features_views_jit(pts_v, valid_v, feat_radius):
    # vmap in view chunks, not lax.map: per-view feature prep is many small
    # ops (tiled kNN blocks, 3x3 eigensolves, 11-bin histograms) that batch
    # into far fewer, fatter launches — but a whole-stack vmap would let
    # peak memory scale with the view count (~50-100 MB of kNN transients
    # per view), so the batching is bounded at 8 views at a time
    n_views = pts_v.shape[0]
    chunk = min(FEATURE_CHUNK, n_views)
    outs = [jax.vmap(lambda p, v: _prep_features_jit(p, v, feat_radius))(
                pts_v[s:s + chunk], valid_v[s:s + chunk])
            for s in range(0, n_views, chunk)]
    return (jnp.concatenate([o[0] for o in outs]),
            jnp.concatenate([o[1] for o in outs]))


def _preprocess_views(clouds, voxel: float, sample_before: int,
                      keep_raw: bool = False):
    """Preprocess every view to ONE fixed padded size: per-view voxel
    downsample (one reused executable) + host compaction, then stacked
    normals+FPFH. A single pad size means a single compile for every
    downstream per-pair stage — the round-2 chain re-jitted whenever
    consecutive views straddled a 2048 bucket boundary (verdict weak #7).

    ``keep_raw``: also return the raw padded view uploads as device stacks
    ([V, n_raw, 3] f32, [V, n_raw] bool) — the device-accumulate path
    reuses them so the transformed merged cloud never round-trips the
    host (only meaningful when sample_before <= 1, i.e. sampled == full).
    Returns preps, or (preps, (raw_pts, raw_valid)) with keep_raw."""
    p_stack, v_stack, raw = _voxel_pack_views(clouds, voxel, sample_before,
                                              keep_raw)
    nr_all, feat_all = _features_views_jit(p_stack, v_stack,
                                           jnp.float32(FEAT_RADIUS_SCALE * voxel))
    preps = [_Prep(p_stack[i], v_stack[i], nr_all[i], feat_all[i])
             for i in range(p_stack.shape[0])]
    if keep_raw:
        return preps, raw
    return preps


def _voxel_pack_views(clouds, voxel: float, sample_before: int,
                      keep_raw: bool = False):
    """The voxel+pack half of _preprocess_views: per-view downsample, host
    compaction, one-bucket padding. Returns (p_stack [V,n_pad,3],
    v_stack [V,n_pad], raw_or_None) — split out so the profiler can time
    it apart from the feature stage."""
    sampled = []
    for p_full, c_full in clouds:
        sampled.append(_sample_every(np.asarray(p_full, np.float32),
                                     np.asarray(c_full, np.uint8),
                                     sample_before))
    # pad RAW inputs to one bucket: per-view raw sizes differ, and an
    # unpadded loop compiles voxel_downsample once per distinct size. Views
    # are batched into fixed-size chunks (one compile, few launches) with
    # the chunk sized to bound resident memory — full-res views would
    # otherwise stack several GB at once.
    n_views = len(sampled)
    n_raw = -(-max(len(p) for p, _ in sampled) // 8192) * 8192
    chunk = max(1, min(n_views, (8 << 20) // n_raw))  # <= ~100 MB f32 points
    views_p = []      # device-resident voxelized views (no 14 MB D2H+H2D:
    counts = []       # on a tunneled chip those round trips are network time)
    raw_chunks = []
    for s in range(0, n_views, chunk):
        part = sampled[s:s + chunk]
        pts = np.full((chunk, n_raw, 3), 1e9, np.float32)
        valid = np.zeros((chunk, n_raw), bool)
        for k, (p_s, _) in enumerate(part):
            pts[k, :len(p_s)] = p_s
            valid[k, :len(p_s)] = True
        pts_dev = jnp.asarray(pts)
        valid_dev = jnp.asarray(valid)
        if keep_raw:
            raw_chunks.append((pts_dev, valid_dev, len(part)))
        p_all, v_all = _voxel_views_jit(pts_dev, valid_dev,
                                        jnp.float32(voxel))
        # survivor COUNTS are the only host transfer (survivors occupy a
        # contiguous slot prefix — test_voxel_downsample_survivor_prefix);
        # each view is sliced to its chunk's 2048-bucket immediately so the
        # big [chunk, n_raw] buffer frees at loop end — holding every
        # chunk's full-slot output until the final stack would defeat the
        # residency bound this loop exists for
        cnts = np.asarray(v_all.sum(axis=1))[:len(part)].astype(int)
        counts.extend(int(x) for x in cnts)
        bucket = _bucket_pad(int(cnts.max()))
        views_p.extend(p_all[k, :bucket] for k in range(len(part)))

    # pad every view up to ONE size on device; invalid slots hold zeros,
    # which every downstream op masks via `valid` (knn parks them at _FAR
    # itself)
    n_pad = _bucket_pad(max(counts))
    views_p = [vp if vp.shape[0] == n_pad else
               jnp.concatenate([vp, jnp.zeros((n_pad - vp.shape[0], 3),
                                              jnp.float32)])
               for vp in views_p]
    p_stack = jnp.stack(views_p)
    v_stack = (jnp.asarray(counts, jnp.int32)[:, None]
               > jnp.arange(n_pad, dtype=jnp.int32)[None, :])
    raw = None
    if keep_raw:
        raw = (jnp.concatenate([p[:k] for p, _, k in raw_chunks]),
               jnp.concatenate([v[:k] for _, v, k in raw_chunks]))
    return p_stack, v_stack, raw


def _device_accumulate_ok(cfg: MergeConfig, mesh, step_callback,
                          n_views: int, slots: int, n_actual: int) -> bool:
    """The ONE gate for both device-resident accumulate paths (host-list
    keep_raw and DeviceClouds): accelerator backend, full postprocess
    chain on this device, nothing needing per-step host clouds, an HBM
    bound on the retained raw stack (+ its transformed copy), and slot
    occupancy — one huge view must not pad every view's slots and
    balloon the postprocess sort with mostly-invalid rows."""
    return (mesh is None and step_callback is None
            and jax.default_backend() != "cpu"
            and (not cfg.sample_before or cfg.sample_before <= 1)
            and _full_postprocess(cfg)
            and n_views * slots * 12 <= (1 << 30)
            and n_actual >= 0.5 * n_views * slots)


def _preprocess_views_device(dc: DeviceClouds, voxel: float):
    """_preprocess_views for a DeviceClouds stack: no host pack, no
    re-upload — voxel downsample the resident stack, one survivor-count
    sync, features on the shared bucket. Returns (preps, raw)."""
    p_all, v_all = _voxel_views_jit(dc.points, dc.valid, jnp.float32(voxel))
    cnts = np.asarray(v_all.sum(axis=1)).astype(int)      # one small sync
    n_pad = _bucket_pad(int(cnts.max()), p_all.shape[1])
    p_stack = p_all[:, :n_pad]
    v_stack = (jnp.asarray(cnts, jnp.int32)[:, None]
               > jnp.arange(n_pad, dtype=jnp.int32)[None, :])
    nr_all, feat_all = _features_views_jit(
        p_stack, v_stack, jnp.float32(FEAT_RADIUS_SCALE * voxel))
    preps = [_Prep(p_stack[i], v_stack[i], nr_all[i], feat_all[i])
             for i in range(p_stack.shape[0])]
    return preps, (dc.points, dc.valid)


def _register_chain_batched(preps, cfg: MergeConfig, voxel: float,
                            loop_closure: bool, mesh=None,
                            feat_bf16: bool | None = None):
    """All chain pairs (i-1 <- i), plus optionally (0 <- n-1), registered in
    ONE device launch via ops.registration.register_pairs — or sharded over
    ``mesh`` (pairs split across every device, zero hot-path collectives)
    when one is given. Returns host arrays (T [P,4,4], gfit [P], ifit [P],
    irmse [P])."""
    srcs = preps[1:] + ([preps[-1]] if loop_closure else [])
    dsts = preps[:-1] + ([preps[0]] if loop_closure else [])
    args = (jnp.stack([p.points for p in srcs]),
            jnp.stack([p.valid for p in srcs]),
            jnp.stack([p.features for p in srcs]),
            jnp.stack([p.points for p in dsts]),
            jnp.stack([p.valid for p in dsts]),
            jnp.stack([p.features for p in dsts]),
            jnp.stack([p.normals for p in dsts]))
    kw = dict(max_dist=voxel * 1.5,
              icp_max_dist=voxel * float(cfg.icp_dist_ratio),
              trials=cfg.ransac_trials, icp_iters=cfg.icp_iters,
              feat_bf16=feat_bf16)
    if mesh is not None:
        out = reg.register_pairs_sharded(mesh, *args, **kw)
    else:
        out = reg.register_pairs(*args, **kw)
    # ONE gathered transfer for all four results (separate np.asarray calls
    # are four round trips on a tunneled device)
    T, gfit, ifit, irmse = jax.device_get(out)
    return (np.asarray(T, np.float32), np.asarray(gfit, np.float32),
            np.asarray(ifit, np.float32), np.asarray(irmse, np.float32))


# ---------------------------------------------------------------------------
# Canonical per-view / per-pair registration (the streaming-merge contract)
# ---------------------------------------------------------------------------
#
# The streaming pipeline registers pair (i, i+1) the moment both views are
# cleaned, while the barrier arm registers every pair at once — and the two
# must produce BYTE-IDENTICAL merged output. f32 reductions are not
# associative, so bit-parity demands every pair be computed at shapes that
# are a function of the pair alone, never of its launch-mates:
#
#   - prep_view: per-view shapes (8192-multiple raw pad, 2048-multiple
#     survivor bucket) derived from that view's own counts
#   - pair bucket: max of the two views' buckets; the smaller prep is
#     zero-padded (invalid rows contribute exact zeros to every masked
#     reduction, and the shape — hence XLA's tiling — is schedule-invariant)
#   - RANSAC key: folds the pair's explicit chain id (register_pairs
#     pair_ids), not its position in whatever launch carried it
#   - launches group same-bucket pairs on the _pair_group_bucket ladder;
#     lax.map applies the same compiled body per pair, so group composition
#     cannot change a pair's numbers
#
# merge_360's host path routes through exactly this machinery, which is what
# makes `merge.stream=false` (barrier) and the streamed pipeline two
# schedules of one computation.

@jax.jit
def _voxel_view_jit(pts, valid, vs):
    p, _, v = pc.voxel_downsample(pts, jnp.zeros(pts.shape, jnp.uint8),
                                  valid, vs)
    return p, v


def prep_view(points, voxel: float, sample_before: int = 0) -> _Prep:
    """Canonical per-view registration prep: voxel downsample -> normals ->
    FPFH at shapes derived from THIS view alone. A view prepped as it
    streams out of the reconstruct executor is bit-identical to the same
    view prepped inside a barrier merge — the invariant the
    streamed≡barrier byte-parity contract rests on."""
    p = np.asarray(points, np.float32)
    if sample_before and sample_before > 1:
        p = p[::sample_before]
    n = len(p)
    n_raw = -(-max(n, 1) // 8192) * 8192
    pts = np.full((n_raw, 3), 1e9, np.float32)
    pts[:n] = p
    valid = np.zeros(n_raw, bool)
    valid[:n] = True
    p_all, v_all = _voxel_view_jit(jnp.asarray(pts), jnp.asarray(valid),
                                   jnp.float32(voxel))
    cnt = int(np.asarray(v_all.sum()))            # one small sync
    bucket = _bucket_pad(cnt, n_raw)
    # survivors occupy a contiguous slot prefix (pinned by
    # test_voxel_downsample_survivor_prefix), so the bucket slice is sound
    p_c = p_all[:bucket]
    v_c = jnp.arange(bucket, dtype=jnp.int32) < cnt
    nr, feat = _prep_features_jit(p_c, v_c,
                                  jnp.float32(FEAT_RADIUS_SCALE * voxel))
    return _Prep(p_c, v_c, nr, feat)


@functools.partial(jax.jit, static_argnames=("n_raw",))
def _repad_view_jit(pts, n, n_raw: int):
    # the compacted gather's tail rows (>= n) hold REAL unselected
    # coordinates, not sentinels — re-sentinel them before re-padding so
    # the voxel grid sees exactly prep_view's host-padded 1e9 rows
    rows = jnp.arange(pts.shape[0], dtype=jnp.int32)
    p = jnp.where(rows[:, None] < n, pts, jnp.float32(1e9))
    if n_raw > pts.shape[0]:
        p = jnp.concatenate(
            [p, jnp.full((n_raw - pts.shape[0], 3), 1e9, jnp.float32)])
    return p, jnp.arange(n_raw, dtype=jnp.int32) < n


def prep_view_device(points, count: int, voxel: float) -> _Prep:
    """:func:`prep_view` consuming a DEVICE buffer (the fused clean's
    compacted per-view output) without the host round-trip: rows below
    ``count`` are the view's points in prefix order; the tail is
    re-sentineled and the array re-padded to the same 8192-multiple the
    host prep uses, so every downstream shape, jit program, and bit
    matches ``prep_view(host_points)`` exactly (``count`` is a dynamic
    argument — no per-count retrace)."""
    n = int(count)
    n_raw = -(-max(n, 1) // 8192) * 8192
    if points.shape[0] > n_raw:   # cannot happen on _bucket_pad inputs
        points = points[:n_raw]
    p_pad, valid = _repad_view_jit(jnp.asarray(points, jnp.float32),
                                   jnp.int32(n), n_raw)
    p_all, v_all = _voxel_view_jit(p_pad, valid, jnp.float32(voxel))
    cnt = int(np.asarray(v_all.sum()))            # one small sync
    bucket = _bucket_pad(cnt, n_raw)
    p_c = p_all[:bucket]
    v_c = jnp.arange(bucket, dtype=jnp.int32) < cnt
    nr, feat = _prep_features_jit(p_c, v_c,
                                  jnp.float32(FEAT_RADIUS_SCALE * voxel))
    return _Prep(p_c, v_c, nr, feat)


def _pair_group_bucket(count: int, batch: int, n_dev: int = 1) -> int:
    """Launch-group size for ready pairs: full groups run at ``batch``
    slots; a ragged tail lands on the next power of two (the _view_bucket
    ladder on the pair axis), so at most log2(batch)+1 programs compile per
    cloud bucket. Sharded groups round up to the device count."""
    if count >= batch:
        b = batch
    else:
        b = 1
        while b < count:
            b *= 2
        b = min(b, batch)
    if n_dev > 1:
        b = -(-b // n_dev) * n_dev
    return b


def _prep_to_bucket(prep: _Prep, bucket: int):
    """Zero-pad one view's prep arrays to a pair bucket (pad rows invalid —
    they contribute exact zeros to every masked reduction)."""
    b = prep.points.shape[0]
    if b == bucket:
        return prep.points, prep.valid, prep.normals, prep.features
    pad = bucket - b
    return (jnp.concatenate([prep.points,
                             jnp.zeros((pad, 3), jnp.float32)]),
            jnp.concatenate([prep.valid, jnp.zeros(pad, bool)]),
            jnp.concatenate([prep.normals,
                             jnp.zeros((pad, 3), jnp.float32)]),
            jnp.concatenate([prep.features,
                             jnp.zeros((pad, prep.features.shape[1]),
                                       jnp.float32)]))


def register_prep_pairs(pairs, pair_ids, cfg: MergeConfig, voxel: float,
                        mesh=None, feat_bf16: bool | None = None,
                        batch: int | None = None):
    """Register (prep_src, prep_dst) pairs through the canonical fixed-shape
    program: pairs group by pair bucket (max of the two views' buckets),
    each group launches at the ``_pair_group_bucket`` ladder size (padded
    with duplicates of the last pair, dropped on return) via
    ``register_pairs`` — or ``register_pairs_sharded`` over ``mesh`` with
    >1 device. ``pair_ids`` are each pair's GLOBAL chain position (the
    RANSAC key id). Returns host (T [P,4,4], gfit, ifit, irmse) in input
    order; results are invariant to how pairs were grouped into launches."""
    n_pairs = len(pairs)
    batch = max(1, int(batch if batch is not None
                       else getattr(cfg, "pair_batch", 4)))
    n_dev = (int(np.prod(list(mesh.shape.values())))
             if mesh is not None else 1)
    T = np.zeros((n_pairs, 4, 4), np.float32)
    gf = np.zeros(n_pairs, np.float32)
    fi = np.zeros(n_pairs, np.float32)
    ir = np.zeros(n_pairs, np.float32)
    kw = dict(max_dist=voxel * 1.5,
              icp_max_dist=voxel * float(cfg.icp_dist_ratio),
              trials=cfg.ransac_trials, icp_iters=cfg.icp_iters,
              feat_bf16=feat_bf16)
    by_bucket: dict[int, list[int]] = {}
    for i, (s, d) in enumerate(pairs):
        b = max(s.points.shape[0], d.points.shape[0])
        by_bucket.setdefault(b, []).append(i)
    for bucket in sorted(by_bucket):
        idxs = by_bucket[bucket]
        for s0 in range(0, len(idxs), batch):
            chunk = idxs[s0:s0 + batch]
            pb = _pair_group_bucket(len(chunk), batch, n_dev)
            launch = chunk + [chunk[-1]] * (pb - len(chunk))
            stacks = [[] for _ in range(7)]
            for i in launch:
                sp, sv, sn, sf = _prep_to_bucket(pairs[i][0], bucket)
                dp, dv, dn, df = _prep_to_bucket(pairs[i][1], bucket)
                for k, a in enumerate((sp, sv, sf, dp, dv, df, dn)):
                    stacks[k].append(a)
            args = tuple(jnp.stack(s) for s in stacks)
            ids = np.asarray([pair_ids[i] for i in launch], np.int32)
            if mesh is not None:
                out = reg.register_pairs_sharded(mesh, *args, pair_ids=ids,
                                                 **kw)
            else:
                out = reg.register_pairs(*args, pair_ids=ids, **kw)
            T_l, gf_l, fi_l, ir_l = jax.device_get(out)
            for j, i in enumerate(chunk):
                T[i] = T_l[j]
                gf[i] = gf_l[j]
                fi[i] = fi_l[j]
                ir[i] = ir_l[j]
    return T, gf, fi, ir


def finalize_chain(clouds, T_pairs, gfit_all, ifit_all, irmse_all,
                   cfg: MergeConfig | None = None, log=print,
                   step_callback=None, mesh=None, timings: dict | None = None,
                   prefold=None):
    """Chain-accumulate per-pair transforms and run the final voxel/outlier
    postprocess — the barrier tail shared by merge_360's host path and the
    streaming pipeline. Given the same per-pair transforms it produces
    byte-identical merged output, whichever schedule registered the pairs.

    The accumulate apply runs as one ``transform_views_batched`` launch
    (historically a per-view host loop); the chain matmul itself stays a
    (cheap) host loop. ``step_callback(i, new_points, new_colors, total)``
    receives only the newly folded view's arrays plus the running point
    count — view 0 is emitted once as a seed call with ``i == 0``.

    ``prefold``: optional incremental-assembly carry
    (``pipeline.assembly.Prefold``, already VALIDATED against this run's
    view order/digests/pair transforms): its folded prefix seeds
    ``transforms``/``merged_p``/``merged_c`` and only the unfolded suffix
    is chained + transformed here — identical arithmetic, so the merged
    bytes are unchanged by how much was prefolded."""
    import time as _time

    cfg = cfg or MergeConfig()
    tm = timings if timings is not None else {}
    n = len(clouds)
    transforms = [np.eye(4, dtype=np.float32)]
    merged_p = [np.asarray(clouds[0][0], np.float32)]
    merged_c = [np.asarray(clouds[0][1], np.uint8)]
    start = 1
    if prefold is not None and 2 <= len(prefold.transforms) <= n:
        transforms = [np.asarray(t, np.float32) for t in prefold.transforms]
        merged_p = [np.asarray(p, np.float32) for p in prefold.merged_p]
        merged_c = [np.asarray(c, np.uint8) for c in prefold.merged_c]
        start = len(transforms)
    t0 = _time.perf_counter()
    t_accum = transforms[-1].copy()
    for i in range(1, n):
        gfit = float(gfit_all[i - 1])
        if gfit < 0.05:
            log(f"[merge_360] WARNING view {i}: global fitness "
                f"{gfit:.3f} < 0.05 — alignment may fail "
                f"(processing.py:566-569 semantics)")
        log(f"[merge_360] view {i}: global fit {gfit:.3f} | "
            f"ICP fit {float(ifit_all[i - 1]):.3f} "
            f"rmse {float(irmse_all[i - 1]):.3f}")
        if i < start:
            continue  # folded incrementally before the last item settled
        t_accum = (t_accum @ np.asarray(T_pairs[i - 1],
                                        np.float32)).astype(np.float32)
        transforms.append(t_accum.copy())
    moved = transform_views_batched(
        [np.asarray(clouds[i][0], np.float32) for i in range(start, n)],
        transforms[start:], mesh=mesh)
    total = sum(len(p) for p in merged_p)
    if step_callback is not None and start == 1:
        step_callback(0, merged_p[0], merged_c[0], total)
    for j, i in enumerate(range(start, n)):
        merged_p.append(moved[j])
        cols_i = np.asarray(clouds[i][1], np.uint8)
        merged_c.append(cols_i)
        total += len(moved[j])
        if step_callback is not None:
            step_callback(i, moved[j], cols_i, total)
    tm["accumulate_s"] = round(_time.perf_counter() - t0, 3)
    t0 = _time.perf_counter()
    points = np.concatenate(merged_p)
    colors = np.concatenate(merged_c)
    points, colors = _postprocess_dispatch(points, colors, cfg, tm, mesh, log)
    tm["postprocess_s"] = round(_time.perf_counter() - t0, 3)
    return points, colors, transforms


def merge_360(clouds, cfg: MergeConfig | None = None, log=print,
              step_callback=None, timings: dict | None = None, mesh=None,
              feat_bf16: bool | None = None):
    """Merge ordered per-view clouds into one 360-degree cloud.

    clouds: list of (points [N,3] f32, colors [N,3] u8) in turntable order.
    Returns (points, colors, transforms) — transforms[i] maps view i into the
    frame of view 0 (T_accum chain, processing.py:585-593).

    TPU-first shape: the reference chain-aligns view i onto view i-1
    sequentially (server/processing.py:549-593); since every pair is
    independent given the odometry formulation, all N-1 registrations run as
    one batched launch, and only the (cheap, host-side) T_accum chain stays
    sequential.

    ``mesh``: optional jax.sharding.Mesh — the multi-chip path: chain pairs
    shard across every device (register_pairs_sharded) and the final
    voxel+outlier pass runs slab-sharded (postprocess_merged_sharded,
    falling back to the single-device pass when the cloud is too thin to
    slab). A 24-view merge on a v5e-8 registers 3 pairs per chip.

    ``timings``: optional dict filled with per-stage wall seconds
    (preprocess_s / register_s / accumulate_s / postprocess_s).
    """
    import time as _time

    cfg = cfg or MergeConfig()
    voxel = float(cfg.voxel_size)
    tm = timings if timings is not None else {}
    # DeviceClouds input: the fused decode->merge handoff. The resident
    # fast path needs the accelerator + the full postprocess chain (it is
    # the device-accumulate path with the upload already elided); any
    # other configuration falls back through the host-list boundary.
    dc = clouds if isinstance(clouds, DeviceClouds) else None
    if dc is not None:
        v_cnt, slots = dc.points.shape[0], dc.points.shape[1]
        cnts = (dc.counts if dc.counts is not None
                else np.asarray(dc.valid.sum(axis=1)).astype(int))
        fast = v_cnt > 1 and _device_accumulate_ok(
            cfg, mesh, step_callback, v_cnt, slots, int(cnts.sum()))
        if not fast:
            clouds = dc.to_host_list()
            dc = None
    n = dc.points.shape[0] if dc is not None else len(clouds)
    if dc is None:
        merged_p = [np.asarray(clouds[0][0], np.float32)]
        merged_c = [np.asarray(clouds[0][1], np.uint8)]
    transforms = [np.eye(4, dtype=np.float32)]
    if n == 1:
        points, colors = _postprocess_merged(merged_p[0], merged_c[0], cfg)
        return points, colors, transforms

    if dc is not None:
        device_acc = True
    else:
        # device accumulate: when nothing needs the per-step host clouds
        # (no preview callback) and the full postprocess chain follows on
        # this device, the raw per-view uploads from preprocess are
        # reused — the transformed merged cloud never round-trips the
        # host (~12 MB of f32 saved per merge on a tunneled chip)
        n_raw_est = -(-max(len(p) for p, _ in clouds) // 8192) * 8192
        n_actual = sum(len(p) for p, _ in clouds)
        device_acc = _device_accumulate_ok(cfg, mesh, step_callback, n,
                                           n_raw_est, n_actual)
        if not device_acc:
            # host path: the canonical per-view/per-pair machinery — the
            # SAME programs and key schedule the streaming pipeline uses,
            # so the barrier merge and a streamed merge of these clouds
            # are two schedules of one computation (byte-identical output)
            t0 = _time.perf_counter()
            preps = [prep_view(p, voxel, cfg.sample_before)
                     for p, _ in clouds]
            tm["preprocess_s"] = round(_time.perf_counter() - t0, 3)
            t0 = _time.perf_counter()
            T_all, gfit_all, ifit_all, irmse_all = register_prep_pairs(
                [(preps[i], preps[i - 1]) for i in range(1, n)],
                list(range(n - 1)), cfg, voxel, mesh=mesh,
                feat_bf16=feat_bf16)
            tm["register_s"] = round(_time.perf_counter() - t0, 3)
            return finalize_chain(clouds, T_all, gfit_all, ifit_all,
                                  irmse_all, cfg, log=log,
                                  step_callback=step_callback, mesh=mesh,
                                  timings=tm)
    t0 = _time.perf_counter()
    if dc is not None:
        preps, raw = _preprocess_views_device(dc, voxel)
    else:
        pre = _preprocess_views(clouds, voxel, cfg.sample_before,
                                keep_raw=device_acc)
        preps, raw = pre if device_acc else (pre, None)
    tm["preprocess_s"] = round(_time.perf_counter() - t0, 3)
    t0 = _time.perf_counter()
    T_all, gfit_all, ifit_all, irmse_all = _register_chain_batched(
        preps, cfg, voxel, loop_closure=False, mesh=mesh,
        feat_bf16=feat_bf16)
    tm["register_s"] = round(_time.perf_counter() - t0, 3)

    t0 = _time.perf_counter()
    t_accum = np.eye(4, dtype=np.float32)
    for i in range(1, n):
        gfit = float(gfit_all[i - 1])
        if gfit < 0.05:
            log(f"[merge_360] WARNING view {i}: global fitness "
                f"{gfit:.3f} < 0.05 — alignment may fail "
                f"(processing.py:566-569 semantics)")
        log(f"[merge_360] view {i}: global fit {gfit:.3f} | "
            f"ICP fit {float(ifit_all[i - 1]):.3f} "
            f"rmse {float(irmse_all[i - 1]):.3f}")
        t_accum = (t_accum @ T_all[i - 1]).astype(np.float32)
        transforms.append(t_accum.copy())
    # past the host-list fallback above device_acc is always True — the
    # resident accumulate is the only arm left
    raw_p, raw_v = raw
    Ts = jnp.asarray(np.stack(transforms))          # [V, 4, 4] tiny H2D
    moved = _accumulate_views_jit(raw_p, Ts)        # one launch
    points = moved.reshape(-1, 3)
    valid_flat = raw_v.reshape(-1)
    if dc is not None:
        colors = dc.colors.reshape(-1, 3)           # already resident
    else:
        cols = np.zeros((n, raw_p.shape[1], 3), np.uint8)
        for i, (_, c_full) in enumerate(clouds):
            cols[i, :len(c_full)] = np.asarray(c_full, np.uint8)
        colors = jnp.asarray(cols).reshape(-1, 3)
    tm["accumulate_s"] = round(_time.perf_counter() - t0, 3)

    t0 = _time.perf_counter()
    points, colors = _postprocess_dispatch(points, colors, cfg, tm, mesh, log,
                                           valid=valid_flat)
    tm["postprocess_s"] = round(_time.perf_counter() - t0, 3)
    return points, colors, transforms


@jax.jit
def _accumulate_views_jit(raw_p, Ts):
    """Apply per-view accumulated transforms on device: the host loop's
    matmuls as one vmapped launch, reusing registration's transform_points
    (single source of truth for the HIGHEST-precision pin)."""
    return jax.vmap(reg.transform_points)(Ts, raw_p)


def _transform_view_np(T, p):
    """Numpy twin of one accumulate apply — the exact arithmetic of the
    historical per-view host loop (f32 matmul + translate, f32 cast)."""
    T = np.asarray(T, np.float32)
    p = np.asarray(p, np.float32)
    return (p @ T[:3, :3].T + T[:3, 3]).astype(np.float32)


def _transform_views_bucket(n_views: int, n_dev: int = 1) -> int:
    """View-axis bucket for the batched accumulate apply: next power of two
    at or above ``n_views``, rounded up to a multiple of the device count so
    the mesh arm shards evenly. Pure schedule — never cache-key material."""
    b = 1
    while b < max(n_views, 1):
        b *= 2
    d = max(int(n_dev), 1)
    return -(-b // d) * d


def _transform_views_local(Ts, P):
    return jax.vmap(reg.transform_points)(Ts, P)


_TRANSFORM_SHARDED: dict = {}


def _transform_views_sharded(mesh, Ts, P):
    """Shard the batched accumulate apply over ``mesh`` along the view axis
    (register_pairs_sharded idiom): each device transforms its local views
    with the same per-view program, so per-view bytes match the
    single-device launch exactly. The jitted program is memoized per mesh —
    the fold tail runs once per scan, and a fresh wrapper per call would
    retrace every launch."""
    key = (tuple(mesh.axis_names), tuple(d.id for d in mesh.devices.flat))
    fn = _TRANSFORM_SHARDED.get(key)
    if fn is None:
        from jax.sharding import PartitionSpec

        from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (  # noqa: E501
            shard_map_unchecked,
        )

        spec = PartitionSpec(tuple(mesh.axis_names))
        fn = jax.jit(shard_map_unchecked(
            mesh=mesh, in_specs=(spec, spec),
            out_specs=spec)(_transform_views_local))
        _TRANSFORM_SHARDED[key] = fn
    return fn(Ts, P)


_TRANSFORM_PARITY: bool | None = None


def _transform_device_parity() -> bool:
    """One-time per-process probe: the device-batched transform must
    reproduce the numpy twin BYTE-identically on a tiny fixed input, or the
    twin stays authoritative for this process (the merged cloud is
    cache-pinned content — a backend whose fused matmul rounds differently
    must not change cache bytes)."""
    global _TRANSFORM_PARITY
    if _TRANSFORM_PARITY is None:
        rng = np.random.default_rng(7)
        p = (rng.normal(size=(64, 3)) * 40).astype(np.float32)
        q, _ = np.linalg.qr(rng.normal(size=(3, 3)))
        T = np.eye(4, dtype=np.float32)
        T[:3, :3] = q.astype(np.float32)
        T[:3, 3] = (rng.normal(size=3) * 5).astype(np.float32)
        try:
            dev = np.asarray(_accumulate_views_jit(
                jnp.asarray(p[None]), jnp.asarray(T[None])), np.float32)[0]
            _TRANSFORM_PARITY = (dev.tobytes()
                                 == _transform_view_np(T, p).tobytes())
        except Exception:
            _TRANSFORM_PARITY = False
    return _TRANSFORM_PARITY


def transform_views_batched(points_list, transforms, mesh=None,
                            use_device=None):
    """Apply per-view accumulated transforms as ONE bucket-padded device
    batch (the accumulate loop's per-view host matmul+apply, replaced).

    ``points_list``: per-view [Ni,3] f32 host arrays; ``transforms``: one
    (4,4) f32 per view. Views zero-pad to a shared ``_bucket_pad`` slot
    count and the view axis pads to ``_transform_views_bucket`` (duplicated
    transforms, dropped on return) so repeat calls at a bucket hit the jit
    cache. With ``mesh`` the launch shards over the view axis. Returns the
    transformed per-view f32 arrays in input order — byte-identical to the
    numpy twin (``_transform_device_parity`` gates the device arm; on probe
    failure the twin runs). Bucketing is pure schedule, never cache-key
    material."""
    n = len(points_list)
    if n == 0:
        return []
    if use_device is None:
        use_device = n >= 2 and _transform_device_parity()
    if not use_device:
        return [_transform_view_np(T, p)
                for T, p in zip(transforms, points_list)]
    n_dev = (int(np.prod(list(mesh.shape.values())))
             if mesh is not None else 1)
    slots = _bucket_pad(max(len(p) for p in points_list))
    vb = _transform_views_bucket(n, n_dev)
    P = np.zeros((vb, slots, 3), np.float32)
    for i, p in enumerate(points_list):
        P[i, :len(p)] = np.asarray(p, np.float32)
    Ts = np.stack([np.asarray(transforms[i % n], np.float32)
                   for i in range(vb)])
    if mesh is not None and n_dev > 1:
        out = _transform_views_sharded(mesh, jnp.asarray(Ts), jnp.asarray(P))
    else:
        out = _accumulate_views_jit(jnp.asarray(P), jnp.asarray(Ts))
    out = np.asarray(out, np.float32)
    return [out[i, :len(points_list[i])] for i in range(n)]


def _postprocess_dispatch(points, colors, cfg: MergeConfig, tm, mesh, log,
                          valid=None):
    """Slab-sharded postprocess over ``mesh`` when the config runs the full
    voxel->outlier chain; the single-device pass otherwise (and as the
    fallback when the cloud cannot slab)."""
    if mesh is not None and _full_postprocess(cfg):
        from structured_light_for_3d_model_replication_tpu.ops import (
            pointcloud_sharded as pcs,
        )

        try:
            return pcs.postprocess_merged_sharded(
                mesh, points, colors, valid, float(cfg.final_voxel),
                cfg.outlier_nb, cfg.outlier_std)
        except (ValueError, RuntimeError) as e:
            # cloud too thin / too wide to slab, or fallback-cap overflow:
            # the single-device pass is always correct, just unsharded
            log(f"[merge] sharded postprocess unavailable ({e}); "
                f"single-device pass")
    return _postprocess_merged(points, colors, cfg, tm, valid=valid)


def _sample_every(p, c, every):
    """Uniform pre-registration subsampling (sample_before semantics)."""
    if every and every > 1:
        return p[::every], c[::every]
    return p, c


def _full_postprocess(cfg: MergeConfig) -> bool:
    """True when the config runs the full voxel->outlier chain with no
    intermediate subsample — the shape both the fused (device-resident)
    single-chip strategy and the slab-sharded multi-chip postprocess
    accelerate; one predicate so their gates can't drift apart."""
    return (bool(cfg.final_voxel and cfg.final_voxel > 0)
            and cfg.outlier_nb > 0
            and not (cfg.sample_after and cfg.sample_after > 1))


def _postprocess_merged(points, colors, cfg: MergeConfig,
                        tm: dict | None = None, valid=None):
    """Final voxel/sample/outlier chain shared by both merge modes
    (processing.py:605-629). ``points``/``colors`` may be host or device
    arrays (the device-accumulate path hands over padded device stacks
    with their ``valid`` mask)."""
    import time as _time

    tm = tm if tm is not None else {}
    if valid is None:
        valid = np.ones(len(points), bool)
    # one stage sequence, two compaction strategies: on accelerators the
    # cloud stays DEVICE-RESIDENT between the voxel pass and the outlier
    # probe (prefix-slice compaction, one scalar sync) — the host-compact
    # strategy bounces the ~12 MB cloud through the host twice, and on a
    # TUNNELED chip every transfer + sync is a network round trip. The
    # prefix slice is sound because survivors occupy a contiguous slot
    # prefix (group segment ids ascend in key order; the invalid-sentinel
    # key sorts last — pinned by test_voxel_downsample_survivor_prefix).
    fused = jax.default_backend() != "cpu" and _full_postprocess(cfg)
    if cfg.final_voxel and cfg.final_voxel > 0:
        t0 = _time.perf_counter()
        # host arrays stay numpy so voxel_downsample's dispatch reads the
        # grid extent on the host (no probe sync); device-resident input
        # (the device-accumulate path) must NOT be np.asarray'd — that
        # would pull the whole cloud down, the very transfer this avoids
        pts_in = points if isinstance(points, jax.Array) else \
            np.asarray(points)
        cols_in = colors if isinstance(colors, jax.Array) else \
            np.asarray(colors)
        p, c, v = pc.voxel_downsample(pts_in, cols_in,
                                      valid, float(cfg.final_voxel))
        if fused:
            n_keep = int(np.asarray(v.sum()))
            n_pad = min(-(-max(n_keep, 1) // 8192) * 8192, p.shape[0])
            points, colors, valid = p[:n_pad], c[:n_pad], v[:n_pad]
        else:
            keep = np.asarray(v)
            points = np.asarray(p)[keep]
            colors = np.asarray(c)[keep]
            valid = np.ones(len(points), bool)
        tm["final_voxel_s"] = round(_time.perf_counter() - t0, 3)
    if cfg.sample_after and cfg.sample_after > 1:  # host arrays (not fused)
        points = points[:: cfg.sample_after]
        colors = colors[:: cfg.sample_after]
        valid = valid[:: cfg.sample_after]
    if cfg.outlier_nb > 0:
        t0 = _time.perf_counter()
        # after the final voxel pass cells hold (near-)single occupants
        # (uniform sampling keeps that property) — the voxelized fast
        # path probes a bounded cell neighborhood instead of dense
        # distance rows. On host backends at this scale the op itself
        # delegates to the cKDTree twin (degraded-mode fast path).
        cell = (float(cfg.final_voxel)
                if cfg.final_voxel and cfg.final_voxel > 0 else None)
        m = np.asarray(pc.statistical_outlier_mask(
            jnp.asarray(points), jnp.asarray(valid),
            cfg.outlier_nb, cfg.outlier_std, voxelized_cell=cell))
        # export boundary: the full-stack D2H below deliberately does NOT
        # wait for the mask — on device inputs np.asarray(points) starts
        # transferring while the mask chain (complement + stats) is still
        # in flight, and the host fancy-index runs once both land. A
        # device-side keep-compaction (sort + count sync + gather) was
        # measured SLOWER in-merge (outlier_s 0.815 -> 0.94, r5): it
        # serializes the transfer behind the mask and adds a round trip,
        # losing more than the ~2.8 MB of padding it saves.
        keep = np.asarray(valid) & m
        points = np.asarray(points)[keep]
        colors = np.asarray(colors)[keep]
        tm["outlier_s"] = round(_time.perf_counter() - t0, 3)
    return np.asarray(points), np.asarray(colors)


def merge_360_posegraph(clouds, cfg: MergeConfig | None = None, log=print,
                        pg_iters: int = 20, step_callback=None, mesh=None,
                        feat_bf16: bool | None = None):
    """Multiway pose-graph merge: the robust mode the reference keeps in its
    legacy layer (Old/360Merge.py:50-78 — sequential edges + a first<->last
    loop-closure edge, globally optimized with LM; Old/new360Merge.py adds the
    per-pair FPFH/RANSAC init this uses too).

    ``mesh``: same multi-chip path as merge_360 — the edge registrations
    (the dominant cost) shard across devices and the postprocess runs
    slab-sharded; only the (small, host-side) pose-graph solve stays
    unsharded.

    Returns (points, colors, transforms) with transforms[i] = world-from-view-i
    after global optimization (world = view 0).
    """
    from structured_light_for_3d_model_replication_tpu.ops import (
        posegraph as pglib,
    )

    cfg = cfg or MergeConfig()
    voxel = float(cfg.voxel_size)
    n = len(clouds)
    if n < 3:
        return merge_360(clouds, cfg, log=log, step_callback=step_callback,
                         mesh=mesh, feat_bf16=feat_bf16)

    preps = _preprocess_views(clouds, voxel, cfg.sample_before)
    # one launch: n-1 odometry edges (i-1 <- i) + the loop closure (0 <- n-1)
    T_all, gfit_all, ifit_all, irmse_all = _register_chain_batched(
        preps, cfg, voxel, loop_closure=True, mesh=mesh,
        feat_bf16=feat_bf16)

    edges_i, edges_j, edge_T, edge_w = [], [], [], []
    init = [np.eye(4, dtype=np.float32)]
    for i in range(1, n):
        T = T_all[i - 1]
        log(f"[posegraph] edge {i - 1}<-{i}: global fit "
            f"{float(gfit_all[i - 1]):.3f} | ICP fit "
            f"{float(ifit_all[i - 1]):.3f} rmse {float(irmse_all[i - 1]):.3f}")
        edges_i.append(i - 1)
        edges_j.append(i)
        edge_T.append(T)
        edge_w.append(max(float(ifit_all[i - 1]), 1e-3))
        init.append((init[-1] @ T).astype(np.float32))
    # loop closure: edge (0 <- n-1), last row of the batch
    T_lc = T_all[n - 1]
    lc_fit = float(ifit_all[n - 1])
    log(f"[posegraph] loop closure 0<-{n - 1}: global fit "
        f"{float(gfit_all[n - 1]):.3f} | ICP fit {lc_fit:.3f} "
        f"rmse {float(irmse_all[n - 1]):.3f}")
    if lc_fit >= 0.05:
        edges_i.append(0)
        edges_j.append(n - 1)
        edge_T.append(T_lc)
        edge_w.append(max(lc_fit, 1e-3))
    else:
        log("[posegraph] WARNING: loop closure rejected (fitness < 0.05); "
            "result equals the odometry chain")

    res = pglib.optimize_pose_graph(np.stack(init), edges_i, edges_j,
                                    np.stack(edge_T), edge_w, iters=pg_iters)
    log(f"[posegraph] residual rmse {float(res.initial_rmse):.4f} -> "
        f"{float(res.residual_rmse[-1]):.4f} over {pg_iters} iters")
    transforms = [np.asarray(res.poses[i], np.float32) for i in range(n)]

    # pose-graph poses move EVERY view (transforms[0] need not be identity
    # after optimization) — one batched launch over all n
    merged_p = transform_views_batched(
        [np.asarray(p_full, np.float32) for p_full, _ in clouds],
        transforms, mesh=mesh)
    merged_c = [np.asarray(c_full, np.uint8) for _, c_full in clouds]
    if step_callback is not None:
        total = 0
        for i in range(n):
            total += len(merged_p[i])
            step_callback(i, merged_p[i], merged_c[i], total)
    points = np.concatenate(merged_p)
    colors = np.concatenate(merged_c)
    points, colors = _postprocess_dispatch(points, colors, cfg, {}, mesh, log)
    return points, colors, transforms


@jax.jit
def _chamfer_nn1_dense_jit(x, y):
    from structured_light_for_3d_model_replication_tpu.ops import (
        registration as reglib,
    )

    return reglib._nn1_brute_jnp(x, y, jnp.ones(y.shape[0], bool))


def chamfer_distance(a, b) -> float:
    """Symmetric mean nearest-neighbor distance between clouds [Na,3], [Nb,3].
    The accuracy metric BASELINE.json tracks (Chamfer vs CPU path)."""
    from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    # translation-invariant: center on the common midpoint so the NN kernels'
    # |p|^2-scale terms (and their f32 cancellation) shrink ~20x — scene
    # coordinates sit decimeters from the camera origin, the object spans
    # centimeters
    mid = 0.5 * (a.mean(0) + b.mean(0))
    a = a - mid
    b = b - mid

    if pk.use_pallas() and max(a.shape[0], b.shape[0]) <= 131072:
        def one_way_nn(x, y):
            _, d2 = pk.nn1(x, y)
            return float(jnp.sqrt(jnp.maximum(d2, 0.0)).mean())

        try:
            return 0.5 * (one_way_nn(a, b) + one_way_nn(b, a))
        except Exception:  # Mosaic compile failure at this shape
            pass

    if jax.default_backend() != "cpu":
        # accelerator fallback (big clouds or no Mosaic): exact chunked dense
        # 1-NN on the MXU — the grid engine below is host-only (its bucket
        # gathers crash the TPU runtime, ops/grid.py module notes)
        def one_way_dense(x, y):
            _, d2 = _chamfer_nn1_dense_jit(x, y)
            return float(jnp.sqrt(jnp.maximum(d2, 0.0)).mean())

        return 0.5 * (one_way_dense(a, b) + one_way_dense(b, a))

    def one_way(x, y):
        ext = np.asarray(jnp.max(y, 0) - jnp.min(y, 0), np.float64)
        vol = float(np.prod(np.maximum(ext, 1e-6)))
        cell = 2.0 * (vol / max(y.shape[0], 1)) ** (1 / 3)
        g = gridlib.build_grid(y, jnp.ones(y.shape[0], bool), cell)
        _, d2 = gridlib.grid_query_knn(g, x, 1, rings=3)
        d = jnp.sqrt(d2[:, 0])
        d = jnp.where(jnp.isfinite(d), d, 0.0)  # out-of-range: grid miss
        return float(d.mean())

    return 0.5 * (one_way(a, b) + one_way(b, a))
