"""SLScanner — the flagship forward model: capture stack -> colored point cloud.

This is the TPU-resident composition of the two hot kernels (Gray decode,
server/processing.py:28-124; ray-plane triangulation, processing.py:127-234)
into ONE jitted forward pass. Calibration tensors (per-pixel ray field, light
plane equations) are uploaded once at construction and live in HBM; per call
only the [F, H, W] uint8 frame stack moves, and everything from bit compare to
3D point fuses into a single XLA program. `forward_views` vmaps the same
program over a batch of turntable views — the per-view loop the reference runs
folder-by-folder (processing.py:314-334) becomes one device launch.
"""
from __future__ import annotations

import contextlib
import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import graycode
from structured_light_for_3d_model_replication_tpu.ops.triangulate import (
    CloudResult,
    pixel_rays,
)

__all__ = ["SLScanner"]


class SLScanner:
    """Decode + triangulate with device-resident calibration.

    Parameters
    ----------
    calib : dict — reference-layout calibration (Nc/Oc/wPlaneCol/wPlaneRow/cam_K)
    cam_size : (width, height) of the camera frames
    proj_size : (width, height) of the projector
    row_mode, epipolar_tol, n_sets_col, n_sets_row, downsample: see ops modules.
    """

    def __init__(self, calib: dict, cam_size: tuple[int, int],
                 proj_size: tuple[int, int] = (1920, 1080),
                 row_mode: int = 1, epipolar_tol: float = 2.0,
                 n_sets_col: int = 11, n_sets_row: int = 11,
                 downsample: int = 1, plane_eval: str = "table"):
        cw, ch = cam_size
        self.cam_size = cam_size
        self.proj_size = proj_size
        self.row_mode = int(row_mode)
        self.epipolar_tol = float(epipolar_tol)
        self._decode_kw = dict(
            n_cols=proj_size[0], n_rows=proj_size[1],
            n_sets_col=n_sets_col, n_sets_row=n_sets_row, downsample=downsample,
        )

        pc = np.asarray(calib["wPlaneCol"], np.float32)
        pr = np.asarray(calib["wPlaneRow"], np.float32)
        if pc.shape[0] == 4:
            pc = pc.T
        if pr.shape[0] == 4:
            pr = pr.T
        nc = calib.get("Nc")
        if nc is not None:
            nc = np.asarray(nc, np.float32)
            if nc.shape[0] == 3:
                nc = nc.T
            if nc.shape[0] != cw * ch:
                nc = None
        if nc is None:
            nc = pixel_rays(np.asarray(calib["cam_K"], np.float32), ch, cw, np)
        # device-resident calibration (uploaded once)
        self.rays = jnp.asarray(nc)
        self.oc = jnp.asarray(np.asarray(calib["Oc"], np.float32).reshape(3))
        self.plane_col = jnp.asarray(pc)
        self.plane_row = jnp.asarray(pr)

        from structured_light_for_3d_model_replication_tpu.ops.triangulate import (
            _check_plane_eval,
        )

        _check_plane_eval(plane_eval)
        use_poly = plane_eval == "quadratic"
        if use_poly:
            from structured_light_for_3d_model_replication_tpu.ops.triangulate import (
                poly_from_calib,
            )

            self.poly_col, self.poly_row = poly_from_calib(calib, jnp)
        else:
            self.poly_col = self.poly_row = jnp.zeros((3, 4), jnp.float32)

        # static compile key for the module-level jitted kernels; calibration
        # tensors are passed as ARGUMENTS (closure capture would bake them into
        # the executable as constants — megabytes of HLO payload)
        self._static = (proj_size[0], proj_size[1], n_sets_col, n_sets_row,
                        downsample, self.row_mode, use_poly)

    def _fwd(self, frames, shadow, contrast):
        return _scan_forward(frames, shadow, contrast, self.rays, self.oc,
                             self.plane_col, self.plane_row,
                             self.poly_col, self.poly_row,
                             jnp.float32(self.epipolar_tol), cfg=self._static)

    def _fuse_capable(self, frames_v) -> bool:
        """The single-pass Mosaic kernel handles the flagship configuration:
        quadratic plane eval, row_mode 0/1, uint8 tile-aligned frames.
        Capability only — whether the fused lowering CAN run, not whether
        auto-dispatch should pick it (see ``_can_fuse``)."""
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        n_cols, n_rows, _, _, downsample, _, use_poly = self._static
        h, w = frames_v.shape[-2], frames_v.shape[-1]
        nbc = max(1, int(np.ceil(np.log2(n_cols // downsample))))
        nbr = max(1, int(np.ceil(np.log2(n_rows // downsample))))
        need = 2 + 2 * (nbc + nbr)  # truncated stacks go through the jnp
        return (pk.scan_fused_ok() and use_poly and self.row_mode in (0, 1)
                and frames_v.dtype == jnp.uint8
                and frames_v.shape[-3] >= need
                and (w, h) == self.cam_size   # frames match the calibrated camera
                and h % 8 == 0 and w % 128 == 0)

    def _can_fuse(self, frames_v) -> bool:
        """Auto-dispatch policy: capability AND the measured-winner policy
        (pallas_kernels.scan_fused_requested — fused by default where
        Mosaic compiles since both r5 in-session on-chip A/Bs measured it
        faster than the jnp lowering; SLSCAN_PALLAS=0 disables)."""
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        return pk.scan_fused_requested() and self._fuse_capable(frames_v)

    def _fused_views(self, frames_v, shadow_v, contrast_v) -> CloudResult:
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        n_cols, n_rows, n_sets_col, n_sets_row, downsample, row_mode, _ = \
            self._static
        h, w = frames_v.shape[-2], frames_v.shape[-1]
        thr_v = jnp.stack([jnp.asarray(shadow_v, jnp.float32),
                           jnp.asarray(contrast_v, jnp.float32)], axis=1)
        pts, valid, tex = pk.scan_points_fused_views(
            frames_v, thr_v, self.rays.reshape(h, w, 3), self.oc,
            self.poly_col, self.poly_row, self.epipolar_tol,
            n_cols=n_cols, n_rows=n_rows, n_use_col=n_sets_col,
            n_use_row=n_sets_row, row_mode=row_mode, downsample=downsample)
        # single gray channel over the wire; RGB replication happens host-
        # side at the export boundary (compact_cloud / compact_views_device)
        return CloudResult(pts, tex[..., None], valid)

    def forward(self, frames, thresh_mode: str = "otsu",
                shadow_val: float = 40.0, contrast_val: float = 10.0) -> CloudResult:
        """One view: frames uint8 [F, H, W] -> CloudResult (fixed shape [H*W])."""
        frames = jnp.asarray(frames)
        s, c = graycode.resolve_thresholds(frames, thresh_mode, shadow_val,
                                           contrast_val, jnp)
        if self._can_fuse(frames):
            out = self._fused_views(frames[None],
                                    np.asarray([s], np.float32),
                                    np.asarray([c], np.float32))
            return CloudResult(out.points[0], out.colors[0], out.valid[0])
        return self._fwd(frames, jnp.float32(s), jnp.float32(c))

    def forward_async(self, frames, thresh_mode: str = "otsu",
                      shadow_val: float = 40.0,
                      contrast_val: float = 10.0) -> CloudResult:
        """Non-blocking ``forward``: enqueue the host->device transfer and the
        fused program and return immediately with in-flight device arrays
        (JAX async dispatch — no host sync anywhere on this path). The caller
        overlaps the NEXT view's disk load/decode with this view's transfer+
        compute and pays the sync only at its drain point
        (``jax.block_until_ready`` / ``np.asarray``), which is how the
        pipelined batch executor keeps the device busy between views.
        Numerically identical to ``forward``: same program, same inputs —
        only the moment the host waits moves."""
        return self.forward(jax.device_put(frames), thresh_mode=thresh_mode,
                            shadow_val=shadow_val, contrast_val=contrast_val)

    def forward_views(self, frames_v, thresh_mode: str = "otsu",
                      shadow_val: float = 40.0, contrast_val: float = 10.0,
                      use_fused: bool | None = None) -> CloudResult:
        """Batched views: uint8 [V, F, H, W] -> CloudResult with leading V axis.

        Runs as ONE jitted program that lax.map's the single-view forward over
        the view axis: each view is already a ~2 Mpix data-parallel problem, so
        serializing views costs nothing while capping live intermediates at one
        view's worth (a 24-view vmap materializes every view's plane gather at
        once — the round-2 HBM OOM) and keeping the Pallas decode kernel on its
        single-view lowering.

        ``use_fused``: None (default) auto-dispatches via ``_can_fuse``;
        False forces the jnp lowering; True requires the fused Mosaic
        kernel (raises if the configuration cannot fuse). The override
        exists so bench/profiling can A/B the two lowerings on the same
        process and the default can be chosen from measurements.
        """
        frames_v = jnp.asarray(frames_v)
        ss, cs = graycode.resolve_thresholds_views(frames_v, thresh_mode,
                                                   shadow_val, contrast_val)
        if use_fused and not self._fuse_capable(frames_v):
            raise ValueError("use_fused=True but this configuration cannot "
                             "take the fused Mosaic kernel (see _fuse_capable)")
        if self._can_fuse(frames_v) if use_fused is None else use_fused:
            return self._fused_views(frames_v, ss, cs)
        return _scan_forward_views(frames_v, jnp.asarray(ss, jnp.float32),
                                   jnp.asarray(cs, jnp.float32), self.rays,
                                   self.oc, self.plane_col, self.plane_row,
                                   self.poly_col, self.poly_row,
                                   jnp.float32(self.epipolar_tol),
                                   cfg=self._static)

    def forward_views_batched(self, frames_v, thresh_mode: str = "otsu",
                              shadow_val: float = 40.0,
                              contrast_val: float = 10.0,
                              mesh=None) -> CloudResult:
        """The batch executor's compute lane: uint8 [V, F, H, W] -> one
        device launch with the frame buffer DONATED (the executor never
        reuses a dispatched bucket, so XLA may recycle its HBM in place).

        ``mesh``: a jax.sharding.Mesh shards the leading view axis across
        every mesh device (``shard_map`` with replication checking off, the
        ``register_pairs_sharded`` mechanism — views are independent, zero
        collectives on the hot path); V must be a multiple of the mesh's
        device count (the executor's bucket padding guarantees it). None
        runs the single-device program (auto-dispatching the fused Mosaic
        kernel exactly like ``forward_views``).

        Numerically identical to per-view ``forward``: the batched program
        lax.map's the same ``_forward_math`` body, and the sharded program
        runs that same lax.map per device shard.
        """
        frames_v = jnp.asarray(frames_v)
        ss, cs = graycode.resolve_thresholds_views(frames_v, thresh_mode,
                                                   shadow_val, contrast_val)
        args = (jnp.asarray(ss, jnp.float32), jnp.asarray(cs, jnp.float32),
                self.rays, self.oc, self.plane_col, self.plane_row,
                self.poly_col, self.poly_row,
                jnp.float32(self.epipolar_tol))
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            if frames_v.shape[0] % n_dev:
                raise ValueError(
                    f"sharded view batch: {frames_v.shape[0]} views not a "
                    f"multiple of the {n_dev}-device mesh (the executor's "
                    f"bucket padding must round to the device count)")
            with _quiet_donation():
                pts, cols, valid = _sharded_views_fn(mesh, self._static)(
                    frames_v, *args)
            return CloudResult(pts, cols, valid)
        if self._can_fuse(frames_v):
            return self._fused_views(frames_v, np.asarray(ss, np.float32),
                                     np.asarray(cs, np.float32))
        with _quiet_donation():
            return _scan_forward_views_donated(frames_v, *args,
                                               cfg=self._static)

    def forward_views_packed(self, planes_v, white_v, black_v, *,
                             n_frames: int, thresh_mode: str = "otsu",
                             shadow_val: float = 40.0,
                             contrast_val: float = 10.0,
                             mesh=None) -> CloudResult:
        """Packed-ingest twin of ``forward_views_batched``: the bucket arrives
        as bit-planes (u8 [V, ceil(P/8), H, W], io/images.py pack layout) plus
        the verbatim white/black frames [V, H, W] — ~8x fewer upload bytes
        than the raw [V, F, H, W] stack for the same decode inputs.

        Bit-identical to the raw path: thresholds read only white/black
        (resolve_thresholds_views on a 2-frame stack), the texture channel IS
        the white frame (exactly ``_forward_math``'s frames[0]), and
        ``_decode_packed_impl`` extracts the same comparison bits the raw
        decode computes (through the Pallas unpack+decode kernel where the
        capability probe admits it). ``n_frames`` is the logical frame count
        of the packed stacks (static — part of the compile key).
        """
        planes_v = jnp.asarray(planes_v)
        white_v = jnp.asarray(white_v)
        black_v = jnp.asarray(black_v)
        ss, cs = graycode.resolve_thresholds_views(
            jnp.stack([white_v, black_v], axis=1), thresh_mode, shadow_val,
            contrast_val)
        args = (jnp.asarray(ss, jnp.float32), jnp.asarray(cs, jnp.float32),
                self.rays, self.oc, self.plane_col, self.plane_row,
                self.poly_col, self.poly_row,
                jnp.float32(self.epipolar_tol))
        cfg = (self._static, int(n_frames))
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            if planes_v.shape[0] % n_dev:
                raise ValueError(
                    f"sharded view batch: {planes_v.shape[0]} views not a "
                    f"multiple of the {n_dev}-device mesh (the executor's "
                    f"bucket padding must round to the device count)")
            with _quiet_donation():
                pts, cols, valid = _sharded_views_packed_fn(mesh, cfg)(
                    planes_v, white_v, black_v, *args)
            return CloudResult(pts, cols, valid)
        with _quiet_donation():
            return _scan_forward_views_packed_donated(
                planes_v, white_v, black_v, *args, cfg=cfg)


def _forward_math(frames, shadow, contrast, rays, oc, plane_col, plane_row,
                  poly_col, poly_row, epipolar_tol, cfg):
    from structured_light_for_3d_model_replication_tpu.ops.graycode import _decode_impl
    from structured_light_for_3d_model_replication_tpu.ops.triangulate import (
        _triangulate_impl,
    )

    n_cols, n_rows, n_sets_col, n_sets_row, downsample, row_mode, use_poly = cfg
    # one gray channel, not an on-device x3 repeat: the texture IS frame 0,
    # so the device program ships [H*W, 1] u8 and the host replicates to RGB
    # at compaction — a third of the color transfer for identical bytes
    texture = frames[0][..., None].astype(jnp.uint8)
    dec = _decode_impl(frames, texture, shadow, contrast,
                       n_cols=n_cols, n_rows=n_rows, n_sets_col=n_sets_col,
                       n_sets_row=n_sets_row, downsample=downsample, xp=jnp)
    return _triangulate_impl(
        dec.col_map, dec.row_map, dec.mask, dec.texture,
        rays, oc, plane_col, plane_row,
        row_mode=row_mode, epipolar_tol=epipolar_tol, xp=jnp,
        poly=(poly_col, poly_row) if use_poly else None,
    )


@functools.partial(jax.jit, static_argnames=("cfg",))
def _scan_forward(frames, shadow, contrast, rays, oc, plane_col, plane_row,
                  poly_col, poly_row, epipolar_tol, *, cfg):
    return _forward_math(frames, shadow, contrast, rays, oc, plane_col,
                         plane_row, poly_col, poly_row, epipolar_tol, cfg)


@contextlib.contextmanager
def _quiet_donation():
    """Donating the uint8 frame bucket is a free HBM-recycling hint where
    XLA can use it (TPU) and a per-compile UserWarning where it cannot
    (CPU: u8 inputs alias no f32/bool output). The hint is intentional
    either way — silence just that warning, just around the dispatch."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def _views_math(frames_v, shadow_v, contrast_v, rays, oc, plane_col,
                plane_row, poly_col, poly_row, epipolar_tol, cfg):
    # lax.map (= scan), NOT vmap: one compiled single-view body executed V
    # times back-to-back. Each body is ~2 Mpix of data parallelism (plenty to
    # fill the chip), while live intermediates stay one view's worth — the
    # vmapped form materialized every view's [H*W, 4] plane gather at once
    # and OOM'd HBM at 24 x 1080p (round-2 verdict weak #2).
    return jax.lax.map(
        lambda args: _forward_math(args[0], args[1], args[2], rays, oc,
                                   plane_col, plane_row, poly_col, poly_row,
                                   epipolar_tol, cfg),
        (frames_v, shadow_v, contrast_v))


@functools.partial(jax.jit, static_argnames=("cfg",))
def _scan_forward_views(frames_v, shadow_v, contrast_v, rays, oc, plane_col,
                        plane_row, poly_col, poly_row, epipolar_tol, *, cfg):
    return _views_math(frames_v, shadow_v, contrast_v, rays, oc, plane_col,
                       plane_row, poly_col, poly_row, epipolar_tol, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("frames_v",))
def _scan_forward_views_donated(frames_v, shadow_v, contrast_v, rays, oc,
                                plane_col, plane_row, poly_col, poly_row,
                                epipolar_tol, *, cfg):
    # the batch executor's single-device lane: same program as
    # _scan_forward_views, but the bucket's frame buffer is donated — the
    # executor assembles a fresh stack per bucket, so XLA reuses its HBM
    # instead of holding frames + outputs live simultaneously
    return _views_math(frames_v, shadow_v, contrast_v, rays, oc, plane_col,
                       plane_row, poly_col, poly_row, epipolar_tol, cfg)


def _forward_math_packed(planes, white, black, shadow, contrast, rays, oc,
                         plane_col, plane_row, poly_col, poly_row,
                         epipolar_tol, cfg):
    from structured_light_for_3d_model_replication_tpu.ops.graycode import (
        _decode_packed_impl,
    )
    from structured_light_for_3d_model_replication_tpu.ops.triangulate import (
        _triangulate_impl,
    )

    (n_cols, n_rows, n_sets_col, n_sets_row, downsample, row_mode,
     use_poly), n_frames = cfg
    # the texture channel IS the white frame — identical to _forward_math's
    # frames[0], so packed and raw buckets compact to the same colors
    texture = white[..., None].astype(jnp.uint8)
    dec = _decode_packed_impl(planes, white, black, texture, shadow, contrast,
                              n_frames=n_frames, n_cols=n_cols, n_rows=n_rows,
                              n_sets_col=n_sets_col, n_sets_row=n_sets_row,
                              downsample=downsample, xp=jnp)
    return _triangulate_impl(
        dec.col_map, dec.row_map, dec.mask, dec.texture,
        rays, oc, plane_col, plane_row,
        row_mode=row_mode, epipolar_tol=epipolar_tol, xp=jnp,
        poly=(poly_col, poly_row) if use_poly else None,
    )


def _views_math_packed(planes_v, white_v, black_v, shadow_v, contrast_v, rays,
                       oc, plane_col, plane_row, poly_col, poly_row,
                       epipolar_tol, cfg):
    # same lax.map-not-vmap rationale as _views_math: one view's worth of
    # live intermediates, single-view Pallas lowering preserved
    return jax.lax.map(
        lambda args: _forward_math_packed(args[0], args[1], args[2], args[3],
                                          args[4], rays, oc, plane_col,
                                          plane_row, poly_col, poly_row,
                                          epipolar_tol, cfg),
        (planes_v, white_v, black_v, shadow_v, contrast_v))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("planes_v",))
def _scan_forward_views_packed_donated(planes_v, white_v, black_v, shadow_v,
                                       contrast_v, rays, oc, plane_col,
                                       plane_row, poly_col, poly_row,
                                       epipolar_tol, *, cfg):
    return _views_math_packed(planes_v, white_v, black_v, shadow_v,
                              contrast_v, rays, oc, plane_col, plane_row,
                              poly_col, poly_row, epipolar_tol, cfg)


@functools.cache
def _sharded_views_packed_fn(mesh, cfg):
    """Packed twin of :func:`_sharded_views_fn`: planes/white/black shard
    data-major on the view axis, calibration replicates, planes donated."""
    from jax.sharding import PartitionSpec

    from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
        shard_map_unchecked,
    )

    axes = tuple(mesh.axis_names)
    vspec = PartitionSpec(axes)
    rep = PartitionSpec()

    def local(planes_v, white_v, black_v, shadow_v, contrast_v, rays, oc,
              plane_col, plane_row, poly_col, poly_row, epipolar_tol):
        return tuple(_views_math_packed(planes_v, white_v, black_v, shadow_v,
                                        contrast_v, rays, oc, plane_col,
                                        plane_row, poly_col, poly_row,
                                        epipolar_tol, cfg))

    return jax.jit(shard_map_unchecked(
        mesh=mesh,
        in_specs=(vspec,) * 5 + (rep,) * 7,
        out_specs=(vspec, vspec, vspec),
    )(local), donate_argnums=(0,))


@functools.cache
def _sharded_views_fn(mesh, cfg):
    """Jitted view-axis-sharded forward program for (mesh, static config),
    built once per pair (the jit object then caches one executable per
    bucket shape). The view axis spreads data-major over EVERY mesh axis,
    calibration tensors are replicated (KB-scale), and replication/VMA
    checking is off for the same reason register_pairs_sharded disables it:
    nothing here is replicated across the sharded axis, and the checker has
    no rule for the decode's control flow on older jax."""
    from jax.sharding import PartitionSpec

    from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
        shard_map_unchecked,
    )

    axes = tuple(mesh.axis_names)
    vspec = PartitionSpec(axes)
    rep = PartitionSpec()

    def local(frames_v, shadow_v, contrast_v, rays, oc, plane_col, plane_row,
              poly_col, poly_row, epipolar_tol):
        return tuple(_views_math(frames_v, shadow_v, contrast_v, rays, oc,
                                 plane_col, plane_row, poly_col, poly_row,
                                 epipolar_tol, cfg))

    return jax.jit(shard_map_unchecked(
        mesh=mesh,
        in_specs=(vspec, vspec, vspec) + (rep,) * 7,
        out_specs=(vspec, vspec, vspec),
    )(local), donate_argnums=(0,))
