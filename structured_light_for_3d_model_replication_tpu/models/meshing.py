"""Point cloud -> printable mesh: the reference's STL reconstruction flows.

Capability parity (behavior studied from server/processing.py):
  - reconstruct_stl (A19, :632-787): normals + centroid/outward orientation
    (+ optional flip), watertight Poisson with density trim, optional
    smoothing/simplification post stage, STL output
  - mesh_360 (A20, :791-860): tunable normal estimation, radial vs tangent
    orientation, screened Poisson with full parameter surface, density
    quantile trim

The compute path is ops/poisson.py (grid Poisson, jit) + ops/surface_nets.py
(iso-surface extraction) + ops/meshproc.py (post ops).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.config import MeshConfig
from structured_light_for_3d_model_replication_tpu.ops import (
    meshproc,
    normals as nrmlib,
    poisson,
    surface_nets,
)
from structured_light_for_3d_model_replication_tpu.ops.poisson import (
    trilinear_sample,
)

__all__ = ["reconstruct_mesh", "mesh_to_stl"]


def reconstruct_mesh(points, valid=None, normals=None,
                     cfg: MeshConfig | None = None, log=print):
    """Full cloud -> mesh flow. Returns (vertices [V,3], faces [F,3]).

    Orientation convention: normals are oriented OUTWARD (radial/centroid
    modes, processing.py:657-670, 809-830); Poisson chi is then < iso inside,
    and extracted faces wind outward (positive signed volume).
    """
    cfg = cfg or MeshConfig()
    if cfg.mode not in ("watertight", "surface"):
        raise ValueError(f"mesh.mode must be 'watertight' or 'surface', "
                         f"got {cfg.mode!r}")
    pts = jnp.asarray(points, jnp.float32)
    v = jnp.asarray(valid) if valid is not None else jnp.ones(pts.shape[0], bool)

    if normals is None:
        nr = nrmlib.estimate_normals(pts, v, k=cfg.normal_max_nn,
                                     radius=cfg.normal_radius or None)
        nr = nrmlib.orient_normals(pts, nr, v, mode="radial")
        log(f"[mesh] normals estimated (hybrid r={cfg.normal_radius}, "
            f"max_nn={cfg.normal_max_nn}, radial orient)")
    else:
        nr = jnp.asarray(normals, jnp.float32)

    if cfg.mode == "surface":
        # ball-pivot analog (processing.py:711-728): interpolates the points,
        # keeps sharp detail, leaves holes where sampling is sparse
        from structured_light_for_3d_model_replication_tpu.ops import (
            surface_recon,
        )

        verts, faces = surface_recon.ball_pivot_surface(
            pts, v, nr, k=cfg.surface_k, alpha_factor=cfg.surface_alpha_factor)
        log(f"[mesh] ball-pivot surface: {len(verts):,} verts, "
            f"{len(faces):,} faces")
    else:
        res = _poisson_dispatch(pts, nr, v, cfg.depth, log,
                                density_cap=cfg.density_cap)
        from structured_light_for_3d_model_replication_tpu.ops import (
            poisson_bricks,
        )

        if isinstance(res, poisson_bricks.BrickPoissonResult):
            verts, faces = poisson_bricks.extract_surface_bricks(res)
            # the density field for the low-support trim comes from the
            # coarse base solve (the bricks never materialize a fine one)
            dens_field, dens_res = res.coarse.density, res.coarse
        else:
            verts, faces = surface_nets.extract_surface(
                res.chi, float(res.iso), origin=np.asarray(res.origin),
                cell=float(res.cell))
            dens_field, dens_res = res.density, res
        log(f"[mesh] surface nets: {len(verts):,} verts, {len(faces):,} faces")

        if cfg.density_trim_quantile and cfg.density_trim_quantile > 0:
            # low-support crop (processing.py:707-709): sample the splat
            # density at mesh vertices, drop the lowest quantile
            coords = ((jnp.asarray(verts) - np.asarray(dens_res.origin))
                      / float(dens_res.cell))
            dens = np.asarray(trilinear_sample(dens_field, coords))
            thresh = np.quantile(dens, cfg.density_trim_quantile)
            verts, faces = meshproc.filter_faces_by_vertex_mask(
                verts, faces, dens >= thresh)
            log(f"[mesh] density trim q={cfg.density_trim_quantile}: "
                f"{len(verts):,} verts remain")

    if cfg.close_holes_max_edges > 0:
        verts, faces, n = meshproc.fill_holes(verts, faces,
                                              cfg.close_holes_max_edges)
        log(f"[mesh] closed {n} holes (<= {cfg.close_holes_max_edges} edges)")

    if cfg.smooth_iters > 0:
        if cfg.smooth_method == "taubin":
            verts = meshproc.taubin_smooth(verts, faces, cfg.smooth_iters)
        else:
            verts = meshproc.laplacian_smooth(verts, faces, cfg.smooth_iters)
        log(f"[mesh] {cfg.smooth_method} smoothing x{cfg.smooth_iters}")

    if cfg.simplify_target_faces and len(faces) > cfg.simplify_target_faces:
        if cfg.simplify_method == "quadric":
            verts, faces = meshproc.quadric_decimate(
                verts, faces, cfg.simplify_target_faces)
        else:
            # derive a clustering cell from the target face budget
            bbox = verts.max(0) - verts.min(0)
            area = 2 * (bbox[0] * bbox[1] + bbox[1] * bbox[2]
                        + bbox[0] * bbox[2])
            cell = float(np.sqrt(area / max(cfg.simplify_target_faces, 1)))
            for _ in range(8):
                nv, nf = meshproc.vertex_cluster_decimate(verts, faces, cell)
                if len(nf) <= cfg.simplify_target_faces or len(nf) == 0:
                    break
                cell *= 1.3
            verts, faces = nv, nf
        log(f"[mesh] decimated ({cfg.simplify_method}) to {len(faces):,} faces")

    return verts, faces


def _poisson_dispatch(pts, nr, v, depth: int, log, density_cap: bool = True):
    """Dense single-chip Poisson up to depth 9; depth 10 runs the exact
    slab-sharded solver on a multi-device accelerator mesh, the
    brick-refined solver on a single accelerator, and steps down to dense
    depth 9 on the CPU backend unless mesh.density_cap=false forces it;
    depth 11..16 runs the brick-refined cascadic solver
    (ops/poisson_bricks) on any backend — cost scales with active bricks
    (surface area), covering the reference's full octree envelope
    (server/gui.py:118 / processing.py:697-709) on one chip. Depth
    policy: docs/ARCHITECTURE.md "Poisson depth policy"."""
    import jax

    # cap resolution by sampling density: a surface of N samples occupies
    # ~(2^d)^2 grid cells, so 2^d beyond ~sqrt(N) splats each point into
    # ever more empty cells — pure cost, no detail. Unlike the reference's
    # octree (which adapts per-sample, processing.py:697-709), the dense
    # grid pays (2^d)^3 everywhere: a 50-point degenerate cloud at the
    # config default depth 10 otherwise steps to a 512^3 dense solve
    # (134M cells, minutes-to-hours; found by hostile-input probing, r4).
    # mesh.density_cap=false honors the requested depth instead.
    n = int(np.asarray(v).sum())
    cap = max(4, int(np.ceil(np.log2(max(n, 2)) / 2)) + 1)
    if cap < depth:
        if density_cap:
            log(f"[mesh] poisson depth {depth} -> {cap}: {n} points "
                f"cannot fill a {1 << depth}^3 grid (cap ~ log2(sqrt(N))+1; "
                f"set mesh.density_cap=false to force depth {depth})")
            depth = cap
        else:
            log(f"[mesh] density cap disabled: honoring depth {depth} for "
                f"{n} points (a {1 << depth}^3 dense grid; cap would have "
                f"chosen {cap})")

    accel = jax.default_backend() != "cpu"
    if depth == 10 and not accel and density_cap:
        # degraded mode: brick refinement on a host CPU costs minutes ON
        # TOP of the depth-9 dense base, so the default steps down; the
        # same mesh.density_cap=false knob that forces depth elsewhere
        # forces the full brick solve here too (depth 11+ has no cheaper
        # alternative and always runs bricks)
        log(f"[mesh] WARNING: depth 10 on the CPU backend steps down to "
            f"depth 9 dense (exact depth 10 needs an accelerator; set "
            f"mesh.density_cap=false to force the brick-refined depth-10 "
            f"solve here)")
        depth = 9

    if depth <= 9:
        res = poisson.poisson_solve(pts, nr, v, depth=depth)
        log(f"[mesh] poisson depth={depth} iso={float(res.iso):.4f}")
        return res

    from structured_light_for_3d_model_replication_tpu.ops import (
        poisson_bricks,
        poisson_sharded,
    )

    n_dev = len(jax.devices())
    # virtual CPU devices share one host's RAM — slabbing buys no memory
    # there, so only real accelerator meshes raise the ceiling
    if depth == 10 and accel and n_dev >= 2 and (1 << depth) % n_dev == 0:
        res = poisson_sharded.poisson_solve_sharded(pts, nr, v, depth=depth)
        log(f"[mesh] poisson depth={depth} sharded over {n_dev} devices "
            f"iso={float(res.iso):.4f}")
        return res
    # depth 11..16 (single-accelerator depth 10; CPU depth 10 only when
    # forced): brick-refined solve — cost scales with active bricks
    # (surface area), reaching the reference's octree depth envelope on
    # ONE chip. The coarse base never needs more resolution than the
    # density cap supports.
    res = poisson_bricks.poisson_solve_bricks(
        pts, nr, v, depth=depth, base_depth=min(9, cap, depth - 1),
        log=log)
    log(f"[mesh] poisson depth={depth} brick-refined "
        f"({res.n_bricks} bricks) iso={res.iso:.4f}")
    return res


def mesh_to_stl(path: str, vertices, faces) -> None:
    from structured_light_for_3d_model_replication_tpu.io import stl

    stl.write_stl(path, vertices, faces)
