"""Ray-plane triangulation: decoded projector coordinates -> colored 3D points.

Capability parity (behavior studied from server/processing.py:127-234):
  - camera rays from a stored per-pixel unit-ray field Nc, or regenerated from
    the pinhole intrinsics when Nc is absent
  - intersection of each camera ray with the projector *column* light plane:
    t = -(N . Oc + d) / (N . ray), with a |denom| > 1e-6 divide-by-zero guard
  - row_mode 0: columns only
  - row_mode 1: epipolar consistency filter — keep points whose column
    intersection lies within ``epipolar_tol`` (mm) of the decoded *row* plane
  - row_mode 2: independently triangulate against row planes and concatenate

TPU-first design notes
----------------------
The reference compacts to a variable-length list of valid pixels up front
(np.where) and gathers — a data-dependent shape. Here every pixel keeps its
slot: points are computed for all H*W rays in fixed shape, invalidity is
carried in a boolean mask, and compaction happens only at export time
(io.ply.compact). That keeps the whole step a single fused XLA program and
makes it trivially shard_map-able over pixel rows and batchable over views.

Numerics: all arithmetic is float32 with identical operation order in the
NumPy and JAX paths, using explicit elementwise dot products (x*x+y*y+z*z).
Under jit, XLA contracts multiply-add chains into FMAs (the contraction
happens at instruction selection inside fused kernels — below HLO, so even
lax.optimization_barrier cannot stop it, and no xla_cpu_* debug flag disables
it), so compiled coordinates can differ from the NumPy backend by 1-2 ULP
(~1e-5 mm at scene scale); validity masks and decoded integer maps are always
bit-exact. Tests pin this contract: masks exactly equal, points to <=1e-3 mm.

``bitexact=True`` removes even that ULP gap by running the float math
through the NumPy twin itself at the export boundary: the device supplies
the decoded integer maps and mask (bit-exact by construction), they are
fetched to host, and ``_triangulate_impl`` executes with ``xp=np`` — the
same code path ``triangulate_np`` runs, so equality is by construction,
not by luck. This replaced an eager per-primitive device variant: eager
dispatch avoids FMA contraction, but TPU hardware f32 divide/rsqrt are
not IEEE-correctly-rounded, so op-by-op device execution still differed
from NumPy on TPU (measured r4: chamfer-level mismatches at 30.3 s/view
in eager dispatch overhead; the host path is exact and ~0.7 s/view).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["CloudResult", "pixel_rays", "triangulate", "triangulate_np", "compact_cloud"]


class CloudResult(NamedTuple):
    """Fixed-shape point cloud: one slot per camera pixel (x2 for row_mode=2).

    ``colors`` is uint8 ``[N, 3]`` RGB on the host/NumPy paths; the device
    scanner paths carry ``[N, 1]`` — the gray texture IS frame 0, so the
    channel replication happens host-side at the export boundary
    (``compact_cloud`` / ``compact_views_device``) instead of tripling every
    device->host color transfer."""

    points: jax.Array | np.ndarray  # float32 [N, 3] camera-frame mm
    colors: jax.Array | np.ndarray  # uint8   [N, 3] RGB (or [N, 1] gray)
    valid: jax.Array | np.ndarray   # bool    [N]


def pixel_rays(cam_K, height: int, width: int, xp=np):
    """Unit view rays through every pixel of an (height, width) camera.

    Matches the reference's Nc construction (server/sl_system.py:357-372):
    x_n = (u - cx)/fx, y_n = (v - cy)/fy, z = 1, normalized. Returns [H*W, 3].
    """
    fx = cam_K[0, 0]
    fy = cam_K[1, 1]
    cx = cam_K[0, 2]
    cy = cam_K[1, 2]
    u = xp.arange(width, dtype=xp.float32)[None, :]
    v = xp.arange(height, dtype=xp.float32)[:, None]
    x = ((u - cx) / fx) * xp.ones((height, 1), xp.float32)
    y = ((v - cy) / fy) * xp.ones((1, width), xp.float32)
    z = xp.ones((height, width), xp.float32)
    inv_norm = 1.0 / xp.sqrt(x * x + y * y + z * z)
    rays = xp.stack([x * inv_norm, y * inv_norm, z * inv_norm], axis=-1)
    return rays.reshape(-1, 3).astype(xp.float32)


def _plane_hit(planes, rays, oc, xp):
    """Intersect rays (from oc) with per-pixel planes [N,4]. Returns (t, hit_ok)."""
    n_x, n_y, n_z, d = planes[:, 0], planes[:, 1], planes[:, 2], planes[:, 3]
    denom = n_x * rays[:, 0] + n_y * rays[:, 1] + n_z * rays[:, 2]
    numer = n_x * oc[0] + n_y * oc[1] + n_z * oc[2] + d
    ok = xp.abs(denom) > 1e-6
    t = xp.where(ok, -numer / xp.where(ok, denom, 1.0), 0.0)
    return t, ok


def _poly_planes(coeffs, idx, n_planes, xp):
    """Evaluate the affine/quadratic plane form n4(i) = A + B i + C i^2 for
    per-pixel indices — the gather-free path (see
    calib.geometry.plane_poly_coefficients). Returns [N, 4] rescaled to unit
    normals so downstream guards (_plane_hit's |denom| > 1e-6 degenerate-ray
    test) and the epipolar distance are scale-invariant, matching the table
    path (which stores unit normals)."""
    i = xp.clip(idx, 0, n_planes - 1).astype(xp.float32)[:, None]
    A = coeffs[0][None, :]
    B = coeffs[1][None, :]
    C = coeffs[2][None, :]
    p = A + i * (B + i * C)
    nrm = xp.sqrt(xp.maximum(
        p[:, 0] ** 2 + p[:, 1] ** 2 + p[:, 2] ** 2, 1e-30))
    return p / nrm[:, None]


def _triangulate_impl(
    col_map, row_map, mask, texture,
    rays, oc, plane_col, plane_row,
    *, row_mode: int, epipolar_tol: float, xp, poly=None,
):
    h, w = col_map.shape
    n = h * w
    cols = xp.clip(col_map.reshape(n), 0, plane_col.shape[0] - 1)
    valid = mask.reshape(n)
    # texture is [H, W, 3] RGB on the host paths, [H, W, 1] gray on the
    # device scanner paths (replicated to RGB host-side at compaction)
    tex = texture.reshape(n, -1)

    if poly is None:
        pc = plane_col[cols]  # [N, 4] gather of column-plane equations
    else:
        pc = _poly_planes(poly[0], cols, plane_col.shape[0], xp)
    t_col, ok_col = _plane_hit(pc, rays, oc, xp)
    p_col = oc[None, :] + rays * t_col[:, None]

    if row_mode in (1, 2):
        rows = xp.clip(row_map.reshape(n), 0, plane_row.shape[0] - 1)
        if poly is None:
            pr = plane_row[rows]
        else:
            pr = _poly_planes(poly[1], rows, plane_row.shape[0], xp)

    if row_mode == 0:
        return CloudResult(p_col.astype(xp.float32), tex, valid & ok_col)

    if row_mode == 1:
        # distance of the column intersection from the decoded row plane
        dist = xp.abs(
            pr[:, 0] * p_col[:, 0]
            + pr[:, 1] * p_col[:, 1]
            + pr[:, 2] * p_col[:, 2]
            + pr[:, 3]
        )
        ok = valid & ok_col & (dist < epipolar_tol)
        return CloudResult(p_col.astype(xp.float32), tex, ok)

    if row_mode == 2:
        t_row, ok_row = _plane_hit(pr, rays, oc, xp)
        p_row = oc[None, :] + rays * t_row[:, None]
        pts = xp.concatenate([p_col, p_row], axis=0).astype(xp.float32)
        colors = xp.concatenate([tex, tex], axis=0)
        ok = xp.concatenate([valid & ok_col, valid & ok_row], axis=0)
        return CloudResult(pts, colors, ok)

    raise ValueError(f"row_mode must be 0, 1 or 2, got {row_mode}")


def _prep_calib(calib, h, w, xp):
    """Normalize a calibration dict: transposed plane arrays, optional Nc."""
    plane_col = xp.asarray(calib["wPlaneCol"], xp.float32)
    plane_row = xp.asarray(calib["wPlaneRow"], xp.float32)
    if plane_col.shape[0] == 4:
        plane_col = plane_col.T  # stored transposed in reference .mat files
    if plane_row.shape[0] == 4:
        plane_row = plane_row.T
    oc = xp.asarray(calib["Oc"], xp.float32).reshape(3)
    nc = calib.get("Nc")
    if nc is not None:
        nc = xp.asarray(nc, xp.float32)
        if nc.shape[0] == 3:
            nc = nc.T  # stored [3, H*W]
        if nc.shape[0] != h * w:
            nc = None
    if nc is None:
        nc = pixel_rays(xp.asarray(calib["cam_K"], xp.float32), h, w, xp)
    return nc, oc, plane_col, plane_row


def _check_plane_eval(plane_eval: str) -> None:
    if plane_eval not in ("table", "quadratic"):
        raise ValueError(
            f"plane_eval must be 'table' or 'quadratic', got {plane_eval!r}")


def poly_from_calib(calib, xp=np):
    """(col_coeffs [3,4], row_coeffs [3,4]) f32 for the gather-free plane
    path, from a calibration dict carrying proj_K/R/T."""
    from structured_light_for_3d_model_replication_tpu.calib import geometry

    for k in ("proj_K", "R", "T"):
        if k not in calib:
            raise ValueError(
                f"plane_eval='quadratic' needs '{k}' in the calibration "
                f"(present in every file this framework writes)")
    w = np.asarray(calib["wPlaneCol"])
    h = np.asarray(calib["wPlaneRow"])
    pw = w.shape[0] if w.shape[0] != 4 else w.shape[1]
    ph = h.shape[0] if h.shape[0] != 4 else h.shape[1]
    cc, rr = geometry.plane_poly_coefficients(
        calib["proj_K"], calib["R"], calib["T"], pw, ph)
    return xp.asarray(cc, xp.float32), xp.asarray(rr, xp.float32)


def triangulate_np(
    col_map, row_map, mask, texture, calib,
    row_mode: int = 1, epipolar_tol: float = 2.0,
    plane_eval: str = "table",
) -> CloudResult:
    """NumPy (bit-exact CPU reference) triangulation. Fixed-shape output."""
    _check_plane_eval(plane_eval)
    h, w = col_map.shape
    rays, oc, p_col, p_row = _prep_calib(calib, h, w, np)
    poly = poly_from_calib(calib, np) if plane_eval == "quadratic" else None
    return _triangulate_impl(
        col_map, row_map, mask, texture, rays, oc, p_col, p_row,
        row_mode=row_mode, epipolar_tol=float(epipolar_tol), xp=np, poly=poly,
    )


@functools.partial(jax.jit, static_argnames=("row_mode", "use_poly"))
def _triangulate_jit(col_map, row_map, mask, texture, rays, oc, p_col, p_row,
                     epipolar_tol, poly_col, poly_row, *, row_mode,
                     use_poly: bool):
    return _triangulate_impl(
        col_map, row_map, mask, texture, rays, oc, p_col, p_row,
        row_mode=row_mode, epipolar_tol=epipolar_tol, xp=jnp,
        poly=(poly_col, poly_row) if use_poly else None,
    )


def triangulate(
    col_map, row_map, mask, texture, calib,
    row_mode: int = 1, epipolar_tol: float = 2.0,
    plane_eval: str = "table", bitexact: bool = False,
) -> CloudResult:
    """JAX/TPU triangulation — one fused XLA program over all H*W pixels.

    ``plane_eval``: ``"table"`` gathers the stored per-index plane equations
    (1-2 ULP of the numpy backend under jit); ``"quadratic"`` evaluates the
    closed-form plane polynomial per pixel instead — no gather, ~20x faster
    on TPU for scattered decode maps, within ~1e-5 relative of the table.

    ``bitexact``: fetch the (integer-exact) decode maps to host and run the
    float math through the NumPy twin — coordinates then match
    triangulate_np bit for bit BY CONSTRUCTION (the BASELINE "bit-exact
    point cloud vs CPU path" contract). Device eager execution cannot honor
    this on TPU: hardware f32 divide/rsqrt round differently from IEEE
    NumPy even without fusion. Requires plane_eval='table' (the NumPy
    reference path). Cost: one H*W device→host fetch + ~0.7 s/view of host
    arithmetic, export-boundary only (like compact_cloud).
    """
    _check_plane_eval(plane_eval)
    if bitexact:
        if plane_eval != "table":
            raise ValueError(
                "bitexact=True requires plane_eval='table' (the NumPy "
                "reference evaluates stored plane tables)")
        if isinstance(col_map, jax.core.Tracer):
            raise ValueError(
                "bitexact=True cannot run under an enclosing jit/vmap "
                "trace: it fetches to host and computes with NumPy. Call "
                "it eagerly at the export boundary.")
        return triangulate_np(
            np.asarray(col_map), np.asarray(row_map), np.asarray(mask),
            np.asarray(texture), calib,
            row_mode=row_mode, epipolar_tol=float(epipolar_tol),
        )
    h, w = col_map.shape
    rays, oc, p_col, p_row = _prep_calib(calib, h, w, jnp)
    if plane_eval == "quadratic":
        poly_col, poly_row = poly_from_calib(calib, jnp)
        use_poly = True
    else:
        poly_col = poly_row = jnp.zeros((3, 4), jnp.float32)
        use_poly = False
    return _triangulate_jit(
        col_map, row_map, mask, texture, rays, oc, p_col, p_row,
        jnp.float32(epipolar_tol), poly_col, poly_row,
        row_mode=row_mode, use_poly=use_poly,
    )


def compact_cloud(cloud: CloudResult) -> tuple[np.ndarray, np.ndarray]:
    """Host-side compaction: drop invalid slots. The only data-dependent-shape
    step, deliberately outside jit (export boundary). Single-channel colors
    (the device paths ship the gray frame-0 texture, one byte per slot) are
    replicated to RGB here, AFTER masking — the cheap end of the wire."""
    pts = np.asarray(cloud.points)
    col = np.asarray(cloud.colors)
    ok = np.asarray(cloud.valid)
    pts, col = pts[ok], col[ok]
    if col.ndim == 2 and col.shape[-1] == 1:
        col = np.repeat(col, 3, axis=1)
    return pts, col
