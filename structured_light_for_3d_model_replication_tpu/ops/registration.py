"""Rigid registration: FPFH features, batched-RANSAC global alignment,
point-to-plane ICP — the Open3D registration stack (server/processing.py:
451-486 preprocess + global RANSAC, :572-582 ICP refine) rebuilt for TPU.

TPU-first design notes
----------------------
  - Correspondence search is the grid engine (ops/grid.py) or, for features,
    a dense [Ns, Nd] similarity matmul on the MXU — no KD-trees.
  - Open3D's sequential 100k-iteration RANSAC (processing.py:484) becomes
    *batched hypothesis scoring*: thousands of 3-point Kabsch solves and their
    inlier counts evaluated in one shot; same statistical power, three orders
    of magnitude fewer serial steps.
  - ICP runs a bounded lax.while_loop with masked correspondences (fixed
    shapes), solving the 6x6 point-to-plane normal equations per step and
    stopping at Open3D's convergence criteria (both absolute deltas < 1e-6)
    or the iteration cap.

All transforms are 4x4 float32 row-major, acting on column vectors.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib
from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib

__all__ = ["RegistrationResult", "icp_point_to_plane", "fpfh_features",
           "ransac_global_registration", "register_pairs",
           "register_pairs_sharded", "transform_points", "compose", "kabsch"]


class RegistrationResult(NamedTuple):
    transform: jax.Array  # [4,4]
    fitness: jax.Array    # inlier fraction of valid source points
    rmse: jax.Array       # inlier RMSE


# geometry contractions run at HIGHEST everywhere in this module: the TPU
# default matmul precision is bf16-class (eps ~4e-3 — millimeters at this
# rig's working distance), measured to leave kabsch rotations off-orthogonal
# by 2e-2 before the pins landed
_MM = jax.lax.Precision.HIGHEST


def transform_points(T, pts):
    return jnp.matmul(pts, T[:3, :3].T, precision=_MM) + T[:3, 3]


def compose(a, b):
    """Transform equivalent to applying b, then a."""
    return jnp.matmul(a, b, precision=_MM)


def _skew(v):
    z = jnp.zeros_like(v[..., 0])
    return jnp.stack([
        jnp.stack([z, -v[..., 2], v[..., 1]], -1),
        jnp.stack([v[..., 2], z, -v[..., 0]], -1),
        jnp.stack([-v[..., 1], v[..., 0], z], -1),
    ], -2)


def _exp_so3(w):
    """Rodrigues: [..,3] axis-angle -> [..,3,3] rotation."""
    theta = jnp.sqrt((w * w).sum(-1, keepdims=True) + 1e-24)[..., None]
    k = _skew(w / theta[..., 0])
    eye = jnp.eye(3, dtype=w.dtype)
    return eye + jnp.sin(theta) * k \
        + (1 - jnp.cos(theta)) * jnp.matmul(k, k, precision=_MM)


def kabsch(p, q, w=None):
    """Least-squares rigid transform aligning p -> q. p, q: [.., M, 3];
    optional weights [.., M]. Returns [.., 4, 4]."""
    if w is None:
        w = jnp.ones(p.shape[:-1], p.dtype)
    ws = jnp.maximum(w.sum(-1, keepdims=True), 1e-12)
    cp = (p * w[..., None]).sum(-2) / ws
    cq = (q * w[..., None]).sum(-2) / ws
    pc = (p - cp[..., None, :]) * w[..., None]
    qc = q - cq[..., None, :]
    h = jnp.einsum("...mi,...mj->...ij", pc, qc, precision=_MM)
    u, s, vt = jnp.linalg.svd(h)
    det = jnp.linalg.det(jnp.einsum("...ij,...jk->...ik",
                                    jnp.swapaxes(vt, -1, -2),
                                    jnp.swapaxes(u, -1, -2), precision=_MM))
    d = jnp.stack([jnp.ones_like(det), jnp.ones_like(det), det], -1)
    r = jnp.einsum("...ji,...j,...jk->...ik", vt, d,
                   jnp.swapaxes(u, -1, -2), precision=_MM)
    # two Newton-Schulz sweeps (R <- R(3I - R^T R)/2) polish the f32 SVD's
    # residual non-orthogonality down to roundoff
    eye3 = jnp.eye(3, dtype=r.dtype)
    for _ in range(2):
        rtr = jnp.einsum("...ji,...jk->...ik", r, r, precision=_MM)
        r = 0.5 * jnp.einsum("...ij,...jk->...ik", r, 3.0 * eye3 - rtr,
                             precision=_MM)
    t = cq - jnp.einsum("...ij,...j->...i", r, cp, precision=_MM)
    bot = jnp.broadcast_to(jnp.asarray([0, 0, 0, 1], p.dtype),
                           r.shape[:-2] + (1, 4))
    top = jnp.concatenate([r, t[..., :, None]], -1)
    return jnp.concatenate([top, bot], -2)


# ---------------------------------------------------------------------------
# Point-to-plane ICP
# ---------------------------------------------------------------------------

def _icp_step_update(T, cur, q, nrm, ok, nv):
    """Solve the 6x6 point-to-plane normal equations for one GN step."""
    w = ok.astype(jnp.float32)
    r = ((cur - q) * nrm).sum(-1)                     # signed p2plane residual
    jac = jnp.concatenate([jnp.cross(cur, nrm), nrm], -1)  # [N, 6]
    a = jnp.einsum("ni,nj->ij", jac * w[:, None], jac, precision=_MM)
    b = -(jac * (w * r)[:, None]).sum(0)
    x = jnp.linalg.solve(a + 1e-6 * jnp.eye(6), b)
    dT = jnp.eye(4, dtype=T.dtype)
    dT = dT.at[:3, :3].set(_exp_so3(x[:3]))
    dT = dT.at[:3, 3].set(x[3:])
    rmse = jnp.sqrt((w * r * r).sum() / jnp.maximum(w.sum(), 1.0))
    fitness = w.sum() / nv
    return compose(dT, T), fitness, rmse


@functools.partial(jax.jit, static_argnames=("iters", "rings"))
def _icp_jit(src, src_valid, grid: gridlib.HashGrid, dst_normals, T0,
             max_dist, iters: int, rings: int):
    """Grid-NN arm of ICP; same convergence-stopped loop as _icp_core so
    both dispatch arms share iteration semantics across backends."""
    nv = jnp.maximum(src_valid.sum().astype(jnp.float32), 1.0)

    def body(state):
        T, _, prev_fit, prev_rmse, it = state
        cur = transform_points(T, src)
        idx, d2 = gridlib._query_knn_jit(grid, cur, 1, rings, 4096)
        j = idx[:, 0]
        d2 = d2[:, 0]
        q = grid.points[j]
        nrm = dst_normals[j]
        ok = src_valid & (d2 <= max_dist * max_dist) & jnp.isfinite(d2)
        T_new, fitness, rmse = _icp_step_update(T, cur, q, nrm, ok, nv)
        return (T_new, (prev_fit, prev_rmse), fitness, rmse, it + 1)

    def cond(state):
        _, (pf, pr), fit, rmse, it = state
        moved = (jnp.abs(fit - pf) > 1e-6) | (jnp.abs(rmse - pr) > 1e-6)
        return (it < iters) & ((it == 0) | moved)

    neg1 = src[0, 0] * 0.0 - 1.0
    init = (T0.astype(jnp.float32), (neg1, neg1), neg1, neg1, jnp.int32(0))
    T, _, fit, rmse, _ = jax.lax.while_loop(cond, body, init)
    return T, fit, rmse


def _nn1_brute_jnp(cur, dst_pts, dst_valid, block_q: int | None = None):
    """Exact 1-NN via dense distance blocks (argmin on-chip). The jnp twin of
    pallas_kernels.nn1 for traced contexts without Mosaic.

    Queries are processed in ``block_q`` chunks (lax.map) so peak memory is
    O(block_q * M) instead of O(N * M) — a 20k x 20k cloud pair would
    otherwise materialize a 1.7 GB matrix per call. The default chunk
    shrinks with M (same ~0.5 GB block bound as knn_dense_approx) so a
    512k-point destination costs 256-row blocks, not a 4 GiB allocation."""
    n = cur.shape[0]
    m = dst_pts.shape[0]
    if block_q is None:
        block_q = 2048
        while block_q > 64 and block_q * m * 4 > (1 << 29):
            block_q //= 2
    d2_dst = (dst_pts * dst_pts).sum(-1)

    def chunk_nn(q):
        # full f32: the d2 expansion cancels catastrophically in bf16 (same
        # reasoning as pallas_kernels._nn1_kernel's HIGHEST-precision dot)
        cross = jnp.matmul(q, dst_pts.T,
                           precision=_MM)
        d2 = ((q * q).sum(-1, keepdims=True) + d2_dst[None, :] - 2.0 * cross)
        d2 = jnp.where(dst_valid[None, :], d2, jnp.inf)
        j = jnp.argmin(d2, axis=1).astype(jnp.int32)
        # selection rides the MXU expansion; the returned distance is
        # recomputed exactly (knn.exact_d2), inf when no valid dst exists
        d2j = jnp.where(dst_valid[j], knnlib.exact_d2(q, dst_pts, j),
                        jnp.inf)
        return j, d2j

    if n * m <= (4 << 20):
        return chunk_nn(cur)
    n_pad = -(-n // block_q) * block_q
    curp = jnp.concatenate(
        [cur, jnp.full((n_pad - n, 3), 1e9, cur.dtype)]) if n_pad > n else cur
    j, d2 = jax.lax.map(chunk_nn, curp.reshape(-1, block_q, 3))
    return j.reshape(-1)[:n], d2.reshape(-1)[:n]


def _nn1_dispatch(cur, dst_pts, dst_valid, nn_mode: str, block: int = 1024):
    """1-NN by ``nn_mode``: the tiled Mosaic kernel ('pallas', bounded VMEM)
    or the dense jnp matrix ('brute'). The loop-invariant dst padding is
    hoisted by XLA when called inside a scan."""
    if nn_mode == "pallas":
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        n = cur.shape[0]
        nb_pad = -(-dst_pts.shape[0] // block) * block
        dst8 = pk._pad8(dst_pts, dst_valid, nb_pad)
        nq_pad = -(-n // block) * block
        q8 = jnp.zeros((nq_pad, 8), jnp.float32).at[:n, :3].set(cur)
        _, idxc = pk._nn1_call(q8, dst8, block, block, False)
        idxc = idxc[:n, 0]
        # same exact-distance recompute as pk.nn1 / the brute arm: ICP's
        # fitness, rmse, and max_correspondence gating must not inherit the
        # kernel expansion's f32 cancellation floor
        return idxc, knnlib.exact_d2(cur, dst8[:, :3], idxc)
    return _nn1_brute_jnp(cur, dst_pts, dst_valid)


def _icp_core(src, src_valid, dst_pts, dst_valid, dst_normals, T0,
              max_dist, iters: int, nn_mode: str, block: int = 1024):
    """Traceable convergence-stopped point-to-plane ICP (max ``iters``
    Gauss-Newton steps, Open3D ICPConvergenceCriteria semantics: stop when
    BOTH relative fitness and relative RMSE move < 1e-6). ``nn_mode``:
    'pallas' = Mosaic brute-force 1-NN kernel (unbatched lowering — safe
    inside lax.map/scan), 'brute' = dense jnp distance matrix. Each 1-NN
    pass is the dominant cost, so early exit is a real saving even inside
    a sequential lax.map over pairs."""
    nv = jnp.maximum(src_valid.sum().astype(jnp.float32), 1.0)

    def body(state):
        T, _, prev_fit, prev_rmse, it = state
        cur = transform_points(T, src)
        j, d2 = _nn1_dispatch(cur, dst_pts, dst_valid, nn_mode, block)
        q = dst_pts[j]
        nrm = dst_normals[j]
        ok = src_valid & (d2 <= max_dist * max_dist) & jnp.isfinite(d2)
        T_new, fitness, rmse = _icp_step_update(T, cur, q, nrm, ok, nv)
        return (T_new, (prev_fit, prev_rmse), fitness, rmse, it + 1)

    def cond(state):
        _, (pf, pr), fit, rmse, it = state
        # Open3D's ICPConvergenceCriteria compares both deltas as absolute
        # 1e-6 thresholds (despite the relative_* parameter names) — which
        # works in its f64 math because rmse genuinely settles. In f32 the
        # converged state OSCILLATES: measured on the bench pairs, fitness
        # freezes by ~it8 while rmse jitters in a +-5e-4 band forever, so
        # a bare 1e-6 never fires and every pair silently burned the full
        # iteration cap (r5 finding; the r4 note claiming 8-12-iter stops
        # was wrong). The rmse leg is therefore direction-aware: the
        # converged state REGRESSES or stalls (measured oscillation band
        # ~2.3e-3 relative, roughly half the steps increase rmse), while
        # genuine slow descent improves monotonically — so convergence is
        # a step that did not improve beyond fp noise AND stayed inside
        # the 2e-3*rmse noise band. Oscillating pairs stop at their first
        # small regression (~it9-10 on the bench pairs, where an
        # icp_iters=10 cap left fitness/gfit bit-identical); a pair whose
        # rmse still descends 0.05%/step keeps iterating to the cap.
        tol_r = jnp.maximum(jnp.float32(1e-6), 2e-3 * rmse)
        improved = (pr - rmse) > 1e-6
        moved = (jnp.abs(fit - pf) > 1e-6) | improved \
            | (jnp.abs(rmse - pr) > tol_r)
        return (it < iters) & ((it == 0) | moved)

    # init scalars derive from the data so their sharding "varying" type
    # matches the loop-computed fitness/rmse under shard_map
    neg1 = src[0, 0] * 0.0 - 1.0
    init = (T0.astype(jnp.float32), (neg1, neg1), neg1, neg1, jnp.int32(0))
    T, _, fit, rmse, _ = jax.lax.while_loop(cond, body, init)
    return T, fit, rmse


@functools.partial(jax.jit, static_argnames=("iters", "block"))
def _icp_jit_pallas(src, src_valid, dst_pts, dst_valid, dst_normals, T0,
                    max_dist, iters: int, block: int):
    """ICP with Pallas brute-force 1-NN correspondences (TPU: the MXU distance
    product beats the gather-heavy grid query by ~two orders of magnitude)."""
    return _icp_core(src, src_valid, dst_pts, dst_valid, dst_normals, T0,
                     max_dist, iters, "pallas", block)


@functools.partial(jax.jit, static_argnames=("iters",))
def _icp_jit_brute(src, src_valid, dst_pts, dst_valid, dst_normals, T0,
                   max_dist, iters: int):
    """ICP with chunked dense-jnp 1-NN: the accelerator fallback when Mosaic
    is unavailable or fails at this shape — the grid engine is host-only
    (its bucket gathers crash the TPU runtime, ops/grid.py module notes)."""
    return _icp_core(src, src_valid, dst_pts, dst_valid, dst_normals, T0,
                     max_dist, iters, "brute")


def icp_point_to_plane(src_pts, src_valid, dst_pts, dst_valid, dst_normals,
                       init_transform=None, max_dist: float = 4.5,
                       iters: int = 30) -> RegistrationResult:
    """Point-to-plane ICP of src onto dst (Open3D TransformationEstimation-
    PointToPlane semantics, processing.py:572-582). Up to ``iters`` Gauss-
    Newton steps, stopped at Open3D's convergence criteria. Correspondence
    dispatch: the Mosaic nn1 kernel (accelerators, dst <= 131072), chunked
    dense-jnp 1-NN (accelerators past the gate or on Mosaic failure — the
    hash grid is host-only), or the hash grid (CPU hosts)."""
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    dst = jnp.asarray(dst_pts, jnp.float32)
    dvalid = jnp.asarray(dst_valid) if dst_valid is not None else \
        jnp.ones(dst.shape[0], bool)
    src = jnp.asarray(src_pts, jnp.float32)
    svalid = jnp.asarray(src_valid) if src_valid is not None \
        else jnp.ones(src_pts.shape[0], bool)
    T0 = jnp.eye(4, dtype=jnp.float32) if init_transform is None \
        else jnp.asarray(init_transform, jnp.float32)

    if pk.use_pallas() and dst.shape[0] <= 131072:
        try:
            T, fit, rmse = _icp_jit_pallas(
                src, svalid, dst, dvalid, jnp.asarray(dst_normals, jnp.float32),
                T0, jnp.float32(max_dist), iters, 1024)
            return RegistrationResult(T, fit, rmse)
        except Exception:  # Mosaic compile/VMEM failure at this shape:
            pass           # fall through to the dense / grid path below

    if jax.default_backend() != "cpu":
        # accelerators never take the grid arm (host-only engine): chunked
        # dense 1-NN blocks stay exact at bounded memory on the MXU
        T, fit, rmse = _icp_jit_brute(
            src, svalid, dst, dvalid, jnp.asarray(dst_normals, jnp.float32),
            T0, jnp.float32(max_dist), iters)
        return RegistrationResult(T, fit, rmse)

    # cell >= max_dist would guarantee exactness but can explode occupancy;
    # 2 rings at cell=max_dist/2 gives the same guarantee at bounded memory
    grid = gridlib.build_grid(dst, dvalid, float(max_dist) / 2 + 1e-6)
    rings = int(np.ceil(float(max_dist) / float(np.asarray(grid.cell)))) + 1
    rings = min(rings, 5)
    T, fit, rmse = _icp_jit(src, svalid,
                            grid, jnp.asarray(dst_normals, jnp.float32), T0,
                            jnp.float32(max_dist), iters, rings)
    return RegistrationResult(T, fit, rmse)


# ---------------------------------------------------------------------------
# FPFH features (A16's compute_fpfh_feature)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k",))
def _fpfh_jit(points, normals, valid, idx, d2, radius, k: int):
    """FPFH from a fixed-k neighborhood (the grid/brute kNN of the caller).

    SPFH: for each neighbor pair, the Darboux-frame angles (alpha, phi, theta)
    binned into 3x11 histograms; FPFH_i = SPFH_i + mean_j w_j SPFH_j with
    w_j = 1/d_ij — Rusu's formulation, fixed shapes.
    """
    n = points.shape[0]
    nb_ok = (d2 <= radius * radius) & valid[idx] & valid[:, None] & (d2 > 0)
    p = points[:, None, :]
    q = points[idx]
    nrm_p = normals[:, None, :]
    nrm_q = normals[idx]
    d = q - p
    dist = jnp.sqrt(jnp.maximum(d2, 1e-20))[..., None]
    u = nrm_p
    dn = d / dist
    # ensure source normal points "toward" consistent frame (Rusu's ordering
    # simplification: swap so angle between u and d is acute)
    v = jnp.cross(dn, u)
    v_n = v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-12)
    w = jnp.cross(u, v_n)
    alpha = (v_n * nrm_q).sum(-1)                       # in [-1,1]
    phi = (u * dn).sum(-1)                              # in [-1,1]
    theta = jnp.arctan2((w * nrm_q).sum(-1), (u * nrm_q).sum(-1))  # [-pi,pi]

    def hist11(x, lo, hi):
        b = jnp.clip(((x - lo) / (hi - lo) * 11).astype(jnp.int32), 0, 10)
        oh = jax.nn.one_hot(b, 11, dtype=jnp.float32)
        return (oh * nb_ok[..., None]).sum(1)           # [N, 11]

    spfh = jnp.concatenate([
        hist11(alpha, -1.0, 1.0),
        hist11(phi, -1.0, 1.0),
        hist11(theta, -jnp.pi, jnp.pi),
    ], axis=-1)                                          # [N, 33]
    cnt = jnp.maximum(nb_ok.sum(-1, keepdims=True).astype(jnp.float32), 1.0)
    spfh = spfh / cnt                                    # normalize per point

    wgt = jnp.where(nb_ok, 1.0 / jnp.sqrt(jnp.maximum(d2, 1e-12)), 0.0)
    wsum = jnp.maximum(wgt.sum(-1, keepdims=True), 1e-12)
    neigh_spfh = (spfh[idx] * wgt[..., None]).sum(1) / wsum
    fpfh = spfh + neigh_spfh
    return jnp.where(valid[:, None], fpfh, 0.0)


def fpfh_features(points, normals, valid, radius: float, k: int = 64,
                  idx_d2=None):
    """FPFH [N, 33] over a radius-bounded k-neighborhood.

    ``idx_d2``: optional precomputed (idx [N,>=k], d2 [N,>=k]) neighbors,
    shared with estimate_normals by feature-prep callers."""
    from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib

    if idx_d2 is not None:
        idx, d2 = (a[:, :k] for a in idx_d2)
    else:
        idx, d2 = knnlib.knn(points, valid, k)
    return _fpfh_jit(jnp.asarray(points, jnp.float32),
                     jnp.asarray(normals, jnp.float32),
                     jnp.asarray(valid), idx, d2, jnp.float32(radius), k)


# ---------------------------------------------------------------------------
# Global registration: feature matching + batched RANSAC (A17)
# ---------------------------------------------------------------------------

def _feature_correspondences(sf, df, sv, dv, mutual: bool,
                             block: int = 2048, feat_bf16: bool = False):
    """Nearest-feature correspondences src->dst via dense feature-distance
    matmuls on the MXU, chunked over src rows so peak memory is
    O(block * Nd), not O(Ns * Nd). With ``mutual`` (Open3D's mutual_filter
    semantics, processing.py:477-484's checker spirit) a correspondence
    survives only if its dst point's nearest src feature points back —
    unless that leaves fewer than 10 matches, in which case the
    one-directional set is kept (round-2 verdict weak #3: one-directional
    argmin matches were the main cause of near-threshold global fitness).

    ``feat_bf16`` (parallel.force_bf16_features): run the feature cross
    product in bf16 with f32 accumulation — one MXU pass instead of
    HIGHEST's three. FPFH distances only pick argmin matches (geometry
    stays f32 downstream), and RANSAC's checkers + refine absorb the
    ~4e-3-relative match noise; near-tie correspondences may differ."""
    ns = sf.shape[0]
    nf = sf.shape[1]
    df2 = (df * df).sum(-1)
    dft = df.astype(jnp.bfloat16).T if feat_bf16 else df.T

    def chunk(args):
        f, v = args
        if feat_bf16:
            cross = jnp.matmul(f.astype(jnp.bfloat16), dft,
                               preferred_element_type=jnp.float32)
        else:
            cross = jnp.matmul(f, dft, precision=_MM)
        d2 = (f * f).sum(-1, keepdims=True) + df2[None, :] - 2.0 * cross
        d2 = jnp.where(dv[None, :], d2, jnp.inf)
        cj = jnp.argmin(d2, axis=1).astype(jnp.int32)
        # dst-side running best over this chunk's valid src rows
        d2s = jnp.where(v[:, None], d2, jnp.inf)
        bmin = d2s.min(axis=0)
        barg = jnp.argmin(d2s, axis=0).astype(jnp.int32)
        return cj, bmin, barg

    if ns <= block:
        corr_j, bmin, barg = chunk((sf, sv))
        back_i = barg
    else:
        n_pad = -(-ns // block) * block
        sfp = jnp.concatenate([sf, jnp.zeros((n_pad - ns, nf), sf.dtype)]) \
            if n_pad > ns else sf
        svp = jnp.concatenate([sv, jnp.zeros(n_pad - ns, bool)]) \
            if n_pad > ns else sv
        cj, bmin, barg = jax.lax.map(
            chunk, (sfp.reshape(-1, block, nf), svp.reshape(-1, block)))
        corr_j = cj.reshape(-1)[:ns]
        kbest = jnp.argmin(bmin, axis=0)                       # [Nd] chunk id
        back_i = (jnp.take_along_axis(barg, kbest[None, :], axis=0)[0]
                  + kbest.astype(jnp.int32) * block)
    corr_ok = sv
    if mutual:
        mut = back_i[corr_j] == jnp.arange(ns, dtype=jnp.int32)
        ok_mut = corr_ok & mut
        corr_ok = jnp.where(ok_mut.sum() >= 10, ok_mut, corr_ok)
    return corr_j, corr_ok


def _ransac_core(src, src_valid, dst, dst_valid, corr_j, corr_ok, max_dist,
                 edge_sim, key, *, trials: int, refine_iters: int,
                 nn_mode: str = "brute"):
    """Batched-hypothesis RANSAC + iterated weighted-Kabsch refine
    (traceable; no host sync).

    Fitness/RMSE follow Open3D's GetRegistrationResultAndCorrespondences:
    nearest-neighbor matches of ALL transformed source points within
    max_dist — an alignment measure — not the feature-correspondence hit
    rate (which on feature-ambiguous geometry, e.g. smooth spheres, caps
    near its match precision no matter how good the transform is)."""
    ns = src.shape[0]
    probs = corr_ok.astype(jnp.float32)
    probs = probs / jnp.maximum(probs.sum(), 1.0)
    samp = jax.random.choice(key, ns, shape=(trials, 3), p=probs)
    p = src[samp]                    # [T,3,3]
    q = dst[corr_j[samp]]            # [T,3,3]

    # Open3D's correspondence checkers: edge-length similarity prune
    def edges(x):
        return jnp.stack([
            jnp.linalg.norm(x[:, 0] - x[:, 1], axis=-1),
            jnp.linalg.norm(x[:, 1] - x[:, 2], axis=-1),
            jnp.linalg.norm(x[:, 0] - x[:, 2], axis=-1)], -1)

    ep, eq = edges(p), edges(q)
    ratio = jnp.minimum(ep, eq) / jnp.maximum(jnp.maximum(ep, eq), 1e-9)
    edge_pass = (ratio > edge_sim).all(-1)

    T = kabsch(p, q)                 # [T,4,4]
    # distance checker (CorrespondenceCheckerBasedOnDistance): the sampled
    # correspondences themselves must land within max_dist under T
    moved_s = jnp.einsum("tij,tnj->tni", T[:, :3, :3], p,
                         precision=_MM) + T[:, None, :3, 3]
    dist_pass = (((moved_s - q) ** 2).sum(-1)
                 <= max_dist * max_dist).all(-1)

    # hypothesis scoring as [T, K] x [K, N] matmuls: expanding
    # ||R s + t - c||^2 = ||s||^2 + ||c||^2 + ||t||^2
    #                     + 2 (R^T t) . s - 2 R:(c x s) - 2 t . c
    # keeps every intermediate at [T, N] (the naive einsum materializes
    # [T, N, 3] moved-point tensors, 3x the traffic and off the MXU).
    # f32 cancellation error here is ~|coord|^2 * eps ~ 0.05 mm^2 against a
    # max_dist^2 threshold of ~20 mm^2 — irrelevant for inlier COUNTING;
    # the refine below uses exact differences.
    # center both clouds first: the expansion's cancellation error scales
    # with |coord|^2, and the rig's working distance (~400 mm) would put
    # ~0.1 mm^2 of noise against the ~20 mm^2 threshold; centered coords
    # (~±100 mm) keep it at ~0.01 mm^2. Shift: ||R s + t - c||
    # = ||R s_c + (t + R mu_s - mu_c) - c_c|| with s_c = s - mu_s etc.
    dst_c = dst[corr_j]
    wv = jnp.where(corr_ok, 1.0, 0.0)
    mu_s = jnp.matmul(wv, src, precision=_MM) / jnp.maximum(corr_ok.sum(), 1)
    mu_c = jnp.matmul(wv, dst_c, precision=_MM) / jnp.maximum(corr_ok.sum(), 1)
    src_c = src - mu_s
    dst_cc = dst_c - mu_c
    s2 = (src_c * src_c).sum(-1)                  # [N]
    c2 = (dst_cc * dst_cc).sum(-1)                # [N]
    cs9 = (dst_cc[:, :, None] * src_c[:, None, :]).reshape(ns, 9)  # c_i s_j
    R9 = T[:, :3, :3].reshape(-1, 9)              # R_ij, i-major
    tt = (T[:, :3, 3] - mu_c[None, :]
          + jnp.einsum("tij,j->ti", T[:, :3, :3], mu_s,
                       precision=_MM))  # [T, 3]
    t2 = (tt * tt).sum(-1)                        # [T]
    Rt = jnp.einsum("tij,ti->tj", T[:, :3, :3], tt,
                    precision=_MM)  # R^T t [T, 3]

    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    if nn_mode == "pallas" and pk.ransac_score_ok():
        # Mosaic scoring: the centered expansion above folds into ONE
        # [T,16] x [16,N] MXU matmul (pallas_kernels._ransac_score_kernel);
        # dead correspondences carry sc=+inf so they can never count. This
        # gate rides the same try/except degrade as the pallas nn1
        # dispatch — any score-time surprise re-runs the registration with
        # nn_mode="brute" and the chunked jnp scoring below.
        sc = jnp.where(corr_ok, s2 + c2, jnp.inf)
        counts = pk.ransac_score(R9, tt, t2, Rt, src_c, cs9, dst_cc, sc,
                                 max_dist * max_dist)
    else:
        def score_chunk(args):
            R9c, ttc, t2c, Rtc = args
            cross = (jnp.matmul(Rtc, src_c.T, precision=_MM)
                     - jnp.matmul(R9c, cs9.T, precision=_MM)
                     - jnp.matmul(ttc, dst_cc.T, precision=_MM))
            d2 = s2[None, :] + c2[None, :] + t2c[:, None] + 2.0 * cross
            inl = (d2 <= max_dist * max_dist) & corr_ok[None, :]
            return inl.sum(-1)

        t_chunk = max(1, min(trials, (8 << 20) // max(ns, 1)))
        pad = (-trials) % t_chunk
        if pad:
            # static shapes want equal chunks: pad the hypothesis set to
            # the next chunk multiple (padded rows score garbage that the
            # slice below discards) — the 8M-element [T,N] bound holds for
            # ANY trial count, with no giant-chunk or serialized fallback
            R9 = jnp.concatenate([R9, jnp.zeros((pad, 9), R9.dtype)])
            tt = jnp.concatenate([tt, jnp.zeros((pad, 3), tt.dtype)])
            t2 = jnp.concatenate([t2, jnp.zeros((pad,), t2.dtype)])
            Rt = jnp.concatenate([Rt, jnp.zeros((pad, 3), Rt.dtype)])
        counts = jax.lax.map(
            score_chunk,
            (R9.reshape(-1, t_chunk, 9), tt.reshape(-1, t_chunk, 3),
             t2.reshape(-1, t_chunk), Rt.reshape(-1, t_chunk, 3))
        ).reshape(-1)[:trials]
    scores = jnp.where(edge_pass & dist_pass, counts, -1)
    best = jnp.argmax(scores)
    moved_b = transform_points(T[best], src)
    d2_b = ((moved_b - dst_c) ** 2).sum(-1)
    inl_best = (d2_b <= max_dist * max_dist) & corr_ok

    # iterated refine: weighted Kabsch on the inlier set, re-evaluate the
    # inliers, repeat — Open3D reaches the same fixpoint through its local
    # refinement; a single weighted solve (round 2) under-converged
    def refine_step(w, _):
        T_ref = kabsch(src, dst[corr_j], w)
        moved = transform_points(T_ref, src)
        d2r = ((moved - dst[corr_j]) ** 2).sum(-1)
        inl_r = (d2r <= max_dist * max_dist) & corr_ok
        # keep the previous inlier set if a step empties it (degenerate guard)
        w_next = jnp.where(inl_r.any(), inl_r.astype(jnp.float32), w)
        return w_next, (T_ref, inl_r, d2r)

    w0 = inl_best.astype(jnp.float32)
    _, (T_refs, _, _) = jax.lax.scan(
        refine_step, w0, None, length=max(int(refine_iters), 1))
    T_ref = T_refs[-1]
    # Open3D-parity evaluation: NN over all valid source points
    cur = transform_points(T_ref, src)
    _, d2n = _nn1_dispatch(cur, dst, dst_valid, nn_mode)
    inl_n = src_valid & (d2n <= max_dist * max_dist) & jnp.isfinite(d2n)
    nv = jnp.maximum(src_valid.sum().astype(jnp.float32), 1.0)
    fitness = inl_n.sum() / nv
    rmse = jnp.sqrt((jnp.where(inl_n, d2n, 0)).sum()
                    / jnp.maximum(inl_n.sum(), 1))
    return T_ref, fitness, rmse


@functools.partial(jax.jit,
                   static_argnames=("trials", "mutual", "refine_iters",
                                    "nn_mode", "feat_bf16"))
def _ransac_jit(src, dst, sf, df, sv, dv, max_dist, edge_sim, key, *,
                trials: int, mutual: bool, refine_iters: int,
                nn_mode: str = "brute", feat_bf16: bool = False):
    corr_j, corr_ok = _feature_correspondences(sf, df, sv, dv, mutual,
                                               feat_bf16=feat_bf16)
    return _ransac_core(src, sv, dst, dv, corr_j, corr_ok, max_dist,
                        edge_sim, key, trials=trials,
                        refine_iters=refine_iters, nn_mode=nn_mode)


def _resolve_feat_bf16(feat_bf16: bool | None) -> bool:
    """None = auto: f32 everywhere. bf16 feature matmuls were measured
    on-chip (r5 register sweep, BENCH_NOTES.md) to cost nothing in time
    (0.356 vs 0.371 s steady at 1024 trials) but drop global fitness
    0.818 -> 0.608 — the 33-bin FPFH histograms are too quantized to
    survive 8-bit mantissas in the correspondence matmul. Explicit
    ``True`` keeps the one-MXU-pass path for callers who want it."""
    if feat_bf16 is None:
        return False
    return bool(feat_bf16)


def ransac_global_registration(src_pts, src_feat, src_valid,
                               dst_pts, dst_feat, dst_valid,
                               max_dist: float, trials: int = 4096,
                               edge_sim: float = 0.9,
                               seed: int = 0, mutual: bool = True,
                               refine_iters: int = 3,
                               feat_bf16: bool | None = None) -> RegistrationResult:
    """Feature-matched RANSAC alignment (processing.py:471-486 semantics:
    FPFH nearest-neighbor correspondences with mutual filter, edge-length 0.9
    + distance checkers, iterated inlier refine).

    Correspondences come from a dense [Ns, Nd] feature-distance matmul (MXU);
    ``trials`` batched hypotheses replace Open3D's 100k sequential iterations.
    """
    src = jnp.asarray(src_pts, jnp.float32)
    dst = jnp.asarray(dst_pts, jnp.float32)
    sf = jnp.asarray(src_feat, jnp.float32)
    df = jnp.asarray(dst_feat, jnp.float32)
    sv = jnp.asarray(src_valid) if src_valid is not None else \
        jnp.ones(src.shape[0], bool)
    dv = jnp.asarray(dst_valid) if dst_valid is not None else \
        jnp.ones(dst.shape[0], bool)
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    key = jax.random.PRNGKey(seed)
    fb16 = _resolve_feat_bf16(feat_bf16)
    if pk.use_pallas() and dst.shape[0] <= 131072:
        try:
            T, fit, rmse = _ransac_jit(src, dst, sf, df, sv, dv,
                                       jnp.float32(max_dist),
                                       jnp.float32(edge_sim), key,
                                       trials=trials, mutual=mutual,
                                       refine_iters=refine_iters,
                                       nn_mode="pallas", feat_bf16=fb16)
            return RegistrationResult(T, fit, rmse)
        except Exception:
            pass
    T, fit, rmse = _ransac_jit(src, dst, sf, df, sv, dv,
                               jnp.float32(max_dist), jnp.float32(edge_sim),
                               key, trials=trials, mutual=mutual,
                               refine_iters=refine_iters, feat_bf16=fb16)
    return RegistrationResult(T, fit, rmse)


# ---------------------------------------------------------------------------
# All-pairs batched registration: the merge chain in ONE device launch
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "trials", "icp_iters", "mutual", "refine_iters", "nn_mode",
    "feat_bf16"))
def _register_pairs_jit(src_pts, src_valid, src_feat,
                        dst_pts, dst_valid, dst_feat, dst_normals,
                        max_dist, icp_max_dist, edge_sim, key, pair_ids, *,
                        trials: int, icp_iters: int, mutual: bool,
                        refine_iters: int, nn_mode: str,
                        feat_bf16: bool = False):
    # pair_ids [P] i32: the RANSAC key folds each pair's EXPLICIT id, not
    # its position in this launch — so a pair's transform is a pure
    # function of (its two padded clouds, its id, the knobs), invariant to
    # how pairs are grouped into launches or sharded across devices. The
    # streaming merge depends on this: pairs registered one batch at a
    # time must be bit-identical to the all-pairs barrier launch.
    def one(args):
        i, sp, sv, sf, dp, dv, df, dn = args
        corr_j, corr_ok = _feature_correspondences(sf, df, sv, dv, mutual,
                                                   feat_bf16=feat_bf16)
        k = jax.random.fold_in(key, i)
        T0, gfit, grmse = _ransac_core(sp, sv, dp, dv, corr_j, corr_ok,
                                       max_dist, edge_sim, k, trials=trials,
                                       refine_iters=refine_iters,
                                       nn_mode=nn_mode)
        T, fit, rmse = _icp_core(sp, sv, dp, dv, dn, T0, icp_max_dist,
                                 icp_iters, nn_mode)
        return T, gfit, fit, rmse

    return jax.lax.map(one, (pair_ids, src_pts, src_valid, src_feat,
                             dst_pts, dst_valid, dst_feat, dst_normals))


def register_pairs(src_pts, src_valid, src_feat,
                   dst_pts, dst_valid, dst_feat, dst_normals,
                   max_dist: float, icp_max_dist: float,
                   trials: int = 4096, icp_iters: int = 30,
                   edge_sim: float = 0.9, seed: int = 0,
                   mutual: bool = True, refine_iters: int = 3,
                   feat_bf16: bool | None = None, pair_ids=None):
    """Register P independent (src, dst) cloud pairs — FPFH correspondence +
    RANSAC global init + point-to-plane ICP refine per pair — in ONE jitted
    launch (lax.map over pairs; every stage inside is fixed-shape device
    code, so P pairs cost one compile and zero host round-trips).

    This is the turntable merge chain reshaped for TPU: the reference runs
    23 sequential Open3D registrations (server/processing.py:549-593), but
    with the odometry formulation each pair (i-1 <- i) is independent, so
    the whole chain is a batch.

    All per-pair arrays must share one padded shape: src_pts [P, N, 3],
    src_valid [P, N], src_feat [P, N, 33], dst_* likewise, dst_normals
    [P, M, 3]. Returns (T [P, 4, 4], global_fitness [P], icp_fitness [P],
    icp_rmse [P]) as device arrays.

    ``pair_ids``: optional [P] i32 RANSAC-key ids (default ``arange(P)`` —
    the historical schedule). Each pair's result depends only on its own
    (padded clouds, id, knobs), never on its launch-mates, so callers that
    split one logical pair set across several launches (the streaming
    merge) pass each pair's GLOBAL id and get bit-identical transforms.
    """
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    p = src_pts.shape[0]
    ids = (jnp.arange(p, dtype=jnp.int32) if pair_ids is None
           else jnp.asarray(pair_ids, jnp.int32))
    args = (jnp.asarray(src_pts, jnp.float32), jnp.asarray(src_valid),
            jnp.asarray(src_feat, jnp.float32),
            jnp.asarray(dst_pts, jnp.float32), jnp.asarray(dst_valid),
            jnp.asarray(dst_feat, jnp.float32),
            jnp.asarray(dst_normals, jnp.float32),
            jnp.float32(max_dist), jnp.float32(icp_max_dist),
            jnp.float32(edge_sim), jax.random.PRNGKey(seed), ids)
    kw = dict(trials=trials, icp_iters=icp_iters, mutual=mutual,
              refine_iters=refine_iters,
              feat_bf16=_resolve_feat_bf16(feat_bf16))
    # same gate + graceful degrade as icp_point_to_plane: the Mosaic kernel
    # only up to the VMEM-safe base size, and any Mosaic compile failure
    # falls back to the dense-jnp correspondence path
    if pk.use_pallas() and dst_pts.shape[1] <= 131072:
        try:
            return _register_pairs_jit(*args, nn_mode="pallas", **kw)
        except Exception:
            pass
    return _register_pairs_jit(*args, nn_mode="brute", **kw)


def register_pairs_sharded(mesh, src_pts, src_valid, src_feat,
                           dst_pts, dst_valid, dst_feat, dst_normals,
                           max_dist: float, icp_max_dist: float,
                           trials: int = 4096, icp_iters: int = 30,
                           edge_sim: float = 0.9, seed: int = 0,
                           mutual: bool = True, refine_iters: int = 3,
                           feat_bf16: bool | None = None, pair_ids=None):
    """register_pairs distributed over a device mesh: the pair axis shards
    across every device (pairs are independent — zero collectives on the hot
    path), each device lax.map's its local chunk. A 24-view turntable merge
    on a v5e-8 runs 3 pairs per chip instead of 23 on one.

    ``mesh`` is a jax.sharding.Mesh; the pair axis spreads over ALL its
    axes (data-major). P is padded to a multiple of the device count with
    duplicate rows, which are dropped from the returned arrays.

    ``pair_ids`` shard alongside the pairs and feed each pair's RANSAC key
    directly (default ``arange(P)``) — the key schedule follows the pair,
    not the device, so a sharded launch returns the same transforms as
    ``register_pairs`` on one device given the same padded shapes.
    """
    from jax.sharding import PartitionSpec

    from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
        shard_map_unchecked,
    )

    p = src_pts.shape[0]
    n_dev = int(np.prod(list(mesh.shape.values())))
    pad = -p % n_dev
    axes = tuple(mesh.axis_names)

    def _pad(a):
        # device-aware: jnp.asarray is a no-op for device-resident stacks
        # (merge_360's mesh route builds them on device) — an np.asarray
        # here would bounce tens of MB through the host per merge
        a = jnp.asarray(a)
        if pad:
            a = jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)])
        return a

    arrays = [_pad(a) for a in (src_pts, src_valid, src_feat, dst_pts,
                                dst_valid, dst_feat, dst_normals)]
    ids = (jnp.arange(p, dtype=jnp.int32) if pair_ids is None
           else jnp.asarray(pair_ids, jnp.int32))
    ids = _pad(ids)
    key = jax.random.PRNGKey(seed)
    # every device shard sees the same base key; each pair folds in its own
    # global id inside the body (device-independent key schedule)
    keys = jnp.tile(key[None, :], (n_dev, 1))
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    nn_mode = ("pallas" if pk.use_pallas() and dst_pts.shape[1] <= 131072
               else "brute")
    kw = dict(trials=trials, icp_iters=icp_iters, mutual=mutual,
              refine_iters=refine_iters, nn_mode=nn_mode,
              feat_bf16=_resolve_feat_bf16(feat_bf16))

    spec = PartitionSpec(axes)          # pair axis over the whole mesh
    md = jnp.float32(max_dist)
    imd = jnp.float32(icp_max_dist)
    es = jnp.float32(edge_sim)

    def local(sp, sv, sf, dp, dv, df, dn, ids_l, k):
        return _register_pairs_jit(sp, sv, sf, dp, dv, df, dn,
                                   md, imd, es, k[0], ids_l, **kw)

    # replication/VMA checking OFF: _icp_core's lax.while_loop has no
    # replication rule in the shard_map checker (jax<=0.4.x raises
    # NotImplementedError at trace time), and there is nothing to check —
    # every in/out spec shards the pair axis, nothing is replicated
    fn = jax.jit(shard_map_unchecked(
        mesh=mesh,
        in_specs=(spec,) * 9,
        out_specs=(spec, spec, spec, spec),
    )(local))
    inputs = arrays
    try:
        T, gfit, ifit, irmse = fn(*inputs, ids, keys)
    except Exception:
        if kw["nn_mode"] == "brute":
            raise
        # Mosaic compile failure at this shape: degrade like register_pairs
        kw["nn_mode"] = "brute"
        T, gfit, ifit, irmse = fn(*inputs, ids, keys)
    return T[:p], gfit[:p], ifit[:p], irmse[:p]
