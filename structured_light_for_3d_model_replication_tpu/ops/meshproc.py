"""Mesh post-processing: smoothing, decimation, hole close, density trim.

Covers the reference's optional pymeshlab stage (server/processing.py:744-787:
Taubin/Laplacian smoothing, quadric-edge-collapse simplification, hole close)
and the Poisson density-quantile crop (:707-709, :845-853) with array-native
equivalents: uniform-Laplacian smoothing via segment ops over the edge list,
batched-greedy quadric edge collapse (plus the cheaper vertex-clustering
variant), boundary-loop hole filling, and mask-based face filtering with
vertex compaction.
"""
from __future__ import annotations

import numpy as np

__all__ = ["laplacian_smooth", "taubin_smooth", "vertex_cluster_decimate",
           "quadric_decimate", "boundary_loops", "fill_holes",
           "filter_faces_by_vertex_mask", "remove_unreferenced", "mesh_volume"]


def _vertex_neighbors_mean(vertices: np.ndarray, faces: np.ndarray):
    """Mean neighbor position per vertex via scatter-adds over directed edges."""
    e = np.concatenate([faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [2, 0]],
                        faces[:, [1, 0]], faces[:, [2, 1]], faces[:, [0, 2]]])
    acc = np.zeros_like(vertices)
    cnt = np.zeros(len(vertices), vertices.dtype)
    np.add.at(acc, e[:, 0], vertices[e[:, 1]])
    np.add.at(cnt, e[:, 0], 1)
    cnt = np.maximum(cnt, 1)
    return acc / cnt[:, None]


def laplacian_smooth(vertices, faces, iters: int = 5, lam: float = 0.5):
    """Uniform-weight Laplacian smoothing (pymeshlab 'laplacian' parity)."""
    v = np.asarray(vertices, np.float32).copy()
    for _ in range(iters):
        v = v + lam * (_vertex_neighbors_mean(v, faces) - v)
    return v


def taubin_smooth(vertices, faces, iters: int = 5, lam: float = 0.5,
                  mu: float = -0.53):
    """Taubin lambda/mu smoothing — volume-preserving (pymeshlab 'taubin')."""
    v = np.asarray(vertices, np.float32).copy()
    for _ in range(iters):
        v = v + lam * (_vertex_neighbors_mean(v, faces) - v)
        v = v + mu * (_vertex_neighbors_mean(v, faces) - v)
    return v


def vertex_cluster_decimate(vertices, faces, cell_size: float):
    """Decimate by clustering vertices on a grid of ``cell_size`` (the
    array-native stand-in for quadric edge collapse: same knob — target
    resolution — different mechanics)."""
    v = np.asarray(vertices, np.float64)
    origin = v.min(0)
    key = np.floor((v - origin) / cell_size).astype(np.int64)
    uniq, inv, cnt = np.unique(key, axis=0, return_inverse=True,
                               return_counts=True)
    newv = np.zeros((len(uniq), 3))
    np.add.at(newv, inv, v)
    newv /= cnt[:, None]
    nf = inv[np.asarray(faces, np.int64)]
    keep = (nf[:, 0] != nf[:, 1]) & (nf[:, 1] != nf[:, 2]) & (nf[:, 0] != nf[:, 2])
    return newv.astype(np.float32), nf[keep].astype(np.int32)


def filter_faces_by_vertex_mask(vertices, faces, keep_mask):
    """Drop faces touching any removed vertex; compact vertices.
    (The density-quantile trim applies this with keep = density >= q.)"""
    keep_mask = np.asarray(keep_mask, bool)
    fkeep = keep_mask[faces].all(axis=1)
    return remove_unreferenced(vertices, faces[fkeep])


def remove_unreferenced(vertices, faces):
    used = np.zeros(len(vertices), bool)
    used[faces.reshape(-1)] = True
    remap = np.cumsum(used) - 1
    return (np.asarray(vertices)[used],
            remap[np.asarray(faces, np.int64)].astype(np.int32))


def boundary_loops(faces, max_loops: int = 10000):
    """Closed loops of boundary edges (edges referenced by exactly one face).

    Returns a list of vertex-index arrays, each tracing one open hole in face
    winding order. Non-manifold junctions (a boundary vertex with more than
    one outgoing boundary edge) break the chain there; such fragments are
    dropped rather than guessed at.
    """
    f = np.asarray(faces, np.int64)
    if f.size == 0:
        return []
    # directed edges in winding order; a boundary edge is one whose reverse
    # has no partner
    e = np.concatenate([f[:, [0, 1]], f[:, [1, 2]], f[:, [2, 0]]])
    key = e[:, 0] * (f.max() + 1) + e[:, 1]
    rkey = e[:, 1] * (f.max() + 1) + e[:, 0]
    boundary = e[~np.isin(key, rkey)]
    if len(boundary) == 0:
        return []
    # hole loops run OPPOSITE to face winding; walk successor map b -> a
    succ: dict[int, int] = {}
    multi: set[int] = set()
    for a, b in boundary:
        if b in succ:
            multi.add(b)
        succ[int(b)] = int(a)
    loops = []
    visited: set[int] = set()
    for start in list(succ):
        if start in visited or start in multi:
            continue
        loop = [start]
        visited.add(start)
        cur = succ[start]
        ok = True
        while cur != start:
            if cur in visited or cur in multi or cur not in succ:
                ok = False  # broken / non-manifold chain
                break
            loop.append(cur)
            visited.add(cur)
            cur = succ[cur]
        if ok and len(loop) >= 3:
            loops.append(np.asarray(loop, np.int64))
        if len(loops) >= max_loops:
            break
    return loops


def fill_holes(vertices, faces, max_hole_edges: int = 200):
    """Close boundary loops with a centroid fan (pymeshlab meshing_close_holes
    parity, server/processing.py:769-771; ``max_hole_edges`` plays the role
    of its maxholesize knob). Returns (vertices', faces', n_filled)."""
    v = np.asarray(vertices, np.float32)
    f = np.asarray(faces, np.int32)
    loops = [lp for lp in boundary_loops(f) if len(lp) <= max_hole_edges]
    if not loops:
        return v, f, 0
    new_v = [v]
    new_f = [f]
    next_idx = len(v)
    for lp in loops:
        centroid = v[lp].mean(axis=0, keepdims=True)
        new_v.append(centroid.astype(np.float32))
        nxt = np.roll(lp, -1)
        # fan wound so the new faces match the surrounding surface orientation
        # (the loop runs opposite to face winding; fan centroid->nxt->cur
        # restores it)
        fan = np.stack([np.full(len(lp), next_idx, np.int64), nxt, lp], axis=1)
        new_f.append(fan.astype(np.int32))
        next_idx += 1
    return (np.concatenate(new_v), np.concatenate(new_f), len(loops))


def _face_quadrics(v, f):
    """Per-face plane quadric K = p p^T (p = [n, d], |n| = 1)."""
    a, b, c = v[f[:, 0]], v[f[:, 1]], v[f[:, 2]]
    n = np.cross(b - a, c - a)
    nrm = np.linalg.norm(n, axis=1, keepdims=True)
    n = n / np.maximum(nrm, 1e-20)
    d = -(n * a).sum(1)
    p = np.concatenate([n, d[:, None]], axis=1)  # [F, 4]
    return np.einsum("fi,fj->fij", p, p)


def quadric_decimate(vertices, faces, target_faces: int,
                     max_rounds: int = 40):
    """Garland-Heckbert quadric edge collapse, batched-greedy.

    Instead of a serial priority queue, every round scores ALL edges by the
    summed endpoint quadric (error of the best of {a, b, midpoint}), picks an
    independent set of cheap edges (no shared vertices — each vertex accepts
    only its minimum-rank incident edge, found with scatter-min), collapses
    them simultaneously, and repeats until the face budget is met. Shape
    fidelity matches serial QEM closely while every round is vectorized
    numpy (no per-edge Python loop).

    pymeshlab parity: meshing_decimation_quadric_edge_collapse
    (server/processing.py:773-787). Returns (vertices', faces').
    """
    v = np.asarray(vertices, np.float64).copy()
    f = np.asarray(faces, np.int64).copy()
    if target_faces <= 0 or len(f) <= target_faces:
        return v.astype(np.float32), f.astype(np.int32)

    for _ in range(max_rounds):
        if len(f) <= target_faces:
            break
        # vertex quadrics from current faces
        kf = _face_quadrics(v, f)
        q = np.zeros((len(v), 4, 4))
        for col in range(3):
            np.add.at(q, f[:, col], kf)
        # candidate edges (undirected, deduped)
        e = np.concatenate([f[:, [0, 1]], f[:, [1, 2]], f[:, [2, 0]]])
        e = np.unique(np.sort(e, axis=1), axis=0)
        qe = q[e[:, 0]] + q[e[:, 1]]                      # [E, 4, 4]
        cand = np.stack([v[e[:, 0]], v[e[:, 1]],
                         0.5 * (v[e[:, 0]] + v[e[:, 1]])], axis=1)  # [E, 3, 3]
        ch = np.concatenate([cand, np.ones((len(e), 3, 1))], axis=2)
        cost3 = np.einsum("eci,eij,ecj->ec", ch, qe, ch)
        pick = cost3.argmin(axis=1)
        cost = cost3[np.arange(len(e)), pick]
        target = cand[np.arange(len(e)), pick]

        # independent set: an edge collapses iff it is the cheapest (by rank)
        # edge at BOTH endpoints — vectorized via scatter-min of edge ranks
        rank = np.empty(len(e), np.int64)
        rank[np.argsort(cost)] = np.arange(len(e))
        vmin = np.full(len(v), len(e), np.int64)
        np.minimum.at(vmin, e[:, 0], rank)
        np.minimum.at(vmin, e[:, 1], rank)
        sel = (vmin[e[:, 0]] == rank) & (vmin[e[:, 1]] == rank)
        chosen = np.nonzero(sel)[0]
        if len(chosen) == 0:
            break
        # cap collapses so a single round can't undershoot the budget badly
        budget = max((len(f) - target_faces) // 2 + 1, 1)
        if len(chosen) > budget:
            chosen = chosen[np.argsort(cost[chosen])[:budget]]
        # collapse b -> a, a moves to the optimal position
        remap = np.arange(len(v))
        remap[e[chosen, 1]] = e[chosen, 0]
        v[e[chosen, 0]] = target[chosen]
        f = remap[f]
        keep = ((f[:, 0] != f[:, 1]) & (f[:, 1] != f[:, 2])
                & (f[:, 0] != f[:, 2]))
        f = f[keep]

    v32, f32 = remove_unreferenced(v.astype(np.float32), f.astype(np.int32))
    return v32, f32


def mesh_volume(vertices, faces) -> float:
    """Signed volume (positive when faces wind outward)."""
    v = np.asarray(vertices, np.float64)
    a, b, c = v[faces[:, 0]], v[faces[:, 1]], v[faces[:, 2]]
    return float(np.einsum("ij,ij->i", a, np.cross(b, c)).sum() / 6.0)
