"""Mesh post-processing: smoothing, decimation, density trim, cleanup.

Covers the reference's optional pymeshlab stage (server/processing.py:744-787:
Taubin/Laplacian smoothing, quadric-edge-collapse simplification, hole close)
and the Poisson density-quantile crop (:707-709, :845-853) with array-native
equivalents: uniform-Laplacian smoothing via segment ops over the edge list,
vertex-clustering decimation on a target-resolution grid, and mask-based face
filtering with vertex compaction.
"""
from __future__ import annotations

import numpy as np

__all__ = ["laplacian_smooth", "taubin_smooth", "vertex_cluster_decimate",
           "filter_faces_by_vertex_mask", "remove_unreferenced", "mesh_volume"]


def _vertex_neighbors_mean(vertices: np.ndarray, faces: np.ndarray):
    """Mean neighbor position per vertex via scatter-adds over directed edges."""
    e = np.concatenate([faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [2, 0]],
                        faces[:, [1, 0]], faces[:, [2, 1]], faces[:, [0, 2]]])
    acc = np.zeros_like(vertices)
    cnt = np.zeros(len(vertices), vertices.dtype)
    np.add.at(acc, e[:, 0], vertices[e[:, 1]])
    np.add.at(cnt, e[:, 0], 1)
    cnt = np.maximum(cnt, 1)
    return acc / cnt[:, None]


def laplacian_smooth(vertices, faces, iters: int = 5, lam: float = 0.5):
    """Uniform-weight Laplacian smoothing (pymeshlab 'laplacian' parity)."""
    v = np.asarray(vertices, np.float32).copy()
    for _ in range(iters):
        v = v + lam * (_vertex_neighbors_mean(v, faces) - v)
    return v


def taubin_smooth(vertices, faces, iters: int = 5, lam: float = 0.5,
                  mu: float = -0.53):
    """Taubin lambda/mu smoothing — volume-preserving (pymeshlab 'taubin')."""
    v = np.asarray(vertices, np.float32).copy()
    for _ in range(iters):
        v = v + lam * (_vertex_neighbors_mean(v, faces) - v)
        v = v + mu * (_vertex_neighbors_mean(v, faces) - v)
    return v


def vertex_cluster_decimate(vertices, faces, cell_size: float):
    """Decimate by clustering vertices on a grid of ``cell_size`` (the
    array-native stand-in for quadric edge collapse: same knob — target
    resolution — different mechanics)."""
    v = np.asarray(vertices, np.float64)
    origin = v.min(0)
    key = np.floor((v - origin) / cell_size).astype(np.int64)
    uniq, inv, cnt = np.unique(key, axis=0, return_inverse=True,
                               return_counts=True)
    newv = np.zeros((len(uniq), 3))
    np.add.at(newv, inv, v)
    newv /= cnt[:, None]
    nf = inv[np.asarray(faces, np.int64)]
    keep = (nf[:, 0] != nf[:, 1]) & (nf[:, 1] != nf[:, 2]) & (nf[:, 0] != nf[:, 2])
    return newv.astype(np.float32), nf[keep].astype(np.int32)


def filter_faces_by_vertex_mask(vertices, faces, keep_mask):
    """Drop faces touching any removed vertex; compact vertices.
    (The density-quantile trim applies this with keep = density >= q.)"""
    keep_mask = np.asarray(keep_mask, bool)
    fkeep = keep_mask[faces].all(axis=1)
    return remove_unreferenced(vertices, faces[fkeep])


def remove_unreferenced(vertices, faces):
    used = np.zeros(len(vertices), bool)
    used[faces.reshape(-1)] = True
    remap = np.cumsum(used) - 1
    return (np.asarray(vertices)[used],
            remap[np.asarray(faces, np.int64)].astype(np.int32))


def mesh_volume(vertices, faces) -> float:
    """Signed volume (positive when faces wind outward)."""
    v = np.asarray(vertices, np.float64)
    a, b, c = v[faces[:, 0]], v[faces[:, 1]], v[faces[:, 2]]
    return float(np.einsum("ij,ij->i", a, np.cross(b, c)).sum() / 6.0)
