"""Multi-device screened Poisson: the dense grid sharded into axis-0 slabs.

Raises the depth ceiling past the single-chip dense limit (ops/poisson.py
guards depth <= 9: a 1024^3 fp32 CG state does not fit one chip's HBM; the
reference's octree default is depth 10 with a <=16 guard,
server/processing.py:697-709). Across D devices each holds a [G/D, G, G]
slab, and the 7-point Laplacian / central-difference divergence exchange one
boundary plane per side per application via ``jax.lax.ppermute`` over ICI —
the classic distributed-stencil halo pattern. CG dot products are ``psum``
reductions. The splat is computed per-slab (every device masks the trilinear
corner contributions that land in its slab), so no scatter ever crosses
devices.

Numerics match ops/poisson.py up to fp32 reduction order; tests assert
dense-vs-sharded agreement on the 8-virtual-device CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
    shard_map_unchecked,
)

from structured_light_for_3d_model_replication_tpu.ops.poisson import (
    PoissonResult,
)

__all__ = ["poisson_solve_sharded"]

_AXIS = "slab"


def _slab_mesh(devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (_AXIS,))


def _halo_from_prev(plane, n_dev):
    """Each device receives ``plane`` from its predecessor (zeros on dev 0)."""
    return jax.lax.ppermute(plane, _AXIS,
                            [(i, i + 1) for i in range(n_dev - 1)])


def _halo_from_next(plane, n_dev):
    return jax.lax.ppermute(plane, _AXIS,
                            [(i + 1, i) for i in range(n_dev - 1)])


def _neighbors_axis0(u, n_dev):
    """(u[i-1], u[i+1]) along the sharded axis with edge replication at the
    global boundary — one halo plane exchanged per side."""
    zi = jax.lax.axis_index(_AXIS)
    prev_last = _halo_from_prev(u[-1:], n_dev)
    prev_last = jnp.where(zi == 0, u[:1], prev_last)
    next_first = _halo_from_next(u[:1], n_dev)
    next_first = jnp.where(zi == n_dev - 1, u[-1:], next_first)
    up = jnp.concatenate([prev_last, u[:-1]], axis=0)   # u[i-1]
    dn = jnp.concatenate([u[1:], next_first], axis=0)   # u[i+1]
    return up, dn


def _inplane_neighbors(u, axis):
    """(u[j-1], u[j+1]) along an unsharded axis with edge replication."""
    fwd = jnp.roll(u, -1, axis)
    bwd = jnp.roll(u, 1, axis)
    idx_last = [slice(None)] * 3
    idx_last[axis] = -1
    fwd = fwd.at[tuple(idx_last)].set(u[tuple(idx_last)])
    idx_first = [slice(None)] * 3
    idx_first[axis] = 0
    bwd = bwd.at[tuple(idx_first)].set(u[tuple(idx_first)])
    return bwd, fwd


def _laplacian_slab(u, n_dev):
    up, dn = _neighbors_axis0(u, n_dev)
    lap = -6.0 * u + up + dn
    for axis in (1, 2):
        bwd, fwd = _inplane_neighbors(u, axis)
        lap = lap + bwd + fwd
    return lap


def _splat_slab(coords, values, zi, slab, g):
    """Trilinear scatter of [N, C] values into this device's [slab, G, G, C]
    piece; corner contributions outside the slab are masked, so summing the
    slabs reproduces ops/poisson._trilinear_scatter exactly."""
    base = jnp.floor(coords).astype(jnp.int32)
    frac = coords - base
    out = jnp.zeros((slab, g, g, values.shape[-1]), jnp.float32)
    x0 = zi * slab
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                w = (jnp.abs(1 - dx - frac[:, 0])
                     * jnp.abs(1 - dy - frac[:, 1])
                     * jnp.abs(1 - dz - frac[:, 2]))
                gx = jnp.clip(base[:, 0] + dx, 0, g - 1)
                iy = jnp.clip(base[:, 1] + dy, 0, g - 1)
                iz = jnp.clip(base[:, 2] + dz, 0, g - 1)
                lx = gx - x0
                in_slab = (lx >= 0) & (lx < slab)
                w = jnp.where(in_slab, w, 0.0)
                lx = jnp.clip(lx, 0, slab - 1)
                out = out.at[lx, iy, iz].add(values * w[:, None])
    return out


def _divergence_slab(vfield, n_dev):
    """Central-difference divergence of a [slab, G, G, 3] field (cell units),
    edge-replicated at the global boundary like the dense solver."""
    div = jnp.zeros(vfield.shape[:3], jnp.float32)
    f0 = vfield[..., 0]
    up, dn = _neighbors_axis0(f0, n_dev)
    div = div + 0.5 * (dn - up)
    for axis in (1, 2):
        f = vfield[..., axis]
        bwd, fwd = _inplane_neighbors(f, axis)
        div = div + 0.5 * (fwd - bwd)
    return div


def _psum(x):
    return jax.lax.psum(x, _AXIS)


def poisson_solve_sharded(points, normals, valid=None, depth: int = 10,
                          devices=None, cg_iters: int = 350,
                          screen: float = 4.0,
                          margin: float = 0.08,
                          compile_only: bool = False) -> PoissonResult | None:
    """Screened grid Poisson across a device mesh. Same contract as
    ops/poisson.poisson_solve; chi/density come back sharded on axis 0
    (np.asarray gathers them for extraction).

    The reference's depth guard is <= 16 (processing.py:697-699); here depth
    is bounded by aggregate HBM: D devices fit depth d when each [2^d / D,
    2^d, 2^d] fp32 slab times ~6 CG arrays fits one chip (depth 10 on 8 x
    v5e comfortably).

    ``compile_only``: lower + compile the sharded program from
    ShapeDtypeStructs and return None without allocating grid buffers or
    running — how the multichip dryrun proves the beyond-single-chip depth
    (a 1024^3 CG sweep is minutes of wall on virtual CPU devices, but its
    COMPILATION — shardings, halo collectives, layouts — is checkable
    anywhere).
    """
    if depth > 16:
        raise ValueError(f"depth {depth} > 16 (the reference's own guard: "
                         "processing.py:697-699)")
    mesh = _slab_mesh(devices)
    n_dev = mesh.devices.size
    g = 1 << depth
    if g % n_dev:
        raise ValueError(f"grid {g} not divisible by {n_dev} devices")
    slab = g // n_dev

    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], bool)
    valid = jnp.asarray(valid)

    # grid frame (host, fp32 — mirrors ops/poisson._poisson_jit)
    pnp = np.asarray(points)
    vnp = np.asarray(valid)
    lo = np.min(np.where(vnp[:, None], pnp, np.inf), axis=0)
    hi = np.max(np.where(vnp[:, None], pnp, -np.inf), axis=0)
    extent = np.float32(np.max(hi - lo) * (1.0 + 2.0 * margin))
    cell = np.float32(extent / g)
    origin = (0.5 * (lo + hi) - 0.5 * extent).astype(np.float32)

    spec_grid = P(_AXIS, None, None)

    @shard_map_unchecked(
        mesh=mesh,
        in_specs=(P(), P(), P()),
        out_specs=(spec_grid, spec_grid),
    )
    def solve(pts, nrm, w):
        zi = jax.lax.axis_index(_AXIS)
        coords = (pts - origin) / cell - 0.5
        coords = jnp.where(w[:, None] > 0, coords, -10.0)
        splat = _splat_slab(coords, jnp.concatenate([nrm * w[:, None], w[:, None]],
                                                    axis=-1), zi, slab, g)
        vfield = splat[..., :3]
        density = splat[..., 3]
        div = _divergence_slab(vfield, n_dev)

        dmax = jax.lax.pmax(jnp.max(density), _AXIS)
        wgt = density / jnp.maximum(dmax, 1e-12)

        def a_mul(x):
            return -_laplacian_slab(x, n_dev) + screen * wgt * x

        b = -div

        def cg_step(state, _):
            x, r, p, rs = state
            ap = a_mul(p)
            alpha = rs / jnp.maximum(_psum((p * ap).sum()), 1e-20)
            x = x + alpha * p
            r = r - alpha * ap
            rs_new = _psum((r * r).sum())
            beta = rs_new / jnp.maximum(rs, 1e-20)
            p = r + beta * p
            return (x, r, p, rs_new), rs_new

        state0 = (jnp.zeros_like(b), b, b, _psum((b * b).sum()))
        (chi, _, _, _), _ = jax.lax.scan(cg_step, state0, None,
                                         length=cg_iters)
        return chi, density

    if compile_only:
        n = points.shape[0]
        s = jax.ShapeDtypeStruct
        jax.jit(solve).lower(s((n, 3), jnp.float32), s((n, 3), jnp.float32),
                             s((n,), jnp.float32)).compile()
        return None

    w = valid.astype(jnp.float32)
    chi, density = solve(points, normals, w)

    # iso on host: weighted mean of chi at the sample points (the gathered
    # chi is the extraction input anyway)
    chi_np = np.asarray(chi)
    coords = (pnp - origin) / cell - 0.5
    iso = _trilinear_sample_np(chi_np, np.where(vnp[:, None], coords, 0.0))
    wnp = vnp.astype(np.float32)
    iso = np.float32((iso * wnp).sum() / max(wnp.sum(), 1.0))

    return PoissonResult(chi, jnp.float32(iso), density,
                         jnp.asarray(origin + 0.5 * cell), jnp.float32(cell))


def _trilinear_sample_np(field, coords):
    g = field.shape
    base = np.floor(coords).astype(np.int64)
    frac = (coords - base).astype(np.float32)
    acc = np.zeros(coords.shape[0], np.float32)
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                w = (np.abs(1 - dx - frac[:, 0])
                     * np.abs(1 - dy - frac[:, 1])
                     * np.abs(1 - dz - frac[:, 2]))
                ix = np.clip(base[:, 0] + dx, 0, g[0] - 1)
                iy = np.clip(base[:, 1] + dy, 0, g[1] - 1)
                iz = np.clip(base[:, 2] + dz, 0, g[2] - 1)
                acc += w * field[ix, iy, iz]
    return acc
