"""Normal estimation and orientation — jax-native PCA over kNN neighborhoods.

Capability parity (behavior studied from server/processing.py):
  - estimate_normals (A19:653-655, A20:805-806): plane fit to the k-neighborhood
  - orientation modes: 'centroid' outward + global flip (A19:657-670),
    'radial' center-out (A20:811-817), 'tangent' graph-consistency propagation
    with radial fallback (A19:682-686, A20:819-830)

The covariance eigenvector is computed with a closed-form 3x3 symmetric
eigensolver (no LAPACK round-trip): smallest-eigenvalue direction via the
characteristic cubic + cross-product null-space extraction — branch-free and
vmappable, so a million normals are one fused kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib

__all__ = ["estimate_normals", "estimate_normals_np", "orient_normals",
           "smallest_eigvec_sym3"]


def smallest_eigvec_sym3(cov):
    """Unit eigenvector of the smallest eigenvalue of symmetric [.., 3, 3].

    Closed form: eigenvalues by the trigonometric solution of the
    characteristic cubic (Smith's method), eigenvector as the best cross
    product of two rows of (C - lambda I) — branch-free, fp32-safe.
    """
    a = cov
    tr = jnp.trace(a, axis1=-2, axis2=-1)
    q = tr / 3.0
    b = a - q[..., None, None] * jnp.eye(3, dtype=a.dtype)
    p2 = (b * b).sum((-2, -1)) / 6.0
    p = jnp.sqrt(jnp.maximum(p2, 1e-30))
    detb = jnp.linalg.det(b)
    r = detb / (2.0 * p**3)
    r = jnp.clip(r, -1.0, 1.0)
    phi = jnp.arccos(r) / 3.0
    # eigenvalues: q + 2p cos(phi + 2k pi/3); smallest at k giving cos closest to -1
    lam_min = q + 2.0 * p * jnp.cos(phi + 2.0 * jnp.pi / 3.0)

    m = a - lam_min[..., None, None] * jnp.eye(3, dtype=a.dtype)
    # null space of m: cross products of row pairs; pick the largest
    r0, r1, r2 = m[..., 0, :], m[..., 1, :], m[..., 2, :]
    c01 = jnp.cross(r0, r1)
    c02 = jnp.cross(r0, r2)
    c12 = jnp.cross(r1, r2)
    n01 = (c01 * c01).sum(-1)
    n02 = (c02 * c02).sum(-1)
    n12 = (c12 * c12).sum(-1)
    best = jnp.argmax(jnp.stack([n01, n02, n12], axis=-1), axis=-1)
    vec = jnp.take_along_axis(
        jnp.stack([c01, c02, c12], axis=-2), best[..., None, None], axis=-2
    )[..., 0, :]
    # degenerate neighborhoods (collinear): fall back to +z
    norm = jnp.sqrt((vec * vec).sum(-1, keepdims=True))
    fallback = jnp.zeros_like(vec).at[..., 2].set(1.0)
    ok = norm[..., 0] > 1e-12
    return jnp.where(ok[..., None], vec / jnp.where(ok[..., None], norm, 1.0),
                     fallback)


def estimate_normals(points, valid, k: int = 30, radius: float | None = None,
                     idx_d2=None):
    """Unit normals [N,3] from PCA of each point's k-neighborhood.

    ``radius``: hybrid query semantics (Open3D KDTreeSearchParamHybrid,
    processing.py:455-466 and :653-655 — radius=2*voxel, max_nn cap): of the
    k nearest neighbors, only those within ``radius`` enter the plane fit.
    None keeps the pure-kNN neighborhood.

    ``idx_d2``: optional precomputed ascending (idx [N,>=k], d2 [N,>=k])
    neighbor arrays — callers that also run FPFH share one kNN this way
    instead of paying the dominant neighbor search twice."""
    if idx_d2 is not None:
        idx, d2 = (a[:, :k] for a in idx_d2)
    else:
        idx, d2 = knnlib.knn(points, valid, k)
    neigh = points[idx]  # [N, k, 3]
    ok = valid[idx]      # [N, k] — padded/invalid neighbors excluded
    if radius is not None:
        ok_r = ok & (d2 <= jnp.float32(radius) ** 2)
        # a plane fit needs >= 3 points: where the radius leaves fewer (cloud
        # scale coarser than the radius), fall back to the pure-kNN
        # neighborhood for that point instead of degenerating to +z
        enough = ok_r.sum(axis=1, keepdims=True) >= 3
        ok = jnp.where(enough, ok_r, ok)
    w = ok.astype(jnp.float32)[..., None]
    cnt = jnp.maximum(w.sum(1), 1.0)
    mean = (neigh * w).sum(1) / cnt
    d = (neigh - mean[:, None, :]) * w
    # HIGHEST: the TPU default matmul precision is bf16-class, which is too
    # coarse for covariance accumulation (normals feed point-to-plane ICP)
    cov = jnp.einsum("nki,nkj->nij", d, d,
                     precision=jax.lax.Precision.HIGHEST) / cnt[..., None]
    return smallest_eigvec_sym3(cov)


def estimate_normals_np(points, valid, k: int = 30,
                        radius: float | None = None):
    """Reference: numpy eigh over cKDTree neighborhoods (hybrid semantics
    when ``radius`` is given, as in estimate_normals)."""
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    idx, d2 = knnlib.knn_np(points, valid, k)
    normals = np.zeros((points.shape[0], 3), np.float32)
    for i in range(points.shape[0]):
        if not valid[i]:
            normals[i] = (0, 0, 1)
            continue
        keep = valid[idx[i]]
        if radius is not None:
            keep = keep & (d2[i] <= radius * radius)
        nb = points[idx[i]][keep]
        if nb.shape[0] < 3:
            normals[i] = (0, 0, 1)
            continue
        c = np.cov(nb.T)
        wv, vv = np.linalg.eigh(c)
        normals[i] = vv[:, 0]
    return normals


@functools.partial(jax.jit, static_argnames=("mode", "flip"))
def orient_normals(points, normals, valid, mode: str = "radial",
                   center=None, flip: bool = False):
    """Orient normals consistently.

    - 'radial'/'centroid': point away from the cloud centroid (A20:811-817 /
      A19:657-663); ``flip=True`` reproduces A19's final *-1 inversion
      (:666-670, inward orientation for Poisson).
    """
    if center is None:
        w = valid.astype(jnp.float32)[:, None]
        center = (points * w).sum(0) / jnp.maximum(w.sum(), 1.0)
    out = points - center[None, :]
    sign = jnp.sign((out * normals).sum(-1, keepdims=True))
    sign = jnp.where(sign == 0, 1.0, sign)
    oriented = normals * sign
    if flip:
        oriented = -oriented
    if mode not in ("radial", "centroid"):
        raise ValueError(f"unknown orientation mode: {mode}")
    return oriented
