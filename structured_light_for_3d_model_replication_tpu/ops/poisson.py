"""Screened Poisson surface reconstruction on a regular grid — jax-native.

Replaces Open3D's octree Poisson solver (the engine behind
server/processing.py:697-709 reconstruct_stl "watertight" mode and
:839-843 mesh_360). The adaptive octree is pointer-heavy and hostile to XLA;
on a TPU a dense power-of-two grid is faster up to depth ~9 (512^3 would
exceed HBM; 256^3 solves in well under a second of stencil work):

  1. splat oriented normals onto the grid (trilinear scatter) -> vector field V
  2. divergence of V by central differences -> b
  3. conjugate-gradient solve of (L - screen*W) chi = b with a 7-point
     Laplacian stencil (screening follows the splat weight W, which plays the
     role of Kazhdan's point-interpolation term)
  4. iso level = weight-averaged chi at the sample points, like Open3D's
     density-weighted iso selection
  5. per-cell splat weight doubles as the "density" used for the low-density
     crop (processing.py:707-709's quantile trim)

Everything is fixed-shape: scatter-adds, stencil shifts, and a lax.scan CG.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PoissonResult", "poisson_solve"]


class PoissonResult(NamedTuple):
    chi: jax.Array       # [G,G,G] implicit function (inside < iso < outside)
    iso: jax.Array       # scalar iso level at the surface
    density: jax.Array   # [G,G,G] splat weight (sample support per cell)
    origin: jax.Array    # [3] world position of voxel (0,0,0) center
    cell: jax.Array      # scalar voxel size (world units)


def _trilinear_scatter(grid_shape, coords, values):
    """Scatter-add values [N, C] at fractional grid coords [N, 3].
    Returns [G,G,G,C]."""
    g = grid_shape
    base = jnp.floor(coords).astype(jnp.int32)
    frac = coords - base
    out = jnp.zeros(g + (values.shape[-1],), jnp.float32)
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                w = (jnp.abs(1 - dx - frac[:, 0])
                     * jnp.abs(1 - dy - frac[:, 1])
                     * jnp.abs(1 - dz - frac[:, 2]))
                ix = jnp.clip(base[:, 0] + dx, 0, g[0] - 1)
                iy = jnp.clip(base[:, 1] + dy, 0, g[1] - 1)
                iz = jnp.clip(base[:, 2] + dz, 0, g[2] - 1)
                out = out.at[ix, iy, iz].add(values * w[:, None])
    return out


def trilinear_sample(field, coords):
    """Sample [G,G,G] field at fractional coords [N,3]."""
    g = field.shape
    base = jnp.floor(coords).astype(jnp.int32)
    frac = coords - base
    acc = jnp.zeros(coords.shape[0], jnp.float32)
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                w = (jnp.abs(1 - dx - frac[:, 0])
                     * jnp.abs(1 - dy - frac[:, 1])
                     * jnp.abs(1 - dz - frac[:, 2]))
                ix = jnp.clip(base[:, 0] + dx, 0, g[0] - 1)
                iy = jnp.clip(base[:, 1] + dy, 0, g[1] - 1)
                iz = jnp.clip(base[:, 2] + dz, 0, g[2] - 1)
                acc = acc + w * field[ix, iy, iz]
    return acc


def _laplacian(u):
    """7-point stencil with Neumann (edge-replicate) boundaries."""
    def sh(a, axis, off):
        return jnp.roll(a, off, axis)

    lap = -6.0 * u
    for axis in range(3):
        for off in (1, -1):
            nb = jnp.roll(u, off, axis)
            # replicate boundary: rolled-in wrap values replaced by edge value
            idx = [slice(None)] * 3
            idx[axis] = 0 if off == 1 else -1
            nb = nb.at[tuple(idx)].set(u[tuple(idx)])
            lap = lap + nb
    return lap


@functools.partial(jax.jit, static_argnames=("depth", "cg_iters"))
def _poisson_jit(points, normals, valid, depth: int, cg_iters: int,
                 screen, margin):
    g = 1 << depth
    w = valid.astype(jnp.float32)[:, None]
    lo = jnp.min(jnp.where(valid[:, None], points, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(valid[:, None], points, -jnp.inf), axis=0)
    extent = jnp.max(hi - lo) * (1.0 + 2.0 * margin)
    cell = extent / g
    origin = 0.5 * (lo + hi) - 0.5 * extent
    coords = (points - origin) / cell - 0.5

    splat = _trilinear_scatter((g, g, g),
                               jnp.where(valid[:, None], coords, -10.0),
                               jnp.concatenate([normals * w, w], axis=-1))
    vfield = splat[..., :3]
    density = splat[..., 3]

    # divergence by central differences (cell units)
    div = jnp.zeros((g, g, g), jnp.float32)
    for axis in range(3):
        f = vfield[..., axis]
        fwd = jnp.roll(f, -1, axis)
        bwd = jnp.roll(f, 1, axis)
        idx0 = [slice(None)] * 3
        idx0[axis] = -1
        fwd = fwd.at[tuple(idx0)].set(f[tuple(idx0)])
        idx1 = [slice(None)] * 3
        idx1[axis] = 0
        bwd = bwd.at[tuple(idx1)].set(f[tuple(idx1)])
        div = div + 0.5 * (fwd - bwd)

    # CG on A = L - screen * W (negative definite; solve -A x = -b style via CG
    # on symmetric positive definite -(L) + screen*W)
    wgt = density / jnp.maximum(density.max(), 1e-12)

    def a_mul(x):
        return -_laplacian(x) + screen * wgt * x

    b = -div

    def cg_step(state, _):
        x, r, p, rs = state
        ap = a_mul(p)
        alpha = rs / jnp.maximum((p * ap).sum(), 1e-20)
        x = x + alpha * p
        r = r - alpha * ap
        rs_new = (r * r).sum()
        beta = rs_new / jnp.maximum(rs, 1e-20)
        p = r + beta * p
        return (x, r, p, rs_new), rs_new

    x0 = jnp.zeros_like(b)
    r0 = b
    state0 = (x0, r0, r0, (r0 * r0).sum())
    (chi, _, _, _), _ = jax.lax.scan(cg_step, state0, None, length=cg_iters)

    # iso level: weighted mean of chi at the sample positions
    chi_at = trilinear_sample(chi, coords)
    iso = (chi_at * w[:, 0]).sum() / jnp.maximum(w.sum(), 1.0)
    return PoissonResult(chi, iso, density, origin + 0.5 * cell, cell)


def poisson_solve(points, normals, valid=None, depth: int = 8,
                  cg_iters: int = 350, screen: float = 4.0,
                  margin: float = 0.08) -> PoissonResult:
    """Screened grid Poisson. Normals must point OUTWARD (chi < iso inside).

    depth: grid resolution 2^depth per axis (the reference guards depth <= 16
    for its octree, processing.py:697-699; dense grids cap at 9 for HBM).
    """
    if depth > 9:
        raise ValueError(
            f"depth {depth} > 9: a dense {1 << depth}^3 fp32 grid does not "
            "fit one chip's HBM; use ops/poisson_sharded.poisson_solve_"
            "sharded (slab-decomposed across the device mesh) for depth 10+")
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], bool)
    return _poisson_jit(points, normals, jnp.asarray(valid), depth, cg_iters,
                        jnp.float32(screen), jnp.float32(margin))
