"""Point-cloud cleaning ops — the Open3D-filter replacements, TPU-native.

Capability parity (behavior studied from server/processing.py):
  - remove_background (A12, :337-364): RANSAC largest-plane segmentation, keep
    the *inverse* of the plane inliers
  - remove_statistical_outlier (A13, :367-388): mean distance to k neighbors,
    keep points within mu + std_ratio * sigma
  - largest_cluster (A14, :391-427): density clustering (eps, min_points),
    keep the most-populated cluster
  - remove_radius_outlier (A15, :430-448): keep points with >= nb_points
    neighbors within radius
  - voxel_downsample (used throughout A16-A18): average points/colors per voxel

TPU-first design notes
----------------------
Sequential RANSAC becomes *batched hypothesis scoring*: all T candidate planes
are sampled and scored at once ([T, N] distance evaluation — dense, regular,
embarrassingly parallel). DBSCAN's region-growing becomes iterative min-label
propagation over the kNN graph (a fixed-k approximation of the eps-graph) run
under lax.while_loop until the labels stop changing. Voxel averaging is
sort + segment-sum over quantized keys. Everything keeps fixed shapes with
validity masks; the NumPy twins (same function name + _np) give exact
reference semantics via scipy/cKDTree.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib

__all__ = [
    "statistical_outlier_mask", "statistical_outlier_mask_np",
    "radius_outlier_mask", "radius_outlier_mask_np",
    "segment_plane", "segment_plane_np",
    "largest_cluster_mask", "largest_cluster_mask_np",
    "voxel_downsample", "voxel_downsample_np",
    "clean_chain", "clean_chain_np", "chain_params", "CLEAN_STEPS",
]


# ---------------------------------------------------------------------------
# Statistical outlier removal (A13)
# ---------------------------------------------------------------------------

def _stat_outlier_from_knn(mean_d, valid, std_ratio, xp):
    # A non-finite mean distance means the k-th neighbor fell outside the
    # grid search range — farther than any in-range point, an outlier by
    # construction. It must also stay OUT of mu/var: one inf would make the
    # threshold NaN and wipe the whole cloud (observed on 24-view merges).
    ok = valid & xp.isfinite(mean_d)
    n_valid = xp.maximum(ok.sum(), 1)
    m = xp.where(ok, mean_d, 0.0)
    mu = m.sum() / n_valid
    var = (xp.where(ok, (mean_d - mu) ** 2, 0.0)).sum() / n_valid
    thresh = mu + std_ratio * xp.sqrt(var)
    return ok & (mean_d <= thresh)


def statistical_outlier_mask(points, valid, nb_neighbors: int = 20,
                             std_ratio: float = 2.0,
                             voxelized_cell: float | None = None,
                             approximate: bool = False):
    """Keep-mask for statistical outlier removal (Open3D semantics,
    processing.py:376-379). points [N,3] padded, valid [N].

    Exact at every size BY DEFAULT — Open3D's KDTree statistics are exact,
    so the reference-parity contract is that the TPU and NumPy backends
    remove the identical outlier set. Large accelerator clouds route
    through the sorted-axis slab-window engine (certified rows exact, the
    rest get a chunked dense pass); ``approximate=True`` opts a large-N
    accelerator call into the approx_min_k selection instead (recall 0.99
    per row, one-sided error — mask agreement vs exact measured at 99.7%
    on the bench's 171k merged cloud).

    ``voxelized_cell``: pass the voxel size when ``points`` just came out of
    voxel_downsample(cell) — it sets the slab engine's certification
    radius (4*cell covers the 20th neighbor of a voxelized cloud), and
    rows it cannot certify get an exact dense pass. Results match the
    generic path exactly (same Open3D statistics). Without the hint,
    large accelerator clouds estimate an equivalent cell from the median
    nearest-neighbor spacing. Ignored on host backends — concrete host
    calls above 32768 points delegate to the cKDTree twin instead (same
    statistics, ~13x faster than the host grid kNN)."""
    concrete = not (isinstance(points, jax.core.Tracer)
                    or isinstance(valid, jax.core.Tracer))
    accel = concrete and jax.default_backend() != "cpu"
    n = points.shape[0]
    if n == 0:  # empty clouds flow through the clean chain gracefully
        return jnp.zeros(0, bool)
    if concrete and not accel and n > 32768:
        # host backend at production scale: the cKDTree twin computes the
        # identical Open3D statistics ~13x faster than the host grid kNN
        # (22.3 s -> 1.7 s at the bench's 170k merged cloud, r5) — on the
        # backend users hit when the accelerator is wedged, the np twin
        # IS the fast path. Small clouds stay on the jax arm (no win to
        # harvest there, and the CPU parity tests keep their teeth).
        return jnp.asarray(statistical_outlier_mask_np(
            np.asarray(points), np.asarray(valid), nb_neighbors, std_ratio))
    if accel and not (approximate and voxelized_cell is None):
        # accelerators only: the host fast path is the cKDTree twin above
        cell = voxelized_cell
        if cell is None and n > knnlib._BRUTE_MAX:
            # exact accelerator default for unhinted large clouds: a
            # certification radius of 4 * (0.75 * median NN spacing) =
            # 3x spacing covers the k-th neighbor for k<=30 on both
            # surface (r20 ~ 2.5x) and volumetric (r20 ~ 1.7x) clouds
            cell = 0.75 * _estimate_spacing(points, valid)
        if cell is not None:
            # the slab-window engine has no grid-resolution or occupancy
            # limits (the old ring probe's 1023-cells-per-axis pack gate
            # and its exact-brute escape are gone with it)
            return _stat_outlier_voxelized(points, valid, nb_neighbors,
                                           std_ratio, cell)
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    if n <= 32768 and pk.knn_mean_ok():
        # bucket-resident clouds where Mosaic compiles (this branch is
        # reached TRACED inside the fused clean chain, so it must not
        # consult `concrete`/`accel`): the dense bisection kernel computes
        # the identical k-NN mean wholly in VMEM — rows with fewer than k
        # valid neighbors come back +inf, exactly like the brute knn's
        # inf-padded d2, so the statistics below are unchanged
        mean_d, _ = pk.knn_mean(points, valid, int(nb_neighbors))
        return _stat_outlier_from_knn(mean_d, valid, jnp.float32(std_ratio),
                                      jnp)
    _, d2 = knnlib.knn(points, valid, nb_neighbors)
    mean_d = jnp.sqrt(jnp.maximum(d2, 0.0)).mean(axis=1)
    return _stat_outlier_from_knn(mean_d, valid, jnp.float32(std_ratio), jnp)


def _estimate_spacing(points, valid) -> float:
    """Median nearest-neighbor distance from a subsample: 2048 probe rows
    against a <=32768-point base, one tiny [2048, 32768] dense launch. A
    missed true NN (base is a stride of the cloud) only OVERestimates a
    row's spacing — and the slab engine stays exact at any cell choice, the
    estimate only tunes how much work lands on its dense fallback."""
    idx = np.flatnonzero(np.asarray(valid))
    if len(idx) < 2:
        return 1.0
    q = idx[:: max(1, len(idx) // 2048)][:2048]
    b = idx[:: max(1, len(idx) // 32768)][:32768]
    d2 = np.asarray(_spacing_d2_jit(jnp.asarray(points)[q],
                                    jnp.asarray(points)[b],
                                    jnp.asarray(q), jnp.asarray(b)))
    med = float(np.median(np.sqrt(np.maximum(d2, 0.0))))
    return max(med, 1e-6)


@jax.jit
def _spacing_d2_jit(q, b, qi, bi):
    d2 = ((q * q).sum(-1)[:, None] + (b * b).sum(-1)[None, :]
          - 2.0 * jnp.matmul(q, b.T, precision=jax.lax.Precision.HIGHEST))
    # self-exclusion by global index (the query stride is frequently a
    # multiple of the base stride, so most probe rows ARE in the base) —
    # an epsilon test on the expansion d2 would drown in its ~0.04 mm^2
    # cancellation noise and drag the median toward zero; the reported
    # minimum is recomputed exactly for the same reason
    d2 = jnp.where(qi[:, None] == bi[None, :], jnp.inf, d2)
    j = jnp.argmin(d2, axis=1).astype(jnp.int32)
    return knnlib.exact_d2(q, b, j)


def _stat_outlier_voxelized(points, valid, nb_neighbors, std_ratio, cell):
    """Slab-window + exact-fallback outlier mask for quasi-uniform clouds
    (the accelerator arm of statistical_outlier_mask; backend-agnostic in
    itself, which is what the CPU parity test exercises).

    SLSCAN_TRACE_OUTLIER=1 prints sub-stage wall times (engine wait,
    host complement, mask) for tunnel-overhead attribution."""
    import time as _time

    trace = os.environ.get("SLSCAN_TRACE_OUTLIER") == "1"
    t0 = _time.perf_counter()
    md_dev = _voxelized_knn_mean_dist(points, valid, jnp.float32(cell),
                                      nb_neighbors)
    # overlap the host complement's cKDTree BUILD with the device slab pass
    # (async dispatch above): the build is pure host work, the engine pure
    # device work, and the complement below almost always fires (cloud
    # boundaries). Host backends skip the prebuild — there the "device"
    # work occupies the same core, so nothing overlaps
    pts_np = np.asarray(points, np.float32)
    val_np = np.asarray(valid)
    if trace:
        print(f"[outlier-trace] dispatch+pts_D2H {_time.perf_counter()-t0:.3f}s",
              flush=True)
    tree_vi = (knnlib.kdtree_build(pts_np, val_np)
               if jax.default_backend() != "cpu" else None)
    if trace:
        print(f"[outlier-trace] +tree_build {_time.perf_counter()-t0:.3f}s",
              flush=True)
    # only the FINITENESS of each row crosses to the host (bool, 1/4 the
    # bytes of the mean vector) — the means themselves stay on device and
    # the complement patches in by scatter, avoiding the md D2H + H2D
    # round trip the first r5 engine paid
    bad = np.asarray(_uncertified_rows_jit(md_dev, valid))
    if trace:
        print(f"[outlier-trace] +engine_wait {_time.perf_counter()-t0:.3f}s",
              flush=True)
    # rows the slab window could not certify (k-th neighbor beyond 4*cell:
    # cloud-boundary points and true outliers) get an exact dense pass —
    # Open3D's statistics include the huge mean distances of far outliers,
    # which inflate sigma, so censoring them as inf would systematically
    # tighten the threshold
    if bad.any():
        # exact complement on the HOST: uncertified rows (cloud boundary +
        # true outliers, typically a few % of the cloud) go through the
        # twin's own cKDTree semantics (knnlib.kdtree_distances_rows) —
        # identical statistics by construction, including inf means for
        # degenerate clouds with < k other points; an N log N build +
        # m log N query beats the old chunked [2048, N] dense device
        # passes, whose per-row lax.top_k over the full cloud lowers to
        # sorts (~1 s of the r5 on-chip outlier stage at 324k points)
        bad_idx = np.flatnonzero(bad)
        dsel = knnlib.kdtree_distances_rows(pts_np, val_np, bad_idx,
                                            nb_neighbors, tree_vi=tree_vi)
        vals = dsel.mean(axis=1).astype(np.float32)
        # pad to a bucket so the scatter executable caches across clouds
        # (duplicate writes of the same value are harmless)
        m = len(bad_idx)
        pad = -(-max(m, 1) // 2048) * 2048 - m
        if pad:
            bad_idx = np.concatenate([bad_idx, np.full(pad, bad_idx[0])])
            vals = np.concatenate([vals, np.full(pad, vals[0], np.float32)])
        md_dev = _patch_rows_jit(md_dev, jnp.asarray(bad_idx),
                                 jnp.asarray(vals))
    if trace:
        print(f"[outlier-trace] +complement({int(bad.sum())} rows) "
              f"{_time.perf_counter()-t0:.3f}s", flush=True)
    # returned DEVICE-backed (on accelerators): the fused merge boundary
    # consumes the mask on device — materializing np here would add a
    # mask D2H + re-upload round trip
    out = _stat_outlier_from_knn(md_dev, valid, jnp.float32(std_ratio), jnp)
    if trace:
        out = jax.block_until_ready(out)
        print(f"[outlier-trace] +mask {_time.perf_counter()-t0:.3f}s",
              flush=True)
    return out


@jax.jit
def _uncertified_rows_jit(md, valid):
    return valid & ~jnp.isfinite(md)


@jax.jit
def _patch_rows_jit(md, idx, vals):
    return md.at[idx].set(vals)


_SLAB_FAR = 3e9


def _voxelized_knn_mean_dist(points, valid, cell, k: int,
                             tile: int | None = None,
                             window: int | None = None,
                             selector: str = "auto"):
    """Mean distance to the k nearest neighbors of a quasi-uniform (e.g.
    voxel-downsampled) cloud, certified-exact, via sorted-axis slab
    windows: sort along the cloud's widest axis, give each ``tile`` of
    consecutive sorted queries ONE contiguous ``window`` of sorted
    candidates, and run a dense MXU distance block + small top_k per
    tile. A row is certified (finite) only when its k-th candidate
    distance is <= r = 4*cell (the same coverage radius the old 4-ring
    probe used: r20 ~ 2.5x spacing on surface clouds, ~1.7x volumetric)
    AND its window actually spans [x_q - r, x_q + r]; uncertified rows
    return inf for the caller's exact dense fallback.

    Defaults (1024, 8192) are the r5 on-chip sweep's net optimum at
    bench scale (engine 0.584 s / 87% certified vs 0.707 s / 94.7% at
    (2048, 16384); the extra ~13k uncertified rows cost ~0.06 s on the
    overlapped-cKDTree host complement, netting ~0.06 s). The result is
    identical for ANY (tile, window): certification routes exactly the
    rows a narrower window cannot prove to the exact host pass.

    Replaces the 729-offset searchsorted ring probe, whose serial
    binary-search gather chains cost 26.3 s of a 27.8 s TPU merge
    (BENCH_NOTES round-5 first on-chip line) — one dynamic_slice per
    tile keeps this path matmul-shaped instead. Unlike the ring probe
    it has no cell-occupancy or 1023-cells-per-axis limits."""
    pts = jnp.asarray(points, jnp.float32)
    val = jnp.asarray(valid, bool)
    # widest-axis pick via the on-device extent reduction (transfer 24
    # bytes, not the cloud); any axis is CORRECT — certification covers a
    # bad pick — the widest just minimizes dense-fallback work
    lo, hi = _masked_extent_jit(pts, val)
    ax = int(np.argmax(np.nan_to_num(np.asarray(hi) - np.asarray(lo))))
    perm = (ax, (ax + 1) % 3, (ax + 2) % 3)
    if selector == "auto":
        # where Mosaic compiles, the bisection kernel IS the engine: the
        # r5 on-chip sweep measured 0.360-0.397 s vs lax.top_k's 0.684 s
        # at the same 94.7% certification on the 175k bench cloud — and
        # its selection is EXACT (in-VMEM difference distances; the jnp
        # engine selects on the MXU expansion, whose f32 cancellation can
        # swap near-tied neighbors). Hosts, non-Mosaic accelerators, and
        # callers who tuned explicit (tile, window) — those values are
        # topk-engine geometry; e.g. tile 2048 overflows the kernel's
        # VMEM budget — keep the top_k engine.
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        if (pk.knn_mean_ok() and pts.shape[0] <= 32768 and tile is None
                and window is None):
            # small enough that ALL candidates fit one VMEM pass: the
            # dense bisection kernel needs no sort, no window, and no
            # certification radius — every row with >= k valid neighbors
            # comes back exact and finite, so only degenerate rows reach
            # the caller's host complement
            selector = "dense"
        elif pk.slab_bisect_ok() and tile is None and window is None:
            selector, tile, window = "bisect", 64, 8192
        else:
            selector = "topk"
    if selector == "dense":
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        md, _ = pk.knn_mean(pts, val, int(k))
        return md
    if tile is None:
        tile = 64 if selector == "bisect" else 1024
    if window is None:
        window = 8192
    if selector == "bisect":
        # quantize r to a coarse log grid (~9% steps): its bit pattern is
        # baked into the kernel as a static, and the UNHINTED path derives
        # cell from per-cloud spacing — unquantized, every distinct cloud
        # would retrace + re-run Mosaic. Any r is CORRECT (certification
        # covers the choice); quantization only nudges how much work the
        # host complement sees.
        r = 4.0 * float(cell)
        r_q = float(np.float32(2.0 ** (round(np.log2(max(r, 1e-9)) * 8)
                                       / 8.0)))
        return _slab_bisect_engine_jit(pts[:, jnp.asarray(perm)], val,
                                       r_q, k, tile, window)
    return _slab_knn_mean_dist_jit(pts[:, jnp.asarray(perm)], val,
                                   jnp.float32(4.0 * float(cell)), k,
                                   tile, window, selector)


@functools.partial(jax.jit, static_argnames=("r", "k", "tile", "wblk"))
def _slab_bisect_engine_jit(points, valid, r: float, k: int, tile: int,
                            wblk: int):
    """Slab engine on the Pallas bisection kernel (pallas_kernels.
    slab_mean_knn): same sort/certify/scatter frame as the jnp engine,
    but the per-tile distance block stays in VMEM and the k-th order
    statistic comes from exact f32-bit bisection instead of a top_k
    sort. ``r`` is static (its bit pattern is baked into the kernel)."""
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    n = points.shape[0]
    L = max(-(-n // wblk) * wblk, 2 * wblk)
    x = jnp.where(valid, points[:, 0], jnp.inf)
    order = jnp.argsort(x)
    pts_s = jnp.where(valid[order][:, None], points[order],
                      jnp.float32(_SLAB_FAR))
    if L > n:
        pts_s = jnp.concatenate(
            [pts_s, jnp.full((L - n, 3), _SLAB_FAR, jnp.float32)])
    md, cnt, win_end = pk.slab_mean_knn(pts_s, r, k, tile=tile, wblk=wblk)
    x_s = pts_s[:, 0]
    # left coverage holds by construction (window start block-aligns DOWN
    # from the searchsorted slab start); only the right edge can truncate
    right_ok = ((win_end >= L)
                | (x_s[jnp.minimum(win_end, L) - 1] >= x_s + r))
    cert = (cnt >= k) & right_ok & (x_s < _SLAB_FAR)
    md = jnp.where(cert, md, jnp.inf)
    return jnp.full(n, jnp.inf, jnp.float32).at[order].set(md[:n])


@functools.partial(jax.jit,
                   static_argnames=("k", "tile", "window", "selector"))
def _slab_knn_mean_dist_jit(points, valid, r, k: int, tile: int,
                            window: int, selector: str = "topk"):
    n = points.shape[0]
    L = max(-(-n // tile) * tile, window)
    x = jnp.where(valid, points[:, 0], jnp.inf)
    order = jnp.argsort(x)
    pts_s = jnp.where(valid[order][:, None], points[order],
                      jnp.float32(_SLAB_FAR))
    if L > n:
        pts_s = jnp.concatenate(
            [pts_s, jnp.full((L - n, 3), _SLAB_FAR, jnp.float32)])
    x_s = pts_s[:, 0]           # ascending: real xs, then the _SLAB_FAR block
    n_tiles = L // tile
    first_x = x_s[jnp.arange(n_tiles, dtype=jnp.int32) * tile]
    starts = jnp.clip(jnp.searchsorted(x_s, first_x - r), 0, L - window)

    def per_tile(args):
        t, start = args
        q = jax.lax.dynamic_slice(pts_s, (t * tile, 0), (tile, 3))
        cand = jax.lax.dynamic_slice(pts_s, (start, 0), (window, 3))
        # selection rides the MXU expansion (its f32 cancellation only
        # risks picking among near-ties); self-exclusion is by global
        # sorted INDEX, not a distance threshold the noise could defeat
        q2 = (q * q).sum(-1)[:, None]
        b2 = (cand * cand).sum(-1)[None, :]
        cross = jax.lax.dot_general(q, cand, (((1,), (1,)), ((), ())),
                                    precision=jax.lax.Precision.HIGHEST)
        d2 = q2 + b2 - 2.0 * cross
        qg = t * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, window), 0)
        cg = start + jax.lax.broadcasted_iota(jnp.int32, (tile, window), 1)
        d2 = jnp.where(qg == cg, jnp.inf, d2)
        if selector == "approx1":
            # measured SLOWER on-chip (r5 tune_outlier sweep: ~4x vs
            # lax.top_k, and not bit-identical at recall_target=1.0 on
            # TPU) — kept only as an A/B arm, never the default
            _, jidx = jax.lax.approx_min_k(d2, k, recall_target=1.0)
        elif selector == "nosel":
            # DIAGNOSTIC ONLY (tuner arm): skip selection entirely — the
            # "result" is the first k columns, WRONG by construction —
            # to isolate the selector's share of the engine's cost
            jidx = jnp.broadcast_to(
                jnp.arange(k, dtype=jnp.int32)[None, :], (tile, k))
        elif selector == "iter":
            # exact k-pass min extraction: k sequential argmin+mask
            # passes over the [tile, window] block — pure VPU reductions
            # instead of a sort/TopK call (tuner arm)
            def body(d2c, _):
                m = jnp.argmin(d2c, axis=1).astype(jnp.int32)
                d2c = d2c.at[jnp.arange(tile, dtype=jnp.int32), m].set(
                    jnp.inf)
                return d2c, m

            _, ms = jax.lax.scan(body, d2, None, length=k)
            jidx = ms.T
        elif selector == "tournament" and window % 128 == 0 and k <= 128:
            # EXACT two-stage selection: top-k within each 128-wide
            # group, then top-k of the group winners. Any global top-k
            # element is top-k within its own group, so the candidate
            # union provably contains the global top-k — same result as
            # the full sort at ~1/3 the sort work (128-wide sorts are
            # log^2(128)/log^2(W) of the compare stages; the stage-2
            # sort sees only groups*k keys). The full-width lax.top_k
            # sort is the slab engine's dominant cost on TPU.
            g = window // 128
            nd, ji = jax.lax.top_k(-d2.reshape(tile, g, 128), k)
            off = (jnp.arange(g, dtype=jnp.int32) * 128)[None, :, None]
            cand_i = (off + ji).reshape(tile, g * k)
            _, sel2 = jax.lax.top_k(nd.reshape(tile, g * k), k)
            jidx = jnp.take_along_axis(cand_i, sel2, axis=1)
        else:
            _, jidx = jax.lax.top_k(-d2, k)              # [tile, k]
        # exact distances for the winners (knn.exact_d2: the expansion's
        # cancellation floor would otherwise leak into the outlier
        # statistic and the certification test)
        kd2 = knnlib.exact_d2(q, cand, jidx)
        md = jnp.sqrt(kd2).mean(axis=1)
        qx = q[:, 0]
        # left coverage holds by construction: searchsorted guarantees
        # x_s[start-1] < first_x - r <= qx - r for every query in the tile,
        # and the downward clip only widens the window. Only the right edge
        # can truncate coverage.
        right_ok = (start + window >= L) | (x_s[start + window - 1] >= qx + r)
        certified = (kd2.max(axis=1) <= r * r) & right_ok & (qx < _SLAB_FAR)
        return jnp.where(certified, md, jnp.inf)

    # PLAIN sequential lax.map — do NOT add batch_size: vmapping per_tile
    # turns its dynamic_slice windows (different start per tile) into
    # full gathers, measured 4x slower on-chip (r5 tune_outlier run 4:
    # 2.77 s vs 0.69 s for the identical config; the regression was first
    # misread as tunnel variance until the batched code was the only
    # difference)
    md_s = jax.lax.map(per_tile,
                       (jnp.arange(n_tiles, dtype=jnp.int32), starts))
    return jnp.full(n, jnp.inf, jnp.float32).at[order].set(
        md_s.reshape(-1)[:n])


def statistical_outlier_mask_np(points, valid, nb_neighbors: int = 20,
                                std_ratio: float = 2.0):
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    _, d2 = knnlib.knn_np(points, valid, nb_neighbors)
    mean_d = np.sqrt(np.maximum(d2, 0)).mean(axis=1).astype(np.float32)
    return np.asarray(
        _stat_outlier_from_knn(mean_d, valid, np.float32(std_ratio), np))


# ---------------------------------------------------------------------------
# Radius outlier removal (A15)
# ---------------------------------------------------------------------------

def radius_outlier_mask(points, valid, radius=5.0, nb_points: int = 100):
    """Keep points with >= nb_points neighbors within radius
    (processing.py:439)."""
    counts = knnlib.radius_count(points, valid, radius)
    return valid & (counts >= nb_points)


def radius_outlier_mask_np(points, valid, radius=5.0, nb_points: int = 100):
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    counts = knnlib.radius_count_np(points, valid, radius)
    return valid & (counts >= nb_points)


# ---------------------------------------------------------------------------
# Plane segmentation / background removal (A12)
# ---------------------------------------------------------------------------

def _plane_from_triples(p0, p1, p2, xp):
    n = xp.cross(p1 - p0, p2 - p0)
    norm = xp.sqrt((n * n).sum(-1, keepdims=True))
    n = n / xp.maximum(norm, 1e-12)
    d = -(n * p0).sum(-1)
    return n, d


@functools.partial(jax.jit, static_argnames=("num_iterations",))
def segment_plane(points, valid, distance_threshold=2.0,
                  num_iterations: int = 512, key=None):
    """Batched-hypothesis RANSAC plane fit.

    Returns (plane [4], inlier_mask [N]). The reference keeps the *inverse* of
    the inliers to delete the turntable surface (processing.py:349-354) —
    callers do `valid & ~inliers`.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    n = points.shape[0]
    if n == 0:  # empty clouds flow through the clean chain gracefully
        return jnp.zeros(4, jnp.float32), jnp.zeros(0, bool)
    pts = points.astype(jnp.float32)
    # sample triples among valid points: draw from the valid-weighted categorical
    probs = valid.astype(jnp.float32)
    probs = probs / jnp.maximum(probs.sum(), 1.0)
    tri_idx = jax.random.choice(key, n, shape=(num_iterations, 3), p=probs)
    p0, p1, p2 = (pts[tri_idx[:, i]] for i in range(3))
    nrm, d = _plane_from_triples(p0, p1, p2, jnp)  # [T,3], [T]

    # score all hypotheses: |P . n + d| <= t   — [T, N] via MXU matmul
    dist = jnp.abs(
        jax.lax.dot_general(nrm, pts, (((1,), (1,)), ((), ())),
                            precision=jax.lax.Precision.HIGHEST)
        + d[:, None]
    )
    within = (dist <= distance_threshold) & valid[None, :]
    scores = within.sum(axis=1)
    best = jnp.argmax(scores)
    plane = jnp.concatenate([nrm[best], d[best][None]])
    inliers = within[best]
    return plane, inliers


def segment_plane_np(points, valid, distance_threshold=2.0,
                     num_iterations: int = 512, seed: int = 0):
    rng = np.random.default_rng(seed)
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    vi = np.where(valid)[0]
    pts = points.astype(np.float64)
    best_score, best_plane = -1, None
    tri = rng.choice(vi, size=(num_iterations, 3))
    p0, p1, p2 = pts[tri[:, 0]], pts[tri[:, 1]], pts[tri[:, 2]]
    nrm, d = _plane_from_triples(p0, p1, p2, np)
    for t in range(num_iterations):
        dist = np.abs(pts @ nrm[t] + d[t])
        score = int(((dist <= distance_threshold) & valid).sum())
        if score > best_score:
            best_score, best_plane = score, (nrm[t], d[t])
    nb, db = best_plane
    inliers = (np.abs(pts @ nb + db) <= distance_threshold) & valid
    return np.concatenate([nb, [db]]).astype(np.float32), inliers


# ---------------------------------------------------------------------------
# Density clustering -> largest cluster (A14)
# ---------------------------------------------------------------------------

def cluster_labels(points, valid, eps=5.0, min_points: int = 200,
                   k: int = 16, max_iters: int = 200):
    """DBSCAN-style labels via min-label propagation on the kNN graph.

    Core points (>= min_points neighbors within eps) propagate the minimum
    label across edges shorter than eps until fixpoint. Border points adopt a
    neighboring core label; sparse points get label -1 (noise). This is the
    fixed-shape XLA formulation of Open3D's cluster_dbscan (processing.py:400)
    — identical partitions whenever cluster connectivity survives the k-edge
    approximation of the eps-graph (k defaults to 16; raise for dense clouds).
    """
    n = points.shape[0]
    if n == 0:  # empty clouds flow through the clean chain gracefully
        return jnp.zeros(0, jnp.int32)
    idx, d2 = knnlib.knn(points, valid, k)
    eps2 = jnp.float32(eps) ** 2
    counts = knnlib.radius_count(points, valid, eps)
    core = valid & (counts >= min_points)
    edge_ok = (d2 <= eps2) & valid[idx] & valid[:, None]  # [N,k]

    labels0 = jnp.where(core, jnp.arange(n, dtype=jnp.int32), jnp.int32(n))
    cc_edge = edge_ok & core[idx] & core[:, None]  # core-to-core edges [N,k]
    flat_idx = idx.reshape(-1)
    push_ok = cc_edge.reshape(-1)

    def body(state):
        labels, _, it = state
        # pull the min label over core->core edges
        neigh = jnp.where(cc_edge, labels[idx], n)
        pulled = jnp.minimum(labels, neigh.min(axis=1))
        # scatter-min: push my label to my core neighbors (makes edges symmetric)
        push_val = jnp.where(push_ok, jnp.repeat(labels, k), n)
        pushed = jnp.full((n,), n, jnp.int32).at[flat_idx].min(push_val)
        new = jnp.minimum(pulled, pushed)
        new = jnp.where(core, new, jnp.int32(n))
        return new, jnp.any(new != labels), it + 1

    labels, _, _ = jax.lax.while_loop(
        lambda s: s[1] & (s[2] < max_iters), body,
        (labels0, jnp.bool_(True), jnp.int32(0)))

    # border points: adopt the min label among in-eps core neighbors
    neigh_core = jnp.where(edge_ok & core[idx], labels[idx], n)
    border = jnp.where(valid & ~core, neigh_core.min(axis=1), n)
    final = jnp.where(core, labels, border)
    return jnp.where(final >= n, -1, final)  # -1 = noise


def largest_cluster_mask(points, valid, eps=5.0, min_points: int = 200,
                         k: int = 16):
    """Keep-mask of the most populated cluster (processing.py:400-420)."""
    n = points.shape[0]
    if n == 0:  # argmax over zero clusters is undefined
        return jnp.zeros(0, bool)
    labels = cluster_labels(points, valid, eps, min_points, k)
    safe = jnp.where(labels >= 0, labels, 0)
    counts = jnp.zeros((n,), jnp.int32).at[safe].add(
        (labels >= 0).astype(jnp.int32))
    best = jnp.argmax(counts)
    return valid & (labels == best)


def cluster_labels_np(points, valid, eps=5.0, min_points: int = 200):
    """Exact DBSCAN reference (cKDTree region growing)."""
    from scipy.spatial import cKDTree

    n = points.shape[0]
    if valid is None:
        valid = np.ones(n, bool)
    vi = np.where(valid)[0]
    if len(vi) == 0:
        return np.full(n, -1, np.int64)
    tree = cKDTree(points[vi])
    neigh = tree.query_ball_point(points[vi], eps)
    counts = np.array([len(x) - 1 for x in neigh])
    core = counts >= min_points
    labels_v = np.full(len(vi), -1, np.int64)
    cur = 0
    for i in range(len(vi)):
        if labels_v[i] != -1 or not core[i]:
            continue
        stack = [i]
        labels_v[i] = cur
        while stack:
            j = stack.pop()
            if not core[j]:
                continue
            for m in neigh[j]:
                if labels_v[m] == -1:
                    labels_v[m] = cur
                    stack.append(m)
        cur += 1
    labels = np.full(n, -1, np.int64)
    labels[vi] = labels_v
    return labels


def largest_cluster_mask_np(points, valid, eps=5.0, min_points: int = 200):
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    labels = cluster_labels_np(points, valid, eps, min_points)
    pos = labels[labels >= 0]
    if pos.size == 0:
        return np.zeros_like(valid)
    best = np.bincount(pos).argmax()
    return valid & (labels == best)


# ---------------------------------------------------------------------------
# Voxel downsample (A16/A18)
# ---------------------------------------------------------------------------

def voxel_downsample(points, colors, valid, voxel_size):
    """Average points (and colors) per voxel. Fixed shape: returns
    (points' [N,3], colors' [N,3], valid' [N]) where each surviving voxel
    occupies one slot (first-slot-of-voxel order after sort).

    Dispatch: with concrete inputs whose grid fits 2^10 cells per axis, the
    cell triple packs collision-free into one int32 and grouping costs ONE
    sort; TPU sorts are the dominant cost here, and the general path's
    3-key lexsort runs three of them. Traced inputs (or big grids) use the
    general path."""
    if not isinstance(points, jax.core.Tracer):
        if isinstance(points, np.ndarray):
            v_host = np.asarray(valid)
            sel = points[v_host] if v_host.any() else points[:1]
            ext = sel.max(axis=0) - sel.min(axis=0)
        else:  # device array: reduce on device, transfer 24 bytes, not MBs
            lo, hi = _masked_extent_jit(points, valid)
            ext = np.maximum(np.asarray(hi) - np.asarray(lo), 0.0)
        if np.all(np.floor(ext / np.float32(voxel_size)) < 1023):
            return _voxel_downsample_packed(points, colors, valid,
                                            jnp.float32(voxel_size))
    return _voxel_downsample_lex(points, colors, valid,
                                 jnp.float32(voxel_size))


@jax.jit
def _masked_extent_jit(points, valid):
    lo = jnp.where(valid[:, None], points, jnp.inf).min(axis=0)
    hi = jnp.where(valid[:, None], points, -jnp.inf).max(axis=0)
    return (jnp.where(jnp.isfinite(lo), lo, 0.0),
            jnp.where(jnp.isfinite(hi), hi, 0.0))


def _voxel_group_reduce(seg, v_s, p_s, c_s, n):
    cnt = jnp.zeros((n,), jnp.float32).at[seg].add(v_s.astype(jnp.float32))
    psum = jnp.zeros((n, 3), jnp.float32).at[seg].add(
        jnp.where(v_s[:, None], p_s, 0.0))
    csum = jnp.zeros((n, 3), jnp.float32).at[seg].add(
        jnp.where(v_s[:, None], c_s, 0.0))
    denom = jnp.maximum(cnt, 1.0)[:, None]
    return psum / denom, (csum / denom).astype(jnp.uint8), cnt > 0


@jax.jit
def _voxel_downsample_lex(points, colors, valid, vs):
    n = points.shape[0]
    origin = jnp.where(valid[:, None], points, jnp.inf).min(axis=0)
    ijk = jnp.floor((points - origin) / vs).astype(jnp.int32)
    # exact grouping: lexicographic sort on the raw (i, j, k) triple — no
    # packed/hashed key, so no collisions at any grid size (int32 can't hold
    # a collision-free pack of three 2^20 axes; three chained stable sorts
    # can). Invalid rows park at a sentinel cell past the clip range and
    # group together at the end with cnt=0.
    ijk = jnp.clip(ijk, 0, 2**20 - 1)
    ijk = jnp.where(valid[:, None], ijk, jnp.int32(2**20))
    order = jnp.lexsort((ijk[:, 2], ijk[:, 1], ijk[:, 0]))
    k_s = ijk[order]
    newgrp = jnp.concatenate(
        [jnp.ones(1, bool), jnp.any(k_s[1:] != k_s[:-1], axis=1)])
    seg = jnp.cumsum(newgrp.astype(jnp.int32)) - 1  # segment id per sorted slot
    return _voxel_group_reduce(seg, valid[order], points[order],
                               colors[order].astype(jnp.float32), n)


@jax.jit
def _voxel_downsample_packed(points, colors, valid, vs):
    """Single-sort grouping for grids under 2^10 cells per axis (the caller
    checked): key = i<<20 | j<<10 | k is collision-free in 30 bits, and the
    invalid sentinel (1<<30) sorts past every real cell."""
    n = points.shape[0]
    origin = jnp.where(valid[:, None], points, jnp.inf).min(axis=0)
    ijk = jnp.clip(jnp.floor((points - origin) / vs).astype(jnp.int32),
                   0, 1023)
    key = (ijk[:, 0] << 20) | (ijk[:, 1] << 10) | ijk[:, 2]
    key = jnp.where(valid, key, jnp.int32(1 << 30))
    order = jnp.argsort(key)
    k_s = key[order]
    newgrp = jnp.concatenate([jnp.ones(1, bool), k_s[1:] != k_s[:-1]])
    seg = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    return _voxel_group_reduce(seg, valid[order], points[order],
                               colors[order].astype(jnp.float32), n)


# ---------------------------------------------------------------------------
# Masked cleanup chain (the tab-3 chain as ONE fixed-shape program)
# ---------------------------------------------------------------------------

CLEAN_STEPS = ("background", "cluster", "radius", "statistical")


def chain_params(cfg, steps=CLEAN_STEPS) -> tuple:
    """Freeze a CleanConfig + step selection into the hashable static key
    ``clean_chain`` traces under: a tuple of (step, ((param, value), ...)).
    One bucket size + one params tuple = one compile for every view and
    every rerun. ``background`` honors ``remove_background_plane`` exactly
    like the file-level chain: disabled, the step vanishes (no count)."""
    params = []
    for step in steps:
        if step not in CLEAN_STEPS:
            raise ValueError(
                f"unknown clean step {step!r}; valid: {CLEAN_STEPS}")
        if step == "background":
            if not cfg.remove_background_plane:
                continue
            kw = (("dist", float(cfg.plane_ransac_dist)),
                  ("trials", int(cfg.plane_ransac_trials)))
        elif step == "cluster":
            kw = (("eps", float(cfg.cluster_eps)),
                  ("min_points", int(cfg.cluster_min_points)))
        elif step == "radius":
            kw = (("radius", float(cfg.radius)),
                  ("nb_points", int(cfg.radius_nb_points)))
        else:  # statistical
            kw = (("nb", int(cfg.outlier_nb_neighbors)),
                  ("std", float(cfg.outlier_std_ratio)))
        params.append((step, kw))
    return tuple(params)


def _chain_step(points, valid, step: str, kw: dict, jaxpath: bool, key=None):
    """One masked step: same op the file-level chain ran, but the survivors
    stay where they are — only the keep-mask narrows."""
    if step == "background":
        # the reference keeps the INVERSE of the plane inliers
        if jaxpath:
            _, inliers = segment_plane(points, valid,
                                       distance_threshold=kw["dist"],
                                       num_iterations=kw["trials"], key=key)
            return valid & ~inliers
        _, inliers = segment_plane_np(points, valid,
                                      distance_threshold=kw["dist"],
                                      num_iterations=kw["trials"])
        return valid & ~inliers
    if step == "cluster":
        fn = largest_cluster_mask if jaxpath else largest_cluster_mask_np
        return fn(points, valid, eps=kw["eps"], min_points=kw["min_points"])
    if step == "radius":
        fn = radius_outlier_mask if jaxpath else radius_outlier_mask_np
        return valid & fn(points, valid, radius=kw["radius"],
                          nb_points=kw["nb_points"])
    fn = (statistical_outlier_mask if jaxpath
          else statistical_outlier_mask_np)
    return valid & fn(points, valid, kw["nb"], kw["std"])


@functools.partial(jax.jit, static_argnames=("params",))
def _clean_chain_jit(points, valid, key, params: tuple):
    masks, counts = [], []
    for step, kw in params:
        valid = _chain_step(points, valid, step, dict(kw), jaxpath=True,
                            key=key)
        masks.append(valid)
        counts.append(valid.sum())
    return jnp.stack(masks), jnp.stack(counts).astype(jnp.int32)


def clean_chain(points, valid, cfg, steps=CLEAN_STEPS, key=None):
    """The cleanup chain (background plane -> largest cluster -> radius ->
    statistical, individually selectable) as masked fixed-shape steps in ONE
    jitted program: each step narrows a ``valid`` mask in place instead of
    host-compacting the survivors, so per-view sizes never reshape the trace
    — pad every cloud to its _bucket_pad bucket and one compile covers all
    views and reruns (assert via ``_clean_chain_jit._cache_size()``).

    points [N,3] f32 (padded), valid [N] bool. Returns (masks [S,N] bool,
    counts [S] i32) with one row per EFFECTIVE step (``chain_params``
    semantics: a disabled background step vanishes), masks[i] the
    accumulated keep-mask after step i — masks[-1] is the final survivor
    set, earlier rows feed per-step callbacks/artifacts.

    Host backends above the brute-kNN ceiling run the same masked steps
    eagerly instead (one extra dispatch per step, no jit): under trace the
    statistical/radius ops cannot reach their concrete-input host fast
    paths (cKDTree delegation), and the host grid kNN needs concrete
    extents."""
    params = chain_params(cfg, steps)
    n = points.shape[0]
    if n == 0 or not params:
        return (jnp.zeros((len(params), n), bool),
                jnp.zeros(len(params), jnp.int32))
    if key is None:
        key = jax.random.PRNGKey(0)
    concrete = not (isinstance(points, jax.core.Tracer)
                    or isinstance(valid, jax.core.Tracer))
    if (concrete and jax.default_backend() == "cpu"
            and n > knnlib._BRUTE_MAX):
        masks, counts = [], []
        v = jnp.asarray(valid)
        p = jnp.asarray(points)
        for step, kw in params:
            v = _chain_step(p, v, step, dict(kw), jaxpath=True, key=key)
            masks.append(v)
            counts.append(v.sum())
        return jnp.stack(masks), jnp.stack(counts).astype(jnp.int32)
    return _clean_chain_jit(jnp.asarray(points), jnp.asarray(valid), key,
                            params)


def clean_chain_np(points, valid, cfg, steps=CLEAN_STEPS):
    """Bit-exact NumPy twin of ``clean_chain`` (same masked semantics via
    the _np reference ops)."""
    params = chain_params(cfg, steps)
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    masks, counts = [], []
    v = np.asarray(valid, bool)
    for step, kw in params:
        v = _chain_step(np.asarray(points), v, step, dict(kw), jaxpath=False)
        masks.append(v)
        counts.append(int(v.sum()))
    if not masks:
        return (np.zeros((0, points.shape[0]), bool),
                np.zeros(0, np.int32))
    return np.stack(masks), np.asarray(counts, np.int32)


def voxel_downsample_np(points, colors, valid, voxel_size):
    """Exact reference: average per occupied voxel (Open3D semantics)."""
    if valid is None:
        valid = np.ones(points.shape[0], bool)
    pts = points[valid]
    cols = colors[valid] if colors is not None else None
    origin = pts.min(axis=0)
    # divide in f32 like the jnp path: a python-float divisor would promote
    # to f64 and voxel-boundary points could land in a different cell than
    # the device path (order-dependent test flake, caught 2026-07-30)
    ijk = np.floor((pts - origin) / np.float32(voxel_size)).astype(np.int64)
    _, inv, cnt = np.unique(ijk, axis=0, return_inverse=True, return_counts=True)
    m = cnt.shape[0]
    out_p = np.zeros((m, 3), np.float64)
    np.add.at(out_p, inv, pts)
    out_p /= cnt[:, None]
    out_c = None
    if cols is not None:
        out_c = np.zeros((m, 3), np.float64)
        np.add.at(out_c, inv, cols)
        out_c = (out_c / cnt[:, None]).astype(np.uint8)
    return out_p.astype(np.float32), out_c, np.ones(m, bool)
