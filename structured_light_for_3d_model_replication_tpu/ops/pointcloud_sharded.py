"""Multi-device merged-cloud postprocess: voxel downsample + statistical
outlier removal sharded over a point-axis device mesh.

The reference's merge tail (server/processing.py:605-629: final voxel
downsample, then remove_statistical_outlier) is a single-machine Open3D
call; at multi-chip scale the cloud is sharded and the same semantics are
built SPMD:

  1. HOST PRE-BUCKETING (``shard_points_by_slab``): points partition into
     contiguous z-slabs whose boundaries sit on voxel-cell multiples of the
     GLOBAL grid origin — a voxel cell then never spans two devices, so a
     purely local packed-key downsample per shard is exactly the global
     ``ops.pointcloud.voxel_downsample`` (same origin, same keys, same
     per-cell means).
  2. LOCAL voxel downsample per shard (single sort over absolute 30-bit
     packed keys, origin passed in — the same kernel as the single-device
     packed path).
  3. HALO EXCHANGE: each shard ppermutes its full (points, valid) buffer to
     both z-neighbors; a point's k nearest neighbors after voxelization lie
     within ``halo`` (a few cells), and ``halo <= min slab thickness`` is
     asserted on the host, so own + prev + next slabs contain every true
     neighbor of every CERTIFIED row.
  4. LOCAL mean-kNN distance over the 3*Np candidate set (chunked dense
     blocks on the MXU), certification = k-th candidate within ``halo``.
  5. GLOBAL Open3D statistics via psum (sum, sumsq, count of certified
     rows) -> one mu/sigma threshold applied everywhere.

Certified rows match the single-device ``statistical_outlier_mask`` exactly
(tests assert set-equality of the kept cloud on the 8-virtual-device CPU
mesh); a row whose k-th neighbor lies beyond ``halo`` is dropped as an
outlier (one-sided, same direction as the grid engine's out-of-range rule)
— on voxelized clouds that only happens to points ``halo``-isolated from
everything, which the threshold would drop anyway.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from structured_light_for_3d_model_replication_tpu.utils.jax_compat import shard_map

from structured_light_for_3d_model_replication_tpu.ops import pointcloud as pc

__all__ = ["shard_points_by_slab", "postprocess_merged_sharded"]

_AXIS = "points"
# per-shard cap on uncertified rows given the exact global fallback; rows
# past the cap stay inf (excluded from stats + dropped) — on voxelized
# clouds uncertified rows are far outliers, far fewer than this
_BAD_CAP = 512


def shard_points_by_slab(points, colors, valid, n_dev: int, cell: float):
    """Partition a merged cloud into z-slabs aligned to the global voxel grid.

    Returns (pts [D,Np,3] f32, cols [D,Np,3] u8, valid [D,Np] bool,
    origin [3] f32, min_slab_z f32 — the thinnest slab's z extent, the upper
    bound for a sound ``halo``). Np is the max bucket size padded to 256.
    """
    pts = np.asarray(points, np.float32)
    cols = (np.asarray(colors, np.uint8) if colors is not None
            else np.zeros_like(pts, dtype=np.uint8))
    v = (np.asarray(valid, bool) if valid is not None
         else np.ones(len(pts), bool))
    if not v.any():
        raise ValueError("shard_points_by_slab: empty cloud")
    cell = np.float32(cell)
    origin = pts[v].min(axis=0)  # identical to voxel_downsample's origin
    ext = pts[v].max(axis=0) - origin
    if np.any(np.floor(ext / cell) >= 1023):
        # the absolute 30-bit packed key caps the grid at 1023 cells/axis;
        # clipping would silently merge distinct voxels (and break the
        # slab-alignment premise along z) — the single-device path
        # dispatches to a lexsort kernel here instead
        raise ValueError(
            f"cloud spans {np.floor(ext / cell).astype(int)} voxel cells — "
            f"the sharded postprocess's packed keys cap at 1023 per axis; "
            f"raise final_voxel (or crop far outliers first)")
    zc = np.floor((pts[:, 2] - origin[2]) / cell).astype(np.int64)
    zc = np.where(v, zc, 0)
    z_hi = int(zc[v].max()) + 1
    # contiguous cell-index ranges, one per device (aligned: boundaries are
    # whole cells, so no voxel spans two shards)
    bounds = [round(i * z_hi / n_dev) for i in range(n_dev + 1)]
    if any(bounds[i + 1] == bounds[i] for i in range(n_dev)):
        raise ValueError(
            f"cloud spans only {z_hi} voxel cells in z — too thin to slab "
            f"over {n_dev} devices (an empty slab would break the +-1-slab "
            f"halo soundness); use fewer devices or a smaller cell")
    shard_of = np.searchsorted(np.asarray(bounds[1:]), zc, side="right")
    shard_of = np.minimum(shard_of, n_dev - 1)
    counts = np.bincount(shard_of[v], minlength=n_dev)
    n_p = int(-(-max(int(counts.max()), 1) // 256) * 256)
    pts_sh = np.full((n_dev, n_p, 3), 1e9, np.float32)
    cols_sh = np.zeros((n_dev, n_p, 3), np.uint8)
    valid_sh = np.zeros((n_dev, n_p), bool)
    for d in range(n_dev):
        sel = v & (shard_of == d)
        k = int(sel.sum())
        pts_sh[d, :k] = pts[sel]
        cols_sh[d, :k] = cols[sel]
        valid_sh[d, :k] = True
    min_slab_z = float(cell) * min(
        (bounds[i + 1] - bounds[i]) for i in range(n_dev))
    return pts_sh, cols_sh, valid_sh, origin.astype(np.float32), min_slab_z


@jax.jit
def _voxel_packed_origin(points, colors, valid, vs, origin):
    """The packed single-sort voxel downsample with an EXTERNAL grid origin
    (absolute keys shared across shards — pc._voxel_downsample_packed
    computes the origin from its own input, which per-shard would shift
    every shard onto a different grid)."""
    ijk = jnp.clip(jnp.floor((points - origin) / vs).astype(jnp.int32),
                   0, 1023)
    key = (ijk[:, 0] << 20) | (ijk[:, 1] << 10) | ijk[:, 2]
    key = jnp.where(valid, key, jnp.int32(1 << 30))
    order = jnp.argsort(key)
    k_s = key[order]
    newgrp = jnp.concatenate([jnp.ones(1, bool), k_s[1:] != k_s[:-1]])
    seg = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    return pc._voxel_group_reduce(seg, valid[order], points[order],
                                  colors[order].astype(jnp.float32),
                                  points.shape[0])


def postprocess_merged_sharded(mesh_or_devices, points, colors, valid,
                               final_voxel: float, outlier_nb: int = 20,
                               outlier_std: float = 2.0,
                               halo: float | None = None):
    """Sharded final voxel + statistical outlier pass over a merged cloud.

    ``mesh_or_devices``: a 1D Mesh, a device list, or an int (first N
    jax.devices()). Input arrays are HOST arrays (the merged cloud);
    returns (points [M,3] f32, colors [M,3] u8) gathered and compacted.
    """
    if isinstance(mesh_or_devices, Mesh):
        devices = list(mesh_or_devices.devices.reshape(-1))
    elif isinstance(mesh_or_devices, int):
        devices = jax.devices()[:mesh_or_devices]
    else:
        devices = list(mesh_or_devices)
    n_dev = len(devices)
    mesh = Mesh(np.asarray(devices), (_AXIS,))

    cell = float(final_voxel)
    pts_sh, cols_sh, valid_sh, origin, min_slab_z = shard_points_by_slab(
        points, colors, valid, n_dev, cell)
    if halo is None:
        # post-voxel spacing ~ cell: the k-th neighbor of any interior point
        # sits within a few cells; 8 covers nb=20 with headroom
        halo = 8.0 * cell
    if n_dev > 1 and halo > min_slab_z:
        # soundness needs halo <= slab thickness (neighbors beyond +-1 slab
        # are invisible to the exchange). Clamping is harmless while the
        # clamped halo still covers the certification radius (~4 cells for
        # nb<=30 at voxel pitch); below that, interior rows mass-uncertify
        # and the result would silently diverge — refuse loudly instead.
        if min_slab_z < 4.0 * cell:
            raise ValueError(
                f"slab thickness {min_slab_z:.1f} < certification radius "
                f"{4.0 * cell:.1f} (4 cells): too many devices for this "
                f"cloud's z extent — use fewer devices or a smaller "
                f"final_voxel")
        halo = min_slab_z
    out = _postprocess_sharded_jit(mesh, pts_sh, cols_sh, valid_sh,
                                   jnp.float32(cell),
                                   jnp.asarray(origin),
                                   jnp.float32(halo),
                                   jnp.float32(outlier_std),
                                   outlier_nb, n_dev)
    p, c, keep, n_overflow = (np.asarray(x) for x in out)
    if int(n_overflow.max()) > 0:
        raise RuntimeError(
            f"{int(n_overflow.max())} uncertified rows exceeded the "
            f"per-shard exact-fallback cap ({_BAD_CAP}) — the result would "
            f"silently drop valid points. A larger halo, larger "
            f"final_voxel, or fewer devices reduces uncertified rows.")
    keep = keep.reshape(-1)
    return p.reshape(-1, 3)[keep], c.reshape(-1, 3)[keep]


@functools.partial(jax.jit, static_argnames=("mesh", "k", "n_dev"))
def _postprocess_sharded_jit(mesh, pts, cols, vld, cell, origin, halo,
                             std_ratio, k: int, n_dev: int):
    spec = P(_AXIS)

    def local(p_s, c_s, v_s):
        p = p_s[0]
        c = c_s[0]
        v = v_s[0]
        # stage 1: local voxel downsample on the GLOBAL grid
        pv, cv, vv = _voxel_packed_origin(p, c, v, cell, origin)

        # stage 2: full-buffer halo exchange with both z-neighbors
        # (ppermute fills missing links with zeros -> valid=False)
        def from_prev(x):
            return jax.lax.ppermute(
                x, _AXIS, [(i, i + 1) for i in range(n_dev - 1)])

        def from_next(x):
            return jax.lax.ppermute(
                x, _AXIS, [(i + 1, i) for i in range(n_dev - 1)])

        if n_dev > 1:
            cand_p = jnp.concatenate([pv, from_prev(pv), from_next(pv)])
            cand_v = jnp.concatenate([vv, from_prev(vv), from_next(vv)])
        else:
            cand_p, cand_v = pv, vv
        cand_p = jnp.where(cand_v[:, None], cand_p, 1e9)

        # stage 3: chunked dense mean-kNN distance with certification; the
        # chunk shrinks with the candidate count so each [chunk, 3*Np] d2
        # block stays under ~0.5 GB (the same bound as knn_dense_approx)
        b2 = (cand_p * cand_p).sum(-1)
        n_own = pv.shape[0]
        chunk = min(2048, n_own)
        while chunk > 64 and chunk * cand_p.shape[0] * 4 > (1 << 29):
            chunk //= 2
        n_pad = -(-n_own // chunk) * chunk
        qp = jnp.concatenate(
            [pv, jnp.full((n_pad - n_own, 3), 1e9, jnp.float32)]
        ) if n_pad > n_own else pv

        def one_chunk(q):
            d2 = ((q * q).sum(-1)[:, None] + b2[None, :]
                  - 2.0 * jnp.matmul(q, cand_p.T,
                                     precision=jax.lax.Precision.HIGHEST))
            d2 = jnp.where(cand_v[None, :], d2, jnp.inf)
            d2 = jnp.where(d2 <= 1e-9, jnp.inf, d2)  # self (centroids differ)
            negk, _ = jax.lax.top_k(-d2, k)
            kd2 = jnp.maximum(-negk, 0.0)
            md = jnp.sqrt(kd2).mean(axis=1)
            certified = kd2[:, -1] <= halo * halo
            return jnp.where(certified, md, jnp.inf)

        md = jax.lax.map(one_chunk, qp.reshape(-1, chunk, 3)
                         ).reshape(-1)[:n_own]

        # stage 3b: exact GLOBAL fallback for uncertified rows. Open3D's
        # statistics include the huge mean distances of far outliers —
        # censoring them as inf would inflate-proof sigma and systematically
        # tighten the threshold (the same trap the single-device voxelized
        # probe documents). The few uncertified rows (far outliers, halo-
        # isolated points) are all_gathered, scored against every shard's
        # candidates, and their true k-th distances merged per row.
        bad = vv & ~jnp.isfinite(md)
        bad_rank = jnp.cumsum(bad.astype(jnp.int32)) - 1
        n_overflow = jnp.maximum(bad.sum() - _BAD_CAP, 0)  # host raises
        in_buf = bad & (bad_rank < _BAD_CAP)
        slot = jnp.where(in_buf, bad_rank, _BAD_CAP)
        qbuf = jnp.full((_BAD_CAP + 1, 3), 1e9, jnp.float32
                        ).at[slot].set(pv, mode="drop")[:_BAD_CAP]
        qall = jax.lax.all_gather(qbuf, _AXIS).reshape(-1, 3)  # [D*CAP, 3]
        own_p = jnp.where(vv[:, None], pv, 1e9)
        own_b2 = (own_p * own_p).sum(-1)

        def bad_chunk(qc):
            d2g = ((qc * qc).sum(-1)[:, None] + own_b2[None]
                   - 2.0 * jnp.matmul(qc, own_p.T,
                                      precision=jax.lax.Precision.HIGHEST))
            d2g = jnp.where(vv[None, :], d2g, jnp.inf)
            d2g = jnp.where(d2g <= 1e-9, jnp.inf, d2g)  # self / padding
            return jax.lax.top_k(-d2g, k)[0]

        # same ~0.5 GB block bound for the [rows, Np] fallback matrix
        bchunk = qall.shape[0]
        while bchunk > 64 and bchunk * n_own * 4 > (1 << 29):
            bchunk //= 2
        bpad = -(-qall.shape[0] // bchunk) * bchunk
        qall_p = jnp.concatenate(
            [qall, jnp.full((bpad - qall.shape[0], 3), 1e9, jnp.float32)]
        ) if bpad > qall.shape[0] else qall
        negk_l = jax.lax.map(bad_chunk, qall_p.reshape(-1, bchunk, 3)
                             ).reshape(bpad, k)[:qall.shape[0]]
        kd_all = jax.lax.all_gather(-negk_l, _AXIS)    # [D, D*CAP, k]
        comb = jnp.moveaxis(kd_all, 0, 1).reshape(qall.shape[0],
                                                  n_dev * k)
        negk_g, _ = jax.lax.top_k(-comb, k)
        md_g = jnp.sqrt(jnp.maximum(-negk_g, 0.0)).mean(axis=1)  # [D*CAP]
        mine = jax.lax.dynamic_slice(
            md_g, (jax.lax.axis_index(_AXIS) * _BAD_CAP,), (_BAD_CAP,))
        md = jnp.where(in_buf, mine[jnp.clip(bad_rank, 0, _BAD_CAP - 1)], md)

        # stage 4: GLOBAL Open3D statistics (psum over the mesh)
        ok = vv & jnp.isfinite(md)
        m = jnp.where(ok, md, 0.0)
        cnt = jnp.maximum(
            jax.lax.psum(ok.sum().astype(jnp.float32), _AXIS), 1.0)
        mu = jax.lax.psum(m.sum(), _AXIS) / cnt
        # two-pass variance, the same formulation as _stat_outlier_from_knn
        # (sum-of-squares minus mu^2 cancels catastrophically in f32 and
        # would shift threshold ties vs the single-device path)
        var = jax.lax.psum(
            jnp.where(ok, (md - mu) ** 2, 0.0).sum(), _AXIS) / cnt
        thresh = mu + std_ratio * jnp.sqrt(var)
        keep = ok & (md <= thresh)
        return pv[None], cv[None], keep[None], n_overflow[None]

    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=(spec, spec, spec, spec))
    return fn(pts, cols, vld)
