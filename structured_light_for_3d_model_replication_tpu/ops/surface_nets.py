"""Surface Nets iso-surface extraction — the mesh extractor for the Poisson grid.

Marching cubes needs 256-entry hand-built lookup tables; Surface Nets
(Gibson '98 "naive surface nets") achieves a watertight quad/tri mesh with
pure array ops, which suits XLA: one vertex per sign-change cell (placed at
the mean of its edge crossings), one quad per sign-change grid edge joining
the 4 cells that share it. Device side computes fixed-shape masks and vertex
positions; the only data-dependent step (compacting active cells/edges) is a
host-side np.where at the export boundary, like every other compaction in
this framework.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["extract_surface"]


@jax.jit
def _cell_vertices(field, iso):
    """Per-cell Surface-Nets vertex. field [G,G,G] sampled at cell centers.

    Cells are the dual cubes between 8 neighboring samples; cell (i,j,k) spans
    samples [i:i+2, j:j+2, k:k+2]. Returns (active [g-1]^3 bool,
    vertex [g-1]^3 x 3 fractional grid coords relative to sample (0,0,0)).
    """
    f = field
    g = f.shape[0]
    c = {}
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                c[(dx, dy, dz)] = f[dx:g - 1 + dx, dy:g - 1 + dy, dz:g - 1 + dz]
    d = jnp.float32(iso)
    inside = {k: v < d for k, v in c.items()}

    # 12 cube edges: (corner a, corner b)
    corners = list(c.keys())
    edges = []
    for a in corners:
        for b in corners:
            if a < b and sum(abs(a[i] - b[i]) for i in range(3)) == 1:
                edges.append((a, b))
    vsum = jnp.zeros(c[(0, 0, 0)].shape + (3,), jnp.float32)
    wsum = jnp.zeros(c[(0, 0, 0)].shape, jnp.float32)
    for a, b in edges:
        fa, fb = c[a], c[b]
        cross = inside[a] != inside[b]
        t = jnp.where(cross, (d - fa) / jnp.where(jnp.abs(fb - fa) > 1e-12,
                                                  fb - fa, 1.0), 0.0)
        t = jnp.clip(t, 0.0, 1.0)
        pa = jnp.asarray(a, jnp.float32)
        pb = jnp.asarray(b, jnp.float32)
        pt = pa[None, None, None, :] + t[..., None] * (pb - pa)[None, None, None, :]
        vsum = vsum + jnp.where(cross[..., None], pt, 0.0)
        wsum = wsum + cross.astype(jnp.float32)
    active = wsum > 0
    vertex = vsum / jnp.maximum(wsum, 1.0)[..., None]
    return active, vertex


@jax.jit
def _edge_quads(field, iso):
    """Sign-change masks for grid edges along each axis, and their direction.

    Edge along axis a at sample (i,j,k) connects samples (i,j,k) and +1 on a.
    A sign change emits a quad between the 4 dual cells sharing that edge.
    Returns per-axis (cross mask, flip mask) with shape [g-1 on a, g on rest].
    """
    f = field
    d = jnp.float32(iso)
    inside = f < d
    out = []
    for axis in range(3):
        a0 = jax.lax.slice_in_dim(inside, 0, f.shape[axis] - 1, axis=axis)
        a1 = jax.lax.slice_in_dim(inside, 1, f.shape[axis], axis=axis)
        cross = a0 != a1
        flip = a0  # inside -> outside vs outside -> inside orientation
        out.append((cross, flip))
    return out


def extract_surface(field, iso, origin=None, cell=1.0):
    """Extract the iso-surface triangle mesh of a [G,G,G] scalar field.

    Returns (vertices [V,3] f32 world coords, faces [F,3] i32). Watertight on
    closed iso-surfaces away from the grid boundary.
    """
    field = jnp.asarray(field, jnp.float32)
    g = field.shape[0]
    active, vertex = _cell_vertices(field, iso)
    edge_data = _edge_quads(field, iso)

    active_np = np.asarray(active)
    vertex_np = np.asarray(vertex)

    # host compaction: dense cell-id -> compact vertex id
    cell_id = np.full(active_np.shape, -1, np.int64)
    ai, aj, ak = np.nonzero(active_np)
    cell_id[ai, aj, ak] = np.arange(len(ai))
    verts = vertex_np[ai, aj, ak] + np.stack([ai, aj, ak], axis=1)

    faces = []
    gm = g - 1  # cell grid size per axis
    for axis in range(3):
        cross, flip = (np.asarray(x) for x in edge_data[axis])
        # edge at sample (i,j,k) along `axis`; adjacent cells: subtract 1 in
        # the two OTHER axes. Valid only where all 4 cells exist.
        o1, o2 = [a for a in range(3) if a != axis]
        ii, jj, kk = np.nonzero(cross)
        pos = np.stack([ii, jj, kk], axis=1)
        ok = (pos[:, o1] >= 1) & (pos[:, o1] <= gm - 0) & \
             (pos[:, o2] >= 1) & (pos[:, o2] <= gm - 0) & \
             (pos[:, axis] <= gm - 1)
        ok &= (pos[:, o1] - 1 >= 0) & (pos[:, o2] - 1 >= 0) & \
              (pos[:, o1] < gm + 1) & (pos[:, o2] < gm + 1)
        pos = pos[ok]
        fl = flip[ii, jj, kk][ok]
        if len(pos) == 0:
            continue

        def cid(dp1, dp2):
            q = pos.copy()
            q[:, o1] -= dp1
            q[:, o2] -= dp2
            inb = ((q >= 0).all(1) & (q[:, 0] < gm) & (q[:, 1] < gm)
                   & (q[:, 2] < gm))
            out = np.full(len(q), -1, np.int64)
            out[inb] = cell_id[q[inb, 0], q[inb, 1], q[inb, 2]]
            return out

        c00 = cid(1, 1)
        c10 = cid(0, 1)
        c11 = cid(0, 0)
        c01 = cid(1, 0)
        quad_ok = (c00 >= 0) & (c10 >= 0) & (c11 >= 0) & (c01 >= 0)
        c00, c10, c11, c01 = (c[quad_ok] for c in (c00, c10, c11, c01))
        fl = fl[quad_ok]
        if axis == 1:
            # permutation (axis, o1, o2) = (1, 0, 2) is odd: the (o1, o2) ring
            # runs clockwise seen from +axis, unlike axes 0 and 2 — flip
            fl = ~fl
        # two triangles per quad; winding by crossing direction
        t1 = np.where(fl[:, None], np.stack([c00, c10, c11], 1),
                      np.stack([c00, c11, c10], 1))
        t2 = np.where(fl[:, None], np.stack([c00, c11, c01], 1),
                      np.stack([c00, c01, c11], 1))
        faces.append(t1)
        faces.append(t2)

    faces_np = (np.concatenate(faces).astype(np.int32) if faces
                else np.zeros((0, 3), np.int32))
    verts_world = verts.astype(np.float32)
    if origin is not None:
        verts_world = verts_world * np.float32(cell) + np.asarray(origin,
                                                                  np.float32)
    return verts_world, faces_np
