"""Surface Nets iso-surface extraction — the mesh extractor for the Poisson grid.

Marching cubes needs 256-entry hand-built lookup tables; Surface Nets
(Gibson '98 "naive surface nets") achieves a watertight quad/tri mesh with
pure array ops, which suits XLA: one vertex per sign-change cell (placed at
the mean of its edge crossings), one quad per sign-change grid edge joining
the 4 cells that share it.

The export boundary compacts ON DEVICE (count -> sized flatnonzero ->
gather) and transfers only the ~1% active cells/edges: pulling the dense
[G-1]^3 x 3 vertex grid plus the edge masks at depth-9 is ~2.5 GB D2H,
which over a tunneled chip was the bulk of the bench's 182-274 s meshing
tail (r5). Host-side work is then pure index arithmetic on the compact
arrays (neighbor lookup by searchsorted on the sorted active cell ids —
no dense [G-1]^3 cell-id table either).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["extract_surface"]


def _bucket(n: int) -> int:
    """Static compaction size: next power of two (>= 1024) so nearby meshes
    reuse one executable instead of recompiling per surface."""
    m = 1024
    while m < n:
        m <<= 1
    return m


@jax.jit
def _counts(field, iso):
    """[4] i32: active-cell count and per-axis edge-crossing counts — via
    the cheap corner-sign formulation (a cell is active iff its 8 corners
    straddle iso, which is exactly 'some edge crosses')."""
    inside = field < jnp.float32(iso)
    g = field.shape[0]
    c000 = inside[:g - 1, :g - 1, :g - 1]
    all_in = c000
    any_in = c000
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                if (dx, dy, dz) == (0, 0, 0):
                    continue
                s = inside[dx:g - 1 + dx, dy:g - 1 + dy, dz:g - 1 + dz]
                all_in = all_in & s
                any_in = any_in | s
    n_cells = (any_in & ~all_in).sum(dtype=jnp.int32)
    crosses = []
    for axis in range(3):
        a0 = jax.lax.slice_in_dim(inside, 0, g - 1, axis=axis)
        a1 = jax.lax.slice_in_dim(inside, 1, g, axis=axis)
        crosses.append((a0 != a1).sum(dtype=jnp.int32))
    return jnp.stack([n_cells] + crosses)


@functools.partial(jax.jit, static_argnames=("m",))
def _compact_cells(field, iso, m: int):
    """(flat cell ids [m] ascending, vertices [m,3]) of the active cells;
    ids beyond the true count are filled with the (out-of-range) grid size."""
    active, vertex = _cell_vertices(field, iso)
    size = active.size
    idx = jnp.flatnonzero(active.ravel(), size=m, fill_value=size)
    v = vertex.reshape(-1, 3)[jnp.minimum(idx, size - 1)]
    return idx, v


@functools.partial(jax.jit, static_argnames=("axis", "m"))
def _compact_edges(field, iso, axis: int, m: int):
    """(flat edge ids [m] ascending, flip [m] bool) of the sign-changing
    grid edges along ``axis``."""
    cross, flip = _edge_axis(field, iso, axis)
    size = cross.size
    idx = jnp.flatnonzero(cross.ravel(), size=m, fill_value=size)
    fl = flip.ravel()[jnp.minimum(idx, size - 1)]
    return idx, fl


@jax.jit
def _cell_vertices(field, iso):
    """Per-cell Surface-Nets vertex. field [G,G,G] sampled at cell centers.

    Cells are the dual cubes between 8 neighboring samples; cell (i,j,k) spans
    samples [i:i+2, j:j+2, k:k+2]. Returns (active [g-1]^3 bool,
    vertex [g-1]^3 x 3 fractional grid coords relative to sample (0,0,0)).
    """
    f = field
    g = f.shape[0]
    c = {}
    for dx in (0, 1):
        for dy in (0, 1):
            for dz in (0, 1):
                c[(dx, dy, dz)] = f[dx:g - 1 + dx, dy:g - 1 + dy, dz:g - 1 + dz]
    d = jnp.float32(iso)
    inside = {k: v < d for k, v in c.items()}

    # 12 cube edges: (corner a, corner b)
    corners = list(c.keys())
    edges = []
    for a in corners:
        for b in corners:
            if a < b and sum(abs(a[i] - b[i]) for i in range(3)) == 1:
                edges.append((a, b))
    vsum = jnp.zeros(c[(0, 0, 0)].shape + (3,), jnp.float32)
    wsum = jnp.zeros(c[(0, 0, 0)].shape, jnp.float32)
    for a, b in edges:
        fa, fb = c[a], c[b]
        cross = inside[a] != inside[b]
        t = jnp.where(cross, (d - fa) / jnp.where(jnp.abs(fb - fa) > 1e-12,
                                                  fb - fa, 1.0), 0.0)
        t = jnp.clip(t, 0.0, 1.0)
        pa = jnp.asarray(a, jnp.float32)
        pb = jnp.asarray(b, jnp.float32)
        pt = pa[None, None, None, :] + t[..., None] * (pb - pa)[None, None, None, :]
        vsum = vsum + jnp.where(cross[..., None], pt, 0.0)
        wsum = wsum + cross.astype(jnp.float32)
    active = wsum > 0
    vertex = vsum / jnp.maximum(wsum, 1.0)[..., None]
    return active, vertex


def _edge_axis(field, iso, axis: int):
    """Sign-change mask + direction for grid edges along one axis (called
    inside _compact_edges' jit; axis is static there).

    Edge along ``axis`` at sample (i,j,k) connects samples (i,j,k) and +1
    on that axis. A sign change emits a quad between the 4 dual cells
    sharing the edge. Shapes: [g-1 on axis, g on the rest]."""
    f = field
    inside = f < jnp.float32(iso)
    a0 = jax.lax.slice_in_dim(inside, 0, f.shape[axis] - 1, axis=axis)
    a1 = jax.lax.slice_in_dim(inside, 1, f.shape[axis], axis=axis)
    return a0 != a1, a0  # flip: inside -> outside vs outside -> inside


def extract_surface(field, iso, origin=None, cell=1.0,
                    face_cells: bool = False):
    """Extract the iso-surface triangle mesh of a [G,G,G] scalar field.

    Returns (vertices [V,3] f32 world coords, faces [F,3] i32). Watertight on
    closed iso-surfaces away from the grid boundary.

    ``face_cells``: also return, per face, the (i,j,k) grid coords of the
    face's OWNER cell (the minimal-corner cell of its generating edge),
    and per VERTEX the (i,j,k) coords of its surface cell — the
    provenance the brick-stitched extraction uses to emit each face from
    exactly one brick and to key vertices canonically
    (ops/poisson_bricks.extract_surface_bricks). Return becomes
    (verts, faces, face_owner_cells [F,3] i32, vert_cells [V,3] i32).
    """
    field = jnp.asarray(field, jnp.float32)
    g = field.shape[0]
    gm = g - 1  # cell grid size per axis

    counts = np.asarray(_counts(field, jnp.float32(iso)))
    n_cells = int(counts[0])
    if n_cells == 0:
        verts = np.zeros((0, 3), np.float32)
        if origin is not None:
            verts = verts * np.float32(cell) + np.asarray(origin, np.float32)
        if face_cells:
            z = np.zeros((0, 3), np.int32)
            return verts, z, z, z
        return verts, np.zeros((0, 3), np.int32)

    cell_flat, vert_cells = _compact_cells(field, jnp.float32(iso),
                                           m=_bucket(n_cells))
    cell_flat = np.asarray(cell_flat).astype(np.int64)[:n_cells]  # ascending
    vert_cells = np.asarray(vert_cells)[:n_cells]
    ai, aj, ak = np.unravel_index(cell_flat, (gm, gm, gm))
    verts = vert_cells + np.stack([ai, aj, ak], axis=1)

    faces = []
    owners = []
    for axis in range(3):
        n_e = int(counts[1 + axis])
        if n_e == 0:
            continue
        e_shape = tuple(g - 1 if a == axis else g for a in range(3))
        e_flat, fl = _compact_edges(field, jnp.float32(iso), axis=axis,
                                    m=_bucket(n_e))
        e_flat = np.asarray(e_flat)[:n_e]
        fl = np.asarray(fl)[:n_e]
        ii, jj, kk = np.unravel_index(e_flat, e_shape)
        pos = np.stack([ii, jj, kk], axis=1)
        # edge at sample (i,j,k) along `axis`; adjacent cells: subtract 1 in
        # the two OTHER axes. This prefilter only drops edges with NO cell
        # on their low side (pos ranges make every other bound a tautology);
        # full 4-cell validity is enforced by cid's bounds + quad_ok below.
        o1, o2 = [a for a in range(3) if a != axis]
        ok = (pos[:, o1] >= 1) & (pos[:, o2] >= 1)
        pos = pos[ok]
        fl = fl[ok]
        if len(pos) == 0:
            continue

        def cid(dp1, dp2):
            # compact-vertex id of the cell at pos - (dp1 on o1, dp2 on o2):
            # searchsorted on the sorted active flat ids replaces the old
            # dense [gm]^3 cell-id table (0.5 GB host RAM at depth 9)
            q = pos.copy()
            q[:, o1] -= dp1
            q[:, o2] -= dp2
            inb = ((q >= 0).all(1) & (q[:, 0] < gm) & (q[:, 1] < gm)
                   & (q[:, 2] < gm))
            flat = (q[:, 0].astype(np.int64) * gm + q[:, 1]) * gm + q[:, 2]
            p = np.searchsorted(cell_flat, flat)
            pc = np.minimum(p, n_cells - 1)
            hit = inb & (cell_flat[pc] == flat)
            return np.where(hit, pc, -1)

        c00 = cid(1, 1)
        c10 = cid(0, 1)
        c11 = cid(0, 0)
        c01 = cid(1, 0)
        quad_ok = (c00 >= 0) & (c10 >= 0) & (c11 >= 0) & (c01 >= 0)
        c00, c10, c11, c01 = (c[quad_ok] for c in (c00, c10, c11, c01))
        fl = fl[quad_ok]
        if axis == 1:
            # permutation (axis, o1, o2) = (1, 0, 2) is odd: the (o1, o2) ring
            # runs clockwise seen from +axis, unlike axes 0 and 2 — flip
            fl = ~fl
        # two triangles per quad; winding by crossing direction
        t1 = np.where(fl[:, None], np.stack([c00, c10, c11], 1),
                      np.stack([c00, c11, c10], 1))
        t2 = np.where(fl[:, None], np.stack([c00, c11, c01], 1),
                      np.stack([c00, c01, c11], 1))
        faces.append(t1)
        faces.append(t2)
        if face_cells:
            own = pos[quad_ok].copy()
            own[:, o1] -= 1
            own[:, o2] -= 1
            owners.append(own)
            owners.append(own)

    faces_np = (np.concatenate(faces).astype(np.int32) if faces
                else np.zeros((0, 3), np.int32))
    verts_world = verts.astype(np.float32)
    if origin is not None:
        verts_world = verts_world * np.float32(cell) + np.asarray(origin,
                                                                  np.float32)
    if face_cells:
        own_np = (np.concatenate(owners).astype(np.int32) if owners
                  else np.zeros((0, 3), np.int32))
        vcell_np = np.stack([ai, aj, ak], axis=1).astype(np.int32)
        return verts_world, faces_np, own_np, vcell_np
    return verts_world, faces_np
