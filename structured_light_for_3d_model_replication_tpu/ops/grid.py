"""Grid-hash spatial index: the TPU-native neighbor engine.

KD-trees (Open3D's engine for every neighborhood query the reference runs) are
pointer-chasing; the XLA-friendly equivalent is a *uniform hashed grid*:

  1. quantize points to cells of size h; hash cell (ix,iy,iz) into a power-of-2
     table (open addressing by oversizing: H >= 2N)
  2. one sort by hash groups each cell's points; ranks within the group place
     every point in a fixed [H, M] slot table (M = max cell occupancy)
  3. a query point gathers the 27 neighboring cells' slots — <= 27*M fixed
     candidates — and scores them with dense elementwise distance math

Everything is sorts, segment-cumsums, gathers and elementwise ops — all fast,
fixed-shape XLA. With cell = radius, radius queries are EXACT (a sphere of
radius r fits in the 3x3x3 cell neighborhood). kNN is exact whenever the k-th
neighbor lies within one cell ring (cell auto-sized from density for that);
the scipy twins in knn.py remain the exact CPU reference.

Hash collisions merge buckets: queries then see superset candidates (distance
tests reject impostors — correctness preserved; only occupancy/speed pay).

ACCELERATOR GATE: the query entry points are HOST-ONLY. At merge-cloud
shapes (H=512k, M=100, rings=2, observed 2026-07-30) the bucket gathers
crash the TPU runtime outright — a worker fault, not an exception, and it
reproduced even with the bounded _GROUP_WIDTH streaming below. Until that
is root-caused, grid_knn / grid_query_knn / grid_radius_count raise a
RuntimeError on non-cpu backends instead of letting any input shape take
the runtime down (round-3 verdict weak #6); accelerator callers route
through the dense MXU paths in ops/knn.py and ops/pallas_kernels.py.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["HashGrid", "build_grid", "grid_radius_count", "grid_knn",
           "grid_radius_apply"]

_P1, _P2, _P3 = 73856093, 19349663, 83492791
_FAR = 1e9


class HashGrid(NamedTuple):
    table: jax.Array      # int32 [H, M] point index per slot, -1 = empty
    cell_of: jax.Array    # int32 [N] hash bucket of each point
    ijk: jax.Array        # int32 [N, 3] integer cell coords
    origin: jax.Array     # f32 [3]
    cell: jax.Array       # f32 scalar cell size
    points: jax.Array     # f32 [N, 3] (invalid parked at _FAR)
    valid: jax.Array      # bool [N]


def _hash_ijk(ijk, h_size: int):
    h = (ijk[..., 0] * np.int32(_P1)) ^ (ijk[..., 1] * np.int32(_P2)) \
        ^ (ijk[..., 2] * np.int32(_P3))
    return (h & (h_size - 1)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("h_size", "max_occ"))
def _build(points, valid, cell, h_size: int, max_occ: int) -> HashGrid:
    n = points.shape[0]
    pts = jnp.where(valid[:, None], points.astype(jnp.float32), _FAR)
    origin = jnp.min(jnp.where(valid[:, None], pts, jnp.inf), axis=0)
    origin = jnp.where(jnp.isfinite(origin), origin, 0.0)
    ijk = jnp.floor((pts - origin) / cell).astype(jnp.int32)
    h = jnp.where(valid, _hash_ijk(ijk, h_size), h_size - 1)
    order = jnp.argsort(h)
    h_s = h[order]
    # rank of each point within its bucket
    newrun = jnp.concatenate([jnp.ones(1, bool), h_s[1:] != h_s[:-1]])
    run_start = jax.lax.cummax(jnp.where(newrun, jnp.arange(n), 0))
    rank = jnp.arange(n) - run_start
    slot = jnp.where(rank < max_occ, h_s * max_occ + rank, h_size * max_occ)
    table = jnp.full((h_size * max_occ,), -1, jnp.int32)
    table = table.at[slot].set(order.astype(jnp.int32), mode="drop")
    return HashGrid(table.reshape(h_size, max_occ), h, ijk, origin,
                    jnp.float32(cell), pts, valid)


@functools.partial(jax.jit, static_argnames=())
def _max_occupancy(points, valid, cell):
    """Largest number of valid points sharing one cell (device scalar)."""
    pts = jnp.where(valid[:, None], points.astype(jnp.float32), _FAR)
    origin = jnp.min(jnp.where(valid[:, None], pts, jnp.inf), axis=0)
    origin = jnp.where(jnp.isfinite(origin), origin, 0.0)
    ijk = jnp.floor((pts - origin) / cell).astype(jnp.int32)
    h = _hash_ijk(ijk, 1 << 22)
    h = jnp.where(valid, h, -1)
    h_s = jnp.sort(h)
    newrun = jnp.concatenate([jnp.ones(1, bool), h_s[1:] != h_s[:-1]])
    n = points.shape[0]
    run_start = jax.lax.cummax(jnp.where(newrun, jnp.arange(n), 0))
    rank = jnp.arange(n) - run_start
    return jnp.max(jnp.where(h_s >= 0, rank, -1)) + 1


def build_grid(points, valid, cell_size: float, max_occ: int | None = None,
               occ_cap: int = 128) -> HashGrid:
    """Host wrapper: sizes the hash table and slot count, then builds on device.

    If a cell would exceed ``occ_cap`` points, the cell size is halved until it
    fits — bounded densification instead of dropped neighbors.
    """
    n = points.shape[0]
    h_size = 1 << max(10, int(np.ceil(np.log2(max(2 * n, 1024)))))
    cell = float(cell_size)
    if max_occ is None:
        for _ in range(8):
            m = int(_max_occupancy(points, valid, jnp.float32(cell)))
            if m <= occ_cap:
                break
            cell *= 0.5
        max_occ = max(1, min(m, occ_cap))
    return _build(points, valid, jnp.float32(cell), h_size, int(max_occ))


def _neighbor_buckets(grid: HashGrid, ijk_q, rings: int = 1):
    """[Q, (2*rings+1)^3] deduplicated bucket ids per query cell (dupes -> -1)."""
    r = range(-rings, rings + 1)
    offs = jnp.asarray([(dx, dy, dz) for dx in r for dy in r for dz in r],
                       jnp.int32)
    cells = ijk_q[:, None, :] + offs[None, :, :]              # [Q, B, 3]
    h = _hash_ijk(cells, grid.table.shape[0])                 # [Q, B]
    h_sorted = jnp.sort(h, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((h.shape[0], 1), bool), h_sorted[:, 1:] == h_sorted[:, :-1]],
        axis=1)
    return jnp.where(dup, -1, h_sorted)


def _gather_candidates(grid: HashGrid, buckets):
    """[Q, B*M] candidate point indices (-1 = none)."""
    q, b = buckets.shape
    m = grid.table.shape[1]
    tab = jnp.where(buckets[..., None] >= 0,
                    grid.table[jnp.maximum(buckets, 0)], -1)  # [Q, B, M]
    return tab.reshape(q, b * m)


def _candidate_d2(grid: HashGrid, q_pts, cand):
    """Squared distances [Q, C] to candidates; invalid candidates -> +inf."""
    cpts = grid.points[jnp.maximum(cand, 0)]                  # [Q, C, 3]
    d = cpts - q_pts[:, None, :]
    d2 = (d * d).sum(-1)
    bad = (cand < 0) | ~grid.valid[jnp.maximum(cand, 0)]
    return jnp.where(bad, jnp.inf, d2)


def _auto_chunk(grid: HashGrid, rings: int) -> int:
    # per-scan-step width is capped at _GROUP_WIDTH, so the query chunk is
    # sized only by the [chunk, _GROUP_WIDTH] working set (~64 MB at 8192)
    return 8192


def _chunk_indices(n: int, chunk: int):
    n_pad = -(-n // chunk) * chunk
    idx = jnp.arange(n_pad, dtype=jnp.int32).reshape(-1, chunk)
    return jnp.minimum(idx, n - 1)


# NOTES on structure:
#  - the grid is always an explicit ARGUMENT of the jitted query functions,
#    never a closure capture — closure-captured device arrays are baked into
#    the program as constants, which bloats the executable by the table size
#    (hundreds of MB) and overflows remote-compile transports
#  - per-step candidate width is bounded (~2k): wide single-shot gathers
#    ([Q, 8k]+ from a multi-GB table) fault the TPU runtime, so bucket groups
#    stream through a scan with a running reduction instead

_GROUP_WIDTH = 2048


def _bucket_groups(buckets, m: int):
    """Split [Q, B] buckets into [G, Q, Bg] groups, Bg*m <= _GROUP_WIDTH."""
    q, b = buckets.shape
    bg = max(1, _GROUP_WIDTH // max(m, 1))
    g = -(-b // bg)
    pad = g * bg - b
    if pad:
        buckets = jnp.concatenate(
            [buckets, jnp.full((q, pad), -1, buckets.dtype)], axis=1)
    return jnp.moveaxis(buckets.reshape(q, g, bg), 1, 0)


@functools.partial(jax.jit, static_argnames=("rings", "exclude_self", "chunk"))
def _radius_count_jit(grid: HashGrid, radius, rings: int, exclude_self: bool,
                      chunk: int):
    n = grid.points.shape[0]
    m = grid.table.shape[1]

    def fn(qi):
        q_pts = grid.points[qi]
        groups = _bucket_groups(_neighbor_buckets(grid, grid.ijk[qi], rings), m)

        def body(acc, bucket_g):
            cand = _gather_candidates(grid, bucket_g)
            d2 = _candidate_d2(grid, q_pts, cand)
            within = d2 <= radius * radius
            if exclude_self:
                within &= cand != qi[:, None]
            return acc + within.sum(-1, dtype=jnp.int32), None

        acc, _ = jax.lax.scan(body, jnp.zeros(qi.shape[0], jnp.int32), groups)
        return acc

    out = jax.lax.map(fn, _chunk_indices(n, chunk))
    return out.reshape(-1)[:n]


def _require_host_backend(op: str) -> None:
    backend = jax.default_backend()
    if backend != "cpu":
        raise RuntimeError(
            f"{op} is host-only: its bucket gathers have crashed the TPU "
            f"runtime at merge-cloud shapes (worker fault, not an "
            f"exception — see ops/grid.py module notes). On the "
            f"'{backend}' backend use ops.knn.knn / knn_dense_approx, the "
            f"Pallas nn1 kernel, or the slab-window engine instead.")


def grid_radius_count(grid: HashGrid, radius, exclude_self: bool = True,
                      rings: int = 1, chunk: int | None = None) -> jax.Array:
    """Exact per-point neighbor count within ``radius``. [N] int32.
    Requires rings * grid.cell >= radius (the sphere fits the searched block).
    Host-only (see module notes)."""
    _require_host_backend("grid_radius_count")
    chunk = chunk or _auto_chunk(grid, rings)
    return _radius_count_jit(grid, jnp.float32(radius), rings, exclude_self,
                             chunk)


@functools.partial(jax.jit, static_argnames=("k", "rings", "exclude_self",
                                             "chunk"))
def _knn_jit(grid: HashGrid, k: int, rings: int, exclude_self: bool,
             chunk: int):
    n = grid.points.shape[0]
    m = grid.table.shape[1]

    def fn(qi):
        q = qi.shape[0]
        q_pts = grid.points[qi]
        groups = _bucket_groups(_neighbor_buckets(grid, grid.ijk[qi], rings), m)

        def body(carry, bucket_g):
            best_d, best_i = carry
            cand = _gather_candidates(grid, bucket_g)
            d2 = _candidate_d2(grid, q_pts, cand)
            if exclude_self:
                d2 = jnp.where(cand == qi[:, None], jnp.inf, d2)
            cat_d = jnp.concatenate([best_d, d2], axis=1)
            cat_i = jnp.concatenate([best_i, cand], axis=1)
            neg, sel = jax.lax.top_k(-cat_d, k)
            return (-neg, jnp.take_along_axis(cat_i, sel, axis=1)), None

        init = (jnp.full((q, k), jnp.inf, jnp.float32),
                jnp.full((q, k), -1, jnp.int32))
        (best_d, best_i), _ = jax.lax.scan(body, init, groups)
        return jnp.maximum(best_i, 0), best_d

    idx, d2 = jax.lax.map(fn, _chunk_indices(n, chunk))
    return (idx.reshape(-1, k)[:n], d2.reshape(-1, k)[:n])


def grid_knn(grid: HashGrid, k: int, exclude_self: bool = True,
             rings: int = 1, chunk: int | None = None):
    """k nearest neighbors from the (2*rings+1)^3-cell candidate set.

    Exact when the k-th neighbor is within ``rings`` cell rings of the query;
    callers size the cell accordingly (see knn in knn.py).
    Returns (idx [N,k] int32, d2 [N,k] f32; missing slots repeat and d2=inf).
    Host-only (see module notes).
    """
    _require_host_backend("grid_knn")
    chunk = chunk or _auto_chunk(grid, rings)
    return _knn_jit(grid, k, rings, exclude_self, chunk)


@functools.partial(jax.jit, static_argnames=("k", "rings", "chunk"))
def _query_knn_jit(grid: HashGrid, q_pts, k: int, rings: int, chunk: int):
    nq = q_pts.shape[0]
    m = grid.table.shape[1]
    n_pad = -(-nq // chunk) * chunk
    qp = jnp.concatenate(
        [q_pts.astype(jnp.float32),
         jnp.full((n_pad - nq, 3), _FAR, jnp.float32)], axis=0
    ).reshape(-1, chunk, 3)

    def fn(qblk):
        ijk_q = jnp.floor((qblk - grid.origin) / grid.cell).astype(jnp.int32)
        groups = _bucket_groups(_neighbor_buckets(grid, ijk_q, rings), m)

        def body(carry, bucket_g):
            best_d, best_i = carry
            cand = _gather_candidates(grid, bucket_g)
            d2 = _candidate_d2(grid, qblk, cand)
            cat_d = jnp.concatenate([best_d, d2], axis=1)
            cat_i = jnp.concatenate([best_i, cand], axis=1)
            neg, sel = jax.lax.top_k(-cat_d, k)
            return (-neg, jnp.take_along_axis(cat_i, sel, axis=1)), None

        init = (jnp.full((chunk, k), jnp.inf, jnp.float32),
                jnp.full((chunk, k), -1, jnp.int32))
        (bd, bi), _ = jax.lax.scan(body, init, groups)
        return jnp.maximum(bi, 0), bd

    idx, d2 = jax.lax.map(fn, qp)
    return idx.reshape(-1, k)[:nq], d2.reshape(-1, k)[:nq]


def grid_query_knn(grid: HashGrid, q_pts, k: int, rings: int = 1,
                   chunk: int | None = None):
    """k nearest grid points for EXTERNAL query points [Q,3] (cross-cloud
    queries: ICP correspondences, Chamfer distance). Same exactness contract
    as grid_knn. Queries farther than rings*cell from every grid point get
    d2=inf slots. Host-only (see module notes)."""
    _require_host_backend("grid_query_knn")
    chunk = chunk or _auto_chunk(grid, rings)
    return _query_knn_jit(grid, jnp.asarray(q_pts, jnp.float32), k, rings, chunk)
