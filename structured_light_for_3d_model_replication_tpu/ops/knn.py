"""Nearest-neighbor primitives as MXU-shaped reductions.

Open3D's point-cloud ops (outlier removal, normals, FPFH, ICP, DBSCAN — the
C++ core behind server/processing.py:337-629) are all KD-tree neighborhood
queries. KD-trees are pointer-chasing and hostile to XLA; on TPU the same
queries become *tiled brute-force distance products*: the [Nq, Nb] squared
distance matrix is ||q||^2 + ||b||^2 - 2 q.b, whose cross term is a matmul the
MXU eats at hundreds of TFLOP/s. The matrix never materializes — base points
stream through in blocks with a running top-k merge, so memory stays
O(block^2) while FLOPs stay dense.

All functions are fixed-shape (padded) with validity masks, so they jit,
vmap, and shard cleanly. A NumPy/scipy cKDTree twin of each op (knn_np, ...)
is the bit-for-semantics CPU reference used by the numpy backend and tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["knn", "knn_np", "knn_dense_approx", "radius_count",
           "radius_count_np", "pad_points"]

_FAR = 1e9  # coordinate assigned to invalid/padded points: far from everything


def pad_points(points: np.ndarray, valid: np.ndarray | None, multiple: int):
    """Pad [N,3] points (+mask) to a multiple of ``multiple`` with far-away
    sentinels. Returns (points_p, valid_p, n_orig)."""
    n = points.shape[0]
    n_pad = (-n) % multiple
    if valid is None:
        valid = np.ones(n, bool)
    if n_pad:
        points = np.concatenate(
            [points, np.full((n_pad, 3), _FAR, points.dtype)], axis=0)
        valid = np.concatenate([valid, np.zeros(n_pad, bool)])
    return points, valid, n


def _masked_coords(points, valid, xp):
    # park invalid points far away so they never appear as neighbors
    return xp.where(valid[:, None], points, xp.asarray(_FAR, points.dtype))


def exact_d2(queries, base, idx):
    """Exact squared distances from each query to ``base[idx]`` by direct
    difference — the shared recompute behind every MXU-expansion selection
    path in this package: |q|^2+|b|^2-2q.b keeps distance matrices on the
    MXU but cancels catastrophically in f32 (~0.04 mm^2 absolute noise at
    decimeter-scale scene coordinates, measured as a 0.064 mm chamfer
    floor on clouds whose true separation is ~1e-4 mm). Selection may
    ride the expansion; reported distances must not.

    ``idx`` is [N] (1-NN) or [N,k]; invalid/padded handling is the
    caller's policy (park base rows FAR before selecting, or guard the
    returned values)."""
    sel = base[idx]
    q = queries if idx.ndim == 1 else queries[:, None, :]
    diff = q - sel
    return jnp.maximum((diff * diff).sum(-1), 0.0)


def _choose_blocks(n: int, block_q: int, block_b: int) -> tuple[int, int, int]:
    """Effective (block_q, block_b, padded_n) for an arbitrary N."""
    pow2 = 1 << max(0, (n - 1)).bit_length()
    block_b = min(block_b, max(256, pow2))
    block_q = min(block_q, block_b)
    block_b -= block_b % block_q  # base blocks iterate in query-divisible units
    n_pad = -(-n // block_b) * block_b
    return block_q, block_b, n_pad


def _pad_jax(points, valid, n_pad):
    n = points.shape[0]
    if n == n_pad:
        return points, valid
    extra = n_pad - n
    points = jnp.concatenate(
        [points, jnp.full((extra, 3), _FAR, points.dtype)], axis=0)
    valid = jnp.concatenate([valid, jnp.zeros(extra, bool)])
    return points, valid


_BRUTE_MAX = 65536  # above this, dispatch to the grid-hash engine


def knn(points: jax.Array, valid: jax.Array, k: int,
        block_q: int = 512, block_b: int = 8192,
        exclude_self: bool = True, exact: bool = False,
        recall_target: float = 0.99, selector: str = "topk"):
    """k nearest neighbors among valid points, for every point.

    points [N,3] float32 (any N), valid [N] bool. Returns (idx [N,k] int32,
    d2 [N,k] f32). Rows of invalid points contain arbitrary (masked) results.

    Dispatch: tiled brute-force (dense matmul-shaped, exact) for small N;
    for large N, dense rows + approx_min_k on accelerators
    (knn_dense_approx) or grid-hash candidate search (ops/grid.py) on
    hosts, with the cell sized from mean density and a 2-ring search.
    The grid path is exact wherever the k-th neighbor lies within 2 cell
    rings; for sparse outliers beyond that it *overestimates* distances
    (never underestimates) — the safe direction for every consumer
    (outlier filters flag such points harder).

    ``exact=True`` forces the tiled brute path at ANY size (the reference's
    KDTree is exact; precision-sensitive callers opt out of both large-N
    approximations — O(N^2) FLOPs, so expect seconds at merge-cloud scale).
    ``recall_target`` tunes the accelerator approx_min_k selection (per-row
    recall; misses only ever overestimate the k-th neighbor distance).
    ``selector`` is forwarded to the brute path (see knn_brute; the
    large-N accelerator path already selects via approx_min_k).
    """
    n = points.shape[0]
    if n <= _BRUTE_MAX or exact:
        return knn_brute(points, valid, k, block_q, block_b, exclude_self,
                         selector)
    if jax.default_backend() != "cpu":
        # accelerators: dense distance rows + the hardware-partial-reduce
        # top-k (lax.approx_min_k). The grid-hash path below is built for
        # hosts — on TPU its wide bucket gathers have faulted the runtime
        # outright at merge-cloud shapes (H=512k, M=100, rings=2; observed
        # 2026-07-30), and XLA lowers lax.top_k over the concatenated
        # candidate sets to full sorts that run ~20x slower than this
        # dense pass (27 s vs 1.4 s at 259k points).
        return knn_dense_approx(points, valid, k, exclude_self, recall_target)
    from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib

    pts = jnp.asarray(points, jnp.float32)
    lo = jnp.min(jnp.where(valid[:, None], pts, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(valid[:, None], pts, -jnp.inf), axis=0)
    ext = np.asarray(hi - lo, np.float64)
    nv = max(int(np.asarray(valid.sum())), 1)
    vol = float(np.prod(np.maximum(ext, 1e-6)))
    # cell from mean density, searched 2 rings deep: covers the k-neighborhood
    # even where local density runs well below the mean
    cell = 1.2 * (vol * max(k, 8) / nv) ** (1.0 / 3.0)
    grid = gridlib.build_grid(pts, valid, cell)
    return gridlib.grid_knn(grid, k, exclude_self, rings=2)


def knn_dense_approx(points: jax.Array, valid: jax.Array, k: int,
                     exclude_self: bool = True,
                     recall_target: float = 0.99):
    """Large-N kNN for accelerators: full distance rows in query chunks,
    selected with ``lax.approx_min_k`` (TPU PartialReduce).

    Distances are exact; only the top-k *selection* is approximate
    (recall_target per row, misses can only overestimate the k-th
    neighbor). Every consumer at this scale (statistical outlier mean
    distance, normals' covariance neighborhoods) degrades gracefully
    under that one-sided error.
    """
    n = points.shape[0]
    # pad to 8192s so executables cache across nearby cloud sizes, and pick
    # the largest power-of-two chunk (always divides the pad) keeping the
    # [chunk, n] f32 distance block within ~0.5 GB; the chunk floor is 64,
    # so the block stays < 1 GB up to ~4M points (beyond any merge size)
    n_pad = -(-n // 8192) * 8192
    bq = 2048
    while bq > 64 and bq * n_pad * 4 > (1 << 29):
        bq //= 2
    pts, vld = _pad_jax(jnp.asarray(points, jnp.float32), valid, n_pad)
    idx, d2 = _knn_dense_jit(pts, vld, k, bq, exclude_self,
                             float(recall_target))
    return idx[:n], d2[:n]


@functools.partial(jax.jit, static_argnames=("k", "bq", "exclude_self",
                                             "recall_target"))
def _knn_dense_jit(points, valid, k: int, bq: int, exclude_self: bool,
                   recall_target: float):
    pts = _masked_coords(points.astype(jnp.float32), valid, jnp)
    b2 = (pts * pts).sum(-1)

    def fn(args):
        qi, q = args
        q2 = (q * q).sum(-1)[:, None]
        cross = jax.lax.dot_general(q, pts, (((1,), (1,)), ((), ())),
                                    precision=jax.lax.Precision.HIGHEST)
        d2 = q2 + b2[None, :] - 2.0 * cross
        if exclude_self:
            qidx = qi * bq + jnp.arange(bq, dtype=jnp.int32)
            d2 = d2.at[jnp.arange(bq), qidx].set(jnp.inf)
        _, ios = jax.lax.approx_min_k(d2, k, recall_target=recall_target)
        # exact d2 for the selected neighbors (see exact_d2: the expansion
        # has an f32 cancellation floor the statistical outlier's
        # mean-distance statistic would otherwise inherit)
        return exact_d2(q, pts, ios), ios

    qb = pts.reshape(-1, bq, 3)
    d2o, io = jax.lax.map(fn, (jnp.arange(qb.shape[0], dtype=jnp.int32), qb))
    return (io.reshape(-1, k).astype(jnp.int32),
            jnp.maximum(d2o.reshape(-1, k), 0.0))


def knn_brute(points: jax.Array, valid: jax.Array, k: int,
              block_q: int = 512, block_b: int = 8192,
              exclude_self: bool = True, selector: str = "topk"):
    """Tiled brute-force kNN (O(N^2) distances on the MXU).

    ``selector``: ``"topk"`` (exact selection, the default) or
    ``"approx:<recall>"`` (``lax.approx_min_k`` PartialReduce at that
    recall — the full sort behind lax.top_k is the dominant cost of
    feature-prep kNN on TPU, and a missed neighbor only swaps in a
    slightly-farther one). The approx selection runs at EVERY base-block
    scan step, so effective per-row recall compounds to ~recall^nb for
    nb = N/block_b base blocks — at the per-view feature-prep sizes this
    serves (nb <= 2) that is the advertised ballpark; callers at larger
    N should size recall for the compounding or keep "topk". Both
    selectors report exact re-computed distances, ascending."""
    n = points.shape[0]
    block_q, block_b, n_pad = _choose_blocks(n, block_q, block_b)
    points, valid = _pad_jax(points, valid, n_pad)
    idx, d2 = _knn_blocks(points, valid, k, block_q, block_b, exclude_self,
                          selector)
    return idx[:n], d2[:n]


@functools.partial(jax.jit, static_argnames=("k", "block_q", "block_b",
                                             "exclude_self", "selector"))
def _knn_blocks(points, valid, k: int, block_q: int, block_b: int,
                exclude_self: bool, selector: str = "topk"):
    n = points.shape[0]
    pts = _masked_coords(points.astype(jnp.float32), valid, jnp)
    nq = n // block_q
    nb = n // block_b
    qblocks = pts.reshape(nq, block_q, 3)
    bblocks = pts.reshape(nb, block_b, 3)
    b2_all = (bblocks * bblocks).sum(-1)  # [nb, block_b]

    def per_query_block(qi, qblk):
        q2 = (qblk * qblk).sum(-1)[:, None]  # [bq, 1]
        init = (jnp.full((block_q, k), jnp.inf, jnp.float32),
                jnp.zeros((block_q, k), jnp.int32))

        def scan_base(carry, bi):
            best_d, best_i = carry
            bblk = bblocks[bi]
            cross = jax.lax.dot_general(
                qblk, bblk, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )  # [bq, bb]
            d2 = q2 + b2_all[bi][None, :] - 2.0 * cross
            base_idx = bi * block_b + jnp.arange(block_b, dtype=jnp.int32)
            if exclude_self:
                qidx = qi * block_q + jnp.arange(block_q, dtype=jnp.int32)
                d2 = jnp.where(qidx[:, None] == base_idx[None, :], jnp.inf, d2)
            cat_d = jnp.concatenate([best_d, d2], axis=1)
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(base_idx, (block_q, block_b))], axis=1)
            if selector == "topk":
                neg_d, sel = jax.lax.top_k(-cat_d, k)
                sel_d = -neg_d
            else:
                recall = float(selector.split(":", 1)[1])
                sel_d, sel = jax.lax.approx_min_k(cat_d, k,
                                                  recall_target=recall)
            return (sel_d, jnp.take_along_axis(cat_i, sel, axis=1)), None

        (best_d, best_i), _ = jax.lax.scan(scan_base, init,
                                           jnp.arange(nb, dtype=jnp.int32))
        # exact d2 for the winners (exact_d2); unfilled slots (best_d
        # still inf) stay inf
        d2e = jnp.where(jnp.isinf(best_d),
                        jnp.inf, exact_d2(qblk, pts, best_i))
        if selector != "topk":
            # approx_min_k returns unsorted rows: restore the ascending
            # contract (consumers slice the nearest-k' prefix) by the
            # EXACT distances — a 48-wide sort, trivial next to the full
            # candidate sort this selector replaced
            neg_d, ordr = jax.lax.top_k(-d2e, k)
            return -neg_d, jnp.take_along_axis(best_i, ordr, axis=1)
        return d2e, best_i

    best_d, best_i = jax.lax.map(
        lambda args: per_query_block(*args),
        (jnp.arange(nq, dtype=jnp.int32), qblocks),
    )
    return (best_i.reshape(n, k),
            jnp.maximum(best_d.reshape(n, k), 0.0))


def radius_count(points: jax.Array, valid: jax.Array, radius,
                 block_q: int = 512, block_b: int = 8192,
                 exclude_self: bool = True) -> jax.Array:
    """Number of valid points within ``radius`` of each point. [N] int32.

    Exact at every size: dense streaming blocks for small N (and at ANY
    size on accelerators, where the grid path's wide bucket gathers fault
    the TPU runtime and counting needs no top-k anyway); grid-hash with
    cell = radius (sphere fits the 27-cell neighborhood) for large N on
    hosts.
    """
    n = points.shape[0]
    if n == 0:
        return jnp.zeros(0, jnp.int32)
    if n <= _BRUTE_MAX:
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        if pk.use_pallas() and exclude_self:
            try:
                return pk.radius_count_pallas(points, valid, radius)
            except Exception:  # Mosaic compile failure at this shape: jnp twin
                pass
    if n <= _BRUTE_MAX or jax.default_backend() != "cpu":
        block_q, block_b, n_pad = _choose_blocks(n, block_q, block_b)
        points, valid = _pad_jax(points, valid, n_pad)
        return _radius_blocks(points, valid, jnp.float32(radius), block_q,
                              block_b, exclude_self)[:n]
    from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib

    # keep the exactness invariant rings*cell >= radius: if density forces a
    # cell smaller than the radius, widen the searched ring count instead
    pts = jnp.asarray(points, jnp.float32)
    cell = float(radius)
    rings = 1
    for _ in range(4):
        occ = int(gridlib._max_occupancy(pts, valid, jnp.float32(cell)))
        if occ <= 128 or rings >= 8:
            break
        cell *= 0.5
        rings *= 2
    grid = gridlib.build_grid(pts, valid, cell, max_occ=min(occ, 128))
    return gridlib.grid_radius_count(grid, radius, exclude_self, rings=rings)


@functools.partial(jax.jit, static_argnames=("block_q", "block_b", "exclude_self"))
def _radius_blocks(points, valid, radius, block_q: int, block_b: int,
                   exclude_self: bool) -> jax.Array:
    n = points.shape[0]
    pts = _masked_coords(points.astype(jnp.float32), valid, jnp)
    r2 = jnp.float32(radius) ** 2
    nq = n // block_q
    nb = n // block_b
    qblocks = pts.reshape(nq, block_q, 3)
    bblocks = pts.reshape(nb, block_b, 3)
    b2_all = (bblocks * bblocks).sum(-1)

    def per_query_block(qi, qblk):
        q2 = (qblk * qblk).sum(-1)[:, None]

        def scan_base(count, bi):
            bblk = bblocks[bi]
            cross = jax.lax.dot_general(
                qblk, bblk, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )
            d2 = q2 + b2_all[bi][None, :] - 2.0 * cross
            within = d2 <= r2
            if exclude_self:
                qidx = qi * block_q + jnp.arange(block_q, dtype=jnp.int32)
                base_idx = bi * block_b + jnp.arange(block_b, dtype=jnp.int32)
                within &= qidx[:, None] != base_idx[None, :]
            return count + within.sum(-1, dtype=jnp.int32), None

        count, _ = jax.lax.scan(scan_base, jnp.zeros(block_q, jnp.int32),
                                jnp.arange(nb, dtype=jnp.int32))
        return count

    counts = jax.lax.map(lambda args: per_query_block(*args),
                         (jnp.arange(nq, dtype=jnp.int32), qblocks))
    return counts.reshape(n)


# ---------------------------------------------------------------------------
# NumPy / scipy reference twins
# ---------------------------------------------------------------------------

def kdtree_build(points: np.ndarray, valid: np.ndarray):
    """(cKDTree over the valid rows, their global indices) for
    kdtree_distances_rows — split out so callers can overlap the
    O(N log N) host build with concurrent device work (the slab-window
    outlier engine runs ~0.7 s on-chip while the host sits idle)."""
    from scipy.spatial import cKDTree

    pts = np.asarray(points, np.float32)
    vi = np.flatnonzero(np.asarray(valid))
    return (cKDTree(pts[vi]) if len(vi) else None), vi


def kdtree_distances_rows(points: np.ndarray, valid: np.ndarray,
                          rows: np.ndarray, k: int,
                          tree_vi=None) -> np.ndarray:
    """Euclidean distances [len(rows), k] from the given cloud rows to their
    k nearest OTHER valid points, with knn_np's exact semantics (cKDTree,
    self dropped by global index, duplicates kept at 0, and knn_np's
    degenerate fill: rows with fewer than k real neighbors repeat their
    last real distance, so only rows with ZERO other valid points carry
    inf). Shared by the slab-window outlier engine's host fallback so the
    twin contract lives here once.

    ``tree_vi``: optional prebuilt ``kdtree_build(points, valid)`` result
    (must be over the same cloud/mask)."""
    rows = np.asarray(rows)
    pts = np.asarray(points, np.float32)
    tree, vi = tree_vi if tree_vi is not None else kdtree_build(points, valid)
    if tree is None:
        return np.full((len(rows), k), np.inf, np.float32)
    kk = min(k + 1, len(vi))
    d, j = tree.query(pts[rows], k=kk, workers=-1)
    d = np.asarray(d).reshape(len(rows), kk)
    j = np.asarray(j).reshape(len(rows), kk)
    dd = np.where(vi[j] == rows[:, None], np.inf, d)
    order = np.argsort(dd, axis=1, kind="stable")[:, :k]
    out = np.full((len(rows), k), np.inf, np.float32)
    m = order.shape[1]
    out[:, :m] = np.take_along_axis(dd, order, axis=1)
    # finite entries are a prefix (stable ascending sort, inf last):
    # repeat the last real distance into the suffix, as knn_np does
    fin = np.isfinite(out).sum(axis=1)
    has = fin > 0
    last = out[np.arange(out.shape[0]), np.maximum(fin - 1, 0)]
    fill = (np.arange(k)[None, :] >= fin[:, None]) & has[:, None]
    return np.where(fill, last[:, None], out)


def knn_np(points: np.ndarray, valid: np.ndarray | None, k: int,
           exclude_self: bool = True):
    """cKDTree reference. Same contract as knn() (unpadded N allowed).

    This twin IS the production host path at merged-cloud scale (see
    statistical_outlier_mask's delegation), so the common case — at
    least k+1 valid points — is fully vectorized; only degenerate tiny
    clouds take the per-row fill loop."""
    from scipy.spatial import cKDTree

    n = points.shape[0]
    if valid is None:
        valid = np.ones(n, bool)
    vi = np.where(valid)[0]
    if len(vi) == 0:
        return (np.zeros((n, k), np.int32),
                np.full((n, k), np.inf, np.float32))
    tree = cKDTree(points[vi])
    kk = k + 1 if exclude_self else k
    kk = min(kk, len(vi))
    d, j = tree.query(points, k=kk, workers=-1)
    # scipy squeezes the k axis when kk == 1; restore the (n, kk) contract
    # explicitly (np.atleast_2d would put the restored axis on the wrong
    # side, silently transposing the outputs)
    d = np.asarray(d).reshape(n, kk)
    j = np.asarray(j).reshape(n, kk)
    if exclude_self and kk == k + 1:
        # every row has >= k non-self candidates: drop the (at most one)
        # self entry by inf-ing it and re-taking the k smallest — d is
        # already sorted, so a stable argsort only moves the self slot
        cand = vi[j]                                   # [n, k+1] global ids
        dd = np.where(cand == np.arange(n)[:, None], np.inf, d)
        order = np.argsort(dd, axis=1, kind="stable")[:, :k]
        rows = np.arange(n)[:, None]
        return (cand[rows, order].astype(np.int32),
                (dd[rows, order].astype(np.float32) ** 2))
    if not exclude_self and kk == k:
        return (vi[j].astype(np.int32), (d.astype(np.float32) ** 2))
    # degenerate: fewer valid points than k(+1) — per-row fill
    idx = np.zeros((n, k), np.int32)
    d2 = np.full((n, k), np.inf, np.float32)
    for row in range(n):
        cand = vi[j[row]]
        dd = d[row]
        if exclude_self:
            keep = cand != row
            cand, dd = cand[keep], dd[keep]
        cand, dd = cand[:k], dd[:k]
        idx[row, : len(cand)] = cand
        d2[row, : len(dd)] = dd.astype(np.float32) ** 2
        if len(cand) < k and len(cand) > 0:  # repeat last to fill fixed shape
            idx[row, len(cand):] = cand[-1]
            d2[row, len(dd):] = d2[row, len(dd) - 1]
    return idx, d2


def radius_count_np(points: np.ndarray, valid: np.ndarray | None, radius: float,
                    exclude_self: bool = True) -> np.ndarray:
    from scipy.spatial import cKDTree

    n = points.shape[0]
    if valid is None:
        valid = np.ones(n, bool)
    vi = np.where(valid)[0]
    if len(vi) == 0:
        return np.zeros(n, np.int32)
    tree = cKDTree(points[vi])
    counts = np.asarray(tree.query_ball_point(points, radius,
                                              return_length=True), np.int32)
    if exclude_self:
        counts = counts - valid.astype(np.int32)
    return counts
