"""Direct point-cloud triangulation — the 'surface' meshing mode.

Capability parity with the reference's ball-pivoting branch
(server/processing.py:711-728: BPA with radii scaled from the average
nearest-neighbor distance), re-designed for TPU: instead of pivoting a ball
edge-to-edge (a serial, pointer-chasing frontier), every candidate triangle in
every point's k-neighbor fan is scored AT ONCE with the ball-pivoting
acceptance test — circumradius <= alpha and an empty alpha-ball touching the
three vertices — as a batched, fixed-shape kernel. Accepted triangles are
deduplicated on the host at the export boundary.

Like BPA (and unlike Poisson), the result interpolates the input points
exactly, preserves sharp detail, and leaves holes where sampling is too
sparse for the ball radius — the documented semantics of the reference's
"surface" mode vs its "watertight" mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib

__all__ = ["ball_pivot_surface", "average_nn_distance"]


def average_nn_distance(points, valid) -> float:
    """Mean distance to the nearest neighbor over valid points (the radius
    heuristic of processing.py:713-716)."""
    idx, d2 = knnlib.knn(points, valid, 1)  # knn excludes self: slot 0 = 1st NN
    d = jnp.sqrt(jnp.maximum(d2[:, 0], 0.0))
    w = valid.astype(jnp.float32)
    return float((d * w).sum() / jnp.maximum(w.sum(), 1.0))


@functools.partial(jax.jit, static_argnames=("m",))
def _score_chunk(ci, pts, nrm, valid, nb_i, pool_i, pairs_p, pairs_q,
                 alpha, *, m):
    """Score all neighbor-fan triangles of a chunk of seed points.

    ci [B] seed ids; nb_i [B,k] fan neighbors; pool_i [B,pk] empty-test pool.
    Returns (faces [B*m,3] i32, accept [B*m] bool) — orientation already
    aligned to the vertex normals.
    """
    eps = 1e-4 * alpha
    i = ci[:, None]                      # [B,1]
    j = nb_i[:, pairs_p]                 # [B,m]
    l = nb_i[:, pairs_q]                 # [B,m]
    a = pts[ci][:, None, :]              # [B,1,3]
    b = pts[j]                           # [B,m,3]
    c = pts[l]

    ok = (j != i) & (l != i) & (j != l)
    ok &= valid[ci][:, None] & valid[j] & valid[l]

    # circumcenter/radius in the triangle plane
    ab = b - a
    ac = c - a
    n = jnp.cross(ab, ac)
    n2 = (n * n).sum(-1)
    degenerate = n2 < 1e-20
    n2s = jnp.maximum(n2, 1e-20)
    ab2 = (ab * ab).sum(-1, keepdims=True)
    ac2 = (ac * ac).sum(-1, keepdims=True)
    # circumcenter: cc = a + (|ac|^2 (n x ab) + |ab|^2 (ac x n)) / (2 n.n)
    cc = a + (ac2 * jnp.cross(n, ab) + ab2 * jnp.cross(ac, n)) / (
        2.0 * n2s[..., None])
    rc2 = ((cc - a) ** 2).sum(-1)
    ok &= ~degenerate & (rc2 <= alpha * alpha)

    n_hat = n / jnp.sqrt(n2s)[..., None]
    h = jnp.sqrt(jnp.maximum(alpha * alpha - rc2, 0.0))[..., None]
    c_up = cc + h * n_hat                # the two balls touching a,b,c
    c_dn = cc - h * n_hat

    # empty-ball test against the seed's pool (minus the triangle's vertices)
    pool_pts = pts[pool_i]               # [B,pk,3]
    excl = ((pool_i[:, None, :] == i[:, :, None])
            | (pool_i[:, None, :] == j[..., None])
            | (pool_i[:, None, :] == l[..., None])
            | ~valid[pool_i][:, None, :])          # [B,m,pk]

    def min_d2(center):
        d = pool_pts[:, None, :, :] - center[:, :, None, :]   # [B,m,pk,3]
        d2 = (d * d).sum(-1)
        return jnp.where(excl, jnp.inf, d2).min(-1)           # [B,m]

    a2 = (alpha - eps) ** 2
    empty = (min_d2(c_up) >= a2) | (min_d2(c_dn) >= a2)
    ok &= empty

    # orient with the vertex normals (radial/centroid-oriented upstream)
    if nrm is not None:
        vote = ((nrm[ci][:, None, :] + nrm[j] + nrm[l]) * n_hat).sum(-1)
        flip = vote < 0
        jj = jnp.where(flip, l, j)
        ll = jnp.where(flip, j, l)
    else:
        jj, ll = j, l
    faces = jnp.stack(
        [jnp.broadcast_to(i, j.shape), jj, ll], axis=-1).reshape(-1, 3)
    return faces.astype(jnp.int32), ok.reshape(-1)


def ball_pivot_surface(points, valid=None, normals=None, alpha: float | None
                       = None, k: int = 12, pool_k: int = 24,
                       alpha_factor: float = 2.5, chunk: int = 4096):
    """Triangulate a point cloud directly (BPA-analog). Returns
    (vertices [N,3] f32 = the input points compacted, faces [F,3] i32).

    alpha: ball radius; default alpha_factor * average NN distance, the
    reference's radius heuristic (processing.py:713-719).
    """
    pts = jnp.asarray(points, jnp.float32)
    n = pts.shape[0]
    v = jnp.asarray(valid) if valid is not None else jnp.ones(n, bool)
    nrm = jnp.asarray(normals, jnp.float32) if normals is not None else None
    if alpha is None:
        alpha = alpha_factor * average_nn_distance(pts, v)
    kk = max(k, 3)
    pk = max(pool_k, kk)
    idx_pool, _ = knnlib.knn(pts, v, pk)
    idx_fan = idx_pool[:, :kk]
    pairs = np.asarray([(p, q) for p in range(kk) for q in range(p + 1, kk)])
    m = len(pairs)
    pp = jnp.asarray(pairs[:, 0])
    qq = jnp.asarray(pairs[:, 1])

    all_faces = []
    for s in range(0, n, chunk):
        ci = jnp.arange(s, min(s + chunk, n), dtype=jnp.int32)
        if ci.shape[0] < chunk:  # pad to the compiled chunk shape
            pad = chunk - ci.shape[0]
            ci = jnp.concatenate([ci, jnp.zeros(pad, jnp.int32)])
            live = np.arange(chunk) < (chunk - pad)
        else:
            live = np.ones(chunk, bool)
        faces, ok = _score_chunk(ci, pts, nrm, v, idx_fan[ci], idx_pool[ci],
                                 pp, qq, jnp.float32(alpha), m=m)
        ok = np.asarray(ok) & np.repeat(live, m)
        all_faces.append(np.asarray(faces)[ok])

    if not all_faces or sum(map(len, all_faces)) == 0:
        return np.asarray(pts), np.zeros((0, 3), np.int32)
    faces = np.concatenate(all_faces)
    # dedup on the unordered triple, keep the first occurrence's orientation
    key = np.sort(faces, axis=1)
    _, first = np.unique(key, axis=0, return_index=True)
    faces = faces[np.sort(first)]

    from structured_light_for_3d_model_replication_tpu.ops import meshproc

    return meshproc.remove_unreferenced(np.asarray(pts), faces)
