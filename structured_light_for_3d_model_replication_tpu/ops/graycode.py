"""Gray-code pattern generation and per-pixel decode.

Capability parity (behavioral spec studied from the reference, re-designed for XLA):
  - pattern generation: server/sl_system.py:44-86 (reflected Gray code, MSB-first,
    column-stripe and row-stripe bit-plane images, white + black + pattern/inverse pairs)
  - decode: server/processing.py:28-124 (Otsu or manual shadow+contrast masks,
    first-n-bit decode with always-advancing frame pointer, Gray->binary conversion,
    coordinate rescale by 2^(max_bits - n_use))

TPU-first design notes
----------------------
The reference decodes with a Python loop of per-bit cv2.imread + compares. Here the
whole stack lives as one [F, H, W] array: the bit compare is a single vectorized
``pattern > inverse`` over all bit-planes at once, the Gray->binary conversion is a
log2-depth XOR-downshift cascade (exact in int32), and everything fuses into one XLA
program with no host round-trips. Frames enter as uint8 and are compared in integer
space (no float upcast needed for exactness; the reference's float32 upcast of uint8
values is value-preserving, so integer compare is bit-identical).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "gray_bits",
    "generate_pattern_stack",
    "frames_per_view",
    "otsu_threshold",
    "otsu_threshold_np",
    "decode_stack",
    "decode_stack_np",
    "decode_packed",
    "decode_packed_np",
    "DecodeResult",
]


def _n_bits(size: int) -> int:
    return max(1, int(np.ceil(np.log2(size))))


def gray_bits(size: int, n_bits: int | None = None) -> np.ndarray:
    """Bit-planes of the reflected Gray code for positions [0, size).

    Returns bool array [n_bits, size]; row b is the MSB-first bit b of gray(x),
    where gray(x) = x ^ (x >> 1). This closed form equals the reference's
    recursive string construction (server/sl_system.py:56-62): the reflect-and-
    prefix recursion generates exactly the sequence gray(x) = x ^ (x >> 1).
    """
    if n_bits is None:
        n_bits = _n_bits(size)
    x = np.arange(size, dtype=np.int64)
    g = x ^ (x >> 1)
    shifts = np.arange(n_bits - 1, -1, -1, dtype=np.int64)  # MSB first
    return ((g[None, :] >> shifts[:, None]) & 1).astype(bool)


def frames_per_view(width: int = 1920, height: int = 1080, downsample: int = 1) -> int:
    """Frame count of one capture sequence: white + black + 2*(n_col_bits + n_row_bits).

    1920x1080 -> 2 + 2*(11+11) = 46, matching server/sl_system.py:126-150. With
    pattern downsampling k, the stripe images carry fewer bit-planes:
    2 + 2*(bits(w//k) + bits(h//k)).
    """
    return 2 + 2 * (_n_bits(width // downsample) + _n_bits(height // downsample))


def generate_pattern_stack(
    width: int = 1920,
    height: int = 1080,
    brightness: int = 200,
    downsample: int = 1,
) -> np.ndarray:
    """Full projector frame sequence as uint8 [F, height, width].

    Order (the capture-file contract, server/sl_system.py:126-150): frame 0 white,
    frame 1 black, then for each column bit MSB->LSB (pattern, inverse), then each
    row bit (pattern, inverse). ``downsample`` = D_SAMPLE_PROJ (server/config.py:22):
    patterns are computed at (width//k, height//k) — fewer, coarser bit-planes — and
    nearest-upsampled back to full projector resolution for display, matching the
    reference's resize-before-imshow (server/sl_system.py:144-147). Decode the
    resulting captures with ``decode_stack(..., downsample=k)`` to recover
    full-range projector coordinates.
    """
    w, h = width // downsample, height // downsample
    nc, nr = _n_bits(w), _n_bits(h)
    col = gray_bits(w, nc)  # [nc, w]
    row = gray_bits(h, nr)  # [nr, h]
    frames = np.zeros((2 + 2 * (nc + nr), h, w), dtype=np.uint8)
    frames[0] = brightness
    # frames[1] stays black
    f = 2
    for b in range(nc):
        stripe = np.where(col[b], brightness, 0).astype(np.uint8)  # [w]
        frames[f] = np.broadcast_to(stripe, (h, w))
        frames[f + 1] = brightness - frames[f]
        f += 2
    for b in range(nr):
        stripe = np.where(row[b], brightness, 0).astype(np.uint8)  # [h]
        frames[f] = np.broadcast_to(stripe[:, None], (h, w))
        frames[f + 1] = brightness - frames[f]
        f += 2
    if downsample > 1:
        # nearest-neighbor upsample to the full projector raster
        xi = (np.arange(width) * w) // width
        yi = (np.arange(height) * h) // height
        frames = frames[:, yi[:, None], xi[None, :]]
    return frames


# ---------------------------------------------------------------------------
# Otsu threshold — histogram argmax of between-class variance. Matches OpenCV's
# algorithm (first maximum wins; classes with zero mass score 0) so the manual
# masks used by the reference (server/processing.py:63-72) reproduce exactly.
# ---------------------------------------------------------------------------

def _otsu_from_hist(counts, xp):
    # float64 on the NumPy path; float32 on TPU (x64 is disabled under jit). The
    # moments are exact integers well inside fp32's 2^24 only for small images, so
    # the fp32 score can differ from fp64 in the ~1e-7 relative tail; the argmax is
    # validated against OpenCV at 1080p in tests (test_otsu_matches_cv2_fullres).
    dtype = xp.float64 if xp is np else xp.float32
    counts = counts.astype(dtype)
    total = counts.sum()
    levels = xp.arange(256, dtype=dtype)
    w1 = xp.cumsum(counts)                     # mass of class {0..t}
    m1 = xp.cumsum(counts * levels)            # unnormalized first moment of class {0..t}
    mT = m1[-1]
    w2 = total - w1
    # between-class variance: w1*w2*(mu1-mu2)^2 = (mT*w1 - total*m1)^2 / (w1*w2*total^2)
    num = (mT * w1 - total * m1) ** 2
    den = w1 * w2
    sigma_b = xp.where(den > 0, num / xp.where(den > 0, den, 1.0), 0.0)
    return xp.argmax(sigma_b)  # first max, like OpenCV's strict-> scan


def otsu_threshold_np(img_u8: np.ndarray) -> int:
    """Otsu threshold of a uint8 image (NumPy reference path)."""
    counts = np.bincount(img_u8.reshape(-1), minlength=256)[:256]
    return int(_otsu_from_hist(counts, np))


def otsu_threshold(img_u8: jax.Array) -> jax.Array:
    """Otsu threshold of a uint8 image (JAX path, jit-safe, returns 0-d int array)."""
    counts = jnp.bincount(img_u8.reshape(-1).astype(jnp.int32), length=256)
    return _otsu_from_hist(counts, jnp)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

class DecodeResult(NamedTuple):
    """Per-pixel decode output. Shapes stay fixed [H, W]; invalid pixels carry mask=False."""

    col_map: jax.Array | np.ndarray  # int32 [H, W], projector column in [0, 2^nc)
    row_map: jax.Array | np.ndarray  # int32 [H, W], projector row in [0, 2^nr)
    mask: jax.Array | np.ndarray     # bool  [H, W], shadow & contrast valid
    texture: jax.Array | np.ndarray  # uint8 [H, W, 3] color of the white frame


def _gray_to_binary(g, xp):
    # XOR-downshift cascade: exact inverse of gray(x) = x ^ (x >> 1) for <= 16 bits.
    g = g ^ (g >> 1)
    g = g ^ (g >> 2)
    g = g ^ (g >> 4)
    g = g ^ (g >> 8)
    return g


def _decode_axis(frames_i16, start, max_bits, n_use, xp, n_frames=None):
    """Decode one axis from pattern/inverse pairs at frames[start : start+2*max_bits].

    Reads only the first ``n_use`` bit pairs (the rest are skipped with the frame
    pointer still advancing, per server/processing.py:88-111) and scales the result
    by 2^(max_bits - n_use) to keep projector coordinates full-range.

    ``n_frames`` (the O2 truncated-stack variant, Old/multi_point_cloud_process
    .py:96-105 early ``break``): bit pairs beyond the end of the stack decode
    as 0 in the LSBs instead of raising — the pair count actually read is
    ``min(n_use, (n_frames - start) // 2)``.
    """
    avail = n_use if n_frames is None else max(0, min(n_use, (n_frames - start) // 2))
    pat = frames_i16[start : start + 2 * avail : 2]      # [avail, H, W]
    inv = frames_i16[start + 1 : start + 2 * avail : 2]  # [avail, H, W]
    bits = (pat > inv).astype(xp.int32)                  # [avail, H, W]
    # bit b is the MSB-first bit (n_use-1-b) of an n_use-bit gray value
    weights = (1 << np.arange(n_use - 1, n_use - 1 - avail, -1, dtype=np.int32))
    if avail == 0:
        gray = xp.zeros(frames_i16.shape[1:], xp.int32)
    else:
        gray = xp.sum(bits * xp.asarray(weights)[:, None, None], axis=0)
    binary = _gray_to_binary(gray, xp)
    return binary * (1 << (max_bits - n_use))


def _decode_impl(
    frames,          # uint8/int [F, H, W] grayscale capture stack
    texture,         # uint8 [H, W, 3]
    shadow_thresh,   # scalar: mask keeps white > shadow_thresh
    contrast_thresh, # scalar: mask keeps (white - black) > contrast_thresh
    *,
    n_cols: int,
    n_rows: int,
    n_sets_col: int,
    n_sets_row: int,
    downsample: int,
    xp,
    skip_remaining_before_row: bool = False,
):
    # patterns projected with downsample k carry bits of the k-decimated raster;
    # decode in that space, then scale by k to restore full projector coordinates
    n_cols = n_cols // downsample
    n_rows = n_rows // downsample
    max_col_bits = _n_bits(n_cols)
    max_row_bits = _n_bits(n_rows)
    n_use_col = max(1, min(int(n_sets_col), max_col_bits))
    n_use_row = max(1, min(int(n_sets_row), max_row_bits))

    need = 2 + 2 * (max_col_bits + max_row_bits)
    n_frames = None
    if frames.shape[0] < need:
        if not skip_remaining_before_row:
            raise ValueError(
                f"Not enough frames: got {frames.shape[0]}, need {need} "
                f"(white + black + 2*({max_col_bits} col + {max_row_bits} row bit-planes)) "
                f"for a {n_cols}x{n_rows} projector. Pass "
                f"skip_remaining_before_row=True for the legacy truncated-stack "
                f"decode (Old/multi_point_cloud_process.py:96-125)."
            )
        n_frames = frames.shape[0]

    if xp is not np and n_frames is None:
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        h, w = frames.shape[1], frames.shape[2]
        if (pk.use_pallas() and frames.dtype == jnp.uint8
                and h % 8 == 0 and w % 128 == 0):
            # fused Pallas path: one VMEM pass over the stack (bit-exact twin
            # of the arithmetic below; gated to tile-aligned frames). This
            # decode-maps kernel stays AUTO — it was active inside the r4
            # A/B's faster "jnp" arm (0.1045 s), so it is part of the
            # measured winner; only the single-pass scan kernel
            # (scan_points_fused_views) measured slower and sits behind the
            # SLSCAN_PALLAS=1 opt-in. The except arm only helps eager
            # callers — under an outer jit a Mosaic failure surfaces at that
            # jit's compile; the compiled-kernel probe in pallas_mode() is
            # the guard for that case.
            try:
                col, row, mask = pk.decode_maps_fused(
                    frames, shadow_thresh, contrast_thresh,
                    n_bits_col=max_col_bits, n_bits_row=max_row_bits,
                    n_use_col=n_use_col, n_use_row=n_use_row)
                return DecodeResult((col * downsample).astype(xp.int32),
                                    (row * downsample).astype(xp.int32),
                                    mask, texture)
            except Exception:
                pass  # fall through to the jnp twin below

    fr = frames.astype(xp.int16)
    white = fr[0]
    black = fr[1]
    mask = (white > shadow_thresh) & ((white - black) > contrast_thresh)

    col_map = _decode_axis(fr, 2, max_col_bits, n_use_col, xp,
                           n_frames=n_frames) * downsample
    row_map = _decode_axis(fr, 2 + 2 * max_col_bits, max_row_bits, n_use_row,
                           xp, n_frames=n_frames) * downsample
    return DecodeResult(col_map.astype(xp.int32), row_map.astype(xp.int32), mask, texture)


def _decode_axis_packed(planes, pair_start, max_bits, n_use, xp, n_pairs=None):
    """Packed twin of :func:`_decode_axis`: the comparison bits already exist
    in the bit-plane array (plane p at byte p//8, bit p%8 — the io/images.py
    pack layout), so "decode" is a shift-and-mask extraction feeding the same
    weights / Gray->binary cascade / rescale arithmetic.

    ``pair_start`` indexes pattern PAIRS, not frames: the raw stack's frame
    offset ``2 + 2*pair_start`` maps to plane ``pair_start``. ``n_pairs``
    (truncated-stack variant) clamps like _decode_axis's n_frames: with F
    frames the pairs readable from frame offset ``2 + 2*s`` number
    ``(F - 2 - 2*s)//2 = (F-2)//2 - s``, i.e. exactly ``n_pairs - s``.
    """
    avail = n_use if n_pairs is None else max(0, min(n_use, n_pairs - pair_start))
    if avail == 0:
        gray = xp.zeros(planes.shape[1:], xp.int32)
    else:
        p = np.arange(pair_start, pair_start + avail)
        shifts = xp.asarray((p & 7).astype(np.uint8))[:, None, None]
        bits = ((planes[p >> 3] >> shifts) & 1).astype(xp.int32)  # [avail, H, W]
        weights = (1 << np.arange(n_use - 1, n_use - 1 - avail, -1, dtype=np.int32))
        gray = xp.sum(bits * xp.asarray(weights)[:, None, None], axis=0)
    binary = _gray_to_binary(gray, xp)
    return binary * (1 << (max_bits - n_use))


def _decode_packed_impl(
    planes,          # uint8 [ceil(P/8), H, W] bit-planes (pack_stack layout)
    white,           # uint8 [H, W] frame 0, verbatim
    black,           # uint8 [H, W] frame 1, verbatim
    texture,         # uint8 [H, W, 3]
    shadow_thresh,
    contrast_thresh,
    *,
    n_frames: int,   # logical frame count of the packed stack (static)
    n_cols: int,
    n_rows: int,
    n_sets_col: int,
    n_sets_row: int,
    downsample: int,
    xp,
    skip_remaining_before_row: bool = False,
):
    n_cols = n_cols // downsample
    n_rows = n_rows // downsample
    max_col_bits = _n_bits(n_cols)
    max_row_bits = _n_bits(n_rows)
    n_use_col = max(1, min(int(n_sets_col), max_col_bits))
    n_use_row = max(1, min(int(n_sets_row), max_row_bits))

    need = 2 + 2 * (max_col_bits + max_row_bits)
    n_pairs = None
    if n_frames < need:
        if not skip_remaining_before_row:
            raise ValueError(
                f"Not enough frames: got {n_frames}, need {need} "
                f"(white + black + 2*({max_col_bits} col + {max_row_bits} row "
                f"bit-planes)) for a {n_cols}x{n_rows} projector. Pass "
                f"skip_remaining_before_row=True for the legacy "
                f"truncated-stack decode."
            )
        n_pairs = (n_frames - 2) // 2

    if xp is not np and n_pairs is None:
        from structured_light_for_3d_model_replication_tpu.ops import (
            pallas_kernels as pk,
        )

        h, w = white.shape
        if (pk.decode_packed_kernel_ok() and planes.dtype == jnp.uint8
                and h % 8 == 0 and w % 128 == 0):
            # fused Pallas unpack+decode: one VMEM pass over the packed
            # planes; bit-exact twin of the arithmetic below, same gating
            # discipline as decode_maps_fused above (probe + kill switch;
            # except arm only helps eager callers).
            try:
                col, row, mask = pk.decode_packed_maps_fused(
                    planes, white, black, shadow_thresh, contrast_thresh,
                    n_bits_col=max_col_bits, n_bits_row=max_row_bits,
                    n_use_col=n_use_col, n_use_row=n_use_row)
                return DecodeResult((col * downsample).astype(xp.int32),
                                    (row * downsample).astype(xp.int32),
                                    mask, texture)
            except Exception:
                pass  # fall through to the jnp twin below

    w16 = white.astype(xp.int16)
    b16 = black.astype(xp.int16)
    mask = (w16 > shadow_thresh) & ((w16 - b16) > contrast_thresh)

    col_map = _decode_axis_packed(planes, 0, max_col_bits, n_use_col, xp,
                                  n_pairs=n_pairs) * downsample
    row_map = _decode_axis_packed(planes, max_col_bits, max_row_bits,
                                  n_use_row, xp, n_pairs=n_pairs) * downsample
    return DecodeResult(col_map.astype(xp.int32), row_map.astype(xp.int32),
                        mask, texture)


def _shadow_contrast_hists(white_u8, diff_u8, xp):
    """256-bin histograms of the white frame and the clipped white-black diff."""
    if xp is np:
        h_w = np.bincount(white_u8.reshape(-1), minlength=256)[:256]
        h_d = np.bincount(diff_u8.reshape(-1), minlength=256)[:256]
    else:
        h_w = jnp.bincount(white_u8.reshape(-1).astype(jnp.int32), length=256)
        h_d = jnp.bincount(diff_u8.reshape(-1).astype(jnp.int32), length=256)
    return h_w, h_d


def _white_diff_u8(frames, xp):
    white = frames[0]
    diff = xp.clip(
        white.astype(xp.float32) - frames[1].astype(xp.float32), 0, 255
    ).astype(xp.uint8)
    return white.astype(xp.uint8), diff


@jax.jit
def _hists_device(frames):
    white_u8, diff_u8 = _white_diff_u8(frames, jnp)
    return _shadow_contrast_hists(white_u8, diff_u8, jnp)


@jax.jit
def _hists_device_views(frames_v):
    def one(frames):
        white_u8, diff_u8 = _white_diff_u8(frames, jnp)
        return _shadow_contrast_hists(white_u8, diff_u8, jnp)

    return jax.lax.map(one, frames_v)


def resolve_thresholds(frames, thresh_mode: str, shadow_val: float, contrast_val: float,
                       xp=np) -> tuple[float, float]:
    """Shadow/contrast thresholds for a capture stack.

    In ``otsu`` mode the 256-bin histograms are built wherever the frames live
    (on-device for JAX) and scored HOST-SIDE in exact float64, so the NumPy and
    JAX backends are guaranteed to pick the same bin — fp32 on-device scoring
    can flip near-tied bins (see ``otsu_device`` mode for the fully fused
    variant that accepts that risk).
    """
    if thresh_mode != "otsu":
        return float(shadow_val), float(contrast_val)
    if xp is np:
        white_u8, diff_u8 = _white_diff_u8(frames, np)
        h_w, h_d = _shadow_contrast_hists(white_u8, diff_u8, np)
    else:
        h_w, h_d = _hists_device(frames)
        h_w, h_d = np.asarray(h_w), np.asarray(h_d)
    return float(_otsu_from_hist(h_w, np)), float(_otsu_from_hist(h_d, np))


def resolve_thresholds_views(frames_v, thresh_mode: str, shadow_val: float,
                             contrast_val: float) -> tuple[np.ndarray, np.ndarray]:
    """Per-view (shadow, contrast) threshold arrays [V] f32 for a [V, F, H, W]
    capture stack. In ``otsu`` mode all V histogram pairs are built on-device
    in one launch and fetched in ONE transfer, then scored host-side in exact
    fp64 (same backend-parity contract as resolve_thresholds); the round-2
    per-view host round-trip loop is gone."""
    v = frames_v.shape[0]
    if thresh_mode != "otsu":
        return (np.full(v, shadow_val, np.float32),
                np.full(v, contrast_val, np.float32))
    h_w, h_d = _hists_device_views(frames_v)
    h_w = np.asarray(h_w)
    h_d = np.asarray(h_d)
    ss = np.array([_otsu_from_hist(h_w[i], np) for i in range(v)], np.float32)
    cs = np.array([_otsu_from_hist(h_d[i], np) for i in range(v)], np.float32)
    return ss, cs


def decode_stack_np(
    frames: np.ndarray,
    texture: np.ndarray | None = None,
    *,
    n_cols: int = 1920,
    n_rows: int = 1080,
    n_sets_col: int = 11,
    n_sets_row: int = 11,
    thresh_mode: str = "otsu",
    shadow_val: float = 40.0,
    contrast_val: float = 10.0,
    downsample: int = 1,
    skip_remaining_before_row: bool = False,
) -> DecodeResult:
    """NumPy (bit-exact CPU reference) decode of a [F, H, W] capture stack."""
    if texture is None:
        texture = np.repeat(frames[0][..., None], 3, axis=-1).astype(np.uint8)
    shadow, contrast = resolve_thresholds(frames, thresh_mode, shadow_val, contrast_val, np)
    return _decode_impl(
        frames, texture, shadow, contrast,
        n_cols=n_cols, n_rows=n_rows, n_sets_col=n_sets_col, n_sets_row=n_sets_row,
        downsample=downsample, xp=np,
        skip_remaining_before_row=skip_remaining_before_row,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_cols", "n_rows", "n_sets_col", "n_sets_row", "otsu_device",
                     "downsample", "skip_remaining_before_row"),
)
def _decode_jit(
    frames, texture, shadow_val, contrast_val,
    *, n_cols, n_rows, n_sets_col, n_sets_row, otsu_device, downsample,
    skip_remaining_before_row,
):
    if otsu_device:
        white_u8, diff_u8 = _white_diff_u8(frames, jnp)
        shadow = otsu_threshold(white_u8).astype(jnp.int16)
        contrast = otsu_threshold(diff_u8).astype(jnp.int16)
    else:
        shadow, contrast = shadow_val, contrast_val
    return _decode_impl(
        frames, texture, shadow, contrast,
        n_cols=n_cols, n_rows=n_rows, n_sets_col=n_sets_col, n_sets_row=n_sets_row,
        downsample=downsample, xp=jnp,
        skip_remaining_before_row=skip_remaining_before_row,
    )


def decode_stack(
    frames: jax.Array,
    texture: jax.Array | None = None,
    *,
    n_cols: int = 1920,
    n_rows: int = 1080,
    n_sets_col: int = 11,
    n_sets_row: int = 11,
    thresh_mode: str = "otsu",
    shadow_val: float = 40.0,
    contrast_val: float = 10.0,
    downsample: int = 1,
    skip_remaining_before_row: bool = False,
) -> DecodeResult:
    """JAX/TPU decode of a [F, H, W] capture stack.

    ``thresh_mode``:
      - ``"otsu"`` (default): histograms on-device, 256-bin scoring host-side in
        exact fp64 — guaranteed threshold parity with ``decode_stack_np``.
      - ``"otsu_device"``: fully fused on-device Otsu (fp32 scoring) — zero host
        sync, for jit-composed batch pipelines; near-tied histogram bins may
        pick a neighboring threshold vs the NumPy backend.
      - ``"manual"``: use ``shadow_val`` / ``contrast_val`` as given.
    """
    if texture is None:
        texture = jnp.repeat(frames[0][..., None], 3, axis=-1).astype(jnp.uint8)
    otsu_device = thresh_mode == "otsu_device"
    if thresh_mode == "otsu":
        shadow_val, contrast_val = resolve_thresholds(
            frames, "otsu", shadow_val, contrast_val, jnp
        )
    return _decode_jit(
        frames, texture,
        jnp.asarray(shadow_val, jnp.float32), jnp.asarray(contrast_val, jnp.float32),
        n_cols=n_cols, n_rows=n_rows, n_sets_col=n_sets_col, n_sets_row=n_sets_row,
        otsu_device=otsu_device, downsample=downsample,
        skip_remaining_before_row=skip_remaining_before_row,
    )


def decode_packed_np(
    planes: np.ndarray,
    white: np.ndarray,
    black: np.ndarray,
    texture: np.ndarray | None = None,
    *,
    n_frames: int,
    n_cols: int = 1920,
    n_rows: int = 1080,
    n_sets_col: int = 11,
    n_sets_row: int = 11,
    thresh_mode: str = "otsu",
    shadow_val: float = 40.0,
    contrast_val: float = 10.0,
    downsample: int = 1,
    skip_remaining_before_row: bool = False,
) -> DecodeResult:
    """NumPy decode of a packed bit-plane stack (io/images.py ``pack_stack``
    layout) — bit-identical to ``decode_stack_np`` on the raw stack the planes
    were packed from: thresholds and mask read only the verbatim white/black
    frames, and the stored bits ARE the per-pair comparisons decode computes.
    """
    if texture is None:
        texture = np.repeat(white[..., None], 3, axis=-1).astype(np.uint8)
    shadow, contrast = resolve_thresholds(
        np.stack([white, black]), thresh_mode, shadow_val, contrast_val, np)
    return _decode_packed_impl(
        planes, white, black, texture, shadow, contrast,
        n_frames=n_frames, n_cols=n_cols, n_rows=n_rows,
        n_sets_col=n_sets_col, n_sets_row=n_sets_row, downsample=downsample,
        xp=np, skip_remaining_before_row=skip_remaining_before_row,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_frames", "n_cols", "n_rows", "n_sets_col", "n_sets_row",
                     "otsu_device", "downsample", "skip_remaining_before_row"),
)
def _decode_packed_jit(
    planes, white, black, texture, shadow_val, contrast_val,
    *, n_frames, n_cols, n_rows, n_sets_col, n_sets_row, otsu_device,
    downsample, skip_remaining_before_row,
):
    if otsu_device:
        frames2 = jnp.stack([white, black])
        white_u8, diff_u8 = _white_diff_u8(frames2, jnp)
        shadow = otsu_threshold(white_u8).astype(jnp.int16)
        contrast = otsu_threshold(diff_u8).astype(jnp.int16)
    else:
        shadow, contrast = shadow_val, contrast_val
    return _decode_packed_impl(
        planes, white, black, texture, shadow, contrast,
        n_frames=n_frames, n_cols=n_cols, n_rows=n_rows,
        n_sets_col=n_sets_col, n_sets_row=n_sets_row, downsample=downsample,
        xp=jnp, skip_remaining_before_row=skip_remaining_before_row,
    )


def decode_packed(
    planes: jax.Array,
    white: jax.Array,
    black: jax.Array,
    texture: jax.Array | None = None,
    *,
    n_frames: int,
    n_cols: int = 1920,
    n_rows: int = 1080,
    n_sets_col: int = 11,
    n_sets_row: int = 11,
    thresh_mode: str = "otsu",
    shadow_val: float = 40.0,
    contrast_val: float = 10.0,
    downsample: int = 1,
    skip_remaining_before_row: bool = False,
) -> DecodeResult:
    """JAX/TPU decode of a packed bit-plane stack. Same threshold modes as
    ``decode_stack``; the stack arrives as ~8x fewer bytes (the streaming
    ingest lane's wire format) and decode runs straight from the packed bits
    — through the Pallas unpack+decode kernel when the capability probe
    admits it, the jnp twin otherwise."""
    if texture is None:
        texture = jnp.repeat(white[..., None], 3, axis=-1).astype(jnp.uint8)
    otsu_device = thresh_mode == "otsu_device"
    if thresh_mode == "otsu":
        shadow_val, contrast_val = resolve_thresholds(
            jnp.stack([white, black]), "otsu", shadow_val, contrast_val, jnp)
    return _decode_packed_jit(
        planes, white, black, texture,
        jnp.asarray(shadow_val, jnp.float32), jnp.asarray(contrast_val, jnp.float32),
        n_frames=n_frames, n_cols=n_cols, n_rows=n_rows,
        n_sets_col=n_sets_col, n_sets_row=n_sets_row, otsu_device=otsu_device,
        downsample=downsample,
        skip_remaining_before_row=skip_remaining_before_row,
    )
