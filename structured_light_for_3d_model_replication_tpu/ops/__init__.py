"""Pure array math: the TPU compute core.

Every op in this package is written as a pure function, generic over the array
namespace where practical, with a jitted JAX entry point (the TPU path) and a
NumPy entry point (the bit-exact CPU reference path selected by
``ParallelConfig.backend == "numpy"``).
"""
