"""Device-resident view fastpath: decode output -> cleaned cloud in HBM.

The batched executor's discrete drain syncs the WHOLE decode slot stack to
host ([V, H*W] slots at ~15-25% occupancy), boolean-masks each view on host,
then `_clean_arrays` re-uploads every cloud for the jitted clean chain and
syncs the masks back — three bulk round-trips per view of which two move
mostly padding. This module fuses the span: the batch's clouds are
compacted, bucket-padded, cleaned, and final-mask-compacted entirely on
device, and the ONE host sync is a single ``jax.device_get`` of the
per-view compact results (the collect/writeback boundary). The cleaned
device buffers additionally hand to the streaming registrar as-is
(``prep_view_device``), so pair prep consumes HBM-resident points without
another upload.

Byte parity with the discrete arm is BY CONSTRUCTION, not by tolerance:

  - device compaction is the stable valid-first order
    (``_compact_order_counts_jit``), which is exactly the row order host
    boolean masking produces;
  - each view's clean input is rebuilt to the identical array
    ``_clean_arrays`` would upload: the same ``_bucket_pad(n)`` bucket,
    real points in the prefix, ``1e9`` sentinel rows after, validity
    ``arange < n`` — so ``pc.clean_chain`` runs the SAME jitted program on
    the same bits and emits identical masks;
  - the final-mask selection replicates the host chain's abort-at-zero
    semantics on device: step counts are monotone non-increasing, so
    ``argmax(cnts == 0)`` IS the host loop's first-zero break index.

Gray -> RGB replication happens on host after the final slice (replicate
commutes with row masking), matching ``triangulate.compact_cloud``.
"""
from __future__ import annotations

import functools
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.models import (
    reconstruction as recon,
)
from structured_light_for_3d_model_replication_tpu.ops import pointcloud as pc

__all__ = ["fused_clean_views", "FusedView"]


@dataclass
class FusedView:
    """One cleaned view out of the fused drain: host arrays for the
    write/collect boundary plus the device-resident compact points the
    registrar's ``prep_view_device`` consumes without re-upload."""
    points: np.ndarray          # [n,3] f32, final-mask compacted
    colors: np.ndarray          # [n,3] u8 (gray replicated host-side)
    dev_points: object          # [bucket,3] f32 device array, prefix order
    count: int                  # n — valid prefix length of dev_points


@functools.partial(jax.jit, static_argnames=("bucket",))
def _gather_pad_jit(pts, cols, order, n, bucket: int):
    """Gather one view's survivors (prefix of the compaction order) into a
    ``_bucket_pad(n)`` bucket and rebuild EXACTLY the array _clean_arrays
    uploads: sentinel 1e9 rows / zero colors / ``arange < n`` validity.
    ``n`` is dynamic — no per-count retrace; the bucket is the only static
    shape key (the _view_bucket ladder keeps it bounded)."""
    take = min(bucket, pts.shape[0])
    o = order[:take]
    p = jnp.take(pts, o, axis=0)
    c = jnp.take(cols, o, axis=0)
    if bucket > take:   # view nearly full: bucket rounds past the slot count
        p = jnp.concatenate([p, jnp.zeros((bucket - take, 3), p.dtype)])
        c = jnp.concatenate(
            [c, jnp.zeros((bucket - take, c.shape[1]), c.dtype)])
    rows = jnp.arange(bucket, dtype=jnp.int32)
    p = jnp.where(rows[:, None] < n, p, jnp.float32(1e9))
    c = jnp.where(rows[:, None] < n, c, jnp.uint8(0))
    return p, c, rows < n


@jax.jit
def _select_clean_jit(pts, cols, masks, cnts):
    """Apply the chain's FINAL mask (host abort-at-zero semantics: counts
    are monotone non-increasing, so the first zero step — argmax of the
    boolean — is where the host loop breaks; otherwise the last mask) and
    compact survivors to the prefix, all on device."""
    fidx = jnp.where((cnts == 0).any(), jnp.argmax(cnts == 0),
                     masks.shape[0] - 1)
    final = masks[fidx]
    order, n2 = recon._compact_order_counts_jit(final[None])
    return (jnp.take(pts, order[0], axis=0),
            jnp.take(cols, order[0], axis=0), n2[0])


def _cache_sizes() -> dict:
    """Jit-cache sizes of the fused helpers (the no-retrace gauge tests
    pin: same bucket ladder -> stable sizes across batches)."""
    return {"gather": _gather_pad_jit._cache_size(),
            "select": _select_clean_jit._cache_size()}


def fused_clean_views(points, colors, valid, clean_cfg, steps):
    """Compact + clean + final-compact every view of one decoded batch on
    device; sync the results with ONE ``jax.device_get``.

    ``points`` [V,S,3] f32 / ``colors`` [V,S,C] u8 / ``valid`` [V,S] bool —
    a batched ``CloudResult`` still on device. Returns
    ``(views, d2h_bytes, clean_s)``: per-view :class:`FusedView`, the bulk
    device->host bytes that one sync moved, and the wall spent dispatching
    the clean-chain programs (the drain splits its lane accounting on it).
    """
    pts_v = jnp.asarray(points)
    cols_v = jnp.asarray(colors)
    val_v = jnp.asarray(valid)
    if pts_v.shape[1] > (1 << recon._COMPACT_IOTA_BITS):
        raise ValueError(
            f"fused clean supports up to 2^{recon._COMPACT_IOTA_BITS} slots "
            f"per view, got {pts_v.shape[1]}")   # caller degrades per-view
    params = pc.chain_params(clean_cfg, tuple(steps)) if steps else ()

    order_v, cnts_d = recon._compact_order_counts_jit(val_v)
    cnts = np.asarray(cnts_d).astype(int)         # one small [V] sync
    clean_s = 0.0
    staged = []
    for j in range(pts_v.shape[0]):
        n = int(cnts[j])
        bucket = recon._bucket_pad(n)             # _clean_arrays' bucket
        p_b, c_b, v_b = _gather_pad_jit(pts_v[j], cols_v[j], order_v[j],
                                        jnp.int32(n), bucket)
        if params:
            t0 = time.perf_counter()
            masks_d, cnts_step = pc.clean_chain(p_b, v_b, clean_cfg,
                                                tuple(steps))
            p_c, c_c, n2 = _select_clean_jit(p_b, c_b, masks_d, cnts_step)
            clean_s += time.perf_counter() - t0
        else:
            p_c, c_c, n2 = p_b, c_b, jnp.int32(n)
        staged.append((p_c, c_c, n2))

    host = jax.device_get(staged)                 # THE one bulk sync
    d2h = sum(int(p.nbytes + c.nbytes + np.asarray(n).nbytes)
              for p, c, n in host)
    views = []
    for (p_c, _c_c, _n2), (p_h, c_h, n2_h) in zip(staged, host):
        n2 = int(n2_h)
        p_out = np.asarray(p_h[:n2], np.float32)
        c_out = np.asarray(c_h[:n2], np.uint8)
        if c_out.ndim == 2 and c_out.shape[-1] == 1:
            c_out = np.repeat(c_out, 3, axis=1)   # compact_cloud's gray->RGB
        views.append(FusedView(p_out, c_out, p_c, n2))
    return views, d2h, clean_s
