"""Hand-written Pallas TPU kernels for the hot ops.

Three kernels where fusing beats what XLA does on its own:

  decode_fused    One pass over the [F, H, W] capture stack in VMEM tiles:
                  shadow/contrast masks, all per-bit pattern>inverse compares,
                  the Gray->binary XOR cascade and the coordinate rescale fuse
                  into a single HBM read of the stack (the reference re-reads
                  the stack per bit-plane, server/processing.py:88-111; XLA
                  fuses the compares but still materializes the [bits, H, W]
                  gray stack between the compare and the cascade).

  nn1             Tiled brute-force nearest neighbor (k=1): the ICP
                  correspondence step (processing.py:572-582's per-iteration
                  NN query). Distances via an [Bq,8]x[8,Bb] dot on the MXU,
                  running min/argmin in VMEM scratch — no sort needed, so it
                  sidesteps Mosaic's missing top_k lowering.

  radius_count    Neighbor counting for radius outlier removal
                  (processing.py:430-448): same tiling, accumulates
                  (d2 <= r^2) counts instead of minima.

Each kernel has the jnp implementation as its twin (ops/knn.py, ops/graycode
.py); `use_pallas()` gates dispatch to compiled kernels on TPU only, and the
tests run the kernels in interpreter mode on CPU for bit parity.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["use_pallas", "pallas_mode", "nn1", "radius_count_pallas",
           "decode_maps_fused", "decode_packed_maps_fused",
           "decode_packed_kernel_ok", "scan_points_fused_views",
           "slab_mean_knn", "slab_bisect_ok",
           "knn_mean", "knn_mean_np", "knn_mean_ok",
           "ransac_score", "ransac_score_np", "ransac_score_ok",
           "kernel_report"]

_FAR = 1e9

_PALLAS_MODE: str | None = None  # "compiled" | "interpret" (probe result, cached)
_VIEWS_KERNEL_OK = True          # view-batched decode lowering probe result
_PACKED_KERNEL_OK = True         # packed bit-plane decode probe result
_PACKED_VIEWS_OK = True          # view-batched packed decode probe result
_SCAN_FUSED_OK = True            # fused decode+triangulate lowering probe result
_SLAB_BISECT_OK = True           # slab bisection kernel probe result
_KNN_MEAN_OK = True              # dense knn-mean kernel probe result
_RANSAC_SCORE_OK = True          # RANSAC hypothesis-scoring kernel probe result

# candidate-count cutoff for the dense knn-mean kernel: any d2 at or below
# these f32 bits is a REAL candidate (valid rows park at _FAR, so their
# squared distances sit around 1e18 — an order of magnitude above)
_KNN_R2_BITS = int(np.float32(1e17).view(np.int32))


def slab_bisect_ok() -> bool:
    """True when the COMPILED slab bisection kernel passed its capability
    probe — i.e. False in interpret mode (the auto selector then keeps
    the topk engine; the CPU parity tests exercise the bisect arm
    explicitly via interpret)."""
    return use_pallas() and _SLAB_BISECT_OK


def scan_fused_ok() -> bool:
    """True when the fused scan kernel compiled in the capability probe
    (always True in interpret mode — tests exercise it explicitly)."""
    return use_pallas() and _SCAN_FUSED_OK


def knn_mean_ok() -> bool:
    """True when the COMPILED dense knn-mean kernel passed its capability
    probe (False in interpret mode — the outlier stage then keeps its jnp
    fallthrough; CPU parity tests run the kernel via interpret explicitly).
    ``SLSCAN_KNN_KERNEL=0`` is the operator kill switch."""
    if os.environ.get("SLSCAN_KNN_KERNEL", "").strip().lower() in (
            "0", "off", "false"):
        return False
    return use_pallas() and _KNN_MEAN_OK


def ransac_score_ok() -> bool:
    """True when the COMPILED RANSAC hypothesis-scoring kernel passed its
    capability probe. ``SLSCAN_RANSAC_KERNEL=0`` is the kill switch; the
    caller (_ransac_core) additionally rides the existing nn_mode="pallas"
    try/except, so a surprise at score time degrades to the chunked jnp
    scoring exactly like an nn1 failure does."""
    if os.environ.get("SLSCAN_RANSAC_KERNEL", "").strip().lower() in (
            "0", "off", "false"):
        return False
    return use_pallas() and _RANSAC_SCORE_OK


def decode_packed_kernel_ok() -> bool:
    """True when the COMPILED packed bit-plane decode kernel passed its
    capability probe (False in interpret mode — graycode's packed decode
    then keeps its jnp twin; CPU parity tests run the kernel via interpret
    explicitly). ``SLSCAN_PACKED_KERNEL=0`` is the operator kill switch."""
    if os.environ.get("SLSCAN_PACKED_KERNEL", "").strip().lower() in (
            "0", "off", "false"):
        return False
    return use_pallas() and _PACKED_KERNEL_OK


def kernel_report() -> dict:
    """Per-kernel capability verdicts (probe results + kill switches) —
    what `sl3d warmup` logs so an operator can see which Mosaic lowerings
    this process will actually dispatch."""
    mode = pallas_mode()
    compiled = mode == "compiled"
    return {
        "mode": mode,
        "nn1": compiled,
        "radius_count": compiled,
        "decode": compiled,
        "decode_views": compiled and _VIEWS_KERNEL_OK,
        "decode_packed": decode_packed_kernel_ok(),
        "decode_packed_views": decode_packed_kernel_ok() and _PACKED_VIEWS_OK,
        "scan_fused": scan_fused_ok(),
        "slab_bisect": slab_bisect_ok(),
        "knn_mean": knn_mean_ok(),
        "ransac_score": ransac_score_ok(),
    }


def _probe_compiled() -> bool:
    """Run each kernel on tiny inputs through the COMPILED (non-interpreter)
    Mosaic path and check the results. This is the capability gate: the
    platform NAME is not trusted — this container's TPU registers as 'axon',
    not 'tpu', and a name check would silently disable every kernel there
    (round-1 verdict item 3)."""
    try:
        q = jnp.asarray(np.arange(24, dtype=np.float32).reshape(8, 3))
        b = q + 0.25
        q8 = jnp.zeros((8, 8), jnp.float32).at[:, :3].set(q)
        b8 = jnp.zeros((128, 8), jnp.float32).at[:8, :3].set(b).at[8:, :3].set(_FAR)
        d2, idx = _nn1_call(q8, b8, 8, 128, False)
        if not np.allclose(np.asarray(d2[:8, 0]), 3 * 0.25**2, atol=1e-4):
            return False
        if not (np.asarray(idx[:8, 0]) == np.arange(8)).all():
            return False

        r2 = jnp.asarray([30.0], jnp.float32)  # chain spacing d2 = 27
        counts = _radius_call(b8, r2, 128, 128, False)
        if int(np.asarray(counts[:8, 0]).min()) < 1:
            return False

        frames = jnp.asarray(  # 10 = 2 + 2*(3 col bits + 1 row bit)
            np.tile(np.arange(256, dtype=np.uint8)[None, None, :], (10, 8, 1)))
        col, _, _ = _decode_call(frames, jnp.asarray([40.0, 10.0], jnp.float32),
                                 3, 1, 3, 1, 8, 256, False)
        if col.shape != (8, 256):
            return False
    except Exception:
        return False

    # the round-2 failure mode: under jax.vmap the kernel lowers through
    # the batching rule (custom_vmap -> the view-batched kernel); probe it
    # at a small batched shape so "probe passes, flagship crashes" cannot
    # recur. A views-kernel failure does NOT disable the other kernels —
    # the batching rule just falls back to lax.map of the single-view
    # lowering (_VIEWS_KERNEL_OK gate).
    global _VIEWS_KERNEL_OK
    try:
        colb, _, _ = _decode_call_views(
            jnp.stack([frames, frames]),
            jnp.asarray([[40.0, 10.0], [35.0, 8.0]], jnp.float32),
            3, 1, 3, 1, 8, 256, False)
        _VIEWS_KERNEL_OK = colb.shape == (2, 8, 256)
    except Exception:
        _VIEWS_KERNEL_OK = False

    # packed bit-plane decode kernel: COMPILED run on a varied small stack
    # checked bit-for-bit against the raw-stack decode kernel, then a
    # compile-only lowering at the 1080p production geometry (22 pairs ->
    # 3 plane bytes). A failure demotes only the packed fastpath — the jnp
    # packed twin in graycode._decode_packed_impl remains.
    global _PACKED_KERNEL_OK, _PACKED_VIEWS_OK
    try:
        rngq = np.random.default_rng(7)
        pstack = rngq.integers(0, 256, (10, 8, 256), dtype=np.uint8)
        pbits = (pstack[2::2].astype(np.int16)
                 > pstack[3::2].astype(np.int16)).astype(np.uint8)
        pplanes = jnp.asarray(np.packbits(pbits, axis=0, bitorder="little"))
        pthr = jnp.asarray([40.0, 10.0], jnp.float32)
        cr, rr, mr = _decode_call(jnp.asarray(pstack), pthr,
                                  3, 1, 3, 1, 8, 256, False)
        cp, rp, mp = _decode_packed_call(
            pplanes, jnp.asarray(pstack[0]), jnp.asarray(pstack[1]), pthr,
            3, 1, 3, 1, 8, 256, False)
        _PACKED_KERNEL_OK = bool(
            np.array_equal(np.asarray(cp), np.asarray(cr))
            and np.array_equal(np.asarray(rp), np.asarray(rr))
            and np.array_equal(np.asarray(mp), np.asarray(mr)))
        if _PACKED_KERNEL_OK:
            _decode_packed_call.lower(
                jax.ShapeDtypeStruct((3, 1080, 1920), jnp.uint8),
                jax.ShapeDtypeStruct((1080, 1920), jnp.uint8),
                jax.ShapeDtypeStruct((1080, 1920), jnp.uint8),
                jax.ShapeDtypeStruct((2,), jnp.float32),
                11, 11, 11, 11, 8, 128, False).compile()
    except Exception:
        _PACKED_KERNEL_OK = False
    try:
        cpv, rpv, mpv = _decode_packed_call_views(
            jnp.stack([pplanes, pplanes]),
            jnp.stack([jnp.asarray(pstack[0])] * 2),
            jnp.stack([jnp.asarray(pstack[1])] * 2),
            jnp.asarray([[40.0, 10.0], [35.0, 8.0]], jnp.float32),
            3, 1, 3, 1, 8, 256, False)
        _PACKED_VIEWS_OK = (_PACKED_KERNEL_OK and cpv.shape == (2, 8, 256)
                            and np.array_equal(np.asarray(cpv[0]),
                                               np.asarray(cp)))
    except Exception:
        _PACKED_VIEWS_OK = False

    global _SCAN_FUSED_OK
    try:
        rays = np.zeros((8, 256, 3), np.float32)
        rays[..., 2] = 1.0
        pts, valid, _ = scan_points_fused_views(
            jnp.stack([frames, frames]),
            jnp.asarray([[40.0, 10.0], [35.0, 8.0]], jnp.float32),
            rays, np.zeros(3, np.float32),
            np.asarray([[0, 0, 1, -400], [0, 0, 0, 0], [0, 0, 0, 0]],
                       np.float32),
            np.asarray([[0, 1, 0, -1], [0, 0, 0, 0], [0, 0, 0, 0]],
                       np.float32),
            2.0, n_cols=8, n_rows=2, n_use_col=3, n_use_row=1, row_mode=1,
            interpret=False)
        _SCAN_FUSED_OK = pts.shape == (2, 8 * 256, 3)
    except Exception:
        _SCAN_FUSED_OK = False

    # slab bisection kernel (the outlier engine's selector where Mosaic
    # compiles): COMPILED run on a tiny sorted line, checked numerically
    # against brute force — a lowering/rounding surprise demotes only the
    # bisect selector (topk engine remains), never the other kernels
    global _SLAB_BISECT_OK
    try:
        rngp = np.random.default_rng(0)
        line = np.sort(rngp.uniform(0, 50, 512)).astype(np.float32)
        pts3 = np.stack([line, rngp.uniform(0, 1, 512).astype(np.float32),
                         np.zeros(512, np.float32)], axis=1)
        md, cnt, _ = slab_mean_knn(jnp.asarray(pts3), 4.0, 4, tile=8,
                                   wblk=256, interpret=False)
        md = np.asarray(md)
        cnt = np.asarray(cnt)
        d = np.linalg.norm(pts3[None] - pts3[:, None], axis=-1)
        np.fill_diagonal(d, np.inf)
        ref = np.sort(d, axis=1)[:, :4].mean(axis=1)
        fin = np.isfinite(md) & (cnt >= 4)
        _SLAB_BISECT_OK = bool(fin.sum() > 50 and np.allclose(
            md[fin], ref[fin], rtol=1e-4))
        if _SLAB_BISECT_OK:
            # ALSO compile (no data, no execution) at the PRODUCTION
            # geometry (tile 64, wblk 8192): a shape-dependent Mosaic
            # failure — e.g. VMEM exhaustion on the [64, 8192] d2 blocks
            # — must demote the selector here, not crash the first merge
            # ("probe passes, flagship crashes", the round-2 lesson)
            L = 2 * 8192
            _slab_bisect_call.lower(
                jax.ShapeDtypeStruct((L, 8), jnp.float32),
                jax.ShapeDtypeStruct((2, 8, 8192), jnp.float32),
                jax.ShapeDtypeStruct((L // 64,), jnp.int32),
                20, int(np.float32(4.0).view(np.int32)), 64, 8192,
                False).compile()
    except Exception:
        _SLAB_BISECT_OK = False

    # dense knn-mean bisection kernel (the statistical-outlier stage on
    # bucket-resident clouds): COMPILED numeric check against the NumPy
    # twin, then a compile-only lowering at the production geometry —
    # a failure demotes only this kernel, the jnp fallthrough remains
    global _KNN_MEAN_OK
    try:
        rngk = np.random.default_rng(11)
        kpts = rngk.uniform(0.0, 10.0, (96, 3)).astype(np.float32)
        kval = np.ones(96, bool)
        kval[90:] = False
        kmd, kcnt = knn_mean(jnp.asarray(kpts), jnp.asarray(kval), 4,
                             interpret=False)
        rmd, rcnt = knn_mean_np(kpts, kval, 4)
        kfin = np.isfinite(rmd)
        _KNN_MEAN_OK = bool(
            kfin.sum() > 50
            and np.allclose(np.asarray(kmd)[kfin], rmd[kfin], rtol=1e-4)
            and (np.asarray(kcnt) == rcnt).all())
        if _KNN_MEAN_OK:
            Lk = 32768
            _knn_mean_call.lower(
                jax.ShapeDtypeStruct((Lk, 8), jnp.float32),
                jax.ShapeDtypeStruct((8, Lk), jnp.float32),
                20, _KNN_R2_BITS, 8, False).compile()
    except Exception:
        _KNN_MEAN_OK = False

    # RANSAC hypothesis-scoring kernel: COMPILED inlier counts must match
    # the NumPy twin (±1 borderline slot tolerated — f32 matmul rounding),
    # then compile-only at a production geometry (4096 trials x 64k pts)
    global _RANSAC_SCORE_OK
    try:
        rngr = np.random.default_rng(12)
        tn, nn = 16, 96
        rsrc = rngr.uniform(-1, 1, (nn, 3)).astype(np.float32)
        rdst = rngr.uniform(-1, 1, (nn, 3)).astype(np.float32)
        rcs9 = (rdst[:, :, None] * rsrc[:, None, :]).reshape(nn, 9)
        rR9 = rngr.uniform(-1, 1, (tn, 9)).astype(np.float32)
        rtt = rngr.uniform(-1, 1, (tn, 3)).astype(np.float32)
        rt2 = (rtt * rtt).sum(-1)
        rRt = rngr.uniform(-1, 1, (tn, 3)).astype(np.float32)
        rsc = ((rsrc * rsrc).sum(-1) + (rdst * rdst).sum(-1)).astype(
            np.float32)
        rref = ransac_score_np(rR9, rtt, rt2, rRt, rsrc, rcs9, rdst, rsc, 4.0)
        rgot = np.asarray(ransac_score(
            jnp.asarray(rR9), jnp.asarray(rtt), jnp.asarray(rt2),
            jnp.asarray(rRt), jnp.asarray(rsrc), jnp.asarray(rcs9),
            jnp.asarray(rdst), jnp.asarray(rsc), 4.0, interpret=False))
        _RANSAC_SCORE_OK = bool(rref.max() > 0
                                and np.abs(rgot - rref).max() <= 1)
        if _RANSAC_SCORE_OK:
            _ransac_score_call.lower(
                jax.ShapeDtypeStruct((4096, 16), jnp.float32),
                jax.ShapeDtypeStruct((65536, 16), jnp.float32),
                jax.ShapeDtypeStruct((1, 65536), jnp.float32),
                jax.ShapeDtypeStruct((1,), jnp.float32),
                128, 2048, False).compile()
    except Exception:
        _RANSAC_SCORE_OK = False
    return True


def pallas_mode() -> str:
    """'compiled' when the default backend compiles and runs Mosaic kernels
    correctly (probed once per process, cached); 'interpret' otherwise
    (CPU tests, or a TPU whose Mosaic path fails to compile, or the
    ``SLSCAN_PALLAS=0`` operator kill switch)."""
    global _PALLAS_MODE
    if _PALLAS_MODE is None:
        if os.environ.get("SLSCAN_PALLAS", "").strip().lower() in (
                "0", "off", "false", "interpret"):
            _PALLAS_MODE = "interpret"
            return _PALLAS_MODE
        try:
            backend = jax.default_backend()
        except Exception:  # pragma: no cover - backend init failure
            backend = "cpu"
        _PALLAS_MODE = (
            "compiled" if backend != "cpu" and _probe_compiled() else "interpret"
        )
    return _PALLAS_MODE


def use_pallas() -> bool:
    """True when compiled Mosaic kernels are available on this backend."""
    return pallas_mode() == "compiled"


def scan_fused_requested() -> bool:
    """Dispatch policy for the single-pass fused SCAN Mosaic kernel
    (scan_points_fused_views: decode + triangulate in one kernel).

    Default ON where Mosaic compiles: both r5 in-session on-chip A/Bs
    measured the fused kernel FASTER than the jnp lowering (0.1154 vs
    0.1489 s and 0.1091 vs 0.1486 s, 24 views @1080p — BENCH_NOTES.md).
    The r4 window had measured the pre-fix kernel slower (0.1747 vs
    0.1045 s); after the plane-normalization fix and the 8x128 tile
    clamp the sign flipped, consistently, within single sessions where
    tunnel variance cancels. ``SLSCAN_PALLAS=0`` (the same kill switch
    that forces interpret mode) disables it; ``1``/``force`` requests it
    explicitly (bench uses the override arg instead to A/B both)."""
    env = os.environ.get("SLSCAN_PALLAS", "").strip().lower()
    if env in ("0", "off", "false", "interpret"):
        return False
    if env in ("1", "on", "true", "force", "fused"):
        return True
    return use_pallas()


def _interpret() -> bool:
    return not use_pallas()


# ---------------------------------------------------------------------------
# nn1: tiled brute-force 1-nearest-neighbor
# ---------------------------------------------------------------------------

def _nn1_kernel(q_ref, b_ref, d_ref, i_ref, *, block_b: int, n_base: int):
    """One query block vs all base blocks. q_ref [Bq, 8], b_ref [Nb, 8]
    (xyz padded with zeros); outputs d2 [Bq, 1] f32, idx [Bq, 1] i32."""
    q = q_ref[:]
    q2 = (q * q).sum(axis=1, keepdims=True)           # [Bq, 1]
    nb = n_base // block_b

    def body(bi, carry):
        best_d, best_i = carry
        b = b_ref[pl.ds(bi * block_b, block_b), :]    # [Bb, 8]
        cross = jax.lax.dot_general(
            q, b, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,  # full f32: the d2 expansion
        )                                         # cancels catastrophically in bf16
        b2 = (b * b).sum(axis=1)[None, :]             # [1, Bb]
        d2 = q2 + b2 - 2.0 * cross
        blk_d = jnp.min(d2, axis=1, keepdims=True)    # [Bq, 1]
        blk_a = jnp.argmin(d2, axis=1).astype(jnp.int32)[:, None]
        blk_i = blk_a + bi * block_b
        better = blk_d < best_d
        return (jnp.where(better, blk_d, best_d),
                jnp.where(better, blk_i, best_i))

    init = (jnp.full(q2.shape, jnp.inf, jnp.float32),
            jnp.zeros(q2.shape, jnp.int32))
    best_d, best_i = jax.lax.fori_loop(0, nb, body, init)
    d_ref[:] = jnp.maximum(best_d, 0.0)
    i_ref[:] = best_i


def _pad8(points, valid, n_pad):
    """[N,3]+mask -> [n_pad, 8] with invalid/padded rows parked far away."""
    pts = jnp.where(valid[:, None], points.astype(jnp.float32),
                    jnp.float32(_FAR))
    n = pts.shape[0]
    out = jnp.zeros((n_pad, 8), jnp.float32)
    out = out.at[:n, :3].set(pts)
    if n_pad > n:
        out = out.at[n:, :3].set(_FAR)
    return out


@functools.partial(jax.jit, static_argnames=("block_q", "block_b", "interpret"))
def _nn1_call(q8, b8, block_q: int, block_b: int, interpret: bool):
    nq_pad = q8.shape[0]
    nb_pad = b8.shape[0]
    grid = (nq_pad // block_q,)
    d2, idx = pl.pallas_call(
        functools.partial(_nn1_kernel, block_b=block_b, n_base=nb_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, 8), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nb_pad, 8), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((block_q, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_q, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nq_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((nq_pad, 1), jnp.int32),
        ),
        interpret=interpret,
    )(q8, b8)
    return d2, idx


def nn1(queries, base, base_valid=None, block_q: int = 1024,
        block_b: int = 1024):
    """Nearest valid base point for every query. Returns (idx [N] i32,
    d2 [N] f32). Exact brute force; invalid base rows never match."""
    queries = jnp.asarray(queries, jnp.float32)
    base = jnp.asarray(base, jnp.float32)
    if base_valid is None:
        base_valid = jnp.ones(base.shape[0], bool)
    nq = queries.shape[0]
    nb = base.shape[0]
    block_q = min(block_q, max(8, 1 << (nq - 1).bit_length()))
    block_b = min(block_b, max(128, 1 << (nb - 1).bit_length()))
    nq_pad = -(-nq // block_q) * block_q
    nb_pad = -(-nb // block_b) * block_b
    q8 = _pad8(queries, jnp.ones(nq, bool), nq_pad)
    b8 = _pad8(base, base_valid, nb_pad)
    _, idx = _nn1_call(q8, b8, block_q, block_b, _interpret())
    idx = idx[:nq, 0]
    # exact-distance recompute against the same parked coordinates the
    # kernel saw (b8: invalid/padded rows sit at _FAR) — see knn.exact_d2
    # for why the kernel's expansion d2 must not be reported
    from structured_light_for_3d_model_replication_tpu.ops.knn import exact_d2
    return idx, exact_d2(queries, b8[:, :3], idx)


# ---------------------------------------------------------------------------
# radius_count: neighbor counting
# ---------------------------------------------------------------------------

def _radius_kernel(q_ref, b_ref, r2_ref, c_ref, *, block_b: int, n_base: int,
                   block_q: int):
    q = q_ref[:]
    q2 = (q * q).sum(axis=1, keepdims=True)
    r2 = r2_ref[0]
    qi = pl.program_id(0)
    nb = n_base // block_b

    def body(bi, count):
        b = b_ref[pl.ds(bi * block_b, block_b), :]
        cross = jax.lax.dot_general(
            q, b, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        b2 = (b * b).sum(axis=1)[None, :]
        d2 = q2 + b2 - 2.0 * cross
        within = d2 <= r2
        # self-exclusion by global index equality
        qidx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_b), 0)
        bidx = bi * block_b + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_b), 1)
        within &= qidx != bidx
        return count + within.sum(axis=1, keepdims=True, dtype=jnp.int32)

    c_ref[:] = jax.lax.fori_loop(0, nb, body,
                                 jnp.zeros((q.shape[0], 1), jnp.int32))


@functools.partial(jax.jit, static_argnames=("block_q", "block_b", "interpret"))
def _radius_call(q8, radius2, block_q: int, block_b: int, interpret: bool):
    n_pad = q8.shape[0]
    grid = (n_pad // block_q,)
    counts = pl.pallas_call(
        functools.partial(_radius_kernel, block_b=block_b, n_base=n_pad,
                          block_q=block_q),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, 8), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_pad, 8), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block_q, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        interpret=interpret,
    )(q8, q8, radius2)
    return counts


def radius_count_pallas(points, valid, radius, block_q: int = 1024,
                        block_b: int = 1024):
    """Count of valid points within ``radius`` of each point (self excluded).
    Twin of ops/knn.radius_count's brute path."""
    points = jnp.asarray(points, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], bool)
    n = points.shape[0]
    block_q = min(block_q, max(8, 1 << (n - 1).bit_length()))
    block_b = block_q
    n_pad = -(-n // block_q) * block_q
    p8 = _pad8(points, valid, n_pad)
    r2 = jnp.asarray([jnp.float32(radius) ** 2], jnp.float32)
    counts = _radius_call(p8, r2, block_q, block_b, _interpret())
    return counts[:n, 0]


# ---------------------------------------------------------------------------
# decode_maps_fused: Gray decode in one pass over the frame stack
# ---------------------------------------------------------------------------

def _decode_tile(read_frame, shadow, contrast, *, n_bits_col: int,
                 n_bits_row: int, n_use_col: int, n_use_row: int):
    """Shared tile math: bit compares, Gray->binary XOR cascade, rescale
    shift, and the shadow+contrast mask — all on one VMEM-resident tile.
    ``read_frame(i)`` returns frame i of the tile as int32."""
    # Mosaic lacks a direct u8->f32 cast; widen through int32 first
    white = read_frame(0).astype(jnp.float32)
    black = read_frame(1).astype(jnp.float32)
    mask = (white > shadow) & ((white - black) > contrast)

    def decode_axis(start, n_bits, n_use):
        shape = white.shape
        binary = jnp.zeros(shape, jnp.int32)
        gray_prev = jnp.zeros(shape, jnp.int32)
        for b in range(n_use):  # static unroll: n_use <= 11
            img_p = read_frame(start + 2 * b)
            img_i = read_frame(start + 2 * b + 1)
            g = (img_p > img_i).astype(jnp.int32)
            bit = gray_prev ^ g          # XOR cascade: binary bit from gray
            binary = (binary << 1) | bit
            gray_prev = bit
        return binary << (n_bits - n_use)  # coordinate rescale

    col = decode_axis(2, n_bits_col, n_use_col)
    row = decode_axis(2 + 2 * n_bits_col, n_bits_row, n_use_row)
    return col, row, mask


def _decode_kernel(frames_ref, thr_ref, col_ref, row_ref, mask_ref, *,
                   n_bits_col: int, n_bits_row: int, n_use_col: int,
                   n_use_row: int):
    """frames_ref [F, th, tw] u8 tile; thr_ref [2] f32 (shadow, contrast)."""
    col, row, mask = _decode_tile(
        lambda i: frames_ref[i].astype(jnp.int32), thr_ref[0], thr_ref[1],
        n_bits_col=n_bits_col, n_bits_row=n_bits_row, n_use_col=n_use_col,
        n_use_row=n_use_row)
    col_ref[:] = col
    row_ref[:] = row
    mask_ref[:] = mask


def _decode_kernel_views(frames_ref, thr_ref, col_ref, row_ref, mask_ref, *,
                         n_bits_col: int, n_bits_row: int, n_use_col: int,
                         n_use_row: int):
    """View-batched twin: frames_ref [1, F, th, tw] u8 (one view per grid
    step along axis 0); thr_ref [V, 2] f32 lives whole in SMEM and is indexed
    by the view grid coordinate — per-view thresholds enter through
    program_id instead of picking up a vmap batch dimension (the round-2
    Mosaic lowering failure: SMEM operands cannot be batched)."""
    v = pl.program_id(0)
    col, row, mask = _decode_tile(
        lambda i: frames_ref[0, i].astype(jnp.int32),
        thr_ref[v, 0], thr_ref[v, 1],
        n_bits_col=n_bits_col, n_bits_row=n_bits_row, n_use_col=n_use_col,
        n_use_row=n_use_row)
    col_ref[0] = col
    row_ref[0] = row
    mask_ref[0] = mask


@functools.partial(jax.jit, static_argnames=(
    "n_bits_col", "n_bits_row", "n_use_col", "n_use_row", "tile_h", "tile_w",
    "interpret"))
def _decode_call(frames, thr, n_bits_col: int, n_bits_row: int,
                 n_use_col: int, n_use_row: int, tile_h: int, tile_w: int,
                 interpret: bool):
    f, h, w = frames.shape
    grid = (h // tile_h, w // tile_w)
    col, row, mask = pl.pallas_call(
        functools.partial(_decode_kernel, n_bits_col=n_bits_col,
                          n_bits_row=n_bits_row, n_use_col=n_use_col,
                          n_use_row=n_use_row),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f, tile_h, tile_w), lambda i, j: (0, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((tile_h, tile_w), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_h, tile_w), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_h, tile_w), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((h, w), jnp.int32),
            jax.ShapeDtypeStruct((h, w), jnp.int32),
            jax.ShapeDtypeStruct((h, w), jnp.bool_),
        ),
        interpret=interpret,
    )(frames, thr)
    return col, row, mask


@functools.partial(jax.jit, static_argnames=(
    "n_bits_col", "n_bits_row", "n_use_col", "n_use_row", "tile_h", "tile_w",
    "interpret"))
def _decode_call_views(frames, thr, n_bits_col: int, n_bits_row: int,
                       n_use_col: int, n_use_row: int, tile_h: int,
                       tile_w: int, interpret: bool):
    v, f, h, w = frames.shape
    grid = (v, h // tile_h, w // tile_w)
    col, row, mask = pl.pallas_call(
        functools.partial(_decode_kernel_views, n_bits_col=n_bits_col,
                          n_bits_row=n_bits_row, n_use_col=n_use_col,
                          n_use_row=n_use_row),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, f, tile_h, tile_w), lambda v, i, j: (v, 0, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),  # thr [V,2] whole in SMEM
        ],
        out_specs=(
            pl.BlockSpec((1, tile_h, tile_w), lambda v, i, j: (v, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_h, tile_w), lambda v, i, j: (v, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_h, tile_w), lambda v, i, j: (v, i, j),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((v, h, w), jnp.int32),
            jax.ShapeDtypeStruct((v, h, w), jnp.int32),
            jax.ShapeDtypeStruct((v, h, w), jnp.bool_),
        ),
        interpret=interpret,
    )(frames, thr)
    return col, row, mask


@functools.lru_cache(maxsize=None)
def _decode_caller(n_bits_col: int, n_bits_row: int, n_use_col: int,
                   n_use_row: int, tile_h: int, tile_w: int, interpret: bool):
    """custom_vmap wrapper: a plain call runs the single-view kernel; a
    ``jax.vmap`` over views dispatches to the natively view-batched kernel
    (grid axis over views, SMEM thresholds indexed per view) instead of
    Mosaic's generic batching rule, which rejects batched SMEM operands."""

    @jax.custom_batching.custom_vmap
    def call(frames, thr):
        return _decode_call(frames, thr, n_bits_col, n_bits_row, n_use_col,
                            n_use_row, tile_h, tile_w, interpret)

    @call.def_vmap
    def _batched(axis_size, in_batched, frames, thr):
        frames_b, thr_b = in_batched
        if not frames_b:
            frames = jnp.broadcast_to(frames[None],
                                      (axis_size,) + frames.shape)
        if not thr_b:
            thr = jnp.broadcast_to(thr[None], (axis_size, 2))
        if _VIEWS_KERNEL_OK:
            out = _decode_call_views(frames, thr, n_bits_col, n_bits_row,
                                     n_use_col, n_use_row, tile_h, tile_w,
                                     interpret)
        else:  # views lowering unavailable: serialize over the single-view
            out = jax.lax.map(
                lambda ft: _decode_call(ft[0], ft[1], n_bits_col, n_bits_row,
                                        n_use_col, n_use_row, tile_h, tile_w,
                                        interpret),
                (frames, thr))
        return out, (True, True, True)

    return call


def _scan_fused_kernel(frames_ref, thr_ref, sc_ref, rx_ref, ry_ref, rz_ref,
                       px_ref, py_ref, pz_ref, valid_ref, tex_ref, *,
                       n_bits_col: int, n_bits_row: int, n_use_col: int,
                       n_use_row: int, n_cols: int, n_rows: int,
                       row_mode: int, downsample: int):
    """Whole scan forward on one VMEM tile: Gray decode + quadratic light-
    plane evaluation + ray-plane intersection + epipolar filter, ONE read of
    the [F, th, tw] frame stack, no [H, W] intermediates in HBM.

    Fuses the two hot stages of the reference pipeline
    (server/processing.py:28-124 decode, :127-207 triangulate modes 0/1)
    that even XLA keeps as separate HBM-materialized maps.

    Scalar layout sc_ref (SMEM f32[32]): oc xyz @0..2, epipolar_tol @3,
    poly_col A/B/C rows @4..15, poly_row A/B/C rows @16..27 (each 3x4
    row-major: n4(i) = A + B i + C i^2, calib.geometry
    plane_poly_coefficients).
    """
    v = pl.program_id(0)
    col, row, mask = _decode_tile(
        lambda i: frames_ref[0, i].astype(jnp.int32),
        thr_ref[v, 0], thr_ref[v, 1],
        n_bits_col=n_bits_col, n_bits_row=n_bits_row, n_use_col=n_use_col,
        n_use_row=n_use_row)
    ox = sc_ref[0]
    oy = sc_ref[1]
    oz = sc_ref[2]
    eps = sc_ref[3]
    rx = rx_ref[...]
    ry = ry_ref[...]
    rz = rz_ref[...]

    def poly_plane(idx, n_planes, base):
        i = jnp.clip(idx * downsample, 0, n_planes - 1).astype(jnp.float32)
        comps = []
        for c in range(4):
            a = sc_ref[base + c]
            b = sc_ref[base + 4 + c]
            q = sc_ref[base + 8 + c]
            comps.append(a + i * (b + i * q))
        nx, ny, nz, d = comps
        # direct sqrt+divide, NOT lax.rsqrt: the TPU VPU's rsqrt is a
        # coarser approximation, and this normalization was the one
        # primitive where the fused kernel diverged from the jnp lowering
        # (r4 bench: 0.064 mm chamfer vs the jnp path's 1.3e-4). Divides
        # (not reciprocal-multiply) reproduce _poly_planes' p/nrm
        # expression rounding-for-rounding
        nrm = jnp.sqrt(jnp.maximum(nx * nx + ny * ny + nz * nz, 1e-30))
        return nx / nrm, ny / nrm, nz / nrm, d / nrm

    nx, ny, nz, d = poly_plane(col, n_cols, 4)
    denom = nx * rx + ny * ry + nz * rz
    numer = nx * ox + ny * oy + nz * oz + d
    ok = jnp.abs(denom) > 1e-6
    t = jnp.where(ok, -numer / jnp.where(ok, denom, 1.0), 0.0)
    px = ox + rx * t
    py = oy + ry * t
    pz = oz + rz * t
    valid = mask & ok
    if row_mode == 1:
        mx, my, mz, dr = poly_plane(row, n_rows, 16)
        dist = jnp.abs(mx * px + my * py + mz * pz + dr)
        valid = valid & (dist < eps)

    px_ref[0] = px
    py_ref[0] = py
    pz_ref[0] = pz
    valid_ref[0] = valid
    tex_ref[0] = frames_ref[0, 0]


@functools.partial(jax.jit, static_argnames=(
    "n_bits_col", "n_bits_row", "n_use_col", "n_use_row", "n_cols", "n_rows",
    "row_mode", "downsample", "tile_h", "tile_w", "interpret"))
def _scan_fused_call(frames_v, thr_v, scalars, rx, ry, rz, *,
                     n_bits_col: int, n_bits_row: int, n_use_col: int,
                     n_use_row: int, n_cols: int, n_rows: int, row_mode: int,
                     downsample: int, tile_h: int, tile_w: int,
                     interpret: bool):
    v, f, h, w = frames_v.shape
    grid = (v, h // tile_h, w // tile_w)
    hw_spec = pl.BlockSpec((tile_h, tile_w), lambda v, i, j: (i, j),
                           memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((1, tile_h, tile_w), lambda v, i, j: (v, i, j),
                            memory_space=pltpu.VMEM)
    out2 = jax.ShapeDtypeStruct((v, h, w), jnp.float32)
    px, py, pz, valid, tex = pl.pallas_call(
        functools.partial(_scan_fused_kernel, n_bits_col=n_bits_col,
                          n_bits_row=n_bits_row, n_use_col=n_use_col,
                          n_use_row=n_use_row, n_cols=n_cols, n_rows=n_rows,
                          row_mode=row_mode, downsample=downsample),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, f, tile_h, tile_w), lambda v, i, j: (v, 0, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),   # thr [V,2]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # scalars [32]
            hw_spec, hw_spec, hw_spec,               # rays x/y/z [H,W]
        ],
        out_specs=(out_spec, out_spec, out_spec, out_spec, out_spec),
        out_shape=(out2, out2, out2,
                   jax.ShapeDtypeStruct((v, h, w), jnp.bool_),
                   jax.ShapeDtypeStruct((v, h, w), jnp.uint8)),
        interpret=interpret,
    )(frames_v, thr_v, scalars, rx, ry, rz)
    return px, py, pz, valid, tex


def scan_points_fused_views(frames_v, thr_v, rays_hw3, oc, poly_col, poly_row,
                            epipolar_tol, *, n_cols: int, n_rows: int,
                            n_use_col: int, n_use_row: int, row_mode: int,
                            downsample: int = 1, tile_h: int = 8,
                            tile_w: int = 256, interpret: bool | None = None):
    """Fused capture-stack -> 3D points for a [V, F, H, W] uint8 batch.

    Returns (points [V, H*W, 3] f32, valid [V, H*W] bool, tex [V, H*W] u8).
    Quadratic (gather-free) plane evaluation only; row_mode 0 or 1.
    """
    frames_v = jnp.asarray(frames_v)
    vb, f, h, w = frames_v.shape
    while h % tile_h:
        tile_h //= 2
    while w % tile_w:
        tile_w //= 2
    nbc = max(1, int(np.ceil(np.log2(n_cols // downsample))))
    nbr = max(1, int(np.ceil(np.log2(n_rows // downsample))))
    scalars = jnp.concatenate([
        jnp.asarray(oc, jnp.float32).reshape(3),
        jnp.asarray(epipolar_tol, jnp.float32).reshape(1),
        jnp.asarray(poly_col, jnp.float32).reshape(12),
        jnp.asarray(poly_row, jnp.float32).reshape(12),
        jnp.zeros(4, jnp.float32),
    ])
    rays = jnp.asarray(rays_hw3, jnp.float32)
    itp = _interpret() if interpret is None else interpret
    px, py, pz, valid, tex = _scan_fused_call(
        frames_v, jnp.asarray(thr_v, jnp.float32), scalars,
        rays[..., 0], rays[..., 1], rays[..., 2],
        n_bits_col=nbc, n_bits_row=nbr,
        n_use_col=max(1, min(n_use_col, nbc)),
        n_use_row=max(1, min(n_use_row, nbr)),
        n_cols=n_cols, n_rows=n_rows, row_mode=row_mode,
        downsample=downsample, tile_h=tile_h, tile_w=tile_w, interpret=itp)
    pts = jnp.stack([px, py, pz], axis=-1).reshape(vb, h * w, 3)
    return pts, valid.reshape(vb, h * w), tex.reshape(vb, h * w)


def decode_maps_fused(frames, shadow, contrast, *, n_bits_col: int,
                      n_bits_row: int, n_use_col: int, n_use_row: int,
                      tile_h: int = 8, tile_w: int = 256,
                      interpret: bool | None = None):
    """Fused col/row/mask decode of a [F, H, W] uint8 stack.

    Equivalent to ops/graycode._decode_impl's map computation (manual
    thresholds); H and W must divide by the tile (1080p does: 1080 = 135*8,
    1920 = 7.5*256 -> use tile_w=128 there). vmap-safe over views (one
    level): the batched call lowers to the view-batched kernel.
    """
    frames = jnp.asarray(frames)
    f, h, w = frames.shape
    while h % tile_h:
        tile_h //= 2
    while w % tile_w:
        tile_w //= 2
    thr = jnp.stack([jnp.asarray(shadow, jnp.float32),
                     jnp.asarray(contrast, jnp.float32)])
    itp = _interpret() if interpret is None else interpret
    call = _decode_caller(n_bits_col, n_bits_row, n_use_col, n_use_row,
                          tile_h, tile_w, itp)
    return call(frames, thr)


# ---------------------------------------------------------------------------
# decode_packed_maps_fused: unpack + Gray decode straight from bit-planes
# ---------------------------------------------------------------------------

def _decode_packed_tile(read_plane_byte, white_i32, black_i32, shadow,
                        contrast, *, n_bits_col: int, n_bits_row: int,
                        n_use_col: int, n_use_row: int):
    """Packed twin of :func:`_decode_tile`: the per-pair ``pattern > inverse``
    compare is replaced by a shift-and-mask bit extraction from the packed
    planes (io/images.py layout: pair p at byte p//8, bit p%8), feeding the
    identical XOR cascade and rescale shift. ``read_plane_byte(k)`` returns
    plane-byte k of the tile as int32; the plane index arithmetic is static
    (unrolled loop), so consecutive bits of one byte share a single VMEM read.
    """
    white = white_i32.astype(jnp.float32)
    black = black_i32.astype(jnp.float32)
    mask = (white > shadow) & ((white - black) > contrast)

    def decode_axis(pair_start, n_bits, n_use):
        shape = white.shape
        binary = jnp.zeros(shape, jnp.int32)
        gray_prev = jnp.zeros(shape, jnp.int32)
        for b in range(n_use):  # static unroll: n_use <= 11
            p = pair_start + b
            g = (read_plane_byte(p >> 3) >> (p & 7)) & 1
            bit = gray_prev ^ g
            binary = (binary << 1) | bit
            gray_prev = bit
        return binary << (n_bits - n_use)

    col = decode_axis(0, n_bits_col, n_use_col)
    row = decode_axis(n_bits_col, n_bits_row, n_use_row)
    return col, row, mask


def _decode_packed_kernel(planes_ref, white_ref, black_ref, thr_ref, col_ref,
                          row_ref, mask_ref, *, n_bits_col: int,
                          n_bits_row: int, n_use_col: int, n_use_row: int):
    """planes_ref [Pb, th, tw] u8; white/black_ref [th, tw] u8; thr_ref [2]."""
    col, row, mask = _decode_packed_tile(
        lambda k: planes_ref[k].astype(jnp.int32),
        white_ref[...].astype(jnp.int32), black_ref[...].astype(jnp.int32),
        thr_ref[0], thr_ref[1],
        n_bits_col=n_bits_col, n_bits_row=n_bits_row, n_use_col=n_use_col,
        n_use_row=n_use_row)
    col_ref[:] = col
    row_ref[:] = row
    mask_ref[:] = mask


def _decode_packed_kernel_views(planes_ref, white_ref, black_ref, thr_ref,
                                col_ref, row_ref, mask_ref, *,
                                n_bits_col: int, n_bits_row: int,
                                n_use_col: int, n_use_row: int):
    """View-batched twin; thr [V, 2] whole in SMEM, indexed by the view grid
    coordinate (same SMEM-can't-batch workaround as _decode_kernel_views)."""
    v = pl.program_id(0)
    col, row, mask = _decode_packed_tile(
        lambda k: planes_ref[0, k].astype(jnp.int32),
        white_ref[0].astype(jnp.int32), black_ref[0].astype(jnp.int32),
        thr_ref[v, 0], thr_ref[v, 1],
        n_bits_col=n_bits_col, n_bits_row=n_bits_row, n_use_col=n_use_col,
        n_use_row=n_use_row)
    col_ref[0] = col
    row_ref[0] = row
    mask_ref[0] = mask


@functools.partial(jax.jit, static_argnames=(
    "n_bits_col", "n_bits_row", "n_use_col", "n_use_row", "tile_h", "tile_w",
    "interpret"))
def _decode_packed_call(planes, white, black, thr, n_bits_col: int,
                        n_bits_row: int, n_use_col: int, n_use_row: int,
                        tile_h: int, tile_w: int, interpret: bool):
    pb, h, w = planes.shape
    grid = (h // tile_h, w // tile_w)
    hw_spec = pl.BlockSpec((tile_h, tile_w), lambda i, j: (i, j),
                           memory_space=pltpu.VMEM)
    col, row, mask = pl.pallas_call(
        functools.partial(_decode_packed_kernel, n_bits_col=n_bits_col,
                          n_bits_row=n_bits_row, n_use_col=n_use_col,
                          n_use_row=n_use_row),
        grid=grid,
        in_specs=[
            pl.BlockSpec((pb, tile_h, tile_w), lambda i, j: (0, i, j),
                         memory_space=pltpu.VMEM),
            hw_spec, hw_spec,
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(hw_spec, hw_spec, hw_spec),
        out_shape=(
            jax.ShapeDtypeStruct((h, w), jnp.int32),
            jax.ShapeDtypeStruct((h, w), jnp.int32),
            jax.ShapeDtypeStruct((h, w), jnp.bool_),
        ),
        interpret=interpret,
    )(planes, white, black, thr)
    return col, row, mask


@functools.partial(jax.jit, static_argnames=(
    "n_bits_col", "n_bits_row", "n_use_col", "n_use_row", "tile_h", "tile_w",
    "interpret"))
def _decode_packed_call_views(planes, white, black, thr, n_bits_col: int,
                              n_bits_row: int, n_use_col: int, n_use_row: int,
                              tile_h: int, tile_w: int, interpret: bool):
    v, pb, h, w = planes.shape
    grid = (v, h // tile_h, w // tile_w)
    hw_spec = pl.BlockSpec((1, tile_h, tile_w), lambda v, i, j: (v, i, j),
                           memory_space=pltpu.VMEM)
    col, row, mask = pl.pallas_call(
        functools.partial(_decode_packed_kernel_views, n_bits_col=n_bits_col,
                          n_bits_row=n_bits_row, n_use_col=n_use_col,
                          n_use_row=n_use_row),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, pb, tile_h, tile_w), lambda v, i, j: (v, 0, i, j),
                         memory_space=pltpu.VMEM),
            hw_spec, hw_spec,
            pl.BlockSpec(memory_space=pltpu.SMEM),  # thr [V,2] whole in SMEM
        ],
        out_specs=(hw_spec, hw_spec, hw_spec),
        out_shape=(
            jax.ShapeDtypeStruct((v, h, w), jnp.int32),
            jax.ShapeDtypeStruct((v, h, w), jnp.int32),
            jax.ShapeDtypeStruct((v, h, w), jnp.bool_),
        ),
        interpret=interpret,
    )(planes, white, black, thr)
    return col, row, mask


@functools.lru_cache(maxsize=None)
def _decode_packed_caller(n_bits_col: int, n_bits_row: int, n_use_col: int,
                          n_use_row: int, tile_h: int, tile_w: int,
                          interpret: bool):
    """custom_vmap wrapper, same construction as _decode_caller: vmap over
    views dispatches the natively view-batched packed kernel instead of
    Mosaic's generic batching rule (which rejects batched SMEM operands)."""

    @jax.custom_batching.custom_vmap
    def call(planes, white, black, thr):
        return _decode_packed_call(planes, white, black, thr, n_bits_col,
                                   n_bits_row, n_use_col, n_use_row, tile_h,
                                   tile_w, interpret)

    @call.def_vmap
    def _batched(axis_size, in_batched, planes, white, black, thr):
        pb, wb, bb, tb = in_batched
        if not pb:
            planes = jnp.broadcast_to(planes[None],
                                      (axis_size,) + planes.shape)
        if not wb:
            white = jnp.broadcast_to(white[None], (axis_size,) + white.shape)
        if not bb:
            black = jnp.broadcast_to(black[None], (axis_size,) + black.shape)
        if not tb:
            thr = jnp.broadcast_to(thr[None], (axis_size, 2))
        if _PACKED_VIEWS_OK:
            out = _decode_packed_call_views(planes, white, black, thr,
                                            n_bits_col, n_bits_row, n_use_col,
                                            n_use_row, tile_h, tile_w,
                                            interpret)
        else:  # views lowering unavailable: serialize the single-view kernel
            out = jax.lax.map(
                lambda t: _decode_packed_call(t[0], t[1], t[2], t[3],
                                              n_bits_col, n_bits_row,
                                              n_use_col, n_use_row, tile_h,
                                              tile_w, interpret),
                (planes, white, black, thr))
        return out, (True, True, True)

    return call


def decode_packed_maps_fused(planes, white, black, shadow, contrast, *,
                             n_bits_col: int, n_bits_row: int, n_use_col: int,
                             n_use_row: int, tile_h: int = 8,
                             tile_w: int = 256,
                             interpret: bool | None = None):
    """Fused col/row/mask decode straight from a packed bit-plane stack
    (planes u8 [ceil(P/8), H, W] + white/black u8 [H, W], the io/images.py
    pack layout). The stack never exists unpacked anywhere — HBM holds the
    ~8x-smaller planes and the kernel extracts bits in VMEM. Bit-exact twin
    of ops/graycode._decode_packed_impl's jnp arithmetic; vmap-safe over
    views (one level) via the view-batched kernel."""
    planes = jnp.asarray(planes)
    pb, h, w = planes.shape
    while h % tile_h:
        tile_h //= 2
    while w % tile_w:
        tile_w //= 2
    thr = jnp.stack([jnp.asarray(shadow, jnp.float32),
                     jnp.asarray(contrast, jnp.float32)])
    itp = _interpret() if interpret is None else interpret
    call = _decode_packed_caller(n_bits_col, n_bits_row, n_use_col, n_use_row,
                                 tile_h, tile_w, itp)
    return call(planes, jnp.asarray(white), jnp.asarray(black), thr)


# ---------------------------------------------------------------------------
# slab_mean_knn: fused slab-window mean-of-k-NN for the outlier engine
# ---------------------------------------------------------------------------

def _slab_bisect_kernel(s_ref, q_ref, c0_ref, c1_ref, m_ref, n_ref, *,
                        k: int, r2_bits: int, tile: int, wblk: int,
                        n_iters: int):
    """Mean distance to the k nearest candidates, exactly, without a sort.

    One program = ``tile`` consecutive sorted queries vs a 2*wblk-wide
    aligned candidate window (two half-window refs picked by the
    prefetched per-tile block index ``s_ref``). Distances are computed by
    coordinate DIFFERENCES (the package's exact_d2 policy — no MXU
    expansion, no cancellation) and stay in VMEM; the k-th order
    statistic comes from integer bisection on the f32 bit pattern
    (monotone for non-negative floats), which is EXACT in <= 31 passes;
    the mean is then one masked sum plus the tie-count correction
    (k - #strictly-smaller) * sqrt(t) — identical to a top_k selection's
    mean under any tie-breaking, because tied values are equal.

    q_ref [tile, 8] f32; c0/c1_ref [1, 8, wblk] f32 (coords in sublanes;
    the leading block axis walks wblk-aligned window blocks);
    outputs: m_ref [tile, 1] f32 mean, n_ref [tile, 1] i32 count(<= r^2).
    """
    pid = pl.program_id(0)
    sblk = s_ref[pid]
    q = q_ref[...]

    def half_d2i(c_ref, blk_idx):
        d2 = jnp.zeros((tile, wblk), jnp.float32)
        for d in range(3):
            qd = q[:, d][:, None]                    # [tile, 1]
            cd = c_ref[0, d, :][None, :]             # [1, wblk]
            diff = qd - cd
            d2 = d2 + diff * diff
        d2i = jax.lax.bitcast_convert_type(jnp.maximum(d2, 0.0), jnp.int32)
        # self-exclusion by GLOBAL sorted index, not a distance test
        qg = pid * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
        cg = (blk_idx * wblk
              + jax.lax.broadcasted_iota(jnp.int32, (1, wblk), 1))
        return jnp.where(cg == qg, jnp.int32(2**31 - 2), d2i)

    a = half_d2i(c0_ref, sblk)
    b = half_d2i(c1_ref, sblk + 1)
    r2b = jnp.int32(r2_bits)
    cnt_ok = ((a <= r2b).astype(jnp.int32).sum(axis=1, keepdims=True)
              + (b <= r2b).astype(jnp.int32).sum(axis=1, keepdims=True))

    def body(_, c):
        lo, hi = c
        mid = lo + (hi - lo) // 2
        cnt = ((a <= mid).astype(jnp.int32).sum(axis=1, keepdims=True)
               + (b <= mid).astype(jnp.int32).sum(axis=1, keepdims=True))
        ge = cnt >= k
        return jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi)

    lo = jnp.zeros((tile, 1), jnp.int32)
    hi = jnp.full((tile, 1), r2b + 1, jnp.int32)
    lo, hi = jax.lax.fori_loop(0, n_iters, body, (lo, hi))
    t = hi                                           # k-th smallest bits

    def half_sum(d2i):
        lt = d2i < t
        dist = jnp.sqrt(jax.lax.bitcast_convert_type(d2i, jnp.float32))
        s = jnp.where(lt, dist, 0.0).sum(axis=1, keepdims=True)
        return s, lt.astype(jnp.int32).sum(axis=1, keepdims=True)

    sa, ca = half_sum(a)
    sb, cb = half_sum(b)
    tf = jax.lax.bitcast_convert_type(t, jnp.float32)
    mean = (sa + sb + (k - ca - cb).astype(jnp.float32)
            * jnp.sqrt(tf)) / jnp.float32(k)
    m_ref[...] = mean
    n_ref[...] = cnt_ok


@functools.partial(jax.jit, static_argnames=("k", "r2_bits", "tile", "wblk",
                                             "interpret"))
def _slab_bisect_call(q8, ptsW, starts_blk, k: int, r2_bits: int, tile: int,
                      wblk: int, interpret: bool):
    L = q8.shape[0]
    grid = (L // tile,)
    nblk = ptsW.shape[0]
    spec_c = lambda off: pl.BlockSpec(
        (1, 8, wblk), lambda i, s: (jnp.minimum(s[i] + off, nblk - 1), 0, 0),
        memory_space=pltpu.VMEM)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, 8), lambda i, s: (i, 0),
                         memory_space=pltpu.VMEM),
            spec_c(0),
            spec_c(1),
        ],
        out_specs=(
            pl.BlockSpec((tile, 1), lambda i, s: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i, s: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
    )
    mean, cnt = pl.pallas_call(
        functools.partial(_slab_bisect_kernel, k=k, r2_bits=r2_bits,
                          tile=tile, wblk=wblk, n_iters=31),
        grid_spec=gs,
        out_shape=(jax.ShapeDtypeStruct((L, 1), jnp.float32),
                   jax.ShapeDtypeStruct((L, 1), jnp.int32)),
        interpret=interpret,
    )(starts_blk, q8, ptsW, ptsW)
    return mean[:, 0], cnt[:, 0]


def slab_mean_knn(pts_sorted, r: float, k: int, tile: int = 128,
                  wblk: int = 8192, interpret: bool | None = None):
    """Slab-window mean-of-k-NN over an x-sorted padded cloud [L, 3]
    (invalid rows parked at the far sentinel, L a multiple of ``tile``
    and of ``wblk``). Returns (mean_d [L] f32, cnt_ok [L] i32,
    win_end [L] i32): rows are certified by the CALLER as
    cnt_ok >= k (k-th neighbor within r) plus its window-coverage test
    using win_end (exclusive end slot of the aligned candidate window).

    The engine behind statistical_outlier_mask's accelerator arm when
    Mosaic is available: it replaces the [tile, window] HBM distance
    blocks + lax.top_k sort of the jnp slab engine with VMEM-resident
    bisection (see _slab_bisect_kernel)."""
    if wblk % tile:
        raise ValueError(
            f"tile ({tile}) must divide wblk ({wblk}): the grid walks "
            f"L//tile query tiles and L pads to wblk multiples — a "
            f"non-dividing tile leaves trailing query rows unwritten")
    L = pts_sorted.shape[0]
    x = pts_sorted[:, 0]
    r32 = np.float32(r)
    r2_bits = int(np.float32(r32 * r32).view(np.int32))
    nblk = L // wblk
    first_x = x[jnp.arange(L // tile, dtype=jnp.int32) * tile]
    a = jnp.searchsorted(x, first_x - r32).astype(jnp.int32)
    starts_blk = jnp.minimum(a // wblk, max(nblk - 2, 0)).astype(jnp.int32)
    q8 = jnp.zeros((L, 8), jnp.float32).at[:, :3].set(pts_sorted)
    # [nblk, 8, wblk]: Mosaic needs the BLOCK's last two dims (8, wblk)
    # tile-aligned; the leading axis walks wblk-aligned window blocks
    ptsW = jnp.transpose(q8, (1, 0)).reshape(8, nblk, wblk).transpose(1, 0, 2)
    itp = _interpret() if interpret is None else interpret
    mean, cnt = _slab_bisect_call(q8, ptsW, starts_blk, k, r2_bits, tile,
                                  wblk, itp)
    win_end = jnp.repeat((starts_blk + 2) * wblk, tile)
    return mean, cnt, win_end


def _kernel_event(name: str, **fields):
    """Trace-time kernel marker: fires once per (re)trace/launch from the
    host-side wrapper, so the run journal records WHICH kernels a program
    took without touching the traced computation. Best-effort — telemetry
    disabled or absent is never an error on the hot path."""
    try:
        from structured_light_for_3d_model_replication_tpu.utils import (
            telemetry,
        )
        tr = telemetry.current()
        if tr is not None:
            tr.instant(name, **fields)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# knn_mean: dense all-pairs mean-of-k-NN (bucket-resident clouds)
# ---------------------------------------------------------------------------

def _knn_mean_kernel(q_ref, b_ref, m_ref, n_ref, *, k: int, r2_bits: int,
                     tile: int, n_base: int, n_iters: int):
    """Exact mean distance to the k nearest candidates, no sort, no window.

    The dense sibling of _slab_bisect_kernel for the bucket-resident clean
    chain (clouds <= 32k slots fit whole in VMEM): one program = ``tile``
    queries vs ALL candidates. Distances by coordinate DIFFERENCES (the
    package's exact_d2 policy — never the MXU expansion), the k-th order
    statistic by integer bisection on the f32 bit pattern (exact in <= 31
    passes), the mean as one masked sum plus the tie correction.

    q_ref [tile, 8] f32; b_ref [8, n_base] f32 (coords in sublanes,
    candidates along lanes); outputs m_ref [tile, 1] f32 mean,
    n_ref [tile, 1] i32 count of real candidates (d2 <= r2_bits — valid
    rows park at _FAR, so their d2 sits an order of magnitude above).
    """
    pid = pl.program_id(0)
    q = q_ref[...]
    d2 = jnp.zeros((tile, n_base), jnp.float32)
    for d in range(3):
        qd = q[:, d][:, None]                        # [tile, 1]
        cd = b_ref[d, :][None, :]                    # [1, n_base]
        diff = qd - cd
        d2 = d2 + diff * diff
    d2i = jax.lax.bitcast_convert_type(jnp.maximum(d2, 0.0), jnp.int32)
    # self-exclusion by GLOBAL index, not a distance test
    qg = pid * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
    cg = jax.lax.broadcasted_iota(jnp.int32, (1, n_base), 1)
    d2i = jnp.where(cg == qg, jnp.int32(2**31 - 2), d2i)
    r2b = jnp.int32(r2_bits)
    cnt_ok = (d2i <= r2b).astype(jnp.int32).sum(axis=1, keepdims=True)

    def body(_, c):
        lo, hi = c
        mid = lo + (hi - lo) // 2
        cnt = (d2i <= mid).astype(jnp.int32).sum(axis=1, keepdims=True)
        ge = cnt >= k
        return jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi)

    lo = jnp.zeros((tile, 1), jnp.int32)
    hi = jnp.full((tile, 1), r2b + 1, jnp.int32)
    lo, hi = jax.lax.fori_loop(0, n_iters, body, (lo, hi))
    t = hi                                           # k-th smallest bits
    lt = d2i < t
    dist = jnp.sqrt(jax.lax.bitcast_convert_type(d2i, jnp.float32))
    s = jnp.where(lt, dist, 0.0).sum(axis=1, keepdims=True)
    c_lt = lt.astype(jnp.int32).sum(axis=1, keepdims=True)
    tf = jax.lax.bitcast_convert_type(t, jnp.float32)
    m_ref[...] = (s + (k - c_lt).astype(jnp.float32)
                  * jnp.sqrt(tf)) / jnp.float32(k)
    n_ref[...] = cnt_ok


@functools.partial(jax.jit, static_argnames=("k", "r2_bits", "tile",
                                             "interpret"))
def _knn_mean_call(q8, b8t, k: int, r2_bits: int, tile: int, interpret: bool):
    L = q8.shape[0]
    grid = (L // tile,)
    mean, cnt = pl.pallas_call(
        functools.partial(_knn_mean_kernel, k=k, r2_bits=r2_bits, tile=tile,
                          n_base=L, n_iters=31),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, 8), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, L), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((tile, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(jax.ShapeDtypeStruct((L, 1), jnp.float32),
                   jax.ShapeDtypeStruct((L, 1), jnp.int32)),
        interpret=interpret,
    )(q8, b8t)
    return mean[:, 0], cnt[:, 0]


def knn_mean(points, valid, k: int, tile: int = 8,
             interpret: bool | None = None):
    """Mean distance to the k nearest VALID neighbors of every point (self
    excluded), exact, via dense all-pairs bisection (_knn_mean_kernel).

    Returns (mean_d [N] f32 — +inf where the point is invalid or has fewer
    than k valid neighbors, cnt [N] i32 — valid candidates seen). The
    engine behind statistical_outlier_mask's kernel arm on bucket-resident
    clouds; callable traced (inside the fused clean chain) or eagerly."""
    points = jnp.asarray(points, jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], bool)
    valid = jnp.asarray(valid)
    n = points.shape[0]
    L = -(-max(n, 1) // 128) * 128
    q8 = _pad8(points, valid, L)
    b8t = q8.T                                       # [8, L]
    itp = _interpret() if interpret is None else interpret
    _kernel_event("kernel.knn_mean", n=int(n), k=int(k),
                  compiled=not itp,
                  traced=isinstance(points, jax.core.Tracer))
    mean, cnt = _knn_mean_call(q8, b8t, int(k), _KNN_R2_BITS, tile, itp)
    mean = mean[:n]
    # invalid rows all park at the SAME far coordinate, so they see each
    # other (and the pad slots) at distance zero — zero their counts, they
    # carry no signal and the mean is masked to +inf regardless
    cnt = jnp.where(valid, cnt[:n], 0)
    return jnp.where(valid & (cnt >= k), mean, jnp.inf), cnt


def knn_mean_np(points, valid, k: int):
    """NumPy numeric twin of ``knn_mean`` (same parking, same cutoff)."""
    pts = np.asarray(points, np.float32)
    if valid is None:
        valid = np.ones(len(pts), bool)
    val = np.asarray(valid, bool)
    p = np.where(val[:, None], pts, np.float32(_FAR))
    d2 = ((p[None, :, :] - p[:, None, :]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    cnt = np.where(val, (d2 <= np.float32(1e17)).sum(axis=1), 0).astype(
        np.int32)
    nd = np.sqrt(np.sort(d2, axis=1)[:, :k])
    mean = nd.mean(axis=1).astype(np.float32)
    return np.where(val & (cnt >= k), mean, np.inf).astype(np.float32), cnt


# ---------------------------------------------------------------------------
# ransac_score: hypothesis inlier counting for the RANSAC core
# ---------------------------------------------------------------------------

def _ransac_score_kernel(h_ref, p_ref, sc_ref, md2_ref, o_ref):
    """Inlier counts for a block of rigid-transform hypotheses.

    The centered-coordinate d2 expansion of _ransac_core folds into ONE
    MXU matmul: with H[t] = [Rt, -R9, -tt, t2/2] and P[n] = [src_c, cs9,
    dst_cc, 1] (both 16-wide), d2[t, n] = sc[n] + 2 * (H @ P^T)[t, n],
    where sc[n] = s2 + c2 for live correspondences and +inf for dead ones
    (so they can never count). The output block is revisited along the
    innermost grid axis — @pl.when(j == 0) zeroes it, every j accumulates.

    h_ref [bt, 16] f32; p_ref [bp, 16] f32; sc_ref [1, bp] f32;
    md2_ref [1] f32 in SMEM; o_ref [bt, 1] i32.
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    cross = jax.lax.dot_general(
        h_ref[...], p_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    d2 = sc_ref[0, :][None, :] + 2.0 * cross
    inl = d2 <= md2_ref[0]
    o_ref[...] += inl.sum(axis=1, keepdims=True).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("block_t", "block_p",
                                             "interpret"))
def _ransac_score_call(hM, pM, sc, md2, block_t: int, block_p: int,
                       interpret: bool):
    t_pad = hM.shape[0]
    n_pad = pM.shape[0]
    grid = (t_pad // block_t, n_pad // block_p)
    counts = pl.pallas_call(
        _ransac_score_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, 16), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_p, 16), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_p), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block_t, 1), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t_pad, 1), jnp.int32),
        interpret=interpret,
    )(hM, pM, sc, md2)
    return counts[:, 0]


def ransac_score(R9, tt, t2, Rt, src_c, cs9, dst_cc, sc, max_dist2,
                 block_t: int = 128, block_p: int = 2048,
                 interpret: bool | None = None):
    """Inlier counts [T] i32 for T hypotheses against N correspondences.

    Inputs are _ransac_core's scoring prelude, verbatim: R9 [T,9] rotation
    rows, tt [T,3] effective translation, t2 [T] its square norm, Rt [T,3]
    R^T t, src_c/dst_cc [N,3] centered coordinates, cs9 [N,9] their outer
    products, sc [N] = s2+c2 with +inf at dead correspondences, max_dist2
    the inlier threshold (squared). Padded hypothesis rows are sliced off;
    padded correspondence slots carry sc=+inf so they never count."""
    R9 = jnp.asarray(R9, jnp.float32)
    t = R9.shape[0]
    n = src_c.shape[0]
    hM = jnp.concatenate([
        jnp.asarray(Rt, jnp.float32), -R9,
        -jnp.asarray(tt, jnp.float32),
        0.5 * jnp.asarray(t2, jnp.float32)[:, None]], axis=1)
    pM = jnp.concatenate([
        jnp.asarray(src_c, jnp.float32), jnp.asarray(cs9, jnp.float32),
        jnp.asarray(dst_cc, jnp.float32),
        jnp.ones((n, 1), jnp.float32)], axis=1)
    block_t = min(block_t, max(8, 1 << (max(t, 1) - 1).bit_length()))
    block_p = min(block_p, max(128, 1 << (max(n, 1) - 1).bit_length()))
    t_pad = -(-t // block_t) * block_t
    n_pad = -(-n // block_p) * block_p
    hM = jnp.zeros((t_pad, 16), jnp.float32).at[:t].set(hM)
    pM = jnp.zeros((n_pad, 16), jnp.float32).at[:n].set(pM)
    scp = jnp.full((1, n_pad), jnp.inf, jnp.float32).at[0, :n].set(
        jnp.asarray(sc, jnp.float32))
    md2 = jnp.asarray(max_dist2, jnp.float32).reshape(1)
    itp = _interpret() if interpret is None else interpret
    _kernel_event("kernel.ransac_score", trials=int(t), n=int(n),
                  compiled=not itp,
                  traced=isinstance(R9, jax.core.Tracer))
    return _ransac_score_call(hM, pM, scp, md2, block_t, block_p, itp)[:t]


def ransac_score_np(R9, tt, t2, Rt, src_c, cs9, dst_cc, sc, max_dist2):
    """NumPy numeric twin of ``ransac_score`` (same single-matmul fold)."""
    hM = np.concatenate([
        np.asarray(Rt, np.float32), -np.asarray(R9, np.float32),
        -np.asarray(tt, np.float32),
        0.5 * np.asarray(t2, np.float32)[:, None]], axis=1)
    pM = np.concatenate([
        np.asarray(src_c, np.float32), np.asarray(cs9, np.float32),
        np.asarray(dst_cc, np.float32),
        np.ones((len(src_c), 1), np.float32)], axis=1)
    d2 = np.asarray(sc, np.float32)[None, :] + 2.0 * (hM @ pM.T)
    return (d2 <= np.float32(max_dist2)).sum(axis=-1).astype(np.int32)
