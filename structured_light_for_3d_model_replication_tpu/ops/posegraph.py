"""SE(3) pose-graph optimization for multiway registration.

Capability parity: the reference's robust 360-degree merge builds a pose graph
over the turntable views — sequential odometry edges plus a first<->last loop
closure — and runs Open3D's Levenberg-Marquardt global optimization
(Old/360Merge.py:50-78, Old/new360Merge.py:96-130). That solver is a C++
sparse LM; here the graph is tiny (24 nodes x 6 dof) so the TPU-native design
is a DENSE Gauss-Newton/LM iteration built from batched SE(3) ops: all edge
residuals and Jacobian blocks are computed vmapped, scattered into the
[6N, 6N] normal matrix, and solved with one Cholesky per iteration inside
``lax.scan`` — fixed shapes, fixed iteration count, no data-dependent control
flow.

Conventions: poses are world-from-view 4x4 matrices; edge (i, j, Z) measures
view-i-from-view-j (points_j mapped into frame i). Residual per edge:
``Log(Z^-1 · T_i^-1 · T_j)`` with right-multiplicative perturbations
``T <- T · exp(xi)`` and the small-residual Jacobian approximation
``dr/dxi_j = I``, ``dr/dxi_i = -Ad(E^-1)`` — standard g2o-style linearization.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# all SE(3) contractions pinned: TPU default matmul precision is bf16-class
# (eps ~4e-3), far too coarse for pose algebra at millimeter targets (the
# same slop measurably broke kabsch orthogonality in ops/registration.py)
_MM = jax.lax.Precision.HIGHEST


def _mm(a, b):
    return jnp.matmul(a, b, precision=_MM)

__all__ = ["exp_se3", "log_se3", "adjoint_se3", "optimize_pose_graph",
           "PoseGraphResult"]


def _skew(v):
    z = jnp.zeros_like(v[..., 0])
    return jnp.stack([
        jnp.stack([z, -v[..., 2], v[..., 1]], -1),
        jnp.stack([v[..., 2], z, -v[..., 0]], -1),
        jnp.stack([-v[..., 1], v[..., 0], z], -1),
    ], -2)


def exp_se3(xi):
    """xi = [w(3), v(3)] -> 4x4. Batched over leading dims."""
    w, v = xi[..., :3], xi[..., 3:]
    theta2 = (w * w).sum(-1)[..., None, None]
    theta = jnp.sqrt(theta2 + 1e-24)
    k = _skew(w)
    k2 = _mm(k, k)
    eye = jnp.eye(3, dtype=xi.dtype)
    # closed-form with small-angle-safe coefficients
    a = jnp.sin(theta) / theta
    b = (1 - jnp.cos(theta)) / theta2.clip(1e-24)
    c = (theta - jnp.sin(theta)) / (theta2.clip(1e-24) * theta)
    small = theta2[..., 0, 0] < 1e-12
    a = jnp.where(small[..., None, None], 1.0, a)
    b = jnp.where(small[..., None, None], 0.5, b)
    c = jnp.where(small[..., None, None], 1.0 / 6.0, c)
    R = eye + a * k + b * k2
    V = eye + b * k + c * k2
    t = jnp.einsum("...ij,...j->...i", V, v, precision=_MM)
    bot = jnp.broadcast_to(jnp.asarray([0, 0, 0, 1], xi.dtype),
                           R.shape[:-2] + (1, 4))
    return jnp.concatenate(
        [jnp.concatenate([R, t[..., :, None]], -1), bot], -2)


def _log_so3(R):
    """Rotation matrix -> axis-angle, batched; safe at 0 and near pi."""
    tr = R[..., 0, 0] + R[..., 1, 1] + R[..., 2, 2]
    cos = jnp.clip((tr - 1) / 2, -1.0, 1.0)
    theta = jnp.arccos(cos)
    ax = jnp.stack([R[..., 2, 1] - R[..., 1, 2],
                    R[..., 0, 2] - R[..., 2, 0],
                    R[..., 1, 0] - R[..., 0, 1]], -1)
    s = jnp.maximum(2 * jnp.sin(theta), 1e-12)[..., None]
    w_generic = ax * (theta[..., None] / s)
    # near pi: axis from the diagonal of (R + I)/2
    diag = jnp.stack([R[..., 0, 0], R[..., 1, 1], R[..., 2, 2]], -1)
    axis2 = jnp.clip((diag + 1) / 2, 0, 1)
    axis = jnp.sqrt(axis2)
    # fix signs from off-diagonals
    sx = jnp.where(R[..., 2, 1] - R[..., 1, 2] >= 0, 1.0, -1.0)
    sy = jnp.where(R[..., 0, 2] - R[..., 2, 0] >= 0, 1.0, -1.0)
    sz = jnp.where(R[..., 1, 0] - R[..., 0, 1] >= 0, 1.0, -1.0)
    axis = axis * jnp.stack([sx, sy, sz], -1)
    nrm = jnp.maximum(jnp.linalg.norm(axis, axis=-1, keepdims=True), 1e-12)
    w_pi = axis / nrm * theta[..., None]
    near_pi = (jnp.pi - theta) < 1e-3
    w = jnp.where(near_pi[..., None], w_pi, w_generic)
    return jnp.where((theta < 1e-7)[..., None], ax / 2, w)


def log_se3(T):
    """4x4 -> xi = [w, v], batched."""
    R = T[..., :3, :3]
    t = T[..., :3, 3]
    w = _log_so3(R)
    theta2 = (w * w).sum(-1)[..., None, None]
    theta = jnp.sqrt(theta2 + 1e-24)
    k = _skew(w)
    k2 = _mm(k, k)
    eye = jnp.eye(3, dtype=T.dtype)
    b = (1 - jnp.cos(theta)) / theta2.clip(1e-24)
    c = (theta - jnp.sin(theta)) / (theta2.clip(1e-24) * theta)
    small = theta2[..., 0, 0] < 1e-12
    b = jnp.where(small[..., None, None], 0.5, b)
    c = jnp.where(small[..., None, None], 1.0 / 6.0, c)
    V = eye + b * k + c * k2
    v = jnp.linalg.solve(V, t[..., :, None])[..., 0]
    return jnp.concatenate([w, v], -1)


def adjoint_se3(T):
    """6x6 adjoint of a 4x4 pose (w-then-v twist ordering), batched."""
    R = T[..., :3, :3]
    t = T[..., :3, 3]
    z = jnp.zeros_like(R)
    top = jnp.concatenate([R, z], -1)
    bot = jnp.concatenate([_mm(_skew(t), R), R], -1)
    return jnp.concatenate([top, bot], -2)


class PoseGraphResult(NamedTuple):
    poses: jax.Array          # [N, 4, 4] optimized world-from-view
    residual_rmse: jax.Array  # [iters] per-iteration edge residual RMS
    initial_rmse: jax.Array


@functools.partial(jax.jit, static_argnames=("iters",))
def _optimize_jit(poses0, ei, ej, Z, w_edge, iters: int, damping):
    n = poses0.shape[0]
    Zinv = jnp.linalg.inv(Z)

    def residuals(poses):
        Ti_inv = jnp.linalg.inv(poses[ei])
        E = _mm(_mm(Zinv, Ti_inv), poses[ej])
        return log_se3(E), E

    def gn_step(poses, _):
        r, E = residuals(poses)                     # [E,6], [E,4,4]
        # right-perturbation T_i <- T_i exp(xi_i) gives E <- E exp(-Ad(A^-1) xi_i)
        # with A = T_i^-1 T_j, so dr/dxi_i = -Ad(A^-1); dr/dxi_j = +I
        A_inv = _mm(jnp.linalg.inv(poses[ej]), poses[ei])
        Ji = -adjoint_se3(A_inv)                    # [E,6,6]
        wgt = w_edge[:, None]
        # normal equations over stacked 6-dof blocks; node 0 held fixed by
        # masking its block to identity
        H = jnp.zeros((n * 6, n * 6), poses.dtype)
        g = jnp.zeros((n * 6,), poses.dtype)

        eye6 = jnp.eye(6, dtype=poses.dtype)
        JiT_Ji = jnp.einsum("eki,e,ekj->eij", Ji, w_edge, Ji, precision=_MM)
        JiT_Jj = jnp.einsum("eki,e->eik", Ji, w_edge)      # Ji^T W I
        JjT_Jj = w_edge[:, None, None] * eye6
        JiT_r = jnp.einsum("eki,ek->ei", Ji, w_edge[:, None] * r * 1.0,
                           precision=_MM)
        JjT_r = wgt * r

        def scatter_block(H, rows, cols, blocks):
            # rows/cols: [E] node ids; blocks: [E,6,6]
            ri = rows[:, None] * 6 + jnp.arange(6)[None, :]
            ci = cols[:, None] * 6 + jnp.arange(6)[None, :]
            return H.at[ri[:, :, None], ci[:, None, :]].add(blocks)

        H = scatter_block(H, ei, ei, JiT_Ji)
        H = scatter_block(H, ei, ej, JiT_Jj)
        H = scatter_block(H, ej, ei, jnp.swapaxes(JiT_Jj, -1, -2))
        H = scatter_block(H, ej, ej, JjT_Jj)
        g = g.at[(ei[:, None] * 6 + jnp.arange(6)[None, :])].add(-JiT_r)
        g = g.at[(ej[:, None] * 6 + jnp.arange(6)[None, :])].add(-JjT_r)

        # gauge fix: clamp node 0 (its 6x6 block -> large diagonal)
        anchor = jnp.zeros(n * 6, poses.dtype).at[:6].set(1e12)
        H = H + jnp.diag(anchor) + damping * jnp.eye(n * 6, dtype=poses.dtype)
        xi = jnp.linalg.solve(H, g).reshape(n, 6)
        poses_new = _mm(poses, exp_se3(xi))
        r_new, _ = residuals(poses_new)   # residual AFTER this update
        rmse = jnp.sqrt((w_edge * (r_new * r_new).sum(-1)).sum()
                        / jnp.maximum(w_edge.sum(), 1e-9))
        return poses_new, rmse

    r0, _ = residuals(poses0)
    rmse0 = jnp.sqrt((w_edge * (r0 * r0).sum(-1)).sum()
                     / jnp.maximum(w_edge.sum(), 1e-9))
    poses, rmse_hist = jax.lax.scan(gn_step, poses0, None, length=iters)
    return poses, rmse_hist, rmse0


def optimize_pose_graph(init_poses, edges_i, edges_j, edge_transforms,
                        edge_weights=None, iters: int = 20,
                        damping: float = 1e-6) -> PoseGraphResult:
    """Globally optimize world-from-view poses against relative-pose edges.

    init_poses: [N,4,4]; edges_{i,j}: int arrays [E]; edge_transforms: [E,4,4]
    measuring frame-i-from-frame-j; edge_weights: [E] information weights
    (e.g. registration fitness). Node 0 is the gauge anchor.
    """
    poses0 = jnp.asarray(init_poses, jnp.float32)
    ei = jnp.asarray(edges_i, jnp.int32)
    ej = jnp.asarray(edges_j, jnp.int32)
    Z = jnp.asarray(edge_transforms, jnp.float32)
    w = jnp.ones(ei.shape[0], jnp.float32) if edge_weights is None \
        else jnp.asarray(edge_weights, jnp.float32)
    poses, hist, rmse0 = _optimize_jit(poses0, ei, ej, Z, w, iters,
                                       jnp.float32(damping))
    # re-orthonormalize rotations after accumulated float updates
    u, _, vt = jnp.linalg.svd(poses[:, :3, :3])
    Rn = _mm(u, vt)
    poses = poses.at[:, :3, :3].set(Rn)
    return PoseGraphResult(poses, hist, rmse0)
