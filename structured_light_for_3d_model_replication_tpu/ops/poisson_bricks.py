"""Brick-refined screened Poisson: the depth-11..16 envelope, single chip.

The reference's octree Poisson accepts depth up to 16
(server/processing.py:697-709) because its cost scales with the SURFACE
(octree nodes concentrate at samples); a dense grid pays (2^d)^3 volume
everywhere and caps at depth 9 on one chip / depth 10 slab-sharded
(ops/poisson.py, ops/poisson_sharded.py). This module recovers the
octree's surface-scaling on TPU terms — fixed shapes, batched bricks, no
pointer chasing:

  1. solve the GLOBAL problem dense at ``base_depth`` (<= 9) — the
     cascadic-multigrid coarse pass that fixes the far field;
  2. mark the fine-level bricks (``brick``^3 cells) that contain samples
     — their count scales with surface area, not volume;
  3. refine each active brick locally: splat the fine RHS from the
     brick's samples, initialize from the trilinearly-upsampled coarse
     chi, and run projected CG with the outer shell FROZEN at the coarse
     solution (Dirichlet). All bricks solve as one vmapped batch of
     identical [D,D,D] stencil programs (D = brick + 2*halo); refined
     fields stream to host per batch, so device memory is one batch,
     host memory ~ active_bricks * D^3 * 4 B.
  4. extract the iso-surface per brick (interior + one overlap ring) and
     weld the duplicate boundary vertices/faces.

The refinement is cascadic (one coarse->fine pass, frozen boundaries),
NOT a global fine solve: chi seams across brick boundaries are bounded by
the coarse solve's accuracy there (the far field is smooth, and samples
near a boundary sit in BOTH bricks' halos). poisson_bricks is validated
against the dense solver where both exist (iso-surface agreement at
depth <= 9) and is the only reachable path for depth >= 11.
"""
from __future__ import annotations

import contextlib
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from structured_light_for_3d_model_replication_tpu.ops import poisson as dense
from structured_light_for_3d_model_replication_tpu.ops import surface_nets

__all__ = ["poisson_solve_bricks", "extract_surface_bricks",
           "BrickPoissonResult"]


class BrickPoissonResult(NamedTuple):
    chi: np.ndarray       # [B, D, D, D] refined local fields (host)
    brick_lo: np.ndarray  # [B, 3] fine-cell index of each DOMAIN corner
    n_bricks: int
    iso: float            # iso level (mean refined chi at the samples)
    origin: np.ndarray    # [3] world position of fine voxel (0,0,0) CENTER
    cell: float           # fine voxel size
    depth: int
    brick: int
    halo: int
    coarse: dense.PoissonResult   # the base dense solve (far field)


def _fine_grid_params(points, valid, depth: int, margin: float):
    """Mirror ops/poisson._poisson_jit's bounding-box convention (f32) so
    the coarse and fine grids are nested."""
    pts = np.asarray(points, np.float32)
    val = np.asarray(valid, bool)
    lo = pts[val].min(axis=0)
    hi = pts[val].max(axis=0)
    extent = np.float32((hi - lo).max() * (1.0 + 2.0 * margin))
    g = 1 << depth
    cell = np.float32(extent / g)
    origin = (0.5 * (lo + hi) - 0.5 * extent).astype(np.float32)
    return origin, cell, g


@functools.partial(jax.jit, static_argnames=("D", "brick", "halo",
                                             "cg_iters"))
def _refine_bricks_jit(pts_b, nrm_b, ok_b, lo_b, chi_c, origin, cell,
                       factor, screen, D: int, brick: int, halo: int,
                       cg_iters: int):
    """Refine a batch of bricks. pts_b [B, P, 3] world points assigned to
    each brick's dilated domain, ok_b [B, P] validity, lo_b [B, 3] the
    fine-cell index of each DOMAIN corner (interior lo - halo). Returns
    (chi_f [B, D, D, D], iso_sum [B], iso_cnt [B]) — the iso terms count
    each sample once, in the brick whose INTERIOR owns its cell."""

    def one(pts, nrm, ok, lo):
        w = ok.astype(jnp.float32)[:, None]
        # local fractional coords in the brick domain (cell-center space)
        coords = (pts - origin) / cell - 0.5 - lo.astype(jnp.float32)
        coords = jnp.where(ok[:, None], coords, -10.0)
        splat = dense._trilinear_scatter(
            (D, D, D), coords, jnp.concatenate([nrm * w, w], axis=-1))
        vfield, density = splat[..., :3], splat[..., 3]
        div = jnp.zeros((D, D, D), jnp.float32)
        for axis in range(3):
            f = vfield[..., axis]
            fwd = jnp.roll(f, -1, axis)
            bwd = jnp.roll(f, 1, axis)
            i0 = [slice(None)] * 3
            i0[axis] = -1
            fwd = fwd.at[tuple(i0)].set(f[tuple(i0)])
            i1 = [slice(None)] * 3
            i1[axis] = 0
            bwd = bwd.at[tuple(i1)].set(f[tuple(i1)])
            div = div + 0.5 * (fwd - bwd)

        # initial/boundary field: coarse chi upsampled at local fine cells
        ii = jnp.arange(D, dtype=jnp.float32)
        axes = [(lo[a] + ii + 0.5) / factor - 0.5 for a in range(3)]
        cc = jnp.stack(jnp.meshgrid(*axes, indexing="ij"),
                       axis=-1).reshape(-1, 3)
        x0 = dense.trilinear_sample(chi_c, cc).reshape(D, D, D)

        # projected CG: the one-cell outer shell stays at the coarse
        # solution (Dirichlet); the interior relaxes against the local RHS
        interior = jnp.zeros((D, D, D), bool).at[1:-1, 1:-1, 1:-1].set(True)
        wgt = density / jnp.maximum(density.max(), 1e-12)

        def a_mul(x):
            return -dense._laplacian(x) + screen * wgt * x

        b = jnp.where(interior, -div - a_mul(x0), 0.0)

        def cg_step(state, _):
            x, r, p, rs = state
            ap = jnp.where(interior, a_mul(p), 0.0)
            alpha = rs / jnp.maximum((p * ap).sum(), 1e-20)
            x = x + alpha * p
            r = r - alpha * ap
            rs_new = (r * r).sum()
            beta = rs_new / jnp.maximum(rs, 1e-20)
            p = jnp.where(interior, r + beta * p, 0.0)
            return (x, r, p, rs_new), None

        r0 = b
        state0 = (jnp.zeros_like(b), r0, jnp.where(interior, r0, 0.0),
                  (r0 * r0).sum())
        (dx, _, _, _), _ = jax.lax.scan(cg_step, state0, None,
                                        length=cg_iters)
        chi_f = x0 + jnp.where(interior, dx, 0.0)

        # iso contribution: refined chi at samples whose CELL lies in the
        # interior brick (interiors partition the grid -> each sample
        # counts exactly once across the batch loop)
        cells = jnp.floor(coords).astype(jnp.int32)
        owned = ok & ((cells >= halo) & (cells < halo + brick)).all(axis=1)
        chi_at = dense.trilinear_sample(chi_f, coords)
        return (chi_f, jnp.where(owned, chi_at, 0.0).sum(),
                owned.astype(jnp.float32).sum())

    return jax.vmap(one)(pts_b, nrm_b, ok_b, lo_b)


def poisson_solve_bricks(points, normals, valid=None, depth: int = 11,
                         base_depth: int = 9, brick: int = 32,
                         halo: int = 8, cg_iters: int = 120,
                         base_cg_iters: int = 350, screen: float = 4.0,
                         margin: float = 0.08, batch: int = 32,
                         max_points_per_brick: int = 8192,
                         log=lambda m: None) -> BrickPoissonResult:
    """Screened Poisson at depth 11..16 via dense-base + brick refinement.

    Cost scales with ACTIVE BRICKS (surface area at brick granularity),
    not (2^depth)^3 — the dense-grid envelope's TPU-native answer to the
    reference's octree depths (processing.py:697-709). Samples beyond
    ``max_points_per_brick`` in one brick's domain are dropped from that
    brick's RHS (density-cap spirit; raise the cap for pathological
    densities)."""
    if depth <= base_depth:
        raise ValueError(f"depth {depth} <= base_depth {base_depth}: use "
                         f"ops/poisson.poisson_solve directly")
    if depth > 16:
        raise ValueError("depth > 16 rejected (the reference's own guard, "
                         "processing.py:697-699)")
    if halo < 2:
        raise ValueError(f"halo {halo} < 2: the stitched extraction needs "
                         f"one ring below and two above the interior")
    pts = np.asarray(points, np.float32)
    nrm = np.asarray(normals, np.float32)
    val = (np.ones(len(pts), bool) if valid is None
           else np.asarray(valid, bool))
    if not val.any():
        raise ValueError("no valid samples")
    base_depth = min(base_depth, 9)

    coarse = dense.poisson_solve(pts, nrm, val, depth=base_depth,
                                 cg_iters=base_cg_iters, screen=screen,
                                 margin=margin)
    origin, cell, g = _fine_grid_params(pts, val, depth, margin)
    factor = float(g >> base_depth)

    D = brick + 2 * halo
    pts_v, nrm_v = pts[val], nrm[val]
    cidx = np.floor((pts_v - origin) / cell - 0.5).astype(np.int64)
    nb = g // brick
    bid = np.clip(cidx // brick, 0, nb - 1)
    uniq = np.unique(bid[:, 0] * nb * nb + bid[:, 1] * nb + bid[:, 2])
    lo_all = np.stack(np.unravel_index(uniq, (nb, nb, nb)),
                      axis=1).astype(np.int64) * brick
    n_bricks = len(lo_all)
    log(f"[poisson-bricks] depth {depth}: {n_bricks} active bricks of "
        f"{nb}^3 ({brick}^3 cells each, halo {halo}, domain {D}^3)")

    # bucket points by their own brick once: a brick's dilated domain
    # (reach halo+2 <= brick) only sees points from its 27-neighborhood,
    # so assignment is O(N log N + bricks * local) instead of a full-N
    # scan per brick
    if halo + 2 > brick:
        raise ValueError(f"halo {halo} + 2 must not exceed brick {brick} "
                         f"(the 27-neighborhood candidate gather)")
    pkey = (bid[:, 0] * nb + bid[:, 1]) * nb + bid[:, 2]
    ordp = np.argsort(pkey)
    pk_sorted = pkey[ordp]

    def _candidates(g3):
        sels = []
        for di in (-1, 0, 1):
            for dj in (-1, 0, 1):
                for dk in (-1, 0, 1):
                    q = (g3[0] + di, g3[1] + dj, g3[2] + dk)
                    if not all(0 <= q[a] < nb for a in range(3)):
                        continue
                    k = (q[0] * nb + q[1]) * nb + q[2]
                    a, z = np.searchsorted(pk_sorted, [k, k + 1])
                    if z > a:
                        sels.append(ordp[a:z])
        return (np.concatenate(sels) if sels
                else np.zeros(0, np.int64))

    chi_blocks, lo_blocks = [], []
    iso_sum = iso_cnt = 0.0
    p_cap = max_points_per_brick
    chi_c = coarse.chi
    for s in range(0, n_bricks, batch):
        part = lo_all[s:s + batch]
        bsz = len(part)
        pb = np.zeros((batch, p_cap, 3), np.float32)
        nb_arr = np.zeros((batch, p_cap, 3), np.float32)
        ob = np.zeros((batch, p_cap), bool)
        for t, lo3 in enumerate(part):
            dlo = lo3 - halo
            cand = _candidates(tuple(lo3 // brick))
            # splat reach: points whose 2-cell stencil touches the domain
            cc = cidx[cand]
            inside = ((cc >= dlo - 2) & (cc < dlo + D + 2)).all(axis=1)
            sel = cand[inside][:p_cap]
            pb[t, :len(sel)] = pts_v[sel]
            nb_arr[t, :len(sel)] = nrm_v[sel]
            ob[t, :len(sel)] = True
        lo_dom = np.concatenate(
            [part - halo, np.zeros((batch - bsz, 3), np.int64)]).astype(
                np.int32)
        chi_f, s_iso, c_iso = _refine_bricks_jit(
            jnp.asarray(pb), jnp.asarray(nb_arr), jnp.asarray(ob),
            jnp.asarray(lo_dom), chi_c, jnp.asarray(origin),
            jnp.float32(cell), jnp.float32(factor), jnp.float32(screen),
            D=D, brick=brick, halo=halo, cg_iters=cg_iters)
        chi_blocks.append(np.asarray(chi_f[:bsz]))   # stream to host
        lo_blocks.append(lo_dom[:bsz])
        iso_sum += float(np.asarray(s_iso[:bsz]).sum())
        iso_cnt += float(np.asarray(c_iso[:bsz]).sum())
    chi_all = np.concatenate(chi_blocks)
    lo_np = np.concatenate(lo_blocks)
    iso = iso_sum / max(iso_cnt, 1.0)
    return BrickPoissonResult(chi_all, lo_np, n_bricks, iso,
                              origin + 0.5 * cell, float(cell), depth,
                              brick, halo, coarse)


def extract_surface_bricks(res: BrickPoissonResult):
    """Iso-surface of a brick-refined solve, stitched CANONICALLY:

    - each face is emitted by exactly ONE brick — the owner of its
      generating edge's minimal cell (interiors partition the grid);
    - each vertex is keyed by its GLOBAL surface cell, and its position
      comes from the brick that owns that cell, so seam faces from
      adjacent bricks reference the identical vertex — no tolerance
      welding. Ring cells whose owner brick is inactive keep the first
      emitting brick's position.

    Before extraction every brick's slab is HARMONIZED: ring cells are
    overwritten with the neighboring bricks' refined INTERIOR values, so
    the overlap band is bit-identical on both sides and seam crossings
    agree exactly. Residual cracks can occur only against inactive
    neighbors (no refined field to agree with — the surface rarely runs
    there, and meshproc.fill_holes closes stragglers).
    Returns (verts [V,3] f32 world, faces [F,3] i32)."""
    # the per-brick surface-nets calls run small jitted kernels on HOST
    # numpy fields: pin them to the CPU device — on a tunneled
    # accelerator, thousands of per-brick upload/count/download round
    # trips would otherwise dominate the whole extraction
    try:
        cpu_dev = jax.local_devices(backend="cpu")[0]
    except Exception:  # no CPU platform registered: use the default
        cpu_dev = None
    ctx = (jax.default_device(cpu_dev) if cpu_dev is not None
           else contextlib.nullcontext())
    with ctx:
        return _extract_stitched(res)


def _extract_stitched(res: BrickPoissonResult):
    h, b = res.halo, res.brick
    bids = (res.brick_lo + h) // b                    # [B,3] brick grid ids
    idx_of = {tuple(k): i for i, k in enumerate(bids)}
    key_chunks, pos_chunks, ownflag_chunks = [], [], []
    face_chunks = []
    span = np.int64(1) << 21
    for i in range(res.n_bricks):
        # interior plus one ring low / two rings high: an owner cell at
        # the top interior row has quad cells at owner+1 (needs halo >= 2)
        f = res.chi[i][h - 1:h + b + 2, h - 1:h + b + 2,
                       h - 1:h + b + 2].copy()
        slab_lo = res.brick_lo[i] + (h - 1)           # global fine cell
        for di in (-1, 0, 1):
            for dj in (-1, 0, 1):
                for dk in (-1, 0, 1):
                    if di == dj == dk == 0:
                        continue
                    j = idx_of.get((bids[i, 0] + di, bids[i, 1] + dj,
                                    bids[i, 2] + dk))
                    if j is None:
                        continue
                    n_lo = res.brick_lo[j] + h        # neighbor interior
                    lo_g = np.maximum(slab_lo, n_lo)
                    hi_g = np.minimum(slab_lo + b + 3, n_lo + b)
                    if (lo_g >= hi_g).any():
                        continue
                    dst = tuple(slice(lo_g[a] - slab_lo[a],
                                      hi_g[a] - slab_lo[a])
                                for a in range(3))
                    src = tuple(slice(lo_g[a] - res.brick_lo[j][a],
                                      hi_g[a] - res.brick_lo[j][a])
                                for a in range(3))
                    f[dst] = res.chi[j][src]
        # brick_lo is the DOMAIN corner; the extracted slab starts h-1 in
        org = res.origin + (res.brick_lo[i] + h - 1) * res.cell
        v, fc, own, vcell = surface_nets.extract_surface(
            f, res.iso, origin=org, cell=res.cell, face_cells=True)
        if not len(v):
            continue
        # slab-local owner cell 1..b == this brick's interior
        keep = ((own >= 1) & (own < 1 + b)).all(axis=1)
        fc = np.asarray(fc, np.int64)[keep]
        if not len(fc):
            continue
        gcell = vcell.astype(np.int64) + (res.brick_lo[i] + (h - 1))
        gkey = (gcell[:, 0] * span + gcell[:, 1]) * span + gcell[:, 2]
        interior = ((vcell >= 1) & (vcell < 1 + b)).all(axis=1)
        used = np.unique(fc)
        key_chunks.append(gkey[used])
        pos_chunks.append(np.asarray(v, np.float32)[used])
        ownflag_chunks.append(interior[used])
        face_chunks.append(gkey[fc])
    if not key_chunks:
        return np.zeros((0, 3), np.float32), np.zeros((0, 3), np.int32)
    keys = np.concatenate(key_chunks)
    pos = np.concatenate(pos_chunks)
    owned = np.concatenate(ownflag_chunks)
    fkeys = np.concatenate(face_chunks)
    # canonical position per key: prefer the owner brick's copy
    order = np.lexsort((~owned, keys))      # per key: owner copies first
    ks, ps = keys[order], pos[order]
    uk, first = np.unique(ks, return_index=True)
    verts = ps[first]
    faces = np.searchsorted(uk, fkeys).astype(np.int64)
    good = ((faces[:, 0] != faces[:, 1]) & (faces[:, 1] != faces[:, 2])
            & (faces[:, 0] != faces[:, 2]))
    return verts.astype(np.float32), faces[good].astype(np.int32)
