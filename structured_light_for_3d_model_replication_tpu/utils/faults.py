"""Deterministic fault injection + the retry/quarantine toolkit.

The scan-to-print chain is a long sequence of fallible steps (serial turntable
moves, HTTP frame capture, per-view decode/triangulate, disk I/O). This module
supplies the two halves of making that chain resilient:

1. **Fault injection** — named sites in the product code call :func:`fire`;
   a :class:`FaultPlan` (armed from the ``faults`` config section or the
   ``SL3D_FAULTS`` env var, seeded so chaos runs are reproducible) decides
   which calls raise. Disabled by default: ``fire`` is a single ``None``
   check, so production paths pay nothing.

   Sites wired through the codebase:

   ====================  ====================================================
   ``frame.load``        per-view frame-stack load (both batch executors)
   ``frame.pack``        bit-plane pack/unpack codec step: the packed
                         ingest loader (pipeline/stages.py) and the
                         pack-on-capture step (acquire/sequencer.py)
   ``compute.view``      per-view decode+triangulate dispatch
   ``ply.write``         every PLY/STL artifact write (io/ply.py, io/stl.py)
   ``cache.get``         stage-cache lookup (pipeline/stagecache.py)
   ``cache.put``         stage-cache publish
   ``register.pair``     streamed-merge pair registration (item is
                         ``"<dst>-><src>"`` view indices; an exhausted or
                         permanent hit falls back to the identity transform)
   ``http.capture``      phone HTTP frame capture (acquire/android.py)
   ``serial.rotate``     turntable rotate+wait (acquire/turntable.py)
   ``worker.item``       coordinated-run worker item execution (item is
                         ``"<worker_id>:<item_id>"``; parallel/worker.py)
   ``coord.grant``       coordinator lease grant (item is
                         ``"<worker_id>:<item_id>"``; the coordinator-crash
                         site for resume tests; parallel/coordinator.py)
   ``serve.crash``       serving-gateway crash boundaries (item is
                         ``"grant:<item_id>"``, ``"complete:<item_id>"``
                         or ``"assembly:<scan_id>"``; the restart-resume
                         site for durable serving; pipeline/serving.py)
   ``ledger.append``     every work-ledger event append (item is the
                         event type; a crash here loses the line replay
                         must tolerate; parallel/coordinator.py)
   ``http.submit``       gateway /submit handling before admission (the
                         client-visible 503 + Retry-After path;
                         pipeline/serving.py)
   ``election.acquire``  HA leader-lease acquire attempt (item is the
                         member's owner id; parallel/election.py)
   ``blob.fetch``        fabric L2 blob fetch (item is the blob name;
                         transient absorbs into one retry, anything else
                         degrades to a cache miss; pipeline/blobstore.py)
   ``blob.push``         fabric L2 blob publish (best-effort: a failed
                         push leaves the payload in L1 only;
                         pipeline/blobstore.py)
   ``worker.sock``       every control frame on a worker's coordinator /
                         blobstore socket (item is ``"coord:<op>"`` or
                         ``"blob:<op>"``); the ``net.slowlink(T)`` kind
                         lands here to delay frames on the wire
                         (parallel/worker.py, pipeline/blobstore.py)
   ``election.renew``    HA leader-lease renew — a ``stall(T)`` here with
                         T past the lease is how a ZOMBIE leader is
                         manufactured: the lease expires mid-stall, a
                         standby steals it, and the waker's next append
                         is fenced (parallel/election.py)
   ``fleet.decide``      fleet-supervisor decision tick (item is the tick
                         counter): a transient skips the tick, a crash
                         fells the gateway exactly like an engine-loop
                         crash — the kill matrix's supervisor-death arm
                         (parallel/fleet.py)
   ``worker.spawn``      fleet worker spawn, fired BETWEEN the journaled
                         spawn decision and the Popen (item is the
                         worker name, e.g. ``fw0``): a transient retries
                         under the rank's backoff, a crash leaves a
                         journaled-but-unspawned rank — exactly what the
                         next resume respawns (parallel/fleet.py)
   ====================  ====================================================

2. **Retry/quarantine toolkit** — the exception classifier
   (:func:`is_transient`), the bounded exponential-backoff
   :class:`RetryPolicy` + :func:`retry_call`, and the structured
   :class:`FailureRecord` the pipeline quarantines permanently-failed views
   with.

Fault-spec grammar (comma-separated rules)::

    site[~substr]:kind[@n][xM][%p]

    kind     transient | permanent | crash | stall[(T)] | slow[(T)]
             | worker.kill | worker.preempt[(T)] | net.partition[(T)]
             | net.slowlink[(T)]
    ~substr  only fire() calls whose item contains substr count as hits
    @n       arm on the n-th matching hit (1-based; default 1)
    xM       fire at most M times (default: unlimited for permanent,
             1 for every other kind)
    %p       each armed hit fires with probability p (seeded RNG)

Examples::

    frame.load:transient                 first stack load fails once
    compute.view~144deg:permanent        view 144deg never decodes
    ply.write:transient@2x3              writes 2,3,4 fail
    cache.get:transient%0.5              each lookup fails with p=.5 (seeded)
    ply.write~merged:crash               simulated kill -9 at the merged write
    register.pair:stall(2.5)             first pair registration hangs 2.5s
    frame.load~072deg:slow(0.5)          view 072deg's load straggles 0.5s

``transient``/``permanent`` raise ordinary exceptions the retry/quarantine
machinery handles; ``crash`` raises :class:`InjectedCrash` (a BaseException,
like KeyboardInterrupt) that no per-item handler may swallow — the
interrupt-mid-stage simulation for crash-safety tests.

``stall``/``slow`` model faults that do not raise at all: the ``fire()``
call BLOCKS for T seconds (defaults: ``STALL_DEFAULT_S``/``SLOW_DEFAULT_S``)
and then returns normally, as if the wedge resolved. Both are cancel-aware
(:func:`~.deadline.sleep_cancellable`): a watchdog hard breach cancels the
run token and the sleeping site raises :class:`~.deadline.Cancelled`
instead — so injected hangs are always bounded and chaos tests terminate.
``stall`` is the hang the deadline layer must catch (pick T above the
lane's deadline); ``slow`` is the straggler that must trip only the SOFT
watchdog threshold and still complete.

The **host-scope kinds** model whole-process fates in a coordinated
multi-process run (parallel/coordinator.py):

  ``worker.kill``        raises :class:`WorkerKilled` (an
                         :class:`InjectedCrash`): the worker loop turns it
                         into an immediate ``os._exit`` — SIGKILL at item
                         granularity, no cleanup, no journal close
  ``worker.preempt(T)``  raises :class:`WorkerPreempted` (also an
                         :class:`InjectedCrash`) carrying a ``grace_s`` of
                         T: the worker loop stops taking work and exits
                         after the grace window — the cloud-VM preemption
                         notice shape
  ``net.partition(T)``   raises :class:`NetPartition` (a
                         :class:`TransientFault`) carrying ``duration_s``:
                         the worker's coordinator client drops its
                         connection and stays dark for T seconds before
                         reconnecting — long enough partitions expire the
                         worker's leases and exercise steal + the
                         stolen-item late-complete path
  ``net.slowlink(T)``    the degraded-but-alive link: like ``slow`` it
                         never raises, it just blocks the firing site for
                         T seconds (default ``SLOWLINK_DEFAULT_S``) and
                         returns. Aimed at the per-frame socket sites
                         (``worker.sock``) so every control frame on a
                         worker's wire straggles — heartbeats still land,
                         leases stay alive, throughput just sags
"""
from __future__ import annotations

import math
import os
import random
import threading
import time
import urllib.error
from dataclasses import dataclass, field

from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import telemetry

__all__ = [
    "InjectedFault", "TransientFault", "PermanentFault", "InjectedCrash",
    "WorkerKilled", "WorkerPreempted", "NetPartition",
    "FaultRule", "FaultPlan", "configure", "configure_from", "reset", "fire",
    "active_plan", "is_transient", "RetryPolicy", "retry_call", "annotate",
    "jitter_rng", "FailureRecord", "STALL_DEFAULT_S", "SLOW_DEFAULT_S",
    "PREEMPT_GRACE_DEFAULT_S", "PARTITION_DEFAULT_S", "SLOWLINK_DEFAULT_S",
]


# ---------------------------------------------------------------------------
# injected exception types
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Base of the injectable (catchable) faults."""

    transient = False


class TransientFault(InjectedFault):
    """Models a recoverable blip (dropped connection, EAGAIN, torn read)."""

    transient = True


class PermanentFault(InjectedFault):
    """Models a deterministic failure (corrupt capture, bad view)."""

    transient = False


class InjectedCrash(BaseException):
    """Simulated ``kill -9``: escapes every ``except Exception`` handler, so
    per-item tolerance cannot swallow it — only crash-safe artifact handling
    (tmp+rename, startup sweeps, the stage cache) may mask its effects."""


class WorkerKilled(InjectedCrash):
    """Host-scope ``worker.kill``: the worker loop must die IMMEDIATELY
    (``os._exit``, no cleanup) — the SIGKILL / OOM-kill simulation. An
    InjectedCrash subclass so no per-item handler can absorb it."""


class WorkerPreempted(InjectedCrash):
    """Host-scope ``worker.preempt(T)``: the worker got a preemption notice
    with ``grace_s`` seconds to vacate. The loop stops taking work and
    exits after the grace window; in-flight leases expire and are stolen."""

    def __init__(self, detail: str, grace_s: float):
        super().__init__(detail)
        self.grace_s = grace_s


class NetPartition(TransientFault):
    """Host-scope ``net.partition(T)``: the worker's link to the
    coordinator goes dark for ``duration_s`` seconds. Transient — the
    worker survives, reconnects, and may find its leases stolen."""

    def __init__(self, detail: str, duration_s: float):
        super().__init__(detail)
        self.duration_s = duration_s


# ---------------------------------------------------------------------------
# the fault plan
# ---------------------------------------------------------------------------

_KINDS = ("transient", "permanent", "crash", "stall", "slow",
          "worker.kill", "worker.preempt", "net.partition",
          "net.slowlink")

# the kinds that accept a ``(T)`` duration, and what T means for each:
# stall/slow/net.slowlink block for T; worker.preempt grants T of grace
# before the forced exit; net.partition keeps the link dark for T
_DURATION_KINDS = ("stall", "slow", "worker.preempt", "net.partition",
                   "net.slowlink")

# default block durations for the non-raising kinds when no ``(T)`` is
# given. Long enough to trip production-default lane deadlines / the
# watchdog; chaos tests pass explicit small durations
STALL_DEFAULT_S = 30.0
SLOW_DEFAULT_S = 1.0
PREEMPT_GRACE_DEFAULT_S = 0.5
PARTITION_DEFAULT_S = 1.0
SLOWLINK_DEFAULT_S = 0.25   # per-frame delay: visible, never lease-fatal


@dataclass
class FaultRule:
    site: str
    kind: str
    match: str = ""
    arm_at: int = 1          # start firing on the n-th matching hit
    times: float = math.inf  # how many times to fire once armed
    prob: float = 1.0        # per-armed-hit probability (seeded)
    duration_s: float | None = None  # stall/slow block time (None=default)
    hits: int = 0
    fired: int = 0

    @classmethod
    def parse(cls, text: str) -> "FaultRule":
        head, sep, tail = text.strip().partition(":")
        if not sep:
            raise ValueError(f"fault rule {text!r}: expected site:kind")
        site, _, match = head.partition("~")
        kind, arm_at, times, prob = tail, 1, None, 1.0
        if "%" in kind:
            kind, p = kind.split("%", 1)
            prob = float(p)
        if "x" in kind:     # no kind name or (T) digits contain an 'x'
            kind, m = kind.split("x", 1)
            times = int(m)
        if "@" in kind:
            kind, n = kind.split("@", 1)
            arm_at = int(n)
        duration = None
        if kind.endswith(")") and "(" in kind:
            kind, d = kind[:-1].split("(", 1)
            duration = float(d)
        if kind not in _KINDS:
            raise ValueError(
                f"fault rule {text!r}: kind {kind!r} not in {_KINDS}")
        if duration is not None and kind not in _DURATION_KINDS:
            raise ValueError(
                f"fault rule {text!r}: only "
                f"{'/'.join(_DURATION_KINDS)} take a (T) duration")
        if times is None:
            times = math.inf if kind == "permanent" else 1
        return cls(site=site.strip(), kind=kind, match=match,
                   arm_at=arm_at, times=times, prob=prob,
                   duration_s=duration)

    @property
    def block_s(self) -> float:
        """Effective ``(T)`` duration for the duration-taking kinds."""
        if self.duration_s is not None:
            return self.duration_s
        return {"stall": STALL_DEFAULT_S,
                "worker.preempt": PREEMPT_GRACE_DEFAULT_S,
                "net.partition": PARTITION_DEFAULT_S,
                "net.slowlink": SLOWLINK_DEFAULT_S,
                }.get(self.kind, SLOW_DEFAULT_S)

    def throw(self) -> None:
        detail = (f"injected {self.kind} fault at {self.site}"
                  + (f" (match {self.match!r})" if self.match else ""))
        if self.kind == "worker.kill":
            raise WorkerKilled(detail)
        if self.kind == "worker.preempt":
            raise WorkerPreempted(detail, grace_s=self.block_s)
        if self.kind == "net.partition":
            raise NetPartition(detail, duration_s=self.block_s)
        if self.kind == "crash":
            raise InjectedCrash(detail)
        if self.kind == "transient":
            raise TransientFault(detail)
        raise PermanentFault(detail)


class FaultPlan:
    """A parsed, seeded fault plan. Thread-safe: fire() is called from the
    prefetch/drain/writeback worker threads as well as the main thread."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self._rng = random.Random(seed)
        # a SEPARATE seeded stream for retry-backoff jitter: drawing
        # jitter from ``_rng`` would shift the %p decision sequence,
        # changing which faults fire between jittered and unjittered runs
        self._jitter_rng = random.Random(seed ^ 0x6A77)
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlan":
        rules = [FaultRule.parse(r) for r in spec.split(",") if r.strip()]
        return cls(rules, seed)

    def fire(self, site: str, item=None) -> None:
        text = "" if item is None else str(item)
        hit: FaultRule | None = None
        # decide under the lock, act OUTSIDE it: a stall/slow rule sleeps
        # for seconds, and holding the plan lock through that would
        # serialize every other lane's fire() behind the injected wedge
        with self._lock:
            for rule in self.rules:
                if rule.site != site:
                    continue
                if rule.match and rule.match not in text:
                    continue
                rule.hits += 1
                if rule.hits < rule.arm_at or rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and self._rng.random() > rule.prob:
                    continue
                rule.fired += 1
                hit = rule
                break
        if hit is None:
            return
        tr = telemetry.current()
        if tr is not None:
            # chaos runs leave their injections in the journal, so
            # the fault ledger needs no log scraping
            tr.instant("fault.injected", site=site, kind=hit.kind,
                       item=text or None,
                       duration_s=(hit.block_s
                                   if hit.kind in _DURATION_KINDS
                                   else None))
        if hit.kind in ("stall", "slow", "net.slowlink"):
            # block, then RESUME normally (a wedge that eventually
            # resolves); cancel-aware so a watchdog hard breach raises
            # deadline.Cancelled out of the sleep and the item is
            # abandoned instead of waiting out the full duration
            dl.sleep_cancellable(
                hit.block_s,
                what=f"injected {hit.kind} at {site}"
                     + (f" ({text})" if text else ""))
            return
        hit.throw()

    def counts(self) -> dict[str, int]:
        """Fired-per-site accounting (for manifests and assertions)."""
        out: dict[str, int] = {}
        for r in self.rules:
            if r.fired:
                out[r.site] = out.get(r.site, 0) + r.fired
        return out


# module-global active plan; None (the default) means every fire() is a no-op
_PLAN: FaultPlan | None = None


def configure(spec: str = "", seed: int = 0) -> FaultPlan | None:
    """Install a fault plan process-wide; empty spec deactivates. Returns the
    installed plan (or None)."""
    global _PLAN
    _PLAN = FaultPlan.from_spec(spec, seed) if spec.strip() else None
    return _PLAN


def configure_from(faults_cfg) -> FaultPlan | None:
    """Arm from a ``FaultsConfig`` section; the ``SL3D_FAULTS`` /
    ``SL3D_FAULTS_SEED`` env vars win over the config (the chaos-run switch
    that needs no config file edit)."""
    spec = os.environ.get("SL3D_FAULTS", "")
    if spec:
        seed = int(os.environ.get("SL3D_FAULTS_SEED", "0"))
    else:
        spec = getattr(faults_cfg, "spec", "") or ""
        seed = int(getattr(faults_cfg, "seed", 0) or 0)
    return configure(spec, seed)


def reset() -> None:
    configure("")


def active_plan() -> FaultPlan | None:
    return _PLAN


def fire(site: str, item=None) -> None:
    """Injection site: raises per the active plan; no-op (one None check)
    when no plan is armed — the zero-overhead-by-default contract."""
    if _PLAN is None:
        return
    _PLAN.fire(site, item)


# ---------------------------------------------------------------------------
# transient-vs-permanent classification
# ---------------------------------------------------------------------------

_TRANSIENT_ERRNOS = frozenset({
    4,    # EINTR
    11,   # EAGAIN
    16,   # EBUSY
    104,  # ECONNRESET
    110,  # ETIMEDOUT
    111,  # ECONNREFUSED (service restarting)
})


def is_transient(exc: BaseException) -> bool:
    """Classify an exception as transient (worth a bounded retry) or
    permanent (retry is wasted work; quarantine instead).

    Unknown exception types default to permanent — a retry budget spent on a
    deterministic failure just delays the quarantine decision."""
    if isinstance(exc, InjectedFault):
        return exc.transient
    if isinstance(exc, dl.Cancelled):
        # a cancelled item was abandoned by the watchdog/run teardown;
        # retrying would re-enter the wedge the cancel just broke
        return False
    if isinstance(exc, (ConnectionError, TimeoutError)):
        # includes deadline.DeadlineExceeded (a TimeoutError subclass):
        # hitting a deadline is a scheduling outcome, not proof the item
        # is poisoned, so a retry budget MAY be spent on it
        return True
    if isinstance(exc, urllib.error.URLError):
        # wraps socket-level failures; the HTTP capture path's blip class
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


# ---------------------------------------------------------------------------
# bounded retry + exponential backoff
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff: retry ``max_retries`` times, sleeping
    ``backoff_base_s * 2**(retry-1)`` (capped at ``backoff_max_s``) before
    each. ``max_retries=0`` disables retrying entirely.

    ``jitter=True`` turns each sleep into FULL jitter — uniform in
    ``[0, delay_s(retry)]`` — so N workers tripping over the same
    transient (a coordinator blip, a shared-mount hiccup) spread their
    retries instead of thundering back in lockstep. The draw comes from
    the armed fault plan's seeded jitter stream (:func:`jitter_rng`), so
    chaos tests stay reproducible; ``delay_s`` itself stays deterministic
    (it is the CEILING, and what retry logs/traces may quote)."""

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_max_s: float = 1.0
    jitter: bool = False

    def delay_s(self, retry: int) -> float:
        """Deterministic backoff ceiling before the ``retry``-th retry
        (1-based). With ``jitter``, the actual sleep is drawn uniformly
        below this inside :func:`retry_call`."""
        return min(self.backoff_base_s * (2.0 ** (retry - 1)),
                   self.backoff_max_s)


_JITTER_FALLBACK = random.Random()


def jitter_rng() -> random.Random:
    """The seeded jitter stream when a fault plan is armed (deterministic
    chaos runs), else an OS-seeded RNG (real runs, where true randomness
    is exactly what anti-herd jitter wants)."""
    plan = _PLAN
    if plan is not None:
        return plan._jitter_rng
    return _JITTER_FALLBACK


def retry_call(fn, policy: RetryPolicy, *, classify=is_transient,
               on_retry=None, sleep=time.sleep):
    """Run ``fn()`` with the policy's transient-retry budget.

    Permanent (per ``classify``) or budget-exhausted exceptions re-raise the
    ORIGINAL exception annotated with ``_sl3d_attempts`` (total attempts
    made) so failure records can report the true attempt count.
    ``on_retry(retry_index, exc)`` fires before each backoff sleep — the
    hook retry counters and logs hang off. :class:`InjectedCrash` is never
    retried (it models a process kill)."""
    attempts = 1
    while True:
        try:
            return fn()
        except InjectedCrash:
            raise
        except Exception as e:
            retries_done = attempts - 1
            if retries_done >= policy.max_retries or not classify(e):
                annotate(e, attempts=attempts)
                raise
            if on_retry is not None:
                on_retry(retries_done + 1, e)
            delay = policy.delay_s(retries_done + 1)
            if policy.jitter:
                delay = jitter_rng().uniform(0.0, delay)
            tr = telemetry.current()
            if tr is not None:
                tr.instant("retry", attempt=retries_done + 1,
                           error=type(e).__name__,
                           backoff_s=round(delay, 4))
            sleep(delay)
            attempts += 1


def annotate(exc: BaseException, stage: str | None = None,
             attempts: int | None = None) -> BaseException:
    """Attach failure-record context to an exception that will cross a
    thread/future boundary before being recorded."""
    if stage is not None:
        exc._sl3d_stage = stage  # type: ignore[attr-defined]
    if attempts is not None:
        exc._sl3d_attempts = attempts  # type: ignore[attr-defined]
    return exc


# ---------------------------------------------------------------------------
# structured failure records (the quarantine payload)
# ---------------------------------------------------------------------------

@dataclass
class FailureRecord:
    """One per-item failure, structured for the failure manifest: which
    stage, which view, how many attempts were made, what raised, and whether
    the final exception classified transient (budget exhausted) or permanent
    (not worth retrying)."""

    stage: str
    view: str
    attempts: int
    error_type: str
    message: str
    transient: bool
    extra: dict = field(default_factory=dict)

    @classmethod
    def from_exception(cls, stage: str, view: str, exc: BaseException,
                       attempts: int | None = None) -> "FailureRecord":
        return cls(
            stage=getattr(exc, "_sl3d_stage", None) or stage,
            view=view,
            attempts=attempts if attempts is not None
            else getattr(exc, "_sl3d_attempts", 1),
            error_type=type(exc).__name__,
            message=str(exc),
            transient=is_transient(exc),
        )

    def as_dict(self) -> dict:
        out = {"stage": self.stage, "view": self.view,
               "attempts": self.attempts, "error_type": self.error_type,
               "message": self.message, "transient": self.transient}
        if self.extra:
            out["extra"] = self.extra
        return out
