"""Deadlines, cooperative cancellation, and the lane watchdog.

PR 3 made the pipeline survive faults that *raise* (transient/permanent/
crash) and PR 6 gave every run a flight recorder — but nothing protected
against faults that simply *never return*: a wedged frame load, device
dispatch, PLY write, or pair registration froze a scan forever with no
diagnostic. This module is the missing half of the failure model:

  - :class:`Deadline` — a monotonic-clock time budget (``time.monotonic``
    only; wall-clock arithmetic drifts across NTP steps/suspends and is
    banned for deadlines repo-wide).
  - :class:`DeadlineExceeded` — raised when a budget runs out. Subclasses
    :class:`TimeoutError`, so ``faults.is_transient`` classifies it
    TRANSIENT: a deadline hit is a scheduling outcome, not proof the item
    is poisoned, and a retry budget *may* be spent on it where one exists.
  - :class:`CancelToken` — cooperative cancellation. Nothing in Python can
    safely kill a wedged thread; instead, long sleeps and injected stalls
    poll the token (:func:`sleep_cancellable`) and raise
    :class:`Cancelled` (classified PERMANENT — a cancelled item is
    abandoned, never retried).
  - :func:`wait_future` / :func:`wait_settled` — the bounded replacements
    for bare ``Future.result()`` / ``Future.exception()``. Built on
    ``concurrent.futures.wait`` so a poll-window expiry can never be
    confused with a ``TimeoutError`` *raised by the work itself* (on
    py3.11+ ``futures.TimeoutError`` IS builtin ``TimeoutError``).
  - :class:`Watchdog` — a daemon thread consuming the lane heartbeats
    that ``OverlapStats.add``/``add_pair_launch`` emit (the PR-6
    can't-drift pattern: the same calls that accumulate lane walls feed
    the liveness signal, so the two can never disagree). No heartbeat
    from ANY lane for ``soft_stall_s`` -> a ``watchdog.stall`` trace
    event + warning; for ``hard_stall_s`` -> the run token is cancelled
    (breaking any cancel-aware stall so its item quarantines like a
    permanently-failed one) and every thread's stack is dumped via
    ``faulthandler`` into a crash-safe ``stalls.json`` next to
    ``failures.json``. When progress resumes the cancel level is lowered
    again — the token is a stall-breaker, not a run abort.

Ambient context (the ``faults._PLAN`` / ``telemetry._TRACER`` pattern):
``run_pipeline``/``reconstruct`` install a :class:`RunContext` with
:func:`activate`; hot paths fetch it with :func:`current` (one
module-global ``None`` check when the deadline layer is disabled — the
zero-overhead-by-default contract the faults and telemetry layers hold).

Division of labor, by where a stall lives:

  worker-thread stall   the main thread's bounded ``wait_future`` on that
                        item's future raises :class:`DeadlineExceeded`
                        after the lane budget -> the item is recorded and
                        quarantined, the run continues (DEGRADED above
                        the survivor floor)
  main-thread stall     no future guards it; the watchdog's hard breach
                        cancels the token and a cancel-aware stall
                        raises :class:`Cancelled` out of the wedge ->
                        same per-item quarantine path
  real hard hang        cannot be interrupted from Python; the watchdog
                        still dumps every thread's stack to
                        ``stalls.json`` so the wedge is diagnosable from
                        artifacts, and the overall ``pipeline.
                        run_budget_s`` bounds everything reachable from
                        the main thread
"""
from __future__ import annotations

import faulthandler
import json
import os
import threading
import time
from concurrent.futures import wait as _futures_wait
from dataclasses import dataclass, field

from structured_light_for_3d_model_replication_tpu.utils import telemetry

__all__ = [
    "DeadlineExceeded", "Cancelled", "Deadline", "CancelToken",
    "wait_future", "wait_settled", "sleep_cancellable", "Watchdog",
    "RunContext", "activate", "deactivate", "current", "beat",
    "watchdog_suspend", "watchdog_resume", "STALLS_SCHEMA",
]

STALLS_SCHEMA = "sl3d-stalls-v1"


class DeadlineExceeded(TimeoutError):
    """A time budget ran out. TimeoutError subclass on purpose:
    ``faults.is_transient`` classifies it transient — hitting a deadline
    is a scheduling outcome, not proof the item is poisoned."""


class Cancelled(RuntimeError):
    """The run's CancelToken was raised while this op waited/slept. NOT
    transient: a cancelled item is abandoned (quarantined), never
    retried — retrying would re-enter the wedge the cancel broke."""


class Deadline:
    """Monotonic-clock time budget. ``None`` (from :meth:`after` with a
    non-positive budget) means unbounded everywhere it is accepted."""

    __slots__ = ("t_end", "budget_s", "what")

    def __init__(self, budget_s: float, what: str = ""):
        self.budget_s = float(budget_s)
        self.t_end = time.monotonic() + self.budget_s
        self.what = what

    @classmethod
    def after(cls, budget_s: float | None,
              what: str = "") -> "Deadline | None":
        """A Deadline ``budget_s`` from now, or None for no/zero budget —
        the config convention (``0`` == unbounded) in one place."""
        if budget_s is None or budget_s <= 0:
            return None
        return cls(budget_s, what)

    def remaining(self) -> float:
        return self.t_end - time.monotonic()

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.t_end

    def check(self, what: str = "") -> None:
        if self.expired:
            label = what or self.what or "operation"
            raise DeadlineExceeded(
                f"{label} exceeded its {self.budget_s:g}s budget")


class CancelToken:
    """Cooperative cancellation flag. ``cancel`` is a LEVEL, not an edge:
    the watchdog raises it to break a wedge and lowers it (:meth:`clear`)
    once the run makes progress again, so one stalled item is abandoned
    without dragging the rest of the run down with it."""

    def __init__(self):
        self._event = threading.Event()
        self._reason = ""
        self._lock = threading.Lock()

    def cancel(self, reason: str = "") -> None:
        with self._lock:
            if reason:
                self._reason = reason
        self._event.set()

    def clear(self) -> None:
        """Lower the cancel level (the watchdog's progress-resumed path)."""
        self._event.clear()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> str:
        return self._reason

    def check(self, what: str = "") -> None:
        if self._event.is_set():
            detail = self._reason or "cancelled"
            raise Cancelled(f"{what or 'operation'} cancelled ({detail})")

    def wait(self, timeout_s: float) -> bool:
        """Block up to ``timeout_s`` for cancellation; True if cancelled."""
        return self._event.wait(timeout_s)


def wait_future(fut, timeout_s: float | None, what: str = ""):
    """``fut.result()`` bounded by ``timeout_s`` (None/<=0 = unbounded).

    Built on ``concurrent.futures.wait`` so the poll expiry is decided by
    *settledness*, never by catching TimeoutError — a work function that
    itself raises TimeoutError propagates immediately instead of being
    mistaken for an unexpired wait (futures.TimeoutError aliases the
    builtin on py3.11+)."""
    if timeout_s is None or timeout_s <= 0:
        return fut.result()
    done, _ = _futures_wait([fut], timeout=timeout_s)
    if not done:
        raise DeadlineExceeded(
            f"{what or 'future'} still pending after {timeout_s:g}s")
    return fut.result()


def wait_settled(fut, timeout_s: float | None) -> bool:
    """Block until ``fut`` settles (result OR exception — never raises
    either), bounded by ``timeout_s``; False if still pending at expiry.
    The backpressure-wait twin of :func:`wait_future`: callers that only
    need "is the slot free yet" must not hang on a wedged slot."""
    if timeout_s is None or timeout_s <= 0:
        fut.exception()     # blocks without raising the work's error
        return True
    done, _ = _futures_wait([fut], timeout=timeout_s)
    return bool(done)


def sleep_cancellable(seconds: float, token: CancelToken | None = None,
                      what: str = "") -> None:
    """Sleep ``seconds`` unless the token (given, or the ambient run
    context's) is cancelled first — then raise :class:`Cancelled`. The
    primitive injected stalls/slows are built on, so chaos tests always
    terminate: a stall is breakable by the watchdog and bounded by its
    own duration."""
    if token is None:
        ctx = _CTX
        token = ctx.token if ctx is not None else None
    if token is None:
        time.sleep(max(0.0, seconds))
        return
    if token.wait(max(0.0, seconds)):
        token.check(what)   # raises Cancelled with the cancel reason


# ---------------------------------------------------------------------------
# the lane watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Daemon thread that turns lane heartbeats into stall diagnostics.

    ``beat(lane)`` is called from inside ``OverlapStats.add`` /
    ``add_pair_launch`` (via the ambient :func:`beat`) — the same calls
    that accumulate lane walls, so liveness and accounting cannot drift.
    The poll loop tracks the age of the NEWEST heartbeat across all lanes
    (per-lane idleness is normal — the write lane goes quiet once writes
    finish; a run where *no* lane beats is wedged):

      age >= soft_stall_s   one ``watchdog.stall`` trace event + warning
                            per stall episode (re-armed when progress
                            resumes)
      age >= hard_stall_s   cancel the run token (any cancel-aware stall
                            raises Cancelled out of the wedge -> its item
                            quarantines), dump EVERY thread's stack via
                            ``faulthandler`` into a crash-safe
                            ``stalls.json``, keep polling; the cancel
                            level drops again on the next heartbeat

    All breaches are retained in ``self.breaches`` (the stall ledger);
    ``stop()`` persists them even when the hard path never fired.
    """

    def __init__(self, soft_stall_s: float, hard_stall_s: float,
                 token: CancelToken, poll_s: float = 1.0,
                 out_dir: str | None = None, run_id: str | None = None,
                 log=None, heartbeat_trace_min_s: float = 1.0):
        self.soft_s = float(soft_stall_s)
        self.hard_s = float(hard_stall_s)
        self.poll_s = max(0.01, float(poll_s))
        self.token = token
        self.out_dir = out_dir
        self.run_id = run_id
        self.log = log or (lambda m: None)
        self.breaches: list[dict] = []
        # host-scoped in coordinated-run workers (stalls.w0-123.json) so N
        # workers sharing an out dir never clobber each other's evidence
        self.stalls_path = (
            os.path.join(out_dir, telemetry.host_scoped("stalls.json"))
            if out_dir else None)
        self._hb_trace_min_s = float(heartbeat_trace_min_s)
        self._lock = threading.Lock()
        self._beats: dict[str, float] = {}
        self._hb_emitted: dict[str, float] = {}
        self._t0 = time.monotonic()
        self._soft_fired = False
        self._hard_fired = False
        self._suspended = 0
        self._t_resume = self._t0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- heartbeat sink (any thread, hot path) ----------------------------

    def beat(self, lane: str) -> None:
        now = time.monotonic()
        emit = False
        with self._lock:
            self._beats[lane] = now
            # throttled lane.heartbeat instants: liveness in the journal
            # without a line per OverlapStats.add call
            if now - self._hb_emitted.get(lane, 0.0) >= self._hb_trace_min_s:
                self._hb_emitted[lane] = now
                emit = True
        if emit:
            tr = telemetry.current()
            if tr is not None:
                tr.instant("lane.heartbeat", lane=lane)

    def lane_ages(self) -> dict[str, float]:
        """Seconds since each lane's last heartbeat (the ledger payload)."""
        now = time.monotonic()
        with self._lock:
            return {ln: round(now - ts, 3) for ln, ts in self._beats.items()}

    def suspend(self) -> None:
        """Pause breach detection (re-entrant). The barrier stages
        (merge accumulate, Poisson mesh) are single opaque device/numpy
        calls: no cooperative mechanism can observe progress inside them,
        so 'no heartbeat' there is expected, not a stall — those phases
        are covered by the overall run budget instead."""
        with self._lock:
            self._suspended += 1

    def resume(self) -> None:
        with self._lock:
            self._suspended = max(0, self._suspended - 1)
            # suspended time is not silence: restart the age clock
            self._t_resume = time.monotonic()
            self._soft_fired = False
            self._hard_fired = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="sl3d-watchdog")
        self._thread.start()

    def stop(self) -> None:
        """Stop polling and persist the stall ledger (if any breaches).
        Idempotent; runs in the pipeline's ``finally``."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 4 * self.poll_s))
            self._thread = None
        if self.breaches and self.stalls_path:
            self._write_stalls()

    # -- poll loop ---------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._poll()
            except Exception:   # the watchdog must never kill the run
                pass

    def _poll(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._suspended:
                return
            last = max(self._beats.values(), default=self._t0)
            last = max(last, self._t_resume)
        age = now - last
        if age < self.soft_s:
            if self._hard_fired and self.token.cancelled:
                # progress resumed after a hard breach: lower the cancel
                # level so the rest of the run proceeds normally
                self.token.clear()
                self.log("[watchdog] progress resumed; cancel level "
                         "lowered")
            self._soft_fired = False
            self._hard_fired = False
            return
        if age >= self.hard_s > 0 and not self._hard_fired:
            self._hard_fired = True
            self._breach("hard", age)
            self.token.cancel(
                f"watchdog hard breach: no lane heartbeat for "
                f"{age:.1f}s (hard_stall_s={self.hard_s:g})")
            self.log(f"[watchdog] HARD STALL: no lane heartbeat for "
                     f"{age:.1f}s — cancelling the stalled item and "
                     f"dumping thread stacks"
                     + (f" -> {self.stalls_path}" if self.stalls_path
                        else ""))
            if self.stalls_path:
                self._write_stalls()
        elif not self._soft_fired and self.soft_s > 0:
            self._soft_fired = True
            self._breach("soft", age)
            self.log(f"[watchdog] WARNING: possible stall — no lane "
                     f"heartbeat for {age:.1f}s "
                     f"(soft_stall_s={self.soft_s:g})")

    def _breach(self, level: str, age: float) -> None:
        rec = {"level": level, "age_s": round(age, 3),
               "t_unix": round(time.time(), 3),
               "lane_ages": self.lane_ages()}
        self.breaches.append(rec)
        tr = telemetry.current()
        if tr is not None:
            tr.instant("watchdog.stall", level=level,
                       age_s=rec["age_s"], lanes=rec["lane_ages"])

    def _thread_stacks(self) -> list[str]:
        # faulthandler writes through a raw fd (it is designed to work
        # mid-crash), so a StringIO won't do — stage through a real file
        import tempfile

        try:
            with tempfile.TemporaryFile(mode="w+",
                                        encoding="utf-8",
                                        errors="replace") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
                f.seek(0)
                return f.read().splitlines()
        except Exception:
            return ["<faulthandler dump failed>"]

    def _write_stalls(self) -> None:
        """Crash-safe (tmp+rename) stall ledger next to failures.json."""
        payload = {"schema": STALLS_SCHEMA, "run_id": self.run_id,
                   "soft_stall_s": self.soft_s,
                   "hard_stall_s": self.hard_s,
                   "breaches": self.breaches,
                   "thread_stacks": self._thread_stacks()}
        tmp = self.stalls_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=2)
                f.write("\n")
            os.replace(tmp, self.stalls_path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# ambient run context (the faults._PLAN / telemetry._TRACER pattern)
# ---------------------------------------------------------------------------

@dataclass
class RunContext:
    """Deadline/cancel state for one run, installed process-wide so deep
    call sites (injected stalls, lane waits) need no plumbed-through
    arguments. ``run_deadline`` is the overall ``pipeline.run_budget_s``
    (None = unbounded) checked at stage boundaries and executor loops —
    the ABORT path; the token + watchdog are the per-item STALL-BREAK
    path (quarantine, continue)."""

    token: CancelToken = field(default_factory=CancelToken)
    watchdog: Watchdog | None = None
    run_deadline: Deadline | None = None

    def check_run_budget(self, what: str = "pipeline run") -> None:
        if self.run_deadline is not None:
            self.run_deadline.check(what)

    def abort(self, reason: str = "externally aborted") -> None:
        """Externally abort the run THIS context governs (the serving
        drain lever): replace the run deadline with one already expired
        and raise the cancel level, so the next stage boundary /
        cancellable wait exits through the normal DeadlineExceeded abort
        path — failures.json manifest included — instead of being
        hard-killed mid-write."""
        d = Deadline(0.0, reason)
        d.t_end = float("-inf")
        self.run_deadline = d
        self.token.cancel(reason)


_CTX: RunContext | None = None


def current() -> RunContext | None:
    """The active run context, or None when the deadline layer is off.
    Hot paths fetch once and guard with ``is not None`` — the disabled
    path is exactly one module-global None check."""
    return _CTX


def activate(ctx: RunContext | None) -> RunContext | None:
    """Install ``ctx`` process-wide; returns the PREVIOUS context so a
    nested scope (bench arms, tests) can restore it on exit."""
    global _CTX
    prev = _CTX
    _CTX = ctx
    return prev


def deactivate(restore: RunContext | None = None) -> None:
    global _CTX
    _CTX = restore


def beat(lane: str) -> None:
    """Lane heartbeat from the hot accounting path (``OverlapStats.add``).
    One None check when no watchdog is armed."""
    ctx = _CTX
    if ctx is not None and ctx.watchdog is not None:
        ctx.watchdog.beat(lane)


def watchdog_suspend() -> None:
    """Pause the ambient watchdog across a barrier stage (see
    :meth:`Watchdog.suspend`); no-op when none is armed."""
    ctx = _CTX
    if ctx is not None and ctx.watchdog is not None:
        ctx.watchdog.suspend()


def watchdog_resume() -> None:
    ctx = _CTX
    if ctx is not None and ctx.watchdog is not None:
        ctx.watchdog.resume()
