"""Per-stage timing, structured logging, and TPU profiler hooks.

The reference has no tracing or metrics at all — its only instrumentation is
wall-clock elapsed/remaining in the auto-scan popup (server/gui.py:1740-1783)
and bare print() calls with a Tk log_callback. This module supplies the
observability layer SURVEY.md section 5 calls for:

  - ``StageTimer``: nested context-managed stage timing with a queryable
    report (the artifact-per-stage pipeline wraps each stage).
  - ``trace``: context manager around ``jax.profiler`` so any stage can emit
    a TensorBoard-loadable device trace (set ``SL3D_TRACE_DIR`` or pass a
    path).
  - ``get_logger``: stdlib logging with levels, honoring ``SL3D_LOG`` and
    forwarding to reference-style ``log_callback`` sinks so GUI/CLI share one
    stream.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as _deadline,
)
from structured_light_for_3d_model_replication_tpu.utils import telemetry

__all__ = ["StageTimer", "OverlapStats", "trace", "get_logger",
           "attach_callback", "attached_callback", "detach_callback",
           "set_heartbeat_hook"]

# ambient progress-heartbeat hook (the faults._PLAN / telemetry._TRACER
# pattern): a coordinated-run worker installs its lease-renewal client
# here so EVERY ``OverlapStats.add`` — the same call that accumulates lane
# walls and feeds the stall watchdog — also renews the worker's leases.
# Liveness-as-seen-by-the-coordinator and actual compute progress come
# from one call site and cannot drift. The hook must never raise (the
# client swallows its own socket errors); one None check when unset.
_HEARTBEAT: "callable | None" = None


def set_heartbeat_hook(hook) -> "callable | None":
    """Install (or clear, with None) the ambient progress-heartbeat hook;
    returns the previous hook so nested scopes can restore it."""
    global _HEARTBEAT
    prev = _HEARTBEAT
    _HEARTBEAT = hook
    return prev

_LOGGER_NAME = "sl3d"


def get_logger(name: str = _LOGGER_NAME) -> logging.Logger:
    """Framework logger; level from SL3D_LOG (DEBUG/INFO/WARNING, default INFO)."""
    logger = logging.getLogger(name)
    if not getattr(logger, "_sl3d_configured", False):
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("SL3D_LOG", "INFO").upper())
        logger.propagate = False
        logger._sl3d_configured = True  # type: ignore[attr-defined]
    return logger


class _CallbackHandler(logging.Handler):
    def __init__(self, callback):
        super().__init__()
        self._cb = callback

    def emit(self, record):  # pragma: no cover - passthrough
        self._cb(self.format(record))


def attach_callback(callback, level=logging.INFO) -> logging.Handler:
    """Forward the framework log to a reference-style ``log_callback(str)``
    sink (the Tk text-widget pattern, server/processing.py:272-274). Returns
    the handler so callers can detach it (``detach_callback``), and prefer
    the context-manager form :func:`attached_callback`, which cannot leak.

    Re-attaching the SAME callback replaces its previous handler instead of
    stacking a duplicate — a caller that forgets to detach between attaches
    (the GUI reconnect loop) no longer leaks a handler (and a duplicated
    line) per attach."""
    logger = get_logger()
    for h in list(logger.handlers):
        # == not `is`: a bound method (gui.log_box.append) is a fresh object
        # on every attribute access, but compares equal to its twin
        if isinstance(h, _CallbackHandler) and h._cb == callback:
            logger.removeHandler(h)
            h.close()
    h = _CallbackHandler(callback)
    h.setLevel(level)
    h.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(h)
    return h


def detach_callback(handler: logging.Handler) -> None:
    """Remove a handler returned by :func:`attach_callback`."""
    get_logger().removeHandler(handler)
    handler.close()


@contextlib.contextmanager
def attached_callback(callback, level=logging.INFO):
    """Scoped :func:`attach_callback`: the handler is detached on exit no
    matter how the block leaves (the guaranteed-detach form)."""
    h = attach_callback(callback, level)
    try:
        yield h
    finally:
        detach_callback(h)


@dataclass
class _Record:
    name: str
    elapsed_s: float
    depth: int


@dataclass
class StageTimer:
    """Nested stage timing:

        timer = StageTimer()
        with timer.stage("decode"):
            ...
        with timer.stage("merge"):
            with timer.stage("merge/icp"):
                ...
        print(timer.report())
    """

    records: list[_Record] = field(default_factory=list)
    _depth: int = 0

    @contextlib.contextmanager
    def stage(self, name: str, log=None):
        t0 = time.perf_counter()
        self._depth += 1
        try:
            yield self
        finally:
            self._depth -= 1
            dt = time.perf_counter() - t0
            self.records.append(_Record(name, dt, self._depth))
            if log is not None:
                log(f"[timing] {name}: {dt:.3f}s")

    def total(self, name: str) -> float:
        return sum(r.elapsed_s for r in self.records if r.name == name)

    def as_dict(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for r in self.records:
            out[r.name] = out.get(r.name, 0.0) + r.elapsed_s
        return out

    def report(self) -> str:
        # records complete innermost-first; display in completion order with
        # indentation from nesting depth
        lines = [f"{'  ' * r.depth}{r.name:<32} {r.elapsed_s:9.3f}s"
                 for r in self.records]
        return "\n".join(lines)


class OverlapStats:
    """Overlap accounting for a pipelined executor (load / compute / clean /
    write — the clean lane is zero unless the executor runs the fused
    pipeline's per-view cleanup stage).

    Worker threads accumulate per-stage wall time with ``add``; the owner
    stamps the end-to-end wall with ``finish``. The win of a pipeline is
    then *measurable*, not asserted: ``critical_path_s`` strictly below
    ``load_s + compute_s + write_s`` (the ``serial_sum_s``) means stages
    genuinely ran concurrently; equality means the pipeline degenerated to
    the serial schedule. ``sample_queue`` records prefetch-queue depth at
    each scheduling step — the backpressure gauge (a queue pinned at 0
    means compute is starved by I/O; pinned at the bound means I/O is
    ahead and the bound is doing its job).

    Memory is O(1) in run length: queue-depth and per-launch gauges are
    exact running aggregates (count/sum/min/max), never retained sample
    lists — a multi-thousand-view serving run costs the same bytes as a
    4-view test, and the reported gauges are unchanged on runs of any
    size because the aggregates are exact, not sampled (ISSUE-6
    satellite).

    Flight recorder: when a :mod:`~.utils.telemetry` tracer is active,
    ``add``/``add_pair_launch`` emit the per-lane span events and the
    retry/failure/launch accessors emit instants — journal-derived lane
    walls and these sums come from the SAME calls, so the two layers
    cannot drift. Disabled cost is one module-global None check.
    """

    _STAGES = ("load", "transfer", "compute", "clean", "write", "register")

    def __init__(self):
        self._lock = threading.Lock()
        self._stage_s = {s: 0.0 for s in self._STAGES}
        self._retries = {s: 0 for s in self._STAGES}
        self._failures = {s: 0 for s in self._STAGES}
        self._items = 0
        # queue-depth gauge: exact running aggregates, not a sample list
        self._q_n = 0
        self._q_sum = 0
        self._q_max = 0
        # batch-launch accounting (the view-batched executor): how many
        # device launches carried how many real views, and the first
        # dispatch wall per bucket size (the compile-cost proxy — later
        # launches of the same bucket reuse the executable)
        self._launches = 0
        self._views_dispatched = 0
        self._bv_min: int | None = None
        self._bv_max: int | None = None
        self._bucket_first_s: dict[int, float] = {}
        # register-lane launch accounting (the streaming merge): how many
        # pair-registration launches carried how many real pairs
        self._pair_launches = 0
        self._pairs_dispatched = 0
        # device<->host transfer accounting (bytes, exact running sums):
        # ``frames`` is the irreducible input upload (the stripe stacks) so
        # the fused-vs-discrete comparison can subtract it and compare only
        # the cloud-path traffic the fusion is supposed to eliminate
        self._h2d_bytes = 0
        self._d2h_bytes = 0
        self._frame_bytes = 0
        # what the frame uploads would have cost unpacked (raw u8 stacks);
        # equals _frame_bytes when the ingest is raw, ~8x it when packed
        self._frame_raw_bytes = 0
        # pod-fabric blob traffic (pipeline/blobstore.py): L2 fetches
        # that saved a recompute, write-through pushes, and pushes the
        # store already held (dedup). All zero off-fabric
        self._fabric_fetched = 0
        self._fabric_pushed = 0
        self._fabric_deduped = 0
        # per-kernel launch accounting: name -> [launches, wall_s, bytes]
        self._kernels: dict[str, list] = {}
        # incremental-assembly fold lane (merge.incremental pods): fold
        # wall + folded view/pair counts, and the tail wall from
        # last-item-settled to artifacts-on-disk. All zero/None otherwise
        self._asm_fold_s = 0.0
        self._asm_views = 0
        self._asm_pairs = 0
        self._asm_tail_s: float | None = None
        self.critical_path_s = 0.0

    def add(self, stage: str, elapsed_s: float, items: int = 0,
            view=None) -> None:
        """Accumulate ``elapsed_s`` of wall time into ``stage`` (thread-safe).
        ``view`` (a name or index) only annotates the trace span — it never
        changes the aggregate accounting."""
        if stage not in self._stage_s:
            raise ValueError(f"unknown pipeline stage {stage!r}; "
                             f"valid: {self._STAGES}")
        with self._lock:
            self._stage_s[stage] += elapsed_s
            self._items += items
        # lane heartbeat for the stall watchdog — emitted from the SAME
        # call that accumulates the lane wall (the telemetry can't-drift
        # pattern), so liveness and accounting cannot disagree. One None
        # check when no watchdog is armed.
        _deadline.beat(stage)
        hb = _HEARTBEAT
        if hb is not None:   # coordinated-run lease renewal, same call site
            hb(stage)
        tr = telemetry.current()
        if tr is not None:
            tr.lane(stage, elapsed_s, view=view)

    def add_retry(self, stage: str) -> None:
        """Count one transient-fault retry in a lane (the resilience layer's
        per-lane gauge: a climbing load retry count with a flat failure
        count means backoff is absorbing the blips it is meant to)."""
        if stage not in self._retries:
            raise ValueError(f"unknown pipeline stage {stage!r}")
        with self._lock:
            self._retries[stage] += 1
        tr = telemetry.current()
        if tr is not None:
            tr.instant("lane.retry", lane=stage)

    def add_failure(self, stage: str) -> None:
        """Count one exhausted/permanent per-item failure in a lane."""
        if stage not in self._failures:
            raise ValueError(f"unknown pipeline stage {stage!r}")
        with self._lock:
            self._failures[stage] += 1
        tr = telemetry.current()
        if tr is not None:
            tr.instant("lane.failure", lane=stage)

    def add_launch(self, n_views: int, bucket: int,
                   dispatch_s: float) -> None:
        """Record one batched device launch carrying ``n_views`` real views
        padded to ``bucket`` slots; ``dispatch_s`` is the (async) dispatch
        wall — dominated by trace+compile the first time a bucket is seen,
        near-zero after (the no-retrace gauge)."""
        n = int(n_views)
        with self._lock:
            self._launches += 1
            self._views_dispatched += n
            self._bv_min = n if self._bv_min is None else min(self._bv_min, n)
            self._bv_max = n if self._bv_max is None else max(self._bv_max, n)
            if bucket not in self._bucket_first_s:
                self._bucket_first_s[int(bucket)] = round(dispatch_s, 4)
        tr = telemetry.current()
        if tr is not None:
            tr.instant("launch", views=n, bucket=int(bucket),
                       dispatch_s=round(dispatch_s, 6))

    def add_pair_launch(self, n_pairs: int, dispatch_s: float) -> None:
        """Record one register-lane launch carrying ``n_pairs`` real pairs
        (group padding excluded); ``dispatch_s`` accumulates into the
        ``register`` lane as well, so register_s vs critical_path_s reads
        directly as how much pair registration the stream hid."""
        n = int(n_pairs)
        with self._lock:
            self._pair_launches += 1
            self._pairs_dispatched += n
            self._stage_s["register"] += dispatch_s
        _deadline.beat("register")
        hb = _HEARTBEAT
        if hb is not None:
            hb("register")
        tr = telemetry.current()
        if tr is not None:
            # the register wall includes launch dispatch — mirror it as a
            # lane span so journal-derived walls stay equal to register_s
            tr.lane("register", dispatch_s, pairs=n)
            tr.instant("pair_launch", pairs=n,
                       dispatch_s=round(dispatch_s, 6))

    def add_transfer(self, h2d: int = 0, d2h: int = 0,
                     frames: int = 0, frames_raw: int = 0) -> None:
        """Accumulate device<->host transfer bytes. ``frames`` counts the
        stripe-frame upload separately (it also adds into ``h2d``): every
        arm pays it, so the fused-vs-discrete byte ratio subtracts it and
        compares only the cloud-path round-trips fusion removes.
        ``frames_raw`` is the unpacked size of the same stacks — when the
        packed ingest lane is on, ``frames`` is the wire size (~1/8th) and
        ``frames_raw`` what a raw upload would have cost; defaults to
        ``frames`` so the raw lane needs no changes."""
        h, d, fr = int(h2d), int(d2h), int(frames)
        fr_raw = int(frames_raw) or fr
        with self._lock:
            self._h2d_bytes += h + fr
            self._d2h_bytes += d
            self._frame_bytes += fr
            self._frame_raw_bytes += fr_raw
        tr = telemetry.current()
        if tr is not None:
            tr.instant("transfer.bytes", h2d=h + fr or None, d2h=d or None,
                       frames=fr or None,
                       frames_raw=fr_raw if fr_raw != fr else None)
            if fr and fr_raw > fr:
                tr.instant("transfer.packed_ratio",
                           ratio=round(fr_raw / fr, 3),
                           wire=fr, raw=fr_raw)

    def add_fabric(self, fetched: int = 0, pushed: int = 0,
                   deduped: int = 0) -> None:
        """Accumulate pod-fabric blob bytes: ``fetched`` (L2 hit promoted
        into L1), ``pushed`` (write-through publish that L2 accepted), and
        ``deduped`` (push the store already held — bytes that crossed the
        wire only to be recognized). The journal instant is emitted from
        this same call, so ``sl3d report``'s fabric line cross-checks
        these counters by construction."""
        f, p, d = int(fetched), int(pushed), int(deduped)
        with self._lock:
            self._fabric_fetched += f
            self._fabric_pushed += p
            self._fabric_deduped += d
        tr = telemetry.current()
        if tr is not None:
            tr.instant("fabric.bytes", fetched=f or None, pushed=p or None,
                       deduped=d or None)

    def add_kernel(self, name: str, wall_s: float, bucket=None,
                   bytes_moved: int = 0) -> None:
        """Record one kernel-lane launch (``fused_view``, ``knn_mean``,
        ``ransac_score``): wall, optional bucket, and bytes moved across
        the host boundary on its behalf. The span instant is emitted from
        this same call (the can't-drift pattern)."""
        w = float(wall_s)
        with self._lock:
            agg = self._kernels.setdefault(name, [0, 0.0, 0])
            agg[0] += 1
            agg[1] += w
            agg[2] += int(bytes_moved)
        tr = telemetry.current()
        if tr is not None:
            tr.instant(f"kernel.{name}", wall_s=round(w, 6),
                       bucket=int(bucket) if bucket is not None else None,
                       bytes=int(bytes_moved) or None)

    def add_fold(self, kind: str, idx: int, dur_s: float) -> None:
        """Record one incremental-assembly fold (``kind`` 'view' or
        'pair'). The pod phase has no live tracer (coordinated dispatch
        happens before run_pipeline opens one), so the assembler buffers
        its fold events and the assembly pass REPLAYS them through here —
        the ``assembly`` lane span and this aggregate come from the same
        call (can't-drift), they just both land at replay time."""
        d = float(dur_s)
        with self._lock:
            self._asm_fold_s += d
            if kind == "view":
                self._asm_views += 1
            else:
                self._asm_pairs += 1
        tr = telemetry.current()
        if tr is not None:
            tr.lane("assembly", d, **{str(kind): int(idx)})

    def set_assembly_tail(self, tail_s: float, info: dict | None = None) \
            -> None:
        """Stamp the assembly-tail wall (last-item-settled ->
        artifacts-on-disk) and emit the ``assembly.tail`` journal instant
        from the SAME call — the report's ≤1% drift cross-check between
        the journal and the metrics gauge rides on this single store."""
        t = float(tail_s)
        with self._lock:
            self._asm_tail_s = t
        tr = telemetry.current()
        if tr is not None:
            tr.instant("assembly.tail", **{"tail_s": round(t, 6),
                                           **(info or {})})

    def assembly_snapshot(self) -> dict:
        """The assembly-lane gauges alone (for a late overlap update —
        the tail is only known after the main as_dict snapshot)."""
        with self._lock:
            out = {"assembly_s": round(self._asm_fold_s, 4),
                   "assembly_folded_views": self._asm_views,
                   "assembly_folded_pairs": self._asm_pairs}
            if self._asm_tail_s is not None:
                out["assembly_tail_s"] = round(self._asm_tail_s, 4)
            return out

    def sample_queue(self, depth: int) -> None:
        d = int(depth)
        with self._lock:
            self._q_n += 1
            self._q_sum += d
            if d > self._q_max:
                self._q_max = d

    def finish(self, critical_path_s: float) -> None:
        self.critical_path_s = critical_path_s
        tr = telemetry.current()
        if tr is not None:
            tr.instant("executor.finish",
                       critical_path_s=round(critical_path_s, 6))

    @property
    def serial_sum_s(self) -> float:
        return sum(self._stage_s.values())

    def as_dict(self) -> dict:
        """The bench/report payload: per-stage walls, critical path, gauges."""
        out = {f"{s}_s": round(v, 4) for s, v in self._stage_s.items()}
        out["critical_path_s"] = round(self.critical_path_s, 4)
        out["serial_sum_s"] = round(self.serial_sum_s, 4)
        out["overlap_ratio"] = (round(self.serial_sum_s / self.critical_path_s, 3)
                                if self.critical_path_s > 0 else None)
        out["items"] = self._items
        out["max_queue_depth"] = self._q_max
        out["mean_queue_depth"] = (round(self._q_sum / self._q_n, 2)
                                   if self._q_n else 0.0)
        out["retries"] = dict(self._retries)
        out["failures"] = dict(self._failures)
        out["retry_total"] = sum(self._retries.values())
        out["failure_total"] = sum(self._failures.values())
        # batched-launch gauges (zeros/None on the per-view executors);
        # the per-item normalizations make batched and per-view lines
        # directly comparable
        out["launches"] = self._launches
        out["views_dispatched"] = self._views_dispatched
        out["mean_views_per_launch"] = (
            round(self._views_dispatched / self._launches, 2)
            if self._launches else 0.0)
        out["min_views_per_launch"] = self._bv_min or 0
        out["max_views_per_launch"] = self._bv_max or 0
        out["bucket_first_dispatch_s"] = {
            str(k): v for k, v in sorted(self._bucket_first_s.items())}
        # register-lane gauges (zeros on runs without a streaming merge)
        out["pair_launches"] = self._pair_launches
        out["pairs_dispatched"] = self._pairs_dispatched
        out["mean_pairs_per_launch"] = (
            round(self._pairs_dispatched / self._pair_launches, 2)
            if self._pair_launches else 0.0)
        # transfer-byte + kernel gauges (zeros on unaccounted paths)
        out["transfer_bytes_h2d"] = self._h2d_bytes
        out["transfer_bytes_d2h"] = self._d2h_bytes
        out["transfer_bytes_frames"] = self._frame_bytes
        out["transfer_bytes_frames_raw"] = self._frame_raw_bytes
        out["frame_bytes_ratio"] = (
            round(self._frame_raw_bytes / self._frame_bytes, 2)
            if self._frame_bytes else None)
        out["fabric_bytes_fetched"] = self._fabric_fetched
        out["fabric_bytes_pushed"] = self._fabric_pushed
        out["fabric_bytes_deduped"] = self._fabric_deduped
        out["kernels"] = {
            name: {"launches": agg[0], "wall_s": round(agg[1], 4),
                   "bytes_moved": agg[2]}
            for name, agg in sorted(self._kernels.items())}
        # incremental-assembly gauges (zeros off-pod / knob off)
        out.update(self.assembly_snapshot())
        items = self._items
        out["compute_per_item_s"] = (round(self._stage_s["compute"] / items, 4)
                                     if items else None)
        out["transfer_per_item_s"] = (
            round(self._stage_s["transfer"] / items, 4) if items else None)
        return out

    def summary(self) -> str:
        d = self.as_dict()
        clean = (f" + clean {d['clean_s']}s" if d.get("clean_s") else "")
        xfer = (f" + transfer {d['transfer_s']}s" if d.get("transfer_s")
                else "")
        resil = ""
        if d["retry_total"] or d["failure_total"]:
            resil = (f", {d['retry_total']} retries / "
                     f"{d['failure_total']} failures")
        batched = ""
        if d["launches"]:
            batched = (f", {d['views_dispatched']} views in {d['launches']} "
                       f"launches (mean {d['mean_views_per_launch']}/launch)")
        if d["pair_launches"]:
            batched += (f", {d['pairs_dispatched']} pairs in "
                        f"{d['pair_launches']} register launches "
                        f"(register {d['register_s']}s)")
        return (f"load {d['load_s']}s{xfer} + compute {d['compute_s']}s"
                f"{clean} + write {d['write_s']}s = {d['serial_sum_s']}s "
                f"serial-equivalent in {d['critical_path_s']}s wall "
                f"(overlap x{d['overlap_ratio']}, queue depth "
                f"max {d['max_queue_depth']} mean {d['mean_queue_depth']}"
                f"{batched}{resil})")


# jax.profiler supports exactly ONE active trace per process and raises on a
# nested start_trace — the pipelined executor wraps its whole schedule in
# trace() while per-view helpers (the serial fallback lane, merge_views
# called mid-pipeline) carry their own trace() calls, so nesting is a real
# code path, not an error. Track the active trace here and no-op inner
# entries (reentrancy satellite, ISSUE 6).
_TRACE_LOCK = threading.Lock()
_TRACE_DEPTH = 0


@contextlib.contextmanager
def trace(trace_dir: str | None = None):
    """Device-level profiler trace around a block (TensorBoard format).

    No-ops unless a directory is given or ``SL3D_TRACE_DIR`` is set — safe to
    leave in production paths. Reentrant: entering while a ``jax.profiler``
    trace is already active (any thread) no-ops the inner call instead of
    raising, so nested stage instrumentation composes; the OUTER call owns
    the device trace and everything inside lands in its capture.
    """
    global _TRACE_DEPTH
    trace_dir = trace_dir or os.environ.get("SL3D_TRACE_DIR")
    if not trace_dir:
        yield
        return
    with _TRACE_LOCK:
        owner = _TRACE_DEPTH == 0
        _TRACE_DEPTH += 1
    try:
        if not owner:
            yield
            return
        import jax

        jax.profiler.start_trace(trace_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
    finally:
        with _TRACE_LOCK:
            _TRACE_DEPTH -= 1
