"""Synthetic scan generator: analytic scenes rendered through a synthetic rig.

The reference has no test harness at all (SURVEY.md section 4); this module is
the foundation of ours. It renders Gray-code pattern stacks of known analytic
geometry (sphere, plane, composite object-on-background) through a synthetic
projector-camera rig, producing capture stacks whose exact decode values and
triangulated 3D points are known in closed form — golden data for every stage
from decode through 360-degree merge, with no hardware in the loop.

Conventions match the reference rig (server/sl_system.py:336-425):
camera at the origin, x_proj = R x_cam + T, millimeter units.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from structured_light_for_3d_model_replication_tpu.calib.geometry import build_calibration
from structured_light_for_3d_model_replication_tpu.ops import graycode

__all__ = ["Rig", "Sphere", "Plane", "Scene", "default_rig", "render_scene",
           "rotate_y", "turntable_poses"]


@dataclass
class Rig:
    cam_K: np.ndarray
    proj_K: np.ndarray
    R: np.ndarray        # camera -> projector rotation
    T: np.ndarray        # camera -> projector translation (mm)
    cam_size: tuple[int, int]   # (width, height)
    proj_size: tuple[int, int]  # (width, height)

    def calibration(self) -> dict:
        return build_calibration(
            self.cam_K, np.zeros(5), self.proj_K, self.R, self.T,
            self.cam_size[0], self.cam_size[1],
            self.proj_size[0], self.proj_size[1],
        )


def _rot_y(deg: float) -> np.ndarray:
    a = np.deg2rad(deg)
    c, s = np.cos(a), np.sin(a)
    return np.array([[c, 0, s], [0, 1, 0], [-s, 0, c]], np.float64)


def rotate_y(deg: float) -> np.ndarray:
    """Rotation about the +y (vertical) axis — the turntable axis."""
    return _rot_y(deg)


def default_rig(cam_size=(320, 240), proj_size=(256, 128)) -> Rig:
    """A plausible scanner rig: projector ~150 mm left of the camera, toed in."""
    cw, ch = cam_size
    pw, ph = proj_size
    cam_K = np.array([[1.1 * cw, 0, cw / 2 - 0.5],
                      [0, 1.1 * cw, ch / 2 - 0.5],
                      [0, 0, 1]], np.float64)
    proj_K = np.array([[1.3 * pw, 0, pw / 2 - 0.5],
                       [0, 1.3 * pw, ph / 2 - 0.5],
                       [0, 0, 1]], np.float64)
    R = _rot_y(-12.0)  # projector toed in toward the scene
    # horizontal AND vertical baseline: row-plane triangulation (row_mode=2) is
    # ill-conditioned without a vertical offset between projector and camera
    T = np.array([150.0, 80.0, 20.0], np.float64)
    return Rig(cam_K, proj_K, R, T, cam_size, proj_size)


@dataclass
class Sphere:
    center: np.ndarray
    radius: float
    albedo: np.ndarray = field(default_factory=lambda: np.array([0.8, 0.6, 0.4]))

    def intersect(self, origins, dirs):
        """Nearest positive ray parameter t or +inf. origins/dirs: [N,3]."""
        oc = origins - self.center[None, :]
        b = np.sum(oc * dirs, axis=-1)
        c = np.sum(oc * oc, axis=-1) - self.radius**2
        disc = b * b - c
        hit = disc >= 0
        sq = np.sqrt(np.where(hit, disc, 0))
        t = np.where(hit, -b - sq, np.inf)
        t = np.where(t > 1e-6, t, np.where(hit, -b + sq, np.inf))
        return np.where(t > 1e-6, t, np.inf)

    def transformed(self, R, t):
        return Sphere(R @ self.center + t, self.radius, self.albedo)


@dataclass
class Plane:
    normal: np.ndarray
    d: float  # plane: normal . x + d = 0
    albedo: np.ndarray = field(default_factory=lambda: np.array([0.5, 0.5, 0.55]))

    def intersect(self, origins, dirs):
        denom = dirs @ self.normal
        numer = origins @ self.normal + self.d
        ok = np.abs(denom) > 1e-9
        t = np.where(ok, -numer / np.where(ok, denom, 1), np.inf)
        return np.where(t > 1e-6, t, np.inf)

    def transformed(self, R, t):
        n2 = R @ self.normal
        # n.x + d = 0 -> after x' = R x + t: n2 . x' + (d - n2 . t) = 0
        return Plane(n2, self.d - n2 @ t, self.albedo)


@dataclass
class Scene:
    """A list of analytic primitives; first hit wins."""

    objects: list

    def transformed(self, R, t):
        return Scene([o.transformed(R, t) for o in self.objects])

    def trace(self, origins, dirs):
        """Returns (t [N], object_index [N]; -1 = miss)."""
        n = dirs.shape[0]
        best_t = np.full(n, np.inf)
        best_i = np.full(n, -1, np.int64)
        for i, obj in enumerate(self.objects):
            t = obj.intersect(origins, dirs)
            closer = t < best_t
            best_t = np.where(closer, t, best_t)
            best_i = np.where(closer, i, best_i)
        return best_t, best_i


def sphere_on_background(depth: float = 420.0, radius: float = 70.0,
                         back_depth: float = 560.0) -> Scene:
    """The canonical test scene: a sphere in front of a background wall."""
    return Scene([
        Sphere(np.array([0.0, 0.0, depth]), radius),
        Plane(np.array([0.0, 0.0, -1.0]), back_depth),
    ])


def render_scene(rig: Rig, scene: Scene, brightness: int = 200,
                 ambient: float = 6.0, noise_sigma: float = 0.0,
                 rng: np.random.Generator | None = None,
                 downsample: int = 1):
    """Render the full Gray-code capture sequence of ``scene`` through ``rig``.

    Returns (frames uint8 [F,H,W], ground_truth dict). Ground truth carries the
    exact projector coordinates each camera pixel sees (integer column/row of
    the projector pixel illuminating it), the true 3D point per pixel, and the
    hit mask — everything decode and triangulation must reproduce.
    """
    rng = rng or np.random.default_rng(0)
    cw, ch = rig.cam_size
    pw, ph = rig.proj_size

    # camera rays (z=1 parameterization; t is then metric along the unit ray)
    u, v = np.meshgrid(np.arange(cw, dtype=np.float64),
                       np.arange(ch, dtype=np.float64))
    x = (u - rig.cam_K[0, 2]) / rig.cam_K[0, 0]
    y = (v - rig.cam_K[1, 2]) / rig.cam_K[1, 1]
    dirs = np.stack([x, y, np.ones_like(x)], axis=-1).reshape(-1, 3)
    dirs /= np.linalg.norm(dirs, axis=-1, keepdims=True)
    origins = np.zeros_like(dirs)

    t, obj_idx = scene.trace(origins, dirs)
    hit = np.isfinite(t)
    pts = origins + dirs * np.where(hit, t, 0.0)[:, None]  # camera-frame 3D

    # project hit points into the projector
    pp = pts @ rig.R.T + rig.T[None, :]
    in_front = pp[:, 2] > 1e-6
    zz = np.where(in_front, pp[:, 2], 1.0)
    up = rig.proj_K[0, 0] * pp[:, 0] / zz + rig.proj_K[0, 2]
    vp = rig.proj_K[1, 1] * pp[:, 1] / zz + rig.proj_K[1, 2]
    ui = np.round(up).astype(np.int64)
    vi = np.round(vp).astype(np.int64)
    lit = hit & in_front & (ui >= 0) & (ui < pw) & (vi >= 0) & (vi < ph)
    ui_c = np.clip(ui, 0, pw - 1)
    vi_c = np.clip(vi, 0, ph - 1)

    albedos = np.array([o.albedo for o in scene.objects] + [np.zeros(3)])
    alb = albedos[obj_idx][:, :3]            # [N,3]; miss -> index -1 -> zeros row
    gray_alb = alb.mean(axis=-1)

    patterns = graycode.generate_pattern_stack(pw, ph, brightness, downsample)
    f = patterns.shape[0]
    # pattern value seen by each camera pixel, per frame: [F, N]
    seen = patterns[:, vi_c, ui_c].astype(np.float64) * lit[None, :]
    img = seen * gray_alb[None, :] + ambient
    if noise_sigma > 0:
        img = img + rng.normal(0, noise_sigma, img.shape)
    frames = np.clip(img, 0, 255).astype(np.uint8).reshape(f, ch, cw)

    # color texture as seen under the white frame
    tex = np.clip(
        brightness * alb * lit[:, None] + ambient, 0, 255
    ).astype(np.uint8).reshape(ch, cw, 3)

    gt = {
        "proj_col": ui_c.reshape(ch, cw),
        "proj_row": vi_c.reshape(ch, cw),
        "points": pts.reshape(ch, cw, 3).astype(np.float64),
        "lit": lit.reshape(ch, cw),
        "hit": hit.reshape(ch, cw),
        "object_index": obj_idx.reshape(ch, cw),
        "texture": tex,
    }
    return frames, gt


def turntable_poses(n_views: int = 12, step_deg: float = 30.0,
                    pivot: np.ndarray | None = None):
    """Ground-truth object poses for a turntable sweep about +y through ``pivot``.

    Returns a list of (R, t) with x_view_i = R @ (x_0 - pivot) + pivot: what the
    physical turntable does to the object between captures (gui.py:1700-1787's
    rotation loop), available here in closed form for registration tests.
    """
    pivot = np.zeros(3) if pivot is None else np.asarray(pivot, np.float64)
    poses = []
    for i in range(n_views):
        R = _rot_y(step_deg * i)
        t = pivot - R @ pivot
        poses.append((R, t))
    return poses
