"""One-TPU-client-at-a-time advisory lock for this repo's tooling.

Every tunnel wedge on record (BENCH_NOTES.md) traces to one of two
triggers: concurrent TPU clients on this one-core host, or a client
killed mid-claim. The tools already self-serialize *within* one chain
(tools/tpu_session.py runs steps strictly sequentially), but nothing
stopped two independent entry points — the driver's round-end
``bench.py``, a ``tools/tpu_watch.py`` probe, a manual smoke run — from
opening claims concurrently. This module gives them all one advisory
``flock`` on ``<repo>/.tpu_lock``.

flock, not a pidfile: the kernel releases the lock the instant the
holder's fd closes — including SIGKILL of the whole process group — so
there is no stale-lock state to reap after the kills the wedge playbook
sometimes requires.

Holders spawning TPU-using children set ``SL3D_TPU_LOCK_HELD=<holder pid>``
in the child environment; children then skip acquisition instead of
deadlocking against their parent's lock. A pid-valued claim is *watched*:
the child starts a daemon thread that periodically tries the flock itself
(non-blocking), and the moment the claim goes free — the holder died while
the child still runs, e.g. a session killed alone rather than by process
group — the child re-takes it on its own fd so the tree keeps excluding
other TPU clients. The legacy value ``1`` is still accepted but arms no
watcher.
"""
from __future__ import annotations

import fcntl
import os
import threading
import time

__all__ = ["acquire_tpu_lock", "probe_tpu_lock", "held_by_parent",
           "HOLD_ENV"]

HOLD_ENV = "SL3D_TPU_LOCK_HELD"


def probe_tpu_lock(root: str) -> tuple[bool, str]:
    """Report the lock's state without contending for it.

    Returns (held, detail). Uses a shared (LOCK_SH) non-blocking probe —
    it fails iff someone holds the exclusive claim, and two concurrent
    probes never conflict with each other; the instant of SH hold cannot
    be observed by another probe, only by an exactly-simultaneous
    exclusive acquire (vanishingly small window vs probing with LOCK_EX).
    """
    path = os.path.join(root, ".tpu_lock")
    if not os.path.exists(path):
        return False, "never taken here"
    with open(path, "a+") as f:
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_SH | fcntl.LOCK_NB)
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            return False, "free"
        except OSError:
            f.seek(0)
            return True, f.read().strip() or "unknown holder"


def held_by_parent() -> bool:
    """True when an ancestor process already holds the lock for us."""
    return os.environ.get(HOLD_ENV, "") not in ("", "0")


def _watch_holder(f, holder_pid: int, poll: float) -> None:
    """Daemon-thread body: if the claim-holding ancestor dies while we
    run, its flock is gone and a new TPU client could start concurrently
    with us — the exact wedge the lock exists to prevent.

    The probe is the flock itself, not pid liveness: a non-blocking
    LOCK_EX attempt fails while ANY claim exists (the parent's, or a
    sibling orphan's that already re-claimed) and succeeds the moment the
    file goes free — immune to pid reuse and to zombies (a zombie has
    closed its fds, releasing the flock, yet still answers kill(pid,0)).
    ``holder_pid`` is only used to warn when the named holder is provably
    gone but the lock is held by someone else (a raced external claimant:
    concurrency already happened; make it visible for the post-mortem)."""
    import sys

    warned = False
    while True:
        time.sleep(poll)
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ValueError:
            return  # our own lock file was closed: this client is done
        except OSError:
            # claim still held somewhere — normal while the parent lives
            if not warned and not _pid_alive(holder_pid):
                print(f"[tpulock] WARNING: claim holder pid {holder_pid} "
                      f"is gone but .tpu_lock is held elsewhere — a new "
                      f"client may be running concurrently with this "
                      f"orphaned one (pid {os.getpid()})", file=sys.stderr)
                warned = True
            continue
        try:  # claim re-established in THIS process; leave a breadcrumb
            f.seek(0)
            f.truncate()
            f.write(f"pid {os.getpid()} (orphan re-claim) since "
                    f"{time.strftime('%H:%M:%S')}\n")
            f.flush()
        except OSError:
            pass
        print(f"[tpulock] claim holder pid {holder_pid} gone — re-taken "
              f"by orphaned child pid {os.getpid()}", file=sys.stderr)
        return


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM etc: assume alive (conservative)


def acquire_tpu_lock(root: str, timeout: float = 0.0, poll: float = 5.0):
    """Try to take the repo-wide TPU claim lock.

    Returns the open file object (hold it for the claim's lifetime; the
    lock dies with the fd) or ``None`` if another process still held it
    after ``timeout`` seconds. ``timeout=0`` means one non-blocking try.
    A caller whose parent set ``SL3D_TPU_LOCK_HELD=1`` gets a no-lock
    sentinel open file immediately (the parent's claim covers it).
    """
    path = os.path.join(root, ".tpu_lock")
    f = open(path, "a+")
    if held_by_parent():
        # parent's flock covers this process tree; when the value names
        # the holder's pid, watch it so an orphaned child re-claims
        val = os.environ.get(HOLD_ENV, "")
        if val.isdigit() and int(val) > 1:
            threading.Thread(target=_watch_holder,
                             args=(f, int(val), 10.0), daemon=True).start()
        return f
    deadline = time.monotonic() + timeout
    while True:
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            try:  # who-holds breadcrumb for humans; lock truth is the flock
                f.seek(0)
                f.truncate()
                f.write(f"pid {os.getpid()} since {time.strftime('%H:%M:%S')}\n")
                f.flush()
            except OSError:
                pass
            return f
        except OSError:
            if time.monotonic() >= deadline:
                f.close()
                return None
            time.sleep(min(poll, max(0.1, deadline - time.monotonic())))
