"""One-TPU-client-at-a-time advisory lock for this repo's tooling.

Every tunnel wedge on record (BENCH_NOTES.md) traces to one of two
triggers: concurrent TPU clients on this one-core host, or a client
killed mid-claim. The tools already self-serialize *within* one chain
(tools/tpu_session.py runs steps strictly sequentially), but nothing
stopped two independent entry points — the driver's round-end
``bench.py``, a ``tools/tpu_watch.py`` probe, a manual smoke run — from
opening claims concurrently. This module gives them all one advisory
``flock`` on ``<repo>/.tpu_lock``.

flock, not a pidfile: the kernel releases the lock the instant the
holder's fd closes — including SIGKILL of the whole process group — so
there is no stale-lock state to reap after the kills the wedge playbook
sometimes requires.

Holders spawning TPU-using children set ``SL3D_TPU_LOCK_HELD=1`` in the
child environment; children then skip acquisition instead of deadlocking
against their parent's lock.
"""
from __future__ import annotations

import fcntl
import os
import time

__all__ = ["acquire_tpu_lock", "probe_tpu_lock", "held_by_parent",
           "HOLD_ENV"]

HOLD_ENV = "SL3D_TPU_LOCK_HELD"


def probe_tpu_lock(root: str) -> tuple[bool, str]:
    """Report the lock's state without contending for it.

    Returns (held, detail). Uses a shared (LOCK_SH) non-blocking probe —
    it fails iff someone holds the exclusive claim, and two concurrent
    probes never conflict with each other; the instant of SH hold cannot
    be observed by another probe, only by an exactly-simultaneous
    exclusive acquire (vanishingly small window vs probing with LOCK_EX).
    """
    path = os.path.join(root, ".tpu_lock")
    if not os.path.exists(path):
        return False, "never taken here"
    with open(path, "a+") as f:
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_SH | fcntl.LOCK_NB)
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            return False, "free"
        except OSError:
            f.seek(0)
            return True, f.read().strip() or "unknown holder"


def held_by_parent() -> bool:
    """True when an ancestor process already holds the lock for us."""
    return os.environ.get(HOLD_ENV, "") == "1"


def acquire_tpu_lock(root: str, timeout: float = 0.0, poll: float = 5.0):
    """Try to take the repo-wide TPU claim lock.

    Returns the open file object (hold it for the claim's lifetime; the
    lock dies with the fd) or ``None`` if another process still held it
    after ``timeout`` seconds. ``timeout=0`` means one non-blocking try.
    A caller whose parent set ``SL3D_TPU_LOCK_HELD=1`` gets a no-lock
    sentinel open file immediately (the parent's claim covers it).
    """
    path = os.path.join(root, ".tpu_lock")
    f = open(path, "a+")
    if held_by_parent():
        return f  # parent's flock covers this process tree
    deadline = time.monotonic() + timeout
    while True:
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            try:  # who-holds breadcrumb for humans; lock truth is the flock
                f.seek(0)
                f.truncate()
                f.write(f"pid {os.getpid()} since {time.strftime('%H:%M:%S')}\n")
                f.flush()
            except OSError:
                pass
            return f
        except OSError:
            if time.monotonic() >= deadline:
                f.close()
                return None
            time.sleep(min(poll, max(0.1, deadline - time.monotonic())))
