"""Accelerator preflight: detect a wedged device tunnel without hanging.

A lost pool grant (e.g. a client SIGKILLed mid-claim) makes PJRT client
creation block indefinitely — ``import jax; jax.devices()`` never returns.
Probing in a SUBPROCESS with a timeout turns that unbounded hang into a
3-minute, clearly-labeled verdict. Shared by bench.py and the accelerator
smoke test so the probe expression/timeout can't drift between them.
"""
from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["accelerator_preflight"]

# init AND execute: a wedged tunnel has two hang signatures — PJRT client
# creation blocking forever (round-3 incidents), and client init succeeding
# while the first device execution stalls with zero socket traffic (round-4
# incident, 2026-07-31: two probes passed, then the smoke run sat 28 min at
# 0 CPU inside its first compile). Running one tiny op catches both; on a
# healthy tunnel it adds ~1-2 s.
_PROBE = """\
import jax
b = jax.default_backend()
if b != "cpu":
    import jax.numpy as jnp
    jax.block_until_ready(jnp.add(jnp.float32(1), jnp.float32(1)))
print(b)
"""


def accelerator_preflight(timeout: float = 180.0, cwd: str | None = None
                          ) -> tuple[str, str]:
    """Probe the ambient jax backend (init + one device op) in a subprocess.

    Returns (status, detail): status is ``"ok"`` (detail = backend name),
    ``"hung"`` (init or first execution exceeded ``timeout``), or
    ``"failed"`` (nonzero exit; detail = stderr tail).
    """
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        probe = subprocess.run([sys.executable, "-c", _PROBE],
                               capture_output=True, text=True,
                               timeout=timeout, env=env, cwd=cwd)
    except subprocess.TimeoutExpired:
        return "hung", (f"backend init/exec exceeded {timeout:.0f}s "
                        f"(tunnel wedged?)")
    if probe.returncode != 0:
        return "failed", (probe.stderr or "")[-300:]
    lines = (probe.stdout or "").strip().splitlines()
    return "ok", (lines[-1] if lines else "?")
