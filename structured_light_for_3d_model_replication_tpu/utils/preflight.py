"""Accelerator preflight: detect a wedged device tunnel without hanging.

A lost pool grant (e.g. a client SIGKILLed mid-claim) makes PJRT client
creation block indefinitely — ``import jax; jax.devices()`` never returns.
Probing in a SUBPROCESS with a timeout turns that unbounded hang into a
3-minute, clearly-labeled verdict. Shared by bench.py and the accelerator
smoke test so the probe expression/timeout can't drift between them.
"""
from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["accelerator_preflight"]

_PROBE = "import jax; print(jax.default_backend())"


def accelerator_preflight(timeout: float = 180.0, cwd: str | None = None
                          ) -> tuple[str, str]:
    """Probe the ambient jax backend in a subprocess.

    Returns (status, detail): status is ``"ok"`` (detail = backend name),
    ``"hung"`` (init exceeded ``timeout``), or ``"failed"`` (nonzero exit;
    detail = stderr tail).
    """
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        probe = subprocess.run([sys.executable, "-c", _PROBE],
                               capture_output=True, text=True,
                               timeout=timeout, env=env, cwd=cwd)
    except subprocess.TimeoutExpired:
        return "hung", f"backend init exceeded {timeout:.0f}s (tunnel wedged?)"
    if probe.returncode != 0:
        return "failed", (probe.stderr or "")[-300:]
    lines = (probe.stdout or "").strip().splitlines()
    return "ok", (lines[-1] if lines else "?")
