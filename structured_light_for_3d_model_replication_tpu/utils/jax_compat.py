"""jax API-layout compatibility shims shared by the sharded ops.

``shard_map`` moved from ``jax.experimental.shard_map`` to ``jax.shard_map``
(deprecated in jax 0.8, removed later), and its replication-checking kwarg
was renamed ``check_rep`` -> ``check_vma`` in the same move. Centralising
the shim here keeps the four call sites (ops/registration,
ops/pointcloud_sharded, ops/poisson_sharded, parallel/scan) from drifting.
"""
from __future__ import annotations

import functools
import re

try:
    from jax import shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - older jax layout
    from jax.experimental.shard_map import shard_map  # type: ignore

    _CHECK_KW = "check_rep"

__all__ = ["shard_map", "shard_map_unchecked", "is_backend_init_error"]


def shard_map_unchecked(**kwargs):
    """``shard_map`` decorator with replication/VMA checking disabled,
    under whichever kwarg name this jax spells it."""
    kwargs[_CHECK_KW] = False
    return functools.partial(shard_map, **kwargs)


def is_backend_init_error(exc: BaseException) -> bool:
    """True for the accelerator plugin's fast-fail at first jax use
    ("Unable/unable to initialize backend ..."), a wedge variant observed
    live (r4). Shared by the CLI's CPU-fallback retry and the per-item
    tolerance in pipeline stages: an init failure is a process-level
    condition, not an item failure — swallowing it per scan would report
    every item failed with the same error and defeat the CPU retry.

    Anchored to the message HEAD: an exception that merely *embeds* the
    phrase (a RuntimeError carrying a child process's stderr tail, say)
    must not trigger the CLI's silent full-command re-run on CPU."""
    return re.match(r"[Uu]nable to initialize backend", str(exc)) is not None
