"""Run-scoped flight recorder: typed event tracing + a metrics registry.

PRs 1-5 turned the fused pipeline into five overlapped lanes (prefetch /
transfer+compute / clean / register / writeback) plus a stage cache and a
fault layer — but ``OverlapStats`` only reports aggregate sums. This module
records *when* things happened, to *which* view/pair/launch, so a slow,
degraded, or stalled run is diagnosable from its artifacts alone:

  - :class:`Tracer` — thread-safe recorder of typed span/instant events,
    appended line-by-line (each line flushed) to a crash-safe
    ``trace.jsonl`` journal in the run's out dir. A ``kill -9`` mid-run
    loses at most one partial trailing line; readers tolerate it.
  - :class:`MetricsRegistry` — dependency-free (stdlib-only) counters,
    gauges, and fixed-bucket histograms with p50/p95/p99, serialized to
    ``metrics.json`` next to the STL and exposable as Prometheus text for
    the future serving process (ROADMAP item 1).
  - :func:`export_chrome_trace` — converts a journal into the Chrome
    trace-event JSON Perfetto/chrome://tracing load, one track per
    (lane, thread), so lane overlap is *visible* on a timeline.

The whole layer is off by default (``observability.trace`` config /
``SL3D_TRACE`` env). Disabled cost is one module-global ``None`` check at
every instrumentation point (the ``faults.fire`` contract): call sites do

    tr = telemetry.current()
    if tr is not None:
        tr.instant("cache.hit", stage=stage)

so the disabled path allocates nothing (asserted in tests/test_telemetry.py)
and the pipeline_trace bench arm holds the disabled-overhead contract
(<= 1.02x vs pipeline_e2e, the fault layer's bar).

Journal schema (``sl3d-trace-v1``) — one JSON object per line:

  meta     first line: {"type":"meta","schema","run_id","t0_unix",
           "host_cpus","device_count","backend", ...}
  span     {"type":"span","ev":"lane"|"stage","t":<s since t0>,
           "dur":<s>,"th":<thread>, "lane"|"stage", "view"/"pair"/...}
  instant  {"type":"instant","ev":<name>,"t","th", event fields...}
           wired events: lane.retry, lane.failure, cache.hit/miss/evict/
           put_error, launch (views/bucket/dispatch_s), pair_launch,
           pair.identity, fault.injected (site/kind[/duration_s]), retry,
           quarantine, executor.finish (critical_path_s), lane.heartbeat
           (throttled liveness marker, >=1/s per lane while a watchdog is
           armed), watchdog.stall (level=soft|hard, age_s, lane ages)
  end      last line on a clean close: {"type":"end","t","events"}

The ``lane`` spans are emitted from *inside* ``OverlapStats.add`` /
``add_pair_launch`` — the same calls that accumulate the per-lane walls —
so journal-derived lane walls and ``OverlapStats`` can never drift (the
cross-check test asserts equality within rounding).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = [
    "SCHEMA", "Tracer", "MetricsRegistry", "current", "activate",
    "deactivate", "new_run_id", "stage", "read_journal",
    "export_chrome_trace", "prometheus_text",
    "set_host_tag", "host_tag", "host_scoped",
]

SCHEMA = "sl3d-trace-v1"

# canonical lane display order (the executor lanes, then run-level tracks;
# "assembly" is the incremental fold lane of merge.incremental pods)
LANE_ORDER = ("load", "transfer", "compute", "clean", "write", "register",
              "assembly", "stage")

# histogram bucket ladders: log-ish spacing for seconds, powers of two for
# per-launch counts. The +inf bucket is implicit (the overflow count).
_SECONDS_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 120.0, 300.0)
_COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


# host-scope identity for coordinated multi-process runs: when N workers
# share one out dir, every run id and crash artifact (failures.json,
# stalls.json, trace.jsonl, metrics.json) must carry the writer's identity
# or the workers clobber each other's evidence. Unset (the default, and
# the coordinator/single-process case) everything keeps its canonical name.
_HOST_TAG: str | None = None


def set_host_tag(tag: str | None) -> str | None:
    """Install this process's host tag (``w<rank>-<pid>`` in worker
    processes; None restores canonical names). Returns the previous tag so
    nested scopes can restore it."""
    global _HOST_TAG
    prev = _HOST_TAG
    _HOST_TAG = tag or None
    return prev


def host_tag() -> str | None:
    return _HOST_TAG


def host_scoped(filename: str) -> str:
    """Stamp the host tag into an artifact filename (before the extension:
    ``failures.json`` -> ``failures.w0-1234.json``). Identity when no tag
    is set — the single-process path is unchanged, byte for byte."""
    if _HOST_TAG is None:
        return filename
    stem, dot, ext = filename.rpartition(".")
    if not dot:
        return f"{filename}.{_HOST_TAG}"
    return f"{stem}.{_HOST_TAG}.{ext}"


def new_run_id() -> str:
    """Sortable, collision-safe run identifier (UTC stamp + random hex;
    the host tag is appended in worker processes so per-host journals
    merge without ambiguity)."""
    rid = (time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
           + "-" + os.urandom(4).hex())
    if _HOST_TAG is not None:
        rid += "-" + _HOST_TAG
    return rid


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def _labelkey(labels: dict, extra: dict | None = None) -> tuple:
    """Canonical (sorted, stringified) label identity. ``extra`` is the
    explicit ``labels={}`` dict — it merges OVER the kwargs form so call
    sites can use label names that aren't valid Python identifiers
    (e.g. dotted stage paths) without name-mangling."""
    merged = dict(labels)
    if extra:
        merged.update(extra)
    return tuple(sorted((k, str(v)) for k, v in merged.items()
                        if v is not None))


class _Histogram:
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets=_SECONDS_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1 = +inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v: float) -> None:
        v = float(v)
        i = 0
        for i, edge in enumerate(self.buckets):  # noqa: B007
            if v <= edge:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> float | None:
        """Bucket-interpolated quantile estimate, clamped to [min, max]."""
        if self.count == 0:
            return None
        rank = q * self.count
        seen = 0.0
        lo = 0.0
        for i, c in enumerate(self.counts):
            hi = (self.buckets[i] if i < len(self.buckets)
                  else (self.max if self.max is not None else lo))
            if seen + c >= rank and c > 0:
                frac = (rank - seen) / c
                est = lo + (hi - lo) * frac
                return max(self.min or 0.0, min(est, self.max or est))
            seen += c
            lo = hi
        return self.max


class MetricsRegistry:
    """Dependency-free counters / gauges / fixed-bucket histograms.

    Thread-safe; serializes to a plain dict (``as_dict``) for
    ``metrics.json`` and to Prometheus exposition text (``to_prometheus``)
    for the future serving process. No third-party client library — the
    container bakes none in, and the exposition format is 20 lines.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, _Histogram] = {}

    def inc(self, name: str, value: float = 1.0, labels: dict | None = None,
            **kwlabels) -> None:
        k = (name, _labelkey(kwlabels, labels))
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: dict | None = None, **kwlabels) -> None:
        with self._lock:
            self._gauges[(name, _labelkey(kwlabels, labels))] = float(value)

    def observe(self, name: str, value: float, buckets=_SECONDS_BUCKETS,
                labels: dict | None = None, **kwlabels) -> None:
        k = (name, _labelkey(kwlabels, labels))
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = _Histogram(buckets)
            h.observe(value)

    def counter_value(self, name: str, labels: dict | None = None,
                      **kwlabels) -> float:
        return self._counters.get((name, _labelkey(kwlabels, labels)), 0.0)

    def as_dict(self) -> dict:
        def row(k, v):
            return {"name": k[0], "labels": dict(k[1]), "value": v}

        with self._lock:
            out = {
                "counters": [row(k, round(v, 6))
                             for k, v in sorted(self._counters.items())],
                "gauges": [row(k, round(v, 6))
                           for k, v in sorted(self._gauges.items())],
                "histograms": [],
            }
            for k, h in sorted(self._hists.items()):
                out["histograms"].append({
                    "name": k[0], "labels": dict(k[1]),
                    "buckets": list(h.buckets),
                    "counts": list(h.counts),
                    "count": h.count, "sum": round(h.sum, 6),
                    "min": h.min, "max": h.max,
                    "p50": h.quantile(0.50), "p95": h.quantile(0.95),
                    "p99": h.quantile(0.99),
                })
        return out

    def to_prometheus(self) -> str:
        return prometheus_text(self.as_dict())


def _prom_escape(v) -> str:
    """Label-value escaping per the Prometheus exposition format: backslash,
    double quote, and newline are the only characters the format escapes.
    Values without them pass through unchanged, so pre-existing call sites
    render byte-identical text."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: dict, extra: dict | None = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_prom_escape(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def prometheus_text(metrics: dict) -> str:
    """Prometheus exposition text from a ``MetricsRegistry.as_dict`` payload
    (or a loaded ``metrics.json``) — so a run's persisted metrics can be
    scraped/re-served without the live registry object."""
    lines: list[str] = []
    typed: set[str] = set()

    def head(name, kind):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for row in metrics.get("counters", []):
        head(row["name"], "counter")
        lines.append(f"{row['name']}{_prom_labels(row['labels'])} "
                     f"{row['value']}")
    for row in metrics.get("gauges", []):
        head(row["name"], "gauge")
        lines.append(f"{row['name']}{_prom_labels(row['labels'])} "
                     f"{row['value']}")
    for h in metrics.get("histograms", []):
        name = h["name"]
        head(name, "histogram")
        cum = 0
        for edge, c in zip(h["buckets"] + ["+Inf"],
                           h["counts"]):
            cum += c
            lines.append(f"{name}_bucket"
                         f"{_prom_labels(h['labels'], {'le': edge})} {cum}")
        lines.append(f"{name}_sum{_prom_labels(h['labels'])} {h['sum']}")
        lines.append(f"{name}_count{_prom_labels(h['labels'])} {h['count']}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------

class Tracer:
    """Append-only journal writer + metrics accumulator for ONE run.

    Every emit serializes one JSON line and flushes it, so a crash at any
    point leaves a journal whose every complete line parses (the atomic.py
    contract, at line granularity). Emit failures (disk full) are counted
    and swallowed — observability must never kill the run it observes.
    """

    def __init__(self, path: str, run_id: str | None = None,
                 meta: dict | None = None,
                 registry: MetricsRegistry | None = None):
        self.path = path
        self.run_id = run_id or new_run_id()
        self.registry = registry or MetricsRegistry()
        self.dropped = 0
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._t0_unix = time.time()
        self._events = 0
        self._closed = False
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        head = {"type": "meta", "schema": SCHEMA, "run_id": self.run_id,
                "t0_unix": round(self._t0_unix, 3)}
        head.update(meta or {})
        self._emit(head)

    # -- core --------------------------------------------------------------

    def now(self) -> float:
        return time.perf_counter() - self._t0

    def _emit(self, obj: dict) -> None:
        try:
            line = json.dumps(obj, separators=(",", ":"), default=str)
        except (TypeError, ValueError):
            self.dropped += 1
            return
        with self._lock:
            if self._closed:
                self.dropped += 1
                return
            try:
                self._f.write(line + "\n")
                self._f.flush()
                self._events += 1
            except OSError:
                self.dropped += 1

    @staticmethod
    def _clean(fields: dict) -> dict:
        return {k: v for k, v in fields.items() if v is not None}

    # -- event API ---------------------------------------------------------

    def instant(self, ev: str, **fields) -> None:
        """Typed point event. Known events also feed the metrics registry
        (retry/failure counters per lane, cache event counters per stage,
        launch counters + per-launch histograms, injected-fault counters)."""
        reg = self.registry
        reg.inc("sl3d_events_total", ev=ev)
        if ev == "lane.retry":
            reg.inc("sl3d_retries_total", lane=fields.get("lane"))
        elif ev == "lane.failure":
            reg.inc("sl3d_failures_total", lane=fields.get("lane"))
        elif ev.startswith("cache."):
            reg.inc("sl3d_cache_events_total", stage=fields.get("stage"),
                    kind=ev[6:])
        elif ev == "launch":
            reg.inc("sl3d_launches_total")
            if fields.get("views") is not None:
                reg.observe("sl3d_views_per_launch", fields["views"],
                            buckets=_COUNT_BUCKETS)
        elif ev == "pair_launch":
            reg.inc("sl3d_pair_launches_total")
            if fields.get("pairs") is not None:
                reg.observe("sl3d_pairs_per_launch", fields["pairs"],
                            buckets=_COUNT_BUCKETS)
        elif ev == "fault.injected":
            reg.inc("sl3d_faults_injected_total", site=fields.get("site"),
                    kind=fields.get("kind"))
        elif ev == "transfer.bytes":
            if fields.get("h2d"):
                reg.inc("sl3d_transfer_bytes_total", float(fields["h2d"]),
                        dir="h2d")
            if fields.get("d2h"):
                reg.inc("sl3d_transfer_bytes_total", float(fields["d2h"]),
                        dir="d2h")
        elif ev.startswith("kernel."):
            reg.inc("sl3d_kernel_events_total", kernel=ev[7:])
            if fields.get("wall_s") is not None:
                reg.observe("sl3d_kernel_seconds", fields["wall_s"],
                            kernel=ev[7:])
        elif ev == "watchdog.stall":
            reg.inc("sl3d_stalls_total", level=fields.get("level"))
        self._emit(self._clean(
            {"type": "instant", "ev": ev, "t": round(self.now(), 6),
             "th": threading.current_thread().name, **fields}))

    def lane(self, lane: str, dur_s: float, **fields) -> None:
        """One lane-busy span that ENDED just now (``OverlapStats.add``
        calls this right after measuring, so start = now - dur). The
        journal's per-lane walls are sums of exactly these durations."""
        dur = float(dur_s)
        self.registry.observe("sl3d_lane_seconds", dur, lane=lane)
        self._emit(self._clean(
            {"type": "span", "ev": "lane", "lane": lane,
             "t": round(max(0.0, self.now() - dur), 6),
             "dur": round(dur, 6),
             "th": threading.current_thread().name, **fields}))

    def span_end(self, name: str, dur_s: float, **fields) -> None:
        """A named run-level stage span that just ended (keys/reconstruct/
        merge/mesh/...)."""
        dur = float(dur_s)
        self.registry.inc("sl3d_stage_wall_seconds_total", dur, stage=name)
        self._emit(self._clean(
            {"type": "span", "ev": "stage", "stage": name,
             "t": round(max(0.0, self.now() - dur), 6),
             "dur": round(dur, 6),
             "th": threading.current_thread().name, **fields}))

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.span_end(name, time.perf_counter() - t0, **fields)

    # -- close -------------------------------------------------------------

    def close(self, metrics_path: str | None = None) -> None:
        """Write the end marker, close the journal, and (optionally) persist
        the metrics registry as crash-safe JSON. Idempotent; runs in the
        pipeline's ``finally`` so even an InjectedCrash gets a metrics
        snapshot of everything recorded up to the crash."""
        if self._closed:
            return
        self.registry.set_gauge("sl3d_trace_events", self._events + 1)
        self.registry.set_gauge("sl3d_trace_dropped", self.dropped)
        self._emit({"type": "end", "t": round(self.now(), 6),
                    "events": self._events + 1})
        with self._lock:
            self._closed = True
            try:
                self._f.close()
            except OSError:
                pass
        if metrics_path is not None:
            payload = {"schema": SCHEMA, "run_id": self.run_id,
                       "t0_unix": round(self._t0_unix, 3),
                       "wall_s": round(self.now(), 6)}
            payload.update(self.registry.as_dict())
            tmp = metrics_path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(payload, f, indent=2, sort_keys=True)
                    f.write("\n")
                os.replace(tmp, metrics_path)
            except OSError:
                self.dropped += 1
                try:
                    os.remove(tmp)
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# module-global current tracer (the faults._PLAN pattern: disabled == None)
# ---------------------------------------------------------------------------

_TRACER: Tracer | None = None


def current() -> Tracer | None:
    """The active tracer, or None when tracing is off. Hot paths fetch this
    once and guard with ``is not None`` — the zero-allocation disabled
    path."""
    return _TRACER


def activate(tracer: Tracer | None) -> Tracer | None:
    """Install ``tracer`` process-wide; returns the PREVIOUS tracer so a
    nested scope (bench arms, tests) can restore it on exit."""
    global _TRACER
    prev = _TRACER
    _TRACER = tracer
    return prev


def deactivate(restore: Tracer | None = None) -> None:
    global _TRACER
    _TRACER = restore


@contextlib.contextmanager
def stage(name: str, **fields):
    """Run-level stage span on the CURRENT tracer; no-op without one. Used
    at stage granularity (a handful per run), never in per-view loops."""
    tr = _TRACER
    if tr is None:
        yield
        return
    with tr.span(name, **fields):
        yield


# ---------------------------------------------------------------------------
# journal reading + Chrome/Perfetto export
# ---------------------------------------------------------------------------

def read_journal(path: str) -> dict:
    """Parse a ``trace.jsonl`` tolerantly: every well-formed line becomes an
    event; a torn trailing line (crash mid-write) or stray corruption is
    counted in ``truncated`` instead of failing the read — interrupted runs
    are exactly when the journal matters most.

    The journal is append-only across runs (a rerun into the same out dir —
    the PR-2 resume flow — appends a new meta header rather than destroying
    the previous run's evidence), so the file holds one SEGMENT per run.
    ``meta``/``events`` are the LATEST run's (what ``sl3d report`` and the
    Chrome export show); ``segments`` carries the full history in order."""
    entries: list[dict] = []
    truncated = 0
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                truncated += 1
                continue
            if not isinstance(obj, dict) or "type" not in obj:
                truncated += 1
                continue
            entries.append(obj)
    starts = [i for i, o in enumerate(entries) if o["type"] == "meta"]
    segments: list[dict] = []
    if not starts:
        segments.append({"meta": None, "events": entries})
    else:
        if starts[0] != 0:   # stray pre-header events (should not happen)
            segments.append({"meta": None, "events": entries[:starts[0]]})
        for a, b in zip(starts, starts[1:] + [len(entries)]):
            segments.append({"meta": entries[a], "events": entries[a + 1:b]})
    last = segments[-1]
    return {"meta": last["meta"], "events": last["events"],
            "truncated": truncated, "segments": segments,
            "runs": sum(1 for s in segments if s["meta"] is not None)}


def export_chrome_trace(journal_path: str, out_path: str) -> dict:
    """Convert a journal to Chrome trace-event JSON (Perfetto /
    chrome://tracing / `ui.perfetto.dev` all load it). One track (tid) per
    distinct (lane, thread) so concurrent workers inside a lane don't
    overdraw each other; tracks are sort-indexed by LANE_ORDER so the five
    pipeline lanes read top-to-bottom as in docs/ARCHITECTURE.md."""
    j = read_journal(journal_path)
    meta = j["meta"] or {}
    run_id = meta.get("run_id", "?")
    pid = 1
    tids: dict[tuple, int] = {}
    out: list[dict] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": f"sl3d run {run_id}"}},
    ]

    def tid_for(lane: str, th: str) -> int:
        key = (lane, th)
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = len(tids) + 1
            order = (LANE_ORDER.index(lane) if lane in LANE_ORDER
                     else len(LANE_ORDER))
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": f"{lane} [{th}]"}})
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_sort_index",
                        "args": {"sort_index": order * 64 + tid}})
        return tid

    for ev in j["events"]:
        t_us = float(ev.get("t", 0.0)) * 1e6
        th = str(ev.get("th", "main"))
        if ev["type"] == "span":
            lane = ev.get("lane") or "stage"
            name = (ev.get("stage") if ev["ev"] == "stage"
                    else str(ev.get("view", ev.get("pair", lane))))
            args = {k: v for k, v in ev.items()
                    if k not in ("type", "ev", "t", "dur", "th")}
            out.append({"ph": "X", "pid": pid, "tid": tid_for(lane, th),
                        "ts": t_us, "dur": float(ev.get("dur", 0.0)) * 1e6,
                        "name": str(name), "cat": ev["ev"], "args": args})
        elif ev["type"] == "instant":
            args = {k: v for k, v in ev.items()
                    if k not in ("type", "ev", "t", "th")}
            lane = ev.get("lane") or "events"
            out.append({"ph": "i", "s": "t", "pid": pid,
                        "tid": tid_for(lane, th), "ts": t_us,
                        "name": ev["ev"], "cat": "instant", "args": args})
    payload = {"traceEvents": out, "displayTimeUnit": "ms",
               "metadata": {"schema": SCHEMA, "run_id": run_id,
                            "truncated_lines": j["truncated"]}}
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, separators=(",", ":"))
    os.replace(tmp, out_path)
    return {"events": len(out), "lanes": len({k[0] for k in tids}),
            "tracks": len(tids), "truncated": j["truncated"]}
