"""Command-line interface.

Restores and extends the reference's only non-GUI entry point
(Old/process_cloud.py:221-236) into a full subcommand CLI covering every GUI tab
flow (server/gui.py:176-205). Subcommands are registered here as they land;
each is a thin wrapper over pipeline/ stages so the CLI, GUI, and tests share
one implementation.
"""
from __future__ import annotations

import argparse
import json
import sys

from structured_light_for_3d_model_replication_tpu import __version__, load_config


def _add_config_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", default=None, help="path to a JSON config file")
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="dotted config override, e.g. --set merge.voxel_size=1.5",
    )


def parse_overrides(pairs: list[str]) -> dict[str, str]:
    out: dict[str, str] = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--set expects KEY=VALUE, got {pair!r}")
        k, v = pair.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="sl3d",
        description="TPU-native structured-light scan-to-print framework",
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command")

    p_cfg = sub.add_parser("config", help="print the resolved configuration as JSON")
    _add_config_args(p_cfg)

    # further subcommands (decode, reconstruct, clean, merge, mesh, scan, calibrate,
    # serve) register here as the pipeline layer lands
    from structured_light_for_3d_model_replication_tpu.pipeline import cli_commands

    cli_commands.register(sub, _add_config_args)

    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 1
    if args.command == "config":
        cfg = load_config(args.config, parse_overrides(args.set))
        json.dump(cfg.to_dict(), sys.stdout, indent=2)
        print()
        return 0
    return cli_commands.run(args)


if __name__ == "__main__":
    sys.exit(main())
