"""Typed configuration layer.

Replaces the reference's module constants (server/config.py:10-30) and its ~80
Tk variables (server/gui.py:31-169) with dataclasses that serialize to/from JSON,
can be overridden from CLI flags, and carry the execution-backend choice
(``jax`` on TPU vs ``numpy`` bit-exact CPU reference) required by BASELINE.json.
"""
from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any


@dataclass
class ProjectorConfig:
    """Projector geometry (reference: server/config.py:14-22)."""

    width: int = 1920
    height: int = 1080
    screen_offset_x: int = 1920  # projector is the second monitor
    brightness: int = 200        # PROJ_VALUE: white level of projected patterns
    downsample: int = 1          # D_SAMPLE_PROJ: pattern downsample factor


@dataclass
class CheckerboardConfig:
    """Calibration target (reference: server/config.py:26-30)."""

    rows: int = 7
    cols: int = 7
    square_size_mm: float = 35.0


@dataclass
class DecodeConfig:
    """Gray-code decode (reference: server/processing.py:28-124)."""

    n_cols: int = 1920
    n_rows: int = 1080
    n_sets_col: int = 11     # how many FIRST column bit-planes to use
    n_sets_row: int = 11     # how many FIRST row bit-planes to use
    thresh_mode: str = "otsu"  # 'otsu' | 'manual'
    shadow_val: float = 40.0
    contrast_val: float = 10.0


@dataclass
class TriangulateConfig:
    """Ray-plane triangulation (reference: server/processing.py:127-234)."""

    row_mode: int = 1          # 0=columns only, 1=epipolar filter, 2=merge col+row clouds
    epipolar_tol: float = 2.0  # mm
    # 'table' = gather stored plane equations (1-2 ULP of the numpy backend);
    # 'quadratic' = closed-form per-pixel plane evaluation (no gather, ~20x
    # faster triangulation on TPU, within ~1e-5 relative of the table)
    plane_eval: str = "table"
    # export-path triangulation through the NumPy twin: device decode
    # supplies integer-exact maps, the float math runs on host so exported
    # coordinates match the NumPy backend bit for bit (~0.7 s/view; TPU
    # f32 divide/rsqrt are not IEEE-identical, so no device-side path can
    # honor this). Needs plane_eval='table' — for export paths where the
    # BASELINE bit-exactness contract matters more than throughput
    bitexact: bool = False


@dataclass
class CleanConfig:
    """Point-cloud cleaning (reference: server/processing.py:337-448, gui.py tab 3)."""

    remove_background_plane: bool = True
    plane_ransac_dist: float = 2.0
    plane_ransac_trials: int = 512
    outlier_nb_neighbors: int = 20
    outlier_std_ratio: float = 2.0
    cluster_eps: float = 5.0
    cluster_min_points: int = 200
    radius_nb_points: int = 100
    radius: float = 5.0


@dataclass
class MergeConfig:
    """360-degree merge (reference: server/processing.py:489-629, gui.py:103-111)."""

    voxel_size: float = 3.0
    icp_dist_ratio: float = 1.5
    icp_iters: int = 30
    # batched-hypothesis equivalent of Open3D's 100k sequential iterations
    # (which early-stop at 0.999 confidence). 4096 is the library default —
    # robustness headroom for low-overlap / feature-poor pairs the way the
    # reference's 100k budget provides it; the bench overrides to 2048,
    # which on its (well-overlapped) scene measures the same global fitness
    # (0.846 vs 0.852) at half the trial-scoring cost (ADVICE r3: one bench
    # scene is not evidence enough to halve the LIBRARY default)
    ransac_trials: int = 4096
    outlier_nb: int = 20
    outlier_std: float = 2.0
    sample_before: int = 0       # uniform sample every k-th point before register (0=off)
    sample_after: int = 0
    final_voxel: float = 0.5
    method: str = "sequential"   # 'sequential' (A18) | 'posegraph' (Old/360Merge.py loop closure)
    # streaming merge (the fused pipeline only): register pair (i, i+1) the
    # moment both views are cleaned, overlapping registration with the
    # reconstruction of later views; the accumulate + final voxel/outlier
    # pass stays the only barrier. false = the monolithic barrier merge
    # (also the arm method='posegraph' always takes, with a logged notice).
    # Both arms produce byte-identical merged PLY/STL — stream/pair_batch
    # are SCHEDULE knobs and never enter stage-cache key material.
    stream: bool = True
    # ready pairs per register-lane launch: pairs group into bucket-padded
    # batches of this many (ragged tails land on a power-of-two ladder, so
    # at most log2(pair_batch)+1 programs compile per cloud bucket); with
    # >1 device the group dispatches through register_pairs_sharded
    pair_batch: int = 4
    # incremental assembly (coordinated pods only): the coordinator folds
    # completed views/pair transforms into running merged-cloud state as
    # their blobs land, so the assembly pass after the last item settles is
    # ≈ the postprocess tail. SCHEDULE knob like stream/pair_batch — never
    # cache-key material; incremental ≡ barrier ≡ single-process bytes.
    incremental: bool = False


@dataclass
class MeshConfig:
    """Meshing (reference: server/processing.py:632-860)."""

    mode: str = "watertight"     # 'watertight' (Poisson) | 'surface' (ball-pivot analog)
    # Poisson grid = 2^depth per axis; matches the reference default
    # (server/gui.py:118), full envelope <= 16 as in the reference's
    # guard. <=9 solves dense on one chip; 10 runs the exact slab-sharded
    # solver on a multi-device mesh, the brick-refined solver on a single
    # accelerator, and steps down to 9 on CPU unless density_cap=false
    # forces it; 11..16 runs the brick-refined cascadic solver
    # (ops/poisson_bricks — cost scales with surface bricks)
    depth: int = 10
    # clamp depth to ~log2(sqrt(N))+1 (a denser grid than the sampling
    # density is pure cost on a DENSE grid — unlike the reference's octree,
    # which adapts per sample). False honors the requested depth on a
    # sparse-but-real scan; the hostile-input guard this cap provides
    # (50 points -> 512^3 solve) is then the caller's responsibility.
    density_cap: bool = True
    density_trim_quantile: float = 0.02
    # hybrid normal search radius in WORLD units (Open3D Hybrid semantics);
    # 0 = pure kNN (unit-safe default — a fixed radius is only meaningful
    # once the cloud's scale is known)
    normal_radius: float = 0.0
    normal_max_nn: int = 30
    orientation: str = "radial"  # 'radial' | 'tangent' | 'centroid'
    smooth_iters: int = 0
    smooth_method: str = "taubin"  # 'taubin' | 'laplacian'
    simplify_target_faces: int = 0  # 0 = no decimation
    simplify_method: str = "quadric"  # 'quadric' (QEM) | 'cluster' (vertex grid)
    close_holes_max_edges: int = 0  # fill boundary loops up to this size (0=off)
    surface_alpha_factor: float = 2.5  # mode='surface': ball radius / avg NN dist
    surface_k: int = 12               # mode='surface': neighbor fan size


@dataclass
class AcquireConfig:
    """Capture network + devices (reference: server/server.py, arduino.py, sl_system.py)."""

    http_host: str = "0.0.0.0"
    http_port: int = 5000
    long_poll_hold_s: float = 2.0
    capture_timeout_s: float = 20.0
    disconnect_after_s: float = 5.0
    settle_ms_scan: int = 200
    settle_ms_calib: int = 250
    serial_port: str = ""        # empty = auto-scan /dev/ttyUSB*, /dev/ttyACM*
    serial_baud: int = 115200
    rotate_timeout_s: float = 30.0
    turns: int = 12
    degrees_per_turn: float = 30.0
    simulate: bool = False       # no-hardware mode (reference gui.py:1705-1779)
    # resilience: transient-failure retry budgets for the capture rig.
    # http_retries re-runs a failed phone HTTP request (dropped Wi-Fi, app
    # restart); rotate_retries re-issues a rotation after a missed DONE or a
    # serial error, re-opening the port between attempts; capture_retries
    # re-runs a whole per-view capture sequence before auto-scan records the
    # view as failed and continues the sweep
    http_retries: int = 2
    http_backoff_s: float = 0.2
    rotate_retries: int = 1
    capture_retries: int = 1
    # pack each captured view to the 1-bit bit-plane container
    # (frames.slbp, io/images.py) right after its sequence lands: stripe
    # frames threshold to pat>inv bits at capture time, so the scan folder
    # ships ~8x fewer bytes and the pipeline's packed ingest lane can
    # upload them as-is. pack_keep_raw retains the raw PNGs beside the
    # container (debugging / re-thresholding); default removes them.
    pack_frames: bool = False
    pack_keep_raw: bool = False


@dataclass
class ParallelConfig:
    """Device-mesh layout. New in the TPU build (reference is single-node)."""

    data_axis: int = 0      # shards turntable views; 0 = use all available devices
    model_axis: int = 1     # shards pixel rows / point blocks within a view
    backend: str = "jax"    # 'jax' | 'numpy' (bit-exact CPU reference path)
    # OPT-IN bf16 FPFH feature-distance matmuls with f32 accumulation (one
    # MXU pass vs HIGHEST's three); geometry stays f32. Default off: the r5
    # on-chip sweep measured bf16 matching at equal speed but global
    # fitness 0.818 -> 0.608 (33-bin FPFH histograms don't survive 8-bit
    # mantissas in the correspondence matmul). The pre-r5 knob
    # ``use_bf16_features`` ("auto") is accepted in config files with a
    # deprecation warning and maps to the auto policy (f32) — never to
    # forcing bf16
    force_bf16_features: bool = False
    # run the 360 merge over a device mesh (register_pairs_sharded + slab-
    # sharded postprocess; for method='posegraph' the edge registrations
    # shard and only the small host-side pose-graph solve stays local)
    # whenever >1 device is attached; single-device hosts are unaffected
    merge_mesh: bool = False
    # host I/O thread pool shared by the batch-reconstruct pipeline (frame
    # decode, per-view PLY reads in merge_views). <=1 runs every stage
    # serially — the pre-pipeline behavior, and the A/B arm the bench
    # compares against. Env override: SL3D_IO_WORKERS.
    io_workers: int = field(
        default_factory=lambda: int(os.environ.get("SL3D_IO_WORKERS", "4")))
    # how many view frame-stacks the batch-reconstruct prefetcher may hold
    # in flight ahead of the compute stage (backpressure bound: memory cost
    # is prefetch_depth x one stack, ~95 MB each at 46x1080p). Env
    # override: SL3D_PREFETCH_DEPTH.
    prefetch_depth: int = field(
        default_factory=lambda: int(os.environ.get("SL3D_PREFETCH_DEPTH", "2")))
    # views per device launch for batch reconstruct: the pipelined executor
    # accumulates prefetched stacks into bucket-padded batches of this many
    # views and dispatches each batch as ONE jitted forward_views program
    # (ragged tails land on a power-of-two bucket ladder, so at most
    # log2(compute_batch)+1 programs compile per shape/config). <=1 keeps
    # the per-view dispatch loop — also the numpy-backend / bitexact
    # behavior, which never batch. Env override: SL3D_COMPUTE_BATCH.
    compute_batch: int = field(
        default_factory=lambda: int(os.environ.get("SL3D_COMPUTE_BATCH", "8")))
    # shard each view batch's leading axis across every attached device
    # (shard_map, the register_pairs_sharded mechanism) whenever >1 device
    # is present; single-device hosts and the numpy backend are unaffected
    shard_views: bool = True


@dataclass
class PipelineConfig:
    """The fused scan-to-print command (``slscan pipeline``). New in the TPU
    build: the reference chains four file-level commands through PLY
    artifacts; the fused command hands clouds stage to stage in memory."""

    # content-addressed stage cache under <out>/.slscan-cache: reruns skip
    # every stage whose inputs (frames, calib, config subtree) are unchanged
    cache: bool = True
    # also emit each cleaned per-view cloud as <out>/views/<name>.ply
    # (side output on the writeback queue; the fused handoff never reads it)
    write_view_plys: bool = False
    # final merged-cloud PLY in ASCII (reference interop, %.4f — lossy; see
    # docs/API.md). INTERMEDIATE artifacts ignore this and stay binary.
    ascii_output: bool = False
    # resilience (docs/ARCHITECTURE.md "Failure domains & recovery"):
    # proceed to merge when at least min_views views survive reconstruction
    # (failed views are quarantined with a FailureRecord and the run emits a
    # failure manifest next to the STL); below the floor the run aborts.
    # The floor never drops under 2 — a merge needs two clouds.
    min_views: int = 2
    # bounded retry + exponential backoff for TRANSIENT per-view faults
    # (torn reads, dropped connections, EAGAIN-class OS errors): up to
    # max_retries extra attempts, sleeping retry_backoff_s * 2^(n-1) capped
    # at retry_backoff_max_s. Permanent failures skip straight to quarantine.
    max_retries: int = 2
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 1.0
    # FULL jitter on those backoff sleeps (uniform in [0, delay]): N
    # coordinated workers retrying the same transient must not thundering-
    # herd the coordinator/acquire layer in lockstep. Seeded via the armed
    # fault plan's jitter stream, so chaos runs stay reproducible.
    retry_jitter: bool = True
    # verify stage-cache payloads against their recorded content digest on
    # read; a corrupt entry (bit rot, torn write survivor) is evicted and
    # recomputed instead of poisoning downstream stages
    verify_cache: bool = True
    # overall wall-clock budget for one fused run, seconds (0 = unbounded;
    # env SL3D_RUN_BUDGET_S). Checked at stage boundaries and executor
    # scheduling steps: exceeding it ABORTS the run with an aborted
    # failure manifest — the request-deadline primitive a multi-tenant
    # serving process needs (ROADMAP item 1). Per-lane stall handling is
    # the `deadlines` section; this is the end-to-end ceiling above it.
    run_budget_s: float = field(
        default_factory=lambda: float(
            os.environ.get("SL3D_RUN_BUDGET_S", "0")))
    # HBM-resident view fastpath (batched executor only): the drain
    # compacts + cleans each batch on device and syncs results with ONE
    # jax.device_get; cleaned device buffers hand to the streaming
    # registrar without a re-upload. Byte-identical outputs to the
    # discrete drain (same jitted clean programs on the same bits); any
    # failure inside degrades to the per-view lane. Opt-in while the
    # discrete arm remains the reference path.
    fused_clean: bool = False
    # capture-rate ingest (batched executor only): load each view as a
    # packed bit-plane stack (frames.slbp where present, packed in the
    # loader thread otherwise), stream the ~8x-smaller planes to HBM as
    # they arrive, and decode from bits on device (ops/graycode.py
    # decode_packed). The stored bits ARE the decoder's pat>inv
    # comparisons, so maps/masks/textures — and every artifact downstream
    # — are byte-identical to the raw lane. Opt-in while raw ingest
    # remains the reference path.
    packed_ingest: bool = False


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes",
                                                        "on")


@dataclass
class ObservabilityConfig:
    """Run-scoped flight recorder (utils/telemetry.py). Off by default —
    the disabled path is one module-global None check per instrumentation
    point (benched <= 1.02x vs pipeline_e2e, the fault layer's contract).
    When on, ``sl3d pipeline`` writes an append-only crash-safe
    ``trace.jsonl`` event journal plus a ``metrics.json`` registry snapshot
    into the run's out dir; ``sl3d report <out>`` renders them and
    ``--chrome-trace`` exports a Perfetto-loadable timeline."""

    # arm the tracer for pipeline runs; env override SL3D_TRACE=1 (the
    # config-free switch, like SL3D_FAULTS)
    trace: bool = field(default_factory=lambda: _env_flag("SL3D_TRACE"))
    # journal / metrics filenames inside the run's out dir
    trace_file: str = "trace.jsonl"
    metrics_file: str = "metrics.json"


@dataclass
class DeadlinesConfig:
    """Per-lane deadlines + the lane watchdog (utils/deadline.py): the
    guarantee that a wedged load, device dispatch, write, or pair
    registration can never hang a run forever. Enabled by default — the
    defaults are far above any healthy lane wall, so they only ever fire
    on a genuine stall; ``enabled=false`` (env SL3D_NO_DEADLINES=1)
    restores bare blocking waits, and the disabled path is one None/flag
    check per wait (benched <= 1.02x vs pipeline_e2e, the faults/
    telemetry contract)."""

    enabled: bool = field(
        default_factory=lambda: not _env_flag("SL3D_NO_DEADLINES"))
    # per-lane budgets for each bounded wait, seconds (0 = unbounded).
    # A breach abandons THAT item: it is recorded as a DeadlineExceeded
    # FailureRecord and quarantined exactly like a permanently-failed
    # view/pair — the run continues DEGRADED above the survivor floor.
    load_s: float = 300.0      # frame-stack prefetch wait per view
    compute_s: float = 900.0   # decode+triangulate (incl. device sync)
    write_s: float = 300.0     # one artifact writeback wait
    register_s: float = 900.0  # streaming-merge register-lane drain
    drain_s: float = 600.0     # whole writeback-queue drain/close budget
    cache_s: float = 300.0     # stage-cache keying (frame-byte hashing)
    # the lane watchdog: a daemon thread polling the heartbeats that
    # OverlapStats.add/add_pair_launch emit. No heartbeat from ANY lane
    # for soft_stall_s -> watchdog.stall trace event + warning; for
    # hard_stall_s -> cancel the stalled item (cooperative — it
    # quarantines and the run continues) + dump all thread stacks into a
    # crash-safe stalls.json next to failures.json. 0 disables a level.
    watchdog_poll_s: float = 1.0
    soft_stall_s: float = 60.0
    hard_stall_s: float = 300.0


@dataclass
class CoordinatorConfig:
    """Host-level fault domains (parallel/coordinator.py): shard one scan's
    view-compute + pair-registration items across N worker PROCESSES under
    a lease/heartbeat protocol. ``workers=0`` (the default) disables the
    whole layer — ``run_pipeline`` never touches it. The coordinated
    result is byte-identical to the single-process pipeline: workers only
    warm the content-addressed stage cache; the coordinator's final
    assembly pass is the proven single-process pipeline reading it."""

    # worker processes to spawn (0 = single-process, coordinator disabled)
    workers: int = 0
    # a granted item's lease lifetime; leases renew on every
    # OverlapStats.add heartbeat, so only a killed/preempted/wedged/
    # partitioned worker lets one expire — then the item is STOLEN and
    # regranted to a survivor.  Must cover the longest single opaque
    # stage call (a cold pair registration can run tens of seconds with
    # no heartbeat inside); an expiry is still safe — the late complete
    # is journaled and the result stays in cache — just wasteful
    lease_s: float = 45.0
    # worker -> coordinator heartbeat cadence (rate-limits lease renewal
    # traffic; must be well under lease_s)
    heartbeat_s: float = 2.0
    # times one item may be stolen+regranted before the coordinator stops
    # regranting it (the assembly pass still computes it single-process,
    # so a poisonous item can never live-lock the grant loop)
    max_steals: int = 3
    # coordinator TCP port (loopback only); 0 = ephemeral
    port: int = 0
    # worker -> coordinator connect deadline; a worker that cannot reach
    # the coordinator within it exits with a clear diagnostic
    connect_timeout_s: float = 20.0
    # ---- pod fabric (parallel/netutil.py endpoint grammar) --------------
    # coordinator bind endpoint ("host:port", "[v6]:port", ":port"; empty
    # = PR-8 loopback behavior, fabric layer fully disabled). Setting it
    # turns on the networked fabric: the blobstore co-hosts next to the
    # coordinator, spawned workers get private L1 cache roots, and
    # external `sl3d worker` processes may join over TCP
    listen: str = ""
    # worker-side: coordinator endpoint to dial (external workers; spawned
    # workers get theirs in the spec). Empty = dial loopback `port`
    connect: str = ""
    # shared secret for the hello handshake; when set, every connection
    # (coordinator AND blobstore) must present it in its first request or
    # all further ops answer {"error": "unauthorized"}
    secret: str = ""


@dataclass
class FaultsConfig:
    """Deterministic fault injection (utils/faults.py). Disabled by default
    (empty spec == zero overhead); the SL3D_FAULTS / SL3D_FAULTS_SEED env
    vars override this section for config-free chaos runs."""

    # comma list of `site[~substr]:kind[@n][xM][%p]` rules; see
    # utils/faults.py for the grammar and the wired site names
    spec: str = ""
    seed: int = 0


@dataclass
class ServingConfig:
    """Persistent multi-tenant scan service (pipeline/serving.py, CLI
    ``sl3d serve``). Many tenants' scans multiplex onto ONE shared device
    mesh: a stdlib-HTTP gateway admits submissions through a multi-scan
    generalization of the coordinator's lease/ledger protocol, an engine
    thread fills the batched ``forward_views`` bucket ladder with views
    drawn from DIFFERENT scans (cross-tenant batching), and each request
    is then assembled by the proven single-process pipeline reading the
    warmed content-addressed cache — so every response is byte-identical
    to a solo ``sl3d pipeline`` run of the same input."""

    # gateway bind address; loopback by default — the service speaks
    # plaintext HTTP and has no auth layer of its own
    host: str = "127.0.0.1"
    # 0 = ephemeral (the chosen port is logged and written to status)
    port: int = 8089
    # scans admitted to the engine simultaneously (the cross-tenant
    # batching pool); queued scans wait in weighted-fair order
    max_active_scans: int = 4
    # per-tenant caps: active scans in flight / scans waiting in queue.
    # A submit beyond the queue quota is rejected at the door (HTTP 429)
    tenant_active_quota: int = 2
    tenant_queue_quota: int = 8
    # total queue depth across all tenants (backpressure; 429 when full)
    queue_depth: int = 64
    # engine item-lease lifetime (sec); an engine lane that stops
    # heartbeating has its granted views stolen back to pending
    lease_s: float = 30.0
    # default per-request SLO budget (sec) when a submission does not
    # carry its own ``budget_s``; 0 = no deadline.  Breach aborts THAT
    # request with its own failures.json; the service keeps running
    default_budget_s: float = 0.0
    # default tenant weight for weighted-fair admission + grant
    # interleaving (a tenant at weight 2 drains twice as fast as one
    # at weight 1); per-submit override via the ``weight`` field
    default_weight: float = 1.0
    # engine lanes pulling view grants (each lane assembles one batched
    # launch at a time); 1 is correct and keeps device contention simple
    engine_lanes: int = 1
    # per-view clean-chain steps (comma list, the `sl3d pipeline --steps`
    # vocabulary). Service-global because steps are view-cache key
    # material: one value keeps every tenant's entries dedupable
    clean_steps: str = "background,cluster,radius,statistical"
    # gateway idle poll cadence for the admit/sweep loop (sec)
    poll_s: float = 0.05
    # durable requests: every accepted /submit is persisted as a crash-
    # safe request record (atomic write + fsync BEFORE the response) and
    # replayed together with ledger.jsonl on start() — a restarted
    # service resumes every non-terminal scan with zero recompute of
    # ledger-credited views. False = PR-12 in-memory behaviour
    durable: bool = True
    # graceful-stop budget (sec): on SIGTERM/SIGINT the service drains —
    # new submits get 503 + Retry-After, active scans get this long to
    # finish; past it, in-flight assemblies are aborted mid-stage and
    # CHECKPOINTED (non-terminal, resumed by the next start)
    drain_budget_s: float = 30.0
    # overload shedding: a queued scan whose wait exceeds this is shed
    # (503 + ``shed`` ledger event) BEFORE it burns engine time it can
    # no longer use; 0 = off. Scans with a per-request budget_s are
    # additionally shed once that budget is already exhausted in queue
    max_queue_wait_s: float = 0.0
    # per-tenant circuit breaker: this many CONSECUTIVE failed/aborted
    # scans opens the breaker (submits fast-fail 503 + Retry-After);
    # after breaker_cooldown_s one half-open probe scan is admitted and
    # its outcome closes or re-opens the breaker. 0 = disabled
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 30.0
    # ---- gateway HA (ISSUE 14) ----
    # run this gateway as a member of a leader-elected group over ONE
    # shared root: exactly one member owns the engine (the leader, chosen
    # through the fsync'd lease file <root>/leader.json), the rest serve
    # reads and redirect /submit to the leader. Off = solo gateway,
    # identical to PR-12 behaviour (no lease file, no fence checks)
    ha_enabled: bool = False
    # leader lease lifetime (sec): a leader that stops renewing for this
    # long is considered dead and a standby takes over (epoch bump).
    # Lower = faster failover, more lease-file traffic
    ha_lease_s: float = 5.0
    # leader renew cadence (sec); 0 = ha_lease_s / 3
    ha_renew_s: float = 0.0
    # follower takeover-poll cadence (sec); 0 = ha_lease_s / 5
    ha_poll_s: float = 0.0
    # ---- elastic fleet (ISSUE 18, parallel/fleet.py) ----
    # leader-owned worker autoscaler: the gateway acts as fabric
    # coordinator and spawns/retires `sl3d worker` processes against a
    # target computed from live admission signals (queue depth, queue
    # wait vs SLO, breaker states). Every decision is journaled to the
    # ledger with its signal snapshot; a promoted follower resumes the
    # fleet it inherited. Off = PR-15 behaviour (hand-started workers)
    fleet_enabled: bool = False
    # fleet size bounds; the decision function clamps its target into
    # [fleet_min_workers, fleet_max_workers]
    fleet_min_workers: int = 0
    fleet_max_workers: int = 4
    # supervisor tick cadence (sec): signals are sampled, decisions made
    # and dead workers reaped once per tick
    fleet_poll_s: float = 0.5
    # scale-up pressure: target = ceil(pending_items / this) while work
    # is queued (one worker per this-many grantable views)
    fleet_scale_up_queue: int = 4
    # scale-in: retire down to fleet_min_workers only after the queue
    # has been empty this long (sec) — hysteresis against thrash
    fleet_scale_in_idle_s: float = 5.0
    # restart-after-crash backoff: first respawn waits fleet_backoff_s,
    # doubling per consecutive death up to fleet_backoff_max_s
    fleet_backoff_s: float = 0.5
    fleet_backoff_max_s: float = 30.0
    # flap damping: this many deaths of one rank inside
    # fleet_flap_window_s marks it FLAPPING — respawns for that rank
    # hold at the max backoff until the window drains. 0 = disabled
    fleet_flap_threshold: int = 3
    fleet_flap_window_s: float = 60.0
    # fabric bind endpoint for the fleet bridge (netutil grammar, e.g.
    # ":0" for any port). Empty = loopback 127.0.0.1 with an ephemeral
    # port; workers then warm the SHARED stage cache on this host's
    # disk (byte parity with solo by the PR-8 construction)
    fleet_listen: str = ""
    # shared secret for spawned workers' hello handshake; empty = open
    fleet_secret: str = ""
    # ---- front-door auth (ISSUE 18) ----
    # per-tenant API keys on /submit: keys are verified against sha256
    # hashes at rest in <root>/tenants.json (`sl3d tenant add` writes
    # it). Unknown/missing key = 401, a key presented for a DIFFERENT
    # tenant = 403, both with machine-readable reasons. Off = open door
    auth_enabled: bool = False
    # tenants file path; empty = <root>/tenants.json
    auth_tenants_file: str = ""
    # default per-tenant rate limit: submits allowed per window; 0 =
    # unlimited. Per-tenant overrides live in tenants.json
    auth_rate_limit: int = 0
    auth_rate_window_s: float = 60.0


@dataclass
class Config:
    """Root configuration for the whole framework."""

    projector: ProjectorConfig = field(default_factory=ProjectorConfig)
    checkerboard: CheckerboardConfig = field(default_factory=CheckerboardConfig)
    decode: DecodeConfig = field(default_factory=DecodeConfig)
    triangulate: TriangulateConfig = field(default_factory=TriangulateConfig)
    clean: CleanConfig = field(default_factory=CleanConfig)
    merge: MergeConfig = field(default_factory=MergeConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    acquire: AcquireConfig = field(default_factory=AcquireConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    faults: FaultsConfig = field(default_factory=FaultsConfig)
    coordinator: CoordinatorConfig = field(default_factory=CoordinatorConfig)
    deadlines: DeadlinesConfig = field(default_factory=DeadlinesConfig)
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    scan_root: str = ""  # dated scan folder; empty = ./scans/<date>

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)


# (section class name, legacy key) -> warning; the key is dropped, keeping
# the section's defaults (which preserve the legacy key's old behavior)
_LEGACY_KEYS = {
    ("ParallelConfig", "use_bf16_features"):
        "parallel.use_bf16_features ('auto') is deprecated and ignored — "
        "the auto policy resolves to f32 features since the r5 on-chip "
        "quality sweep; use parallel.force_bf16_features=true to force "
        "the bf16 arm",
}


def _from_dict(cls: type, data: dict[str, Any]) -> Any:
    import typing

    for key in [k for k in data
                if (cls.__name__, k) in _LEGACY_KEYS]:
        import sys

        print(f"[config] WARNING: {_LEGACY_KEYS[(cls.__name__, key)]}",
              file=sys.stderr)
        data = {k: v for k, v in data.items() if k != key}

    hints = typing.get_type_hints(cls)
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - known
    if unknown:
        raise ValueError(
            f"Unknown key(s) in config section {cls.__name__}: {sorted(unknown)}; "
            f"valid keys: {sorted(known)}"
        )
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        ftype = hints.get(f.name)
        if isinstance(v, dict) and dataclasses.is_dataclass(ftype):
            kwargs[f.name] = _from_dict(ftype, v)  # type: ignore[arg-type]
        else:
            kwargs[f.name] = v
    return cls(**kwargs)


def _coerce(cur: Any, value: Any) -> Any:
    """Coerce an override value to the type of the current field value."""
    if dataclasses.is_dataclass(cur):
        raise ValueError(
            f"Cannot override a whole config section with {value!r}; "
            f"use a dotted leaf key like section.field=value"
        )
    if value is None or cur is None or isinstance(cur, (dict, list)):
        return value
    if isinstance(cur, bool):
        if isinstance(value, str):
            low = value.strip().lower()
            if low in ("1", "true", "yes", "on"):
                return True
            if low in ("0", "false", "no", "off"):
                return False
            raise ValueError(f"Cannot interpret {value!r} as a boolean")
        return bool(value)
    if isinstance(cur, int) and not isinstance(cur, bool):
        as_float = float(value)
        if as_float != int(as_float):
            raise ValueError(f"Expected an integer, got {value!r}")
        return int(as_float)
    return type(cur)(value)


def load_config(path: str | None = None, overrides: dict[str, Any] | None = None) -> Config:
    """Load a Config from JSON, with optional dotted-key overrides.

    ``overrides`` maps dotted keys (e.g. ``"merge.voxel_size"``) to values —
    the mechanism the CLI uses for per-flag parameter overrides, replacing the
    reference's per-tab Tk variables.
    """
    cfg = Config()
    if path:
        if not os.path.exists(path):
            raise FileNotFoundError(f"Config file not found: {path}")
        with open(path) as f:
            cfg = _from_dict(Config, json.load(f))
    for key, value in (overrides or {}).items():
        obj: Any = cfg
        parts = key.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        leaf = parts[-1]
        cur = getattr(obj, leaf)  # raises AttributeError on unknown keys
        setattr(obj, leaf, _coerce(cur, value))
    return cfg
