"""Calibration inspection: human-readable geometry summary + quality bands.

Capability parity (behavior studied from Old/read_calib.py:1-130 and
Old/ResultCalibCam.py:1-86): report focal lengths, principal points, the
camera-projector baseline, the relative rotation as Euler angles, distortion
strength, and the reprojection-error quality band (< 0.5 px EXCELLENT,
< 1.0 px GOOD, else POOR — Old/ResultCalibCam.py:72-79). Also backs the
calibration-check visualization data of server/gui.py:1789-1917.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "euler_angles_deg",
    "quality_band",
    "summarize_calibration",
    "format_summary",
]

QUALITY_BANDS = ((0.5, "EXCELLENT"), (1.0, "GOOD"))


def quality_band(reprojection_error_px: float) -> str:
    """Reference quality bands for a reprojection error in pixels."""
    for limit, label in QUALITY_BANDS:
        if reprojection_error_px < limit:
            return label
    return "POOR"


def euler_angles_deg(R: np.ndarray) -> tuple[float, float, float]:
    """ZYX (yaw-pitch-roll) Euler decomposition of a rotation matrix, degrees.

    Same convention as the GUI's calibration plot readout (server/gui.py:1860-1880).
    """
    R = np.asarray(R, np.float64)
    sy = float(np.hypot(R[0, 0], R[1, 0]))
    if sy > 1e-6:
        roll = np.arctan2(R[2, 1], R[2, 2])
        pitch = np.arctan2(-R[2, 0], sy)
        yaw = np.arctan2(R[1, 0], R[0, 0])
    else:  # gimbal lock
        roll = np.arctan2(-R[1, 2], R[1, 1])
        pitch = np.arctan2(-R[2, 0], sy)
        yaw = 0.0
    return tuple(float(np.degrees(a)) for a in (roll, pitch, yaw))


def _intrinsics(K: np.ndarray) -> dict:
    K = np.asarray(K, np.float64)
    return {
        "fx": float(K[0, 0]),
        "fy": float(K[1, 1]),
        "cx": float(K[0, 2]),
        "cy": float(K[1, 2]),
    }


def summarize_calibration(calib: dict,
                          reprojection_error_px: float | None = None) -> dict:
    """Structured geometry summary of a saved calibration dict (.mat layout)."""
    R = np.asarray(calib["R"], np.float64)
    T = np.asarray(calib["T"], np.float64).reshape(3)
    dist = np.asarray(calib.get("dc", np.zeros(5)), np.float64).reshape(-1)
    baseline = float(np.linalg.norm(T))
    proj_center_cam = (-R.T @ T).reshape(3)
    roll, pitch, yaw = euler_angles_deg(R)
    out = {
        "camera": _intrinsics(calib["cam_K"]),
        "projector": _intrinsics(calib["proj_K"]),
        "baseline_mm": baseline,
        "projector_center_cam_mm": proj_center_cam.tolist(),
        "euler_deg": {"roll": roll, "pitch": pitch, "yaw": yaw},
        "distortion": dist.tolist(),
        "distortion_strength": float(np.abs(dist).sum()),
    }
    if "wPlaneCol" in calib:
        out["n_planes_col"] = int(np.asarray(calib["wPlaneCol"]).shape[-1])
    if "wPlaneRow" in calib:
        out["n_planes_row"] = int(np.asarray(calib["wPlaneRow"]).shape[-1])
    if reprojection_error_px is not None:
        out["reprojection_error_px"] = float(reprojection_error_px)
        out["quality"] = quality_band(float(reprojection_error_px))
    return out


def format_summary(summary: dict) -> str:
    """Render the summary as the operator-facing report text."""
    cam, proj = summary["camera"], summary["projector"]
    e = summary["euler_deg"]
    lines = [
        "=== Calibration summary ===",
        f"camera:    fx={cam['fx']:.2f} fy={cam['fy']:.2f} "
        f"cx={cam['cx']:.2f} cy={cam['cy']:.2f}",
        f"projector: fx={proj['fx']:.2f} fy={proj['fy']:.2f} "
        f"cx={proj['cx']:.2f} cy={proj['cy']:.2f}",
        f"baseline:  {summary['baseline_mm']:.2f} mm",
        f"rotation:  roll={e['roll']:.2f} pitch={e['pitch']:.2f} "
        f"yaw={e['yaw']:.2f} deg",
        f"distortion strength: {summary['distortion_strength']:.4f}",
    ]
    if "reprojection_error_px" in summary:
        lines.append(
            f"reprojection error: {summary['reprojection_error_px']:.4f} px "
            f"[{summary['quality']}]"
        )
    return "\n".join(lines)
