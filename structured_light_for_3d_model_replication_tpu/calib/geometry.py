"""Calibration geometry: per-pixel camera rays + projector light-plane equations.

Capability parity (behavior studied from server/sl_system.py:352-423): given the
stereo solve (K_cam, K_proj, R, T with x_proj = R x_cam + T), build
  - Nc: unit view ray per camera pixel, stored [3, H*W] (float64)
  - wPlaneCol [W_proj, 4]: for each projector column c, the plane containing the
    projector center and the column's light sheet, in camera coordinates
  - wPlaneRow [H_proj, 4]: likewise per projector row

The reference builds the 1920 + 1080 planes in a Python loop of single-vector
crosses (server/sl_system.py:405-410); here the whole construction is one
batched cross product — ~3000x fewer interpreter trips, same float64 math.
"""
from __future__ import annotations

import numpy as np

from structured_light_for_3d_model_replication_tpu.ops.triangulate import pixel_rays

__all__ = ["camera_ray_field", "projector_planes", "build_calibration"]


def camera_ray_field(cam_K, height: int, width: int) -> np.ndarray:
    """Unit rays for every camera pixel as float64 [3, H*W] (reference layout)."""
    K = np.asarray(cam_K, np.float64)
    u, v = np.meshgrid(np.arange(width, dtype=np.float64),
                       np.arange(height, dtype=np.float64))
    x = (u - K[0, 2]) / K[0, 0]
    y = (v - K[1, 2]) / K[1, 1]
    z = np.ones_like(x)
    rays = np.stack([x, y, z], axis=-1)
    rays /= np.linalg.norm(rays, axis=-1, keepdims=True)
    return rays.reshape(-1, 3).T


def _planes_from_lines(a_n: np.ndarray, b_n: np.ndarray, r_inv: np.ndarray,
                       c_p: np.ndarray) -> np.ndarray:
    """Planes spanned by projector-frame directions a_n, b_n ([N,3] each) through
    the projector center c_p (camera frame). Returns [N, 4] (nx, ny, nz, d)."""
    r1 = a_n @ r_inv.T  # rotate into camera frame
    r2 = b_n @ r_inv.T
    normal = np.cross(r1, r2)
    normal /= np.linalg.norm(normal, axis=-1, keepdims=True)
    d = -(normal @ c_p.reshape(3))
    return np.concatenate([normal, d[:, None]], axis=-1)


def projector_planes(proj_K, R, T, proj_width: int, proj_height: int
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Light-plane equations (wPlaneCol [W,4], wPlaneRow [H,4]) in camera frame.

    Each projector column c spans a plane through the normalized projector rays
    at (c, 0) and (c, H); each row r through (0, r) and (W, r) — the reference's
    two-point construction (server/sl_system.py:388-410), batched.
    """
    K = np.asarray(proj_K, np.float64)
    R = np.asarray(R, np.float64)
    T = np.asarray(T, np.float64).reshape(3)
    fx, fy, cx, cy = K[0, 0], K[1, 1], K[0, 2], K[1, 2]
    r_inv = R.T
    c_p = -r_inv @ T  # projector center in camera coordinates

    c = np.arange(proj_width, dtype=np.float64)
    xc = (c - cx) / fx
    top = np.stack([xc, np.full_like(xc, (0.0 - cy) / fy), np.ones_like(xc)], axis=-1)
    bot = np.stack([xc, np.full_like(xc, (proj_height - cy) / fy), np.ones_like(xc)], axis=-1)
    plane_col = _planes_from_lines(top, bot, r_inv, c_p)

    r = np.arange(proj_height, dtype=np.float64)
    yr = (r - cy) / fy
    left = np.stack([np.full_like(yr, (0.0 - cx) / fx), yr, np.ones_like(yr)], axis=-1)
    right = np.stack([np.full_like(yr, (proj_width - cx) / fx), yr, np.ones_like(yr)], axis=-1)
    plane_row = _planes_from_lines(left, right, r_inv, c_p)
    return plane_col, plane_row


def build_calibration(cam_K, cam_dist, proj_K, R, T,
                      cam_width: int, cam_height: int,
                      proj_width: int = 1920, proj_height: int = 1080,
                      include_ray_field: bool = True) -> dict:
    """Assemble the full calibration dict in the reference's .mat layout
    (server/sl_system.py:413-423): Nc [3,H*W], Oc [3,1], dc, wPlaneCol/Row
    stored transposed [4,N], plus cam_K/proj_K/R/T."""
    plane_col, plane_row = projector_planes(proj_K, R, T, proj_width, proj_height)
    calib = {
        "Oc": np.zeros((3, 1)),
        "dc": np.asarray(cam_dist, np.float64).reshape(1, -1),
        "wPlaneCol": plane_col.T,
        "wPlaneRow": plane_row.T,
        "cam_K": np.asarray(cam_K, np.float64),
        "proj_K": np.asarray(proj_K, np.float64),
        "R": np.asarray(R, np.float64),
        "T": np.asarray(T, np.float64).reshape(3, 1),
        "cam_size": np.array([cam_width, cam_height], np.int64),
    }
    if include_ray_field:
        calib["Nc"] = camera_ray_field(cam_K, cam_height, cam_width)
    return calib


# expose the float32 per-pixel ray builder for callers that skip the stored field
__all__.append("pixel_rays")


def plane_poly_coefficients(proj_K, R, T, proj_width: int, proj_height: int
                            ) -> tuple[np.ndarray, np.ndarray]:
    """Quadratic closed form of the light planes: gather-free triangulation.

    The two projector-frame directions spanning column c's plane are affine in
    c, so their cross product — the unnormalized plane normal — is EXACTLY
    quadratic in c (same for rows), and the ray-plane intersection
    ``t = -(n.O + d)/(n.ray)`` is invariant to plane scale. Evaluating
    ``n4(c) = A + B c + C c^2`` per pixel replaces the per-pixel gather of
    wPlaneCol/wPlaneRow (a scattered-address load XLA executes ~50x slower
    than the surrounding arithmetic on TPU) with three fused multiply-adds.

    Returns (col_coeffs [3, 4], row_coeffs [3, 4]) float64: rows A, B, C of
    (nx, ny, nz, d); plane4(c) = A + B*c + C*c*c, unnormalized.
    """
    K = np.asarray(proj_K, np.float64)
    R = np.asarray(R, np.float64)
    T = np.asarray(T, np.float64).reshape(3)
    fx, fy, cx, cy = K[0, 0], K[1, 1], K[0, 2], K[1, 2]
    r_inv = R.T
    c_p = -r_inv @ T

    def axis_coeffs(u_axis: bool):
        # direction(v) = base0 + dir1 * v in the projector frame, for the two
        # spanning rays; rotate into camera frame (linear, keeps affinity)
        if u_axis:  # column planes: rays at (c, 0) and (c, H)
            a0 = np.array([-cx / fx, (0.0 - cy) / fy, 1.0])
            b0 = np.array([-cx / fx, (proj_height - cy) / fy, 1.0])
            step = np.array([1.0 / fx, 0.0, 0.0])
        else:       # row planes: rays at (0, r) and (W, r)
            a0 = np.array([(0.0 - cx) / fx, -cy / fy, 1.0])
            b0 = np.array([(proj_width - cx) / fx, -cy / fy, 1.0])
            step = np.array([0.0, 1.0 / fy, 0.0])
        a0, b0, s = a0 @ r_inv.T, b0 @ r_inv.T, step @ r_inv.T
        A3 = np.cross(a0, b0)
        B3 = np.cross(a0, s) + np.cross(s, b0)
        C3 = np.cross(s, s)  # = 0; kept for symmetry
        coeffs = np.stack([A3, B3, C3])          # [3, 3] normals
        d = -(coeffs @ c_p)                      # [3] plane offsets
        return np.concatenate([coeffs, d[:, None]], axis=1)  # [3, 4]

    return axis_coeffs(True), axis_coeffs(False)
