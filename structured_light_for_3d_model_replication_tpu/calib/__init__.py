"""Projector-camera stereo calibration (reference parity: server/sl_system.py:187-425,
Old/sl_calib_process.py, Old/read_calib.py, Old/ResultCalibCam.py).

  chessboard   corner detection + board geometry (OpenCV-gated)
  pipeline     analyze / prune / solve / save end-to-end calibration
  geometry     ray field + projector light-plane construction (batched)
  undistort    Brown-Conrady undistortion as fused JAX remap kernels
  inspect      human-readable geometry summary + quality bands
"""
from structured_light_for_3d_model_replication_tpu.calib.geometry import (  # noqa: F401
    build_calibration,
    camera_ray_field,
    projector_planes,
)
from structured_light_for_3d_model_replication_tpu.calib.chessboard import (  # noqa: F401
    BoardSpec,
    board_object_points,
    find_corners,
)
from structured_light_for_3d_model_replication_tpu.calib.inspect import (  # noqa: F401
    format_summary,
    quality_band,
    summarize_calibration,
)
