"""Calibration geometry visualization.

Capability parity (behavior studied from server/gui.py:1789-1917, the
"Calib Check" tab): a 3-D rig plot — camera at the origin, projector posed by
R/T, frusta, baseline annotation, and Euler-angle readout — plus light-plane
samples so a bad stereo solve is visually obvious. Renders to a PNG file
instead of an embedded Tk canvas so it works headless and from the CLI
(``sl3d inspect-calib --plot``).
"""
from __future__ import annotations

import numpy as np

from structured_light_for_3d_model_replication_tpu.calib.inspect import (
    euler_angles_deg,
)

__all__ = ["plot_rig", "frustum_corners"]


def frustum_corners(K: np.ndarray, width: int, height: int,
                    depth: float) -> np.ndarray:
    """[4, 3] camera-frame corners of the image plane pushed to ``depth``."""
    K = np.asarray(K, np.float64)
    pts = []
    for u, v in ((0, 0), (width, 0), (width, height), (0, height)):
        x = (u - K[0, 2]) / K[0, 0]
        y = (v - K[1, 2]) / K[1, 1]
        pts.append((x * depth, y * depth, depth))
    return np.asarray(pts)


def plot_rig(calib: dict, out_path: str, depth: float = 300.0,
             n_planes: int = 6) -> dict:
    """Render the rig to ``out_path`` (PNG). Returns the numeric summary
    (baseline mm, Euler angles) that the reference prints next to its plot."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    R = np.asarray(calib["R"], np.float64)
    T = np.asarray(calib["T"], np.float64).reshape(3)
    cam_K = np.asarray(calib["cam_K"], np.float64)
    proj_K = np.asarray(calib["proj_K"], np.float64)
    pc = np.asarray(calib["wPlaneCol"], np.float64)
    if pc.shape[0] == 4:
        pc = pc.T

    # projector pose in the camera frame: x_p = R x_c + T -> center at -R^T T
    r_inv = R.T
    proj_center = -r_inv @ T
    baseline = float(np.linalg.norm(T))
    euler = euler_angles_deg(R)

    fig = plt.figure(figsize=(8, 6))
    ax = fig.add_subplot(111, projection="3d")

    def draw_frustum(center, rot, K, w, h, color, label):
        corners = frustum_corners(K, w, h, depth) @ rot.T + center
        for c in corners:
            ax.plot(*zip(center, c), color=color, lw=0.8)
        loop = np.vstack([corners, corners[:1]])
        ax.plot(loop[:, 0], loop[:, 1], loop[:, 2], color=color, lw=1.2,
                label=label)

    cam_wh = (int(2 * cam_K[0, 2]) or 1920, int(2 * cam_K[1, 2]) or 1080)
    proj_wh = (pc.shape[0], int(2 * proj_K[1, 2]) or 1080)
    draw_frustum(np.zeros(3), np.eye(3), cam_K, *cam_wh,
                 color="#1d4ed8", label="camera")
    draw_frustum(proj_center, r_inv, proj_K, *proj_wh,
                 color="#e5484d", label="projector")
    ax.plot(*zip(np.zeros(3), proj_center), "k--", lw=1,
            label=f"baseline {baseline:.1f} mm")

    # a few light planes: intersect plane normals with the viewing volume by
    # drawing the projector ray fan at sampled columns
    for ci in np.linspace(0, pc.shape[0] - 1, n_planes, dtype=int):
        n4 = pc[ci]
        # draw the plane's trace: points at depth where n . p + d = 0
        xs = np.linspace(-0.4 * depth, 0.4 * depth, 2)
        for z in (0.6 * depth, depth):
            # solve n_x x + n_y y + n_z z + d = 0 for y over xs
            if abs(n4[1]) < 1e-9:
                continue
            ys = -(n4[0] * xs + n4[2] * z + n4[3]) / n4[1]
            ax.plot(xs, ys, [z, z], color="#f59e0b", lw=0.5, alpha=0.6)

    ax.set_xlabel("x (mm)")
    ax.set_ylabel("y (mm)")
    ax.set_zlabel("z (mm)")
    ax.set_title(f"baseline {baseline:.1f} mm | "
                 f"euler xyz {euler[0]:.1f}/{euler[1]:.1f}/{euler[2]:.1f} deg")
    ax.legend(loc="upper left", fontsize=8)
    fig.tight_layout()
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return {"baseline_mm": baseline, "euler_deg": euler, "plot": out_path}
