"""Projector-camera stereo calibration pipeline.

Capability parity (behavior studied from server/sl_system.py:187-425):
  analyze:   scan pose folders (>= 3), detect the chessboard in each white frame,
             Gray-decode projector coordinates at every corner, run quick
             per-device calibrations, and report per-pose reprojection errors so
             the operator can prune bad poses.
  calibrate: on the selected poses, solve camera and projector intrinsics, bond
             them with a stereo solve (intrinsics fixed), and emit the geometry
             the scan pipeline consumes: per-pixel camera rays + per-column /
             per-row projector light-plane equations (built batched in
             calib.geometry, not the reference's 3000-iteration Python loop).

The Levenberg-Marquardt bundle solves stay on CPU via OpenCV — they are tiny,
sparse, and branchy (nothing for an MXU). Everything array-shaped around them
(corner-level Gray decode, ray fields, plane construction) is vectorized here.
"""
from __future__ import annotations

import os
from typing import NamedTuple

import numpy as np

from structured_light_for_3d_model_replication_tpu.calib import chessboard as cb
from structured_light_for_3d_model_replication_tpu.calib.geometry import (
    build_calibration,
)
from structured_light_for_3d_model_replication_tpu.io import images as imio
from structured_light_for_3d_model_replication_tpu.io import matfile
from structured_light_for_3d_model_replication_tpu.ops.graycode import (
    _n_bits,
    frames_per_view,
)

__all__ = [
    "PoseObservation",
    "CalibrationSolution",
    "decode_at_points",
    "collect_calibration_data",
    "analyze_calibration",
    "reprojection_errors",
    "select_poses",
    "calibrate_stereo",
    "calibrate_and_save",
]


class PoseObservation(NamedTuple):
    """Matched point triple for one chessboard pose: world <-> camera <-> projector."""

    name: str
    obj_pts: np.ndarray   # [N, 3] float32, board frame (z = 0)
    cam_pts: np.ndarray   # [N, 2] float32, camera pixels (sub-pixel)
    proj_pts: np.ndarray  # [N, 2] float32, decoded projector pixels


class CalibrationSolution(NamedTuple):
    cam_K: np.ndarray
    cam_dist: np.ndarray
    proj_K: np.ndarray
    proj_dist: np.ndarray
    R: np.ndarray          # x_proj = R @ x_cam + T
    T: np.ndarray
    rms_stereo: float
    rms_cam: float
    rms_proj: float
    img_shape: tuple[int, int]   # camera (width, height)
    proj_shape: tuple[int, int]  # projector (width, height)


def decode_at_points(pattern_frames: np.ndarray, points_xy: np.ndarray,
                     n_bits_col: int, n_bits_row: int) -> tuple[np.ndarray, np.ndarray]:
    """Gray-decode projector (col, row) at sparse camera pixels.

    ``pattern_frames``: [2*(n_bits_col+n_bits_row), H, W] — the pattern/inverse
    pairs of one pose, white/black frames already stripped (the capture-file
    contract of ops.graycode.generate_pattern_stack; same frame order as
    server/sl_system.py:126-150). ``points_xy``: [N, 2] float pixel coords.

    The reference decodes corners one bit at a time in Python
    (server/sl_system.py:264-295); here all bits x all corners resolve in one
    vectorized compare + prefix-XOR pass.
    """
    x = points_xy[:, 0].astype(np.intp)
    y = points_xy[:, 1].astype(np.intp)
    h, w = pattern_frames.shape[1:]
    x = np.clip(x, 0, w - 1)
    y = np.clip(y, 0, h - 1)
    vals = pattern_frames[:, y, x].astype(np.int16)  # [F, N]
    pat, inv = vals[0::2], vals[1::2]
    gray = (pat > inv)                                # [bits, N] MSB first

    def axis_value(bits: np.ndarray) -> np.ndarray:
        binary = np.bitwise_xor.accumulate(bits.astype(np.int64), axis=0)
        weights = 1 << np.arange(bits.shape[0] - 1, -1, -1, dtype=np.int64)
        return (binary * weights[:, None]).sum(axis=0).astype(np.float64)

    col = axis_value(gray[:n_bits_col])
    row = axis_value(gray[n_bits_col : n_bits_col + n_bits_row])
    return col, row


def collect_calibration_data(
    base_dir: str,
    pose_list: list[str] | None = None,
    board: cb.BoardSpec = cb.BoardSpec(),
    proj_size: tuple[int, int] = (1920, 1080),
    save_previews: bool = True,
    log=print,
) -> tuple[list[PoseObservation], tuple[int, int]]:
    """Detect + decode every usable pose folder under ``base_dir``.

    Each pose folder holds one capture sequence (white, black, then
    pattern/inverse pairs — 46 files at 1080p). Returns the observations and the
    camera image size (width, height). Poses without a detectable board or with
    an incomplete sequence are skipped with a log line, mirroring the
    reference's per-pose tolerance (server/sl_system.py:226-258).
    """
    if pose_list is None:
        pose_list = sorted(
            d for d in os.listdir(base_dir)
            if os.path.isdir(os.path.join(base_dir, d)) and d != "corners_preview"
        )
    obj = cb.board_object_points(board)
    n_bits_col, n_bits_row = _n_bits(proj_size[0]), _n_bits(proj_size[1])
    need = frames_per_view(proj_size[0], proj_size[1])

    observations: list[PoseObservation] = []
    img_shape: tuple[int, int] | None = None
    for pose in pose_list:
        path = os.path.join(base_dir, pose)
        try:
            files = imio.list_frame_files(path)
        except (FileNotFoundError, NotADirectoryError):
            log(f"[calib] {pose}: not a pose folder, skipped")
            continue
        if len(files) < need:
            log(f"[calib] {pose}: {len(files)} frames < {need} required, skipped")
            continue
        white = imio.load_color(files[0])
        if img_shape is None:
            img_shape = (white.shape[1], white.shape[0])
        corners = cb.find_corners(white, board)
        if corners is None:
            log(f"[calib] {pose}: chessboard not found, skipped")
            continue
        if save_previews:
            preview_dir = os.path.join(base_dir, "corners_preview")
            os.makedirs(preview_dir, exist_ok=True)
            imio.save_image(os.path.join(preview_dir, f"{pose}.png"),
                            cb.draw_corner_preview(white, corners, board))
        patterns = np.stack(
            [imio.load_gray(f) for f in files[2 : need]], axis=0
        )
        col, row = decode_at_points(patterns, corners, n_bits_col, n_bits_row)
        proj_pts = np.column_stack([col, row]).astype(np.float32)
        observations.append(PoseObservation(pose, obj, corners, proj_pts))
    if img_shape is None:
        raise ValueError(f"no usable calibration poses under {base_dir}")
    return observations, img_shape


def _cv2_pts(points_2d: np.ndarray) -> np.ndarray:
    return points_2d.reshape(-1, 1, 2).astype(np.float32)


def reprojection_errors(observations: list[PoseObservation],
                        img_shape: tuple[int, int],
                        proj_size: tuple[int, int] = (1920, 1080),
                        ) -> dict[str, tuple[float, float]]:
    """Per-pose (camera_err, projector_err) in px via quick independent solves.

    True per-pose RMS of the back-projected board corners — the number the
    operator prunes poses with, comparable with the <0.5/<1.0 px quality bands
    (Old/ResultCalibCam.py:72-79). Note the reference reports L2-norm/N
    (server/sl_system.py:326-330), which understates RMS by sqrt(N); RMS here
    keeps the bands meaningful regardless of board size.
    """
    import cv2

    obj = [o.obj_pts for o in observations]
    cam = [_cv2_pts(o.cam_pts) for o in observations]
    proj = [_cv2_pts(o.proj_pts) for o in observations]
    _, mc, dc, rvc, tvc = cv2.calibrateCamera(obj, cam, img_shape, None, None)
    _, mp, dp, rvp, tvp = cv2.calibrateCamera(obj, proj, proj_size, None, None)

    errors: dict[str, tuple[float, float]] = {}
    for i, o in enumerate(observations):
        back_c, _ = cv2.projectPoints(o.obj_pts, rvc[i], tvc[i], mc, dc)
        back_p, _ = cv2.projectPoints(o.obj_pts, rvp[i], tvp[i], mp, dp)
        err_c = float(np.sqrt(np.mean(np.sum((cam[i] - back_c) ** 2, axis=-1))))
        err_p = float(np.sqrt(np.mean(np.sum((proj[i] - back_p) ** 2, axis=-1))))
        errors[o.name] = (err_c, err_p)
    return errors


def analyze_calibration(base_dir: str,
                        board: cb.BoardSpec = cb.BoardSpec(),
                        proj_size: tuple[int, int] = (1920, 1080),
                        log=print):
    """Step-2 analysis: decode all poses, return per-pose errors for pruning.

    Requires >= 3 usable poses for the stereo geometry to be determined
    (server/sl_system.py:194-196).
    """
    observations, img_shape = collect_calibration_data(
        base_dir, board=board, proj_size=proj_size, log=log
    )
    if len(observations) < 3:
        raise ValueError(
            f"need at least 3 usable calibration poses, found {len(observations)}"
        )
    errors = reprojection_errors(observations, img_shape, proj_size)
    return errors, observations, img_shape


def select_poses(errors: dict[str, tuple[float, float]],
                 max_cam_err: float = 1.0,
                 max_proj_err: float = 2.0) -> list[str]:
    """Automatic stand-in for the reference's interactive pose pruning dialog
    (server/gui.py:1211-1239): keep poses under both error ceilings."""
    keep = [p for p, (ec, ep) in errors.items()
            if ec <= max_cam_err and ep <= max_proj_err]
    if len(keep) >= 3:
        return keep
    # fewer than 3 survived the ceilings: fall back to the 3 best-scoring poses
    return sorted(errors, key=lambda p: sum(errors[p]))[:3]


def calibrate_stereo(observations: list[PoseObservation],
                     img_shape: tuple[int, int],
                     proj_size: tuple[int, int] = (1920, 1080),
                     log=print) -> CalibrationSolution:
    """Camera solve + projector-as-camera solve + stereo bond (intrinsics fixed),
    the reference's three-stage scheme (server/sl_system.py:336-350)."""
    import cv2

    obj = [o.obj_pts for o in observations]
    cam = [_cv2_pts(o.cam_pts) for o in observations]
    proj = [_cv2_pts(o.proj_pts) for o in observations]
    log(f"[calib] solving camera intrinsics over {len(obj)} poses...")
    rms_c, mc, dc, _, _ = cv2.calibrateCamera(obj, cam, img_shape, None, None)
    log(f"[calib] camera RMS {rms_c:.4f} px; solving projector intrinsics...")
    rms_p, mp, dp, _, _ = cv2.calibrateCamera(obj, proj, proj_size, None, None)
    log(f"[calib] projector RMS {rms_p:.4f} px; stereo solve...")
    rms_s, K1, D1, K2, D2, R, T, _, _ = cv2.stereoCalibrate(
        obj, cam, proj, mc, dc, mp, dp, img_shape,
        flags=cv2.CALIB_FIX_INTRINSIC,
    )
    log(f"[calib] stereo RMS {rms_s:.4f} px")
    return CalibrationSolution(
        cam_K=K1, cam_dist=D1, proj_K=K2, proj_dist=D2, R=R, T=T,
        rms_stereo=float(rms_s), rms_cam=float(rms_c), rms_proj=float(rms_p),
        img_shape=img_shape, proj_shape=proj_size,
    )


def calibrate_and_save(base_dir: str, output_file: str,
                       selected_poses: list[str] | None = None,
                       board: cb.BoardSpec = cb.BoardSpec(),
                       proj_size: tuple[int, int] = (1920, 1080),
                       include_ray_field: bool = True,
                       observations: list[PoseObservation] | None = None,
                       img_shape: tuple[int, int] | None = None,
                       log=print) -> CalibrationSolution:
    """Full final calibration: decode selected poses, stereo solve, build the
    ray field + light-plane tables, save the .mat-layout calibration file
    (server/sl_system.py:336-425's end-to-end job).

    Pass the ``observations`` + ``img_shape`` that ``analyze_calibration``
    already produced to skip re-reading and re-decoding every pose from disk;
    ``selected_poses`` then filters that list by name.
    """
    if observations is not None and img_shape is not None:
        if selected_poses is not None:
            names = set(selected_poses)
            observations = [o for o in observations if o.name in names]
    else:
        observations, img_shape = collect_calibration_data(
            base_dir, selected_poses, board=board, proj_size=proj_size, log=log
        )
    if len(observations) < 3:
        raise ValueError(
            f"need at least 3 usable calibration poses, found {len(observations)}"
        )
    sol = calibrate_stereo(observations, img_shape, proj_size, log=log)
    calib = build_calibration(
        sol.cam_K, sol.cam_dist, sol.proj_K, sol.R, sol.T,
        cam_width=img_shape[0], cam_height=img_shape[1],
        proj_width=proj_size[0], proj_height=proj_size[1],
        include_ray_field=include_ray_field,
    )
    matfile.save_calibration(output_file, calib)
    log(f"[calib] saved {output_file} (stereo RMS {sol.rms_stereo:.4f} px)")
    return sol
