"""Chessboard corner detection for projector-camera calibration.

Capability parity (behavior studied from server/sl_system.py:222-247): the white
frame of each calibration pose is contrast-enhanced (Gaussian blur + CLAHE), the
inner-corner grid is located, corners are refined to sub-pixel accuracy, and an
annotated preview image is produced for the operator.

OpenCV supplies the corner detector itself (a sparse, branchy CPU algorithm with
no TPU upside); everything around it is ours. The import is lazy and gated so the
rest of the framework works on machines without cv2.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

__all__ = [
    "BoardSpec",
    "board_object_points",
    "find_corners",
    "draw_corner_preview",
]


def _require_cv2():
    try:
        import cv2
    except ImportError as e:  # pragma: no cover - environment dependent
        raise RuntimeError(
            "chessboard detection requires OpenCV (cv2); install opencv-python "
            "or use precomputed corner files"
        ) from e
    return cv2


class BoardSpec(NamedTuple):
    """Inner-corner grid of the calibration chessboard."""

    rows: int = 7
    cols: int = 7
    square_size: float = 35.0  # mm (server/config.py:26-30)


def board_object_points(board: BoardSpec) -> np.ndarray:
    """World coordinates of the inner corners, z=0 plane, row-major [N, 3] float32.

    Grid layout matches the reference's mgrid construction
    (server/sl_system.py:210-212) so saved calibrations are interchangeable.
    """
    obj = np.zeros((board.rows * board.cols, 3), np.float32)
    obj[:, :2] = np.mgrid[0 : board.rows, 0 : board.cols].T.reshape(-1, 2)
    return obj * board.square_size


def enhance_for_detection(gray: np.ndarray) -> np.ndarray:
    """Blur + CLAHE contrast pull, the reference's detection preprocessing
    (server/sl_system.py:230-235)."""
    cv2 = _require_cv2()
    blurred = cv2.GaussianBlur(gray, (5, 5), 0)
    clahe = cv2.createCLAHE(clipLimit=2.0, tileGridSize=(8, 8))
    return clahe.apply(blurred)


def find_corners(image: np.ndarray, board: BoardSpec,
                 refine: bool = True) -> np.ndarray | None:
    """Locate the board's inner corners in a white-frame image.

    Returns sub-pixel corner coordinates [N, 2] float32 (N = rows*cols) or None
    when no complete grid is found. Detection runs on the enhanced image but the
    sub-pixel refinement runs on the raw grayscale (as the reference does:
    server/sl_system.py:236-240) — CLAHE shifts local extrema.
    """
    cv2 = _require_cv2()
    if image.ndim == 3:
        # io.images normalizes to RGB at the IO boundary, so use RGB weights
        gray = cv2.cvtColor(image, cv2.COLOR_RGB2GRAY)
    else:
        gray = image
    ok, corners = cv2.findChessboardCorners(
        enhance_for_detection(gray), (board.rows, board.cols), None
    )
    if not ok:
        return None
    if refine:
        corners = cv2.cornerSubPix(
            gray, corners, (11, 11), (-1, -1),
            (cv2.TERM_CRITERIA_EPS + cv2.TERM_CRITERIA_MAX_ITER, 30, 0.001),
        )
    return corners.reshape(-1, 2).astype(np.float32)


def draw_corner_preview(image: np.ndarray, corners: np.ndarray,
                        board: BoardSpec) -> np.ndarray:
    """Annotated copy of ``image`` with the detected grid drawn on it."""
    cv2 = _require_cv2()
    preview = image.copy()
    if preview.ndim == 2:
        preview = cv2.cvtColor(preview, cv2.COLOR_GRAY2BGR)
    cv2.drawChessboardCorners(
        preview, (board.rows, board.cols),
        corners.reshape(-1, 1, 2).astype(np.float32), True,
    )
    return preview
