"""Lens undistortion as fused JAX kernels (Brown-Conrady model).

The reference leans on OpenCV's CPU undistortion inside its calibration solves
(dc in the saved .mat, server/sl_system.py:413-423) but never undistorts the
capture stack itself. Here undistortion is a first-class TPU op so the scan
pipeline can run on distortion-corrected stacks: the inverse-distortion map is
a fixed-point iteration (data-independent trip count -> unrollable under jit),
and the remap is a gather + bilinear blend that XLA fuses with the decode.

Distortion model (k1, k2, p1, p2, k3), matching OpenCV's ordering so saved
``dc`` vectors drop straight in.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "distort_points",
    "undistort_points",
    "undistort_map",
    "remap_bilinear",
    "undistort_image",
    "undistort_stack",
]


def _split_dist(dist):
    d = jnp.zeros(5, jnp.float32).at[: dist.shape[0]].set(dist[:5].astype(jnp.float32))
    return d[0], d[1], d[2], d[3], d[4]


def distort_points(pts_norm, dist):
    """Apply forward Brown-Conrady distortion to normalized coords [..., 2]."""
    k1, k2, p1, p2, k3 = _split_dist(jnp.asarray(dist).reshape(-1))
    x, y = pts_norm[..., 0], pts_norm[..., 1]
    r2 = x * x + y * y
    radial = 1.0 + r2 * (k1 + r2 * (k2 + r2 * k3))
    xd = x * radial + 2.0 * p1 * x * y + p2 * (r2 + 2.0 * x * x)
    yd = y * radial + p1 * (r2 + 2.0 * y * y) + 2.0 * p2 * x * y
    return jnp.stack([xd, yd], axis=-1)


def undistort_points(pts_norm, dist, iters: int = 8):
    """Invert the distortion by fixed-point iteration (OpenCV uses 5; 8 converges
    past fp32 resolution for typical consumer-lens coefficients)."""
    pts_norm = jnp.asarray(pts_norm, jnp.float32)
    und = pts_norm

    def body(_, und):
        d = distort_points(und, dist)
        return und + (pts_norm - d)

    return jax.lax.fori_loop(0, iters, body, und)


@functools.partial(jax.jit, static_argnames=("width", "height"))
def undistort_map(K, dist, *, width: int, height: int):
    """Sampling map [H, W, 2]: for each undistorted output pixel, the (x, y)
    source location in the distorted input image."""
    K = jnp.asarray(K, jnp.float32)
    fx, fy, cx, cy = K[0, 0], K[1, 1], K[0, 2], K[1, 2]
    u, v = jnp.meshgrid(jnp.arange(width, dtype=jnp.float32),
                        jnp.arange(height, dtype=jnp.float32))
    norm = jnp.stack([(u - cx) / fx, (v - cy) / fy], axis=-1)
    dist_norm = distort_points(norm, dist)
    sx = dist_norm[..., 0] * fx + cx
    sy = dist_norm[..., 1] * fy + cy
    return jnp.stack([sx, sy], axis=-1)


def remap_bilinear(img, sample_map):
    """Bilinear resample of ``img`` [H, W(, C)] at ``sample_map`` [h, w, 2] (x, y).

    Out-of-bounds samples clamp to the border (the gather indices are clipped,
    so the op stays a pure fused gather — no dynamic shapes).
    """
    img = jnp.asarray(img)
    h, w = img.shape[:2]
    x, y = sample_map[..., 0], sample_map[..., 1]
    x0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, w - 1)
    y0 = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    fx = jnp.clip(x - x0.astype(jnp.float32), 0.0, 1.0)
    fy = jnp.clip(y - y0.astype(jnp.float32), 0.0, 1.0)
    if img.ndim == 3:
        fx, fy = fx[..., None], fy[..., None]
    p00 = img[y0, x0].astype(jnp.float32)
    p01 = img[y0, x1].astype(jnp.float32)
    p10 = img[y1, x0].astype(jnp.float32)
    p11 = img[y1, x1].astype(jnp.float32)
    top = p00 * (1 - fx) + p01 * fx
    bot = p10 * (1 - fx) + p11 * fx
    out = top * (1 - fy) + bot * fy
    return out.astype(img.dtype) if jnp.issubdtype(img.dtype, jnp.integer) else out


@jax.jit
def _remap_one(img, sample_map):
    return remap_bilinear(img, sample_map)


def undistort_image(img, K, dist):
    """Undistort one image [H, W(, C)]."""
    h, w = np.asarray(img).shape[:2]
    m = undistort_map(jnp.asarray(K), jnp.asarray(dist), width=w, height=h)
    return _remap_one(jnp.asarray(img), m)


@jax.jit
def _remap_stack(frames, sample_map):
    return jax.vmap(lambda f: remap_bilinear(f, sample_map))(frames)


def undistort_stack(frames, K, dist):
    """Undistort a whole capture stack [F, H, W] with one shared map — the map
    builds once and the F remaps batch on-device."""
    f = jnp.asarray(frames)
    m = undistort_map(jnp.asarray(K), jnp.asarray(dist),
                      width=f.shape[2], height=f.shape[1])
    return _remap_stack(f, m)
