"""Content-addressed blob fabric: the stage cache's network L2.

PR-8's coordinated workers shared one disk, so "shared stage cache" was
free. Across real hosts it is not — this module makes the content-addressed
store a two-level cache:

  L1  the worker's local ``StageCache`` directory (write-through, always
      consulted first, exactly the PR-2 semantics)
  L2  a blob service co-hosted with the coordinator, speaking the same
      newline-JSON control framing as the lease protocol with raw
      length-announced payload bytes after the header line

Entries are immutable and content-addressed (``<stage>-<key16>.npz``), so
there is no consistency problem to solve: a name either resolves to the
right bytes or to a miss. Corruption cannot cross the wire undetected —
every transfer carries a sha256 of the raw blob bytes, verified on BOTH
ends (the server rejects a torn push before publishing; the client drops a
torn fetch), and a fetched blob is then promoted into L1 and re-read
through ``StageCache.get``'s normal ``__key__``/``__digest__`` verification.
A corrupt or torn blob is therefore always a *miss* — never a wrong answer
— and a miss just means the item recomputes, which the cache-warmer
parity construction already tolerates.

Protocol (one connection, sequential request/response):

  ``{"op": "hello", "secret": S}``                 -> ``{"ok": true}``
  ``{"op": "get", "name": N}``                     -> ``{"ok": true,
      "size": n, "sha256": d}`` + n raw bytes, or ``{"ok": false}`` (miss)
  ``{"op": "put", "name": N, "size": n, "sha256": d}`` + n raw bytes
      -> ``{"ok": true, "deduped": bool}``

When the coordinator's shared secret is set, the first request on every
connection must be a matching ``hello``; anything else answers
``{"error": "unauthorized"}`` and nothing is served.

Fault sites: ``blob.fetch`` / ``blob.push`` fire client-side per transfer
(transient faults absorb into one retry; anything else degrades to a
miss / unpushed blob — the fabric is an optimization, never a failure
source). ``worker.sock`` fires per control frame and is where the
``net.slowlink(T)`` kind delays traffic.
"""
from __future__ import annotations

import hashlib
import json
import os
import socket
import threading

from structured_light_for_3d_model_replication_tpu.parallel import netutil
from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
    StageCache,
)
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["BlobServer", "BlobClient", "FabricCache"]

# blobs are whole .npz stage payloads; cap a single transfer well above
# any real payload but below "a corrupted size field just allocated 8 GB"
_MAX_BLOB = 1 << 31


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _safe_name(name: str) -> bool:
    """Blob names are exactly the store's entry basenames
    (``<stage>-<key16>``) — no separators, no dotfiles, no traversal."""
    return bool(name) and all(c.isalnum() or c in "-_" for c in name) \
        and len(name) <= 128


class BlobServer:
    """Serve a ``StageCache`` directory over TCP (daemon accept loop, one
    thread per connection — the coordinator ``_Server`` shape). Co-hosted
    with the coordinator and backed by the SAME directory the assembly
    pass reads, so every blob a worker pushes is already where the
    single-process pipeline expects it."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 secret: str = "", log=None, on_blob=None):
        self.root = root
        self.secret = secret
        self._log = log or (lambda m: None)
        # on_blob(name): called after a pushed blob COMMITS to the store
        # (post os.replace — the bytes are readable). The incremental
        # assembler's earliest wake-up signal; must be cheap/non-blocking
        # (it runs on the per-connection server thread) and must never
        # raise into the protocol loop.
        self._on_blob = on_blob
        os.makedirs(root, exist_ok=True)
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self._lock = threading.Lock()
        self._counters = {"fetches": 0, "misses": 0, "pushes": 0,
                          "dedups": 0, "rejects": 0, "bytes_fetched": 0,
                          "bytes_pushed": 0, "bytes_deduped": 0}
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="sl3d-blobstore", daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return netutil.format_endpoint(self.host, self.port)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def names(self) -> list[str]:
        """Current inventory of the backing store (entry names without the
        ``.npz`` suffix) — the coordinator's own holdings."""
        try:
            return sorted(f[:-4] for f in os.listdir(self.root)
                          if f.endswith(".npz"))
        except OSError:
            return []

    def close(self) -> None:
        self._done.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)

    # -- internals -------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] += n

    def _accept_loop(self) -> None:
        while not self._done.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        authed = not self.secret
        try:
            conn.settimeout(60.0)
            f = conn.makefile("rwb")
            while not self._done.is_set():
                line = f.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                except ValueError:
                    _reply(f, {"error": "bad request"})
                    return
                op = req.get("op")
                if op == "hello":
                    if self.secret and req.get("secret") != self.secret:
                        _reply(f, {"error": "unauthorized"})
                        return
                    authed = True
                    _reply(f, {"ok": True})
                    continue
                if not authed:
                    _reply(f, {"error": "unauthorized"})
                    return
                if op == "get":
                    self._op_get(f, req)
                elif op == "put":
                    self._op_put(f, req)
                else:
                    _reply(f, {"error": f"unknown op {op!r}"})
        except (OSError, ValueError):
            pass    # client went away / torn frame: their retry, our shrug
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _op_get(self, f, req: dict) -> None:
        name = req.get("name", "")
        path = os.path.join(self.root, name + ".npz")
        if not _safe_name(name) or not os.path.exists(path):
            self._bump("misses")
            _reply(f, {"ok": False})
            return
        try:
            with open(path, "rb") as blob:
                data = blob.read()
        except OSError:
            self._bump("misses")
            _reply(f, {"ok": False})
            return
        _reply(f, {"ok": True, "size": len(data), "sha256": _sha256(data)})
        f.write(data)
        f.flush()
        self._bump("fetches")
        self._bump("bytes_fetched", len(data))

    def _op_put(self, f, req: dict) -> None:
        name = req.get("name", "")
        size = int(req.get("size", -1))
        if not _safe_name(name) or not 0 <= size <= _MAX_BLOB:
            _reply(f, {"error": "bad put header"})
            return
        data = f.read(size)
        if len(data) != size or _sha256(data) != req.get("sha256"):
            # torn or corrupted in flight: NEVER publish; the pusher's L1
            # still has the real bytes and assembly recomputes at worst
            self._bump("rejects")
            _reply(f, {"error": "digest mismatch"})
            return
        path = os.path.join(self.root, name + ".npz")
        if os.path.exists(path):
            self._bump("dedups")
            self._bump("bytes_deduped", size)
            _reply(f, {"ok": True, "deduped": True})
            return
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as out:
                out.write(data)
            os.replace(tmp, path)
        except OSError as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            _reply(f, {"error": f"store write failed: {e}"})
            return
        self._bump("pushes")
        self._bump("bytes_pushed", size)
        if self._on_blob is not None:
            try:
                self._on_blob(name)
            except Exception:
                pass   # a notification hook must never break the protocol
        _reply(f, {"ok": True, "deduped": False})


def _reply(f, obj: dict) -> None:
    f.write((json.dumps(obj) + "\n").encode())
    f.flush()


class BlobClient:
    """Worker-side L2 channel: one persistent connection, lazy dial with
    the PR-7 connect deadline, one silent reconnect per call. Every public
    method degrades to a miss / no-op on failure — the fabric must never
    turn a computable item into a failed one."""

    def __init__(self, endpoint: str, secret: str = "",
                 connect_timeout_s: float = 20.0,
                 io_timeout_s: float = 60.0):
        self.host, self.port = netutil.parse_endpoint(endpoint)
        self.secret = secret
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self._sock: socket.socket | None = None
        self._file = None
        self._lock = threading.Lock()

    def _connect(self) -> None:
        deadline = dl.Deadline.after(self.connect_timeout_s,
                                     "blobstore connect")
        last: Exception | None = None
        while True:
            if deadline is not None and deadline.remaining() <= 0:
                raise dl.DeadlineExceeded(
                    f"blobstore at {self.host}:{self.port} unreachable "
                    f"within {self.connect_timeout_s:g}s ({last})")
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0)
                break
            except OSError as e:
                last = e
                dl.sleep_cancellable(0.2)
        self._sock.settimeout(self.io_timeout_s)
        self._file = self._sock.makefile("rwb")
        if self.secret:
            rep = self._roundtrip({"op": "hello", "secret": self.secret})
            if not rep.get("ok"):
                raise ConnectionError(
                    f"blobstore hello rejected: {rep.get('error')}")

    def _roundtrip(self, req: dict, body: bytes = b"") -> dict:
        faults.fire("worker.sock", item=f"blob:{req.get('op')}")
        self._file.write((json.dumps(req) + "\n").encode())
        if body:
            self._file.write(body)
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("blobstore closed the connection")
        return json.loads(line)

    def _reset(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._file = None

    def close(self) -> None:
        with self._lock:
            self._reset()

    def fetch(self, name: str) -> bytes | None:
        """Blob bytes by name, or None on ANY miss: absent, unreachable,
        torn, or digest-mismatched. A transient ``blob.fetch`` fault (and
        one socket hiccup) absorbs into a single retry."""
        for attempt in (1, 2):
            try:
                faults.fire("blob.fetch", item=name)
                with self._lock:
                    if self._file is None:
                        self._connect()
                    rep = self._roundtrip({"op": "get", "name": name})
                    if not rep.get("ok"):
                        return None
                    size = int(rep.get("size", -1))
                    if not 0 <= size <= _MAX_BLOB:
                        raise ConnectionError("bad fetch header")
                    data = self._file.read(size)
                if len(data) == size and _sha256(data) == rep.get("sha256"):
                    return data
                # torn/corrupt in flight — treat exactly like a socket
                # error: drop the connection, maybe retry, else miss
                raise ConnectionError("fetched blob failed digest check")
            except faults.InjectedCrash:
                raise
            except dl.DeadlineExceeded:
                return None     # unreachable within budget: miss, not fatal
            except Exception as e:
                self._reset()
                if attempt == 1 and _retryable(e):
                    continue
                return None
        return None

    def push(self, name: str, data: bytes) -> str | None:
        """Publish blob bytes; returns "pushed", "deduped", or None on
        failure (best-effort — L1 still holds the payload)."""
        for attempt in (1, 2):
            try:
                faults.fire("blob.push", item=name)
                with self._lock:
                    if self._file is None:
                        self._connect()
                    rep = self._roundtrip(
                        {"op": "put", "name": name, "size": len(data),
                         "sha256": _sha256(data)}, body=data)
                if rep.get("ok"):
                    return "deduped" if rep.get("deduped") else "pushed"
                return None
            except faults.InjectedCrash:
                raise
            except dl.DeadlineExceeded:
                return None     # unreachable within budget: no-op, not fatal
            except Exception as e:
                self._reset()
                if attempt == 1 and _retryable(e):
                    continue
                return None
        return None


def _retryable(e: Exception) -> bool:
    """One retry for injected transients and ordinary socket trouble;
    injected *permanent* faults must not retry (that is their contract)."""
    if isinstance(e, faults.InjectedFault):
        return faults.is_transient(e)
    return isinstance(e, (OSError, ConnectionError, ValueError))


class FabricCache(StageCache):
    """Two-level stage cache: local disk is the write-through L1 (all the
    PR-2 semantics — verification, eviction, atomic publish), the blob
    fabric is L2.

    ``get``: L1 first; on miss, fetch by name from L2, promote the raw
    bytes into L1 (tmp + rename), and re-read through the NORMAL verifying
    ``StageCache.get`` — so a fetched blob passes the same
    ``__key__``/``__digest__`` checks as a local entry, and a corrupt one
    evicts and stays a miss. The journal then shows the true story: one
    ``cache.miss`` (L1) followed by one ``cache.hit`` (promoted).

    ``put``: write-through — L1 publish via ``StageCache.put``, then push
    the published file's bytes to L2 so dependents on OTHER hosts can
    fetch it. Names published or promoted since the last drain accumulate
    in a pending set the worker piggybacks on its next heartbeat — the
    inventory protocol behind locality-aware grants.
    """

    def __init__(self, root: str, client: BlobClient | None,
                 enabled: bool = True, log=None, verify: bool = True,
                 stats=None):
        super().__init__(root, enabled=enabled, log=log, verify=verify)
        self._client = client
        self._stats = stats      # OverlapStats (add_fabric) or None
        self._plock = threading.Lock()
        self._pending: set[str] = set()

    def _note(self, name: str) -> None:
        with self._plock:
            self._pending.add(name)

    def drain_inventory(self) -> list[str]:
        """Names newly held since the last drain (heartbeat payload)."""
        with self._plock:
            out = sorted(self._pending)
            self._pending.clear()
            return out

    def requeue_inventory(self, names) -> None:
        """Put a drained diff back (the carrying request never arrived) so
        the next heartbeat retries it — diffs are additive, so replays
        cannot corrupt the coordinator's index."""
        with self._plock:
            self._pending.update(names)

    def local_names(self) -> list[str]:
        """Full L1 inventory — the bootstrap diff a worker sends on
        ``hello`` (resumed workers may hold entries from a prior run)."""
        try:
            return sorted(f[:-4] for f in os.listdir(self.root)
                          if f.endswith(".npz"))
        except OSError:
            return []

    def get(self, stage: str, key: str) -> dict | None:
        hit = super().get(stage, key)
        if hit is not None or not self.enabled or self._client is None:
            return hit
        name = f"{stage}-{key[:16]}"
        data = self._client.fetch(name)
        if data is None:
            return None
        path = self._path(stage, key)
        tmp = path + ".fetch.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        if self._stats is not None:
            self._stats.add_fabric(fetched=len(data))
        hit = super().get(stage, key)    # full verify; corrupt -> evict+miss
        if hit is not None:
            self._note(name)
        return hit

    def put(self, stage: str, key: str, **arrays) -> None:
        super().put(stage, key, **arrays)
        if not self.enabled:
            return
        path = self._path(stage, key)
        if not os.path.exists(path):
            return    # best-effort L1 put failed; nothing to push
        name = f"{stage}-{key[:16]}"
        self._note(name)
        if self._client is None:
            return
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return
        status = self._client.push(name, data)
        if self._stats is not None and status is not None:
            if status == "deduped":
                self._stats.add_fabric(deduped=len(data))
            else:
                self._stats.add_fabric(pushed=len(data))
