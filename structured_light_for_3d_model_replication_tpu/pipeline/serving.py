"""``sl3d serve`` — the persistent multi-tenant scan service.

The paper's L2 layer is a one-shot broker: a phone uploads frames, a CLI
run turns them into a model. This module is its serving-shaped
replacement — ONE long-lived process, many tenants, one shared device
mesh — built by composing layers this repo already proved one at a time:

  gateway   stdlib ``ThreadingHTTPServer`` speaking JSON, the same
            no-deps transport discipline as the PR-8 coordinator's
            newline-JSON wire protocol. ``/submit`` · ``/status/<id>`` ·
            ``/result/<id>`` · ``/metrics`` · ``/healthz`` · ``/usage``.
            With ``serving.auth_enabled`` the door checks per-tenant API
            keys (sha256 at rest in ``<root>/tenants.json``; 401/403
            machine-readable reasons) and per-tenant sliding-window rate
            limits (429, the quota vocabulary) before anything else.
  admission ``parallel/admission.py``: per-tenant quotas (a submit over
            quota is a 429 at the door) + weighted-fair scheduling over
            the multi-scan generalization of the PR-8 lease/ledger —
            every grant/steal/complete is journaled fsync'd.
  engine    in-process lanes that warm the content-addressed stage
            cache, drawing view grants interleaved across tenants so
            views from DIFFERENT scans fill the same bucket-padded
            ``forward_views_batched`` launch (cross-tenant batching —
            the MRI-serving shape: keep the dense solve saturated with
            whoever's work is ready). Numpy-backend deployments take
            the per-view lane; either way the item program is exactly
            the PR-8 worker's (load → compute → compact → clean → put).
  assembly  one request at a time, the proven single-process
            ``run_pipeline`` over the warmed cache with a per-tenant
            cache namespace (``TenantCache``) — so every response is
            **byte-identical to a solo ``sl3d pipeline`` run** of the
            same input, by the PR-8 construction: engine lanes only
            warm; assembly recomputes anything missing through the full
            retry/quarantine lane.

Failure domains are per REQUEST: a poisoned view quarantines inside its
own scan's assembly (PR-3 semantics — that request completes DEGRADED
with its own ``failures.json``); a per-request SLO (``budget_s``,
clock starting at submit) aborts only that request via the PR-7 run
budget; the service keeps running through all of it.

Cache sharing is content-addressed and tenant-scoped at once: identical
frame bytes + config from two tenants hash to ONE cached entry (dedup),
while ``TenantCache`` ref-marker namespaces keep eviction and listing
per-tenant — evicting tenant A never deletes a payload tenant B still
references, and outputs never alias because every request owns its
``out_dir``.

Durability (ISSUE 13) — the service state outlives the process:

  records   every accepted ``/submit`` is persisted FIRST as a request
            record (``<root>/requests/<scan_id>.json``, schema
            ``sl3d-request-v1``, atomic write + fsync) and only then
            journaled/queued/202'd — a crash at any point leaves either
            no trace (client retries) or a resumable record.
  resume    ``start()`` sweeps torn ``.tmp`` records, folds
            ``ledger.jsonl`` through ``replay_serving``, re-registers
            terminal scans (so /status and /result keep answering) and
            re-queues every non-terminal one. Ledger-credited views are
            already bytes in the content-addressed cache, so a restarted
            service re-plans them as WARM: zero recompute, and the
            served PLY/STL stays byte-identical to an uninterrupted run
            (the PR-8 parity construction carried across process death).
            Client-supplied scan_ids are durably idempotent: the same
            (tenant, target, calib) re-submitted after a crash returns
            the existing request, a different one is a 409 conflict.
  lifecycle ``phase``: ready → draining → stopped. SIGTERM/SIGINT (and
            ``stop()``) drain: new submits 503 with Retry-After, active
            scans get ``serving.drain_budget_s`` to finish; past the
            budget the in-flight assembly is aborted through the PR-7
            run-budget lever (``RunContext.abort`` → failures.json) and
            the scan is CHECKPOINTED — non-terminal, re-queued by the
            next start with its warmed views still cached.
  overload  ``shed_expired`` drops queued scans that already blew their
            SLO (or ``serving.max_queue_wait_s``) with a ``shed`` ledger
            event before they waste engine time; a per-tenant circuit
            breaker fast-fails a tenant whose scans keep failing until a
            half-open probe proves recovery.
  chaos     ``serve.crash`` fires at the grant / complete / assembly
            boundaries, ``ledger.append`` on every journal line,
            ``http.submit`` in the gateway — the kill→restart matrix in
            ``tools/soak.py`` and the SERVE_CHAOS_SMOKE CI arm drive
            them end to end.

Gateway HA (ISSUE 14) — ``serving.ha_enabled`` runs N gateways over ONE
shared root, exactly one owning the engine at a time:

  election  ``parallel/election.py``: an fsync'd, atomically-renewed
            leader lease (``<root>/leader.json``) with a monotonic epoch
            that bumps on every takeover. Followers bind HTTP, serve
            reads (/status /result /metrics /healthz answer from a
            cached fold of the shared ledger + the shared artifact
            tree), and answer /submit with a machine-readable
            ``not-leader`` redirect carrying the leader's address.
  fencing   the leader's ledger appends and request records are stamped
            with its epoch and pass ``LeaderLease.fence`` first — a
            deposed leader waking from a stall has the write REJECTED
            (``FencedWrite``) and self-demotes; ``replay_serving``
            applies the same rule offline, ignoring stale-epoch lines.
            Split-brain therefore cannot interleave two writers' credit:
            at most one epoch's appends are ever folded past a takeover.
  takeover  is exactly the restart-resume path run on the standby:
            replay ledger + request records, re-queue non-terminal
            scans, finish ledger-credited views as pure cache hits
            (``views_computed == 0``, byte parity by construction).
            ``serve.json`` is atomically rewritten with the new epoch so
            clients re-discover. ``election.acquire``/``election.renew``
            chaos sites + ``tools/soak.py --ha-runs`` + the HA_SMOKE CI
            arm prove the failover bound end to end.
"""
from __future__ import annotations

import copy
import fcntl
import json
import os
import re
import signal
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io.atomic import (
    atomic_write,
    sweep_tmp,
)
from structured_light_for_3d_model_replication_tpu.parallel.admission import (
    AdmissionController,
    RateLimiter,
    ScanJob,
    TenantAuth,
    fold_usage,
    replay_serving,
)
from structured_light_for_3d_model_replication_tpu.parallel.admission import (
    TERMINAL as _TERMINAL,
)
from structured_light_for_3d_model_replication_tpu.parallel import election
from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
    TenantCache,
)
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import (
    telemetry as tel,
)

__all__ = ["ScanService", "serve", "start_gateway", "REQUEST_SCHEMA"]

_ID_RE = re.compile(r"[^A-Za-z0-9._-]+")
_AUTO_ID_RE = re.compile(r"-s(\d{4,})$")

REQUEST_SCHEMA = "sl3d-request-v1"

# machine-readable /submit rejection reasons -> HTTP status. 429 =
# per-tenant/backlog quota (client backs off and retries), 503 =
# service-side refusal (draining, open breaker, injected transient,
# HA follower redirect — retry after Retry-After, at the advertised
# leader when the body carries one), 409 = durable-id conflict,
# 400 = malformed
_REASON_HTTP = {"tenant-queue-quota": 429, "queue-full": 429,
                "rate-limited": 429,
                "draining": 503, "stopped": 503, "crashed": 503,
                "circuit-open": 503, "transient": 503,
                "not-leader": 503,
                "auth-required": 401, "auth-invalid": 401,
                "auth-forbidden": 403,
                "scan-id-conflict": 409, "bad-request": 400}


def _safe_id(s: str, fallback: str) -> str:
    s = _ID_RE.sub("-", str(s or "")).strip("-.")[:64]
    return s or fallback


class _ScanCtx:
    """Everything the engine holds for one admitted scan: the shared plan
    (``stages._view_plan`` — the SAME key derivation the assembly pass
    will use), this tenant's cache namespace, and the scanner key that
    lets different scans share one batched launch."""

    __slots__ = ("job", "steps", "calib", "sources", "view_keys", "cache",
                 "scanner_key")

    def __init__(self, job, steps, calib, sources, view_keys, cache,
                 scanner_key):
        self.job = job
        self.steps = steps
        self.calib = calib
        self.sources = sources
        self.view_keys = view_keys
        self.cache = cache
        self.scanner_key = scanner_key


class ScanService:
    """The serving core: admission + engine + assembly over one shared
    stage-cache store. HTTP lives in ``_Handler``/``serve`` so tests can
    drive this object directly."""

    def __init__(self, root: str, cfg: Config | None = None, log=print):
        from structured_light_for_3d_model_replication_tpu.pipeline import (
            stages,
        )

        self.cfg = cfg or Config()
        self.log = log
        self.root = os.path.abspath(root)
        self.scans_dir = os.path.join(self.root, "scans")
        self.store_root = os.path.join(self.root, "cache")
        self.ns_root = os.path.join(self.root, "cache-ns")
        self.requests_dir = os.path.join(self.root, "requests")
        os.makedirs(self.scans_dir, exist_ok=True)
        os.makedirs(self.store_root, exist_ok=True)
        os.makedirs(self.requests_dir, exist_ok=True)
        self.run_id = tel.new_run_id()
        self.registry = tel.MetricsRegistry()
        scfg = self.cfg.serving
        self._ledger_path = os.path.join(self.root, "ledger.jsonl")
        # HA (ISSUE 14): with ha_enabled this gateway joins a leader-
        # elected group over the shared root. It boots as a FOLLOWER —
        # no ledger open, no engine — and only builds the admission
        # core when it wins the lease (see _promote). role is one of
        # solo | follower | leader | demoting.
        self.ha = bool(scfg.ha_enabled)
        self.role = "follower" if self.ha else "solo"
        self.election: election.LeaderLease | None = None
        self._adv: dict | None = None   # advertised address (gateway)
        self._guard_f = None            # single-writer flock (solo mode)
        self._ha_thread: threading.Thread | None = None
        self._reign_threads: list[threading.Thread] = []
        self._lead_stop = threading.Event()   # set on demotion only
        self._demote_lock = threading.Lock()
        self._view_key: tuple | None = None   # follower fold cache
        self._view_rs: dict | None = None
        if self.ha:
            self.election = election.LeaderLease(
                os.path.join(self.root, "leader.json"),
                owner=self.run_id, lease_s=scfg.ha_lease_s)
            self._probe_guard()
            self.adm: AdmissionController | None = None
        else:
            # single-writer guard BEFORE the ledger opens: a second solo
            # gateway on this root must fail fast, not interleave meta
            # lines into a ledger someone else is serving from
            self._acquire_guard()
            self.adm = self._make_adm()
        # lifecycle phase: ready -> draining -> stopped (crashed when an
        # injected crash felled the in-process service). A bare
        # ScanService accepts submits from construction (tests drive it
        # without start()); only drain/stop flips the gate
        self.phase = "ready"
        self._draining = threading.Event()   # admit_next gate
        self._drain_breach = threading.Event()
        self.exit_on_crash = False           # serve() sets True: real exit
        self._stages = stages
        self._policy = stages._retry_policy(self.cfg)
        self._fwd_kw = dict(thresh_mode=self.cfg.decode.thresh_mode,
                            shadow_val=self.cfg.decode.shadow_val,
                            contrast_val=self.cfg.decode.contrast_val)
        self._scans: dict[str, _ScanCtx] = {}
        self._scanners: dict[tuple, object] = {}   # scanner_key -> scanner
        # elastic fleet (ISSUE 18): the supervisor belongs to whichever
        # reign owns the engine — solo start() builds it, _promote
        # rebuilds it from the replayed ledger, _demote tears it down
        self.fleet = None
        # front-door auth (ISSUE 18): per-tenant API keys + rate limits.
        # Disabled (the default) costs /submit ONE attribute check — the
        # differential contract the fleet bench stamps
        self._auth: TenantAuth | None = None
        self._rlim: RateLimiter | None = None
        if scfg.auth_enabled:
            self._auth = TenantAuth(
                scfg.auth_tenants_file
                or os.path.join(self.root, "tenants.json"))
            self._rlim = RateLimiter(scfg.auth_rate_limit,
                                     scfg.auth_rate_window_s)
        self._scan_lock = threading.Lock()
        self._assembly_q: list[str] = []
        self._assembly_cv = threading.Condition()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._seq = 0
        self._seq_lock = threading.Lock()

    # ---- HA plumbing -----------------------------------------------------

    @property
    def epoch(self) -> int:
        """This gateway's fencing token: 0 for solo gateways and
        followers, the held lease epoch while leading."""
        return self.election.epoch if self.election is not None else 0

    def _make_adm(self) -> AdmissionController:
        scfg = self.cfg.serving
        ep = fence = None
        if self.election is not None:
            ep = lambda: self.election.epoch      # noqa: E731
            fence = self.election.fence
        return AdmissionController(
            self._ledger_path, self.run_id,
            lease_s=scfg.lease_s, max_active_scans=scfg.max_active_scans,
            tenant_active_quota=scfg.tenant_active_quota,
            tenant_queue_quota=scfg.tenant_queue_quota,
            queue_depth=scfg.queue_depth,
            max_queue_wait_s=scfg.max_queue_wait_s,
            breaker_threshold=scfg.breaker_threshold,
            breaker_cooldown_s=scfg.breaker_cooldown_s,
            epoch=ep, fence=fence, log=self.log)

    def _guard_path(self) -> str:
        return os.path.join(self.root, "serve.lock")

    def _acquire_guard(self) -> None:
        """Single-writer guard for SOLO gateways (ISSUE 14 satellite):
        hold an exclusive flock on ``<root>/serve.lock`` for the life of
        the service. A second solo gateway on the same root fails fast
        with who-owns-it instead of silently interleaving ledger
        appends. Same-pid contention is tolerated — an in-process
        crash-restart twin (tests, soak) still holds the dead instance's
        fd, and the pid proves it is us."""
        lp = os.path.join(self.root, "leader.json")
        try:
            with open(lp, encoding="utf-8") as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = None
        if (cur is not None
                and float(cur.get("expires_unix", 0.0)) > time.time()):
            raise RuntimeError(
                f"root {self.root} already served by HA leader "
                f"{cur.get('owner')!r} (pid {cur.get('pid')}, epoch "
                f"{cur.get('epoch')}); start this gateway with "
                f"serving.ha_enabled to join the group")
        f = open(self._guard_path(), "a+", encoding="utf-8")
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            f.seek(0)
            try:
                info = json.load(f)
            except ValueError:
                info = {}
            f.close()
            if int(info.get("pid", -1)) == os.getpid():
                self.log("[serve] serve.lock held by this process "
                         "(in-process restart); continuing")
                return
            raise RuntimeError(
                f"root {self.root} already served by pid "
                f"{info.get('pid')} (run {info.get('run_id')}, "
                f"{'HA epoch %s' % info.get('epoch') if info.get('ha') else 'solo'}"
                f"); refusing a second writer — stop it or run an HA "
                f"group (serving.ha_enabled)") from None
        f.seek(0)
        f.truncate()
        json.dump({"pid": os.getpid(), "run_id": self.run_id,
                   "ha": False, "epoch": 0}, f)
        f.flush()
        self._guard_f = f

    def _probe_guard(self) -> None:
        """HA members don't HOLD the flock (a zombie's fd must never
        block a takeover — the lease file is their arbiter), but they do
        refuse to join a root a SOLO gateway is actively serving."""
        try:
            f = open(self._guard_path(), "r+", encoding="utf-8")
        except OSError:
            return
        try:
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            except OSError:
                f.seek(0)
                try:
                    info = json.load(f)
                except ValueError:
                    info = {}
                if (not info.get("ha")
                        and int(info.get("pid", -1)) != os.getpid()):
                    raise RuntimeError(
                        f"root {self.root} already served by solo "
                        f"gateway pid {info.get('pid')} (run "
                        f"{info.get('run_id')}); stop it before "
                        f"starting an HA group") from None
        finally:
            f.close()

    def _release_guard(self) -> None:
        if self._guard_f is None:
            return
        try:
            fcntl.flock(self._guard_f.fileno(), fcntl.LOCK_UN)
        except OSError:
            pass
        try:
            self._guard_f.close()
        except OSError:
            pass
        self._guard_f = None

    def advertise(self, host: str, port: int, argv=None) -> None:
        """Record this gateway's bound address — the leader lease and
        serve.json both carry it so clients and followers can point at
        the current leader. Called by start_gateway before start()."""
        self._adv = {"host": host, "port": int(port),
                     "argv": list(argv if argv is not None else sys.argv)}
        if self.election is not None:
            self.election.info.update(host=host, port=int(port))

    def _publish_serve_json(self) -> None:
        """The discovery handshake, epoch-stamped and ATOMICALLY
        rewritten (ISSUE 14 satellite): a client holding a stale leader
        address re-reads this file and sees a newer epoch + address
        instead of retrying a dead socket forever. Solo gateways write
        it once at startup (epoch 0); HA leaders rewrite it on every
        takeover."""
        if self._adv is None:
            return
        info = {"host": self._adv["host"], "port": self._adv["port"],
                "pid": os.getpid(), "run_id": self.run_id,
                "root": self.root, "argv": self._adv["argv"],
                "role": self.role, "epoch": self.epoch}
        path = os.path.join(self.root, "serve.json")
        with atomic_write(path) as tmp:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(info, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())

    def _redirect_body(self) -> dict:
        """The follower's /submit answer: PR-12's machine-readable
        rejection envelope pointing at the current leader."""
        scfg = self.cfg.serving
        body = {"error": f"this gateway is a {self.role}; submit to "
                         f"the leader",
                "reason": "not-leader", "role": self.role,
                "retry_after_s": round(
                    scfg.ha_poll_s or max(0.1, scfg.ha_lease_s / 5.0), 3)}
        cur = self.election.current() if self.election is not None else None
        if cur is not None:
            body["epoch"] = int(cur.get("epoch", 0))
            if cur.get("host") is not None and cur.get("port") is not None:
                body["leader"] = {
                    "host": cur["host"], "port": cur["port"],
                    "url": f"http://{cur['host']}:{cur['port']}"}
        return body

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> None:
        scfg = self.cfg.serving
        if self.ha:
            # HA member: the election loop owns the engine lifecycle —
            # it promotes (building admission + lanes) when this member
            # wins the lease and demotes when it loses it
            self._ha_thread = threading.Thread(
                target=self._ha_loop, name="sl3d-serve-ha", daemon=True)
            self._ha_thread.start()
            self.log(f"[serve] HA member up (run {self.run_id}) "
                     f"root={self.root} — awaiting election")
            return
        if scfg.durable:
            self._resume()
        self._threads.extend(self._start_engine_threads())
        self._start_fleet()
        self.log(f"[serve] service up (run {self.run_id}) root={self.root}")

    def _start_engine_threads(self) -> list[threading.Thread]:
        scfg = self.cfg.serving
        lead = self._lead_stop
        ths: list[threading.Thread] = []
        for i in range(max(1, scfg.engine_lanes)):
            t = threading.Thread(target=self._engine_loop,
                                 args=(f"lane{i}", lead),
                                 name=f"sl3d-serve-engine-{i}", daemon=True)
            t.start()
            ths.append(t)
        t = threading.Thread(target=self._assembler_loop, args=(lead,),
                             name="sl3d-serve-assembler", daemon=True)
        t.start()
        ths.append(t)
        return ths

    # ---- HA lifecycle ----------------------------------------------------

    def _ha_loop(self) -> None:
        """The member's election state machine. Followers try to acquire
        every poll tick (cheap: one flock'd read, a write only on a win);
        the leader renews every renew tick. A renew that comes back
        superseded — the manufactured zombie case: a stalled renew let
        the lease expire and a standby stole it — demotes; the fence on
        every ledger append is the backstop for writes already in
        flight."""
        scfg = self.cfg.serving
        renew_s = scfg.ha_renew_s or max(0.1, scfg.ha_lease_s / 3.0)
        poll_s = scfg.ha_poll_s or max(0.1, scfg.ha_lease_s / 5.0)
        while not self._stop.is_set():
            if self.role == "leader":
                ok = True
                try:
                    ok = self.election.renew()
                except faults.InjectedCrash as e:
                    self._crash("election.renew", e)
                    return
                except BaseException as e:
                    # transient lease-file trouble: keep leading, retry
                    # next tick — expiry + steal is the real arbiter
                    self.log(f"[serve] lease renew error: "
                             f"{type(e).__name__}: {e}")
                if not ok:
                    self._request_demote("lease lost (renew superseded)")
                self._stop.wait(renew_s)
            elif self.role == "follower" and self.phase == "ready":
                won = False
                try:
                    won = self.election.acquire()
                except faults.InjectedCrash as e:
                    self._crash("election.acquire", e)
                    return
                except BaseException as e:
                    self.log(f"[serve] lease acquire error: "
                             f"{type(e).__name__}: {e}")
                if won and not self._stop.is_set():
                    try:
                        self._promote()
                    except BaseException as e:
                        self.log(f"[serve] promotion FAILED: "
                                 f"{type(e).__name__}: {e}")
                        try:
                            self.election.release()
                        except Exception:
                            pass
                else:
                    self._stop.wait(poll_s)
            else:           # demoting (a worker thread is tearing down)
                self._stop.wait(poll_s)

    def _promote(self) -> None:
        """Takeover: PR-12's restart-resume run on the standby. Open a
        new ledger segment stamped with our epoch, fold what every
        previous epoch journaled, re-queue non-terminal scans (their
        credited views are already cache bytes — zero recompute), start
        the engine, and atomically republish serve.json so clients
        re-discover."""
        ep = self.election.epoch
        self.log(f"[serve] elected LEADER (epoch {ep}, run {self.run_id})")
        self._lead_stop = threading.Event()
        self.adm = self._make_adm()
        try:
            self.adm.ledger.event("takeover", owner=self.run_id)
            if self.cfg.serving.durable:
                self._resume()
        except BaseException:
            adm, self.adm = self.adm, None
            try:
                adm.close()
            except Exception:
                pass
            raise
        self._reign_threads = self._start_engine_threads()
        with self._demote_lock:
            self.role = "leader"
        self.registry.inc("sl3d_serve_takeovers_total")
        self._publish_serve_json()
        # the fleet is a LEADER organ: the new supervisor replays the
        # shared ledger's fleet events and respawns the inherited ranks
        # (bumped generations) under OUR epoch's fence
        self._start_fleet()

    def _request_demote(self, why: str) -> None:
        """Thread-safe, idempotent-per-reign demotion trigger — safe to
        call from the engine/assembler threads being torn down (the
        teardown runs on a helper thread and never joins its caller)."""
        with self._demote_lock:
            if not self.ha or self.role != "leader":
                return
            self.role = "demoting"
        threading.Thread(target=self._demote, args=(why,),
                         daemon=True).start()

    def _demote(self, why: str) -> None:
        self.log(f"[serve] DEPOSED (epoch {self.election.epoch}): {why} "
                 f"— demoting to follower")
        self._lead_stop.set()
        # fleet first: its workers hold leases in the adm this teardown
        # is about to close, and its supervisor journals through a fence
        # that already rejects us
        self._stop_fleet()
        with self._assembly_cv:
            self._assembly_cv.notify_all()
        # an in-flight assembly is left to FINISH, not aborted: its
        # terminal journal line is fenced (the new leader owns the
        # credit) and its artifacts are byte-identical to what the new
        # leader produces over the same cache, so letting it run is
        # harmless — while dl.current() is process-global and may
        # belong to the NEW leader's run when both members share a
        # process (tests, soak), so aborting it could kill the wrong
        # reign's work
        me = threading.current_thread()
        for t in self._reign_threads:
            if t is not me and t.is_alive():
                t.join()        # unbounded: engine/assembly always end
        self._reign_threads = []
        adm, self.adm = self.adm, None
        if adm is not None:
            try:
                adm.close()
            except Exception:
                pass
        with self._scan_lock:
            self._scans.clear()
            self._scanners.clear()
        with self._assembly_cv:
            self._assembly_q.clear()
        self.election.epoch = 0
        self.registry.inc("sl3d_serve_demotions_total")
        with self._demote_lock:
            self.role = "follower"

    def _resume(self) -> None:
        """Restart-resume: request records + ledger replay → the queue a
        previous incarnation left behind. Terminal scans come back as
        /status-able history; everything else re-queues. The warmed views
        of a resumed scan are already bytes in the content-addressed
        cache, so ``_plan`` sees them as cache hits — zero recompute of
        ledger-credited work, byte parity by the PR-8 construction."""
        swept = sweep_tmp(self.requests_dir)
        if swept:
            self.log(f"[serve] swept {len(swept)} torn request record(s)")
        rs = replay_serving(self.adm.ledger.path)
        records: list[dict] = []
        torn = 0
        for fn in sorted(os.listdir(self.requests_dir)):
            if not fn.endswith(".json"):
                continue
            path = os.path.join(self.requests_dir, fn)
            try:
                with open(path, encoding="utf-8") as f:
                    rec = json.load(f)
                if (rec.get("schema") != REQUEST_SCHEMA
                        or not rec.get("scan_id") or not rec.get("calib")):
                    raise ValueError("missing fields")
            except (ValueError, OSError) as e:
                # torn/garbled record: tolerated, never resumed — the
                # fsync-before-202 ordering means its client never got
                # an accept to hold us to
                torn += 1
                self.log(f"[serve] skipping unreadable request record "
                         f"{fn}: {e}")
                continue
            records.append(rec)
        records.sort(key=lambda r: (r.get("submitted_unix", 0.0),
                                    r["scan_id"]))
        now_mono, now_unix = time.monotonic(), time.time()
        n_term = n_res = 0
        for rec in records:
            sid = rec["scan_id"]
            job = ScanJob(sid, rec.get("tenant", "anon"), rec["target"],
                          rec["calib"],
                          rec.get("out_dir",
                                  os.path.join(self.scans_dir, sid)),
                          weight=rec.get("weight", 1.0),
                          budget_s=rec.get("budget_s", 0.0))
            # re-base the SLO clock to true wall time since the original
            # submit: a crash does not stop a client's deadline
            job.submitted_unix = rec.get("submitted_unix", now_unix)
            job.submitted_mono = now_mono - max(
                0.0, now_unix - job.submitted_unix)
            m = _AUTO_ID_RE.search(sid)
            if m:        # keep auto scan ids collision-free across runs
                with self._seq_lock:
                    self._seq = max(self._seq, int(m.group(1)))
            led = rs["scans"].get(sid)
            if led is not None and led["state"] in _TERMINAL:
                job.state = led["state"]
                job.error = led["error"]
                job.report = led["report"]
                job.finished_mono = job.submitted_mono + led["elapsed_s"]
                self.adm.restore_terminal(job)
                n_term += 1
            else:
                self.adm.restore(job)
                n_res += 1
        for tenant, fails in rs["tenant_fails"].items():
            self.adm.restore_breaker(tenant, fails)
        self.registry.inc("sl3d_serve_resumed_total", n_res)
        if records or torn:
            self.log(f"[serve] resume: {n_res} scan(s) re-queued, "
                     f"{n_term} terminal restored, {torn} torn record(s) "
                     f"skipped ({rs['segments']} ledger segment(s), "
                     f"{len(rs['completed'])} credited item(s))")

    def drain(self, budget_s: float | None = None) -> dict:
        """Graceful drain: stop admitting, let active scans finish within
        the budget, then abort-and-checkpoint whatever is still running
        (the PR-7 ``RunContext.abort`` lever — the in-flight assembly
        exits through its normal DeadlineExceeded path, failures.json
        included, and the scan parks as CHECKPOINTED for the next
        start). Returns {"finished": n, "checkpointed": [scan_ids]}."""
        scfg = self.cfg.serving
        budget = scfg.drain_budget_s if budget_s is None else budget_s
        self.phase = "draining"
        self._draining.set()
        if self.adm is None:      # HA follower: nothing in flight here
            return {"finished": 0, "checkpointed": []}
        try:
            self.adm.ledger.event("drain", budget_s=budget)
        except Exception:
            pass
        t_end = time.monotonic() + max(0.0, budget)

        def active():
            with self.adm.lock:
                return [j for j in self.adm.jobs.values()
                        if j.state in ("admitted", "warmed", "assembling")]

        while active() and time.monotonic() < t_end:
            time.sleep(0.05)
        left = active()
        checkpointed: list[str] = []
        if left:
            self._drain_breach.set()
            ctx = dl.current()
            if ctx is not None:
                ctx.abort("drain budget exceeded")
            # the aborted assembly settles through _assemble (which sees
            # _drain_breach and checkpoints); give it a bounded window
            t_stop = time.monotonic() + 15.0
            while (time.monotonic() < t_stop
                   and any(j.state == "assembling" for j in active())):
                time.sleep(0.05)
            # an aborted assembly checkpoints ITSELF (in _assemble);
            # everything else still admitted/warmed is parked here
            for j in left:
                if (j.state == "checkpointed"
                        or self.adm.checkpoint(
                            j.scan_id, reason=f"drain budget {budget:g}s "
                                              f"exceeded")):
                    checkpointed.append(j.scan_id)
        n_fin = sum(1 for j in self.adm.jobs.values()
                    if j.state in ("done", "degraded"))
        self.log(f"[serve] drained: {n_fin} finished, "
                 f"{len(checkpointed)} checkpointed")
        return {"finished": n_fin, "checkpointed": checkpointed}

    def stop(self, drain_budget_s: float | None = None) -> dict:
        """Drain then close — the SIGTERM path. A later ScanService over
        the same root resumes anything queued or checkpointed."""
        res = self.drain(drain_budget_s)
        self.close()
        return res

    def close(self) -> None:
        self._stop.set()
        self._stop_fleet()
        with self._assembly_cv:
            self._assembly_cv.notify_all()
        for t in self._threads + self._reign_threads:
            t.join(timeout=10.0)
        if self._ha_thread is not None:
            self._ha_thread.join(timeout=10.0)
        adm = self.adm
        if adm is not None:
            adm.close()
        if (self.election is not None and self.election.epoch > 0
                and self.phase != "crashed"):
            # graceful step-down: expire the lease NOW so the standby
            # takes over on its next poll. A crashed service must NOT
            # release — simulated process death hands over by expiry,
            # exactly like the real kill -9
            try:
                self.election.release()
            except Exception:
                pass
        self._release_guard()
        if self.phase != "crashed":
            self.phase = "stopped"

    def _crash(self, where: str, exc: BaseException) -> None:
        """An injected ``serve.crash`` fired: die like the real thing.
        Under ``serve()`` (exit_on_crash) the PROCESS exits 137 with the
        ledger fd left dangling mid-line — exactly a kill -9. In-process
        (tests/smokes) the service wedges into phase=crashed without
        journaling a finish or closing the ledger; a new ScanService
        over the same root is the restart."""
        self.log(f"[serve] CRASH at {where}: {exc}")
        self.phase = "crashed"
        self._stop.set()
        with self._assembly_cv:
            self._assembly_cv.notify_all()
        if self.exit_on_crash:
            os._exit(137)

    # ---- elastic fleet (ISSUE 18) ----------------------------------------

    def _start_fleet(self) -> None:
        """Spin up this reign's fleet supervisor (no-op unless
        ``serving.fleet_enabled``). Import is lazy — a fleet-less service
        never loads the coordinator stack."""
        if not self.cfg.serving.fleet_enabled or self.adm is None:
            return
        from structured_light_for_3d_model_replication_tpu.parallel import (
            fleet as fleet_mod,
        )
        sup = fleet_mod.FleetSupervisor(
            self.root, self.cfg, self.adm, self.store_root,
            steps=self._engine_steps(), log=self.log,
            registry=self.registry, lease=self.election,
            on_demote=self._request_demote, on_crash=self._crash,
            run_id=self.run_id)
        sup.start()
        self.fleet = sup

    def _stop_fleet(self) -> None:
        sup, self.fleet = self.fleet, None
        if sup is not None:
            try:
                sup.close()
            except Exception as e:
                self.log(f"[serve] fleet teardown error: "
                         f"{type(e).__name__}: {e}")

    def usage(self, tenant: str | None = None) -> dict:
        """Per-tenant usage metering: :func:`fold_usage` over the SAME
        cached epoch-fenced ledger fold the follower read model uses —
        the bill agrees with what the service credited, on leaders and
        followers alike."""
        u = fold_usage(self._follower_view())
        if tenant is not None:
            u = {tenant: u[tenant]} if tenant in u else {}
        return {"schema": "sl3d-usage-v1", "tenants": u}

    # ---- submit ----------------------------------------------------------

    def submit(self, payload: dict) -> tuple[bool, dict]:
        """One scan submission: validate, quota-check, persist, queue.
        Returns (accepted, body) where body is the /submit response JSON;
        rejections carry a machine-readable ``reason`` (and
        ``retry_after_s`` when the client should come back). A re-submit
        of an existing client scan_id with the SAME (tenant, target,
        calib) is idempotent — it returns the existing request — because
        after a gateway crash the client cannot know whether its first
        202 committed."""
        scfg = self.cfg.serving
        if self.phase != "ready":
            self.registry.inc("sl3d_serve_rejected_total",
                              tenant=_safe_id(payload.get("tenant"),
                                              "anon"))
            return False, {"error": f"service is {self.phase}",
                           "reason": ("draining"
                                      if self.phase == "draining"
                                      else self.phase),
                           "retry_after_s": max(1.0, scfg.drain_budget_s)}
        if self._auth is not None:
            # the front door (ISSUE 18): identity before anything else —
            # an unauthenticated caller learns nothing, not even where
            # the leader is. Reasons map to 401/403; a valid key then
            # passes the per-tenant sliding-window rate limit (429 in
            # the same quota vocabulary as tenant-queue-quota)
            t0 = _safe_id(payload.get("tenant"), "anon")
            err = self._auth.check(t0, str(payload.get("api_key") or ""))
            if err is not None:
                self.registry.inc("sl3d_serve_auth_denied_total",
                                  tenant=t0)
                return False, dict(err, tenant=t0)
            limits = self._auth.tenant_limits(t0)
            err = (self._rlim.allow(t0, *limits) if limits
                   else self._rlim.allow(t0))
            if err is not None:
                self.registry.inc("sl3d_serve_rate_limited_total",
                                  tenant=t0)
                return False, dict(err, tenant=t0)
        adm = self.adm
        if self.ha and (self.role != "leader" or adm is None):
            # HA follower / mid-transition member: machine-readable
            # redirect to the current leader (the PR-12 envelope)
            self.registry.inc("sl3d_serve_redirected_total")
            return False, self._redirect_body()
        tenant = _safe_id(payload.get("tenant"), "anon")
        target = str(payload.get("target") or "")
        calib = str(payload.get("calib") or "")
        if not target or not os.path.isdir(target):
            return False, {"error": f"target is not a directory: "
                                    f"{target!r}", "reason": "bad-request"}
        if not calib or not os.path.isfile(calib):
            return False, {"error": f"calib is not a file: {calib!r}",
                           "reason": "bad-request"}
        client_id = _safe_id(payload.get("scan_id"), "")
        if client_id:
            scan_id = f"{tenant}-{client_id}"
        else:
            with self._seq_lock:
                self._seq += 1
                scan_id = f"{tenant}-s{self._seq:04d}"
        out_dir = os.path.join(self.scans_dir, scan_id)
        budget = payload.get("budget_s", scfg.default_budget_s)
        job = ScanJob(scan_id, tenant, os.path.abspath(target),
                      os.path.abspath(calib), out_dir,
                      weight=float(payload.get("weight",
                                               scfg.default_weight)),
                      budget_s=float(budget or 0.0))
        persist = self._write_record if scfg.durable else None
        try:
            with adm.lock:
                prior = adm.jobs.get(scan_id)
                if prior is not None:
                    if (prior.tenant, prior.target, prior.calib) == \
                            (job.tenant, job.target, job.calib):
                        return True, {"scan_id": scan_id, "tenant": tenant,
                                      "state": prior.state,
                                      "duplicate": True}
                    return False, {"error": f"scan_id {scan_id!r} already "
                                            "exists with different "
                                            "inputs",
                                   "reason": "scan-id-conflict"}
                ok, info = adm.submit(job, persist=persist)
        except faults.InjectedCrash:
            raise
        except election.FencedWrite as e:
            # deposed between the role check and the journal append: the
            # fence rejected the write before any line hit the ledger
            self.log(f"[serve] submit fenced: {e}")
            self._request_demote(f"submit: {e}")
            return False, self._redirect_body()
        except BaseException as e:
            # durable-record or journal write failed: nothing admitted,
            # the client can safely retry the same scan_id
            self.registry.inc("sl3d_serve_rejected_total", tenant=tenant)
            return False, {"error": f"submit not durable: {e}",
                           "reason": "transient", "retry_after_s": 1.0}
        if not ok:
            self.registry.inc("sl3d_serve_rejected_total", tenant=tenant)
            body = {"error": info.get("error", "rejected"),
                    "reason": info.get("reason", "bad-request"),
                    "tenant": tenant}
            if "retry_after_s" in info:
                body["retry_after_s"] = info["retry_after_s"]
            return False, body
        self.registry.inc("sl3d_serve_submitted_total", tenant=tenant)
        return True, {"scan_id": scan_id, "tenant": tenant,
                      "state": "queued"}

    def _write_record(self, job) -> None:
        """The durability point: the request record is bytes-on-disk
        (fsync'd) BEFORE the scan is journaled, queued, or 202'd — so an
        accepted request can always be replayed, and anything the crash
        interrupted earlier left no accept for the client to hold."""
        rec = {"schema": REQUEST_SCHEMA, "scan_id": job.scan_id,
               "tenant": job.tenant, "target": job.target,
               "calib": job.calib, "out_dir": job.out_dir,
               "weight": job.weight, "budget_s": job.budget_s,
               "submitted_unix": job.submitted_unix,
               "epoch": self.epoch}   # writer's fencing token (HA)
        path = os.path.join(self.requests_dir, f"{job.scan_id}.json")
        with atomic_write(path) as tmp:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(rec, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())

    def _follower_view(self) -> dict:
        """The follower read model: a fold of the SHARED ledger, cached
        by (size, mtime) so /status polls don't re-fold an unchanged
        file. Epoch fencing inside replay_serving means a follower never
        reports state a deposed writer raced in."""
        try:
            st = os.stat(self._ledger_path)
            key = (st.st_size, st.st_mtime_ns)
        except OSError:
            key = None
        if key is not None and key == self._view_key \
                and self._view_rs is not None:
            return self._view_rs
        rs = replay_serving(self._ledger_path)
        self._view_key, self._view_rs = key, rs
        return rs

    def status(self, scan_id: str) -> dict | None:
        adm = self.adm
        if adm is None:       # HA follower: answer from the shared ledger
            r = self._follower_view()["scans"].get(scan_id)
            if r is None:
                return None
            return {"scan_id": scan_id, "tenant": r["tenant"],
                    "state": r["state"], "error": r["error"],
                    "report": r["report"], "elapsed_s": r["elapsed_s"],
                    "items": {}, "via": "follower-replay"}
        with adm.lock:
            job = adm.jobs.get(scan_id)
            if job is None:
                return None
            d = job.as_dict()
            d["items"] = adm.scan_item_states(scan_id)
            return d

    def result_path(self, scan_id: str, artifact: str) -> tuple[str, dict]:
        """Path of a finished request's artifact, or ("", error-body).
        Works on followers too: artifacts live on the SHARED root, and
        the ledger fold says which requests are terminal."""
        adm = self.adm
        if adm is None:
            r = self._follower_view()["scans"].get(scan_id)
            if r is None:
                return "", {"error": f"unknown scan_id {scan_id!r}"}
            state, out_dir = r["state"], r["out_dir"]
        else:
            with adm.lock:
                job = adm.jobs.get(scan_id)
            if job is None:
                return "", {"error": f"unknown scan_id {scan_id!r}"}
            state, out_dir = job.state, job.out_dir
        if state not in ("done", "degraded"):
            return "", {"error": f"scan {scan_id!r} is {state}",
                        "state": state}
        name = {"ply": "merged.ply", "stl": "model.stl"}.get(artifact)
        if name is None:
            return "", {"error": f"unknown artifact {artifact!r} "
                                 "(want ply|stl)"}
        path = os.path.join(out_dir, name)
        if not os.path.isfile(path):
            return "", {"error": f"{name} missing for {scan_id!r}"}
        return path, {}

    def snapshot(self) -> dict:
        adm = self.adm
        if adm is None:
            states = [r["state"]
                      for r in self._follower_view()["scans"].values()]
            snap = {"active": sum(1 for s in states
                                  if s in ("admitted", "warmed",
                                           "assembling")),
                    "queued": states.count("queued"),
                    "scans": len(states)}
        else:
            snap = adm.snapshot()
        snap["run_id"] = self.run_id
        snap["role"] = self.role
        snap["epoch"] = self.epoch
        return snap

    # ---- engine: plan ----------------------------------------------------

    def _plan(self, job) -> None:
        """Plan one admitted scan: derive sources + content-addressed view
        keys through the SAME ``_view_plan`` the assembly pass uses, probe
        the scanner key, register the cache-miss views as grantable items.
        A warm view (this tenant or ANY other — the keys carry no
        identity) completes at plan time: cross-tenant dedup is free."""
        st = self._stages
        job_log = self._job_log(job)
        cache = TenantCache(self.store_root, job.tenant,
                            ns_root=self.ns_root, enabled=True,
                            verify=self.cfg.pipeline.verify_cache,
                            log=lambda *_: None)
        calib, sources, _view_cfg, view_keys = st._view_plan(
            job.calib, job.target, self.cfg, self._engine_steps(), cache,
            job_log)
        scanner_key = self._scanner_key(job.calib, sources)
        specs, warm = [], 0
        for i, (src, key) in enumerate(zip(sources, view_keys)):
            if cache.get("view", key) is not None:
                warm += 1          # get() also marked this tenant's ref
                continue
            specs.append({"index": i, "src": src, "key": key,
                          "scan": job.scan_id})
        ctx = _ScanCtx(job, self._engine_steps(), calib, sources,
                       view_keys, cache, scanner_key)
        with self._scan_lock:
            self._scans[job.scan_id] = ctx
        self.adm.add_items(job.scan_id, specs)
        self.registry.inc("sl3d_serve_views_planned_total",
                          len(specs) + warm, tenant=job.tenant)
        self.registry.inc("sl3d_serve_views_dedup_total", warm,
                          tenant=job.tenant)
        job_log(f"[serve] {job.scan_id}: planned {len(specs)} view(s) to "
                f"warm, {warm} already cached")

    def _engine_steps(self) -> tuple:
        s = tuple(x.strip() for x in
                  self.cfg.serving.clean_steps.split(",") if x.strip())
        return s or tuple(self._stages._CLEAN_STEPS)

    def _scanner_key(self, calib_path: str, sources) -> tuple | None:
        """Scans sharing (calib file, camera geometry) share one scanner —
        the identity a cross-scan batched launch groups on. None on the
        numpy/bitexact paths (no device scanner; per-view lane)."""
        cfg = self.cfg
        if cfg.parallel.backend == "numpy" or cfg.triangulate.bitexact:
            return None
        from structured_light_for_3d_model_replication_tpu.io import (
            images as imio,
        )

        first = imio.list_frame_files(sources[0])
        hdr = imio.probe_packed(first[0])
        if hdr is not None:
            cam_size = (int(hdr["width"]), int(hdr["height"]))
        else:
            probe = imio.load_gray(first[0])
            cam_size = (probe.shape[1], probe.shape[0])
        return (os.path.abspath(calib_path), cam_size)

    def _scanner_for(self, ctx: _ScanCtx):
        if ctx.scanner_key is None:
            return None
        with self._scan_lock:
            sc = self._scanners.get(ctx.scanner_key)
            if sc is None:
                sc = self._stages._build_scanner(ctx.sources, ctx.calib,
                                                 self.cfg)
                self._scanners[ctx.scanner_key] = sc
            return sc

    # ---- engine: item programs ------------------------------------------

    def _engine_loop(self, lane: str, lead: threading.Event) -> None:
        poll = max(0.01, self.cfg.serving.poll_s)
        batch_n = max(1, self.cfg.parallel.compute_batch)
        while not self._stop.is_set() and not lead.is_set():
            try:
                self.adm.sweep_expired()
                for job in self.adm.shed_expired():
                    self._finish_metrics(job, "shed")
                    self.log(f"[serve] {job.scan_id}: SHED ({job.error})")
                if not self._draining.is_set():
                    for job in self.adm.admit_next():
                        try:
                            self._plan(job)
                        except election.FencedWrite:
                            raise
                        except Exception as e:
                            self.adm.finish(job.scan_id, "failed",
                                            error=f"plan: {e}")
                            self._finish_metrics(job, "failed")
                            self.log(f"[serve] {job.scan_id}: plan FAILED "
                                     f"({type(e).__name__}: {e})")
                self._queue_settled()
                grants = self.adm.next_views(lane, batch_n)
                if not grants:
                    self._stop.wait(poll)
                    continue
                self._run_grants(lane, grants)
            except faults.InjectedCrash as e:
                # an injected crash is the one thing the engine must NOT
                # survive: it simulates process death (restart-resume is
                # the recovery path, not this loop)
                self._crash(f"engine {lane}", e)
                return
            except election.FencedWrite as e:
                # a journal append was rejected: this gateway was deposed
                # while the lane worked. Nothing hit the ledger; the new
                # leader's resume owns every affected scan. Self-demote.
                self.log(f"[serve] engine {lane}: write fenced ({e})")
                self._request_demote(f"engine {lane}: {e}")
                return
            except BaseException as e:
                # the engine must survive anything else an item throws at
                # it (the service IS the process that must not die);
                # affected leases age into steals
                self.log(f"[serve] engine {lane}: {type(e).__name__}: {e}")
                self._stop.wait(poll)

    def _run_grants(self, lane: str, grants) -> None:
        """One grant set → loads → one (or more) launches. Grouping is by
        (scanner, frame shape): views from different scans land in the
        SAME group whenever their geometry matches — this is where
        cross-tenant batching actually happens."""
        st = self._stages
        loaded: dict[tuple | None, list] = {}
        for iid, gen, spec in grants:
            # crash boundary: the grant is journaled but no work happened
            # — restart re-plans the view as a cache miss
            faults.fire("serve.crash", item=f"grant:{iid}")
            with self._scan_lock:
                ctx = self._scans.get(spec["scan"])
            if ctx is None:            # scan finished/failed underneath us
                self.adm.failed(iid, lane, gen, "scan context gone")
                continue
            try:
                frames, texture = st._retry_stage(
                    "load",
                    lambda s=spec["src"]: st._load_fired(s, self.cfg),
                    self._policy)
            except (faults.InjectedCrash, election.FencedWrite):
                raise
            except BaseException as e:
                self.adm.failed(iid, lane, gen, f"load: {e}")
                self.registry.inc("sl3d_serve_view_failures_total",
                                  tenant=ctx.job.tenant)
                continue
            gkey = (None if ctx.scanner_key is None
                    else ctx.scanner_key + (frames.shape,))
            loaded.setdefault(gkey, []).append(
                (iid, gen, spec, ctx, frames, texture))
            self.adm.beat(lane)
        for gkey, items in loaded.items():
            if gkey is None or len(items) == 1:
                for it in items:
                    self._view_single(lane, it)
            else:
                self._view_batched(lane, items)

    def _finish_item(self, lane, iid, gen, spec, ctx, pts, cols) -> None:
        """Clean + cache one computed view (the PR-8 worker tail) and
        settle its lease."""
        st = self._stages
        pts, cols, _ = st._clean_arrays(pts, cols, self.cfg, ctx.steps)
        ctx.cache.put("view", spec["key"], points=pts, colors=cols)
        # crash boundary: the bytes are cached but the complete event is
        # NOT journaled — restart still re-plans this view WARM (the
        # cache, not the ledger, is the source of truth for bytes)
        faults.fire("serve.crash", item=f"complete:{iid}")
        self.adm.complete(iid, lane, gen)
        self.registry.inc("sl3d_serve_views_warmed_total",
                          tenant=ctx.job.tenant)

    def _view_single(self, lane: str, item) -> None:
        """The per-view engine lane: exactly the PR-8 worker's
        ``_do_view`` program. ``compute.view`` fires inside
        ``_compute_fired`` — a seeded fault fails the item here, the item
        is NOT cached, and the request's assembly pass recomputes it
        through the full retry/quarantine lane (failure policy lives in
        one place)."""
        st = self._stages
        iid, gen, spec, ctx, frames, texture = item
        from structured_light_for_3d_model_replication_tpu.ops import (
            triangulate as tri,
        )

        try:
            scanner = self._scanner_for(ctx)
            pts, cols = st._retry_stage(
                "compute",
                lambda: tri.compact_cloud(st._compute_fired(
                    frames, texture, ctx.calib, self.cfg, scanner,
                    spec["src"])),
                self._policy)
            self._finish_item(lane, iid, gen, spec, ctx, pts, cols)
        except (faults.InjectedCrash, election.FencedWrite):
            raise
        except BaseException as e:
            self.adm.failed(iid, lane, gen, f"compute: {e}")
            self.registry.inc("sl3d_serve_view_failures_total",
                              tenant=ctx.job.tenant)

    def _view_batched(self, lane: str, items) -> None:
        """One bucket-padded ``forward_views_batched`` launch over views
        from possibly MANY scans — ``_reconstruct_batched``'s dispatch
        math with the grant set as the batch. The ``compute.view`` site
        fires per item at assembly (chaos semantics survive batching);
        any batch-level failure degrades the whole group to the per-view
        lane, where a poisoned view fails ALONE and its groupmates (other
        tenants included) complete normally."""
        st = self._stages
        from structured_light_for_3d_model_replication_tpu.ops import (
            triangulate as tri,
        )

        poisoned = None
        for iid, gen, spec, ctx, _f, _t in items:
            try:
                faults.fire("compute.view", item=spec["src"])
            except faults.InjectedCrash:
                raise
            except BaseException as e:
                poisoned = e
                break
        if poisoned is None:
            try:
                import jax

                scanner = self._scanner_for(items[0][3])
                v = len(items)
                batch_n = max(1, self.cfg.parallel.compute_batch)
                bucket = st._view_bucket(v, batch_n)
                fv = np.stack([f for _, _, _, _, f, _ in items])
                if bucket > v:
                    fv = np.concatenate(
                        [fv, np.repeat(fv[-1:], bucket - v, axis=0)])
                fv_d = jax.device_put(fv)
                cloud = scanner.forward_views_batched(fv_d, mesh=None,
                                                      **self._fwd_kw)
                pts_v, cols_v, val_v = jax.device_get(
                    (cloud.points[:v], cloud.colors[:v], cloud.valid[:v]))
                tenants = {it[3].job.tenant for it in items}
                scans = {it[2]["scan"] for it in items}
                self.registry.inc("sl3d_serve_launches_total")
                self.registry.inc("sl3d_serve_launch_views_total", v)
                if len(scans) > 1:
                    self.registry.inc("sl3d_serve_cross_scan_launches_total")
                if len(tenants) > 1:
                    self.registry.inc(
                        "sl3d_serve_cross_tenant_launches_total")
                for j, (iid, gen, spec, ctx, _f, _t) in enumerate(items):
                    try:
                        pts, cols = tri.compact_cloud(
                            tri.CloudResult(pts_v[j], cols_v[j], val_v[j]))
                        self._finish_item(lane, iid, gen, spec, ctx, pts,
                                          cols)
                    except (faults.InjectedCrash, election.FencedWrite):
                        raise
                    except BaseException as e:
                        self.adm.failed(iid, lane, gen, f"drain: {e}")
                        self.registry.inc("sl3d_serve_view_failures_total",
                                          tenant=ctx.job.tenant)
                return
            except (faults.InjectedCrash, election.FencedWrite):
                raise
            except BaseException as e:
                poisoned = e
        self.log(f"[serve] batch of {len(items)} view(s) degraded to "
                 f"per-view compute ({type(poisoned).__name__}: "
                 f"{poisoned})")
        for it in items:
            self._view_single(lane, it)

    # ---- assembly --------------------------------------------------------

    def _queue_settled(self) -> None:
        """Flip admitted scans whose items all settled to WARMED and hand
        them to the assembler (a scan with zero cache-miss items settles
        immediately — the fully-deduped fast path)."""
        with self.adm.lock:
            ready = [sid for sid, j in self.adm.jobs.items()
                     if j.state == "admitted"
                     and self.adm.scan_settled(sid)]
            for sid in ready:
                self.adm.jobs[sid].state = "warmed"
                self.adm.ledger.event("warmed", scan=sid)
        if ready:
            with self._assembly_cv:
                self._assembly_q.extend(ready)
                self._assembly_cv.notify_all()

    def _assembler_loop(self, lead: threading.Event) -> None:
        """ONE assembly at a time: requests share the engine for warming
        but serialize through the proven single-process pipeline — device
        contention stays simple and the byte-parity argument stays
        exactly PR-8's."""
        while True:
            with self._assembly_cv:
                while (not self._assembly_q and not self._stop.is_set()
                       and not lead.is_set()):
                    self._assembly_cv.wait(timeout=0.5)
                if lead.is_set():
                    return      # deposed: the new leader owns the queue
                if self._stop.is_set() and not self._assembly_q:
                    return
                sid = self._assembly_q.pop(0)
            adm = self.adm
            if adm is None:     # deposed underneath us
                return
            with adm.lock:
                job = adm.jobs.get(sid)
            if job is None or job.state != "warmed":
                continue        # checkpointed/finished underneath us
            try:
                self._assemble(job)
            except faults.InjectedCrash as e:
                # simulated process death mid-assembly: no finish event
                # journaled, scan left "assembling" — restart re-queues
                # it and re-assembles over the warm cache
                self._crash(f"assembly {sid}", e)
                return
            except election.FencedWrite as e:
                # the terminal journal line was rejected: deposed mid-
                # assembly. The artifacts are fine (atomic writes, same
                # bytes the new leader will produce over the same cache)
                # but the CREDIT belongs to the new epoch — self-demote
                self.log(f"[serve] assembly {sid}: write fenced ({e})")
                self._request_demote(f"assembly {sid}: {e}")
                return

    def _job_log(self, job):
        def _log(msg):
            self.log(f"[{job.scan_id}] {msg}")
        return _log

    def _assemble(self, job) -> None:
        """The request's answer: ``run_pipeline`` over the warmed shared
        cache, in this tenant's namespace, under the request's REMAINING
        SLO budget. Terminal state maps: clean run → done; quarantined
        views above the floor → degraded (its own failures.json); budget
        breach → aborted (PR-7 manifest); anything else → failed. The
        service outlives every one of these."""
        st = self._stages
        adm = self.adm      # capture: demotion swaps self.adm to None
        with self._scan_lock:
            ctx = self._scans.get(job.scan_id)
        with adm.lock:
            job.state = "assembling"
        # crash boundary: warmed + journaled, assembly never started —
        # restart finds every view cached and re-assembles for free
        faults.fire("serve.crash", item=f"assembly:{job.scan_id}")
        rcfg = copy.deepcopy(self.cfg)
        rcfg.coordinator.workers = 0
        rem = job.budget_remaining()
        if rem is not None:
            # the PR-7 run budget, re-based to what the queue+warm phases
            # left; an already-blown budget aborts at the first stage
            # boundary and still leaves a manifest
            rcfg.pipeline.run_budget_s = max(0.05, rem)
        cache = (ctx.cache if ctx is not None else TenantCache(
            self.store_root, job.tenant, ns_root=self.ns_root,
            enabled=True, verify=rcfg.pipeline.verify_cache,
            log=lambda *_: None))
        steps = ctx.steps if ctx is not None else self._engine_steps()
        t0 = time.monotonic()
        state, error, report_d = "failed", "", {}
        try:
            report = st.run_pipeline(job.calib, job.target, job.out_dir,
                                     cfg=rcfg, steps=steps,
                                     log=self._job_log(job), cache=cache)
            state = "degraded" if report.degraded else "done"
            report_d = {"run_id": report.run_id,
                        "views_computed": report.views_computed,
                        "views_cached": report.views_cached,
                        "merged_points": report.merged_points,
                        "failed_views": len(report.failed),
                        "merged_ply": report.merged_ply,
                        "stl_path": report.stl_path,
                        "assembly_s": round(report.elapsed_s, 3)}
        except dl.DeadlineExceeded as e:
            if self._drain_breach.is_set():
                # not an SLO verdict — the SERVICE ran out of drain
                # budget. Park the scan (failures.json already written by
                # the abort path); the next start() re-queues it
                state, error = "checkpointed", f"drain checkpoint: {e}"
            else:
                state, error = "aborted", f"SLO budget exceeded: {e}"
        except faults.InjectedCrash:
            raise
        except BaseException as e:
            state, error = "failed", f"{type(e).__name__}: {e}"
        finally:
            with self._scan_lock:
                self._scans.pop(job.scan_id, None)
        if state == "checkpointed":
            adm.checkpoint(job.scan_id, reason=error)
            self.registry.inc("sl3d_serve_checkpointed_total",
                              tenant=job.tenant)
        else:
            adm.finish(job.scan_id, state, error=error,
                       report=report_d)
            self._finish_metrics(job, state,
                                 assembly_s=time.monotonic() - t0)
        self.log(f"[serve] {job.scan_id}: {state.upper()} "
                 f"({job.elapsed_s():.2f}s total)" +
                 (f" — {error}" if error else ""))

    def _finish_metrics(self, job, state: str, assembly_s: float = 0.0):
        self.registry.inc("sl3d_serve_requests_total", tenant=job.tenant,
                          state=state)
        self.registry.observe("sl3d_serve_request_seconds",
                              job.elapsed_s(), tenant=job.tenant)
        if assembly_s:
            self.registry.observe("sl3d_serve_assembly_seconds",
                                  assembly_s, tenant=job.tenant)

    # ---- metrics surface -------------------------------------------------

    def metrics_text(self) -> str:
        snap = self.snapshot()
        self.registry.set_gauge("sl3d_serve_scans_active",
                                snap.get("active", 0))
        self.registry.set_gauge("sl3d_serve_scans_queued",
                                snap.get("queued", 0))
        self.registry.set_gauge("sl3d_serve_ready",
                                1.0 if self.phase == "ready" else 0.0)
        self.registry.set_gauge(
            "sl3d_serve_leader",
            1.0 if self.role in ("solo", "leader") else 0.0)
        self.registry.set_gauge("sl3d_serve_epoch", float(self.epoch))
        return tel.prometheus_text(self.registry.as_dict())


# ---- HTTP gateway --------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Thin JSON shim over ScanService; one instance per request (stdlib
    threading server), all state on ``self.server.service``."""

    protocol_version = "HTTP/1.1"

    @property
    def service(self) -> ScanService:
        return self.server.service      # type: ignore[attr-defined]

    def log_message(self, fmt, *args):   # route through the service log
        self.service.log("[serve.http] " + fmt % args)

    def _json(self, code: int, body: dict,
              retry_after: float | None = None) -> None:
        data = (json.dumps(body) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after is not None:
            self.send_header("Retry-After",
                             str(max(1, int(round(retry_after)))))
        self.end_headers()
        self.wfile.write(data)

    def _bytes(self, code: int, data: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path != "/submit":
            return self._json(404, {"error": f"no route {parsed.path!r}"})
        try:
            n = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad JSON body: {e}",
                                    "reason": "bad-request"})
        if isinstance(payload, dict) and not payload.get("api_key"):
            # header form of the credential; the body field wins so a
            # scripted client can carry both through one JSON blob
            key = self.headers.get("X-API-Key")
            if key:
                payload["api_key"] = key
        try:
            faults.fire("http.submit",
                        item=str(payload.get("tenant") or ""))
        except faults.InjectedCrash as e:
            self.service._crash("http.submit", e)
            raise
        except BaseException as e:
            return self._json(503, {"error": f"injected: {e}",
                                    "reason": "transient",
                                    "retry_after_s": 1.0}, retry_after=1.0)
        ok, body = self.service.submit(payload)
        if ok:
            return self._json(200, body)
        # the machine-readable ``reason`` picks the status; retryable
        # rejections (429 backpressure, 503 service-side) carry
        # Retry-After so clients back off instead of hammering
        code = _REASON_HTTP.get(body.get("reason", "bad-request"), 400)
        ra = body.get("retry_after_s", 1.0) if code in (429, 503) else None
        return self._json(code, body, retry_after=ra)

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        if path == "/healthz":
            snap = self.service.snapshot()
            phase = self.service.phase
            return self._json(200, {"ok": phase == "ready",
                                    "phase": phase,
                                    "role": snap["role"],
                                    "epoch": snap["epoch"],
                                    "run_id": snap["run_id"],
                                    "active": snap["active"],
                                    "queued": snap["queued"]})
        if path == "/metrics":
            return self._bytes(200, self.service.metrics_text().encode(),
                               "text/plain; version=0.0.4")
        if path == "/usage":
            q = urllib.parse.parse_qs(parsed.query)
            tenant = (q.get("tenant") or [None])[0]
            return self._json(200, self.service.usage(tenant))
        if path.startswith("/status/"):
            d = self.service.status(path[len("/status/"):])
            if d is None:
                return self._json(404, {"error": "unknown scan_id"})
            return self._json(200, d)
        if path.startswith("/result/"):
            scan_id = path[len("/result/"):]
            q = urllib.parse.parse_qs(parsed.query)
            artifact = (q.get("artifact") or ["ply"])[0]
            fpath, err = self.service.result_path(scan_id, artifact)
            if not fpath:
                code = 409 if err.get("state") else 404
                return self._json(code, err)
            with open(fpath, "rb") as f:
                return self._bytes(200, f.read(),
                                   "application/octet-stream")
        return self._json(404, {"error": f"no route {path!r}"})


def start_gateway(root: str, cfg: Config | None = None, log=print,
                  ready_file: str | None = None):
    """Bind + start the service WITHOUT blocking: returns (httpd, svc).
    The caller runs ``httpd.serve_forever`` (``serve`` does, on the main
    thread; tests/bench push it to a daemon thread) and tears down with
    ``httpd.shutdown(); httpd.server_close(); svc.close()``. Writes
    ``<root>/serve.json`` (and optional ``ready_file``) with the bound
    address — the discovery handshake for CI and the load generator."""
    cfg = cfg or Config()
    svc = ScanService(root, cfg=cfg, log=log)
    httpd = ThreadingHTTPServer((cfg.serving.host, cfg.serving.port),
                                _Handler)
    httpd.service = svc                  # type: ignore[attr-defined]
    httpd.daemon_threads = True
    host, port = httpd.server_address[0], httpd.server_address[1]
    # the bound address must be known BEFORE start(): an HA member that
    # wins the election advertises it in the lease + serve.json
    svc.advertise(host, port, argv=sys.argv)
    svc.start()
    if not svc.ha:
        # solo: publish the discovery handshake now (epoch 0). HA:
        # serve.json is the LEADER's to write — _promote rewrites it
        # atomically with the new epoch on every takeover
        svc._publish_serve_json()
    info = {"host": host, "port": port, "pid": os.getpid(),
            "run_id": svc.run_id, "root": svc.root, "role": svc.role,
            "epoch": svc.epoch,
            "argv": list(sys.argv)}   # loadgen --restart relaunch recipe
    if ready_file:
        with open(ready_file, "w") as f:
            json.dump(info, f)
    log(f"[serve] listening on http://{host}:{port} role={svc.role} "
        f"(endpoints: /submit /status/<id> /result/<id> /metrics "
        f"/healthz /usage)")
    return httpd, svc


def serve(root: str, cfg: Config | None = None, log=print,
          ready_file: str | None = None) -> int:
    """Run the gateway until interrupted (the ``sl3d serve`` entry).

    SIGTERM and SIGINT both DRAIN: new submits 503 with Retry-After,
    active scans get ``serving.drain_budget_s`` to finish or checkpoint,
    then the process exits cleanly — a container stop is a resume point,
    not a data loss. An injected ``serve.crash`` under this entry exits
    the process 137 (the kill -9 twin the chaos smokes restart from)."""
    cfg = cfg or Config()
    faults.configure_from(cfg.faults)
    httpd, svc = start_gateway(root, cfg=cfg, log=log,
                               ready_file=ready_file)
    svc.exit_on_crash = True

    def _on_signal(signum, frame):
        log(f"[serve] signal {signum}; draining")
        # serve_forever must NOT be shut down from inside its own
        # signal frame (deadlock); a helper thread breaks the loop
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    prev = {}
    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[s] = signal.signal(s, _on_signal)
        except ValueError:
            pass        # not the main thread (tests drive serve() there)
    try:
        httpd.serve_forever(poll_interval=0.2)
    except KeyboardInterrupt:
        log("[serve] interrupted; draining")
    finally:
        for s, h in prev.items():
            try:
                signal.signal(s, h)
            except ValueError:
                pass
        httpd.server_close()
        svc.stop()
        log("[serve] stopped cleanly; restart resumes from "
            f"{svc.root}")
    return 0
