"""``sl3d serve`` — the persistent multi-tenant scan service.

The paper's L2 layer is a one-shot broker: a phone uploads frames, a CLI
run turns them into a model. This module is its serving-shaped
replacement — ONE long-lived process, many tenants, one shared device
mesh — built by composing layers this repo already proved one at a time:

  gateway   stdlib ``ThreadingHTTPServer`` speaking JSON, the same
            no-deps transport discipline as the PR-8 coordinator's
            newline-JSON wire protocol. ``/submit`` · ``/status/<id>`` ·
            ``/result/<id>`` · ``/metrics`` · ``/healthz``.
  admission ``parallel/admission.py``: per-tenant quotas (a submit over
            quota is a 429 at the door) + weighted-fair scheduling over
            the multi-scan generalization of the PR-8 lease/ledger —
            every grant/steal/complete is journaled fsync'd.
  engine    in-process lanes that warm the content-addressed stage
            cache, drawing view grants interleaved across tenants so
            views from DIFFERENT scans fill the same bucket-padded
            ``forward_views_batched`` launch (cross-tenant batching —
            the MRI-serving shape: keep the dense solve saturated with
            whoever's work is ready). Numpy-backend deployments take
            the per-view lane; either way the item program is exactly
            the PR-8 worker's (load → compute → compact → clean → put).
  assembly  one request at a time, the proven single-process
            ``run_pipeline`` over the warmed cache with a per-tenant
            cache namespace (``TenantCache``) — so every response is
            **byte-identical to a solo ``sl3d pipeline`` run** of the
            same input, by the PR-8 construction: engine lanes only
            warm; assembly recomputes anything missing through the full
            retry/quarantine lane.

Failure domains are per REQUEST: a poisoned view quarantines inside its
own scan's assembly (PR-3 semantics — that request completes DEGRADED
with its own ``failures.json``); a per-request SLO (``budget_s``,
clock starting at submit) aborts only that request via the PR-7 run
budget; the service keeps running through all of it.

Cache sharing is content-addressed and tenant-scoped at once: identical
frame bytes + config from two tenants hash to ONE cached entry (dedup),
while ``TenantCache`` ref-marker namespaces keep eviction and listing
per-tenant — evicting tenant A never deletes a payload tenant B still
references, and outputs never alias because every request owns its
``out_dir``.
"""
from __future__ import annotations

import copy
import json
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.parallel.admission import (
    AdmissionController,
    ScanJob,
)
from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
    TenantCache,
)
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import (
    telemetry as tel,
)

__all__ = ["ScanService", "serve", "start_gateway"]

_ID_RE = re.compile(r"[^A-Za-z0-9._-]+")


def _safe_id(s: str, fallback: str) -> str:
    s = _ID_RE.sub("-", str(s or "")).strip("-.")[:64]
    return s or fallback


class _ScanCtx:
    """Everything the engine holds for one admitted scan: the shared plan
    (``stages._view_plan`` — the SAME key derivation the assembly pass
    will use), this tenant's cache namespace, and the scanner key that
    lets different scans share one batched launch."""

    __slots__ = ("job", "steps", "calib", "sources", "view_keys", "cache",
                 "scanner_key")

    def __init__(self, job, steps, calib, sources, view_keys, cache,
                 scanner_key):
        self.job = job
        self.steps = steps
        self.calib = calib
        self.sources = sources
        self.view_keys = view_keys
        self.cache = cache
        self.scanner_key = scanner_key


class ScanService:
    """The serving core: admission + engine + assembly over one shared
    stage-cache store. HTTP lives in ``_Handler``/``serve`` so tests can
    drive this object directly."""

    def __init__(self, root: str, cfg: Config | None = None, log=print):
        from structured_light_for_3d_model_replication_tpu.pipeline import (
            stages,
        )

        self.cfg = cfg or Config()
        self.log = log
        self.root = os.path.abspath(root)
        self.scans_dir = os.path.join(self.root, "scans")
        self.store_root = os.path.join(self.root, "cache")
        self.ns_root = os.path.join(self.root, "cache-ns")
        os.makedirs(self.scans_dir, exist_ok=True)
        os.makedirs(self.store_root, exist_ok=True)
        self.run_id = tel.new_run_id()
        self.registry = tel.MetricsRegistry()
        scfg = self.cfg.serving
        self.adm = AdmissionController(
            os.path.join(self.root, "ledger.jsonl"), self.run_id,
            lease_s=scfg.lease_s, max_active_scans=scfg.max_active_scans,
            tenant_active_quota=scfg.tenant_active_quota,
            tenant_queue_quota=scfg.tenant_queue_quota,
            queue_depth=scfg.queue_depth, log=log)
        self._stages = stages
        self._policy = stages._retry_policy(self.cfg)
        self._fwd_kw = dict(thresh_mode=self.cfg.decode.thresh_mode,
                            shadow_val=self.cfg.decode.shadow_val,
                            contrast_val=self.cfg.decode.contrast_val)
        self._scans: dict[str, _ScanCtx] = {}
        self._scanners: dict[tuple, object] = {}   # scanner_key -> scanner
        self._scan_lock = threading.Lock()
        self._assembly_q: list[str] = []
        self._assembly_cv = threading.Condition()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._seq = 0
        self._seq_lock = threading.Lock()

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> None:
        scfg = self.cfg.serving
        for i in range(max(1, scfg.engine_lanes)):
            t = threading.Thread(target=self._engine_loop,
                                 args=(f"lane{i}",),
                                 name=f"sl3d-serve-engine-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._assembler_loop,
                             name="sl3d-serve-assembler", daemon=True)
        t.start()
        self._threads.append(t)
        self.log(f"[serve] service up (run {self.run_id}) root={self.root}")

    def close(self) -> None:
        self._stop.set()
        with self._assembly_cv:
            self._assembly_cv.notify_all()
        for t in self._threads:
            t.join(timeout=10.0)
        self.adm.close()

    # ---- submit ----------------------------------------------------------

    def submit(self, payload: dict) -> tuple[bool, dict]:
        """One scan submission: validate, quota-check, queue. Returns
        (accepted, body) where body is the /submit response JSON."""
        tenant = _safe_id(payload.get("tenant"), "anon")
        target = str(payload.get("target") or "")
        calib = str(payload.get("calib") or "")
        if not target or not os.path.isdir(target):
            return False, {"error": f"target is not a directory: {target!r}"}
        if not calib or not os.path.isfile(calib):
            return False, {"error": f"calib is not a file: {calib!r}"}
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        scan_id = _safe_id(payload.get("scan_id"),
                           f"s{seq:04d}") or f"s{seq:04d}"
        scan_id = f"{tenant}-{scan_id}"
        out_dir = os.path.join(self.scans_dir, scan_id)
        scfg = self.cfg.serving
        budget = payload.get("budget_s", scfg.default_budget_s)
        job = ScanJob(scan_id, tenant, os.path.abspath(target),
                      os.path.abspath(calib), out_dir,
                      weight=float(payload.get("weight",
                                               scfg.default_weight)),
                      budget_s=float(budget or 0.0))
        with self.adm.lock:
            if scan_id in self.adm.jobs:
                return False, {"error": f"scan_id {scan_id!r} already exists"}
            ok, reason = self.adm.submit(job)
        if not ok:
            self.registry.inc("sl3d_serve_rejected_total", tenant=tenant)
            return False, {"error": reason, "tenant": tenant}
        self.registry.inc("sl3d_serve_submitted_total", tenant=tenant)
        return True, {"scan_id": scan_id, "tenant": tenant,
                      "state": "queued"}

    def status(self, scan_id: str) -> dict | None:
        with self.adm.lock:
            job = self.adm.jobs.get(scan_id)
            if job is None:
                return None
            d = job.as_dict()
            d["items"] = self.adm.scan_item_states(scan_id)
            return d

    def result_path(self, scan_id: str, artifact: str) -> tuple[str, dict]:
        """Path of a finished request's artifact, or ("", error-body)."""
        with self.adm.lock:
            job = self.adm.jobs.get(scan_id)
        if job is None:
            return "", {"error": f"unknown scan_id {scan_id!r}"}
        if job.state not in ("done", "degraded"):
            return "", {"error": f"scan {scan_id!r} is {job.state}",
                        "state": job.state}
        name = {"ply": "merged.ply", "stl": "model.stl"}.get(artifact)
        if name is None:
            return "", {"error": f"unknown artifact {artifact!r} "
                                 "(want ply|stl)"}
        path = os.path.join(job.out_dir, name)
        if not os.path.isfile(path):
            return "", {"error": f"{name} missing for {scan_id!r}"}
        return path, {}

    def snapshot(self) -> dict:
        snap = self.adm.snapshot()
        snap["run_id"] = self.run_id
        return snap

    # ---- engine: plan ----------------------------------------------------

    def _plan(self, job) -> None:
        """Plan one admitted scan: derive sources + content-addressed view
        keys through the SAME ``_view_plan`` the assembly pass uses, probe
        the scanner key, register the cache-miss views as grantable items.
        A warm view (this tenant or ANY other — the keys carry no
        identity) completes at plan time: cross-tenant dedup is free."""
        st = self._stages
        job_log = self._job_log(job)
        cache = TenantCache(self.store_root, job.tenant,
                            ns_root=self.ns_root, enabled=True,
                            verify=self.cfg.pipeline.verify_cache,
                            log=lambda *_: None)
        calib, sources, _view_cfg, view_keys = st._view_plan(
            job.calib, job.target, self.cfg, self._engine_steps(), cache,
            job_log)
        scanner_key = self._scanner_key(job.calib, sources)
        specs, warm = [], 0
        for i, (src, key) in enumerate(zip(sources, view_keys)):
            if cache.get("view", key) is not None:
                warm += 1          # get() also marked this tenant's ref
                continue
            specs.append({"index": i, "src": src, "key": key,
                          "scan": job.scan_id})
        ctx = _ScanCtx(job, self._engine_steps(), calib, sources,
                       view_keys, cache, scanner_key)
        with self._scan_lock:
            self._scans[job.scan_id] = ctx
        self.adm.add_items(job.scan_id, specs)
        self.registry.inc("sl3d_serve_views_planned_total",
                          len(specs) + warm, tenant=job.tenant)
        self.registry.inc("sl3d_serve_views_dedup_total", warm,
                          tenant=job.tenant)
        job_log(f"[serve] {job.scan_id}: planned {len(specs)} view(s) to "
                f"warm, {warm} already cached")

    def _engine_steps(self) -> tuple:
        s = tuple(x.strip() for x in
                  self.cfg.serving.clean_steps.split(",") if x.strip())
        return s or tuple(self._stages._CLEAN_STEPS)

    def _scanner_key(self, calib_path: str, sources) -> tuple | None:
        """Scans sharing (calib file, camera geometry) share one scanner —
        the identity a cross-scan batched launch groups on. None on the
        numpy/bitexact paths (no device scanner; per-view lane)."""
        cfg = self.cfg
        if cfg.parallel.backend == "numpy" or cfg.triangulate.bitexact:
            return None
        from structured_light_for_3d_model_replication_tpu.io import (
            images as imio,
        )

        first = imio.list_frame_files(sources[0])
        hdr = imio.probe_packed(first[0])
        if hdr is not None:
            cam_size = (int(hdr["width"]), int(hdr["height"]))
        else:
            probe = imio.load_gray(first[0])
            cam_size = (probe.shape[1], probe.shape[0])
        return (os.path.abspath(calib_path), cam_size)

    def _scanner_for(self, ctx: _ScanCtx):
        if ctx.scanner_key is None:
            return None
        with self._scan_lock:
            sc = self._scanners.get(ctx.scanner_key)
            if sc is None:
                sc = self._stages._build_scanner(ctx.sources, ctx.calib,
                                                 self.cfg)
                self._scanners[ctx.scanner_key] = sc
            return sc

    # ---- engine: item programs ------------------------------------------

    def _engine_loop(self, lane: str) -> None:
        poll = max(0.01, self.cfg.serving.poll_s)
        batch_n = max(1, self.cfg.parallel.compute_batch)
        while not self._stop.is_set():
            try:
                self.adm.sweep_expired()
                for job in self.adm.admit_next():
                    try:
                        self._plan(job)
                    except Exception as e:
                        self.adm.finish(job.scan_id, "failed",
                                        error=f"plan: {e}")
                        self._finish_metrics(job, "failed")
                        self.log(f"[serve] {job.scan_id}: plan FAILED "
                                 f"({type(e).__name__}: {e})")
                self._queue_settled()
                grants = self.adm.next_views(lane, batch_n)
                if not grants:
                    self._stop.wait(poll)
                    continue
                self._run_grants(lane, grants)
            except BaseException as e:
                # the engine must survive anything an item throws at it
                # (incl. an injected crash — the service IS the process
                # that must not die); affected leases age into steals
                self.log(f"[serve] engine {lane}: {type(e).__name__}: {e}")
                self._stop.wait(poll)

    def _run_grants(self, lane: str, grants) -> None:
        """One grant set → loads → one (or more) launches. Grouping is by
        (scanner, frame shape): views from different scans land in the
        SAME group whenever their geometry matches — this is where
        cross-tenant batching actually happens."""
        st = self._stages
        loaded: dict[tuple | None, list] = {}
        for iid, gen, spec in grants:
            with self._scan_lock:
                ctx = self._scans.get(spec["scan"])
            if ctx is None:            # scan finished/failed underneath us
                self.adm.failed(iid, lane, gen, "scan context gone")
                continue
            try:
                frames, texture = st._retry_stage(
                    "load",
                    lambda s=spec["src"]: st._load_fired(s, self.cfg),
                    self._policy)
            except BaseException as e:
                self.adm.failed(iid, lane, gen, f"load: {e}")
                self.registry.inc("sl3d_serve_view_failures_total",
                                  tenant=ctx.job.tenant)
                continue
            gkey = (None if ctx.scanner_key is None
                    else ctx.scanner_key + (frames.shape,))
            loaded.setdefault(gkey, []).append(
                (iid, gen, spec, ctx, frames, texture))
            self.adm.beat(lane)
        for gkey, items in loaded.items():
            if gkey is None or len(items) == 1:
                for it in items:
                    self._view_single(lane, it)
            else:
                self._view_batched(lane, items)

    def _finish_item(self, lane, iid, gen, spec, ctx, pts, cols) -> None:
        """Clean + cache one computed view (the PR-8 worker tail) and
        settle its lease."""
        st = self._stages
        pts, cols, _ = st._clean_arrays(pts, cols, self.cfg, ctx.steps)
        ctx.cache.put("view", spec["key"], points=pts, colors=cols)
        self.adm.complete(iid, lane, gen)
        self.registry.inc("sl3d_serve_views_warmed_total",
                          tenant=ctx.job.tenant)

    def _view_single(self, lane: str, item) -> None:
        """The per-view engine lane: exactly the PR-8 worker's
        ``_do_view`` program. ``compute.view`` fires inside
        ``_compute_fired`` — a seeded fault fails the item here, the item
        is NOT cached, and the request's assembly pass recomputes it
        through the full retry/quarantine lane (failure policy lives in
        one place)."""
        st = self._stages
        iid, gen, spec, ctx, frames, texture = item
        from structured_light_for_3d_model_replication_tpu.ops import (
            triangulate as tri,
        )

        try:
            scanner = self._scanner_for(ctx)
            pts, cols = st._retry_stage(
                "compute",
                lambda: tri.compact_cloud(st._compute_fired(
                    frames, texture, ctx.calib, self.cfg, scanner,
                    spec["src"])),
                self._policy)
            self._finish_item(lane, iid, gen, spec, ctx, pts, cols)
        except BaseException as e:
            self.adm.failed(iid, lane, gen, f"compute: {e}")
            self.registry.inc("sl3d_serve_view_failures_total",
                              tenant=ctx.job.tenant)

    def _view_batched(self, lane: str, items) -> None:
        """One bucket-padded ``forward_views_batched`` launch over views
        from possibly MANY scans — ``_reconstruct_batched``'s dispatch
        math with the grant set as the batch. The ``compute.view`` site
        fires per item at assembly (chaos semantics survive batching);
        any batch-level failure degrades the whole group to the per-view
        lane, where a poisoned view fails ALONE and its groupmates (other
        tenants included) complete normally."""
        st = self._stages
        from structured_light_for_3d_model_replication_tpu.ops import (
            triangulate as tri,
        )

        poisoned = None
        for iid, gen, spec, ctx, _f, _t in items:
            try:
                faults.fire("compute.view", item=spec["src"])
            except BaseException as e:
                poisoned = e
                break
        if poisoned is None:
            try:
                import jax

                scanner = self._scanner_for(items[0][3])
                v = len(items)
                batch_n = max(1, self.cfg.parallel.compute_batch)
                bucket = st._view_bucket(v, batch_n)
                fv = np.stack([f for _, _, _, _, f, _ in items])
                if bucket > v:
                    fv = np.concatenate(
                        [fv, np.repeat(fv[-1:], bucket - v, axis=0)])
                fv_d = jax.device_put(fv)
                cloud = scanner.forward_views_batched(fv_d, mesh=None,
                                                      **self._fwd_kw)
                pts_v, cols_v, val_v = jax.device_get(
                    (cloud.points[:v], cloud.colors[:v], cloud.valid[:v]))
                tenants = {it[3].job.tenant for it in items}
                scans = {it[2]["scan"] for it in items}
                self.registry.inc("sl3d_serve_launches_total")
                self.registry.inc("sl3d_serve_launch_views_total", v)
                if len(scans) > 1:
                    self.registry.inc("sl3d_serve_cross_scan_launches_total")
                if len(tenants) > 1:
                    self.registry.inc(
                        "sl3d_serve_cross_tenant_launches_total")
                for j, (iid, gen, spec, ctx, _f, _t) in enumerate(items):
                    try:
                        pts, cols = tri.compact_cloud(
                            tri.CloudResult(pts_v[j], cols_v[j], val_v[j]))
                        self._finish_item(lane, iid, gen, spec, ctx, pts,
                                          cols)
                    except BaseException as e:
                        self.adm.failed(iid, lane, gen, f"drain: {e}")
                        self.registry.inc("sl3d_serve_view_failures_total",
                                          tenant=ctx.job.tenant)
                return
            except BaseException as e:
                poisoned = e
        self.log(f"[serve] batch of {len(items)} view(s) degraded to "
                 f"per-view compute ({type(poisoned).__name__}: "
                 f"{poisoned})")
        for it in items:
            self._view_single(lane, it)

    # ---- assembly --------------------------------------------------------

    def _queue_settled(self) -> None:
        """Flip admitted scans whose items all settled to WARMED and hand
        them to the assembler (a scan with zero cache-miss items settles
        immediately — the fully-deduped fast path)."""
        with self.adm.lock:
            ready = [sid for sid, j in self.adm.jobs.items()
                     if j.state == "admitted"
                     and self.adm.scan_settled(sid)]
            for sid in ready:
                self.adm.jobs[sid].state = "warmed"
                self.adm.ledger.event("warmed", scan=sid)
        if ready:
            with self._assembly_cv:
                self._assembly_q.extend(ready)
                self._assembly_cv.notify_all()

    def _assembler_loop(self) -> None:
        """ONE assembly at a time: requests share the engine for warming
        but serialize through the proven single-process pipeline — device
        contention stays simple and the byte-parity argument stays
        exactly PR-8's."""
        while True:
            with self._assembly_cv:
                while not self._assembly_q and not self._stop.is_set():
                    self._assembly_cv.wait(timeout=0.5)
                if self._stop.is_set() and not self._assembly_q:
                    return
                sid = self._assembly_q.pop(0)
            with self.adm.lock:
                job = self.adm.jobs.get(sid)
            if job is not None:
                self._assemble(job)

    def _job_log(self, job):
        def _log(msg):
            self.log(f"[{job.scan_id}] {msg}")
        return _log

    def _assemble(self, job) -> None:
        """The request's answer: ``run_pipeline`` over the warmed shared
        cache, in this tenant's namespace, under the request's REMAINING
        SLO budget. Terminal state maps: clean run → done; quarantined
        views above the floor → degraded (its own failures.json); budget
        breach → aborted (PR-7 manifest); anything else → failed. The
        service outlives every one of these."""
        st = self._stages
        with self._scan_lock:
            ctx = self._scans.get(job.scan_id)
        with self.adm.lock:
            job.state = "assembling"
        rcfg = copy.deepcopy(self.cfg)
        rcfg.coordinator.workers = 0
        rem = job.budget_remaining()
        if rem is not None:
            # the PR-7 run budget, re-based to what the queue+warm phases
            # left; an already-blown budget aborts at the first stage
            # boundary and still leaves a manifest
            rcfg.pipeline.run_budget_s = max(0.05, rem)
        cache = (ctx.cache if ctx is not None else TenantCache(
            self.store_root, job.tenant, ns_root=self.ns_root,
            enabled=True, verify=rcfg.pipeline.verify_cache,
            log=lambda *_: None))
        steps = ctx.steps if ctx is not None else self._engine_steps()
        t0 = time.monotonic()
        state, error, report_d = "failed", "", {}
        try:
            report = st.run_pipeline(job.calib, job.target, job.out_dir,
                                     cfg=rcfg, steps=steps,
                                     log=self._job_log(job), cache=cache)
            state = "degraded" if report.degraded else "done"
            report_d = {"run_id": report.run_id,
                        "views_computed": report.views_computed,
                        "views_cached": report.views_cached,
                        "merged_points": report.merged_points,
                        "failed_views": len(report.failed),
                        "merged_ply": report.merged_ply,
                        "stl_path": report.stl_path,
                        "assembly_s": round(report.elapsed_s, 3)}
        except dl.DeadlineExceeded as e:
            state, error = "aborted", f"SLO budget exceeded: {e}"
        except BaseException as e:
            state, error = "failed", f"{type(e).__name__}: {e}"
        finally:
            with self._scan_lock:
                self._scans.pop(job.scan_id, None)
        self.adm.finish(job.scan_id, state, error=error, report=report_d)
        self._finish_metrics(job, state,
                             assembly_s=time.monotonic() - t0)
        self.log(f"[serve] {job.scan_id}: {state.upper()} "
                 f"({job.elapsed_s():.2f}s total)" +
                 (f" — {error}" if error else ""))

    def _finish_metrics(self, job, state: str, assembly_s: float = 0.0):
        self.registry.inc("sl3d_serve_requests_total", tenant=job.tenant,
                          state=state)
        self.registry.observe("sl3d_serve_request_seconds",
                              job.elapsed_s(), tenant=job.tenant)
        if assembly_s:
            self.registry.observe("sl3d_serve_assembly_seconds",
                                  assembly_s, tenant=job.tenant)

    # ---- metrics surface -------------------------------------------------

    def metrics_text(self) -> str:
        snap = self.adm.snapshot()
        self.registry.set_gauge("sl3d_serve_scans_active", snap["active"])
        self.registry.set_gauge("sl3d_serve_scans_queued", snap["queued"])
        return tel.prometheus_text(self.registry.as_dict())


# ---- HTTP gateway --------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Thin JSON shim over ScanService; one instance per request (stdlib
    threading server), all state on ``self.server.service``."""

    protocol_version = "HTTP/1.1"

    @property
    def service(self) -> ScanService:
        return self.server.service      # type: ignore[attr-defined]

    def log_message(self, fmt, *args):   # route through the service log
        self.service.log("[serve.http] " + fmt % args)

    def _json(self, code: int, body: dict) -> None:
        data = (json.dumps(body) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _bytes(self, code: int, data: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path != "/submit":
            return self._json(404, {"error": f"no route {parsed.path!r}"})
        try:
            n = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            return self._json(400, {"error": f"bad JSON body: {e}"})
        ok, body = self.service.submit(payload)
        if ok:
            return self._json(200, body)
        # quota/backpressure rejections are 429 (retryable); malformed
        # submissions are 400
        code = 429 if ("quota" in body.get("error", "")
                       or "queue full" in body.get("error", "")) else 400
        return self._json(code, body)

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        if path == "/healthz":
            snap = self.service.snapshot()
            return self._json(200, {"ok": True, "run_id": snap["run_id"],
                                    "active": snap["active"],
                                    "queued": snap["queued"]})
        if path == "/metrics":
            return self._bytes(200, self.service.metrics_text().encode(),
                               "text/plain; version=0.0.4")
        if path.startswith("/status/"):
            d = self.service.status(path[len("/status/"):])
            if d is None:
                return self._json(404, {"error": "unknown scan_id"})
            return self._json(200, d)
        if path.startswith("/result/"):
            scan_id = path[len("/result/"):]
            q = urllib.parse.parse_qs(parsed.query)
            artifact = (q.get("artifact") or ["ply"])[0]
            fpath, err = self.service.result_path(scan_id, artifact)
            if not fpath:
                code = 409 if err.get("state") else 404
                return self._json(code, err)
            with open(fpath, "rb") as f:
                return self._bytes(200, f.read(),
                                   "application/octet-stream")
        return self._json(404, {"error": f"no route {path!r}"})


def start_gateway(root: str, cfg: Config | None = None, log=print,
                  ready_file: str | None = None):
    """Bind + start the service WITHOUT blocking: returns (httpd, svc).
    The caller runs ``httpd.serve_forever`` (``serve`` does, on the main
    thread; tests/bench push it to a daemon thread) and tears down with
    ``httpd.shutdown(); httpd.server_close(); svc.close()``. Writes
    ``<root>/serve.json`` (and optional ``ready_file``) with the bound
    address — the discovery handshake for CI and the load generator."""
    cfg = cfg or Config()
    svc = ScanService(root, cfg=cfg, log=log)
    httpd = ThreadingHTTPServer((cfg.serving.host, cfg.serving.port),
                                _Handler)
    httpd.service = svc                  # type: ignore[attr-defined]
    httpd.daemon_threads = True
    host, port = httpd.server_address[0], httpd.server_address[1]
    svc.start()
    info = {"host": host, "port": port, "pid": os.getpid(),
            "run_id": svc.run_id, "root": svc.root}
    with open(os.path.join(svc.root, "serve.json"), "w") as f:
        json.dump(info, f)
    if ready_file:
        with open(ready_file, "w") as f:
            json.dump(info, f)
    log(f"[serve] listening on http://{host}:{port} "
        f"(endpoints: /submit /status/<id> /result/<id> /metrics "
        f"/healthz)")
    return httpd, svc


def serve(root: str, cfg: Config | None = None, log=print,
          ready_file: str | None = None) -> int:
    """Run the gateway until interrupted (the ``sl3d serve`` entry)."""
    cfg = cfg or Config()
    faults.configure_from(cfg.faults)
    httpd, svc = start_gateway(root, cfg=cfg, log=log,
                               ready_file=ready_file)
    try:
        httpd.serve_forever(poll_interval=0.2)
    except KeyboardInterrupt:
        log("[serve] interrupted; draining")
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()
    return 0
