"""Incremental assembly: the coordinator-side fold lane.

A coordinated pod (parallel.coordinator) turns every worker into a
cache-warmer; the assembly pass afterwards is one single-process
``run_pipeline`` replay over the warmed stage cache. Historically that
replay did ALL of the accumulate work after the last item settled. This
module folds completed work into running merged-cloud state WHILE the pod
is still running: cleaned views fold in index order the moment their blobs
land in the L2 blobstore, and each finalized pair transform folds into the
running ``T_accum`` chain the moment its chain prefix is resolved — the
PR-5 registrar readiness rule (pair i is safe to chain only when views
``0..i`` are all accounted for, so its chain position is final), lifted to
the coordinator. When the last item settles, only the postprocess tail
(voxel/outlier + Poisson + mesh) remains.

Parity argument (incremental ≡ barrier ≡ single-process): the fold uses
the numpy twin of the accumulate arithmetic
(``models.reconstruction._transform_view_np`` — f32 matmul + translate +
f32 cast, exactly the historical host loop) and the SAME chain matmul
order, over payloads addressed by the SAME content-addressed keys the
assembly pass would read. The assembly pass then ``validate``s the folded
prefix against its own view order, output digests, and pair transforms —
any view the single-process rules would quarantine, any identity-fallback
pair (never cached, so never folded), any divergence at all truncates the
prefix — and ``finalize_chain`` seeds from the surviving prefix only.
Bytes cannot differ from the barrier arm because every folded quantity is
re-derivable (and re-derived on mismatch) from the assembly pass's own
state. ``merge.incremental`` is therefore a pure SCHEDULE knob, never
cache-key material.

Failure containment: the fold lane is an optimization and must never turn
a good run into a failed one — every fold error short of an injected
crash is logged and the affected suffix falls back to the assembly pass
(which recomputes it exactly as if the lane never ran). An
``InjectedCrash`` poisons the lane: the prefold is discarded wholesale.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

import numpy as np

from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["Prefold", "IncrementalAssembler"]


@dataclass
class Prefold:
    """The folded prefix handed from the pod phase to the assembly pass.

    ``transforms[k]`` maps view k into the frame of view 0 (``[0]`` is
    identity), ``merged_p``/``merged_c`` are the transformed per-view
    clouds, ``digests[k]`` is view k's cleaned-cloud OUTPUT digest (the
    validation anchor), ``T_pairs[k]`` the raw pair transform that folded
    view ``k+1``. ``events`` are ``(kind, idx, dur_s)`` fold records the
    assembly pass replays into the telemetry journal (no tracer is active
    during the pod phase — coordinated dispatch happens before
    ``run_pipeline`` opens one). ``settled_unix`` is wall time at
    last-item-settled: the anchor the assembly-tail gauge measures from.
    """

    digests: list = field(default_factory=list)
    transforms: list = field(default_factory=list)
    merged_p: list = field(default_factory=list)
    merged_c: list = field(default_factory=list)
    T_pairs: list = field(default_factory=list)
    events: list = field(default_factory=list)
    settled_unix: float | None = None
    offered_views: int = 0   # folded count before validation (for report)

    def validate(self, order, digests_by_view, T_pairs, log=print):
        """Trim to the prefix consistent with the assembly pass's ACTUAL
        view order, output digests, and pair transforms; None when fewer
        than 2 views survive (a 0/1-view prefix saves nothing).

        The prefix rule mirrors the fold rule: view k is trusted only if
        the pass kept view k at chain position k (``order[k] == k`` — a
        quarantined view shifts every later position, truncating here),
        its digest matches what was folded, and the pass's pair transform
        equals the folded one bit-for-bit (an identity-fallback pair was
        never cached, so the fold stalled before it by construction)."""
        k = 0
        lim = min(len(self.transforms), len(order))
        while k < lim:
            if order[k] != k or digests_by_view.get(k) != self.digests[k]:
                break
            if k > 0 and not np.array_equal(
                    np.asarray(T_pairs[k - 1], np.float32),
                    self.T_pairs[k - 1]):
                break
            k += 1
        if k < 2:
            if self.transforms:
                log(f"[assembly] prefold discarded (validated prefix {k} "
                    f"of {len(self.transforms)} folded view(s))")
            return None
        if k == len(self.transforms):
            return self
        log(f"[assembly] prefold trimmed to {k} of "
            f"{len(self.transforms)} folded view(s)")
        return Prefold(
            digests=self.digests[:k], transforms=self.transforms[:k],
            merged_p=self.merged_p[:k], merged_c=self.merged_c[:k],
            T_pairs=self.T_pairs[:k - 1],
            events=[e for e in self.events
                    if (e[0] == "view" and e[1] < k)
                    or (e[0] == "pair" and e[1] <= k - 2)],
            settled_unix=self.settled_unix,
            offered_views=self.offered_views)


class IncrementalAssembler:
    """Coordinator-side fold lane: one worker thread (the registrar's
    1-thread-pool idiom — all fold state is single-threaded) that consumes
    item-settled and blob-landed notifications and folds views in chain
    order as their payloads become readable from the local stage cache.

    A completed item whose payload is NOT readable (a degraded fabric push
    — ``BlobClient.push`` is best-effort) simply stalls the fold at that
    view; later notifications retry, and whatever never folds is
    recomputed by the assembly pass. Nothing here is load-bearing for
    correctness.
    """

    def __init__(self, cfg, view_keys, cache, log=print):
        from concurrent.futures import ThreadPoolExecutor

        from structured_light_for_3d_model_replication_tpu.models import (
            reconstruction as recon,
        )
        from structured_light_for_3d_model_replication_tpu.pipeline import (
            stages,
        )
        from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (  # noqa: E501
            StageCache,
        )

        self._recon = recon
        self.cfg = cfg
        self.cache = cache
        self.log = log
        self.view_keys = list(view_keys)
        self.n = len(self.view_keys)
        self._digest = StageCache.digest_arrays
        # identical key derivation to _StreamRegistrar._enqueue and
        # worker._do_pair: endpoint OUTPUT digests + merge numerics +
        # chain position
        self._pair_cfg = stages._merge_numeric_json(cfg) + json.dumps(
            {"backend": cfg.parallel.backend,
             "force_bf16": cfg.parallel.force_bf16_features})
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="sl3d-assembly")
        self._futs: list = []
        self._closed = False
        self._crashed = False
        # fold state below is mutated only on the fold worker
        self._view_done: set[int] = set()
        self._pair_done: set[int] = set()
        self._clouds: dict[int, tuple] = {}
        self._digests: dict[int, str] = {}
        self._transforms: list = []
        self._merged_p: list = []
        self._merged_c: list = []
        self._T_pairs: list = []
        self._events: list = []
        self._folded = 0   # views folded == len(self._transforms)

    # ---- public API (any thread) ----------------------------------------

    def note_item(self, iid: str) -> None:
        """An item settled successfully (``view:i`` / ``pair:i``) — from
        ``op_complete``, the resume ledger, or the pre-done cache scan."""
        self._submit(self._note, iid)

    def note_blob(self, name: str) -> None:
        """A blob landed in the L2 store (``BlobServer`` ``on_blob``) —
        the earliest wake-up: for fabric-pushed payloads it fires before
        the worker even reports the item complete, and it un-stalls folds
        that previously read a miss."""
        self._submit(self._fold)

    def _submit(self, fn, *args) -> None:
        if self._closed:
            return
        try:
            self._futs.append(self._pool.submit(fn, *args))
        except RuntimeError:
            pass   # raced a shutdown: the assembly pass covers the rest

    def close(self) -> None:
        """Drain the fold worker. Idempotent. Fold errors were already
        contained per-future; an injected crash poisons the lane (the
        prefold is discarded) rather than failing the run here — the
        assembly pass recomputes everything the lane never delivered."""
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=True)
        for f in self._futs:
            e = f.exception()
            if isinstance(e, faults.InjectedCrash):
                self._crashed = True
                self.log("[assembly] fold lane hit an injected crash — "
                         "prefold discarded; the assembly pass recomputes")
            elif e is not None:
                self.log(f"[assembly] WARNING: fold error "
                         f"({type(e).__name__}: {e}); the affected suffix "
                         f"falls back to the assembly pass")

    def prefold(self, settled_unix: float) -> Prefold:
        """Snapshot the folded prefix (call after ``close``)."""
        pf = Prefold(settled_unix=float(settled_unix))
        if self._crashed:
            return pf
        pf.digests = list(self._digests.get(i)
                          for i in range(self._folded))
        pf.transforms = list(self._transforms)
        pf.merged_p = list(self._merged_p)
        pf.merged_c = list(self._merged_c)
        pf.T_pairs = list(self._T_pairs)
        pf.events = list(self._events)
        pf.offered_views = self._folded
        return pf

    # ---- fold-worker internals -------------------------------------------

    def _note(self, iid: str) -> None:
        kind, _, num = iid.partition(":")
        try:
            idx = int(num)
        except ValueError:
            return
        if kind == "view":
            self._view_done.add(idx)
        elif kind == "pair":
            self._pair_done.add(idx)
        else:
            return
        self._fold()

    def _fold(self) -> None:
        # fold readiness rule: view k folds when views 0..k have settled
        # and loaded AND pair k-1's transform is readable — the chain
        # prefix is then resolved, so k's accumulated transform is final
        while self._folded < self.n:
            k = self._folded
            if k not in self._view_done:
                return
            if k >= 1 and (k - 1) not in self._pair_done:
                return
            t0 = time.perf_counter()
            if not self._load_view(k):
                return
            pts, cols = self._clouds[k]
            if k == 0:
                self._transforms.append(np.eye(4, dtype=np.float32))
                self._merged_p.append(pts)
                self._merged_c.append(cols)
                self._events.append(
                    ("view", 0, round(time.perf_counter() - t0, 6)))
                self._folded = 1
                continue
            t1 = time.perf_counter()
            T = self._pair_T(k - 1)
            if T is None:
                return
            t_accum = (self._transforms[-1] @ T).astype(np.float32)
            self._transforms.append(t_accum)
            self._T_pairs.append(T)
            self._merged_p.append(self._recon._transform_view_np(t_accum,
                                                                 pts))
            self._merged_c.append(cols)
            self._events.append(("view", k, round(t1 - t0, 6)))
            self._events.append(
                ("pair", k - 1, round(time.perf_counter() - t1, 6)))
            self._folded += 1
            self._clouds.pop(k, None)   # moved cloud kept, raw no longer

    def _load_view(self, i: int) -> bool:
        if i in self._clouds:
            return True
        hit = self.cache.get("view", self.view_keys[i])
        if hit is None:
            return False
        pts = np.asarray(hit["points"], np.float32)
        cols = np.asarray(hit["colors"], np.uint8)
        self._clouds[i] = (pts, cols)
        self._digests[i] = self._digest(points=pts, colors=cols)
        return True

    def _pair_T(self, pid: int):
        key = self.cache.key(
            "pair", digests=[self._digests[pid], self._digests[pid + 1]],
            config_json=self._pair_cfg + json.dumps({"pair": pid}))
        hit = self.cache.get("pair", key)
        if hit is None:
            return None
        return np.asarray(hit["T"], np.float32)
