"""Content-addressed stage cache for the fused scan-to-print pipeline.

Every pipeline stage is a pure function of (input bytes, config subtree), so
its output can be keyed by a digest of exactly those inputs and reused across
runs: an interrupted or re-invoked ``slscan pipeline`` resumes from the first
stage whose inputs actually changed, paying zero decode/clean/merge/mesh
compute for everything upstream of the edit.

Key scheme (sha256, hex):

  view stage   H(schema | stage | frame-file names+bytes | calib bytes |
                 json(decode+triangulate+projector+clean config, steps,
                 backend))
  pair stage   H(schema | stage | the two views' cleaned-cloud OUTPUT
                 digests | json(merge cfg numerics, chain pair id)) — one
                 entry per registered pair, so a rerun with ONE dirty view
                 re-registers only its <=2 adjacent pairs. Schedule knobs
                 (merge.stream, merge.pair_batch) never enter the key:
                 streamed and barrier runs produce identical bytes and
                 share entries.
  merge stage  H(schema | stage | per-view OUTPUT digests | json(merge cfg))
  mesh stage   H(schema | stage | merged OUTPUT digest | json(mesh cfg))

Chaining through *output* digests (not input keys) means a view recomputed
to identical bytes still hits the merge cache, and any upstream change —
frames, calibration, or the relevant config subtree — dirties every stage
downstream of it and nothing else. Payloads are ``.npz`` files under
``<out>/.slscan-cache/<stage>-<key16>.npz``; a corrupt or half-written entry
reads as a miss (the write is tmp+rename, so interrupts cannot corrupt a
published entry).

Resilience contract (docs/ARCHITECTURE.md "Failure domains & recovery"):

  - every payload carries a ``__digest__`` of its own arrays; reads verify
    it (``verify=True``) and a mismatch — bit rot, a torn-write survivor —
    EVICTS the entry and reads as a miss, so a corrupt entry can never
    poison downstream stages
  - ``put`` is best-effort: a failed write (disk full, injected
    ``cache.put`` fault) cleans up its tmp file, logs, and returns — the
    cache is an optimization, never allowed to kill a computed result
  - init sweeps orphaned ``*.tmp`` files (a ``kill -9`` mid-``put`` leaves
    one behind; they are never valid entries)
"""
from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from structured_light_for_3d_model_replication_tpu.io.atomic import sweep_tmp
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import telemetry

__all__ = ["StageCache", "TenantCache", "config_subtree"]

# bump when a stage's numeric contract changes (payload layout, op
# semantics): stale entries then read as misses instead of wrong hits
# (v2: payloads carry a __digest__ for read-time verification)
_SCHEMA = "slscan-cache-v2"


def config_subtree(cfg, sections: tuple[str, ...]) -> str:
    """Canonical JSON of the config sections a stage's numbers depend on —
    the 'relevant config subtree' part of every cache key."""
    import dataclasses

    return json.dumps(
        {s: dataclasses.asdict(getattr(cfg, s)) for s in sections},
        sort_keys=True)


class StageCache:
    """Filesystem-backed content-addressed cache with hit/miss accounting.

    ``enabled=False`` turns every lookup into a miss and every put into a
    no-op — one code path for cached and uncached runs.
    """

    def __init__(self, root: str, enabled: bool = True, log=None,
                 verify: bool = True):
        self.root = root
        self.enabled = enabled
        self.verify = verify
        self._log = log or (lambda m: None)
        self.hits: list[str] = []
        self.misses: list[str] = []
        self.evicted: list[str] = []
        self.put_errors: list[str] = []
        if enabled:
            os.makedirs(root, exist_ok=True)
            # a kill -9 mid-put leaves a .tmp orphan; never a valid entry
            sweep_tmp(root, log=self._log)

    # -- keys ------------------------------------------------------------

    def key(self, stage: str, *, files: list[str] | None = None,
            digests: list[str] | None = None,
            arrays: dict[str, np.ndarray] | None = None,
            config_json: str = "") -> str:
        h = hashlib.sha256()
        h.update(_SCHEMA.encode())
        h.update(stage.encode())
        for path in files or []:
            h.update(os.path.basename(path).encode())
            with open(path, "rb") as f:
                h.update(f.read())
        for d in digests or []:
            h.update(d.encode())
        for name in sorted(arrays or {}):
            a = np.ascontiguousarray(arrays[name])
            h.update(name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        h.update(config_json.encode())
        return h.hexdigest()

    def keys_parallel(self, stage: str, file_lists: list[list[str]],
                      config_json: str = "", io_workers: int = 1,
                      timeout_s: float | None = None) -> list[str]:
        """Per-item ``key(stage, files=...)`` for a whole batch, hashed on a
        thread pool (``key`` is pure, so order-preserving submission is
        safe). Keying a 24-view 1080p run reads ~2 GB of frame bytes; doing
        it serially stalls the batched executor's first launch behind the
        hash wall. ``timeout_s`` bounds the WHOLE keying pass (one shared
        monotonic deadline): a hung filesystem read raises
        :class:`~.utils.deadline.DeadlineExceeded` instead of wedging the
        run before its first stage. NOTE: executor/batching knobs
        (``parallel.compute_batch``, ``shard_views``, ``io_workers``) must
        NEVER enter ``config_json`` — every execution schedule produces
        identical bytes, so cached views must hit across schedule
        changes."""
        if io_workers > 1 and len(file_lists) > 1:
            from concurrent.futures import ThreadPoolExecutor

            deadline = dl.Deadline.after(timeout_s, "stage-cache keying")
            with ThreadPoolExecutor(
                    max_workers=min(io_workers, len(file_lists)),
                    thread_name_prefix="sl3d-cachekey") as pool:
                futs = [pool.submit(self.key, stage, files=fl,
                                    config_json=config_json)
                        for fl in file_lists]
                try:
                    out = []
                    for i, f in enumerate(futs):
                        rem = (deadline.remaining()
                               if deadline is not None else None)
                        if rem is not None and rem <= 0:
                            # spent budget means expired, never unbounded
                            raise dl.DeadlineExceeded(
                                f"{stage} cache keying exceeded its "
                                f"{timeout_s:g}s budget at key {i}")
                        out.append(dl.wait_future(
                            f, rem, what=f"{stage} cache key {i}"))
                    return out
                except dl.DeadlineExceeded:
                    # don't leave the pool's __exit__ blocked on the same
                    # wedge the deadline just reported
                    pool.shutdown(wait=False, cancel_futures=True)
                    raise
        deadline = dl.Deadline.after(timeout_s, "stage-cache keying")
        out = []
        for fl in file_lists:
            if deadline is not None:
                deadline.check(f"{stage} cache keying")
            out.append(self.key(stage, files=fl, config_json=config_json))
        return out

    @staticmethod
    def digest_arrays(**arrays) -> str:
        """Content digest of a stage OUTPUT — what downstream keys chain on."""
        h = hashlib.sha256()
        for name in sorted(arrays):
            a = np.ascontiguousarray(arrays[name])
            h.update(name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        return h.hexdigest()

    # -- payloads --------------------------------------------------------

    def _path(self, stage: str, key: str) -> str:
        return os.path.join(self.root, f"{stage}-{key[:16]}.npz")

    def _miss(self, stage: str) -> None:
        self.misses.append(stage)
        tr = telemetry.current()
        if tr is not None:
            tr.instant("cache.miss", stage=stage)

    def _hit(self, stage: str) -> None:
        self.hits.append(stage)
        tr = telemetry.current()
        if tr is not None:
            tr.instant("cache.hit", stage=stage)

    def _evict(self, path: str, stage: str, why: str) -> None:
        """Remove a bad entry so it cannot poison a later read."""
        try:
            os.remove(path)
        except OSError:
            pass
        self.evicted.append(stage)
        tr = telemetry.current()
        if tr is not None:
            tr.instant("cache.evict", stage=stage, why=why)
        self._log(f"[cache] {stage}: evicted {os.path.basename(path)} "
                  f"({why}); recomputing")

    def get(self, stage: str, key: str) -> dict | None:
        """Load a stage payload; None on any miss (absent, disabled,
        unreadable, or digest-mismatched — the last two also evict the
        entry). Hits are logged — the resume trail the operator reads."""
        if not self.enabled:
            self._miss(stage)
            return None
        path = self._path(stage, key)
        try:
            faults.fire("cache.get", item=f"{stage}:{key[:16]}")
        except faults.InjectedCrash:
            raise
        except Exception:
            # an injected lookup failure behaves like the corrupt-entry
            # path: evict whatever is there and read as a miss
            self._evict(path, stage, "injected lookup fault")
            self._miss(stage)
            return None
        if not os.path.exists(path):
            self._miss(stage)
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                if "__key__" not in z.files or str(z["__key__"]) != key:
                    self._miss(stage)  # 16-hex-prefix collision
                    return None
                out = {k: z[k] for k in z.files
                       if k not in ("__key__", "__digest__")}
                recorded = (str(z["__digest__"])
                            if "__digest__" in z.files else None)
        except faults.InjectedCrash:
            raise
        except Exception as e:  # half-written/corrupt entry == miss
            self._evict(path, stage, f"unreadable: {e}")
            self._miss(stage)
            return None
        if self.verify:
            # recorded=None is a pre-digest entry (older schema bump
            # should catch this, but stay safe): treat as unverifiable
            if recorded is None or self.digest_arrays(**out) != recorded:
                self._evict(path, stage, "payload digest mismatch "
                            "(bit rot or torn write)")
                self._miss(stage)
                return None
        self._hit(stage)
        self._log(f"[cache] {stage}: hit ({os.path.basename(path)})")
        return out

    def put(self, stage: str, key: str, **arrays) -> None:
        """Publish a stage payload (tmp + atomic rename). Best-effort: any
        write failure cleans up the tmp file and logs instead of raising —
        losing a cache entry must never lose the computed result."""
        if not self.enabled:
            return
        path = self._path(stage, key)
        tmp = path + ".tmp"
        try:
            faults.fire("cache.put", item=f"{stage}:{key[:16]}")
            np.savez(tmp, __key__=np.asarray(key),
                     __digest__=np.asarray(self.digest_arrays(**arrays)),
                     **arrays)
            # np.savez appends .npz to names without it
            if not os.path.exists(tmp) and os.path.exists(tmp + ".npz"):
                tmp = tmp + ".npz"
            os.replace(tmp, path)
        except faults.InjectedCrash:
            raise
        except Exception as e:
            self.put_errors.append(stage)
            tr = telemetry.current()
            if tr is not None:
                tr.instant("cache.put_error", stage=stage,
                           error=type(e).__name__)
            self._log(f"[cache] {stage}: put failed ({e}); continuing "
                      f"uncached")
        finally:
            for leftover in (tmp, tmp + ".npz"):
                if leftover != path and os.path.exists(leftover):
                    try:
                        os.remove(leftover)
                    except OSError:
                        pass

    def stats(self) -> dict:
        return {"hits": len(self.hits), "misses": len(self.misses),
                "hit_stages": list(self.hits),
                "miss_stages": list(self.misses),
                "evicted": len(self.evicted),
                "put_errors": len(self.put_errors)}


def _safe_tenant(tenant: str) -> str:
    """Filesystem-safe tenant id: restricted charset, bounded length, no
    dot-prefix (so a tenant can never escape or shadow the namespace
    root). An empty result is a caller bug, not a default identity."""
    import re

    t = re.sub(r"[^A-Za-z0-9._-]", "_", str(tenant))[:64].lstrip(".")
    if not t:
        raise ValueError(f"unusable tenant id {tenant!r}")
    return t


class TenantCache(StageCache):
    """Per-tenant namespace view over a SHARED content-addressed store.

    Payload bytes live once in the shared store directory — identical
    frame bytes submitted by two tenants hash to the same content key and
    share ONE ``.npz`` entry (cross-tenant dedup is free because the key
    scheme never includes identity, only content). What is per-tenant is
    the *namespace*: a directory of zero-byte ``<stage>-<key16>.ref``
    markers recording which store entries this tenant has read or
    written. ``evict_tenant`` drops a tenant's refs and deletes only the
    payloads no other tenant still references — so evicting tenant A can
    never cold tenant B's entries, and a tenant's cache footprint is
    exactly its ref set. Tenants never share *outputs* (every request
    owns its out_dir); they share only content-keyed intermediates.
    """

    def __init__(self, store_root: str, tenant: str,
                 ns_root: str | None = None, enabled: bool = True,
                 log=None, verify: bool = True):
        super().__init__(store_root, enabled=enabled, log=log,
                         verify=verify)
        self.tenant = _safe_tenant(tenant)
        self.ns_root = ns_root or (store_root.rstrip(os.sep) + "-ns")
        self.ns_dir = os.path.join(self.ns_root, self.tenant)
        if enabled:
            os.makedirs(self.ns_dir, exist_ok=True)

    def _ref_path(self, stage: str, key: str) -> str:
        return os.path.join(self.ns_dir, f"{stage}-{key[:16]}.ref")

    def _touch_ref(self, stage: str, key: str) -> None:
        if not self.enabled:
            return
        try:
            with open(self._ref_path(stage, key), "a", encoding="utf-8"):
                pass
        except OSError:
            pass    # a lost ref marker only risks early eviction, never data

    def get(self, stage: str, key: str) -> dict | None:
        hit = super().get(stage, key)
        if hit is not None:
            # reads ref too: a dedup hit on another tenant's entry must
            # keep the payload alive past THAT tenant's eviction
            self._touch_ref(stage, key)
        return hit

    def put(self, stage: str, key: str, **arrays) -> None:
        super().put(stage, key, **arrays)
        self._touch_ref(stage, key)

    def refs(self) -> list[str]:
        """This tenant's referenced entry names (``<stage>-<key16>``)."""
        try:
            return sorted(f[:-4] for f in os.listdir(self.ns_dir)
                          if f.endswith(".ref"))
        except OSError:
            return []

    @staticmethod
    def tenants(ns_root: str) -> list[str]:
        try:
            return sorted(d for d in os.listdir(ns_root)
                          if os.path.isdir(os.path.join(ns_root, d)))
        except OSError:
            return []

    @classmethod
    def evict_tenant(cls, store_root: str, tenant: str,
                     ns_root: str | None = None, log=None) -> dict:
        """Drop ``tenant``'s namespace and garbage-collect store payloads
        nobody else references. Returns {"refs_dropped", "payloads_deleted",
        "payloads_kept"} — kept means another tenant still holds a ref."""
        log = log or (lambda m: None)
        ns_root = ns_root or (store_root.rstrip(os.sep) + "-ns")
        t = _safe_tenant(tenant)
        ns_dir = os.path.join(ns_root, t)
        mine = set()
        try:
            mine = {f[:-4] for f in os.listdir(ns_dir)
                    if f.endswith(".ref")}
        except OSError:
            pass
        others: set[str] = set()
        for other in cls.tenants(ns_root):
            if other == t:
                continue
            try:
                others.update(f[:-4]
                              for f in os.listdir(os.path.join(ns_root,
                                                               other))
                              if f.endswith(".ref"))
            except OSError:
                continue
        deleted = kept = 0
        for name in sorted(mine):
            if name in others:
                kept += 1
                continue
            try:
                os.remove(os.path.join(store_root, name + ".npz"))
                deleted += 1
            except OSError:
                pass    # already gone (or never published): nothing to GC
        import shutil

        shutil.rmtree(ns_dir, ignore_errors=True)
        log(f"[cache] evicted tenant {t}: {len(mine)} ref(s) dropped, "
            f"{deleted} payload(s) deleted, {kept} kept (still shared)")
        return {"refs_dropped": len(mine), "payloads_deleted": deleted,
                "payloads_kept": kept}
