"""Content-addressed stage cache for the fused scan-to-print pipeline.

Every pipeline stage is a pure function of (input bytes, config subtree), so
its output can be keyed by a digest of exactly those inputs and reused across
runs: an interrupted or re-invoked ``slscan pipeline`` resumes from the first
stage whose inputs actually changed, paying zero decode/clean/merge/mesh
compute for everything upstream of the edit.

Key scheme (sha256, hex):

  view stage   H(schema | stage | frame-file names+bytes | calib bytes |
                 json(decode+triangulate+projector+clean config, steps,
                 backend))
  merge stage  H(schema | stage | per-view OUTPUT digests | json(merge cfg))
  mesh stage   H(schema | stage | merged OUTPUT digest | json(mesh cfg))

Chaining through *output* digests (not input keys) means a view recomputed
to identical bytes still hits the merge cache, and any upstream change —
frames, calibration, or the relevant config subtree — dirties every stage
downstream of it and nothing else. Payloads are ``.npz`` files under
``<out>/.slscan-cache/<stage>-<key16>.npz``; a corrupt or half-written entry
reads as a miss (the write is tmp+rename, so interrupts cannot corrupt a
published entry).
"""
from __future__ import annotations

import hashlib
import json
import os

import numpy as np

__all__ = ["StageCache", "config_subtree"]

# bump when a stage's numeric contract changes (payload layout, op
# semantics): stale entries then read as misses instead of wrong hits
_SCHEMA = "slscan-cache-v1"


def config_subtree(cfg, sections: tuple[str, ...]) -> str:
    """Canonical JSON of the config sections a stage's numbers depend on —
    the 'relevant config subtree' part of every cache key."""
    import dataclasses

    return json.dumps(
        {s: dataclasses.asdict(getattr(cfg, s)) for s in sections},
        sort_keys=True)


class StageCache:
    """Filesystem-backed content-addressed cache with hit/miss accounting.

    ``enabled=False`` turns every lookup into a miss and every put into a
    no-op — one code path for cached and uncached runs.
    """

    def __init__(self, root: str, enabled: bool = True, log=None):
        self.root = root
        self.enabled = enabled
        self._log = log or (lambda m: None)
        self.hits: list[str] = []
        self.misses: list[str] = []
        if enabled:
            os.makedirs(root, exist_ok=True)

    # -- keys ------------------------------------------------------------

    def key(self, stage: str, *, files: list[str] | None = None,
            digests: list[str] | None = None,
            arrays: dict[str, np.ndarray] | None = None,
            config_json: str = "") -> str:
        h = hashlib.sha256()
        h.update(_SCHEMA.encode())
        h.update(stage.encode())
        for path in files or []:
            h.update(os.path.basename(path).encode())
            with open(path, "rb") as f:
                h.update(f.read())
        for d in digests or []:
            h.update(d.encode())
        for name in sorted(arrays or {}):
            a = np.ascontiguousarray(arrays[name])
            h.update(name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        h.update(config_json.encode())
        return h.hexdigest()

    @staticmethod
    def digest_arrays(**arrays) -> str:
        """Content digest of a stage OUTPUT — what downstream keys chain on."""
        h = hashlib.sha256()
        for name in sorted(arrays):
            a = np.ascontiguousarray(arrays[name])
            h.update(name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        return h.hexdigest()

    # -- payloads --------------------------------------------------------

    def _path(self, stage: str, key: str) -> str:
        return os.path.join(self.root, f"{stage}-{key[:16]}.npz")

    def get(self, stage: str, key: str) -> dict | None:
        """Load a stage payload; None on any miss (absent, disabled, or
        unreadable). Hits are logged — the resume trail the operator reads."""
        if not self.enabled:
            self.misses.append(stage)
            return None
        path = self._path(stage, key)
        if not os.path.exists(path):
            self.misses.append(stage)
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                if "__key__" not in z.files or str(z["__key__"]) != key:
                    self.misses.append(stage)  # 16-hex-prefix collision
                    return None
                out = {k: z[k] for k in z.files if k != "__key__"}
        except Exception as e:  # half-written/corrupt entry == miss
            self._log(f"[cache] {stage}: unreadable entry ({e}); recomputing")
            self.misses.append(stage)
            return None
        self.hits.append(stage)
        self._log(f"[cache] {stage}: hit ({os.path.basename(path)})")
        return out

    def put(self, stage: str, key: str, **arrays) -> None:
        if not self.enabled:
            return
        path = self._path(stage, key)
        tmp = path + ".tmp"
        np.savez(tmp, __key__=np.asarray(key), **arrays)
        # np.savez appends .npz to names without it
        if not os.path.exists(tmp) and os.path.exists(tmp + ".npz"):
            tmp = tmp + ".npz"
        os.replace(tmp, path)

    def stats(self) -> dict:
        return {"hits": len(self.hits), "misses": len(self.misses),
                "hit_stages": list(self.hits)}
