"""``sl3d report``: render a run's flight-recorder artifacts.

Reads the journal (``trace.jsonl``), the metrics snapshot (``metrics.json``),
and the failure manifest (``failures.json``) from a pipeline out dir and
renders, on a terminal:

  - the lane timeline — per-lane busy intervals over the run wall, so the
    overlap the executor claims is *visible* (a register bar nested inside
    the compute bar IS the streaming merge working)
  - per-stage walls (cache.keys / reconstruct / merge / mesh / writes)
  - per-lane walls + span counts, derived purely from journal events (the
    cross-check twin of ``OverlapStats`` — same instrumentation calls, so
    the report reproduces the executor's numbers from artifacts alone)
  - cache hit/miss/evict ratios per stage
  - the launch/bucket table (views per launch, pair launches)
  - the fault ledger: retries, failures, injected faults, quarantined
    views — merged from journal events and failures.json
  - the stall ledger: ``watchdog.stall`` breaches, per-lane
    last-heartbeat ages (from the throttled ``lane.heartbeat`` instants
    and lane-span ends), and the ``stalls.json`` thread-stack dump the
    watchdog leaves on a hard breach — the "why did this run hang"
    answer, readable for clean, DEGRADED, and INTERRUPTED runs alike

Degraded and interrupted runs are first-class: a journal with no ``end``
marker (crash/kill) reports as INTERRUPTED, torn trailing lines are
tolerated (counted, never fatal), and a missing metrics.json (written at
close) downgrades to journal-only analysis.

``--chrome-trace`` exports the Perfetto-loadable ``trace.json`` via
:func:`~.utils.telemetry.export_chrome_trace`; ``--prometheus`` re-emits
``metrics.json`` as Prometheus exposition text (the serving-process format).
"""
from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field

from structured_light_for_3d_model_replication_tpu.parallel import netutil
from structured_light_for_3d_model_replication_tpu.utils import telemetry

__all__ = ["RunAnalysis", "analyze_run", "render_report", "validate_journal",
           "host_journals", "merge_host_timeline", "render_host_timeline"]

_LANES = telemetry.LANE_ORDER


# ---------------------------------------------------------------------------
# journal validation (the TRACE_SMOKE contract)
# ---------------------------------------------------------------------------

_REQUIRED = {
    "meta": ("schema", "run_id", "t0_unix"),
    "span": ("ev", "t", "dur"),
    "instant": ("ev", "t"),
    "end": ("t",),
}


def validate_journal(path: str) -> list[str]:
    """Schema-check a journal; returns a list of human-readable problems
    (empty == valid). A missing ``end`` marker is NOT an error — that is
    what an interrupted run looks like — but a missing/late meta line, an
    unknown event type, or a span without a duration is."""
    errors: list[str] = []
    j = telemetry.read_journal(path)
    for s, seg in enumerate(j["segments"]):
        meta = seg["meta"]
        if meta is None:
            errors.append(f"segment {s}: no meta header line")
        else:
            for k in _REQUIRED["meta"]:
                if k not in meta:
                    errors.append(f"segment {s}: meta line missing {k!r}")
            if meta.get("schema") not in (telemetry.SCHEMA,):
                errors.append(f"segment {s}: unknown schema "
                              f"{meta.get('schema')!r} "
                              f"(expected {telemetry.SCHEMA})")
        for i, ev in enumerate(seg["events"]):
            kind = ev.get("type")
            if kind not in _REQUIRED:
                errors.append(f"segment {s} event {i}: unknown type {kind!r}")
                continue
            for k in _REQUIRED[kind]:
                if k not in ev:
                    errors.append(f"segment {s} event {i} "
                                  f"({kind}/{ev.get('ev')}): missing {k!r}")
            if kind == "span" and ev.get("ev") == "lane" and "lane" not in ev:
                errors.append(f"segment {s} event {i}: lane span without "
                              f"a lane")
            t = ev.get("t")
            if isinstance(t, (int, float)) and t < -1e-6:
                errors.append(f"segment {s} event {i}: negative "
                              f"timestamp {t}")
    return errors


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

@dataclass
class RunAnalysis:
    out_dir: str
    run_id: str | None = None
    meta: dict = field(default_factory=dict)
    wall_s: float = 0.0
    ended: bool = False            # end marker present (clean close)
    runs_in_journal: int = 1       # appended segments (reruns keep history)
    truncated_lines: int = 0
    events: int = 0
    lane_walls: dict[str, float] = field(default_factory=dict)
    lane_spans: dict[str, int] = field(default_factory=dict)
    lane_intervals: dict[str, list[tuple[float, float]]] = \
        field(default_factory=dict)
    stage_walls: dict[str, float] = field(default_factory=dict)
    cache: dict[str, dict[str, int]] = field(default_factory=dict)
    launches: list[dict] = field(default_factory=list)
    pair_launches: list[dict] = field(default_factory=list)
    retries: dict[str, int] = field(default_factory=dict)
    failures: dict[str, int] = field(default_factory=dict)
    injected: dict[str, int] = field(default_factory=dict)
    quarantined: list[dict] = field(default_factory=list)
    critical_path_s: float | None = None
    # kernel table: per-kernel launch/wall/bytes totals with a per-bucket
    # breakdown (from the `kernel.*` instants the launch accounting and
    # the Pallas wrappers emit) + the h2d/d2h transfer-byte counters
    kernels: dict[str, dict] = field(default_factory=dict)
    transfer: dict[str, int] = field(default_factory=dict)
    # pod-fabric blob traffic (from `fabric.bytes` instants): bytes this
    # host fetched from / pushed to / deduped against the L2 blobstore —
    # the artifact-side twin of the OverlapStats fabric counters
    fabric: dict[str, int] = field(default_factory=dict)
    manifest: dict | None = None   # failures.json payload
    metrics: dict | None = None    # metrics.json payload
    # incremental-assembly close-out: the `assembly.tail` instant the
    # assembly pass emits when a prefold was in play (tail_s + fold
    # counters) — the journal-side twin of the
    # `sl3d_assembly_tail_seconds` metrics gauge
    assembly: dict | None = None
    # stall ledger: watchdog breaches seen in the journal, the last
    # heartbeat time per lane (span ends + lane.heartbeat instants), and
    # the stalls.json payload the watchdog persists on a breach
    stall_events: list[dict] = field(default_factory=list)
    lane_last_beat: dict[str, float] = field(default_factory=dict)
    stalls: dict | None = None


def _merge_intervals(iv: list[tuple[float, float]]) -> list[tuple[float, float]]:
    out: list[tuple[float, float]] = []
    for a, b in sorted(iv):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def analyze_run(out_dir: str, trace_file: str = "trace.jsonl",
                metrics_file: str = "metrics.json") -> RunAnalysis:
    """Build a :class:`RunAnalysis` from whatever artifacts the out dir
    holds. Requires the journal; metrics.json and failures.json are
    optional (interrupted runs have no metrics, clean runs no manifest)."""
    path = os.path.join(out_dir, trace_file)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no {trace_file} under {out_dir!r} — run the pipeline with "
            f"observability.trace=true (--trace / SL3D_TRACE=1) to record "
            f"one")
    j = telemetry.read_journal(path)
    # meta/events are the journal's LATEST segment: reruns append a fresh
    # run header, so analysis is always run-scoped while history survives
    a = RunAnalysis(out_dir=out_dir, meta=j["meta"] or {},
                    runs_in_journal=j["runs"],
                    truncated_lines=j["truncated"],
                    events=len(j["events"]))
    a.run_id = a.meta.get("run_id")
    t_max = 0.0
    for ev in j["events"]:
        t = float(ev.get("t", 0.0))
        dur = float(ev.get("dur", 0.0) or 0.0)
        t_max = max(t_max, t + max(dur, 0.0))
        kind = ev.get("type")
        name = ev.get("ev")
        if kind == "end":
            a.ended = True
        elif kind == "span" and name == "lane":
            lane = ev.get("lane", "?")
            a.lane_walls[lane] = a.lane_walls.get(lane, 0.0) + dur
            a.lane_spans[lane] = a.lane_spans.get(lane, 0) + 1
            a.lane_intervals.setdefault(lane, []).append((t, t + dur))
            a.lane_last_beat[lane] = max(a.lane_last_beat.get(lane, 0.0),
                                         t + dur)
        elif kind == "span" and name == "stage":
            st = ev.get("stage", "?")
            a.stage_walls[st] = a.stage_walls.get(st, 0.0) + dur
        elif kind == "instant":
            if name and name.startswith("cache."):
                st = ev.get("stage", "?")
                a.cache.setdefault(st, {})
                k = name[6:]
                a.cache[st][k] = a.cache[st].get(k, 0) + 1
            elif name == "launch":
                a.launches.append(ev)
            elif name == "pair_launch":
                a.pair_launches.append(ev)
            elif name == "lane.retry":
                ln = ev.get("lane", "?")
                a.retries[ln] = a.retries.get(ln, 0) + 1
            elif name == "lane.failure":
                ln = ev.get("lane", "?")
                a.failures[ln] = a.failures.get(ln, 0) + 1
            elif name == "fault.injected":
                site = f"{ev.get('site', '?')}:{ev.get('kind', '?')}"
                a.injected[site] = a.injected.get(site, 0) + 1
            elif name == "quarantine":
                a.quarantined.append(ev)
            elif name == "watchdog.stall":
                a.stall_events.append(ev)
            elif name == "lane.heartbeat":
                ln = ev.get("lane", "?")
                a.lane_last_beat[ln] = max(a.lane_last_beat.get(ln, 0.0), t)
            elif name == "executor.finish":
                a.critical_path_s = ev.get("critical_path_s")
            elif name == "assembly.tail":
                a.assembly = ev
            elif name == "transfer.bytes":
                for k in ("h2d", "d2h", "frames", "frames_raw"):
                    v = ev.get(k)
                    if v:
                        a.transfer[k] = a.transfer.get(k, 0) + int(v)
            elif name == "fabric.bytes":
                for k in ("fetched", "pushed", "deduped"):
                    v = ev.get(k)
                    if v:
                        a.fabric[k] = a.fabric.get(k, 0) + int(v)
            elif name and name.startswith("kernel."):
                kn = name[7:]
                rec = a.kernels.setdefault(
                    kn, {"launches": 0, "wall_s": 0.0, "bytes": 0,
                         "compiled": 0, "buckets": {}})
                rec["launches"] += 1
                rec["wall_s"] += float(ev.get("wall_s", 0.0) or 0.0)
                rec["bytes"] += int(ev.get("bytes", 0) or 0)
                if ev.get("compiled"):
                    rec["compiled"] += 1
                b = ev.get("bucket")
                if b is not None:
                    bk = rec["buckets"].setdefault(
                        int(b), {"launches": 0, "wall_s": 0.0, "bytes": 0})
                    bk["launches"] += 1
                    bk["wall_s"] += float(ev.get("wall_s", 0.0) or 0.0)
                    bk["bytes"] += int(ev.get("bytes", 0) or 0)
    a.wall_s = t_max
    for lane in a.lane_intervals:
        a.lane_intervals[lane] = _merge_intervals(a.lane_intervals[lane])
    mpath = os.path.join(out_dir, metrics_file)
    if os.path.exists(mpath):
        try:
            with open(mpath, encoding="utf-8") as f:
                a.metrics = json.load(f)
        except (OSError, ValueError):
            a.metrics = None
    fpath = os.path.join(out_dir, "failures.json")
    if os.path.exists(fpath):
        try:
            with open(fpath, encoding="utf-8") as f:
                a.manifest = json.load(f)
        except (OSError, ValueError):
            a.manifest = None
    spath = os.path.join(out_dir, "stalls.json")
    if os.path.exists(spath):
        try:
            with open(spath, encoding="utf-8") as f:
                a.stalls = json.load(f)
        except (OSError, ValueError):
            a.stalls = None
    return a


# ---------------------------------------------------------------------------
# multi-host journal merge (coordinated runs: N workers share one out dir)
# ---------------------------------------------------------------------------

def host_journals(out_dir: str, trace_file: str = "trace.jsonl") -> list[str]:
    """Every journal in an out dir: the coordinator/single-process
    ``trace_file`` plus the host-scoped ``trace.<rank>-<pid>.jsonl``
    siblings coordinated workers write (``telemetry.host_scoped`` naming).
    The unscoped journal sorts first."""
    stem, dot, ext = trace_file.rpartition(".")
    pat = f"{stem}.*.{ext}" if dot else f"{trace_file}.*"
    main = os.path.join(out_dir, trace_file)
    sibs = sorted(glob.glob(os.path.join(out_dir, pat)))
    out = [main] if os.path.exists(main) else []
    out += [p for p in sibs if p != main]
    return out


def merge_host_timeline(out_dir: str,
                        trace_file: str = "trace.jsonl") -> list[dict]:
    """Fold every per-host journal into ONE time-ordered event list, each
    row stamped with its ``host`` column. Per-host relative timestamps are
    rebased onto each journal's ``t0_unix`` wall anchor, so events from
    different processes interleave in true order (subject to host clock
    skew — irrelevant on one machine, labeled per-host anyway)."""
    rows: list[dict] = []
    for path in host_journals(out_dir, trace_file):
        j = telemetry.read_journal(path)
        meta = j["meta"] or {}
        host = (meta.get("host") or meta.get("tool")
                or os.path.basename(path))
        # fleet respawns reuse the rank but bump the generation stamp:
        # `fw0#g2` is the same lane healed twice, not three workers —
        # the healed-vs-flapping distinction at a glance
        if meta.get("generation"):
            host = netutil.worker_tag(host, int(meta["generation"]))
        # networked workers advertise the address they dialed from; show
        # it in the host column so a pod run reads `w0 10.0.0.2:41234`
        if meta.get("addr"):
            host = f"{host} {meta['addr']}"
        t0 = float(meta.get("t0_unix", 0.0) or 0.0)
        for ev in j["events"]:
            row = dict(ev)
            row["host"] = host
            row["t_unix"] = t0 + float(ev.get("t", 0.0) or 0.0)
            rows.append(row)
    rows.sort(key=lambda r: r["t_unix"])
    return rows


def render_host_timeline(rows: list[dict], limit: int = 60) -> str:
    """The merged cross-host timeline as a host-column table (the last
    ``limit`` events; earlier ones summarize to a count). Pure function —
    the CLI prints it under the per-journal report when worker journals
    are present."""
    L: list[str] = []
    hosts = sorted({r["host"] for r in rows})
    L.append(f"multi-host timeline — {len(rows)} event(s) across "
             f"{len(hosts)} journal(s): {', '.join(hosts)}")
    if not rows:
        return "\n".join(L)
    t_base = rows[0]["t_unix"]
    shown = rows[-limit:] if len(rows) > limit else rows
    if len(rows) > limit:
        L.append(f"  ... {len(rows) - limit} earlier event(s) elided ...")
    wh = max(len(h) for h in hosts)
    for r in shown:
        what = r.get("ev") or r.get("type", "?")
        detail = " ".join(
            f"{k}={r[k]}" for k in ("lane", "stage", "item", "view",
                                    "status", "site", "kind", "error")
            if k in r)
        L.append(f"  +{r['t_unix'] - t_base:8.3f}s  {r['host']:<{wh}}  "
                 f"{what}" + (f"  {detail}" if detail else ""))
    # pod-wide fabric total: the workers' journals carry the
    # `fabric.bytes` instants (the coordinator's own journal has none),
    # so the cross-host fold is where the blobstore traffic is summable —
    # it must reconcile with the coordinator's blob-server counters
    fabric = {k: sum(int(r.get(k) or 0) for r in rows
                     if (r.get("ev") or r.get("type")) == "fabric.bytes")
              for k in ("fetched", "pushed", "deduped")}
    if any(fabric.values()):
        L.append(f"  pod fabric total: {fabric['fetched']} B fetched / "
                 f"{fabric['pushed']} B pushed / {fabric['deduped']} B "
                 f"deduped over the blobstore wire")
    return "\n".join(L)


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _bar(intervals: list[tuple[float, float]], wall: float,
         width: int) -> str:
    cells = [" "] * width
    if wall <= 0:
        return "".join(cells)
    for t0, t1 in intervals:
        i0 = max(0, min(width - 1, int(t0 / wall * width)))
        i1 = max(i0, min(width - 1, int(t1 / wall * width)))
        for i in range(i0, i1 + 1):
            cells[i] = "#"
    return "".join(cells)


def _lane_sort_key(lane: str):
    return (_LANES.index(lane) if lane in _LANES else len(_LANES), lane)


def render_report(a: RunAnalysis, width: int = 60) -> str:
    """The terminal report. Pure function of the analysis — testable, and
    the CLI just prints it."""
    L: list[str] = []
    m = a.meta
    status = "clean close" if a.ended else "INTERRUPTED (no end marker)"
    degraded = bool(a.manifest and a.manifest.get("degraded"))
    if degraded:
        status += ", DEGRADED"
    L.append(f"flight recorder report — run {a.run_id or '?'}")
    L.append(f"  out dir  : {a.out_dir}")
    L.append(f"  status   : {status}")
    L.append(f"  events   : {a.events} "
             f"({a.truncated_lines} torn line(s) tolerated)"
             + (f"; journal holds {a.runs_in_journal} run(s), showing "
                f"the latest" if a.runs_in_journal > 1 else ""))
    regime = (f"{m.get('host_cpus', '?')} host cpu(s), "
              f"{m.get('device_count') if m.get('device_count') is not None else '?'} device(s), "
              f"backend {m.get('backend', '?')}")
    L.append(f"  regime   : {regime}")
    L.append(f"  wall     : {a.wall_s:.2f}s"
             + (f" (critical path {a.critical_path_s:.2f}s)"
                if a.critical_path_s is not None else ""))

    lanes = sorted(a.lane_walls, key=_lane_sort_key)
    if lanes:
        L.append("")
        L.append(f"lane timeline (each column ~{a.wall_s / max(width, 1):.3f}s)")
        for lane in lanes:
            bar = _bar(a.lane_intervals.get(lane, []), a.wall_s, width)
            L.append(f"  {lane:<9}|{bar}| {a.lane_walls[lane]:8.2f}s "
                     f"{a.lane_spans.get(lane, 0):4d} span(s)")
        busy = sum(a.lane_walls.values())
        if a.wall_s > 0:
            L.append(f"  serial-equivalent {busy:.2f}s in {a.wall_s:.2f}s "
                     f"wall (overlap x{busy / a.wall_s:.2f})")

    if a.stage_walls:
        L.append("")
        L.append("stage walls")
        for st, w in sorted(a.stage_walls.items(), key=lambda kv: -kv[1]):
            L.append(f"  {st:<14} {w:8.2f}s")

    if a.cache:
        L.append("")
        L.append("stage cache")
        for st in sorted(a.cache):
            c = a.cache[st]
            hits, misses = c.get("hit", 0), c.get("miss", 0)
            total = hits + misses
            ratio = f"{hits / total * 100:.0f}%" if total else "-"
            extra = "".join(
                f", {k} {v}" for k, v in sorted(c.items())
                if k not in ("hit", "miss"))
            L.append(f"  {st:<6} {hits} hit / {misses} miss ({ratio} hit "
                     f"ratio{extra})")

    if a.launches or a.pair_launches:
        L.append("")
        L.append("device launches")
        if a.launches:
            views = sum(e.get("views", 0) for e in a.launches)
            buckets: dict[int, int] = {}
            for e in a.launches:
                b = e.get("bucket", 0)
                buckets[b] = buckets.get(b, 0) + 1
            L.append(f"  view batches : {views} view(s) in "
                     f"{len(a.launches)} launch(es), mean "
                     f"{views / len(a.launches):.1f}/launch")
            for b in sorted(buckets):
                first = next((e.get("dispatch_s") for e in a.launches
                              if e.get("bucket") == b), None)
                L.append(f"    bucket {b:<4} x{buckets[b]} "
                         f"(first dispatch {first}s)")
        if a.pair_launches:
            pairs = sum(e.get("pairs", 0) for e in a.pair_launches)
            L.append(f"  pair batches : {pairs} pair(s) in "
                     f"{len(a.pair_launches)} register launch(es), mean "
                     f"{pairs / len(a.pair_launches):.1f}/launch")

    if a.assembly is not None or "assembly" in a.lane_walls:
        L.append("")
        L.append("incremental assembly")
        folds = a.lane_spans.get("assembly", 0)
        fold_s = a.lane_walls.get("assembly", 0.0)
        L.append(f"  folds      : {folds} fold event(s), {fold_s:.3f}s "
                 f"folded into the pod window")
        asm = a.assembly or {}
        if asm.get("used_views") is not None:
            L.append(f"  prefix     : {asm.get('used_views')} of "
                     f"{asm.get('folded_views', '?')} folded view(s) "
                     f"validated, {asm.get('folded_pairs', '?')} pair "
                     f"transform(s) pre-chained")
        tail = asm.get("tail_s")
        if tail is not None:
            line = f"  tail_s     : {float(tail):.3f}s after last item settled"
            # can't-drift cross-check: the journal instant and the
            # metrics gauge are written from the SAME report field, so
            # any drift means the close-out path forked — flag >1%
            gauge = None
            for row in (a.metrics or {}).get("gauges", []):
                if row.get("name") == "sl3d_assembly_tail_seconds":
                    gauge = float(row.get("value", 0.0))
            if gauge is None:
                line += " (metrics absent; no cross-check)"
            else:
                ref = max(abs(float(tail)), abs(gauge), 1e-9)
                drift = abs(float(tail) - gauge) / ref
                if drift > 0.01:
                    line += (f" [DRIFT: metrics gauge says {gauge:.3f}s, "
                             f"{drift * 100:.1f}% apart]")
                else:
                    line += f" (= metrics gauge, drift {drift * 100:.2f}%)"
            L.append(line)

    if a.kernels or a.transfer or a.fabric:
        L.append("")
        L.append("kernel table")
        for kn in sorted(a.kernels):
            rec = a.kernels[kn]
            detail = (f", {rec['bytes']} B moved" if rec["bytes"] else "")
            if rec["compiled"]:
                detail += f", {rec['compiled']} compiled dispatch(es)"
            L.append(f"  {kn:<14} {rec['launches']} launch(es), "
                     f"{rec['wall_s']:.3f}s wall{detail}")
            for b in sorted(rec["buckets"]):
                bk = rec["buckets"][b]
                L.append(f"    bucket {b:<4} x{bk['launches']} "
                         f"({bk['wall_s']:.3f}s"
                         + (f", {bk['bytes']} B" if bk["bytes"] else "")
                         + ")")
        if a.transfer:
            fr = a.transfer.get("frames", 0)
            raw = a.transfer.get("frames_raw", 0)
            packed = ""
            if fr and raw > fr:
                # frames_raw is only journaled when it differs from the
                # wire size, i.e. packed ingest was on — show both sides
                packed = (f"; packed ingest: {fr} B wire for {raw} B raw "
                          f"({raw / fr:.1f}x fewer frame bytes)")
            L.append(f"  transfers      {a.transfer.get('h2d', 0)} B h2d "
                     f"({fr} B frame uploads) / "
                     f"{a.transfer.get('d2h', 0)} B d2h" + packed)
        if a.fabric:
            L.append(f"  fabric         {a.fabric.get('fetched', 0)} B "
                     f"fetched / {a.fabric.get('pushed', 0)} B pushed / "
                     f"{a.fabric.get('deduped', 0)} B deduped over the "
                     f"blobstore wire")

    if (a.retries or a.failures or a.injected or a.quarantined
            or (a.manifest and a.manifest.get("failures"))):
        L.append("")
        L.append("fault ledger")
        if a.injected:
            for site, n in sorted(a.injected.items()):
                L.append(f"  injected   {site}: x{n}")
        if a.retries:
            for ln, n in sorted(a.retries.items()):
                L.append(f"  retries    {ln}: x{n}")
        if a.failures:
            for ln, n in sorted(a.failures.items()):
                L.append(f"  failures   {ln}: x{n}")
        for q in a.quarantined:
            L.append(f"  quarantined view {q.get('view')} "
                     f"({q.get('stage')}: {q.get('error')})")
        if a.manifest:
            for rec in a.manifest.get("failures", []):
                L.append(f"  manifest   {rec.get('stage')}/{rec.get('view')}"
                         f": {rec.get('error_type')} after "
                         f"{rec.get('attempts')} attempt(s) "
                         f"({'transient' if rec.get('transient') else 'permanent'})")
            L.append(f"  manifest verdict: degraded="
                     f"{a.manifest.get('degraded')} aborted="
                     f"{a.manifest.get('aborted')} "
                     f"({a.manifest.get('views_survived')}/"
                     f"{a.manifest.get('views_total')} views survived)")
    else:
        L.append("")
        L.append("fault ledger: clean (no retries, failures, or injections)")

    # ---- stall ledger: rendered for clean/DEGRADED/INTERRUPTED alike ----
    breaches = list(a.stall_events)
    if a.stalls:
        # stalls.json is authoritative when present (the journal may have
        # been truncated before the watchdog event flushed)
        breaches = a.stalls.get("breaches", breaches)
    if breaches or a.stalls:
        L.append("")
        L.append("stall ledger")
        for b in breaches:
            lanes = b.get("lane_ages") or b.get("lanes") or {}
            lanestr = ", ".join(f"{ln} {age}s ago"
                                for ln, age in sorted(lanes.items()))
            L.append(f"  {str(b.get('level', '?')).upper():<5} breach: no "
                     f"heartbeat for {b.get('age_s', '?')}s"
                     + (f" (last beats: {lanestr})" if lanestr else ""))
        if a.lane_last_beat and a.wall_s > 0:
            ages = ", ".join(
                f"{ln} {max(0.0, a.wall_s - t):.2f}s"
                for ln, t in sorted(a.lane_last_beat.items(),
                                    key=lambda kv: _lane_sort_key(kv[0])))
            L.append(f"  last-heartbeat age at end of journal: {ages}")
        if a.stalls:
            n_stack = len(a.stalls.get("thread_stacks", []))
            L.append(f"  stalls.json: {len(a.stalls.get('breaches', []))} "
                     f"breach(es), thread-stack dump "
                     f"({n_stack} line(s)) — the wedge's stack lives "
                     f"there")
    else:
        L.append("")
        L.append("stall ledger: clean (no watchdog breaches)")

    if a.metrics is None:
        L.append("")
        L.append("metrics.json: absent (interrupted before close, or "
                 "observability.metrics_file renamed) — journal-only "
                 "analysis above")
    return "\n".join(L)
